#!/usr/bin/env bash
# Regression hunt for the round-5 ResNet-50 b128 number (1182.7 img/s
# vs round-3's 1863): one bench at a time, same patient-claim
# discipline as tpu_watch.sh.  Run ONLY when no other TPU process is
# active (pgrep -f 'python bench.py' must be empty) — a second claim
# wedges the grant (docs/PERF.md round-5 notes; memory: every python
# process with the default PYTHONPATH claims the chip at interpreter
# start, so helpers must run with PYTHONPATH= JAX_PLATFORMS=cpu).
#
# Matrix (each persists to BENCH_LAST_TPU.json under its own key):
#   1. nofuse      — isolates the optimizer fusion (also in tpu_watch)
#   2. bn-unshift  — isolates the shifted BN statistics form
#   3. smallfuse   — the size-capped stack (current default, post-fix)
#   4. rcp8-b256   — recompute retry of the OOM/wedge-suspect batch 256
# Control for "environment changed": check out the round-3 tree
# (git worktree add /tmp/r3tree 843b3d9) and run its bench.py verbatim;
# ~1863 img/s there = code regression here, ~1180 = environment.
set -uo pipefail
cd "$(dirname "$0")/.."
log="docs/regression_hunt.log"

say() { echo "[$(date +%H:%M:%S)] $*" | tee -a "$log"; }

if pgrep -f 'python bench.py' >/dev/null; then
  say "another bench is running — refusing to contend"; exit 1
fi

run_one() {  # run_one <label> [ENV=VAL ...]
  local label="$1"; shift
  say "hunt $label ..."
  if env BENCH_CLAIM_TIMEOUT=0 "$@" timeout 2400 python bench.py \
      >>"$log" 2>&1; then
    say "hunt $label OK: $(grep -o '{.*}' "$log" | tail -1)"
  else
    say "hunt $label FAILED (rc=$?)"
  fi
}

# r3config reproduces the exact round-3 1863 img/s configuration
# (f32 activations, unfused updates, two-pass BN): ~1863 there means
# the environment is unchanged and the delta is in one of the three
# code changes; ~1180 means the chip/tunnel itself got slower.
run_one r3config BENCH_TAG=r3config FLAGS_amp_bf16_act=0 \
    FLAGS_fuse_optimizer=0 FLAGS_bn_shifted_stats=0
run_one nofuse BENCH_TAG=nofuse FLAGS_fuse_optimizer=0
run_one f32act BENCH_TAG=f32act FLAGS_amp_bf16_act=0
run_one bn-unshift BENCH_TAG=bnunshift FLAGS_bn_shifted_stats=0
run_one smallfuse BENCH_TAG=smallfuse
run_one rcp8-b256 BENCH_BATCH=256 BENCH_RECOMPUTE=8
say "done — compare records in BENCH_LAST_TPU.json"
