#!/usr/bin/env bash
# Full single-chip measurement sequence (run when the TPU is healthy):
#   1. headline ResNet-50 bench (batch 128, bf16 + bf16 activations)
#   2. batch-256 variant (MXU utilization lever)
#   3. f32 reference point
#   4. per-HLO-category device profile
# Appends everything to docs/measurements_$(date +%m%d).log
set -uo pipefail
cd "$(dirname "$0")/.."
log="docs/measurements_$(date +%m%d).log"
run() {
  echo "== $* ==" | tee -a "$log"
  "$@" 2>&1 | tail -3 | tee -a "$log"
}
run env BENCH_CLAIM_TIMEOUT=120 python bench.py
run env BENCH_CLAIM_TIMEOUT=120 BENCH_BATCH=256 python bench.py
run env BENCH_CLAIM_TIMEOUT=120 BENCH_AMP=0 python bench.py
run env PROFILE_STEPS=10 python scripts/profile_tpu.py
echo "done -> $log"
