#!/usr/bin/env python
"""Roofline ceiling for a bench model's training step.

    PYTHONPATH= JAX_PLATFORMS=cpu python scripts/roofline.py \
        --model resnet50 --batch 128 --bf16

Prints the per-op-type floor table (fluid/analysis.py) for the same
program bench.py times, so a measured step_ms can be read against its
hardware floor directly.  Pure IR analysis: no chip, no compile.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--class-dim", type=int, default=1000)
    ap.add_argument("--bf16", action="store_true", default=True)
    ap.add_argument("--f32", dest="bf16", action="store_false")
    ap.add_argument("--peak-tflops", type=float, default=None,
                    help="default: analysis.py v5e numbers (halved "
                         "for f32)")
    ap.add_argument("--hbm-gbps", type=float, default=None)
    ap.add_argument("--topk", type=int, default=12)
    args = ap.parse_args()

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import analysis
    from paddle_tpu import models
    from __graft_entry__ import _build_model

    if args.bf16:
        fluid.amp.enable_bf16()
    fn = {"resnet50": models.resnet50, "alexnet": models.alexnet,
          "vgg16": models.vgg16, "vgg19": models.vgg19,
          "googlenet": models.googlenet,
          "smallnet": models.smallnet_mnist_cifar}[args.model]
    main_prog, _, _, _ = _build_model(fn, args.batch, args.image_size,
                                      args.class_dim, with_loss=True)
    peak = args.peak_tflops or (analysis.DEFAULT_PEAK_TFLOPS
                                if args.bf16
                                else analysis.DEFAULT_PEAK_TFLOPS / 2)
    rep = analysis.roofline_report(
        main_prog, peak_tflops=peak,
        hbm_gbps=args.hbm_gbps or analysis.DEFAULT_HBM_GBPS,
        bf16_act=args.bf16)
    print(analysis.format_report(rep, topk=args.topk))


if __name__ == "__main__":
    main()
