#!/usr/bin/env python
"""RETIRED — superseded by `pperf classify` (paddle_tpu.obs.perf).

The hand-run roofline table this script printed is now one half of the
perf subsystem's bottleneck classifier:

    PYTHONPATH= JAX_PLATFORMS=cpu python -m paddle_tpu.tools.perf_cli \
        classify --model resnet50 --batch 128 [--step-ms 51.8]

which prints the same fluid/analysis.py floor table AND, given a
measured step, the compute/hbm/input/host verdict with the dominant op
named (docs/PERF.md).  This stub forwards its arguments so existing
invocations keep working.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if __name__ == "__main__":
    from paddle_tpu.tools import perf_cli

    print("[roofline] retired: forwarding to `pperf classify` "
          "(python -m paddle_tpu.tools.perf_cli classify ...)",
          file=sys.stderr, flush=True)
    sys.exit(perf_cli.main(["classify"] + sys.argv[1:]))
