#!/usr/bin/env bash
# Companion to tpu_watch.sh for a watcher started before the infer rows
# existed: waits for the main suite to complete (docs/TPU_CAPTURED_OK),
# then captures the inference benchmarks.  A freshly-started
# tpu_watch.sh already includes these rows; this script exits once they
# are all persisted.
set -uo pipefail
cd "$(dirname "$0")/.."
log="docs/tpu_watch.log"

say() { echo "[$(date +%H:%M:%S)] [infer-followup] $*" | tee -a "$log"; }

while [ ! -f docs/TPU_CAPTURED_OK ]; do
  sleep 120
done
say "main suite complete — capturing inference rows"

run_one() {  # run_one <label> <key> [ENV=VAL ...]
  local label="$1" key="$2"; shift 2
  if python - "$key" <<'PY'
import json, sys
try:
    store = json.load(open("BENCH_LAST_TPU.json"))
except Exception:
    sys.exit(1)
sys.exit(0 if store.get(sys.argv[1]) else 1)
PY
  then
    say "bench $label already captured — skipping"
    return 0
  fi
  say "bench $label ..."
  if env BENCH_CLAIM_TIMEOUT=0 "$@" timeout 2400 python bench.py \
      >>"$log" 2>&1; then
    say "bench $label OK"
  else
    say "bench $label FAILED (rc=$?)"
    return 1
  fi
}

ok=1
run_one "resnet50-b128-nofuse" \
  "resnet50_train_imgs_per_sec_batch128+nofuse|bf16" \
  BENCH_MODEL=resnet50 BENCH_BATCH=128 BENCH_TAG=nofuse \
  FLAGS_fuse_optimizer=0 || ok=0
run_one "transformer-b16-seq512" \
  "transformer_train_tokens_per_sec_batch16_seq512_d512|bf16" \
  BENCH_MODEL=transformer || ok=0
run_one "resnet50-b16-infer" "resnet50_infer_imgs_per_sec_batch16|bf16" \
  BENCH_MODEL=resnet50 BENCH_MODE=infer || ok=0
run_one "vgg19-b16-infer" "vgg19_infer_imgs_per_sec_batch16|bf16" \
  BENCH_MODEL=vgg19 BENCH_MODE=infer || ok=0
run_one "googlenet-b16-infer" "googlenet_infer_imgs_per_sec_batch16|bf16" \
  BENCH_MODEL=googlenet BENCH_MODE=infer || ok=0
run_one "alexnet-b16-infer" "alexnet_infer_imgs_per_sec_batch16|bf16" \
  BENCH_MODEL=alexnet BENCH_MODE=infer || ok=0
[ "$ok" = 1 ] && say "infer suite complete" || say "infer suite incomplete"
