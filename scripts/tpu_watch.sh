#!/usr/bin/env bash
# Round-long TPU capture loop.
#
# The claim behaves badly when the tunnel is wedged: it BLOCKS (observed
# ~100 min) and then fails with UNAVAILABLE; killing a claim mid-flight
# can re-wedge the grant.  So this watcher uses ONE patient probe per
# attempt with a very generous timeout (the probe itself is the wait),
# never a tight kill-retry loop.  The moment a probe succeeds, it
# captures the full measurement suite; each bench run persists itself to
# BENCH_LAST_TPU.json so the driver's end-of-round bench.py can never
# lose the numbers.
#
# Status lands in docs/tpu_watch.log; docs/TPU_CAPTURED_OK marks a
# complete suite.
set -uo pipefail
cd "$(dirname "$0")/.."
log="docs/tpu_watch.log"
probe_timeout="${WATCH_PROBE_TIMEOUT:-7200}"

say() { echo "[$(date +%H:%M:%S)] $*" | tee -a "$log"; }

watch_start_epoch="$(date +%s)"

has_record() {  # has_record <metric|amp key> — fresh this watch run
  python - "$1" "$watch_start_epoch" <<'PY'
import json, sys
try:
    store = json.load(open("BENCH_LAST_TPU.json"))
except Exception:
    sys.exit(1)
rec = store.get(sys.argv[1])
# only skip for records measured AFTER this watcher started: a stale
# store from an earlier round must never satisfy the suite
ok = rec is not None and rec.get("measured_at", 0) >= float(sys.argv[2])
sys.exit(0 if ok else 1)
PY
}

bench_one() {  # bench_one <label> <record-key> [ENV=VAL ...]
  local label="$1" key="$2"; shift 2
  if has_record "$key"; then
    say "bench $label already captured — skipping"
    return 0
  fi
  say "bench $label ..."
  if env BENCH_CLAIM_TIMEOUT=0 "$@" timeout 2400 python bench.py \
      >>"$log" 2>&1; then
    say "bench $label OK: $(tail -1 "$log" >/dev/null; grep -o '"value": [0-9.]*' "$log" | tail -1)"
  else
    say "bench $label FAILED (rc=$?)"
    return 1
  fi
}

attempt=0
while true; do
  attempt=$((attempt + 1))
  say "attempt $attempt: patient claim probe (up to ${probe_timeout}s)"
  if timeout "$probe_timeout" python -c \
      "import jax; print(jax.devices(), flush=True)" >>"$log" 2>&1; then
    say "claim OK — capturing measurement suite"
    ok=1
    bench_one "resnet50-b128" "resnet50_train_imgs_per_sec_batch128|bf16" \
      BENCH_MODEL=resnet50 BENCH_BATCH=128 || ok=0
    bench_one "resnet50-b256" "resnet50_train_imgs_per_sec_batch256|bf16" \
      BENCH_MODEL=resnet50 BENCH_BATCH=256 || ok=0
    bench_one "vgg16-b128" "vgg16_train_imgs_per_sec_batch128|bf16" \
      BENCH_MODEL=vgg16 BENCH_BATCH=128 || ok=0
    bench_one "lstm-b256-h256" \
      "lstm_train_samples_per_sec_batch256_hidden256|bf16" \
      BENCH_MODEL=lstm BENCH_BATCH=256 BENCH_HIDDEN=256 || ok=0
    bench_one "alexnet-b128" "alexnet_train_imgs_per_sec_batch128|bf16" \
      BENCH_MODEL=alexnet BENCH_BATCH=128 || ok=0
    bench_one "googlenet-b128" \
      "googlenet_train_imgs_per_sec_batch128|bf16" \
      BENCH_MODEL=googlenet BENCH_BATCH=128 || ok=0
    bench_one "resnet50-b128-f32" \
      "resnet50_train_imgs_per_sec_batch128|f32" \
      BENCH_MODEL=resnet50 BENCH_BATCH=128 BENCH_AMP=0 || ok=0
    # A/B the stacked optimizer updates (docs/PERF.md round-5 #1):
    # unfused run persists under the same metric via BENCH_TAG
    bench_one "resnet50-b128-nofuse" \
      "resnet50_train_imgs_per_sec_batch128+nofuse|bf16" \
      BENCH_MODEL=resnet50 BENCH_BATCH=128 BENCH_TAG=nofuse \
      FLAGS_fuse_optimizer=0 || ok=0
    bench_one "transformer-b16-seq512" \
      "transformer_train_tokens_per_sec_batch16_seq512_d512|bf16" \
      BENCH_MODEL=transformer || ok=0
    bench_one "resnet50-b16-infer" \
      "resnet50_infer_imgs_per_sec_batch16|bf16" \
      BENCH_MODEL=resnet50 BENCH_MODE=infer || ok=0
    bench_one "vgg19-b16-infer" "vgg19_infer_imgs_per_sec_batch16|bf16" \
      BENCH_MODEL=vgg19 BENCH_MODE=infer || ok=0
    bench_one "googlenet-b16-infer" \
      "googlenet_infer_imgs_per_sec_batch16|bf16" \
      BENCH_MODEL=googlenet BENCH_MODE=infer || ok=0
    bench_one "alexnet-b16-infer" \
      "alexnet_infer_imgs_per_sec_batch16|bf16" \
      BENCH_MODEL=alexnet BENCH_MODE=infer || ok=0
    say "profiling ..."
    env PROFILE_STEPS=10 timeout 2400 python scripts/profile_tpu.py \
      >>"$log" 2>&1 && say "profile OK" || say "profile FAILED"
    if [ "$ok" = 1 ]; then
      date > docs/TPU_CAPTURED_OK
      say "suite complete — exiting"
      exit 0
    fi
    say "suite incomplete; retrying after 600s"
    sleep 600
  else
    say "claim failed/timed out; next patient probe after 300s"
    sleep 300
  fi
done
