#!/usr/bin/env bash
# Install the repo's git hooks: pre-push runs a fast gate (syntax +
# native build + the quick test subset); the full scripts/ci.sh gate
# runs in the workflow (.github/workflows/ci.yml) and can be run
# locally before a release.
set -euo pipefail
cd "$(dirname "$0")/.."

hook=.git/hooks/pre-push
cat > "$hook" <<'EOF'
#!/usr/bin/env bash
set -euo pipefail
echo "[pre-push] fast gate (scripts/ci.sh has the full one)"
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
export PYTHONPATH=
python -m compileall -q paddle_tpu tests examples bench.py __graft_entry__.py
make -C native -q || make -C native
# the checked-in golden ProgramDescs must be well-formed IR, not just
# byte-stable: proglint walks each fixture through the full verifier,
# the SPMD analyzer under the default dryrun mesh, AND the donation
# alias analysis (a pinned program must always plan with 0 A errors)
python -m paddle_tpu.tools.lint_cli --golden --quiet --mesh dp=4,mp=2 \
    --donation
python -m pytest tests/test_math_ops.py tests/test_fit_a_line.py -q
EOF
chmod +x "$hook"
echo "installed $hook"
