"""The full measurement suite in ONE process / one TPU claim.

Every wedge observed on this tunnel hits a FRESH process's first big
remote compile — claims stay instant, and compiles within an
already-claimed process have worked back-to-back (bench warmup +
profile traces).  So instead of one process per config (phase-1/2
hunts: 27 wedged minutes per leg), this driver calls bench.py's main()
once per config inside a single process: per-config env overrides are
applied and FLAGS_* re-parsed (utils/flags.py is runtime state), and
every successful run persists its record to BENCH_LAST_TPU.json
immediately, so a mid-suite wedge keeps all completed measurements.

Config order = information value: the headline (the sweep-1 factor
hunt concluded bf16-act + unfused + plain BN stats wins — now the
default), then single-factor A/B legs each pinning its flags
EXPLICITLY relative to that default (run_one resets un-overridden
flags to registered defaults, so a tag must never rely on a default
it means to vary), then batch/memory/layout levers, the model suite,
inference rows, and last the googlenet compile that hung sweep 1.

Usage:  python scripts/mega_bench.py            # everything
        MEGA_CONFIGS=f32act,fused python ...    # subset
A config is skipped when BENCH_LAST_TPU.json already holds a record
for it newer than MEGA_FRESH_SINCE (default: this round's start).

Known-pathological legs (RISKY, e.g. the GoogLeNet inception wedge)
run behind a per-leg subprocess guard: MEGA_LEG_TIMEOUT seconds
(default 2400, 0 disables) and a killed leg is recorded in the BENCH
json as {"skipped": "compile-timeout"} instead of forfeiting the whole
TPU window.  MEGA_SUBPROC=all extends the guard to every leg.

Every leg's wall/compile timings flow through the paddle_tpu.obs
registry (mega_leg_wall_seconds / mega_leg_jit_traces, labeled by
leg) and the leg's registry DELTA (telemetry.snapshot_delta: counter
increments + current gauges — leg timings, executor trace/transfer
movement, per-segment xla_* memory and FLOP gauges) is stamped into
the leg's BENCH_LAST_TPU.json records as the "metrics" blob, so a
round's artifact carries its own measurement context without claiming
earlier legs' counters.  In-process non-RISKY legs run with
FLAGS_xla_cost_attribution on (attribution now rides the same AOT
artifact that executes the segment — executor._run_attr_aot — so it
no longer doubles first-build compiles; it stays off the
known-pathological googlenet legs anyway).  The persistent executable
cache is ON by default for the whole suite (FLAGS_compile_cache_dir
-> <repo>/.pcache; MEGA_COMPILE_CACHE=0 opts out): repeat rounds of
the same configs reload executables instead of recompiling, and every
BENCH record's "compile_cache" blob says whether its leg started warm.
Each leg also appends a normalized line (named by leg) to
perf_history.jsonl via bench.py, the trajectory `pperf gate` checks.
"""

import gc
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402

CONFIGS = [
    # --- headline: the sweep-1 winner is now the flag default
    # (bf16 activations, unfused updates, plain one-pass BN stats),
    # plus the saved-stats backward fix — re-measure first ---
    ("default-b128", {}),
    # --- single-factor A/B legs vs that default (each pins only the
    # factor it varies; defaults cover the rest) ---
    ("f32act", {"BENCH_TAG": "f32act", "FLAGS_amp_bf16_act": "0"}),
    ("fused", {"BENCH_TAG": "fused", "FLAGS_fuse_optimizer": "1"}),
    ("bnshifted", {"BENCH_TAG": "bnshifted",
                   "FLAGS_bn_shifted_stats": "1"}),
    ("r3config", {"BENCH_TAG": "r3config", "FLAGS_amp_bf16_act": "0",
                  "FLAGS_fuse_optimizer": "0",
                  "FLAGS_bn_shifted_stats": "0"}),
    # --- batch/memory levers ---
    ("b256", {"BENCH_BATCH": "256"}),
    ("b256rcp8", {"BENCH_BATCH": "256", "BENCH_RECOMPUTE": "8"}),
    ("nhwc-b128", {"BENCH_LAYOUT": "NHWC"}),
    ("f32-b128", {"BENCH_AMP": "0"}),
    # --- cost-model-guided pass pipeline (compile/opt_passes.py):
    # auto_remat prices the HBM-bound b256 leg's activation peak
    # against the budget and rematerializes only when it busts ---
    ("opt-b256", {"BENCH_BATCH": "256",
                  "FLAGS_compile_passes": "default+auto_remat:stride=8"}),
    # --- device-prefetch input pipeline vs the input-bound verdict
    # (AlexNet 14% MFU): the A/B that measures the overlap win ---
    ("alexnet-pf2", {"BENCH_MODEL": "alexnet", "BENCH_PREFETCH": "2"}),
    # --- the model suite (BASELINE.md rows) ---
    ("vgg16", {"BENCH_MODEL": "vgg16"}),
    ("alexnet", {"BENCH_MODEL": "alexnet"}),
    ("lstm", {"BENCH_MODEL": "lstm", "BENCH_BATCH": "256",
              "BENCH_HIDDEN": "256"}),
    ("transformer", {"BENCH_MODEL": "transformer"}),
    # --- inference rows (IntelOptimizedPaddle.md:68-104) ---
    ("infer-resnet50", {"BENCH_MODEL": "resnet50",
                        "BENCH_MODE": "infer"}),
    # the layout+fuse pipeline applies to the inference clone (no
    # backward): NHWC accepted only on a predicted tiled-roofline win
    ("infer-resnet50-opt", {"BENCH_MODEL": "resnet50",
                            "BENCH_MODE": "infer",
                            "FLAGS_compile_passes":
                                "default+layout+fuse"}),
    ("infer-vgg19", {"BENCH_MODEL": "vgg19", "BENCH_MODE": "infer"}),
    ("infer-googlenet", {"BENCH_MODEL": "googlenet",
                         "BENCH_MODE": "infer"}),
    ("infer-alexnet", {"BENCH_MODEL": "alexnet",
                       "BENCH_MODE": "infer"}),
    # --- serving tail latency (obs/load.py): open-loop Poisson load
    # against a loopback server; the record's `latency` blob is what
    # `pperf gate --latency-tolerance` regresses on ---
    ("serving-slo", {"BENCH_SERVING": "1"}),
    # last: its ~1500-op inception graph is the one compile that has
    # hung the remote compile service (sweep 1: >40 min, killed) — a
    # hang here can only cost this leg, not the suite
    ("googlenet", {"BENCH_MODEL": "googlenet"}),
]

_MANAGED = ("BENCH_TAG", "BENCH_MODEL", "BENCH_MODE", "BENCH_BATCH",
            "BENCH_HIDDEN", "BENCH_RECOMPUTE", "BENCH_LAYOUT",
            "BENCH_AMP", "BENCH_LEG", "BENCH_MESH",
            "BENCH_MICRO_BATCH", "BENCH_PREFETCH", "BENCH_MEMORY",
            "BENCH_SERVING",
            "FLAGS_amp_bf16_act", "FLAGS_fuse_optimizer",
            "FLAGS_bn_shifted_stats", "FLAGS_compile_passes")

# legs whose single huge graph has wedged the remote compile service
# (sweep 1: googlenet >40 min, killed): run these behind the
# subprocess guard so a hang forfeits the leg, never the whole window
RISKY = {"googlenet", "infer-googlenet"}


def _store():
    try:
        with open(bench._LAST_TPU_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _fresh_records(since):
    return {k for k, r in _store().items()
            if r.get("measured_at", 0) >= since}


def _compile_cache_summary(blob):
    """The leg's persistent-executable-cache efficacy, distilled from
    the registry delta: hits/misses and the compile wall-clock the
    cache refunded (sum of original compile durations served back as
    hits).  Stamped into every BENCH record so the perf trajectory
    says whether a leg started warm."""
    return {
        "hits": blob.get("compile_cache_hits_total", 0),
        "misses": blob.get("compile_cache_misses_total", 0),
        "compile_seconds_saved": round(
            blob.get("compile_cache_saved_compile_seconds_total",
                     0.0), 3),
    }


def _attach_metrics(keys, blob):
    """Stamp each freshly-persisted BENCH record with the leg's
    observability blob — the leg's telemetry.snapshot_delta() over the
    unified registry (leg wall/compile gauges, executor counter
    increments, xla_* memory and FLOP attribution), so the round's
    artifact carries its own measurement context."""
    if not blob:
        return
    try:
        with open(bench._LAST_TPU_PATH) as f:
            store = json.load(f)
    except (OSError, ValueError):
        return
    changed = False
    for k in keys:
        if k in store:
            store[k]["metrics"] = blob
            store[k]["compile_cache"] = _compile_cache_summary(blob)
            changed = True
    if not changed:
        return
    tmp = bench._LAST_TPU_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(store, f, indent=1, sort_keys=True)
    os.replace(tmp, bench._LAST_TPU_PATH)


def _leg_registry_emit(name, wall_s, jit_traces=None):
    """Each leg's wall/compile timings also land in the unified obs
    registry (labeled by leg), scrapeable by obs_dump after a suite."""
    from paddle_tpu.obs import registry as obs_registry

    reg = obs_registry.get_registry()
    reg.gauge("mega_leg_wall_seconds",
              "wall time of the most recent run of each bench leg",
              labelnames=("leg",)).labels(leg=name).set(round(wall_s, 3))
    if jit_traces is not None:
        reg.gauge("mega_leg_jit_traces",
                  "executor jit trace/compile events during each leg",
                  labelnames=("leg",)).labels(leg=name).set(jit_traces)


def _persist_skip(name, reason):
    """Record a skipped leg in the BENCH json so the round's artifact
    says WHY a row is missing instead of looking unmeasured."""
    try:
        with open(bench._LAST_TPU_PATH) as f:
            store = json.load(f)
    except (OSError, ValueError):
        store = {}
    store["%s|skipped" % name] = {
        "metric": name, "skipped": reason, "measured_at": time.time()}
    # atomic replace, same as bench._persist_tpu_record: this runs
    # exactly when the window is misbehaving, and a kill mid-write
    # must not truncate the round's measured records
    tmp = bench._LAST_TPU_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(store, f, indent=1, sort_keys=True)
    os.replace(tmp, bench._LAST_TPU_PATH)


def _warn_stale_platform(name, keys):
    """Round-5 incident class, surfaced at EMIT time: a leg that
    persisted a record the `pperf gate` would hard-fail (no
    accelerator claimed — `*-stale`/`*-fallback`/empty platform) gets
    a loud WARNING line in the suite log, so the operator learns the
    window was degraded while it can still be re-run, not days later
    at gate time."""
    from paddle_tpu.obs import perf as obs_perf

    store = _store()
    for key in sorted(keys):
        rec = store.get(key) or {}
        if rec.get("skipped"):
            continue
        platform = rec.get("platform")
        if obs_perf.is_stale_platform(platform):
            print("[mega] WARNING: leg %s emitted platform-stale "
                  "record %s (platform=%r) — no accelerator claimed; "
                  "the pperf gate will HARD-FAIL this as a re-emit, "
                  "re-run the leg on the real platform"
                  % (name, key, platform), flush=True)


def run_one_guarded(name, overrides, timeout):
    """Run one leg in a subprocess with a hard wall-clock bound
    (subprocess guard like bench.py:115's claim probe): a pathological
    compile is killed and recorded as skipped, and only this leg's
    measurement is lost.  The child persists its own records to
    BENCH_LAST_TPU.json, so the parent's freshness check still sees
    them."""
    from paddle_tpu.obs import telemetry as obs_tele

    env = dict(os.environ)
    for k in _MANAGED:
        env.pop(k, None)
    env.update(overrides)
    env["BENCH_LEG"] = name  # names the leg in perf_history.jsonl
    # the memory blob rides the same AOT capture as the perf blob —
    # keep it (like attribution) away from the known-pathological
    # googlenet compiles
    env["BENCH_MEMORY"] = "0" if name in RISKY else "1"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    snap_before = obs_tele.snapshot()
    t0 = time.perf_counter()
    proc = subprocess.Popen([sys.executable, "bench.py"], cwd=repo,
                            env=env)
    try:
        rc = proc.wait(timeout=timeout)
        wall = time.perf_counter() - t0
        # child-process legs report wall only (the child's obs
        # registry dies with it); the delta keeps the blob from
        # claiming earlier in-process legs' counters
        _leg_registry_emit(name, wall)
        if rc == 0:
            return "ok", obs_tele.snapshot_delta(snap_before)
        return "failed", None
    except subprocess.TimeoutExpired:
        # same caveat as the claim probe: a child wedged in compile can
        # survive kill() in uninterruptible I/O — never wait unbounded
        proc.kill()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        print("[mega] %s SKIPPED: exceeded %ds leg budget"
              % (name, timeout), flush=True)
        _persist_skip(name, "compile-timeout")
        return "skipped", None


def run_one(name, overrides):
    """Run one leg in-process.  Returns the leg's metrics blob on
    success — telemetry.snapshot_delta() over the leg (wall/compile
    timings land there via _leg_registry_emit, next to executor
    counter increments and the per-segment xla_* gauges captured
    during the leg's jit builds) — None on failure."""
    from paddle_tpu.fluid import amp
    from paddle_tpu.obs import telemetry as obs_tele
    from paddle_tpu.utils import flags

    saved = {k: os.environ.get(k) for k in _MANAGED}
    for k in _MANAGED:
        os.environ.pop(k, None)
    os.environ.update(overrides)
    os.environ["BENCH_LEG"] = name  # names the leg in perf_history
    # memory blob on for the same legs that run attribution (below)
    os.environ["BENCH_MEMORY"] = "0" if name in RISKY else "1"
    flags.parse_flags_from_env()
    for k in ("amp_bf16_act", "fuse_optimizer", "bn_shifted_stats",
              "compile_passes"):
        if "FLAGS_" + k not in overrides:
            flags.set_flag(k, flags._FLAGS[k]["default"])
    amp.disable_bf16()           # bench.main re-enables unless AMP=0
    # memory/FLOP attribution rides the executing AOT artifact
    # (executor._run_attr_aot — no extra compile), but it still
    # changes the dispatch path, so keep it away from the
    # known-pathological googlenet compiles
    flags.set_flag("xla_cost_attribution", name not in RISKY)
    snap_before = obs_tele.snapshot()
    traces_before = obs_tele.jit_trace_count()
    t0 = time.perf_counter()
    try:
        bench.main()
        wall = time.perf_counter() - t0
        jit_traces = obs_tele.jit_trace_count() - traces_before
        _leg_registry_emit(name, wall, jit_traces)
        return obs_tele.snapshot_delta(snap_before)
    except BaseException as e:   # noqa: BLE001 — keep measuring
        if isinstance(e, (KeyboardInterrupt, SystemExit)):
            raise
        print("[mega] %s FAILED: %r" % (name, e), flush=True)
        return None
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        flags.set_flag("xla_cost_attribution",
                       flags._FLAGS["xla_cost_attribution"]["default"])
        flags.parse_flags_from_env()
        gc.collect()


def main():
    subset = os.environ.get("MEGA_CONFIGS")
    names = subset.split(",") if subset else None
    since = float(os.environ.get("MEGA_FRESH_SINCE",
                                 time.time() - 6 * 3600))
    os.environ.setdefault("BENCH_CLAIM_TIMEOUT", "0")

    # ROADMAP item 3 remainder: the persistent executable cache is ON
    # for the suite (in-process legs read the flag after
    # parse_flags_from_env; guarded legs' bench.py children inherit
    # the env var).  A re-run of a measured round reloads instead of
    # recompiling, and each BENCH record's "compile_cache" blob
    # records hits/misses so a warm start is visible in the artifact.
    if os.environ.get("MEGA_COMPILE_CACHE", "1") != "0":
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        os.environ.setdefault("FLAGS_compile_cache_dir",
                              os.path.join(repo, ".pcache"))
    from paddle_tpu.utils import flags as pt_flags

    pt_flags.parse_flags_from_env()

    done_path = os.path.join(os.path.dirname(bench._LAST_TPU_PATH),
                             "docs", "mega_done.json")
    try:
        with open(done_path) as f:
            done = json.load(f)
    except (OSError, ValueError):
        done = {}

    # hard wall-clock bound per guarded leg; 0 disables the guard (all
    # legs stay in-process, the pre-guard behavior)
    leg_timeout = float(os.environ.get("MEGA_LEG_TIMEOUT", "2400"))
    guard_all = os.environ.get("MEGA_SUBPROC") == "all"

    # claim lazily, only when an IN-PROCESS leg actually runs: a
    # guarded leg's bench.py child makes its own claim, and on an
    # exclusive-claim runtime a parent already holding the chip would
    # wedge every child (bench.py:115's probe runs before any parent
    # claim for the same reason)
    claimed = []

    def claim():
        if not claimed:
            import jax

            print("[mega] claiming: %s" % jax.devices(), flush=True)
            claimed.append(True)

    ok = skipped = failed = timed_out = 0
    for name, overrides in CONFIGS:
        if names is not None and name not in names:
            continue
        if done.get(name, 0) >= since:
            print("[mega] %s already captured — skipping" % name,
                  flush=True)
            continue
        before = _fresh_records(since)
        t0 = time.perf_counter()
        print("[mega] --- %s ---" % name, flush=True)
        if leg_timeout > 0 and (guard_all or name in RISKY):
            status, blob = run_one_guarded(name, overrides, leg_timeout)
        else:
            claim()
            blob = run_one(name, overrides)
            status = "ok" if blob is not None else "failed"
        if status == "skipped":
            timed_out += 1
            continue
        if status == "ok":
            gained = _fresh_records(since) - before
            _attach_metrics(gained, blob)
            _warn_stale_platform(name, gained)
            if gained:
                ok += 1
                done[name] = time.time()
                with open(done_path, "w") as f:
                    json.dump(done, f, indent=1)
                print("[mega] %s OK in %.0fs -> %s"
                      % (name, time.perf_counter() - t0,
                         sorted(gained)), flush=True)
            else:
                # ran but persisted nothing fresh: it was already
                # captured (bench skips nothing itself) or ran on CPU
                skipped += 1
                print("[mega] %s ran without a fresh TPU record "
                      "(%.0fs)" % (name, time.perf_counter() - t0),
                      flush=True)
        else:
            failed += 1
    print("[mega] done: %d measured, %d no-record, %d failed, "
          "%d compile-timeout" % (ok, skipped, failed, timed_out),
          flush=True)


if __name__ == "__main__":
    main()
