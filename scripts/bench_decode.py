"""Micro-benchmark: compiled dense beam search vs the host-op path.

Companion to docs/DESIGN_jit_beam_search.md.  Same scorer both ways:

  * jit-dense — models/decode.beam_search_decode_dense: [batch, beam]
    state, lax.top_k per step, one compiled scan to max_len (the
    generation hot path on TPU).
  * host-op  — the reference-parity LoD bookkeeping (ops/beam.py
    beam_search kernel) driven one step at a time from Python, the way
    the fluid while-loop program executes it (reference:
    beam_search_op.cc registers CPU-only, so every step is a
    device->host->device round-trip there too).

Prints one JSON line per path.
"""

import json
import os
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from paddle_tpu.core.ragged import RaggedTensor
from paddle_tpu.models.decode import beam_search_decode_dense
from paddle_tpu.ops.registry import get_op_info


def make_scorer(V, C, seed=0):
    rs = np.random.RandomState(seed)
    table = rs.randn(V, C, V).astype(np.float32)
    jtable = jnp.asarray(table)

    def step_fn(state, tok):
        t = state["t"]
        return jtable[tok, jnp.minimum(t, C - 1)], {"t": t + 1}

    return step_fn, table


def bench_jit_dense(step_fn, B, K, L, iters=5):
    state = {"t": jnp.zeros((B,), jnp.int32)}
    fn = jax.jit(lambda s: beam_search_decode_dense(
        step_fn, s, bos=1, eos=0, beam_size=K, max_len=L, batch_size=B))
    seqs, scores = fn(state)          # compile
    jax.block_until_ready(seqs)
    t0 = time.perf_counter()
    for _ in range(iters):
        seqs, scores = fn(state)
    jax.block_until_ready(seqs)
    dt = (time.perf_counter() - t0) / iters
    return B * L / dt, seqs


def bench_host_op(table, B, K, L, iters=3):
    """Per-step host bookkeeping: softmax+topk on device-side arrays is
    simulated with numpy (the op itself is numpy), beam state carried
    the way the fluid while-loop program carries it."""
    beam = get_op_info("beam_search").kernel
    V = table.shape[0]
    C = table.shape[1]

    def run_once():
        # beam rows per source; start with one bos row per source
        toks = np.full((B, 1), 1, np.int64)       # [rows, 1]
        src_of = np.arange(B)
        scores = np.zeros((B,), np.float32)
        steps_run = 0
        for t in range(L):
            rows = toks.shape[0]
            logits = table[toks[:, 0], min(t, C - 1)]
            logp = logits - np.log(
                np.exp(logits - logits.max(1, keepdims=True))
                .sum(1, keepdims=True)) - logits.max(1, keepdims=True)
            # per-row candidate top-K (the program's topk before the op)
            cand = np.argsort(-logp, axis=1)[:, :K]
            cand_scores = scores[:, None] + np.take_along_axis(
                logp, cand, axis=1)
            high = np.searchsorted(src_of, np.arange(B + 1))
            ids = RaggedTensor(
                cand.astype(np.int64),
                [high.astype(np.int64),
                 np.arange(rows + 1, dtype=np.int64)])
            sc = RaggedTensor(
                cand_scores.astype(np.float32),
                [high.astype(np.int64),
                 np.arange(rows + 1, dtype=np.int64)])
            outs = beam(None, {"pre_ids": [toks], "ids": [ids],
                               "scores": [sc]},
                        {"beam_size": K, "end_id": 0, "level": 0})
            sel = outs["selected_ids"][0]
            sel_ids = np.asarray(sel.values).reshape(-1).astype(np.int64)
            if sel_ids.size == 0:
                break
            splits = np.asarray(sel.row_splits[-1])
            per_row = splits[1:] - splits[:-1]
            src_of = np.repeat(src_of, per_row)
            scores = np.asarray(
                outs["selected_scores"][0].values).reshape(-1)
            toks = sel_ids[:, None]
            steps_run += 1
        return steps_run

    run_once()
    t0 = time.perf_counter()
    steps = 0
    for _ in range(iters):
        steps += run_once()
    dt = time.perf_counter() - t0
    # credit only the decode steps that actually ran (beams can finish
    # before L) so the throughput comparison stays honest
    return B * steps / dt


def main():
    B = int(os.environ.get("DECODE_BATCH", "8"))
    K = int(os.environ.get("DECODE_BEAM", "4"))
    L = int(os.environ.get("DECODE_LEN", "32"))
    V = int(os.environ.get("DECODE_VOCAB", "512"))
    step_fn, table = make_scorer(V, C=8)

    tps, _ = bench_jit_dense(step_fn, B, K, L)
    print(json.dumps({"path": "jit-dense", "tokens_per_sec": round(tps, 1),
                      "batch": B, "beam": K, "len": L, "vocab": V,
                      "platform": jax.devices()[0].platform}))
    tps_h = bench_host_op(table, B, K, L)
    print(json.dumps({"path": "host-op", "tokens_per_sec": round(tps_h, 1),
                      "batch": B, "beam": K, "len": L, "vocab": V,
                      "platform": "cpu-host"}))


if __name__ == "__main__":
    main()
