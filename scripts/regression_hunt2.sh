#!/usr/bin/env bash
# Phase-2 regression hunt for the b128 ResNet-50 delta (r3config
# measured 2016.55 img/s vs the round-5 default's 1182.7 — one of
# {bf16 activations, optimizer fusion, shifted BN stats} is a ~1.7x
# regression on the real chip).  Differences from phase 1:
#
#   * COMPILE-HEALTH PROBE GATE: the wedge failure mode is the remote
#     compile service (127.0.0.1:<port>/remote_compile) blocking ~27
#     min then EOF — claims stay instant throughout.  A tiny-jit probe
#     with a 120 s timeout detects a healthy compile path for a few
#     seconds instead of discovering a wedge 27 minutes into a real
#     bench; legs only launch behind a passing probe.
#   * persistent XLA compilation cache (.jax_cache): if the serialized
#     executable round-trips, a config that ever compiled skips the
#     wedge-prone step on re-run.
#   * failed legs retry in later sweeps instead of being lost.
#
# Factor key: act(bf16/f32) x fuse(full/capped/off) x bn(shift/unshift)
#   default  = (bf16, capped, shift)   -> the round's headline config
#   r3config = (f32,  off,    unshift) -> 2016.55 measured (phase 1)
set -uo pipefail
cd "$(dirname "$0")/.."
log="docs/regression_hunt2.log"
export JAX_COMPILATION_CACHE_DIR="$PWD/.jax_cache"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=0
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
start_epoch="$(date +%s)"

say() { echo "[$(date +%H:%M:%S)] $*" | tee -a "$log"; }

compile_healthy() {  # tiny end-to-end jit through the remote compiler
  timeout 120 python -c "
import jax, jax.numpy as jnp
print(jax.jit(lambda x: x * 2 + 1)(jnp.arange(8.0))[3])" \
    >>"$log" 2>&1
}

captured() {  # captured <record-key> — measured since this hunt began
  PYTHONPATH= JAX_PLATFORMS=cpu python - "$1" "$start_epoch" <<'PY'
import json, sys
try:
    store = json.load(open("BENCH_LAST_TPU.json"))
except Exception:
    sys.exit(1)
rec = store.get(sys.argv[1])
sys.exit(0 if rec and rec.get("measured_at", 0) >= float(sys.argv[2]) - 3600
          else 1)
PY
}

run_one() {  # run_one <label> <record-key> [ENV=VAL ...]
  local label="$1" key="$2"; shift 2
  captured "$key" && { say "$label already captured — skipping"; return 0; }
  until compile_healthy; do
    say "compile path wedged; probe again in 300s (pending: $label)"
    sleep 300
  done
  say "$label (probe healthy) ..."
  local t0=$(date +%s)
  if env BENCH_CLAIM_TIMEOUT=0 "$@" timeout 2400 python bench.py \
      >>"$log" 2>&1; then
    say "$label OK in $(( $(date +%s) - t0 ))s: $(grep -o '{.*}' "$log" | tail -1)"
    return 0
  fi
  say "$label FAILED (rc=$?) after $(( $(date +%s) - t0 ))s"
  return 1
}

for sweep in 1 2 3 4 5 6; do
  say "=== sweep $sweep ==="
  pending=0
  run_one f32act "resnet50_train_imgs_per_sec_batch128+f32act|bf16" \
    BENCH_TAG=f32act FLAGS_amp_bf16_act=0 || pending=1
  run_one nofuse "resnet50_train_imgs_per_sec_batch128+nofuse|bf16" \
    BENCH_TAG=nofuse FLAGS_fuse_optimizer=0 || pending=1
  run_one bnunshift "resnet50_train_imgs_per_sec_batch128+bnunshift|bf16" \
    BENCH_TAG=bnunshift FLAGS_bn_shifted_stats=0 || pending=1
  run_one smallfuse "resnet50_train_imgs_per_sec_batch128+smallfuse|bf16" \
    BENCH_TAG=smallfuse || pending=1
  run_one r3b256 "resnet50_train_imgs_per_sec_batch256+r3b256|bf16" \
    BENCH_TAG=r3b256 BENCH_BATCH=256 FLAGS_amp_bf16_act=0 \
    FLAGS_fuse_optimizer=0 FLAGS_bn_shifted_stats=0 || pending=1
  [ "$pending" = 0 ] && { say "all legs captured"; break; }
  say "sweep $sweep incomplete; sleeping 600"
  sleep 600
done

# Per-HLO profiles of the two ends of the factor space: the category
# deltas (copy/convert/fusion times) are the diagnosis for WHY the
# default config regressed.  Same program as the bench legs, so the
# persistent cache (if the axon backend honors it) makes these cheap.
profile_one() {  # profile_one <outfile> [ENV=VAL ...]
  local out="$1"; shift
  [ -s "$out" ] && { say "profile $out exists — skipping"; return 0; }
  until compile_healthy; do
    say "compile path wedged; probe again in 300s (pending: $out)"
    sleep 300
  done
  say "profiling -> $out"
  if env PROFILE_STEPS=10 "$@" timeout 2400 python scripts/profile_tpu.py \
      >"$out" 2>&1; then
    say "profile $out OK"
  else
    say "profile $out FAILED (rc=$?)"; return 1
  fi
}
profile_one docs/profile_r5_default.txt
profile_one docs/profile_r5_r3config.txt FLAGS_amp_bf16_act=0 \
  FLAGS_fuse_optimizer=0 FLAGS_bn_shifted_stats=0
say "done — records in BENCH_LAST_TPU.json"
