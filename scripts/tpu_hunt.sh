#!/usr/bin/env bash
# Phase-3 TPU capture loop: probe-gated, one-process suite.
#
# Wedge model learned this round (docs/PERF.md): the remote compile
# service wedges FRESH processes' first big compile (~27 min then EOF)
# while claims stay instant, and in-process follow-up compiles have
# worked back-to-back.  So: a 180 s tiny-jit probe detects a healthy
# compile path, then scripts/mega_bench.py measures EVERY pending
# config inside one process / one claim, persisting each record the
# moment it exists.  Progress survives any wedge; sweeps repeat until
# the suite is complete, then two per-HLO profiles (factor-space ends)
# close the session.
set -uo pipefail
cd "$(dirname "$0")/.."
log="docs/tpu_hunt.log"
export JAX_COMPILATION_CACHE_DIR="$PWD/.jax_cache"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=0
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
export MEGA_FRESH_SINCE="${MEGA_FRESH_SINCE:-$(( $(date +%s) - 7200 ))}"

say() { echo "[$(date +%H:%M:%S)] $*" | tee -a "$log"; }

compile_healthy() {
  timeout 180 python -c "
import jax, jax.numpy as jnp
print(jax.jit(lambda x: x * 2 + 1)(jnp.arange(8.0))[3])" \
    >>"$log" 2>&1
}

all_done() {
  PYTHONPATH= JAX_PLATFORMS=cpu python - "$MEGA_FRESH_SINCE" <<'PY'
import json, sys
sys.path.insert(0, ".")
from scripts.mega_bench import CONFIGS
try:
    done = json.load(open("docs/mega_done.json"))
except Exception:
    done = {}
since = float(sys.argv[1])
missing = [n for n, _ in CONFIGS if done.get(n, 0) < since]
print("missing: %s" % (",".join(missing) or "none"))
sys.exit(0 if not missing else 1)
PY
}

profile_one() {  # profile_one <outfile> [ENV=VAL ...]
  local out="$1"; shift
  [ -s "$out" ] && { say "profile $out exists — skipping"; return 0; }
  until compile_healthy; do
    say "compile path wedged; probe again in 480s (pending: $out)"
    sleep 480
  done
  say "profiling -> $out"
  if env PROFILE_STEPS=10 "$@" timeout 2400 python scripts/profile_tpu.py \
      >"$out" 2>&1; then
    say "profile $out OK"
  else
    say "profile $out FAILED (rc=$?)"; return 1
  fi
}

sweep=0
while true; do
  sweep=$((sweep + 1))
  if all_done >>"$log" 2>&1; then
    say "suite complete after $((sweep - 1)) sweeps"
    break
  fi
  if compile_healthy; then
    say "sweep $sweep: compile path healthy — running mega_bench"
    if timeout 10800 python scripts/mega_bench.py >>"$log" 2>&1; then
      say "sweep $sweep: mega_bench finished"
    else
      say "sweep $sweep: mega_bench exited rc=$? (wedge mid-suite?)"
    fi
  else
    say "sweep $sweep: compile path wedged; sleeping 480"
    sleep 480
    continue
  fi
  sleep 60
done

profile_one docs/profile_r5_default.txt
profile_one docs/profile_r5_r3config.txt FLAGS_amp_bf16_act=0 \
  FLAGS_fuse_optimizer=0 FLAGS_bn_shifted_stats=0
say "phase-3 hunt done"
