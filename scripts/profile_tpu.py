"""Device-time profile of a bench model's train step.

Runs the same jitted step as bench.py under `jax.profiler.trace` and
aggregates on-device time by XLA `hlo_category` (the trace events carry
per-instruction category / FLOPs / bytes metadata), printing a table
like the reference's ParseEvents summary but at HLO granularity
(reference: paddle/platform/profiler.h:133-146).

Usage (from the repo root, on the TPU or CPU):
    python scripts/profile_tpu.py            # resnet50, batch 128
    BENCH_MODEL=vgg16 BENCH_BATCH=64 python scripts/profile_tpu.py

NOTE: the "is this leg compute/HBM/input/host bound" triage that used
to be read by hand off this table now lives in `pperf classify` and
the per-leg BENCH "perf" blob (paddle_tpu.obs.perf, docs/PERF.md);
this script remains the drill-down for per-HLO device time once the
classifier has named the bottleneck.
"""

import collections
import glob
import gzip
import json
import os
import sys
import tempfile

import numpy as np


def aggregate_trace(trace_dir):
    paths = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    cat = collections.Counter()
    flops = collections.Counter()
    per_op = collections.defaultdict(collections.Counter)
    shapes = {}
    for p in paths:
        with gzip.open(p, "rt") as f:
            data = json.load(f)
        for ev in data.get("traceEvents", []):
            args = ev.get("args") or {}
            if ev.get("ph") != "X" or "hlo_category" not in args:
                continue
            dur = int(args.get("device_duration_ps", 0))
            c = args["hlo_category"]
            cat[c] += dur
            per_op[c][ev["name"]] += dur
            shapes.setdefault(ev["name"],
                              args.get("shape_with_layout", ""))
            try:
                flops[c] += float(args.get("model_flops") or 0)
            except (TypeError, ValueError):
                pass
    return cat, flops, per_op, shapes


def main():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    steps = int(os.environ.get("PROFILE_STEPS", "10"))

    import jax
    import bench

    model = os.environ.get("BENCH_MODEL", "resnet50")
    batch = int(os.environ.get("BENCH_BATCH", "128"))

    import paddle_tpu.fluid as fluid
    from paddle_tpu.jit import FunctionalProgram, state_from_scope
    from paddle_tpu.fluid.executor import RNG_STATE_NAME

    if os.environ.get("BENCH_AMP", "1") != "0":
        fluid.amp.enable_bf16()
    image_size = int(os.environ.get(
        "BENCH_IMAGE_SIZE", "32" if model == "smallnet" else "224"))
    class_dim = int(os.environ.get(
        "BENCH_CLASS_DIM", "10" if model == "smallnet" else "1000"))
    main_prog, startup, _, avg_loss = bench._build_image_model(
        model, batch, image_size, class_dim)

    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    fp = FunctionalProgram(main_prog, ["image", "label"], [avg_loss.name])
    dev = jax.devices()[0]
    state = {n: jax.device_put(np.asarray(v), dev)
             for n, v in state_from_scope(fp, scope).items()}
    state[RNG_STATE_NAME] = jax.device_put(jax.random.PRNGKey(0), dev)
    feeds = jax.device_put(
        bench._image_feeds(batch, image_size, class_dim), dev)
    step = jax.jit(lambda s, f: fp(s, f), donate_argnums=(0,))

    for _ in range(3):
        fetches, state = step(state, feeds)
    jax.block_until_ready(fetches)

    trace_dir = tempfile.mkdtemp(prefix="paddle_tpu_profile_")
    with jax.profiler.trace(trace_dir):
        for _ in range(steps):
            fetches, state = step(state, feeds)
        jax.block_until_ready(fetches)

    cat, flops, per_op, shapes = aggregate_trace(trace_dir)
    total = sum(cat.values())
    if not total:
        print("no device events captured (trace dir: %s)" % trace_dir)
        return
    ms = 1.0 / (1e9 * steps)  # ps -> ms/step
    print("%s batch=%d: %.2f ms/step device time over %d steps"
          % (model, batch, total * ms, steps))
    print("%-26s %10s %7s %12s" % ("category", "ms/step", "%", "GFLOP/step"))
    for c, d in cat.most_common():
        print("%-26s %10.3f %6.1f%% %12.1f"
              % (c, d * ms, 100.0 * d / total, flops[c] / 1e9 / steps))
    print("\ntop instructions:")
    everything = collections.Counter()
    for c in per_op:
        everything.update(per_op[c])
    for name, d in everything.most_common(15):
        print("%10.3f ms/step  %-30s %s"
              % (d * ms, name[:30], shapes.get(name, "")[:60]))
    print("\ntrace: %s" % trace_dir)


if __name__ == "__main__":
    main()
