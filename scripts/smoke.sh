#!/usr/bin/env bash
# Pre-commit smoke gate: never snapshot a red HEAD again.
#   scripts/smoke.sh          -> import check + fast test subset (~1 min)
#   scripts/smoke.sh --full   -> import check + full suite
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
export XLA_FLAGS=${XLA_FLAGS:---xla_force_host_platform_device_count=8}

echo "[smoke] import paddle_tpu ..."
python -c "import paddle_tpu; import __graft_entry__; print('  ok:', len(paddle_tpu.ops.registry.registered_ops()), 'ops registered')"

if [[ "${1:-}" == "--full" ]]; then
  echo "[smoke] full test suite ..."
  python -m pytest tests/ -x -q
else
  echo "[smoke] fast subset ..."
  python -m pytest tests/test_math_ops.py tests/test_lod_machinery.py -x -q
  python -m pytest tests/ -q --collect-only >/dev/null
fi
echo "[smoke] green"
