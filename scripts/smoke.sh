#!/usr/bin/env bash
# Pre-commit smoke gate: never snapshot a red HEAD again.
#   scripts/smoke.sh          -> import check + fast test subset (~1 min)
#   scripts/smoke.sh --full   -> import check + full suite
set -euo pipefail
cd "$(dirname "$0")/.."

# Force CPU unconditionally: the session env points JAX_PLATFORMS at the
# single real TPU (axon tunnel); the gate must never contend for it.
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"

echo "[smoke] import paddle_tpu ..."
python -c "import paddle_tpu; import __graft_entry__; print('  ok:', len(paddle_tpu.ops.registry.registered_ops()), 'ops registered')"

# The two driver entry points, exactly as the driver invokes them.  Two
# rounds were red because the gate never ran these.  Fresh processes,
# no env presets beyond what this script exports.
echo "[smoke] bench.py (1 iter, tiny shapes, AMP ON — the driver default) ..."
BENCH_ITERS=1 BENCH_WARMUP=1 BENCH_BATCH=4 BENCH_IMAGE_SIZE=32 python bench.py

echo "[smoke] serving selftest (server up, one request, /metrics, drain) ..."
timeout 300 python -m paddle_tpu.tools.serve_cli --selftest

echo "[smoke] obs selftest (traced train+serve, request tracing: traceparent/request_id/exemplar/tail ring, NaN health+flight loop, Perfetto JSON, unified /metrics) ..."
timeout 300 python -m paddle_tpu.tools.obs_dump --selftest

echo "[smoke] chaos selftest (injected I/O fault + preemption + nonfinite; auto-resume must match fault-free run) ..."
timeout 300 python -m paddle_tpu.tools.chaos_cli --selftest

echo "[smoke] pelastic selftest (view-change protocol + simulated-fleet shrink/grow + 2-worker SIGTERM chaos drill) ..."
timeout 600 python -m paddle_tpu.tools.elastic_cli --selftest

echo "[smoke] pcc selftest (persistent compile cache: cold->warm reload, quarantine, rewrite passes incl. layout+fuse opt pipeline) ..."
timeout 300 python -m paddle_tpu.tools.pcache_cli --selftest

echo "[smoke] pperf selftest (regression gate, step profiler, SLO burn, warm pcache blob) ..."
timeout 300 python -m paddle_tpu.tools.perf_cli --selftest

echo "[smoke] pload selftest (open vs closed loop omission gap, tail join, replay fidelity, latency gate) ..."
timeout 300 python -m paddle_tpu.tools.load_cli --selftest

echo "[smoke] pmem selftest (memory timeline, drift join + calibration, A-coded donation audit + off/auto delta, OOM flight bundle) ..."
timeout 300 python -m paddle_tpu.tools.mem_cli --selftest

echo "[smoke] pcomm selftest (comm spans, overlap split, cross-host merge, comm gate) ..."
timeout 300 python -m paddle_tpu.tools.comm_cli --selftest

echo "[smoke] ptune selftest (deterministic plan, S002/S005 rejected pre-measurement, measured top-K + calibration) ..."
timeout 600 python -m paddle_tpu.tools.tune_cli --selftest

echo "[smoke] proglint selftest (verifier + hazard detector + executor verify gate + sharding analyzer over the 4 dryrun meshes + donation A-code corruptions) ..."
timeout 300 python -m paddle_tpu.tools.lint_cli --selftest --mesh dp=4,mp=2

echo "[smoke] pshard selftest (rule precedence, plan round-trip, plan-driven SPMD step, sharded ckpt) ..."
timeout 300 python -m paddle_tpu.tools.shard_cli --selftest

echo "[smoke] pshard plan (lenet5 on dp=4,mp=2 zero1 — the reviewable layout artifact) ..."
_plan=$(mktemp)
timeout 300 python -m paddle_tpu.tools.shard_cli plan --model lenet5 \
    --mesh dp=4,mp=2 --batch 64 --zero-stage 1 --out "$_plan"
rm -f "$_plan"

echo "[smoke] MULTICHIP legs (SPMD scaling across 2 mesh shapes, comm measured vs ring floor) ..."
BENCH_MULTICHIP="dp=8|dp=4,mp=2" BENCH_MODEL=lenet5 BENCH_ITERS=2 \
    BENCH_WARMUP=1 BENCH_PEAK_TFLOPS=0.05 \
    timeout 600 python bench.py

echo "[smoke] dryrun_multichip(8) ..."
# The gate's copy of the driver dryrun, pinned to the virtual CPU mesh
# this script already exports: the old `JAX_PLATFORMS=axon XLA_FLAGS=`
# form cleared the device-count flag and then fought the session's TPU
# tunnel for the real chip — exactly what the header forbids.  timeout
# turns a bootstrap regression into a loud fail.
timeout 300 env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -c "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"

if [[ "${1:-}" == "--full" ]]; then
  echo "[smoke] full test suite ..."
  python -m pytest tests/ -x -q
else
  echo "[smoke] fast subset ..."
  python -m pytest tests/test_math_ops.py tests/test_lod_machinery.py -x -q
  python -m pytest tests/ -q --collect-only >/dev/null
fi
echo "[smoke] green"
