#!/usr/bin/env bash
# CI pipeline (reference: the Travis + docker build flow,
# paddle/scripts/travis + docker/build.sh): style-ish checks, native
# build, full test suite, both driver entry points, and a wheel.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
# the axon sitecustomize on the default PYTHONPATH performs the TPU
# claim handshake at interpreter start of EVERY python process — even
# JAX_PLATFORMS=cpu ones.  CI must never contend with the bench
# watcher for the chip, so drop it entirely.
export PYTHONPATH=

echo "[ci] compile check (syntax across the tree) ..."
python -m compileall -q paddle_tpu tests examples bench.py \
    __graft_entry__.py

echo "[ci] native runtime build ..."
make -C native

echo "[ci] full test suite (examples run for real, small shapes) ..."
# tier-1 includes tests/test_serving.py (engine/batcher/server, not
# slow-marked)
RUN_EXAMPLES=1 python -m pytest tests/ -q

echo "[ci] serving selftest (server up, one request, /metrics, drain) ..."
timeout 300 python -m paddle_tpu.tools.serve_cli --selftest

echo "[ci] obs selftest (traced train+serve, request tracing: traceparent/request_id/exemplar/tail ring, NaN health+flight loop, Perfetto JSON, unified /metrics) ..."
timeout 300 python -m paddle_tpu.tools.obs_dump --selftest

echo "[ci] chaos selftest (injected I/O fault + SIGTERM preemption + nonfinite step; supervised run must match fault-free params) ..."
timeout 300 python -m paddle_tpu.tools.chaos_cli --selftest

echo "[ci] pcc selftest (cold compile populates cache, restart reload = 0 XLA compiles, corrupt entry quarantined, rewrite passes bit-identical, layout+fuse pipeline keys distinct + warm reloads) ..."
timeout 300 python -m paddle_tpu.tools.pcache_cli --selftest

echo "[ci] pperf selftest (gate discriminates 20% regression + tpu-stale, step profiler ring/exports, loopback SLO burn, warm pcache blob) ..."
timeout 300 python -m paddle_tpu.tools.perf_cli --selftest

echo "[ci] pmem selftest (static timeline + counter track, static-vs-XLA drift join on lenet5 with calibration blob, donation audit finds a forked Adam slot, forced-tiny-budget OOM flight bundle blames the peak buffer) ..."
timeout 300 python -m paddle_tpu.tools.mem_cli --selftest

echo "[ci] ptune selftest (deterministic plan, S002/S005 rejected pre-measurement, top-K measured with config blobs, calibration error shrinks) ..."
timeout 600 python -m paddle_tpu.tools.tune_cli --selftest

echo "[ci] proglint selftest (verifier corruptions + sharding analyzer: lenet5/golden clean on 4 dryrun meshes, seeded S-code corruptions) ..."
timeout 300 python -m paddle_tpu.tools.lint_cli --selftest --mesh dp=4,mp=2

echo "[ci] proglint golden fixtures (checked-in IR must be well-formed, not just pinned) ..."
timeout 300 python -m paddle_tpu.tools.lint_cli --golden --quiet

echo "[ci] proglint --golden over POST-PASS programs (a rewrite pass can never emit a program the linter would reject; auto_remat forced via budget_gb=0) ..."
timeout 300 python -m paddle_tpu.tools.lint_cli --golden --quiet \
    --passes "default+layout:force=1+fuse+auto_remat:stride=4:budget_gb=0"

echo "[ci] proglint --mesh over the four dryrun mesh shapes (pinned IR must also SHARD clean) ..."
for mesh in dp=4,mp=2 dp=2,mp=2,sp=2 pp=4,dp=2 dp=2,ep=4; do
    timeout 300 python -m paddle_tpu.tools.lint_cli --golden --quiet \
        --mesh "$mesh"
done

echo "[ci] driver entry points ..."
# two bench runs against one persistent compile cache: the cold run
# populates it, the warm rerun's stamped compile_cache blob must show
# hits (ROADMAP item 3: the cache is now ON for bench/mega_bench legs)
_pcc_dir=$(mktemp -d)
_hist=$(mktemp)
BENCH_ITERS=1 BENCH_WARMUP=1 BENCH_BATCH=4 BENCH_IMAGE_SIZE=32 \
    FLAGS_compile_cache_dir="$_pcc_dir" BENCH_HISTORY="$_hist" \
    python bench.py
BENCH_ITERS=1 BENCH_WARMUP=1 BENCH_BATCH=4 BENCH_IMAGE_SIZE=32 \
    FLAGS_compile_cache_dir="$_pcc_dir" BENCH_HISTORY="$_hist" \
    python bench.py | python -c "
import json, sys
rec = json.loads(sys.stdin.readline())
cc = rec.get('compile_cache') or {}
assert cc.get('hits', 0) > 0, 'warm bench rerun reported no compile-cache hits: %r' % cc
assert rec.get('perf') and rec['perf'].get('verdict'), 'BENCH record carries no perf blob: %r' % rec.get('perf')
print('[ci] warm bench leg: %d pcache hits, verdict %s' % (cc['hits'], rec['perf']['verdict']))
"
rm -rf "$_pcc_dir" "$_hist"
# the dryrun is DEFINED on virtual CPU devices; never claim the real
# chip from CI — a wedged claim would starve the bench watcher
timeout 900 python -c \
    "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"

echo "[ci] wheel build ..."
# --no-build-isolation: build with the env's setuptools (works offline)
pip wheel --no-deps --no-build-isolation -w dist/ . >/dev/null
ls -l dist/*.whl

echo "[ci] green"
