#!/usr/bin/env bash
# CI pipeline (reference: the Travis + docker build flow,
# paddle/scripts/travis + docker/build.sh): style-ish checks, native
# build, full test suite, both driver entry points, and a wheel.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
# the axon sitecustomize on the default PYTHONPATH performs the TPU
# claim handshake at interpreter start of EVERY python process — even
# JAX_PLATFORMS=cpu ones.  CI must never contend with the bench
# watcher for the chip, so drop it entirely.
export PYTHONPATH=

echo "[ci] compile check (syntax across the tree) ..."
python -m compileall -q paddle_tpu tests examples bench.py \
    __graft_entry__.py

echo "[ci] native runtime build ..."
make -C native

echo "[ci] full test suite (examples run for real, small shapes) ..."
# tier-1 includes tests/test_serving.py (engine/batcher/server, not
# slow-marked)
RUN_EXAMPLES=1 python -m pytest tests/ -q

echo "[ci] serving selftest (server up, one request, /metrics, drain) ..."
timeout 300 python -m paddle_tpu.tools.serve_cli --selftest

echo "[ci] obs selftest (traced train+serve, request tracing: traceparent/request_id/exemplar/tail ring, NaN health+flight loop, Perfetto JSON, unified /metrics) ..."
timeout 300 python -m paddle_tpu.tools.obs_dump --selftest

echo "[ci] chaos selftest (injected I/O fault + SIGTERM preemption + nonfinite step; supervised run must match fault-free params) ..."
timeout 300 python -m paddle_tpu.tools.chaos_cli --selftest

echo "[ci] pelastic selftest (two-phase view-change protocol over a real master with lease expiry, simulated-fleet dp 8->4->8 with densify restore, 2 real workers with one SIGTERM'd mid-step: shrink commit + shard-exact continue + rejoin grow) ..."
timeout 600 python -m paddle_tpu.tools.elastic_cli --selftest

echo "[ci] pcc selftest (cold compile populates cache, restart reload = 0 XLA compiles, corrupt entry quarantined, rewrite passes bit-identical, layout+fuse pipeline keys distinct + warm reloads) ..."
timeout 300 python -m paddle_tpu.tools.pcache_cli --selftest

echo "[ci] pperf selftest (gate discriminates 20% regression + tpu-stale, step profiler ring/exports, loopback SLO burn, warm pcache blob) ..."
timeout 300 python -m paddle_tpu.tools.perf_cli --selftest

echo "[ci] pload selftest (open-loop p99 surfaces an injected stall closed-loop hides, worst request joins its /debug/tail span tree, access-log replay reproduces count + bucket mix, latency blob -> pperf gate --latency-tolerance verdict) ..."
timeout 300 python -m paddle_tpu.tools.load_cli --selftest

echo "[ci] pmem selftest (static timeline + counter track, static-vs-XLA drift join on lenet5 with calibration blob, donation audit finds a forked Adam slot, forced-tiny-budget OOM flight bundle blames the peak buffer) ..."
timeout 300 python -m paddle_tpu.tools.mem_cli --selftest

echo "[ci] pcomm selftest (per-bucket comm spans in reduce order, overlap exposed-vs-hidden split, cross-host span merge with recovered clock skew, drift blob -> ptune comm coef, comm gate discriminates) ..."
timeout 300 python -m paddle_tpu.tools.comm_cli --selftest

echo "[ci] ptune selftest (deterministic plan, S002/S005 rejected pre-measurement, top-K measured with config blobs, calibration error shrinks) ..."
timeout 600 python -m paddle_tpu.tools.tune_cli --selftest

echo "[ci] pshard selftest (rule precedence, rules reshape the layout, plan save/load fingerprint-stable, plan-driven SPMD step on 8 devices, sharded checkpoint round-trip with zero densified vars) ..."
timeout 300 python -m paddle_tpu.tools.shard_cli --selftest

echo "[ci] pshard plan (zero-device layout build: the dp=4,mp=2 zero1 artifact must render and carry a comm floor) ..."
_plan=$(mktemp)
timeout 300 python -m paddle_tpu.tools.shard_cli plan --model lenet5 \
    --mesh dp=4,mp=2 --batch 64 --zero-stage 1 --out "$_plan" \
    | grep -q "comm:" || {
        echo "[ci] pshard plan rendered no comm floor" >&2; exit 1; }
timeout 300 python -m paddle_tpu.tools.shard_cli show --plan "$_plan" \
    >/dev/null
rm -f "$_plan"

echo "[ci] proglint selftest (verifier corruptions + sharding analyzer: lenet5/golden clean on 4 dryrun meshes, seeded S-code corruptions) ..."
timeout 300 python -m paddle_tpu.tools.lint_cli --selftest --mesh dp=4,mp=2

echo "[ci] proglint golden fixtures (checked-in IR must be well-formed, not just pinned) ..."
timeout 300 python -m paddle_tpu.tools.lint_cli --golden --quiet

echo "[ci] proglint --golden over POST-PASS programs (a rewrite pass can never emit a program the linter would reject; auto_remat forced via budget_gb=0) ..."
timeout 300 python -m paddle_tpu.tools.lint_cli --golden --quiet \
    --passes "default+layout:force=1+fuse+auto_remat:stride=4:budget_gb=0"

echo "[ci] proglint --mesh over the four dryrun mesh shapes (pinned IR must also SHARD clean) ..."
for mesh in dp=4,mp=2 dp=2,mp=2,sp=2 pp=4,dp=2 dp=2,ep=4; do
    timeout 300 python -m paddle_tpu.tools.lint_cli --golden --quiet \
        --mesh "$mesh"
done

echo "[ci] proglint --donation over golden fixtures (alias analysis must plan every pinned program with 0 errors) ..."
timeout 300 python -m paddle_tpu.tools.lint_cli --golden --quiet \
    --donation

echo "[ci] pmem audit under FLAGS_donation=auto (lenet5 must have 0 reclaimable bytes: everything provably donatable is donated or carries an A-code) ..."
timeout 300 env FLAGS_donation=auto python -m paddle_tpu.tools.mem_cli \
    audit --model lenet5 --json | python -c "
import json, sys
a = json.load(sys.stdin)
assert a['effective_mode'] == 'auto', a.get('effective_mode')
assert a['reclaimable_bytes'] == 0, \
    'lenet5 under auto left %d reclaimable bytes: %r' \
    % (a['reclaimable_bytes'], a['reclaimable'])
print('[ci] lenet5 donation audit: %d bytes donated, 0 reclaimable'
      % a['donated_bytes'])
"

echo "[ci] driver entry points ..."
# two bench runs against one persistent compile cache: the cold run
# populates it, the warm rerun's stamped compile_cache blob must show
# hits (ROADMAP item 3: the cache is now ON for bench/mega_bench legs)
_pcc_dir=$(mktemp -d)
_hist=$(mktemp)
BENCH_ITERS=1 BENCH_WARMUP=1 BENCH_BATCH=4 BENCH_IMAGE_SIZE=32 \
    FLAGS_compile_cache_dir="$_pcc_dir" BENCH_HISTORY="$_hist" \
    python bench.py
BENCH_ITERS=1 BENCH_WARMUP=1 BENCH_BATCH=4 BENCH_IMAGE_SIZE=32 \
    FLAGS_compile_cache_dir="$_pcc_dir" BENCH_HISTORY="$_hist" \
    python bench.py | python -c "
import json, sys
rec = json.loads(sys.stdin.readline())
cc = rec.get('compile_cache') or {}
assert cc.get('hits', 0) > 0, 'warm bench rerun reported no compile-cache hits: %r' % cc
assert rec.get('perf') and rec['perf'].get('verdict'), 'BENCH record carries no perf blob: %r' % rec.get('perf')
print('[ci] warm bench leg: %d pcache hits, verdict %s' % (cc['hits'], rec['perf']['verdict']))
"
rm -rf "$_pcc_dir" "$_hist"
# the MULTICHIP legs: SPMD scaling over two mesh shapes; every record
# must carry the platform_class stamp (so the gate never baselines
# 8-device runs against single-chip history) and a comm blob `ptune
# fit` can price the comm coefficient from
_mhist=$(mktemp)
BENCH_MULTICHIP="dp=8|dp=4,mp=2" BENCH_MODEL=lenet5 BENCH_ITERS=2 \
    BENCH_WARMUP=1 BENCH_PEAK_TFLOPS=0.05 BENCH_HISTORY="$_mhist" \
    timeout 600 python bench.py
python - "$_mhist" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
assert len(recs) >= 2, "MULTICHIP suite wrote %d record(s)" % len(recs)
meshes = set()
for r in recs:
    assert r.get("platform_class", "").count(":") == 2, r
    assert r.get("n_devices") == 8 and r.get("mfu") is not None, r
    comm = r.get("comm") or {}
    assert comm.get("measured_s") and comm.get("pred_s"), r
    meshes.add(tuple(sorted(r["mesh"].items())))
assert len(meshes) >= 2, "scaling curve needs >= 2 mesh shapes"
from paddle_tpu.tune import fit
pairs = fit.join_comm_history(recs)
assert len(pairs) >= 2, "ptune fit rejected the comm measurements"
print("[ci] MULTICHIP legs: %d records, %d mesh shapes, %d comm "
      "pairs for ptune fit" % (len(recs), len(meshes), len(pairs)))
EOF
rm -f "$_mhist"
# the dryrun is DEFINED on virtual CPU devices; never claim the real
# chip from CI — a wedged claim would starve the bench watcher
timeout 900 python -c \
    "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"

echo "[ci] wheel build ..."
# --no-build-isolation: build with the env's setuptools (works offline)
pip wheel --no-deps --no-build-isolation -w dist/ . >/dev/null
ls -l dist/*.whl

echo "[ci] green"
