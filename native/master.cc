// Master task-queue service: dataset chunks -> leased tasks with
// timeouts and a failure cap, snapshot/recover to disk.
//
// TPU-native equivalent of the reference Go master
// (reference: go/master/service.go:89 — partition:106, GetTask:368,
// TaskFinished:411, TaskFailed:455, checkTimeoutFunc:341,
// processFailedTask:313, snapshot:207/recover:166 via etcd; here
// snapshot goes to a local file and discovery is by host:port).
#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "paddle_tpu_rt.h"
#include "transport.h"

namespace ptrt {
namespace {

enum Op : uint32_t {
  kSetDataset = 20,
  kGetTask = 21,
  kTaskFinished = 22,
  kTaskFailed = 23,
  // etcd-style TTL-lease registry (reference:
  // go/pserver/etcd_client.go:31-97 — pserver slot registration with
  // TTL keep-alive; trainers discover live pservers by listing)
  kRegister = 24,
  kKeepAlive = 25,
  kUnregister = 26,
  kList = 27,
};

struct Task {
  int64_t id = 0;
  std::vector<std::string> chunks;
  int failures = 0;
};

struct Lease {
  std::string key;
  std::string value;
  int ttl_ms = 0;
  std::chrono::steady_clock::time_point deadline;
};

using Clock = std::chrono::steady_clock;

class Master {
 public:
  Master(int port, int timeout_ms, int failure_max)
      : timeout_ms_(timeout_ms), failure_max_(failure_max),
        server_(port, [this](uint32_t op, Reader &r, Writer &w) {
          handle(op, r, w);
        }) {
    timeout_thread_ = std::thread([this] { timeoutLoop(); });
  }

  ~Master() { stop(); }

  void stop() {
    {
      std::lock_guard<std::mutex> g(mu_);
      if (stopping_) return;
      stopping_ = true;
    }
    server_.stop();
    if (timeout_thread_.joinable()) timeout_thread_.join();
  }

  int port() const { return server_.port(); }

  int snapshot(const char *path) {
    std::lock_guard<std::mutex> g(mu_);
    Writer w;
    auto dump = [&w](const std::vector<Task> &ts) {
      w.u64(ts.size());
      for (const Task &t : ts) {
        w.i64(t.id);
        w.u32(static_cast<uint32_t>(t.failures));
        w.u64(t.chunks.size());
        for (const auto &c : t.chunks) w.str(c);
      }
    };
    std::vector<Task> pending_all = todo_;
    for (auto &kv : pending_) pending_all.push_back(kv.second.first);
    dump(pending_all);  // leased tasks go back to todo on recover
    dump(done_);
    dump(discarded_);
    w.i64(next_id_);
    w.u32(dataset_set_ ? 1 : 0);
    FILE *f = fopen(path, "wb");
    if (!f) return -1;
    uint32_t crc = crc32(w.buf.data(), w.buf.size());
    uint64_t n = w.buf.size();
    fwrite(&crc, 4, 1, f);
    fwrite(&n, 8, 1, f);
    fwrite(w.buf.data(), 1, n, f);
    fclose(f);
    return 0;
  }

  int recover(const char *path) {
    FILE *f = fopen(path, "rb");
    if (!f) return -1;
    uint32_t crc = 0;
    uint64_t n = 0;
    if (fread(&crc, 4, 1, f) != 1 || fread(&n, 8, 1, f) != 1 ||
        n > (1ull << 32)) {
      fclose(f);
      return -2;
    }
    std::vector<uint8_t> buf(n);
    if (fread(buf.data(), 1, n, f) != n) { fclose(f); return -2; }
    fclose(f);
    if (crc32(buf.data(), n) != crc) return -3;  // corrupted snapshot
    std::lock_guard<std::mutex> g(mu_);
    Reader r(buf.data(), n);
    auto slurp = [&r](std::vector<Task> *ts) {
      uint64_t cnt = r.u64();
      ts->clear();
      for (uint64_t i = 0; i < cnt; ++i) {
        Task t;
        t.id = r.i64();
        t.failures = static_cast<int>(r.u32());
        uint64_t nc = r.u64();
        for (uint64_t k = 0; k < nc; ++k) t.chunks.push_back(r.str());
        ts->push_back(std::move(t));
      }
    };
    slurp(&todo_);
    slurp(&done_);
    slurp(&discarded_);
    next_id_ = r.i64();
    dataset_set_ = r.u32() != 0;
    pending_.clear();
    return 0;
  }

 private:
  void expireLeasesLocked(Clock::time_point now) {
    for (auto it = leases_.begin(); it != leases_.end();) {
      if (now >= it->second.deadline)
        it = leases_.erase(it);
      else
        ++it;
    }
  }

  bool keyHeldLocked(const std::string &key, Clock::time_point now) {
    for (auto &kv : leases_)
      if (kv.second.key == key && now < kv.second.deadline) return true;
    return false;
  }

  void timeoutLoop() {
    // requeue leased tasks whose lease expired (reference:
    // go/master checkTimeoutFunc:341)
    while (true) {
      {
        std::lock_guard<std::mutex> g(mu_);
        if (stopping_) return;
        auto now = Clock::now();
        expireLeasesLocked(now);
        for (auto it = pending_.begin(); it != pending_.end();) {
          auto age = std::chrono::duration_cast<std::chrono::milliseconds>(
                         now - it->second.second)
                         .count();
          if (age > timeout_ms_) {
            Task t = std::move(it->second.first);
            it = pending_.erase(it);
            failTaskLocked(std::move(t));
          } else {
            ++it;
          }
        }
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(
          std::max(10, timeout_ms_ / 4)));
    }
  }

  void failTaskLocked(Task t) {
    t.failures++;
    if (t.failures >= failure_max_) {
      // poisoned task discarded (reference: processFailedTask:313)
      discarded_.push_back(std::move(t));
    } else {
      todo_.push_back(std::move(t));
    }
  }

  void handle(uint32_t op, Reader &r, Writer &w) {
    switch (op) {
      case kSetDataset: {
        uint64_t n = r.u64();
        int per_task = static_cast<int>(r.u32());
        std::lock_guard<std::mutex> g(mu_);
        if (!dataset_set_) {  // first caller wins (SetDataset:280)
          std::vector<std::string> chunks;
          for (uint64_t i = 0; i < n; ++i) chunks.push_back(r.str());
          for (size_t i = 0; i < chunks.size();
               i += static_cast<size_t>(per_task)) {
            Task t;
            t.id = next_id_++;
            for (size_t k = i;
                 k < std::min(chunks.size(),
                              i + static_cast<size_t>(per_task));
                 ++k)
              t.chunks.push_back(chunks[k]);
            todo_.push_back(std::move(t));
          }
          dataset_set_ = true;
        }
        w.u32(0);
        break;
      }
      case kGetTask: {
        std::lock_guard<std::mutex> g(mu_);
        if (todo_.empty()) {
          bool pass_done = pending_.empty() && dataset_set_;
          if (pass_done && !done_.empty()) {
            // report pass end once, then recycle finished tasks so the
            // next get_task starts a fresh pass (reference: go/master
            // rotates todo/done queues between passes)
            todo_ = std::move(done_);
            done_.clear();
          }
          w.u32(pass_done ? 2u : 1u);  // 2: pass finished, 1: retry
          return;
        }
        Task t = todo_.front();
        todo_.erase(todo_.begin());
        int64_t id = t.id;
        std::string joined;
        for (size_t i = 0; i < t.chunks.size(); ++i) {
          if (i) joined += "\n";
          joined += t.chunks[i];
        }
        pending_[id] = {std::move(t), Clock::now()};
        w.u32(0);
        w.i64(id);
        w.str(joined);
        break;
      }
      case kTaskFinished: {
        int64_t id = r.i64();
        std::lock_guard<std::mutex> g(mu_);
        auto it = pending_.find(id);
        if (it != pending_.end()) {
          done_.push_back(std::move(it->second.first));
          pending_.erase(it);
        }
        w.u32(0);
        break;
      }
      case kTaskFailed: {
        int64_t id = r.i64();
        std::lock_guard<std::mutex> g(mu_);
        auto it = pending_.find(id);
        if (it != pending_.end()) {
          Task t = std::move(it->second.first);
          pending_.erase(it);
          failTaskLocked(std::move(t));
        }
        w.u32(0);
        break;
      }
      case kRegister: {
        std::string key = r.str();
        std::string value = r.str();
        int ttl_ms = static_cast<int>(r.u32());
        std::lock_guard<std::mutex> g(mu_);
        auto now = Clock::now();
        expireLeasesLocked(now);
        if (keyHeldLocked(key, now)) {
          // slot taken by a live lease (reference: etcd CAS on the
          // pserver index key — the caller retries another slot or
          // waits for the TTL to lapse)
          w.u32(1);
          return;
        }
        Lease l;
        l.key = std::move(key);
        l.value = std::move(value);
        l.ttl_ms = std::max(1, ttl_ms);
        l.deadline = now + std::chrono::milliseconds(l.ttl_ms);
        int64_t id = next_lease_++;
        leases_[id] = std::move(l);
        w.u32(0);
        w.i64(id);
        break;
      }
      case kKeepAlive: {
        int64_t id = r.i64();
        std::lock_guard<std::mutex> g(mu_);
        auto now = Clock::now();
        auto it = leases_.find(id);
        if (it == leases_.end() || now >= it->second.deadline) {
          if (it != leases_.end()) leases_.erase(it);
          w.u32(1);  // lease lapsed: the holder must re-register
          return;
        }
        it->second.deadline =
            now + std::chrono::milliseconds(it->second.ttl_ms);
        w.u32(0);
        break;
      }
      case kUnregister: {
        int64_t id = r.i64();
        std::lock_guard<std::mutex> g(mu_);
        leases_.erase(id);
        w.u32(0);
        break;
      }
      case kList: {
        std::string prefix = r.str();
        std::lock_guard<std::mutex> g(mu_);
        auto now = Clock::now();
        expireLeasesLocked(now);
        std::vector<std::pair<std::string, std::string>> out;
        for (auto &kv : leases_)
          if (kv.second.key.compare(0, prefix.size(), prefix) == 0)
            out.emplace_back(kv.second.key, kv.second.value);
        std::sort(out.begin(), out.end());
        w.u32(0);
        w.u64(out.size());
        for (auto &p : out) {
          w.str(p.first);
          w.str(p.second);
        }
        break;
      }
      default:
        w.u32(0xFFFF);
    }
  }

  int timeout_ms_;
  int failure_max_;
  std::mutex mu_;
  bool stopping_ = false;
  bool dataset_set_ = false;
  std::vector<Task> todo_, done_, discarded_;
  std::map<int64_t, std::pair<Task, Clock::time_point>> pending_;
  int64_t next_id_ = 0;
  std::map<int64_t, Lease> leases_;
  int64_t next_lease_ = 1;
  std::thread timeout_thread_;
  Server server_;
};

}  // namespace

extern "C" {

void *ptrt_master_start(int port, int timeout_ms, int failure_max) {
  return new Master(port, timeout_ms, failure_max);
}
void ptrt_master_stop(void *m) {
  Master *p = static_cast<Master *>(m);
  p->stop();
  delete p;
}
int ptrt_master_port(void *m) { return static_cast<Master *>(m)->port(); }
int ptrt_master_snapshot(void *m, const char *path) {
  return static_cast<Master *>(m)->snapshot(path);
}
int ptrt_master_recover(void *m, const char *path) {
  return static_cast<Master *>(m)->recover(path);
}

void *ptrt_mclient_connect(const char *host, int port) {
  Client *c = new Client(host ? host : "", port);
  if (!c->connected()) {
    delete c;
    return nullptr;
  }
  return c;
}
void ptrt_mclient_close(void *c) { delete static_cast<Client *>(c); }

int ptrt_mclient_set_dataset(void *c, const char *const *chunks, int n,
                             int chunks_per_task) {
  Writer w;
  w.u64(static_cast<uint64_t>(n));
  w.u32(static_cast<uint32_t>(chunks_per_task));
  for (int i = 0; i < n; ++i) w.str(chunks[i]);
  std::vector<uint8_t> resp;
  if (!static_cast<Client *>(c)->call(kSetDataset, w, &resp)) return -1;
  return 0;
}

int64_t ptrt_mclient_get_task(void *c, char *buf, int64_t buflen) {
  Writer w;
  std::vector<uint8_t> resp;
  // -3: transport failure (distinct from -1 retry-later so callers can
  // tell a dead master from an empty queue)
  if (!static_cast<Client *>(c)->call(kGetTask, w, &resp)) return -3;
  Reader r(resp.data(), resp.size());
  uint32_t rc = r.u32();
  if (rc == 1) return -1;
  if (rc == 2) return -2;
  int64_t id = r.i64();
  std::string chunks = r.str();
  if (buf && buflen > 0) {
    if (chunks.size() > static_cast<size_t>(buflen - 1)) {
      // truncation would hand the worker a broken chunk path; surface
      // an explicit error instead
      return -4;
    }
    memcpy(buf, chunks.data(), chunks.size());
    buf[chunks.size()] = 0;
  }
  return id;
}

int ptrt_mclient_task_finished(void *c, int64_t task_id) {
  Writer w;
  w.i64(task_id);
  std::vector<uint8_t> resp;
  return static_cast<Client *>(c)->call(kTaskFinished, w, &resp) ? 0 : -1;
}

int ptrt_mclient_task_failed(void *c, int64_t task_id) {
  Writer w;
  w.i64(task_id);
  std::vector<uint8_t> resp;
  return static_cast<Client *>(c)->call(kTaskFailed, w, &resp) ? 0 : -1;
}

int64_t ptrt_mclient_register(void *c, const char *key, const char *value,
                              int ttl_ms) {
  Writer w;
  w.str(key);
  w.str(value);
  w.u32(static_cast<uint32_t>(ttl_ms));
  std::vector<uint8_t> resp;
  if (!static_cast<Client *>(c)->call(kRegister, w, &resp)) return -2;
  Reader r(resp.data(), resp.size());
  if (r.u32() != 0) return -1;  // key held by a live lease
  return r.i64();
}

int ptrt_mclient_keepalive(void *c, int64_t lease) {
  Writer w;
  w.i64(lease);
  std::vector<uint8_t> resp;
  if (!static_cast<Client *>(c)->call(kKeepAlive, w, &resp)) return -2;
  Reader r(resp.data(), resp.size());
  return static_cast<int>(r.u32());  // 0 renewed, 1 lapsed
}

int ptrt_mclient_unregister(void *c, int64_t lease) {
  Writer w;
  w.i64(lease);
  std::vector<uint8_t> resp;
  return static_cast<Client *>(c)->call(kUnregister, w, &resp) ? 0 : -1;
}

int64_t ptrt_mclient_list(void *c, const char *prefix, char *buf,
                          int64_t buflen) {
  // entries come back newline-joined as "key=value" lines; returns the
  // entry count, or -4 when the buffer would truncate
  Writer w;
  w.str(prefix);
  std::vector<uint8_t> resp;
  if (!static_cast<Client *>(c)->call(kList, w, &resp)) return -2;
  Reader r(resp.data(), resp.size());
  if (r.u32() != 0) return -1;
  uint64_t n = r.u64();
  std::string joined;
  for (uint64_t i = 0; i < n; ++i) {
    std::string k = r.str();
    std::string v = r.str();
    if (i) joined += "\n";
    joined += k;
    joined += "=";
    joined += v;
  }
  if (buf && buflen > 0) {
    if (joined.size() > static_cast<size_t>(buflen - 1)) return -4;
    memcpy(buf, joined.data(), joined.size());
    buf[joined.size()] = 0;
  }
  return static_cast<int64_t>(n);
}

}  // extern "C"

}  // namespace ptrt
