// Server-side dense/sparse-row optimizers (internal).
//
// TPU-native equivalent of the reference's C optimizer library that the
// Go pserver executes per gradient (reference: paddle/optimizer/
// sgd_optimizer.cc, adagrad_optimizer.cc, adam_optimizer.cc;
// paddle/parameter/FirstOrderOptimizer.h for the math).
#ifndef PADDLE_TPU_RT_OPTIMIZER_H
#define PADDLE_TPU_RT_OPTIMIZER_H

#include <cmath>
#include <cstdint>
#include <vector>

namespace ptrt {

enum OptKind { kSGD = 0, kMomentum = 1, kAdagrad = 2, kAdam = 3 };

struct Optimizer {
  int kind = kSGD;
  double lr = 0.01;
  double hp1 = 0.0;  // momentum | adagrad eps | adam beta1
  double hp2 = 0.0;  // adam beta2
  double hp3 = 0.0;  // adam eps
  int64_t step = 0;
  std::vector<float> m1, m2;  // state buffers sized on first use

  void ensure(size_t n) {
    if (kind == kMomentum || kind == kAdagrad) {
      if (m1.size() != n) m1.assign(n, 0.f);
    } else if (kind == kAdam) {
      if (m1.size() != n) m1.assign(n, 0.f);
      if (m2.size() != n) m2.assign(n, 0.f);
    }
  }

  // dense update over [begin, end) of the parameter
  void apply(float *param, const float *grad, size_t begin, size_t end) {
    switch (kind) {
      case kSGD:
        for (size_t i = begin; i < end; ++i)
          param[i] -= static_cast<float>(lr) * grad[i - begin];
        break;
      case kMomentum:
        for (size_t i = begin; i < end; ++i) {
          float v = static_cast<float>(hp1) * m1[i] + grad[i - begin];
          m1[i] = v;
          param[i] -= static_cast<float>(lr) * v;
        }
        break;
      case kAdagrad: {
        float eps = hp1 > 0 ? static_cast<float>(hp1) : 1e-6f;
        for (size_t i = begin; i < end; ++i) {
          float g = grad[i - begin];
          m1[i] += g * g;
          param[i] -= static_cast<float>(lr) * g /
                      (std::sqrt(m1[i]) + eps);
        }
        break;
      }
      case kAdam: {
        float b1 = hp1 > 0 ? static_cast<float>(hp1) : 0.9f;
        float b2 = hp2 > 0 ? static_cast<float>(hp2) : 0.999f;
        float eps = hp3 > 0 ? static_cast<float>(hp3) : 1e-8f;
        // step counts whole-parameter updates; callers bump once per
        // apply over the full range (sparse paths pass begin offsets)
        double bc1 = 1.0 - std::pow(b1, static_cast<double>(step));
        double bc2 = 1.0 - std::pow(b2, static_cast<double>(step));
        float alpha = static_cast<float>(
            lr * std::sqrt(bc2 > 0 ? bc2 : 1.0) / (bc1 > 0 ? bc1 : 1.0));
        for (size_t i = begin; i < end; ++i) {
          float g = grad[i - begin];
          m1[i] = b1 * m1[i] + (1.f - b1) * g;
          m2[i] = b2 * m2[i] + (1.f - b2) * g * g;
          param[i] -= alpha * m1[i] / (std::sqrt(m2[i]) + eps);
        }
        break;
      }
    }
  }
};

}  // namespace ptrt

#endif
