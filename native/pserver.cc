// Parameter server: named float parameters, sync gradient aggregation
// with trainer barriers or async immediate updates, sparse row access,
// checkpoint with CRC.
//
// TPU-native equivalent of the reference C++/Go parameter servers
// (reference: paddle/pserver/ParameterServer2.h:73 — addGradient:482
// barrier aggregation, asyncSGD:468, getParameter:496,
// getParameterSparse:510, waitPassStart:406 barriers;
// go/pserver/service.go checkpoint:346 with crc+md5 meta).  Optimizers
// run server-side as in both references.
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include "optimizer.h"
#include "paddle_tpu_rt.h"
#include "transport.h"

namespace ptrt {
namespace {

enum Op : uint32_t {
  kInitParam = 1,
  kSendGrad = 2,
  kGetParam = 3,
  kSendSparseGrad = 4,
  kGetRows = 5,
  kBarrier = 6,
};

struct ParamEntry {
  std::vector<float> value;
  std::vector<float> grad_accum;
  int grads_pending = 0;   // trainers aggregated so far this round
  int64_t version = 0;
  Optimizer opt;
};

class PServer {
 public:
  PServer(int port, int num_trainers, int sync, int async_lagged)
      : num_trainers_(num_trainers), sync_(sync),
        async_lagged_(async_lagged),
        server_(port, [this](uint32_t op, Reader &r, Writer &w) {
          handle(op, r, w);
        }) {}

  int port() const { return server_.port(); }

  void stop() {
    {
      // wake sync-barrier / gradient-round waiters so their connection
      // threads can exit before Server::stop() joins them
      std::lock_guard<std::mutex> g(mu_);
      stopping_ = true;
    }
    cv_.notify_all();
    server_.stop();
  }
  int64_t numLagged() {
    std::lock_guard<std::mutex> g(mu_);
    return lagged_grads_;
  }

  int64_t numUpdates() {
    std::lock_guard<std::mutex> g(mu_);
    return updates_;
  }

  int64_t numSparseRows() {
    std::lock_guard<std::mutex> g(mu_);
    return sparse_rows_;
  }

  int save(const char *path) {
    std::lock_guard<std::mutex> g(mu_);
    Writer w;
    w.u64(params_.size());
    for (auto &kv : params_) {
      w.str(kv.first);
      w.i64(kv.second.version);
      w.bytes(kv.second.value.data(), kv.second.value.size() * 4);
      w.bytes(kv.second.opt.m1.data(), kv.second.opt.m1.size() * 4);
      w.bytes(kv.second.opt.m2.data(), kv.second.opt.m2.size() * 4);
      w.i64(kv.second.opt.step);
      // optimizer config: a restored server must keep optimizing the
      // same way (reference: go/pserver checkpoint includes the
      // serialized optimizer state+config)
      w.u32(static_cast<uint32_t>(kv.second.opt.kind));
      w.f64(kv.second.opt.lr);
      w.f64(kv.second.opt.hp1);
      w.f64(kv.second.opt.hp2);
      w.f64(kv.second.opt.hp3);
    }
    uint32_t crc = crc32(w.buf.data(), w.buf.size());
    FILE *f = fopen(path, "wb");
    if (!f) return -1;
    uint64_t n = w.buf.size();
    fwrite(&crc, 4, 1, f);
    fwrite(&n, 8, 1, f);
    fwrite(w.buf.data(), 1, n, f);
    fclose(f);
    return 0;
  }

  int load(const char *path) {
    FILE *f = fopen(path, "rb");
    if (!f) return -1;
    uint32_t crc = 0;
    uint64_t n = 0;
    if (fread(&crc, 4, 1, f) != 1 || fread(&n, 8, 1, f) != 1) {
      fclose(f);
      return -2;
    }
    std::vector<uint8_t> buf(n);
    if (fread(buf.data(), 1, n, f) != n) { fclose(f); return -2; }
    fclose(f);
    if (crc32(buf.data(), n) != crc) return -3;  // corrupted checkpoint
    std::lock_guard<std::mutex> g(mu_);
    Reader r(buf.data(), n);
    uint64_t cnt = r.u64();
    for (uint64_t i = 0; i < cnt; ++i) {
      std::string name = r.str();
      ParamEntry &e = params_[name];
      e.version = r.i64();
      uint64_t len;
      const uint8_t *v = r.blob(&len);
      e.value.resize(len / 4);
      memcpy(e.value.data(), v, len);
      v = r.blob(&len);
      e.opt.m1.resize(len / 4);
      if (len) memcpy(e.opt.m1.data(), v, len);
      v = r.blob(&len);
      e.opt.m2.resize(len / 4);
      if (len) memcpy(e.opt.m2.data(), v, len);
      e.opt.step = r.i64();
      e.opt.kind = static_cast<int>(r.u32());
      e.opt.lr = r.f64();
      e.opt.hp1 = r.f64();
      e.opt.hp2 = r.f64();
      e.opt.hp3 = r.f64();
    }
    return 0;
  }

 private:
  void handle(uint32_t op, Reader &r, Writer &w) {
    switch (op) {
      case kInitParam: {
        std::string name = r.str();
        int kind = static_cast<int>(r.u32());
        double lr = r.f64(), h1 = r.f64(), h2 = r.f64(), h3 = r.f64();
        uint64_t len;
        const uint8_t *data = r.blob(&len);
        std::lock_guard<std::mutex> g(mu_);
        // first trainer wins (reference: Go pserver InitParam once)
        if (!params_.count(name)) {
          ParamEntry &e = params_[name];
          e.value.resize(len / 4);
          memcpy(e.value.data(), data, len);
          e.opt.kind = kind;
          e.opt.lr = lr;
          e.opt.hp1 = h1;
          e.opt.hp2 = h2;
          e.opt.hp3 = h3;
          e.opt.ensure(e.value.size());
        }
        w.u32(0);
        break;
      }
      case kSendGrad: {
        std::string name = r.str();
        int64_t base_version = r.i64();
        uint64_t len;
        const uint8_t *data = r.blob(&len);
        std::unique_lock<std::mutex> g(mu_);
        auto it = params_.find(name);
        if (it == params_.end()) { w.u32(1); return; }
        ParamEntry &e = it->second;
        const float *grad = reinterpret_cast<const float *>(data);
        size_t n = len / 4;
        if (n != e.value.size()) { w.u32(2); return; }
        if (!sync_ || num_trainers_ <= 1) {
          // async staleness bound (reference: ParameterServer2.cpp:416
          // asyncGrdientCommitCheckAndStat over
          // FLAGS_async_lagged_grad_discard_ratio,
          // ParameterServer2.h:243): a gradient computed against
          // parameters at least async_lagged_ versions old is
          // discarded; the trainer still receives the fresh value so
          // it resynchronizes instead of looping on stale state.
          if (!sync_ && async_lagged_ > 0 &&
              e.version - base_version >= async_lagged_) {
            lagged_grads_++;
            w.u32(4);
            w.i64(e.version);
            w.bytes(e.value.data(), e.value.size() * 4);
            return;
          }
          e.opt.step++;
          e.opt.apply(e.value.data(), grad, 0, n);
          e.version++;
          updates_++;
        } else {
          if (e.grad_accum.size() != n) e.grad_accum.assign(n, 0.f);
          for (size_t i = 0; i < n; ++i) e.grad_accum[i] += grad[i];
          e.grads_pending++;
          int64_t my_version = e.version;
          if (e.grads_pending >= num_trainers_) {
            // average + one optimizer step (reference:
            // ParameterServer2 doOperation after all trainers report)
            float inv = 1.f / static_cast<float>(num_trainers_);
            for (size_t i = 0; i < n; ++i) e.grad_accum[i] *= inv;
            e.opt.step++;
            e.opt.apply(e.value.data(), e.grad_accum.data(), 0, n);
            e.grad_accum.assign(n, 0.f);
            e.grads_pending = 0;
            e.version++;
            updates_++;
            cv_.notify_all();
          } else {
            cv_.wait(g, [&] {
              return e.version > my_version || stopping_;
            });
            if (stopping_) { w.u32(3); return; }
          }
        }
        w.u32(0);
        w.i64(e.version);
        w.bytes(e.value.data(), e.value.size() * 4);
        break;
      }
      case kGetParam: {
        std::string name = r.str();
        std::lock_guard<std::mutex> g(mu_);
        auto it = params_.find(name);
        if (it == params_.end()) { w.u32(1); return; }
        w.u32(0);
        w.i64(it->second.version);
        w.bytes(it->second.value.data(), it->second.value.size() * 4);
        break;
      }
      case kSendSparseGrad: {
        // rows update immediately (async semantics — reference sparse
        // remote updates are asynchronous by design:
        // SparseRemoteParameterUpdater)
        std::string name = r.str();
        int64_t width = r.i64();
        uint64_t rlen, vlen;
        const uint8_t *rowsb = r.blob(&rlen);
        const uint8_t *valsb = r.blob(&vlen);
        std::lock_guard<std::mutex> g(mu_);
        auto it = params_.find(name);
        if (it == params_.end()) { w.u32(1); return; }
        ParamEntry &e = it->second;
        const int32_t *rows = reinterpret_cast<const int32_t *>(rowsb);
        const float *vals = reinterpret_cast<const float *>(valsb);
        size_t nrows = rlen / 4;
        // bounds: the vals blob must actually hold nrows*width floats
        if (width <= 0 ||
            vlen < nrows * static_cast<uint64_t>(width) * 4) {
          w.u32(2);
          return;
        }
        e.opt.step++;
        for (size_t i = 0; i < nrows; ++i) {
          // negative ids would wrap the size_t multiply past the bound
          if (rows[i] < 0) continue;
          size_t begin = static_cast<size_t>(rows[i]) * width;
          if (begin + width > e.value.size()) continue;
          e.opt.apply(e.value.data(), vals + i * width, begin,
                      begin + width);
          sparse_rows_++;  // rows actually applied (observability: lets
                           // tests prove updates shipped sparse)
        }
        e.version++;
        updates_++;
        w.u32(0);
        break;
      }
      case kGetRows: {
        std::string name = r.str();
        int64_t width = r.i64();
        uint64_t rlen;
        const uint8_t *rowsb = r.blob(&rlen);
        std::lock_guard<std::mutex> g(mu_);
        auto it = params_.find(name);
        if (it == params_.end()) { w.u32(1); return; }
        const int32_t *rows = reinterpret_cast<const int32_t *>(rowsb);
        size_t nrows = rlen / 4;
        // bounds: reject non-positive or absurd width before the
        // allocation (mirrors the kSendSparseGrad check) so a bad
        // request can't bad_alloc the server process. 1<<28 floats
        // (1 GiB) is far above any real sparse fetch.
        if (width <= 0 ||
            nrows * static_cast<uint64_t>(width) > (1ull << 28)) {
          w.u32(2);
          return;
        }
        std::vector<float> out(nrows * width, 0.f);
        for (size_t i = 0; i < nrows; ++i) {
          if (rows[i] < 0) continue;
          size_t begin = static_cast<size_t>(rows[i]) * width;
          if (begin + width <= it->second.value.size())
            memcpy(out.data() + i * width,
                   it->second.value.data() + begin, width * 4);
        }
        w.u32(0);
        w.bytes(out.data(), out.size() * 4);
        break;
      }
      case kBarrier: {
        // pass-start barrier across trainers (reference:
        // ParameterServer2::waitPassStart:406)
        std::unique_lock<std::mutex> g(mu_);
        barrier_count_++;
        if (barrier_count_ >= num_trainers_) {
          barrier_count_ = 0;
          barrier_gen_++;
          cv_.notify_all();
        } else {
          int64_t gen = barrier_gen_;
          cv_.wait(g, [&] { return barrier_gen_ > gen || stopping_; });
          if (stopping_) { w.u32(3); return; }
        }
        w.u32(0);
        break;
      }
      default:
        w.u32(0xFFFF);
    }
  }

  int num_trainers_;
  int sync_;
  int async_lagged_ = 0;       // 0 = unbounded (legacy behavior)
  int64_t lagged_grads_ = 0;   // discarded-as-stale count
  bool stopping_ = false;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, ParamEntry> params_;
  int barrier_count_ = 0;
  int64_t barrier_gen_ = 0;
  int64_t updates_ = 0;
  int64_t sparse_rows_ = 0;  // total sparse rows applied
  Server server_;
};

}  // namespace

extern "C" {

void *ptrt_pserver_start(int port, int num_trainers, int sync,
                         int async_lagged) {
  return new PServer(port, num_trainers, sync, async_lagged);
}
void ptrt_pserver_stop(void *s) {
  PServer *p = static_cast<PServer *>(s);
  p->stop();
  delete p;
}
int ptrt_pserver_port(void *s) { return static_cast<PServer *>(s)->port(); }
int ptrt_pserver_save(void *s, const char *path) {
  return static_cast<PServer *>(s)->save(path);
}
int ptrt_pserver_load(void *s, const char *path) {
  return static_cast<PServer *>(s)->load(path);
}
int64_t ptrt_pserver_num_updates(void *s) {
  return static_cast<PServer *>(s)->numUpdates();
}
int64_t ptrt_pserver_num_lagged(void *s) {
  return static_cast<PServer *>(s)->numLagged();
}
int64_t ptrt_pserver_num_sparse_rows(void *s) {
  return static_cast<PServer *>(s)->numSparseRows();
}

void *ptrt_client_connect(const char *host, int port) {
  Client *c = new Client(host ? host : "", port);
  if (!c->connected()) {
    delete c;
    return nullptr;
  }
  return c;
}
void ptrt_client_close(void *c) { delete static_cast<Client *>(c); }

int ptrt_client_init_param(void *c, const char *name, const float *data,
                           int64_t n, int opt_kind, double lr, double hp1,
                           double hp2, double hp3) {
  Writer w;
  w.str(name);
  w.u32(static_cast<uint32_t>(opt_kind));
  w.f64(lr);
  w.f64(hp1);
  w.f64(hp2);
  w.f64(hp3);
  w.bytes(data, static_cast<size_t>(n) * 4);
  std::vector<uint8_t> resp;
  if (!static_cast<Client *>(c)->call(kInitParam, w, &resp)) return -1;
  Reader r(resp.data(), resp.size());
  return static_cast<int>(r.u32());
}

int ptrt_client_send_grad(void *c, const char *name, const float *grad,
                          int64_t n, float *out, int64_t base_version,
                          int64_t *new_version) {
  Writer w;
  w.str(name);
  w.i64(base_version);
  w.bytes(grad, static_cast<size_t>(n) * 4);
  std::vector<uint8_t> resp;
  if (!static_cast<Client *>(c)->call(kSendGrad, w, &resp)) return -1;
  Reader r(resp.data(), resp.size());
  int rc = static_cast<int>(r.u32());
  // rc 4 = discarded as stale; the fresh parameter still follows
  if ((rc == 0 || rc == 4)) {
    int64_t ver = r.i64();
    if (new_version) *new_version = ver;
    if (out) {
      uint64_t len;
      const uint8_t *v = r.blob(&len);
      memcpy(out, v, std::min<uint64_t>(len, static_cast<uint64_t>(n) * 4));
    }
  }
  return rc;
}

int ptrt_client_get_param(void *c, const char *name, float *out,
                          int64_t n, int64_t *version) {
  Writer w;
  w.str(name);
  std::vector<uint8_t> resp;
  if (!static_cast<Client *>(c)->call(kGetParam, w, &resp)) return -1;
  Reader r(resp.data(), resp.size());
  int rc = static_cast<int>(r.u32());
  if (rc == 0) {
    int64_t ver = r.i64();
    if (version) *version = ver;
    if (out) {
      uint64_t len;
      const uint8_t *v = r.blob(&len);
      memcpy(out, v, std::min<uint64_t>(len, static_cast<uint64_t>(n) * 4));
    }
  }
  return rc;
}

int ptrt_client_send_sparse_grad(void *c, const char *name,
                                 const int32_t *rows, const float *vals,
                                 int64_t nrows, int64_t width) {
  Writer w;
  w.str(name);
  w.i64(width);
  w.bytes(rows, static_cast<size_t>(nrows) * 4);
  w.bytes(vals, static_cast<size_t>(nrows) * width * 4);
  std::vector<uint8_t> resp;
  if (!static_cast<Client *>(c)->call(kSendSparseGrad, w, &resp))
    return -1;
  Reader r(resp.data(), resp.size());
  return static_cast<int>(r.u32());
}

int ptrt_client_get_rows(void *c, const char *name, const int32_t *rows,
                         float *out, int64_t nrows, int64_t width) {
  Writer w;
  w.str(name);
  w.i64(width);
  w.bytes(rows, static_cast<size_t>(nrows) * 4);
  std::vector<uint8_t> resp;
  if (!static_cast<Client *>(c)->call(kGetRows, w, &resp)) return -1;
  Reader r(resp.data(), resp.size());
  int rc = static_cast<int>(r.u32());
  if (rc == 0 && out) {
    uint64_t len;
    const uint8_t *v = r.blob(&len);
    memcpy(out, v,
           std::min<uint64_t>(len, static_cast<uint64_t>(nrows) * width * 4));
  }
  return rc;
}

int ptrt_client_barrier(void *c) {
  Writer w;
  std::vector<uint8_t> resp;
  if (!static_cast<Client *>(c)->call(kBarrier, w, &resp)) return -1;
  Reader r(resp.data(), resp.size());
  return static_cast<int>(r.u32());
}

}  // extern "C"

}  // namespace ptrt
