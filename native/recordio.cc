// RecordIO-style chunked record files + buddy allocator.
//
// TPU-native equivalents of the reference dataset container and host
// memory pool (reference: recordio usage go/master/service.go
// partition:106 over recordio.Index; paddle/memory/detail/
// buddy_allocator.h:33 BuddyAllocator over system allocators).
// Record format: per record [u32 crc][u32 len][payload]; a chunk is just
// a file (the master leases lists of files).
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <vector>

#include "paddle_tpu_rt.h"
#include "transport.h"  // ptrt::crc32

namespace ptrt {
namespace {

// ---- buddy allocator ----------------------------------------------------

class Buddy {
 public:
  Buddy(int64_t total, int64_t min_block) {
    min_block_ = 64;
    while (min_block_ < min_block) min_block_ <<= 1;
    total_ = min_block_;
    while (total_ < total) total_ <<= 1;
    base_ = static_cast<uint8_t *>(::operator new(total_));
    max_order_ = 0;
    while ((min_block_ << max_order_) < total_) max_order_++;
    free_[max_order_].push_back(0);
  }
  ~Buddy() { ::operator delete(base_); }

  void *alloc(int64_t n) {
    std::lock_guard<std::mutex> g(mu_);
    if (n <= 0) n = 1;
    int order = 0;
    while ((min_block_ << order) < n) order++;
    if (order > max_order_) return nullptr;
    int o = order;
    while (o <= max_order_ && free_[o].empty()) o++;
    if (o > max_order_) return nullptr;
    int64_t off = free_[o].back();
    free_[o].pop_back();
    while (o > order) {  // split down
      o--;
      free_[o].push_back(off + (min_block_ << o));
    }
    used_[off] = order;
    used_bytes_ += (min_block_ << order);
    return base_ + off;
  }

  void free(void *p) {
    std::lock_guard<std::mutex> g(mu_);
    int64_t off = static_cast<uint8_t *>(p) - base_;
    auto it = used_.find(off);
    if (it == used_.end()) return;
    int order = it->second;
    used_.erase(it);
    used_bytes_ -= (min_block_ << order);
    // coalesce with buddy while free (reference: buddy_allocator.h
    // merging free blocks)
    while (order < max_order_) {
      int64_t buddy = off ^ (min_block_ << order);
      auto &fl = free_[order];
      bool merged = false;
      for (size_t i = 0; i < fl.size(); ++i) {
        if (fl[i] == buddy) {
          fl.erase(fl.begin() + i);
          off = std::min(off, buddy);
          order++;
          merged = true;
          break;
        }
      }
      if (!merged) break;
    }
    free_[order].push_back(off);
  }

  int64_t used() {
    std::lock_guard<std::mutex> g(mu_);
    return used_bytes_;
  }

 private:
  uint8_t *base_;
  int64_t total_, min_block_, used_bytes_ = 0;
  int max_order_;
  std::mutex mu_;
  std::map<int, std::vector<int64_t>> free_;
  std::map<int64_t, int> used_;
};

}  // namespace

extern "C" {

void *ptrt_recordio_writer_open(const char *path) {
  return fopen(path, "wb");
}
int ptrt_recordio_write(void *w, const void *data, int64_t n) {
  FILE *f = static_cast<FILE *>(w);
  uint32_t crc = crc32(data, static_cast<size_t>(n));
  uint32_t len = static_cast<uint32_t>(n);
  if (fwrite(&crc, 4, 1, f) != 1) return -1;
  if (fwrite(&len, 4, 1, f) != 1) return -1;
  if (n && fwrite(data, 1, static_cast<size_t>(n), f) !=
               static_cast<size_t>(n))
    return -1;
  return 0;
}
int ptrt_recordio_writer_close(void *w) {
  return fclose(static_cast<FILE *>(w));
}

void *ptrt_recordio_reader_open(const char *path) {
  return fopen(path, "rb");
}
int64_t ptrt_recordio_read(void *r, void *buf, int64_t buflen) {
  FILE *f = static_cast<FILE *>(r);
  uint32_t crc, len;
  if (fread(&crc, 4, 1, f) != 1) return -1;  // EOF
  if (fread(&len, 4, 1, f) != 1) return -2;
  if (len > static_cast<uint64_t>(buflen)) return -2;
  if (len && fread(buf, 1, len, f) != len) return -2;
  if (crc32(buf, len) != crc) return -2;
  return static_cast<int64_t>(len);
}
void ptrt_recordio_reader_close(void *r) { fclose(static_cast<FILE *>(r)); }

void *ptrt_buddy_create(int64_t total_bytes, int64_t min_block) {
  return new Buddy(total_bytes, min_block);
}
void *ptrt_buddy_alloc(void *a, int64_t n) {
  return static_cast<Buddy *>(a)->alloc(n);
}
void ptrt_buddy_free(void *a, void *p) { static_cast<Buddy *>(a)->free(p); }
int64_t ptrt_buddy_used(void *a) { return static_cast<Buddy *>(a)->used(); }
void ptrt_buddy_destroy(void *a) { delete static_cast<Buddy *>(a); }

}  // extern "C"

}  // namespace ptrt
