// See transport.h.  POSIX sockets, thread per connection (the reference
// pserver similarly dedicates threads per channel:
// paddle/pserver/SocketChannel.h, LightNetwork.h worker threads).
#include "transport.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>

namespace ptrt {

namespace {
struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};
}  // namespace

uint32_t crc32(const void *data, size_t n) {
  static const Crc32Table table;  // thread-safe init (magic static)
  uint32_t c = 0xFFFFFFFFu;
  const uint8_t *p = static_cast<const uint8_t *>(data);
  for (size_t i = 0; i < n; ++i)
    c = table.t[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

static bool writeAll(int fd, const void *p, size_t n) {
  const char *b = static_cast<const char *>(p);
  while (n > 0) {
    ssize_t k = ::write(fd, b, n);
    if (k <= 0) return false;
    b += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

static bool readAll(int fd, void *p, size_t n) {
  char *b = static_cast<char *>(p);
  while (n > 0) {
    ssize_t k = ::read(fd, b, n);
    if (k <= 0) return false;
    b += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool sendFrame(int fd, uint32_t opcode, const void *payload, uint64_t len) {
  uint8_t hdr[12];
  memcpy(hdr, &opcode, 4);
  memcpy(hdr + 4, &len, 8);
  if (!writeAll(fd, hdr, 12)) return false;
  return len == 0 || writeAll(fd, payload, len);
}

bool recvFrame(int fd, uint32_t *opcode, std::vector<uint8_t> *payload) {
  uint8_t hdr[12];
  if (!readAll(fd, hdr, 12)) return false;
  uint64_t len;
  memcpy(opcode, hdr, 4);
  memcpy(&len, hdr + 4, 8);
  if (len > (1ull << 33)) return false;  // sanity cap 8GB
  payload->resize(len);
  return len == 0 || readAll(fd, payload->data(), len);
}

Server::Server(int port, Handler handler) : handler_(std::move(handler)) {
  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd_ < 0) return;
  int one = 1;
  setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  // all interfaces: pserver/master serve cross-host DCN traffic
  // (reference: the pservers bind routable addresses; trainers discover
  // them by host:port)
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(listen_fd_, reinterpret_cast<sockaddr *>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(listen_fd_, 128) != 0) {
    ::close(listen_fd_);
    listen_fd_ = -1;
    return;
  }
  socklen_t alen = sizeof(addr);
  getsockname(listen_fd_, reinterpret_cast<sockaddr *>(&addr), &alen);
  port_ = ntohs(addr.sin_port);
  accept_thread_ = std::thread([this] { acceptLoop(); });
}

Server::~Server() { stop(); }

void Server::stop() {
  bool expected = false;
  if (!stopping_.compare_exchange_strong(expected, true)) return;
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    listen_fd_ = -1;
  }
  if (accept_thread_.joinable()) accept_thread_.join();
  std::map<int, std::thread> remaining;
  {
    // unblock connection threads stuck in read() on live clients
    std::lock_guard<std::mutex> g(conn_mu_);
    reapFinishedLocked();
    for (auto &kv : conns_) ::shutdown(kv.first, SHUT_RDWR);
    remaining.swap(conns_);
  }
  for (auto &kv : remaining)
    if (kv.second.joinable()) kv.second.join();
}

void Server::reapFinishedLocked() {
  for (int fd : finished_fds_) {
    auto it = conns_.find(fd);
    if (it != conns_.end()) {
      if (it->second.joinable()) it->second.join();
      conns_.erase(it);
    }
  }
  finished_fds_.clear();
}

void Server::acceptLoop() {
  while (!stopping_.load()) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) break;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::lock_guard<std::mutex> g(conn_mu_);
    reapFinishedLocked();  // bound dead-thread growth on busy servers
    conns_.emplace(fd, std::thread([this, fd] { serveConn(fd); }));
  }
}

void Server::serveConn(int fd) {
  std::vector<uint8_t> payload;
  uint32_t opcode;
  while (!stopping_.load() && recvFrame(fd, &opcode, &payload)) {
    Reader r(payload.data(), payload.size());
    Writer w;
    handler_(opcode, r, w);
    if (!sendFrame(fd, opcode, w.buf.data(), w.buf.size())) break;
  }
  {
    // mark finished BEFORE close: the fd number can be reused by a new
    // accept the moment it closes, and the reaper must find this entry
    std::lock_guard<std::mutex> g(conn_mu_);
    finished_fds_.push_back(fd);
  }
  ::close(fd);
}

Client::Client(const std::string &host, int port) {
  fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) return;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (host.empty() || host == "localhost" || host == "127.0.0.1")
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  else if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    ::close(fd_);
    fd_ = -1;
    return;
  }
  if (::connect(fd_, reinterpret_cast<sockaddr *>(&addr),
                sizeof(addr)) != 0) {
    ::close(fd_);
    fd_ = -1;
    return;
  }
  int one = 1;
  setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

Client::~Client() {
  if (fd_ >= 0) ::close(fd_);
}

bool Client::call(uint32_t opcode, const Writer &req,
                  std::vector<uint8_t> *resp) {
  if (fd_ < 0) return false;
  if (!sendFrame(fd_, opcode, req.buf.data(), req.buf.size())) return false;
  uint32_t op2;
  return recvFrame(fd_, &op2, resp) && op2 == opcode;
}

}  // namespace ptrt
