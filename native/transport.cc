// See transport.h.  POSIX sockets, thread per connection (the reference
// pserver similarly dedicates threads per channel:
// paddle/pserver/SocketChannel.h, LightNetwork.h worker threads).
#include "transport.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>

namespace ptrt {

static bool writeAll(int fd, const void *p, size_t n) {
  const char *b = static_cast<const char *>(p);
  while (n > 0) {
    ssize_t k = ::write(fd, b, n);
    if (k <= 0) return false;
    b += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

static bool readAll(int fd, void *p, size_t n) {
  char *b = static_cast<char *>(p);
  while (n > 0) {
    ssize_t k = ::read(fd, b, n);
    if (k <= 0) return false;
    b += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool sendFrame(int fd, uint32_t opcode, const void *payload, uint64_t len) {
  uint8_t hdr[12];
  memcpy(hdr, &opcode, 4);
  memcpy(hdr + 4, &len, 8);
  if (!writeAll(fd, hdr, 12)) return false;
  return len == 0 || writeAll(fd, payload, len);
}

bool recvFrame(int fd, uint32_t *opcode, std::vector<uint8_t> *payload) {
  uint8_t hdr[12];
  if (!readAll(fd, hdr, 12)) return false;
  uint64_t len;
  memcpy(opcode, hdr, 4);
  memcpy(&len, hdr + 4, 8);
  if (len > (1ull << 33)) return false;  // sanity cap 8GB
  payload->resize(len);
  return len == 0 || readAll(fd, payload->data(), len);
}

Server::Server(int port, Handler handler) : handler_(std::move(handler)) {
  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd_ < 0) return;
  int one = 1;
  setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(listen_fd_, reinterpret_cast<sockaddr *>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(listen_fd_, 128) != 0) {
    ::close(listen_fd_);
    listen_fd_ = -1;
    return;
  }
  socklen_t alen = sizeof(addr);
  getsockname(listen_fd_, reinterpret_cast<sockaddr *>(&addr), &alen);
  port_ = ntohs(addr.sin_port);
  accept_thread_ = std::thread([this] { acceptLoop(); });
}

Server::~Server() { stop(); }

void Server::stop() {
  bool expected = false;
  if (!stopping_.compare_exchange_strong(expected, true)) return;
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    listen_fd_ = -1;
  }
  if (accept_thread_.joinable()) accept_thread_.join();
  {
    // unblock connection threads stuck in read() on live clients
    std::lock_guard<std::mutex> g(conn_mu_);
    for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
  }
  for (auto &t : conns_)
    if (t.joinable()) t.join();
  conns_.clear();
}

void Server::acceptLoop() {
  while (!stopping_.load()) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) break;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    {
      std::lock_guard<std::mutex> g(conn_mu_);
      conn_fds_.push_back(fd);
    }
    conns_.emplace_back([this, fd] { serveConn(fd); });
  }
}

void Server::serveConn(int fd) {
  std::vector<uint8_t> payload;
  uint32_t opcode;
  while (!stopping_.load() && recvFrame(fd, &opcode, &payload)) {
    Reader r(payload.data(), payload.size());
    Writer w;
    handler_(opcode, r, w);
    if (!sendFrame(fd, opcode, w.buf.data(), w.buf.size())) break;
  }
  {
    std::lock_guard<std::mutex> g(conn_mu_);
    for (size_t i = 0; i < conn_fds_.size(); ++i) {
      if (conn_fds_[i] == fd) {
        conn_fds_.erase(conn_fds_.begin() + i);
        break;
      }
    }
  }
  ::close(fd);
}

Client::Client(const std::string &host, int port) {
  fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) return;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (host.empty() || host == "localhost" || host == "127.0.0.1")
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  else if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    ::close(fd_);
    fd_ = -1;
    return;
  }
  if (::connect(fd_, reinterpret_cast<sockaddr *>(&addr),
                sizeof(addr)) != 0) {
    ::close(fd_);
    fd_ = -1;
    return;
  }
  int one = 1;
  setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

Client::~Client() {
  if (fd_ >= 0) ::close(fd_);
}

bool Client::call(uint32_t opcode, const Writer &req,
                  std::vector<uint8_t> *resp) {
  if (fd_ < 0) return false;
  if (!sendFrame(fd_, opcode, req.buf.data(), req.buf.size())) return false;
  uint32_t op2;
  return recvFrame(fd_, &op2, resp) && op2 == opcode;
}

}  // namespace ptrt
