/* paddle_tpu native runtime — C API consumed from Python via ctypes.
 *
 * TPU-native re-implementation of the reference's native runtime
 * services (reference: paddle/pserver/ParameterServer2.h blockwise
 * param store + sync barriers + asyncSGD; paddle/optimizer C ABI lib;
 * go/master/service.go task queue with lease timeouts; RecordIO chunks;
 * paddle/memory/detail/buddy_allocator.h).  Transport is framed
 * messages over TCP sockets (reference: paddle/pserver/LightNetwork.h,
 * ProtoServer.h) — gRPC/RDMA replaced by a dependency-free socket
 * protocol; on-TPU collectives live in XLA, this layer serves the
 * DCN/pserver-style path.
 */
#ifndef PADDLE_TPU_RT_H
#define PADDLE_TPU_RT_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- parameter server ------------------------------------------------ */
/* sync=1: gradients barrier across num_trainers then one optimizer step
 * (reference: ParameterServer2 addGradient + synchronize barriers);
 * sync=0: apply each gradient immediately (reference: asyncSGD).
 * async_lagged > 0 discards async gradients computed against parameters
 * at least that many versions old (reference: ParameterServer2.h:243
 * lagged-async commit control); 0 = unbounded. */
void *ptrt_pserver_start(int port, int num_trainers, int sync,
                         int async_lagged);
void ptrt_pserver_stop(void *s);
int ptrt_pserver_port(void *s);      /* bound port (0 -> ephemeral) */
int ptrt_pserver_save(void *s, const char *path);  /* checkpoint w/ crc */
int ptrt_pserver_load(void *s, const char *path);
int64_t ptrt_pserver_num_updates(void *s);
int64_t ptrt_pserver_num_lagged(void *s);  /* staleness-discarded count */

/* ---- pserver client -------------------------------------------------- */
void *ptrt_client_connect(const char *host, int port);
void ptrt_client_close(void *c);
/* optimizer config applies per-parameter at init time.
 * kind: 0=sgd 1=momentum 2=adagrad 3=adam */
int ptrt_client_init_param(void *c, const char *name, const float *data,
                           int64_t n, int opt_kind, double lr,
                           double hp1, double hp2, double hp3);
/* blocking: returns after the server applied the (sync: aggregated)
 * update; out receives the fresh parameter (may be NULL).
 * base_version: the parameter version the gradient was computed
 * against (from a prior send_grad/get_param); new_version (may be
 * NULL) receives the server's version.  Returns 4 when the gradient
 * was discarded as stale — out is still the fresh parameter. */
int ptrt_client_send_grad(void *c, const char *name, const float *grad,
                          int64_t n, float *out, int64_t base_version,
                          int64_t *new_version);
int ptrt_client_get_param(void *c, const char *name, float *out,
                          int64_t n, int64_t *version);
/* sparse rows (reference: getParameterSparse / SelectedRows path) */
int ptrt_client_send_sparse_grad(void *c, const char *name,
                                 const int32_t *rows, const float *vals,
                                 int64_t nrows, int64_t width);
int ptrt_client_get_rows(void *c, const char *name, const int32_t *rows,
                         float *out, int64_t nrows, int64_t width);
int ptrt_client_barrier(void *c);     /* pass-start style barrier */

/* ---- master task queue ----------------------------------------------- */
void *ptrt_master_start(int port, int timeout_ms, int failure_max);
void ptrt_master_stop(void *m);
int ptrt_master_port(void *m);
int ptrt_master_snapshot(void *m, const char *path);
int ptrt_master_recover(void *m, const char *path);

void *ptrt_mclient_connect(const char *host, int port);
void ptrt_mclient_close(void *c);
int ptrt_mclient_set_dataset(void *c, const char *const *chunks, int n,
                             int chunks_per_task);
/* returns task id >=0 and fills buf with '\n'-joined chunk names;
 * -1: no task available (all leased, retry later); -2: pass finished
 * (reported once per pass, then the queue recycles for the next pass);
 * -3: transport failure (master unreachable); -4: buf too small for the
 * chunk list (task stays leased; retry with a bigger buffer) */
int64_t ptrt_mclient_get_task(void *c, char *buf, int64_t buflen);
int ptrt_mclient_task_finished(void *c, int64_t task_id);
int ptrt_mclient_task_failed(void *c, int64_t task_id);
/* etcd-style TTL-lease registry (pserver registration/discovery) */
int64_t ptrt_mclient_register(void *c, const char *key, const char *value,
                              int ttl_ms);
int ptrt_mclient_keepalive(void *c, int64_t lease); /* 0 ok, 1 lapsed */
int ptrt_mclient_unregister(void *c, int64_t lease);
int64_t ptrt_mclient_list(void *c, const char *prefix, char *buf,
                          int64_t buflen);

/* ---- recordio --------------------------------------------------------- */
void *ptrt_recordio_writer_open(const char *path);
int ptrt_recordio_write(void *w, const void *data, int64_t n);
int ptrt_recordio_writer_close(void *w);
void *ptrt_recordio_reader_open(const char *path);
/* returns record size (<=buflen) or -1 on EOF, -2 on corruption */
int64_t ptrt_recordio_read(void *r, void *buf, int64_t buflen);
void ptrt_recordio_reader_close(void *r);

/* ---- buddy allocator --------------------------------------------------*/
void *ptrt_buddy_create(int64_t total_bytes, int64_t min_block);
void *ptrt_buddy_alloc(void *a, int64_t n);
void ptrt_buddy_free(void *a, void *p);
int64_t ptrt_buddy_used(void *a);
void ptrt_buddy_destroy(void *a);

#ifdef __cplusplus
}
#endif
#endif /* PADDLE_TPU_RT_H */
