// C inference API: load a saved inference model and run it from C.
//
// TPU-native equivalent of the reference's C deployment API
// (reference: paddle/capi/capi.h, gradient_machine.h:36
// paddle_gradient_machine_create_for_inference(_with_parameters) +
// forward).  The reference embeds Python for config parsing
// (paddle/utils/PythonUtil.h); here the whole inference engine is the
// Python/XLA stack, so the C API embeds CPython and drives
// paddle_tpu.capi_impl — the compiled XLA executable does the math, C
// callers get a plain float-buffer interface.
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>

extern "C" {

struct PtCapiEngine {
  PyObject *engine;  // paddle_tpu.capi_impl.CEngine
};

static std::once_flag g_py_init;

static void ensureInterpreter() {
  std::call_once(g_py_init, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // release the GIL the init thread holds, or every other thread's
      // PyGILState_Ensure deadlocks
      PyEval_SaveThread();
    }
  });
}

// Create an engine from a save_inference_model directory.  Returns
// NULL on failure (error printed to stderr).
void *ptcapi_create(const char *model_dir) {
  ensureInterpreter();
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *mod = PyImport_ImportModule("paddle_tpu.capi_impl");
  if (!mod) {
    PyErr_Print();
    PyGILState_Release(gil);
    return nullptr;
  }
  PyObject *engine = PyObject_CallMethod(mod, "CEngine", "s", model_dir);
  Py_DECREF(mod);
  if (!engine) {
    PyErr_Print();
    PyGILState_Release(gil);
    return nullptr;
  }
  PtCapiEngine *h = new PtCapiEngine{engine};
  PyGILState_Release(gil);
  return h;
}

// Run inference: one float input of shape dims[0..ndims), one float
// output written to `output` (capacity in elements); the actual output
// shape lands in out_dims/out_ndims (caller provides space for 8 dims).
// Returns number of output elements, or -1 on error.
int64_t ptcapi_run(void *handle, const float *input, const int64_t *dims,
                   int ndims, float *output, int64_t out_capacity,
                   int64_t *out_dims, int *out_ndims) {
  PtCapiEngine *h = static_cast<PtCapiEngine *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();

  int64_t n = 1;
  PyObject *shape = PyTuple_New(ndims);
  for (int i = 0; i < ndims; ++i) {
    n *= dims[i];
    PyTuple_SET_ITEM(shape, i, PyLong_FromLongLong(dims[i]));
  }
  PyObject *data = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(input), n * 4);
  PyObject *res = PyObject_CallMethod(h->engine, "run_raw", "OO", data,
                                      shape);
  Py_DECREF(data);
  Py_DECREF(shape);
  if (!res) {
    PyErr_Print();
    PyGILState_Release(gil);
    return -1;
  }
  // res = (bytes, shape tuple)
  PyObject *out_bytes = PyTuple_GetItem(res, 0);
  PyObject *out_shape = PyTuple_GetItem(res, 1);
  int64_t out_n = static_cast<int64_t>(PyBytes_Size(out_bytes)) / 4;
  if (out_n > out_capacity) {
    Py_DECREF(res);
    PyGILState_Release(gil);
    return -1;
  }
  memcpy(output, PyBytes_AsString(out_bytes), out_n * 4);
  int nd = static_cast<int>(PyTuple_Size(out_shape));
  if (nd > 8) {  // out_dims contract is 8 entries max
    Py_DECREF(res);
    PyGILState_Release(gil);
    return -1;
  }
  if (out_ndims) *out_ndims = nd;
  if (out_dims) {
    for (int i = 0; i < nd; ++i)
      out_dims[i] = PyLong_AsLongLong(PyTuple_GetItem(out_shape, i));
  }
  Py_DECREF(res);
  PyGILState_Release(gil);
  return out_n;
}

void ptcapi_destroy(void *handle) {
  PtCapiEngine *h = static_cast<PtCapiEngine *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(h->engine);
  PyGILState_Release(gil);
  delete h;
}

}  // extern "C"
