// Framed-message TCP transport (internal).
//
// TPU-native equivalent of the reference's socket RPC layer
// (reference: paddle/pserver/LightNetwork.h:40 SocketServer,
// paddle/pserver/SocketChannel.h message framing, ProtoServer.h
// request/response dispatch).  One thread per connection; messages are
// [u32 opcode][u64 len][payload]; the response reuses the framing.
#ifndef PADDLE_TPU_RT_TRANSPORT_H
#define PADDLE_TPU_RT_TRANSPORT_H

#include <atomic>
#include <cstdint>
#include <cstring>
#include <functional>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace ptrt {

// CRC-32 (IEEE, table-driven); table init is thread-safe (magic static)
uint32_t crc32(const void *data, size_t n);

// binary reader/writer over a byte vector
struct Writer {
  std::vector<uint8_t> buf;
  void u32(uint32_t v) { raw(&v, 4); }
  void u64(uint64_t v) { raw(&v, 8); }
  void i64(int64_t v) { raw(&v, 8); }
  void f64(double v) { raw(&v, 8); }
  void str(const std::string &s) {
    u64(s.size());
    raw(s.data(), s.size());
  }
  void bytes(const void *p, size_t n) {
    u64(n);
    raw(p, n);
  }
  void raw(const void *p, size_t n) {
    const uint8_t *b = static_cast<const uint8_t *>(p);
    buf.insert(buf.end(), b, b + n);
  }
};

struct Reader {
  const uint8_t *p;
  size_t n, off = 0;
  Reader(const void *data, size_t len)
      : p(static_cast<const uint8_t *>(data)), n(len) {}
  bool ok(size_t k) const { return off + k <= n; }
  uint32_t u32() { uint32_t v = 0; get(&v, 4); return v; }
  uint64_t u64() { uint64_t v = 0; get(&v, 8); return v; }
  int64_t i64() { int64_t v = 0; get(&v, 8); return v; }
  double f64() { double v = 0; get(&v, 8); return v; }
  std::string str() {
    uint64_t k = u64();
    if (!ok(k)) return "";
    std::string s(reinterpret_cast<const char *>(p + off), k);
    off += k;
    return s;
  }
  // zero-copy view of a length-prefixed blob
  const uint8_t *blob(uint64_t *len) {
    *len = u64();
    if (!ok(*len)) { *len = 0; return nullptr; }
    const uint8_t *b = p + off;
    off += *len;
    return b;
  }
  void get(void *out, size_t k) {
    if (!ok(k)) { memset(out, 0, k); return; }
    memcpy(out, p + off, k);
    off += k;
  }
};

// handler: (opcode, request reader) -> response writer content
using Handler = std::function<void(uint32_t, Reader &, Writer &)>;

class Server {
 public:
  // port 0 -> ephemeral; bound port readable via port()
  Server(int port, Handler handler);
  ~Server();
  void stop();
  int port() const { return port_; }

 private:
  void acceptLoop();
  void serveConn(int fd);
  void reapFinishedLocked();
  int listen_fd_ = -1;
  int port_ = 0;
  Handler handler_;
  std::atomic<bool> stopping_{false};
  std::thread accept_thread_;
  std::mutex conn_mu_;
  std::map<int, std::thread> conns_;  // fd -> serving thread
  std::vector<int> finished_fds_;     // done threads awaiting join/reap
};

class Client {
 public:
  Client(const std::string &host, int port);
  ~Client();
  bool connected() const { return fd_ >= 0; }
  // send request, block for response; returns false on IO error
  bool call(uint32_t opcode, const Writer &req, std::vector<uint8_t> *resp);

 private:
  int fd_ = -1;
};

bool sendFrame(int fd, uint32_t opcode, const void *payload, uint64_t len);
bool recvFrame(int fd, uint32_t *opcode, std::vector<uint8_t> *payload);

}  // namespace ptrt

#endif
