"""C inference API: save a model, then load + infer from a real C
program linked against libpaddle_tpu_capi.so (reference test analog:
paddle/capi/examples/model_inference)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")

C_SRC = r'''
#include <stdio.h>
#include <stdint.h>

extern void *ptcapi_create(const char *model_dir);
extern int64_t ptcapi_run(void *h, const float *in, const int64_t *dims,
                          int ndims, float *out, int64_t cap,
                          int64_t *out_dims, int *out_ndims);
extern void ptcapi_destroy(void *h);

int main(int argc, char **argv) {
  void *h = ptcapi_create(argv[1]);
  if (!h) { fprintf(stderr, "create failed\n"); return 2; }
  float in[8];
  for (int i = 0; i < 8; ++i) in[i] = (float)i / 8.0f;
  int64_t dims[2] = {2, 4};
  float out[64];
  int64_t out_dims[8];
  int out_nd = 0;
  int64_t n = ptcapi_run(h, in, dims, 2, out, 64, out_dims, &out_nd);
  if (n < 0) { fprintf(stderr, "run failed\n"); return 3; }
  printf("n=%lld nd=%d", (long long)n, out_nd);
  for (int i = 0; i < out_nd; ++i)
    printf(" d%d=%lld", i, (long long)out_dims[i]);
  for (int i = 0; i < (n < 6 ? n : 6); ++i) printf(" v%d=%.6f", i, out[i]);
  printf("\n");
  ptcapi_destroy(h);
  return 0;
}
'''


@pytest.fixture(scope="module", autouse=True)
def _build_capi():
    # binaries are not committed; make is a no-op when fresh
    from paddle_tpu.native import _build

    _build()


def test_c_program_infers_saved_model(tmp_path):
    # 1) build + save a tiny model with known weights
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.fc(input=x, size=3, act=None)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    model_dir = str(tmp_path / "model")
    fluid.io.save_inference_model(model_dir, ["x"], [y], exe)

    inp = (np.arange(8, dtype=np.float32) / 8.0).reshape(2, 4)
    expect, = exe.run(fluid.io.load_inference_model(model_dir, exe)[0],
                      feed={"x": inp}, fetch_list=[y.name])

    # 2) compile the C consumer
    src = tmp_path / "use_capi.c"
    src.write_text(C_SRC)
    exe_path = str(tmp_path / "use_capi")
    subprocess.run(
        ["gcc", str(src), "-o", exe_path,
         "-L" + NATIVE_DIR, "-lpaddle_tpu_capi",
         "-Wl,-rpath," + NATIVE_DIR],
        check=True)

    # 3) run it against the saved model
    env = {**os.environ,
           "PYTHONPATH": os.path.dirname(NATIVE_DIR),
           "JAX_PLATFORMS": "cpu"}
    out = subprocess.run([exe_path, model_dir], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    fields = dict(kv.split("=") for kv in out.stdout.split())
    assert fields["n"] == "6" and fields["nd"] == "2"
    assert fields["d0"] == "2" and fields["d1"] == "3"
    got = [float(fields["v%d" % i]) for i in range(6)]
    np.testing.assert_allclose(got, np.asarray(expect).reshape(-1),
                               rtol=1e-5)
