"""paddle_tpu.compile.passes — the Program-level rewrite engine."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.compile import passes
from paddle_tpu.core.scope import Scope
from paddle_tpu.fluid import executor as executor_mod
from paddle_tpu.utils import flags


@pytest.fixture(autouse=True)
def _reset_compile_flags():
    yield
    flags.set_flag("compile_passes", "")


def _run(main, startup, fetch, feed):
    exe = executor_mod.Executor(executor_mod.CPUPlace())
    with executor_mod.scope_guard(Scope()):
        exe.run(startup)
        return np.asarray(exe.run(main, feed=feed,
                                  fetch_list=[fetch])[0])


def _op_types(program):
    return [od.type for od in program.global_block().desc.ops]


def _crafted():
    """One program exercising every pass: a dead op (dce), a duplicate
    pure op (cse), a static `shape` op (fold), and the vars they
    orphan (dve)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32",
                              append_batch_size=False)
        y = fluid.layers.scale(x=x, scale=2.0)
        fluid.layers.scale(x=x, scale=9.0)          # dead
        y2 = fluid.layers.scale(x=x, scale=2.0)     # duplicate of y
        z = fluid.layers.elementwise_add(x=y, y=y2)
        blk = main.global_block()
        sv = blk.create_var(name="shp_vec", dtype="int32", shape=[1])
        blk.append_op(type="shape", inputs={"Input": [y.name]},
                      outputs={"Out": [sv.name]}, infer_shape=False)
        shp = fluid.layers.cast(x=sv, dtype="float32")
        fin = fluid.layers.elementwise_add(
            x=z, y=fluid.layers.reduce_sum(shp))
    return main, startup, fin.name


class TestIndividualPasses:
    def test_dce_removes_dead_op(self):
        main, _, fetch = _crafted()
        opt = passes.PassManager("dce").run(main, fetches=[fetch])
        assert _op_types(opt).count("scale") == 2  # dead one gone
        assert _op_types(main).count("scale") == 3  # input untouched

    def test_dce_keeps_everything_without_fetches(self):
        # fetch is runtime-invisible: without the fetch set, sinks
        # (the final add) would be false positives — nothing goes
        main, _, fetch = _crafted()
        opt = passes.PassManager("dce").run(main, fetches=[])
        assert len(_op_types(opt)) == len(_op_types(main))

    def test_fold_rewrites_static_shape_op(self):
        main, startup, fetch = _crafted()
        opt = passes.PassManager("fold").run(main, fetches=[fetch])
        types = _op_types(opt)
        assert "shape" not in types and "assign_value" in types
        od = opt.global_block().desc.ops[types.index("assign_value")]
        assert od.attrs["values"] == [4]

    def test_fold_skips_dynamic_dims(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            # data layers get a -1 batch dim: never foldable
            x = fluid.layers.data(name="x", shape=[4],
                                  dtype="float32")
            blk = main.global_block()
            sv = blk.create_var(name="s", dtype="int32", shape=[2])
            blk.append_op(type="shape", inputs={"Input": [x.name]},
                          outputs={"Out": [sv.name]},
                          infer_shape=False)
        opt = passes.PassManager("fold").run(main, fetches=[sv.name])
        assert "shape" in _op_types(opt)

    def test_cse_dedupes_and_renames(self):
        main, _, fetch = _crafted()
        opt = passes.PassManager("cse").run(main, fetches=[fetch])
        # the duplicate scale(x, 2.0) collapses; the dead 9.0 stays
        assert _op_types(opt).count("scale") == 2
        add = next(od for od in opt.global_block().desc.ops
                   if od.type == "elementwise_add")
        assert add.input("X") == add.input("Y")  # both renamed to y

    def test_cse_respects_redefinition(self):
        # two identical ops with a redefinition of the input between
        # them compute DIFFERENT values: they must not merge
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4],
                                  dtype="float32",
                                  append_batch_size=False)
            blk = main.global_block()
            a = fluid.layers.scale(x=x, scale=2.0)
            # redefine a in place (same name out as in)
            blk.append_op(type="scale", inputs={"X": [a.name]},
                          outputs={"Out": [a.name]},
                          attrs={"scale": 5.0}, infer_shape=False)
            b = fluid.layers.scale(x=x, scale=2.0)
            out = fluid.layers.elementwise_add(x=a, y=b)
        opt = passes.PassManager("cse").run(main, fetches=[out.name])
        # `a` has two def sites -> not a CSE candidate; all ops stay
        assert _op_types(opt).count("scale") == 3

    def test_dve_sweeps_orphans(self):
        main, _, fetch = _crafted()
        pm = passes.PassManager("dce,cse,dve", explain=True)
        opt = pm.run(main, fetches=[fetch])
        removed = [r for r in pm.records if r["pass"] == "dve"][0]
        assert removed["vars_after"] < removed["vars_before"]


class TestControlFlow:
    def test_dce_preserves_while_body(self):
        """Regression: a while body's ops write carry vars DECLARED IN
        THE PARENT block — the dead-op fixpoint must treat every
        cross-block name as live, or the whole loop body looks dead
        and the loop silently degenerates."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            i = fluid.layers.fill_constant(shape=[1], dtype="float32",
                                           value=0.0)
            acc = fluid.layers.fill_constant(shape=[1],
                                             dtype="float32",
                                             value=0.0)
            limit = fluid.layers.fill_constant(shape=[1],
                                               dtype="float32",
                                               value=5.0)
            cond = fluid.layers.less_than(x=i, y=limit)
            w = fluid.layers.While(cond=cond)
            with w.block():
                ni = fluid.layers.increment(x=i, value=1.0,
                                            in_place=True)
                nacc = fluid.layers.elementwise_add(x=acc, y=ni)
                fluid.layers.assign(input=nacc, output=acc)
                fluid.layers.less_than(x=ni, y=limit, cond=cond)
            out = fluid.layers.scale(x=acc, scale=2.0)
        opt = passes.PassManager("default").run(main,
                                                fetches=[out.name])
        assert len(opt.desc.block(1).ops) == \
            len(main.desc.block(1).ops)
        plain = _run(main, startup, out.name, {})
        o = _run(opt, startup, out.name, {})
        np.testing.assert_array_equal(plain, o)
        assert float(plain[0]) == 30.0


    def test_dce_preserves_recurrent_body(self):
        """Regression: the `recurrent` op wires its sub-block through
        NAME-LIST ATTRS (mem_post_names/step_output_names...), not
        slots — the dead-op fixpoint must count string attr refs as
        live or the whole scan body is removed when fetches are
        given."""
        x = fluid.layers.data(name="x", shape=[8], dtype="float32",
                              lod_level=1)
        drnn = fluid.layers.DynamicRNN()
        with drnn.block():
            step = drnn.step_input(x)
            mem = drnn.memory(shape=[8], batch_ref=step, value=0.0)
            h = fluid.layers.fc(input=[step, mem], size=8, act="tanh")
            drnn.update_memory(mem, h)
            drnn.output(h)
        last = fluid.layers.sequence_last_step(input=drnn())
        loss = fluid.layers.mean(x=last)
        main = fluid.default_main_program()
        opt = passes.PassManager("dce,dve").run(main,
                                                fetches=[loss.name])
        assert len(opt.desc.block(1).ops) == \
            len(main.desc.block(1).ops)


class TestPassManager:
    def test_semantics_preserved_bit_identical(self):
        main, startup, fetch = _crafted()
        opt = passes.PassManager("default",
                                 verify_level="full").run(
            main, fetches=[fetch])
        xv = np.arange(4, dtype=np.float32)
        a = _run(main, startup, fetch, {"x": xv})
        b = _run(opt, startup, fetch, {"x": xv})
        np.testing.assert_array_equal(a, b)

    def test_input_program_never_mutated(self):
        main, _, fetch = _crafted()
        before = main.desc.serialize_to_string()
        passes.PassManager("default").run(main, fetches=[fetch])
        assert main.desc.serialize_to_string() == before

    def test_pipeline_id_stable_and_versioned(self):
        pm = passes.PassManager("dce,cse")
        assert pm.pipeline_id == "v%d:dce,cse" % passes._PIPELINE_VERSION
        assert passes.PassManager("default").pipeline_id == \
            passes.PassManager().pipeline_id
        assert passes.pipeline_id("") == ""

    def test_unknown_pass_rejected(self):
        with pytest.raises(ValueError, match="unknown pass"):
            passes.PassManager("dce,nope")

    def test_explain_text(self):
        main, _, fetch = _crafted()
        pm = passes.PassManager("default", explain=True)
        pm.run(main, fetches=[fetch])
        text = pm.explain_text()
        assert "pipeline v" in text and "dce" in text
        assert "removed_ops" in text

    def test_verifier_runs_around_every_pass(self, monkeypatch):
        from paddle_tpu.analysis.diagnostics import \
            ProgramVerificationError

        class BreakIR(passes.RewritePass):
            name = "dce"  # masquerade in the pipeline slot

            def run(self, desc, ctx):
                # drop a var another op still reads: V002
                del desc.block(0).vars["x"]
                return {"broke": ["it"]}

        monkeypatch.setitem(passes._PASSES, "dce", BreakIR())
        main, _, fetch = _crafted()
        with pytest.raises(ProgramVerificationError):
            passes.PassManager("dce").run(main, fetches=[fetch])


class TestExecutorFlagWiring:
    def test_flag_applies_pipeline(self):
        main, startup, fetch = _crafted()
        xv = np.arange(4, dtype=np.float32)
        plain = _run(main, startup, fetch, {"x": xv})
        flags.set_flag("compile_passes", "default")
        optimized = _run(main, startup, fetch, {"x": xv})
        np.testing.assert_array_equal(plain, optimized)
        # the user's program object is untouched by the executor
        assert _op_types(main).count("scale") == 3

    def test_flag_flip_invalidates_program_cache(self):
        # a flipped pass config must not reuse a _CompiledProgram
        # built under the old one (the key encodes the flag, like amp)
        main, startup, fetch = _crafted()
        xv = np.arange(4, dtype=np.float32)
        exe = executor_mod.Executor(executor_mod.CPUPlace())
        with executor_mod.scope_guard(Scope()):
            exe.run(startup)
            exe.run(main, feed={"x": xv}, fetch_list=[fetch])
            n_plain = len(exe._cache)
            flags.set_flag("compile_passes", "default")
            out = exe.run(main, feed={"x": xv}, fetch_list=[fetch])
        assert len(exe._cache) == n_plain + 1
        np.testing.assert_array_equal(
            np.asarray(out[0]),
            _run(main, startup, fetch, {"x": xv}))
