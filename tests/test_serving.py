"""paddle_tpu.serving: bucketed compile cache, dynamic micro-batching,
backpressure, metrics, and the end-to-end HTTP server.

Tier-1 (CPU): bucket padding must be invisible to results, split/merge
must round-trip (incl. ragged LoD inputs), deadlines and queue bounds
must reject rather than hang, and two same-bucket requests must share
one compiled executable (measured via jit specialization counts, not
assumed)."""

import http.client
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.ragged import RaggedTensor
from paddle_tpu.core.scope import Scope
from paddle_tpu.fluid import io as fluid_io
from paddle_tpu.serving import (
    InferenceEngine, EngineConfig, MicroBatcher, BatcherConfig,
    InferenceServer, ServerConfig, QueueFullError,
    DeadlineExceededError, ShuttingDownError)
from paddle_tpu.serving.metrics import ServingMetrics


# ---------------------------------------------------------------------------
# model builders
# ---------------------------------------------------------------------------

def _digits_model(tmp_path):
    """A recognize-digits-style MLP exported for inference (startup
    init only: serving correctness is about transport, not accuracy)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[64], dtype="float32")
        hidden = fluid.layers.fc(input=img, size=32, act="tanh")
        probs = fluid.layers.fc(input=hidden, size=10, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(Scope()):
        exe.run(startup)
        fluid_io.save_inference_model(
            str(tmp_path), ["img"], [probs], exe, main_program=main,
            bucket_hints={"batch_buckets": [2, 4, 8]})
    return str(tmp_path)


def _ragged_model():
    """A sequence model (lod_level-1 feed, sequence_pool) built in the
    default program; returns (program, feed_names, fetch_vars)."""
    x = fluid.layers.data(name="x", shape=[4], dtype="float32",
                          lod_level=1)
    pooled = fluid.layers.sequence_pool(input=x, pool_type="sum")
    logits = fluid.layers.fc(input=pooled, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    program = fluid_io.prune_program(fluid.default_main_program(),
                                     [logits])
    return program, ["x"], [logits]


def _rand_images(batch, seed=0):
    return np.random.RandomState(seed).rand(batch, 64).astype(
        np.float32)


# ---------------------------------------------------------------------------
# engine: bucket padding + compile cache
# ---------------------------------------------------------------------------

def test_bucket_padding_matches_direct_run(tmp_path):
    model_dir = _digits_model(tmp_path)
    engine = InferenceEngine.from_saved_model(model_dir)
    assert engine.config.batch_buckets == (2, 4, 8)  # export hints

    # direct executor run on the exact (unpadded) shape
    exe = fluid.Executor(fluid.CPUPlace())
    imgs = _rand_images(3)
    with fluid.scope_guard(engine.scope):
        want, = exe.run(engine.program, feed={"img": imgs},
                        fetch_list=engine.fetch_names,
                        scope=engine.scope)

    got, = engine.run({"img": imgs})
    assert got.shape == (3, 10)  # sliced back from the 4-bucket
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_bucket_for_rounding():
    cfg = EngineConfig(batch_buckets=[2, 4, 8])
    assert [cfg.bucket_for(b) for b in (1, 2, 3, 4, 7, 8)] == \
        [2, 2, 4, 4, 8, 8]
    assert cfg.bucket_for(9) == 16  # beyond top: multiples of 8
    assert cfg.bucket_for(17) == 24
    none_cfg = EngineConfig(batch_buckets=None)
    assert none_cfg.bucket_for(5) == 5


def test_no_recompile_across_same_bucket_requests(tmp_path):
    engine = InferenceEngine.from_saved_model(_digits_model(tmp_path))
    engine.warmup()
    traces_after_warmup = engine.trace_count()
    assert traces_after_warmup > 0

    # two requests with DIFFERENT true batches landing in one bucket
    timings = {}
    engine.run({"img": _rand_images(3, seed=1)}, timings=timings)
    assert timings["compiled"] is False
    engine.run({"img": _rand_images(4, seed=2)}, timings=timings)
    assert timings["compiled"] is False
    assert engine.trace_count() == traces_after_warmup


def test_cache_hit_miss_counters(tmp_path):
    metrics = ServingMetrics()
    engine = InferenceEngine.from_saved_model(_digits_model(tmp_path),
                                              metrics=metrics)
    engine.run({"img": _rand_images(2)})          # cold: compile
    assert metrics.cache_miss_total.value == 1
    engine.run({"img": _rand_images(1, seed=3)})  # same 2-bucket: hit
    assert metrics.cache_hit_total.value == 1
    assert metrics.cache_miss_total.value == 1


# ---------------------------------------------------------------------------
# batcher: split/merge, deadlines, backpressure
# ---------------------------------------------------------------------------

def test_microbatch_split_merge_round_trip(tmp_path):
    engine = InferenceEngine.from_saved_model(_digits_model(tmp_path))
    engine.warmup()
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=8, max_wait_ms=100)).start()
    try:
        inputs = [_rand_images(b, seed=10 + b) for b in (1, 2, 3)]
        singles = [engine.run({"img": x})[0] for x in inputs]

        barrier = threading.Barrier(3)
        futures = [None] * 3

        def submit(i):
            barrier.wait()
            futures[i] = batcher.submit({"img": inputs[i]})

        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, fut in enumerate(futures):
            got, = fut.result(timeout=30)
            assert got.shape == inputs[i].shape[:1] + (10,)
            np.testing.assert_allclose(got, singles[i], rtol=1e-5,
                                       atol=1e-6)
    finally:
        batcher.close()


def test_microbatch_ragged_round_trip():
    program, feed_names, fetch_vars = _ragged_model()
    engine = InferenceEngine(program, feed_names, fetch_vars,
                             config=EngineConfig(batch_buckets=[4],
                                                 token_bucket=16))
    seqs_a = [np.arange(8, dtype=np.float32).reshape(2, 4),
              np.ones((3, 4), np.float32)]
    seqs_b = [np.full((1, 4), 2.0, np.float32)]
    single_a, = engine.run({"x": seqs_a})
    single_b, = engine.run({"x": seqs_b})
    assert np.asarray(single_a).shape == (2, 3)
    assert np.asarray(single_b).shape == (1, 3)

    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=8, max_wait_ms=100)).start()
    try:
        barrier = threading.Barrier(2)
        futures = [None, None]

        def submit(i, seqs):
            barrier.wait()
            futures[i] = batcher.submit({"x": seqs})

        threads = [threading.Thread(target=submit, args=(0, seqs_a)),
                   threading.Thread(target=submit, args=(1, seqs_b))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        got_a, = futures[0].result(timeout=30)
        got_b, = futures[1].result(timeout=30)
        np.testing.assert_allclose(np.asarray(got_a),
                                   np.asarray(single_a), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(got_b),
                                   np.asarray(single_b), rtol=1e-5,
                                   atol=1e-6)
    finally:
        batcher.close()


def test_ragged_warmup_compiles_buckets():
    """warmup() must survive LoD feeds (per-row feature dims kept) and
    actually cover the smallest token shape of each batch bucket."""
    program, feed_names, fetch_vars = _ragged_model()
    engine = InferenceEngine(program, feed_names, fetch_vars,
                             config=EngineConfig(batch_buckets=[2, 4],
                                                 token_bucket=16))
    assert engine.warmup() == 2
    traces = engine.trace_count()
    # one-token sequences land exactly on the warmed shape: no retrace
    got, = engine.run({"x": [np.zeros((1, 4), np.float32),
                             np.ones((1, 4), np.float32)]})
    assert np.asarray(got).shape == (2, 3)
    assert engine.trace_count() == traces


class _SlowEngine:
    """Engine stand-in that blocks until released — makes queue-full
    and deadline states deterministic."""

    def __init__(self, release):
        self.feed_names = ["img"]
        self.fetch_names = ["out"]
        self._feed_meta = {"img": {"shape": [-1, 4],
                                   "dtype": np.dtype(np.float32),
                                   "lod_level": 0}}
        self.metrics = None
        self._release = release

    def batch_size(self, feeds):
        return int(np.asarray(feeds["img"]).shape[0])

    def run(self, feeds, timings=None):
        self._release.wait(timeout=30)
        return [np.asarray(feeds["img"])]


def test_deadline_exceeded_rejection():
    release = threading.Event()
    batcher = MicroBatcher(
        _SlowEngine(release),
        BatcherConfig(max_batch=1, max_wait_ms=0, queue_size=8)).start()
    try:
        # first request occupies the engine; the second's 20ms deadline
        # expires while it waits behind it
        blocker = batcher.submit({"img": np.zeros((1, 4), np.float32)})
        doomed = batcher.submit({"img": np.ones((1, 4), np.float32)},
                                timeout_ms=20)
        time.sleep(0.1)
        release.set()
        blocker.result(timeout=30)
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=30)
    finally:
        batcher.close()


def test_queue_full_load_shedding():
    release = threading.Event()
    metrics = ServingMetrics()
    batcher = MicroBatcher(
        _SlowEngine(release),
        BatcherConfig(max_batch=1, max_wait_ms=0, queue_size=2),
        metrics=metrics).start()
    try:
        feeds = {"img": np.zeros((1, 4), np.float32)}
        futures = [batcher.submit(feeds)]  # occupies the engine
        # fill the admission queue, then overflow it
        admitted = 0
        with pytest.raises(QueueFullError):
            for _ in range(16):
                futures.append(batcher.submit(feeds))
                admitted += 1
        assert admitted <= 3  # 1 in-flight grace + queue_size
        assert metrics.rejected_queue_full.value >= 1
        release.set()
        for fut in futures:  # everything admitted still completes
            fut.result(timeout=30)
    finally:
        batcher.close()


def test_draining_rejects_new_submits():
    release = threading.Event()
    release.set()
    batcher = MicroBatcher(_SlowEngine(release), BatcherConfig()).start()
    batcher.close()
    with pytest.raises(ShuttingDownError):
        batcher.submit({"img": np.zeros((1, 4), np.float32)})


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def test_metrics_counters_monotonic(tmp_path):
    metrics = ServingMetrics()
    engine = InferenceEngine.from_saved_model(_digits_model(tmp_path),
                                              metrics=metrics)
    batcher = MicroBatcher(engine, BatcherConfig(max_wait_ms=0),
                           metrics=metrics).start()
    try:
        seen = []
        for i in range(4):
            batcher.submit_and_wait({"img": _rand_images(2, seed=i)})
            seen.append((metrics.requests_total.value,
                         metrics.responses_total.value,
                         metrics.cache_hit_total.value
                         + metrics.cache_miss_total.value,
                         metrics.total_seconds.count))
        for prev, cur in zip(seen, seen[1:]):
            assert all(c >= p for p, c in zip(prev, cur)), seen
        assert seen[-1][0] == seen[-1][1] == 4
        with pytest.raises(ValueError):
            metrics.requests_total.inc(-1)  # counters can't go down
    finally:
        batcher.close()


def test_metrics_render_text():
    metrics = ServingMetrics()
    metrics.requests_total.inc(3)
    metrics.batch_occupancy.observe(2)
    metrics.observe_stage("queue", 0.004)
    text = metrics.render_text()
    assert "serving_requests_total 3" in text
    assert 'serving_batch_occupancy_bucket{le="2"} 1' in text
    assert "serving_queue_seconds_count 1" in text
    # the profiler mirror row exists too
    from paddle_tpu.fluid import profiler

    assert "serving/queue" in profiler.get_profile_records()


# ---------------------------------------------------------------------------
# end-to-end HTTP server
# ---------------------------------------------------------------------------

def _post(host, port, path, payload, timeout=30):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", path, json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _get(host, port, path, timeout=30):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


def _metric_value(text, name):
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    raise AssertionError("metric %s not in:\n%s" % (name, text))


def test_server_end_to_end_concurrent_clients(tmp_path):
    """Acceptance: N concurrent clients get correct per-request
    outputs, batch-occupancy > 1 lands in metrics, zero recompiles
    after warmup, and the server drains cleanly."""
    engine = InferenceEngine.from_saved_model(_digits_model(tmp_path))
    server = InferenceServer(engine, ServerConfig(
        port=0, max_batch=16, max_wait_ms=150, queue_size=32)).start()
    host, port = server.address
    try:
        traces_after_warmup = engine.trace_count()
        assert traces_after_warmup > 0  # warmup compiled the buckets
        # warmup compiles are startup cost, not traffic: the
        # request-path histograms/counters must still be zero
        assert server.metrics.compute_seconds.count == 0
        assert server.metrics.cache_miss_total.value == 0

        n_clients = 6
        inputs = [_rand_images(1, seed=20 + i) for i in range(n_clients)]
        singles = [engine.run({"img": x})[0] for x in inputs]
        barrier = threading.Barrier(n_clients)
        results = [None] * n_clients

        def client(i):
            barrier.wait()
            results[i] = _post(host, port, "/v1/infer",
                               {"inputs": {"img": inputs[i].tolist()}})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        fetch = engine.fetch_names[0]
        for i, (status, body) in enumerate(results):
            assert status == 200, body
            got = np.asarray(body["outputs"][fetch], np.float32)
            np.testing.assert_allclose(got, singles[i], rtol=1e-4,
                                       atol=1e-5)

        # zero recompiles: every request landed in a warmed bucket
        assert engine.trace_count() == traces_after_warmup

        status, text = _get(host, port, "/metrics")
        assert status == 200
        assert server.metrics.batch_occupancy.max > 1, \
            "no micro-batch coalesced >1 concurrent requests"
        assert _metric_value(text, "serving_responses_total") \
            >= n_clients
        # monotonic across scrapes
        status2, text2 = _get(host, port, "/metrics")
        assert _metric_value(text2, "serving_responses_total") >= \
            _metric_value(text, "serving_responses_total")

        status, body = _get(host, port, "/healthz")
        assert status == 200 and "ok" in body
    finally:
        server.shutdown()
    # drained cleanly: post-shutdown submits are refused, not hung
    with pytest.raises(ShuttingDownError):
        server.batcher.submit({"img": inputs[0]})


def test_server_queue_full_returns_429(tmp_path):
    release = threading.Event()
    engine = _SlowEngine(release)
    server = InferenceServer(engine, ServerConfig(
        port=0, max_batch=1, max_wait_ms=0, queue_size=1,
        warmup=False)).start()
    host, port = server.address
    try:
        payload = {"inputs": {"img": [[0.0] * 4]}}
        codes = [None] * 8
        threads = []

        def client(i):
            codes[i] = _post(host, port, "/v1/infer", payload)[0]

        for i in range(8):
            t = threading.Thread(target=client, args=(i,))
            t.start()
            threads.append(t)
        # the engine is blocked, so overflow shows up quickly
        deadline = time.monotonic() + 10
        while 429 not in codes and time.monotonic() < deadline:
            time.sleep(0.01)
        release.set()
        for t in threads:
            t.join(timeout=30)
        assert 429 in codes, codes  # load was shed, not queued
        assert 200 in codes, codes  # admitted work still answered
    finally:
        server.shutdown()


def test_server_deadline_returns_504(tmp_path):
    release = threading.Event()
    engine = _SlowEngine(release)
    server = InferenceServer(engine, ServerConfig(
        port=0, max_batch=1, max_wait_ms=0, queue_size=8,
        warmup=False)).start()
    host, port = server.address
    try:
        payload = {"inputs": {"img": [[0.0] * 4]}}
        statuses = {}

        def blocker():
            statuses["blocker"] = _post(host, port, "/v1/infer",
                                        payload)[0]

        def doomed():
            statuses["doomed"] = _post(
                host, port, "/v1/infer",
                dict(payload, timeout_ms=20))[0]

        tb = threading.Thread(target=blocker)
        tb.start()
        time.sleep(0.2)  # blocker is in the engine; queue the doomed one
        td = threading.Thread(target=doomed)
        td.start()
        time.sleep(0.2)
        release.set()
        tb.join(timeout=30)
        td.join(timeout=30)
        assert statuses["blocker"] == 200, statuses
        assert statuses["doomed"] == 504, statuses
    finally:
        server.shutdown()


def test_server_bad_request_and_draining(tmp_path):
    engine = InferenceEngine.from_saved_model(_digits_model(tmp_path))
    server = InferenceServer(engine, ServerConfig(
        port=0, warmup=False)).start()
    host, port = server.address
    try:
        status, body = _post(host, port, "/v1/infer", {"inputs": {}})
        assert status == 400 and "img" in body["error"]
        # wrong per-sample shape is rejected at admission (it must
        # never reach the batcher and poison a co-batched request)
        status, body = _post(host, port, "/v1/infer",
                             {"inputs": {"img": [[0.0] * 8]}})
        assert status == 400 and "shape" in body["error"]
        status, _ = _post(host, port, "/nope", {})
        assert status == 404
    finally:
        server.shutdown()
    assert server.draining
    status, body = server.handle_infer(
        {"inputs": {"img": [[0.0] * 64]}})
    assert status == 503


def _post_with_headers(host, port, path, payload, timeout=30):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", path, json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read()), \
            dict(resp.getheaders())
    finally:
        conn.close()


def test_server_429_carries_retry_after():
    """A load-shed reply must advertise its backoff hint: closed-loop
    clients (and pload workers) re-offer shed work immediately
    otherwise (docs/SERVING.md backpressure contract)."""
    release = threading.Event()
    engine = _SlowEngine(release)
    server = InferenceServer(engine, ServerConfig(
        port=0, max_batch=1, max_wait_ms=0, queue_size=1,
        warmup=False, retry_after_s=2.0)).start()
    host, port = server.address
    try:
        payload = {"inputs": {"img": [[0.0] * 4]}}
        results = [None] * 8
        threads = []

        def client(i):
            results[i] = _post_with_headers(host, port, "/v1/infer",
                                            payload)

        for i in range(8):
            t = threading.Thread(target=client, args=(i,))
            t.start()
            threads.append(t)
        deadline = time.monotonic() + 10
        while not any(r and r[0] == 429 for r in results) \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        release.set()
        for t in threads:
            t.join(timeout=30)
        shed = [r for r in results if r and r[0] == 429]
        assert shed, [r and r[0] for r in results]
        for status, body, headers in shed:
            assert headers.get("Retry-After") == "2", headers
            assert body["request_id"]  # shed replies stay quotable
        ok = [r for r in results if r and r[0] == 200]
        assert ok and all("Retry-After" not in r[2] for r in ok)
    finally:
        server.shutdown()


def test_concurrent_load_statuses_complete():
    """The batcher deadline/504 path under concurrent submits: many
    producers against a slow engine must each get exactly one of
    200/429/504 — with a request_id — and no future may hang."""
    release = threading.Event()
    engine = _SlowEngine(release)
    server = InferenceServer(engine, ServerConfig(
        port=0, max_batch=2, max_wait_ms=0, queue_size=4,
        warmup=False))
    server.batcher.start()  # loopback: no HTTP listener needed
    try:
        n = 24
        results = [None] * n
        payload = {"inputs": {"img": [[0.0] * 4]}, "timeout_ms": 150}

        def producer(i):
            results[i] = server.handle_infer(dict(payload))

        threads = [threading.Thread(target=producer, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        # the engine is blocked past every queued request's 150ms
        # deadline: queued work expires (504), overflow sheds (429),
        # the batch already in the engine completes (200)
        time.sleep(0.4)
        release.set()
        for t in threads:
            t.join(timeout=30)
        assert all(r is not None for r in results), \
            "a submit hung: %r" % [i for i, r in enumerate(results)
                                   if r is None]
        statuses = [status for status, _ in results]
        assert all(s in (200, 429, 504) for s in statuses), statuses
        for status, body in results:
            assert body.get("request_id"), (status, body)
        assert 200 in statuses, statuses   # admitted work answered
        assert 429 in statuses, statuses   # overflow shed
        assert 504 in statuses, statuses   # expired at dequeue
        # nothing left pending inside the batcher either
        assert server.metrics.queue_depth.value == 0
    finally:
        server.batcher.close()


def test_queue_depth_peak_high_watermark():
    """The peak gauge keeps the worst depth between scrapes — set on
    enqueue, dequeue AND the shed path — and a render resets the
    window to the live depth."""
    metrics = ServingMetrics()
    metrics.note_queue_depth(3)
    metrics.note_queue_depth(1)
    assert metrics.queue_depth.value == 1
    assert metrics.queue_depth_peak.value == 3
    text = metrics.render_text()
    assert "serving_queue_depth_peak 3" in text
    # the scrape carried the watermark out; the window restarts at
    # the live depth
    assert metrics.queue_depth_peak.value == 1
    assert "serving_queue_depth_peak 1" in metrics.render_text()

    # the shed path publishes the saturated depth (an overflowing
    # queue between enqueue/dequeue samples was formerly invisible)
    release = threading.Event()
    shed_metrics = ServingMetrics()
    batcher = MicroBatcher(
        _SlowEngine(release),
        BatcherConfig(max_batch=1, max_wait_ms=0, queue_size=2),
        metrics=shed_metrics).start()
    try:
        feeds = {"img": np.zeros((1, 4), np.float32)}
        futures = [batcher.submit(feeds)]
        with pytest.raises(QueueFullError):
            for _ in range(16):
                futures.append(batcher.submit(feeds))
        assert shed_metrics.queue_depth.value >= 2
        assert shed_metrics.queue_depth_peak.value >= 2
        release.set()
        for fut in futures:
            fut.result(timeout=30)
    finally:
        batcher.close()
