"""paddle_tpu.obs.perf: step profiler, bottleneck classifier, perf
history + regression gate, SLO burn, and the jit-path attribution fix
(docs/PERF.md, docs/OBSERVABILITY.md)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.obs import perf as obs_perf
from paddle_tpu.obs import registry as obs_registry
from paddle_tpu.obs import telemetry as obs_tele
from paddle_tpu.utils import flags
from paddle_tpu.tools.obs_dump import validate_chrome_trace


def _tiny_train_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        h = fluid.layers.fc(input=x, size=3)
        cost = fluid.layers.mean(x=h)
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(cost)
    return main, startup, cost


# ---------------------------------------------------------------------------
# classifier
# ---------------------------------------------------------------------------

def test_classify_split_four_verdicts():
    # input-dominated step
    v = obs_perf.classify_split(0.1, device_s=0.05, input_s=0.04)
    assert v["verdict"] == "input_bound" and v["dominant"] == "feed/h2d"
    # host-python dominated
    v = obs_perf.classify_split(0.1, device_s=0.04, input_s=0.01)
    assert v["verdict"] == "host_bound"
    # device-dominated, MXU floor above HBM floor
    v = obs_perf.classify_split(0.1, device_s=0.095, input_s=0.0,
                                t_mxu_s=0.08, t_hbm_s=0.02,
                                dominant="conv2d")
    assert v["verdict"] == "compute_bound" and v["dominant"] == "conv2d"
    # device-dominated, HBM floor above MXU floor
    v = obs_perf.classify_split(0.1, device_s=0.095, input_s=0.0,
                                t_mxu_s=0.01, t_hbm_s=0.07)
    assert v["verdict"] == "hbm_bound"
    # every verdict is from the documented set, shares are sane
    assert v["shares"]["device"] == pytest.approx(0.95)
    assert v["verdict"] in obs_perf.VERDICTS


def test_classify_split_degenerate():
    assert obs_perf.classify_split(0.0)["verdict"] is None
    # no roofline data: still a verdict, with an honest reason
    v = obs_perf.classify_split(0.1, device_s=0.09)
    assert v["verdict"] == "compute_bound"
    assert "no roofline" in v["reason"]


def test_roofline_floors_and_leg_blob():
    main, _, _ = _tiny_train_program()
    floors = obs_perf.roofline_floors(main, peak_tflops=100.0,
                                      hbm_gbps=500.0)
    assert floors["t_mxu_s"] > 0 and floors["t_hbm_s"] > 0
    assert floors["top_ops"] and floors["peak_tflops"] == 100.0
    blob = obs_perf.leg_perf_blob(main, step_s=0.005,
                                  peak_tflops=100.0, hbm_gbps=500.0)
    assert blob["verdict"] in obs_perf.VERDICTS
    assert blob["step_ms"] == 5.0
    assert blob["floors_ms"]["serial"] >= blob["floors_ms"]["ideal"]
    assert blob["time_split_ms"]["device"] == 5.0
    json.dumps(blob)  # BENCH records embed it: must serialize


def test_leg_blob_prefers_xla_numbers():
    main, _, _ = _tiny_train_program()
    # huge measured byte traffic vs tiny flops: must flip to hbm_bound
    blob = obs_perf.leg_perf_blob(main, step_s=0.005,
                                  peak_tflops=100.0, hbm_gbps=500.0,
                                  xla_flops=1e6, xla_bytes=1e12)
    assert blob["verdict"] == "hbm_bound"
    assert blob["xla"]["bytes_accessed"] == 1e12


def test_leg_blob_never_raises_on_unanalyzable_program():
    blob = obs_perf.leg_perf_blob(object(), step_s=0.01)
    assert blob["verdict"] in obs_perf.VERDICTS
    assert "floors_ms" not in blob


# ---------------------------------------------------------------------------
# step profiler
# ---------------------------------------------------------------------------

def _run_steps(n, exe, main, cost, scope, profiler_installed=True):
    for i in range(n):
        with obs_tele.step("t1", examples=2):
            exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[cost], scope=scope)


def test_step_profiler_records_ring_and_split():
    main, startup, cost = _tiny_train_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    profiler = obs_perf.install(capacity=8, sample_every=2)
    try:
        _run_steps(5, exe, main, cost, scope)
    finally:
        obs_perf.uninstall()
    recs = profiler.records()
    assert len(recs) == 5
    # first step carries the jit builds as retraces
    assert recs[0]["retraces"] > 0
    assert sum(r["retraces"] for r in recs[1:]) == 0
    # sampled steps (0, 2, 4) measured a device split; others did not
    sampled = [r for r in recs if r["sampled"]]
    assert [r["step"] for r in sampled] == [0, 2, 4]
    for r in sampled:
        assert r["device_s"] is not None and r["device_s"] > 0
        assert r["host_s"] is not None
    for r in recs:
        assert r["wall_s"] > 0
        assert r["input_s"] > 0          # executor feed path timed
        assert r["h2d_bytes"] > 0        # feed bytes counted
        assert r["trainer"] == "t1" and r["examples"] == 2
    # summary + classification over the ring: step 0 sampled but
    # excluded from the split mean (its span includes the jit
    # compile, which would swamp the steady-state device share)
    s = profiler.summary()
    assert s["steps"] == 5 and s["sampled_steps"] == 2
    assert s["split_ms"]["device"] > 0
    v = profiler.classify()
    assert v["verdict"] in obs_perf.VERDICTS
    # registry surface
    fam = obs_registry.get_registry().counter(
        "perf_steps_profiled_total",
        labelnames=("trainer",))
    assert fam.labels(trainer="t1").value == 5


def test_step_profiler_ring_bounded_and_exports(tmp_path):
    main, startup, cost = _tiny_train_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    profiler = obs_perf.install(capacity=3, sample_every=0)
    try:
        _run_steps(5, exe, main, cost, scope)
    finally:
        obs_perf.uninstall()
    recs = profiler.records()
    assert len(recs) == 3                      # bounded
    assert [r["step"] for r in recs] == [2, 3, 4]  # newest kept
    assert profiler.dropped() == 2
    assert all(not r["sampled"] for r in recs)     # sampling off
    # JSONL export parses line by line
    out = tmp_path / "steps.jsonl"
    profiler.export_jsonl(str(out))
    lines = out.read_text().strip().splitlines()
    assert len(lines) == 3
    for line in lines:
        json.loads(line)
    # Chrome trace export is a valid trace-event doc with perf spans
    trace_out = tmp_path / "steps_trace.json"
    profiler.export_chrome_trace(str(trace_out))
    events = validate_chrome_trace(str(trace_out))
    assert sum(1 for e in events if e["ph"] == "X") == 3
    assert json.load(open(str(trace_out)))["otherData"][
        "dropped_steps"] == 2


def test_step_profiler_leaves_tracer_state_alone():
    """A sampling profiler that turned tracing on for its window must
    turn it back off — and must NOT disable tracing someone else
    enabled."""
    from paddle_tpu.obs import trace as obs_trace

    main, startup, cost = _tiny_train_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    # events a user buffered BEFORE installing the profiler must
    # survive owned sampling windows (the window is spliced out, the
    # epoch untouched)
    obs_trace.enable(clear=True)
    obs_trace.instant("user_marker", cat="user")
    obs_trace.disable()
    kept = obs_trace.event_count()
    epoch0 = obs_trace.epoch()
    profiler = obs_perf.install(sample_every=1)
    try:
        assert not obs_trace.is_enabled()
        _run_steps(1, exe, main, cost, scope)
        assert not obs_trace.is_enabled()    # sampling window closed
        assert obs_trace.event_count() == kept   # window spliced out
        assert obs_trace.epoch() == epoch0       # no re-base
        assert any(ev["name"] == "user_marker"
                   for ev in obs_trace.events())
        obs_trace.enable(clear=True)
        _run_steps(1, exe, main, cost, scope)
        assert obs_trace.is_enabled()        # not ours to disable
        assert obs_trace.event_count() > 0   # nor to clear
    finally:
        obs_trace.disable()
        obs_perf.uninstall()
    assert profiler.records()[-1]["sampled"]


def test_attribution_floors_scope_to_executor_segments():
    """The whole-step bench/step gauge covers the same work as the
    per-segment gauges: summing both would double-count."""
    reg = obs_registry.get_registry()
    for seg, flops in (("jit_segment[0:mul..mean x3]", 1e9),
                       ("jit_segment[1:sgd x1]", 2e9),
                       ("bench/step", 3e9)):
        reg.gauge("xla_flops", labelnames=("segment",)) \
           .labels(segment=seg).set(flops)
        reg.gauge("xla_bytes_accessed", labelnames=("segment",)) \
           .labels(segment=seg).set(flops)  # same shape, any value
    floors = obs_perf.attribution_floors(peak_tflops=1.0, hbm_gbps=1.0)
    assert floors["t_mxu_s"] == pytest.approx(3e9 / 1e12)  # 1e9 + 2e9
    assert floors["dominant"].startswith("jit_segment[1")
    whole = obs_perf.attribution_floors(peak_tflops=1.0, hbm_gbps=1.0,
                                        segment_prefix="bench/")
    assert whole["t_mxu_s"] == pytest.approx(3e9 / 1e12)
    assert obs_perf.attribution_floors(
        1.0, 1.0, segment_prefix="nomatch") is None


# ---------------------------------------------------------------------------
# history + gate
# ---------------------------------------------------------------------------

def _hist_record(metric, value, platform="tpu", step_ms=None,
                 verdict="hbm_bound", leg=None, ts=0.0):
    return {"ts": ts, "metric": metric, "value": value, "unit": "img/s",
            "step_ms": step_ms, "mfu": None, "amp_bf16": True,
            "platform": platform, "verdict": verdict,
            "dominant": "conv2d", "leg": leg}


def test_history_append_load_roundtrip(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    rec = {"metric": "m1", "value": 100.0, "unit": "img/s",
           "step_ms": 10.0, "mfu": 0.3, "amp_bf16": True,
           "platform": "tpu",
           "perf": {"verdict": "compute_bound", "dominant": "conv2d"},
           "compile_cache": {"hits": 3, "misses": 1}}
    norm = obs_perf.append_history(rec, path, leg="default", ts=123.0)
    assert norm["verdict"] == "compute_bound" and norm["leg"] == "default"
    assert norm["compile_cache"]["hits"] == 3
    # skip markers (no value) append nothing
    assert obs_perf.append_history({"metric": "m2",
                                    "skipped": "compile-timeout"},
                                   path) is None
    # a torn line must not wedge the loader
    with open(path, "a") as f:
        f.write('{"metric": "m3", "val')
    loaded = obs_perf.load_history(path)
    assert len(loaded) == 1 and loaded[0]["metric"] == "m1"
    assert obs_perf.load_history(str(tmp_path / "absent.jsonl")) == []


def test_gate_passes_within_noise_and_fails_regression():
    base = [_hist_record("m", 1000.0 * n, ts=i)
            for i, n in enumerate([1.0, 0.99, 1.01, 0.985, 1.012])]
    ok = obs_perf.gate_history(base + [_hist_record("m", 995.0)])
    assert ok.ok and ok.checked[0]["metric"] == "m"
    bad = obs_perf.gate_history(
        base + [_hist_record("m", 800.0, leg="default-b128")])
    assert not bad.ok
    f = bad.failures[0]
    assert f["kind"] == "throughput" and f["metric"] == "m"
    assert f["verdict"] == "hbm_bound" and f["leg"] == "default-b128"
    text = obs_perf.format_gate(bad)
    assert "FAIL m" in text and "hbm_bound" in text


def test_gate_median_absorbs_an_outlier_baseline():
    # one crazy-low historical run must not drag the baseline down
    vals = [1000, 400, 1005, 995, 1010]
    base = [_hist_record("m", v, ts=i) for i, v in enumerate(vals)]
    res = obs_perf.gate_history(base + [_hist_record("m", 700.0)])
    assert not res.ok  # median ~1000, 700 is a real regression


def test_gate_step_ms_regression_caught_independently():
    base = [_hist_record("m", 1000.0, step_ms=10.0, ts=i)
            for i in range(5)]
    res = obs_perf.gate_history(
        base + [_hist_record("m", 1000.0, step_ms=13.0)])
    assert not res.ok and res.failures[0]["kind"] == "step_ms"


def test_gate_platform_hard_fails():
    base = [_hist_record("m", 1000.0, ts=i) for i in range(3)]
    # stale re-emit as newest: hard fail even though the value is fine
    res = obs_perf.gate_history(
        base + [_hist_record("m", 1000.0, platform="tpu-stale")])
    assert not res.ok and res.failures[0]["kind"] == "platform"
    assert "stale" in res.failures[0]["why"]
    # allow_stale downgrades to a skip
    res = obs_perf.gate_history(
        base + [_hist_record("m", 1000.0, platform="tpu-stale")],
        allow_stale=True)
    assert res.ok and res.skipped
    # CPU fallback likewise
    res = obs_perf.gate_history(
        base + [_hist_record("m", 1000.0, platform="cpu-fallback")])
    assert not res.ok and res.failures[0]["kind"] == "platform"
    # candidate on a platform with no matching history: mismatch
    res = obs_perf.gate_history(
        base + [_hist_record("m", 1000.0, platform="cpu")])
    assert not res.ok and "mismatch" in res.failures[0]["why"]


def test_gate_tolerances_and_filters():
    base = [_hist_record("m", 1000.0, ts=i) for i in range(4)]
    cand = _hist_record("m", 900.0)   # -10%
    assert not obs_perf.gate_history(base + [cand]).ok
    # loosened per-metric tolerance lets it through
    assert obs_perf.gate_history(
        base + [cand], metric_tolerance={"m": 0.15}).ok
    # metric filter skips everything else
    res = obs_perf.gate_history(base + [cand], metrics={"other"})
    assert res.ok and not res.checked
    # a single record has no baseline: skip, not fail
    res = obs_perf.gate_history([_hist_record("solo", 10.0)])
    assert res.ok and res.skipped[0]["metric"] == "solo"


def test_perf_cli_gate_exit_codes(tmp_path):
    from paddle_tpu.tools import perf_cli

    path = str(tmp_path / "h.jsonl")
    for i, v in enumerate([1000.0, 1005.0, 995.0, 998.0]):
        obs_perf.append_history(
            {"metric": "m", "value": v, "unit": "img/s",
             "platform": "tpu"}, path, ts=float(i))
    assert perf_cli.main(["gate", "--history", path]) == 0
    obs_perf.append_history(
        {"metric": "m", "value": 600.0, "unit": "img/s",
         "platform": "tpu"}, path, ts=99.0)
    assert perf_cli.main(["gate", "--history", path]) == 1
    assert perf_cli.main(["gate", "--history",
                          str(tmp_path / "none.jsonl")]) == 2


# ---------------------------------------------------------------------------
# SLO burn
# ---------------------------------------------------------------------------

def test_slo_tracker_burn_windows():
    from paddle_tpu.serving.metrics import ServingMetrics, SLOTracker

    m = ServingMetrics()
    slo = SLOTracker(m, objective_ms=100.0, target=0.9, model="mdl")
    assert slo.update() == 0.0                   # no traffic yet
    for _ in range(8):
        m.total_seconds.observe(0.01)            # within objective
    for _ in range(2):
        m.total_seconds.observe(5.0)             # violations
    # 20% violating / 10% budget = burn 2x
    assert slo.update() == pytest.approx(2.0, rel=0.05)
    # next window: all good -> burn back to 0
    for _ in range(5):
        m.total_seconds.observe(0.01)
    assert slo.update() == pytest.approx(0.0, abs=1e-9)
    # gauge surfaced in the default registry, labeled by model
    fam = obs_registry.get_registry().gauge(
        "slo_burn_rate", labelnames=("model",))
    assert fam.labels(model="mdl").value == 0.0
    with pytest.raises(ValueError):
        SLOTracker(m, objective_ms=50, target=1.0)
    # objectives beyond the histogram's largest finite bucket are
    # unmeasurable (violations would land in +Inf and read as good)
    with pytest.raises(ValueError):
        SLOTracker(m, objective_ms=60_000)


def test_server_healthz_carries_slo_burn():
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid import io as fluid_io
    from paddle_tpu.serving import (InferenceEngine, EngineConfig,
                                    InferenceServer, ServerConfig)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[4], dtype="float32")
        out = fluid.layers.fc(input=img, size=2)
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    program = fluid_io.prune_program(main, [out])
    engine = InferenceEngine(program, ["img"], [out], scope=scope,
                             config=EngineConfig(batch_buckets=[2]))
    server = InferenceServer(
        engine, ServerConfig(warmup=False, slo_ms=30_000,
                             slo_target=0.99, model_name="m0"))
    server.batcher.start()
    try:
        status, _ = server.handle_infer(
            {"inputs": {"img": np.zeros((1, 4)).tolist()}})
        assert status == 200
        health = server.health_signals()
    finally:
        server.batcher.close()
    assert health["slo"]["objective_ms"] == 30_000
    # generous objective: nothing burned
    assert health["slo_burn_rate"] == 0.0
    # without an SLO config the key stays absent (contract: opt-in)
    server2 = InferenceServer(engine, ServerConfig(warmup=False))
    assert "slo_burn_rate" not in server2.health_signals()


# ---------------------------------------------------------------------------
# jit-path attribution fix (PR 7 leftover)
# ---------------------------------------------------------------------------

def test_attribution_jit_path_lowers_each_segment_once(monkeypatch):
    """FLAGS_xla_cost_attribution on the plain jit path used to pay a
    second, throwaway lower().compile() per segment.  Count actual
    lowerings by counting kernel applications under trace: each
    lowering of a segment runs apply_op once per op."""
    from paddle_tpu.fluid import executor as executor_mod

    main, startup, cost = _tiny_train_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)

    calls = []
    real_apply = executor_mod.apply_op
    monkeypatch.setattr(executor_mod, "apply_op",
                        lambda ctx, od: (calls.append(od.type),
                                         real_apply(ctx, od))[1])
    flags.set_flag("xla_cost_attribution", True)
    try:
        traces0 = obs_tele.jit_trace_count()
        feed = {"x": np.ones((2, 4), np.float32)}
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[cost], scope=scope)
    finally:
        flags.set_flag("xla_cost_attribution", False)
    n_ops = len(main.global_block().desc.ops)
    # ONE lowering total: apply_op ran exactly once per op, not twice
    assert len(calls) == n_ops, (len(calls), n_ops, calls)
    # and exactly one compile was counted for the single jit segment
    assert obs_tele.jit_trace_count() - traces0 == 1
    # the attribution landed (graceful skip allowed only if the
    # runtime exposes no analyses — CPU jax here exposes both)
    snap = obs_tele.snapshot()
    assert any(k.startswith("xla_flops{") for k in snap), \
        [k for k in snap if k.startswith("xla_")]


def test_attribution_artifacts_survive_flag_drop():
    """Segments warmed under force_attribution (serving warmup) must
    keep serving those signatures after the flag drops — no recompile
    on the first real request — while NEW signatures compile through
    the normal jit path."""
    from paddle_tpu.obs import health as obs_health

    main, startup, cost = _tiny_train_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    feed2 = {"x": np.ones((2, 4), np.float32)}
    with obs_health.force_attribution():
        exe.run(main, feed=feed2, fetch_list=[cost], scope=scope)
    traces_warm = obs_tele.jit_trace_count()
    # same signature, flag off: served from the attribution artifact
    out1 = exe.run(main, feed=feed2, fetch_list=[cost], scope=scope)
    assert obs_tele.jit_trace_count() == traces_warm
    # new batch size, flag off: a fresh compile through the jit path
    exe.run(main, feed={"x": np.ones((5, 4), np.float32)},
            fetch_list=[cost], scope=scope)
    assert obs_tele.jit_trace_count() == traces_warm + 1
    assert np.isfinite(out1[0]).all()


def test_attribution_flag_flip_does_not_stall_warm_signatures(
        monkeypatch):
    """Enabling the flag on a LIVE process must not inline-recompile
    signatures already warm in the jit call cache (a multi-second
    stall per segment mid-training); only fresh builds attribute."""
    from paddle_tpu.fluid import executor as executor_mod

    main, startup, cost = _tiny_train_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    feed = {"x": np.ones((2, 4), np.float32)}
    exe.run(main, feed=feed, fetch_list=[cost], scope=scope)  # warm
    traces_warm = obs_tele.jit_trace_count()

    calls = []
    real_apply = executor_mod.apply_op
    monkeypatch.setattr(executor_mod, "apply_op",
                        lambda ctx, od: (calls.append(od.type),
                                         real_apply(ctx, od))[1])
    flags.set_flag("xla_cost_attribution", True)
    try:
        exe.run(main, feed=feed, fetch_list=[cost], scope=scope)
    finally:
        flags.set_flag("xla_cost_attribution", False)
    # no lowering happened (no apply_op under trace), no compile
    assert not calls, calls
    assert obs_tele.jit_trace_count() == traces_warm


def test_attribution_numerics_match_plain_path():
    """The attribution AOT dispatch must be numerically identical to
    the plain jit path (same program, same seed, same feeds)."""
    def run(attr):
        main, startup, cost = _tiny_train_program()
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
        flags.set_flag("xla_cost_attribution", attr)
        try:
            outs = []
            for _ in range(3):
                outs.append(exe.run(
                    main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[cost], scope=scope)[0])
        finally:
            flags.set_flag("xla_cost_attribution", False)
        return np.concatenate(outs)

    np.testing.assert_array_equal(run(False), run(True))