"""paddle_tpu.tune: search space, static ranking (determinism +
S-code rejection), calibration fit, and the history hygiene the fit
depends on (docs/TUNING.md)."""

import json
import os
import subprocess
import sys

import pytest

from paddle_tpu.obs import perf as obs_perf
from paddle_tpu.tune import fit as tune_fit
from paddle_tpu.tune import models as tune_models
from paddle_tpu.tune import rank as tune_rank
from paddle_tpu.tune.rank import Calibration
from paddle_tpu.tune.space import (Candidate, SearchSpace,
                                   mesh_shapes_for)


# ---------------------------------------------------------------------------
# search space
# ---------------------------------------------------------------------------

def test_mesh_shapes_for_enumerates_factorizations():
    assert mesh_shapes_for(8) == [
        "dp=8,mp=1", "dp=4,mp=2", "dp=2,mp=4", "dp=1,mp=8"]
    assert mesh_shapes_for(1) == ["dp=1,mp=1"]
    # three axes: every ordered factorization, leading axis descending
    specs = mesh_shapes_for(4, axes=("dp", "mp", "sp"))
    assert specs[0] == "dp=4,mp=1,sp=1"
    assert "dp=2,mp=2,sp=1" in specs and "dp=1,mp=2,sp=2" in specs
    assert len(specs) == len(set(specs))


def test_space_constraints_never_enumerate_invalid_points():
    space = SearchSpace(8, batches=[12, 32], micro_batches=[1, 2],
                        pipelines=["none"])
    points = space.points()
    for cand in points:
        assert cand.batch % cand.dp == 0, cand
        assert (cand.batch // cand.dp) % cand.micro_batches == 0, cand
    # batch 12 cannot split over dp=8; per-device batch 12/dp=4 -> 3
    # cannot split over micro=2
    assert any("not divisible by dp" in r
               for r in space.skipped.values())
    assert any("micro_batches" in r for r in space.skipped.values())
    # deterministic enumeration: same space, same order
    again = SearchSpace(8, batches=[12, 32], micro_batches=[1, 2],
                        pipelines=["none"]).points()
    assert [c.tag() for c in points] == [c.tag() for c in again]


def test_space_rejects_invalid_knobs_at_construction():
    with pytest.raises(ValueError, match="axis product"):
        SearchSpace(8, meshes=["dp=4,mp=1"])
    with pytest.raises(ValueError, match="unknown pass"):
        SearchSpace(8, pipelines=["dce,not_a_pass"])
    with pytest.raises(ValueError):
        SearchSpace(8, meshes=["dq=8"])  # unknown axis name


def test_candidate_identity_and_bench_env():
    cand = Candidate("dp=4,mp=2", "default", batch=64, micro_batches=2)
    assert cand.n_devices == 8 and cand.dp == 4
    assert cand.per_device_batch == 16
    assert cand.tag() == "dp4.mp2-b64-mb2-dce,fold,cse,dve"
    cfg = cand.config("lenet5")
    assert cfg["per_device_batch"] == 16
    assert cfg["pass_pipeline"] == "v1:dce,fold,cse,dve"
    env = cand.bench_env("lenet5")
    assert env["BENCH_BATCH"] == "16" and env["BENCH_MESH"] == "dp=4,mp=2"
    assert env["BENCH_LEG"] == "ptune:" + cand.tag()
    # "none" and "" are the same pipeline, so one candidate — not two
    assert Candidate("dp=4,mp=2", "none", 64, 2) == \
        Candidate("dp=4,mp=2", "", 64, 2)


def test_space_pass_knob_dimensions():
    """fusion_caps/remat_strides cross only with pipelines carrying a
    bare fuse/auto_remat pass; invalid combos are skipped AT
    enumeration (never candidates), and the knob folds into the
    candidate's pipeline spec + pipeline id."""
    space = SearchSpace(
        4, meshes=["dp=4"], batches=[64], micro_batches=[1],
        pipelines=["none", "default+fuse+auto_remat"],
        fusion_caps=[0, 4], remat_strides=[0, 4])
    points = space.points()
    specs = [c.pipeline for c in points]
    assert "dce,fold,cse,dve,fuse:cap=4,auto_remat:stride=4" in specs
    assert "dce,fold,cse,dve,fuse,auto_remat" in specs
    # "none" never grows knobs; the knobbed combos with it are skipped
    assert "" in specs
    assert any("needs the fuse pass" in r for r in space.skipped.values())
    assert any("needs the auto_remat pass" in r
               for r in space.skipped.values())
    ids = {c.pipeline_id() for c in points}
    assert len(ids) == len(points)  # knob settings never alias
    # deterministic enumeration with knobs
    again = SearchSpace(
        4, meshes=["dp=4"], batches=[64], micro_batches=[1],
        pipelines=["none", "default+fuse+auto_remat"],
        fusion_caps=[0, 4], remat_strides=[0, 4]).points()
    assert [c.tag() for c in points] == [c.tag() for c in again]


def test_space_dedupes_default_valued_knob():
    """A knob spelled at its pass default ("auto_remat:stride=8" — 8
    IS the default) normalizes to the bare pass: the space must rank
    that pipeline ONCE, skipping the duplicate with a reason."""
    space = SearchSpace(
        4, meshes=["dp=4"], batches=[64], micro_batches=[1],
        pipelines=["default+auto_remat"], remat_strides=[0, 8])
    points = space.points()
    assert [c.pipeline for c in points] == \
        ["dce,fold,cse,dve,auto_remat"]
    assert any("duplicate point" in r for r in space.skipped.values())


def test_space_rejects_invalid_pass_knobs_at_construction():
    with pytest.raises(ValueError, match="fusion_caps"):
        SearchSpace(4, fusion_caps=[1])
    with pytest.raises(ValueError, match="remat_strides"):
        SearchSpace(4, remat_strides=[-1])
    # a knobbed pipeline spec with a bad knob value dies at
    # construction too (PassManager validates)
    with pytest.raises(ValueError, match="cap"):
        SearchSpace(4, pipelines=["default+fuse:cap=1"])


def test_space_skips_double_pinned_knob():
    space = SearchSpace(
        4, meshes=["dp=4"], batches=[64], micro_batches=[1],
        pipelines=["fuse:cap=2"], fusion_caps=[0, 4])
    points = space.points()
    assert [c.pipeline for c in points] == ["fuse:cap=2"]
    assert any("already pins" in r for r in space.skipped.values())


def test_space_knob_fold_preserves_repeated_other_passes():
    """Regression: the fold must rewrite the token LIST, not a
    name-keyed dict — a pipeline repeating some OTHER pass (dce twice)
    must keep both occurrences in the knobbed variant, or the knob A/B
    silently compares two different pipelines."""
    space = SearchSpace(
        4, meshes=["dp=4"], batches=[64], micro_batches=[1],
        pipelines=["dce,fuse,dce"], fusion_caps=[0, 4])
    specs = [c.pipeline for c in space.points()]
    assert specs == ["dce,fuse,dce", "dce,fuse:cap=4,dce"]


def test_space_skips_repeated_target_pass_knob_fold():
    """Folding a knob into a pipeline that repeats the TARGET pass is
    ambiguous: skipped with a reason, never a candidate."""
    space = SearchSpace(
        4, meshes=["dp=4"], batches=[64], micro_batches=[1],
        pipelines=["fuse,dce,fuse"], fusion_caps=[0, 4])
    specs = [c.pipeline for c in space.points()]
    assert specs == ["fuse,dce,fuse"]
    assert any("repeats the fuse pass" in r
               for r in space.skipped.values())


# ---------------------------------------------------------------------------
# static ranking
# ---------------------------------------------------------------------------

def _small_plan(hbm_gb=16, extra=(), meshes=("dp=8,mp=1", "dp=2,mp=4"),
                micro=(1, 2), calibration=None):
    space = SearchSpace(8, meshes=list(meshes), batches=[32],
                        micro_batches=list(micro), pipelines=["none"])
    return tune_rank.rank(
        tune_models.builder("lenet5"), space.points() + list(extra),
        8, model="lenet5", hbm_gb=hbm_gb, calibration=calibration,
        space_dict=space.to_dict(), skipped=space.skipped)


def test_rank_entries_carry_prices():
    plan = _small_plan()
    assert plan.ranked and not plan.rejected
    for e in plan.ranked:
        assert e.predicted_step_s > 0
        assert e.peak_hbm_bytes > 0
        assert set(e.terms) == {"compute_s", "comm_s", "overhead_s"}
        d = e.to_dict("lenet5")
        assert d["predicted_step_ms"] > 0
        assert "comm_wire_bytes" in d and "peak_hbm_bytes" in d
        assert d["bench_env"]["BENCH_LEG"] == "ptune:" + d["tag"]
    # ascending predicted step time
    steps = [e.predicted_step_s for e in plan.ranked]
    assert steps == sorted(steps)


def test_rank_rejects_injected_s002_mesh():
    # 36 % dp=8 != 0: the analyzer's concrete-feed divisibility error
    bad = Candidate("dp=8,mp=1", "", batch=36, micro_batches=1)
    plan = _small_plan(extra=[bad])
    assert bad.tag() not in [e.candidate.tag() for e in plan.ranked]
    rej = {r.candidate.tag(): r for r in plan.rejected}
    assert rej[bad.tag()].code == "S002", rej


def test_rank_rejects_s005_over_hbm_citing_bytes():
    plan = _small_plan(hbm_gb=1e-6)
    assert not plan.ranked and plan.rejected
    for r in plan.rejected:
        assert r.code == "S005"
        assert r.peak_hbm_bytes and r.peak_hbm_bytes > 0
        # the message cites the per-device component bytes + budget
        assert "params" in r.message and "activation peak" in r.message
        assert "exceeds" in r.message and "budget" in r.message
        assert r.to_dict()["peak_hbm_bytes"] == r.peak_hbm_bytes


def test_rank_micro_batch_scales_activation_hbm():
    plan = _small_plan(meshes=("dp=8,mp=1",), micro=(1, 2))
    by_mb = {e.candidate.micro_batches: e for e in plan.ranked}
    assert by_mb[2].hbm_breakdown["activation_peak_bytes"] \
        < by_mb[1].hbm_breakdown["activation_peak_bytes"]
    assert by_mb[2].peak_hbm_bytes < by_mb[1].peak_hbm_bytes
    # ...at the price of overhead, not compute
    assert by_mb[2].terms["overhead_s"] > by_mb[1].terms["overhead_s"]
    assert by_mb[2].terms["compute_s"] == by_mb[1].terms["compute_s"]


def test_rank_prices_auto_remat_with_reduced_activation_peak():
    """An auto_remat candidate is analyzed over its PASS-OPTIMIZED
    program, so its S005 pricing uses the post-remat (reduced)
    liveness activation peak — and pays for it in the compute term
    (the recompute FLOPs/bytes are real)."""
    space = SearchSpace(
        8, meshes=["dp=8,mp=1"], batches=[32], micro_batches=[1],
        pipelines=["none", "default+auto_remat:stride=2:budget_gb=0"])
    plan = tune_rank.rank(
        tune_models.builder("lenet5"), space.points(), 8,
        model="lenet5", hbm_gb=16, space_dict=space.to_dict(),
        skipped=space.skipped)
    assert len(plan.ranked) == 2 and not plan.rejected
    by_pipe = {e.candidate.pipeline_label: e for e in plan.ranked}
    remat = by_pipe["dce,fold,cse,dve,auto_remat:budget_gb=0.0:stride=2"]
    raw = by_pipe["none"]
    assert remat.hbm_breakdown["activation_peak_bytes"] \
        < raw.hbm_breakdown["activation_peak_bytes"]
    assert remat.peak_hbm_bytes < raw.peak_hbm_bytes
    assert remat.terms["compute_s"] > raw.terms["compute_s"]


def test_rank_mesh_product_must_match_chips():
    off = Candidate("dp=2,mp=2", "", batch=32, micro_batches=1)
    plan = _small_plan(extra=[off])
    rej = {r.candidate.tag(): r for r in plan.rejected}
    assert rej[off.tag()].code == "MESH"


GOLDEN_ARGS = ["plan", "--model", "lenet5", "--chips", "8",
               "--hbm-gb", "16", "--batches", "32",
               "--micro-batches", "1,2", "--pipelines", "none,default",
               "--json"]


def test_rank_golden_snapshot_byte_identical_across_processes():
    """Determinism is the contract resumeFrom-style reproducibility
    rests on: two FRESH processes must emit byte-identical plans."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="")
    outs = []
    for _ in range(2):
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.tune_cli"]
            + GOLDEN_ARGS, cwd=repo, env=env, capture_output=True,
            text=True, timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        outs.append(proc.stdout)
    assert outs[0] == outs[1]
    plan = json.loads(outs[0])
    assert plan["ranked"] and not plan["rejected"]
    # S001–S005-erroring meshes never appear ranked: every entry
    # re-parses into a candidate whose config is self-consistent
    for e in plan["ranked"]:
        assert e["config"]["batch"] % e["config"]["per_device_batch"] \
            == 0


# ---------------------------------------------------------------------------
# calibration + fit
# ---------------------------------------------------------------------------

def test_calibration_roundtrip_and_rank_application(tmp_path):
    cal = Calibration(coef={"compute": 2.0, "overhead": 3.0},
                      bias_s=0.001, n=4, model="lenet5",
                      error_before=0.5, error_after=0.05)
    path = str(tmp_path / "cal.json")
    cal.save(path)
    loaded = Calibration.load(path)
    assert loaded.to_dict() == cal.to_dict()
    assert not loaded.is_identity

    base = _small_plan(meshes=("dp=8,mp=1",), micro=(1,))
    calibrated = _small_plan(meshes=("dp=8,mp=1",), micro=(1,),
                             calibration=loaded)
    tag = base.ranked[0].candidate.tag()
    assert calibrated.entry(tag).predicted_step_s \
        != base.entry(tag).predicted_step_s
    assert calibrated.to_dict()["calibration"]["coef"]["compute"] == 2.0
    with pytest.raises(ValueError, match="unknown calibration term"):
        Calibration(coef={"wires": 2.0})


def _history_record(tag, step_ms, platform="cpu"):
    return {"leg": "ptune:" + tag, "step_ms": step_ms,
            "platform": platform, "metric": "m", "value": 1.0}


def test_fit_joins_history_and_error_decreases():
    plan = _small_plan(meshes=("dp=8,mp=1",), micro=(1, 2))
    # simulate measurements 50x slower than the floor predicts (a CPU
    # measuring a TPU-priced plan), plus rows fit must ignore: a
    # stale re-emit, a foreign leg, and an unknown tag
    records = []
    for e in plan.ranked:
        t = e.candidate.tag()
        meas = (e.terms["compute_s"] * 8 + e.terms["overhead_s"]) * 50
        records.append(_history_record(t, meas * 1e3))
    records.append(_history_record(plan.ranked[0].candidate.tag(),
                                   999.0, platform="tpu-stale"))
    records.append({"leg": "default-b128", "step_ms": 51.8,
                    "platform": "tpu"})
    records.append(_history_record("dp8.mp1-b99-mb1-none", 1.0))
    pairs = tune_fit.join_history(plan, records)
    assert len(pairs) == len(plan.ranked)
    cal = tune_fit.fit_calibration(pairs, model="lenet5")
    assert cal.n == len(pairs)
    assert cal.error_before > cal.error_after
    # the synthetic data is an exact linear model: the fit nails it
    assert cal.error_after < 0.01
    report = tune_fit.format_fit_report(cal, pairs)
    assert "median relative error" in report

    # the same join works from the serialized plan JSON (the artifact
    # `ptune fit --plan` loads)
    plan_dict = json.loads(plan.to_json())
    pairs2 = tune_fit.join_history(plan_dict, records)
    assert sorted(p["tag"] for p in pairs2) == \
        sorted(p["tag"] for p in pairs)


def test_fit_degenerate_inputs():
    plan = _small_plan(meshes=("dp=8,mp=1",), micro=(1,))
    # no measurements: the prior comes back unchanged
    ident = tune_fit.fit_calibration([], model="lenet5")
    assert ident.is_identity
    # one measurement: scalar fallback still reduces the error
    e = plan.ranked[0]
    meas = (e.terms["compute_s"] * 8 + e.terms["overhead_s"]) * 50
    pairs = tune_fit.join_history(
        plan, [_history_record(e.candidate.tag(), meas * 1e3)])
    cal = tune_fit.fit_calibration(pairs)
    assert cal.n == 1 and cal.error_after <= cal.error_before


# ---------------------------------------------------------------------------
# history hygiene (the prune-stale satellite + config blob)
# ---------------------------------------------------------------------------

def test_prune_stale_history_dry_run_then_apply(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    rows = [
        {"metric": "a", "value": 1, "platform": "tpu", "step_ms": 5},
        {"metric": "b", "value": 2, "platform": "tpu-stale"},
        {"metric": "c", "value": 3, "platform": "cpu-fallback"},
        {"metric": "d", "value": 4, "platform": ""},
        {"metric": "e", "value": 5, "platform": "cpu"},
    ]
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
        f.write("torn line not json\n")
    # dry run reports but does not touch the file
    before = open(path).read()
    kept, dropped = obs_perf.prune_stale_history(path)
    assert kept == 3 and len(dropped) == 3  # a, e + the torn line
    assert {d["metric"] for d in dropped} == {"b", "c", "d"}
    assert open(path).read() == before
    # apply rewrites atomically, preserving the unparsable line
    kept, dropped = obs_perf.prune_stale_history(path, apply=True)
    lines = open(path).read().splitlines()
    assert len(lines) == 3 and "torn line not json" in lines
    metrics = [json.loads(l)["metric"] for l in lines
               if l.startswith("{")]
    assert metrics == ["a", "e"]
    # idempotent
    kept, dropped = obs_perf.prune_stale_history(path, apply=True)
    assert not dropped
    # missing file: no crash
    assert obs_perf.prune_stale_history(str(tmp_path / "nope")) \
        == (0, [])


def test_normalize_record_carries_config_blob():
    cfg = {"model": "lenet5", "mesh": "dp=8,mp=1", "batch": 4,
           "micro_batches": 2, "pass_pipeline": "v1:dce"}
    rec = {"metric": "m", "value": 1.0, "unit": "img/s",
           "step_ms": 9.0, "platform": "cpu", "config": cfg}
    norm = obs_perf.normalize_record(rec, leg="ptune:x")
    assert norm["config"] == cfg and norm["leg"] == "ptune:x"
    # records without one stay unchanged in shape
    rec.pop("config")
    assert "config" not in obs_perf.normalize_record(rec)


def test_ptune_cli_plan_in_process(tmp_path, capsys):
    from paddle_tpu.tools import tune_cli

    out = str(tmp_path / "plan.json")
    # --f32: the CLI's bf16 default flips process-global AMP state,
    # which must not leak into later tests
    rc = tune_cli.main(["plan", "--model", "lenet5", "--chips", "4",
                        "--meshes", "dp=4,mp=1", "--batches", "32",
                        "--micro-batches", "1", "--pipelines", "none",
                        "--hbm-gb", "16", "--f32", "--out", out])
    assert rc == 0
    text = capsys.readouterr().out
    assert "ranked launch plan" in text and "dp4.mp1-b32-mb1-none" \
        in text
    plan = json.load(open(out))
    assert plan["model"] == "lenet5" and len(plan["ranked"]) == 1
