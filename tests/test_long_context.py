"""Long-context stack: flash attention kernel, ring attention,
Ulysses all-to-all, and the sequence-parallel transformer on a virtual
8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.kernels.flash_attention import (flash_attention,
                                                reference_attention)
from paddle_tpu.parallel.ring import (ring_attention, ulysses_attention,
                                      sp_shard_map)
from paddle_tpu.models.transformer import (init_transformer,
                                           transformer_forward,
                                           transformer_loss,
                                           transformer_param_specs)


def _qkv(B=2, H=4, T=64, D=16, seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.randn(B, H, T, D).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_dense(causal):
    q, k, v = _qkv()
    o = flash_attention(q, k, v, None, causal, 16, 16, 0)
    ref = reference_attention(q, k, v, None, causal)
    np.testing.assert_allclose(o, ref, atol=2e-5)


def test_flash_attention_grads_match_dense():
    q, k, v = _qkv()

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.sin(fn(q, k, v)))

    g1 = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, None, True, 16, 16, 0)), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(lambda q, k, v: reference_attention(
        q, k, v, None, True)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-5)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sequence_parallel_attention_matches_dense(impl, causal):
    mesh = Mesh(np.array(jax.devices()[:4]), axis_names=("sp",))
    q, k, v = _qkv()
    ref = reference_attention(q, k, v, None, causal)
    if impl == "ring":
        fn = sp_shard_map(lambda q, k, v: ring_attention(
            q, k, v, "sp", None, causal), mesh)
    else:
        fn = sp_shard_map(lambda q, k, v: ulysses_attention(
            q, k, v, "sp", None, causal, use_flash=False), mesh)
    o = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(o, ref, atol=3e-5)


def test_ring_attention_grads():
    mesh = Mesh(np.array(jax.devices()[:4]), axis_names=("sp",))
    q, k, v = _qkv()
    ring = sp_shard_map(lambda q, k, v: ring_attention(
        q, k, v, "sp", None, True), mesh)

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.sin(fn(q, k, v)))

    g1 = jax.grad(loss(jax.jit(ring)), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(lambda q, k, v: reference_attention(
        q, k, v, None, True)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-5)


def test_ulysses_attention_grads_multi_axis_mesh():
    """Ulysses grads vs dense, on a dp x sp mesh (regression: the
    untiled all_to_all form produced a mis-transposed cotangent under
    multi-axis meshes)."""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                axis_names=("dp", "sp"))
    q, k, v = _qkv()
    uly = sp_shard_map(lambda q, k, v: ulysses_attention(
        q, k, v, "sp", None, True, use_flash=False), mesh)

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.sin(fn(q, k, v)))

    g1 = jax.grad(loss(jax.jit(uly)), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(lambda q, k, v: reference_attention(
        q, k, v, None, True)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-5)


def test_transformer_ring_matches_dense_on_mesh():
    """Full model parity: dense attention vs ring attention under a
    dp x sp mesh, same params/tokens."""
    devices = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devices, axis_names=("dp", "sp"))
    params = init_transformer(0, vocab_size=97, n_layer=2, n_head=4,
                              d_model=64, max_len=128)
    rs = np.random.RandomState(1)
    tokens = jnp.asarray(rs.randint(0, 97, size=(4, 64)), jnp.int32)

    dense = transformer_forward(params, tokens, attn_impl="dense")
    with mesh:
        ring = jax.jit(lambda p, t: transformer_forward(
            p, t, attn_impl="ring", mesh=mesh))(params, tokens)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                               atol=1e-4)


def test_transformer_sharded_train_step():
    """One train step over dp x mp x sp with Megatron-style tp specs;
    loss finite and params update."""
    devices = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devices, axis_names=("dp", "mp", "sp"))
    params = init_transformer(0, vocab_size=64, n_layer=1, n_head=4,
                              d_model=32, max_len=64)
    meta = params.pop("_meta")
    specs = transformer_param_specs({**params, "_meta": meta})
    sharded = {
        n: jax.device_put(v, NamedSharding(mesh, specs[n]))
        for n, v in params.items()}
    sharded["_meta"] = meta

    rs = np.random.RandomState(2)
    tokens = jnp.asarray(rs.randint(0, 64, size=(4, 32)), jnp.int32)
    targets = jnp.asarray(rs.randint(0, 64, size=(4, 32)), jnp.int32)

    def step(p, tok, tgt):
        meta_v = p["_meta"]
        arrs = {n: v for n, v in p.items() if n != "_meta"}

        def loss_fn(arrs):
            return transformer_loss({**arrs, "_meta": meta_v}, tok, tgt,
                                    attn_impl="ring", mesh=mesh)

        loss, grads = jax.value_and_grad(loss_fn)(arrs)
        new = {n: v - 0.1 * grads[n] for n, v in arrs.items()}
        new["_meta"] = meta_v
        return loss, new

    with mesh:
        loss1, sharded = jax.jit(step, static_argnums=())(
            sharded, tokens, targets)
        loss2, sharded = jax.jit(step)(sharded, tokens, targets)
    assert np.isfinite(float(loss1)) and np.isfinite(float(loss2))
    assert float(loss2) < float(loss1)
