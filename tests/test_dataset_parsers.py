"""Real dataset parsers over checked-in fixture files (reference:
python/paddle/v2/dataset/tests/*_test.py — but offline: tiny fixtures
instead of network downloads; the synthetic fallback keeps zero-egress
CI working and is itself checked here)."""

import os

import numpy as np

from paddle_tpu.dataset import cifar, conll05, imdb, mnist

FX = os.path.join(os.path.dirname(__file__), "fixtures")


def test_mnist_idx_parsing():
    r = mnist.train(
        image_path=os.path.join(FX, "mnist_images.idx3.gz"),
        label_path=os.path.join(FX, "mnist_labels.idx1.gz"))
    samples = list(r())
    assert len(samples) == 5
    img, label = samples[0]
    assert img.shape == (784,) and img.dtype == np.float32
    assert -1.0 <= img.min() and img.max() <= 1.0
    assert [l for _, l in samples] == [3, 1, 4, 1, 5]


def test_mnist_rejects_bad_magic(tmp_path):
    import gzip
    import pytest

    bad = tmp_path / "bad.idx3.gz"
    with gzip.open(bad, "wb") as f:
        f.write(b"\x00" * 32)
    with pytest.raises(ValueError, match="magic"):
        mnist.parse_idx_images(str(bad))


def test_cifar_pickle_tar_parsing():
    tar = os.path.join(FX, "cifar10_tiny.tar.gz")
    train = list(cifar.train10(tar_path=tar)())
    test = list(cifar.test10(tar_path=tar)())
    assert len(train) == 6 and len(test) == 2  # 2 batches x 3, 1 x 2
    img, label = train[0]
    assert img.shape == (3072,) and img.dtype == np.float32
    assert 0.0 <= img.min() and img.max() <= 1.0
    assert [l for _, l in train] == [0, 5, 9, 0, 5, 9]
    assert [l for _, l in test] == [2, 7]


def test_imdb_tokenize_and_dict():
    tar = os.path.join(FX, "aclImdb_tiny.tar.gz")
    docs = list(imdb.tokenize(tar, imdb.TRAIN_POS_PATTERN))
    assert len(docs) == 2
    assert "wonderful" in docs[0] and "," not in " ".join(docs[0])

    word_idx = imdb.build_dict(
        tar, imdb.TRAIN_POS_PATTERN, cutoff=0)
    assert word_idx["wonderful"] == 0  # most frequent gets id 0
    assert "<unk>" in word_idx

    train = list(imdb.train(word_idx=word_idx, tar_path=tar)())
    assert len(train) == 4  # 2 pos + 2 neg
    labels = [l for _, l in train]
    assert labels == [0, 0, 1, 1]  # pos first, then neg
    for ids, _ in train:
        assert all(isinstance(i, int) for i in ids)
        assert max(ids) <= word_idx["<unk>"]


def test_conll05_column_parsing():
    words = os.path.join(FX, "conll05_words.gz")
    props = os.path.join(FX, "conll05_props.gz")
    corpus = list(conll05.parse_corpus(words, props)())
    assert len(corpus) == 2
    sent, verb, bio = corpus[0]
    assert sent == ["The", "cat", "chased", "the", "mouse", "."]
    assert verb == "chase"
    assert bio == ["O", "O", "B-V", "B-A1", "I-A1", "O"]
    sent2, verb2, bio2 = corpus[1]
    assert verb2 == "bark"
    assert bio2 == ["B-A0", "B-V", "B-AM-MNR", "O"]

    word_dict = {w: i for i, w in enumerate(
        sorted({w for s, _, _ in corpus for w in s} | {"bos", "eos"}))}
    verb_dict = {"chase": 0, "bark": 1}
    label_dict = {l: i for i, l in enumerate(
        sorted({t for _, _, b in corpus for t in b}))}
    reader = conll05.reader_creator(
        conll05.parse_corpus(words, props), word_dict, verb_dict,
        label_dict)
    samples = list(reader())
    assert len(samples) == 2
    slots = samples[0]
    assert len(slots) == 9
    n = len(slots[0])
    assert all(len(s) == n for s in slots)
    # mark: 5-token window around the verb (index 2) clipped to bounds
    assert slots[7] == [1, 1, 1, 1, 1, 0]


def test_conll05_no_trailing_blank_and_mismatch(tmp_path):
    import gzip
    import pytest

    words = tmp_path / "w.gz"
    props = tmp_path / "p.gz"
    with gzip.open(words, "wt") as wf, gzip.open(props, "wt") as pf:
        for w, p in (("Dogs", "- (A0*)"), ("bark", "bark (V*)")):
            wf.write(w + "\n")
            pf.write(p + "\n")
        # no trailing blank line
    corpus = list(conll05.parse_corpus(str(words), str(props))())
    assert len(corpus) == 1 and corpus[0][1] == "bark"

    short = tmp_path / "short.gz"
    with gzip.open(short, "wt") as pf:
        pf.write("- (A0*)\n")
    with pytest.raises(ValueError, match="different"):
        list(conll05.parse_corpus(str(words), str(short))())


def test_explicit_missing_paths_raise(tmp_path):
    import pytest

    with pytest.raises(FileNotFoundError):
        cifar.train10(tar_path=str(tmp_path / "nope.tar.gz"))
    with pytest.raises(FileNotFoundError):
        mnist.train(image_path=str(tmp_path / "imgs.gz"))
    with pytest.raises(FileNotFoundError):
        imdb.train(tar_path=str(tmp_path / "nope.tar.gz"))
    with pytest.raises(FileNotFoundError):
        conll05.test(words_path=str(tmp_path / "w.gz"),
                     props_path=str(tmp_path / "p.gz"))


def test_conll05_explicit_paths_derive_dicts():
    """Real corpus + no dicts: dictionaries come from the corpus, and
    get_embedding sizes to the dict."""
    reader = conll05.test(
        words_path=os.path.join(FX, "conll05_words.gz"),
        props_path=os.path.join(FX, "conll05_props.gz"))
    samples = list(reader())
    assert len(samples) == 2 and len(samples[0]) == 9
    corpus = conll05.parse_corpus(
        os.path.join(FX, "conll05_words.gz"),
        os.path.join(FX, "conll05_props.gz"))
    wd, vd, ld = conll05.build_dicts_from_corpus(corpus)
    emb = conll05.get_embedding(wd)
    assert emb.shape == (len(wd), 32)


def test_synthetic_fallback_still_works(monkeypatch, tmp_path):
    # no paths, no network, and an empty isolated cache ->
    # deterministic synthetic readers (a developer's populated
    # ~/.cache must not change unit-test behavior)
    from paddle_tpu.dataset import common

    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    s = list(mnist.train()())
    assert len(s) == 2048 and s[0][0].shape == (784,)
    s = list(cifar.train10()())
    assert len(s) == 1024 and s[0][0].shape == (3072,)
    s = list(imdb.train()())
    assert len(s) == 512
    s = list(conll05.test()())
    assert len(s) == 256 and len(s[0]) == 9
