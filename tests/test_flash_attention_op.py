"""The flash_attention framework op (ops/attention.py).

The pallas online-softmax kernel (interpret mode on these CPU tests)
surfaces through the op registry and `fluid.layers.flash_attention`;
the reference's closest surface builds attention from composed ops
(python/paddle/v2/fluid/nets.py:338).  Checks: OpTest output + grad
against the dense reference, the fluid transformer program training
through ParallelTrainer on the 8-device mesh with ring sp engaged,
and ring-vs-dense gradient parity through the Program stack.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu.fluid as fluid
from paddle_tpu.kernels.flash_attention import reference_attention

from op_test import OpTest

RS = np.random.RandomState(5)


def _dense_ref(q, k, v, num_heads, causal):
    b, t, d = q.shape

    def heads(x):
        return x.reshape(b, t, num_heads, d // num_heads) \
                .transpose(0, 2, 1, 3)

    o = reference_attention(jnp.asarray(heads(q)), jnp.asarray(heads(k)),
                            jnp.asarray(heads(v)), None, causal)
    return np.asarray(o).transpose(0, 2, 1, 3).reshape(b, t, d)


class TestFlashAttentionOp(OpTest):
    op_type = "flash_attention"

    def test_causal_multihead(self):
        q = RS.randn(2, 8, 16).astype("float32")
        k = RS.randn(2, 8, 16).astype("float32")
        v = RS.randn(2, 8, 16).astype("float32")
        self.inputs = {"Q": q, "K": k, "V": v}
        self.attrs = {"num_heads": 4, "causal": True}
        self.outputs = {"Out": _dense_ref(q, k, v, 4, True)}
        self.check_output(atol=1e-5)
        # the f32 central-difference probe is noisy through softmax
        # (analytic grads match jax.grad of the dense reference to
        # 1e-7 — see the exact check below); loose numeric bound
        self.check_grad(["Q", "K", "V"], "Out", max_relative_error=0.15)

    def test_full_single_head(self):
        # mild scale keeps the softmax well-conditioned for the f32
        # central-difference probe (correctness itself is pinned by the
        # exact analytic-vs-jax.grad test below)
        q = (0.5 * RS.randn(2, 6, 8)).astype("float32")
        k = (0.5 * RS.randn(2, 6, 8)).astype("float32")
        v = RS.randn(2, 6, 8).astype("float32")
        self.inputs = {"Q": q, "K": k, "V": v}
        self.attrs = {"num_heads": 1, "causal": False}
        self.outputs = {"Out": _dense_ref(q, k, v, 1, False)}
        self.check_output(atol=1e-5)
        # the f32 central-difference probe is noisy through softmax
        # (analytic grads match jax.grad of the dense reference to
        # 1e-7 — see the exact check below); loose numeric bound
        self.check_grad(["Q", "K", "V"], "Out", max_relative_error=0.15)


def _train_transformer(sp_axis, mesh, feed_specs, steps=3,
                       sp_mode="ring"):
    """Build + train the fluid transformer; returns (losses, qkv-weight
    after training)."""
    from paddle_tpu.models.transformer_program import (
        build_transformer_program, transformer_program_feeds)
    from paddle_tpu.parallel import ParallelTrainer

    fluid.framework.reset_unique_name()
    B, T, V = 4, 16, 64
    main, startup, avg_loss, _ = build_transformer_program(
        B, T, V, n_layer=1, n_head=4, d_model=32, sp_axis=sp_axis,
        sp_mode=sp_mode)
    with fluid.program_guard(main, startup):
        fluid.optimizer.Momentum(learning_rate=0.05,
                                 momentum=0.9).minimize(avg_loss)
    trainer = ParallelTrainer(
        main, startup, ["tokens", "positions", "targets"],
        [avg_loss.name], mesh, feed_specs=feed_specs, seed=0)
    trainer.init()
    losses = []
    for _ in range(steps):
        (l,) = trainer.step(transformer_program_feeds(B, T, V, seed=1))
        losses.append(float(np.asarray(l).reshape(-1)[0]))
    weight = sorted(n for n in trainer.state if n.startswith("fc_"))[0]
    return losses, np.asarray(trainer.state[weight]), trainer


def test_fluid_transformer_ring_sp_on_mesh():
    """The Program-stack transformer trains over dp×sp with ring
    attention, and the ring path computes the same losses/weights as
    the dense flash path on the same mesh (grad parity through
    training)."""
    devs = jax.devices()
    assert len(devs) >= 8, "conftest forces an 8-device CPU mesh"
    mesh = Mesh(np.array(devs[:8]).reshape(4, 2), ("dp", "sp"))
    specs = {"tokens": P("dp", "sp"), "positions": P("dp", "sp"),
             "targets": P("dp", "sp", None)}

    ring_losses, ring_w, trainer = _train_transformer("sp", mesh, specs)
    flat_losses, flat_w, _ = _train_transformer("", mesh, specs)

    assert all(np.isfinite(ring_losses)), ring_losses
    assert ring_losses[-1] < ring_losses[0], ring_losses
    # ring merge is online-softmax in f32: same math, mergewise order
    np.testing.assert_allclose(ring_losses, flat_losses, rtol=2e-5)
    np.testing.assert_allclose(ring_w, flat_w, rtol=2e-4, atol=2e-6)

    # momentum accumulators really drive the update (task: no
    # hand-rolled SGD in the sharded paths)
    vel = [n for n in trainer.state if "velocity" in n]
    assert vel and any(
        np.abs(np.asarray(trainer.state[n])).max() > 0 for n in vel)


def test_fluid_transformer_ulysses_sp_on_mesh():
    """The all-to-all (Ulysses) sequence-parallel mode computes the
    same training as the dense path too (heads trade places with the
    sequence shard; 4 heads / sp=2)."""
    devs = jax.devices()
    mesh = Mesh(np.array(devs[:8]).reshape(4, 2), ("dp", "sp"))
    specs = {"tokens": P("dp", "sp"), "positions": P("dp", "sp"),
             "targets": P("dp", "sp", None)}

    uly_losses, uly_w, _ = _train_transformer("sp", mesh, specs,
                                              sp_mode="ulysses")
    flat_losses, flat_w, _ = _train_transformer("", mesh, specs)

    assert all(np.isfinite(uly_losses)), uly_losses
    np.testing.assert_allclose(uly_losses, flat_losses, rtol=2e-5)
    np.testing.assert_allclose(uly_w, flat_w, rtol=2e-4, atol=2e-6)


def test_flash_attention_op_in_program_grads_vs_reference():
    """Program-stack grads of the op match jax.grad of the dense
    reference implementation."""
    B, T, D, H = 2, 8, 16, 2
    q0 = RS.randn(B, T, D).astype("float32")
    k0 = RS.randn(B, T, D).astype("float32")
    v0 = RS.randn(B, T, D).astype("float32")

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        qp = fluid.layers.create_parameter([B, T, D], "float32")
        kp = fluid.layers.create_parameter([B, T, D], "float32")
        vp = fluid.layers.create_parameter([B, T, D], "float32")
        out = fluid.layers.flash_attention(qp, kp, vp, num_heads=H,
                                           causal=True)
        loss = fluid.layers.mean(x=out)
        grads = fluid.backward.calc_gradient(loss, [qp, kp, vp])

    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid.executor import scope_guard, global_scope

    with scope_guard(Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for var, val in ((qp, q0), (kp, k0), (vp, v0)):
            global_scope().set(var.name, jnp.asarray(val))
        got = exe.run(main, feed={}, fetch_list=grads)

    def heads(x):
        return x.reshape(B, T, H, D // H).transpose(0, 2, 1, 3)

    def ref_loss(q, k, v):
        o = reference_attention(heads(q), heads(k), heads(v), None, True)
        return jnp.mean(o.transpose(0, 2, 1, 3).reshape(B, T, D))

    want = jax.grad(ref_loss, argnums=(0, 1, 2))(
        jnp.asarray(q0), jnp.asarray(k0), jnp.asarray(v0))
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-6)
