"""Forward rematerialization pass (fluid/recompute.py).

The reference snapshot has no recompute machinery; this is the
TPU-native memory/compute trade (jax.checkpoint equivalent at the
Program level).  Checks: bit-level training parity with the unrewritten
program, RNG ops never cloned, and a measured peak-memory drop on a
deep matmul chain.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.recompute import recompute_program
from paddle_tpu.jit import FunctionalProgram, state_from_scope


def _build_mlp(depth=6, width=64, checkpoint_every=2, dropout=False):
    main, startup = fluid.Program(), fluid.Program()
    ckpts = []
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[width], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        t = x
        for i in range(depth):
            t = fluid.layers.fc(input=t, size=width, act="relu")
            if dropout and i == depth // 2:
                t = fluid.layers.dropout(t, dropout_prob=0.3)
            if (i + 1) % checkpoint_every == 0:
                ckpts.append(t)
        logits = fluid.layers.fc(input=t, size=10, act="softmax")
        loss = fluid.layers.mean(
            x=fluid.layers.cross_entropy(input=logits, label=y))
    return main, startup, loss, ckpts


def _train(main, startup, loss, steps=5, seed=0):
    rs = np.random.RandomState(seed)
    feeds = {"x": rs.rand(16, 64).astype("float32"),
             "y": rs.randint(0, 10, (16, 1)).astype("int64")}
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    return [float(exe.run(main, feed=feeds, fetch_list=[loss],
                          scope=scope)[0][0]) for _ in range(steps)]


def test_training_parity_and_rewrite_shape():
    losses = {}
    for use_rcp in (False, True):
        main, startup, loss, ckpts = _build_mlp()
        with fluid.program_guard(main, startup):
            fluid.optimizer.MomentumOptimizer(
                learning_rate=0.1, momentum=0.9).minimize(loss)
        if use_rcp:
            n = recompute_program(main, ckpts)
            assert n > 0
            block = main.global_block()
            types = [op.type for op in block.ops]
            assert "recompute_barrier" in types
            # grad ops read the cloned activations, not the originals
            assert any("@RCP" in name
                       for op in block.ops if op.type.endswith("_grad")
                       for name in op.desc.input_names())
        losses[use_rcp] = _train(main, startup, loss)
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-5)
    assert losses[True][-1] < losses[True][0]


def test_recompute_optimizer_wrapper():
    main, startup, loss, ckpts = _build_mlp(depth=4)
    with fluid.program_guard(main, startup):
        opt = fluid.RecomputeOptimizer(
            fluid.optimizer.SGD(learning_rate=0.1), checkpoints=ckpts)
        opt.minimize(loss)
    assert any(op.type == "recompute_barrier"
               for op in main.global_block().ops)
    losses = _train(main, startup, loss)
    assert losses[-1] < losses[0]


def test_rng_ops_never_cloned():
    main, startup, loss, ckpts = _build_mlp(dropout=True)
    with fluid.program_guard(main, startup):
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    recompute_program(main, ckpts)
    ops = main.global_block().ops
    assert sum(1 for op in ops if op.type == "dropout") == 1
    # and the dropout's outputs were treated as checkpoints: they may
    # pass through a barrier (`...@RCP<k>@IN` — the original, live
    # value), but no op produces a re-drawn clone of them
    drop_outs = {n for op in ops if op.type == "dropout"
                 for n in op.desc.output_names()}
    for op in ops:
        for n in op.desc.output_names():
            for d in drop_outs:
                assert not (n.startswith(d + "@RCP")
                            and not n.endswith("@IN")), n
    losses = _train(main, startup, loss)
    assert losses[-1] < losses[0]


def test_rewrite_reaches_xla():
    """A 12-deep 512-wide matmul chain with checkpoints every 3 layers:
    the lowered StableHLO must carry the recomputed dots behind
    optimization_barriers.  (Whether the backend *honors* them is
    platform policy: XLA:CPU strips the barrier and CSEs the clones
    away — verified jax.checkpoint itself gets undone there too — while
    XLA:TPU schedules them late, which is where the HBM win lands; the
    on-chip A/B lives in the bench suite, scripts/tpu_watch.sh.)"""
    import jax

    stats = {}
    for use_rcp in (False, True):
        main, startup = fluid.Program(), fluid.Program()
        ckpts = []
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[512], dtype="float32")
            t = x
            for i in range(12):
                t = fluid.layers.fc(input=t, size=512, act="relu")
                if (i + 1) % 3 == 0:
                    ckpts.append(t)
            loss = fluid.layers.mean(x=t)
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        if use_rcp:
            assert recompute_program(main, ckpts) > 0
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)
        fp = FunctionalProgram(main, ["x"], [loss.name])
        state = state_from_scope(fp, scope)
        feeds = {"x": np.ones((256, 512), np.float32)}
        hlo = jax.jit(lambda s, f: fp(s, f)).lower(state, feeds).as_text()
        stats[use_rcp] = (hlo.count("dot_general"),
                          hlo.count("optimization_barrier"))
    assert stats[False][1] == 0
    assert stats[True][1] > 0, stats
    # the clones add forward dots on top of the baseline's fwd+bwd set
    assert stats[True][0] > stats[False][0], stats
