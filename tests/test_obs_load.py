"""paddle_tpu.obs.load: traffic mix, arrival schedules, replay,
open-vs-closed-loop latency accounting (the coordinated-omission
asymmetry, demonstrated on a fake stalling target), report math, the
tail/exemplar joins, and the latency blob -> gate round trip.

Tier-1 (CPU, no real server — the loopback/HTTP integration is
`pload --selftest`'s job): schedules must be deterministic under
seed, replay must preserve gaps and batches, open-loop latency must
be measured from the SCHEDULE while closed-loop latency is measured
from the send, and `gate_history(latency_tolerance=)` must regress
same-key/same-mode only."""

import json
import random
import threading
import time

import pytest

from paddle_tpu.obs import load as obs_load
from paddle_tpu.obs import perf as obs_perf
from paddle_tpu.obs.registry import MetricsRegistry


# ---------------------------------------------------------------------------
# traffic mix + schedules
# ---------------------------------------------------------------------------

def test_mix_parse_weights_and_fractions():
    mix = obs_load.TrafficMix.parse("1:6,4:3,8:1")
    assert mix.weights == {1: 6.0, 4: 3.0, 8: 1.0}
    fr = mix.fractions()
    assert abs(sum(fr.values()) - 1.0) < 1e-12
    assert fr[1] == pytest.approx(0.6)
    # bare sizes weigh equally
    assert obs_load.TrafficMix.parse("1,4,8").fractions()[4] == \
        pytest.approx(1 / 3)
    with pytest.raises(ValueError):
        obs_load.TrafficMix.parse("0:1")
    with pytest.raises(ValueError):
        obs_load.TrafficMix({})


def test_mix_sample_matches_weights():
    mix = obs_load.TrafficMix.parse("1:3,4:1")
    rng = random.Random(0)
    draws = [mix.sample(rng) for _ in range(4000)]
    assert set(draws) == {1, 4}
    assert 0.70 < draws.count(1) / len(draws) < 0.80


def test_uniform_schedule_deterministic_spacing():
    sched = obs_load.build_schedule(100.0, n=50, arrival="uniform")
    assert len(sched) == 50 and sched[0][0] == 0.0
    gaps = [b[0] - a[0] for a, b in zip(sched, sched[1:])]
    assert all(abs(g - 0.01) < 1e-9 for g in gaps)
    # same seed -> identical schedule (batches included)
    again = obs_load.build_schedule(100.0, n=50, arrival="uniform")
    assert again == sched


def test_poisson_schedule_mean_gap():
    sched = obs_load.build_schedule(200.0, n=500, arrival="poisson",
                                    seed=1)
    gaps = [b[0] - a[0] for a, b in zip(sched, sched[1:])]
    mean = sum(gaps) / len(gaps)
    assert 1 / 200 * 0.7 < mean < 1 / 200 * 1.3
    assert obs_load.build_schedule(200.0, n=500, arrival="poisson",
                                   seed=1) == sched
    assert obs_load.build_schedule(200.0, n=500, arrival="poisson",
                                   seed=2) != sched


def test_phases_and_ramp_modulate_rate():
    phases = obs_load.parse_phases("5:400,6:100")
    assert phases == [(5.0, 400.0), (6.0, 100.0)]
    assert obs_load.rate_at(0.0, 100.0, phases=phases) == 100.0
    assert obs_load.rate_at(5.5, 100.0, phases=phases) == 400.0
    assert obs_load.rate_at(7.0, 100.0, phases=phases) == 100.0
    # linear ramp-in scales the base rate, floored at 5%
    assert obs_load.rate_at(1.0, 100.0, ramp_s=2.0) == \
        pytest.approx(50.0)
    assert obs_load.rate_at(0.0, 100.0, ramp_s=2.0) == \
        pytest.approx(5.0)
    assert obs_load.rate_at(3.0, 100.0, ramp_s=2.0) == 100.0
    # a burst phase thins the uniform gaps after its start
    sched = obs_load.build_schedule(
        10.0, duration_s=2.0, arrival="uniform",
        phases=[(1.0, 1000.0)])
    early = [t for t, _ in sched if t < 1.0]
    late = [t for t, _ in sched if t >= 1.0]
    assert len(late) > len(early) * 10


def test_schedule_needs_bound_and_valid_arrival():
    with pytest.raises(ValueError):
        obs_load.build_schedule(100.0)
    with pytest.raises(ValueError):
        obs_load.build_schedule(100.0, n=10, arrival="bursty")


# ---------------------------------------------------------------------------
# access-log replay
# ---------------------------------------------------------------------------

def test_access_log_replay_preserves_gaps_and_batches(tmp_path):
    entries = [
        {"t": 100.0, "batch": 2, "status": 200, "request_id": "a",
         "trace_id": "t" * 32, "latency_ms": 3.0, "bucket": 2},
        {"t": 100.5, "batch": 1, "status": 200, "request_id": "b",
         "trace_id": "u" * 32, "latency_ms": 2.0, "bucket": 1},
        {"t": 101.5, "batch": 4, "status": 429, "request_id": "c",
         "trace_id": "v" * 32, "latency_ms": 0.1, "bucket": 4},
    ]
    path = tmp_path / "access.jsonl"
    with open(path, "w") as f:
        f.write("not json, torn append\n")
        for e in entries:
            f.write(json.dumps(e) + "\n")
        f.write("\n")
    loaded = obs_load.load_access_log(str(path))
    assert [e["request_id"] for e in loaded] == ["a", "b", "c"]
    sched = obs_load.replay_schedule(loaded, speed=2.0)
    assert sched == [(0.0, 2), (0.25, 1), (0.75, 4)]
    with pytest.raises(ValueError):
        obs_load.replay_schedule(loaded, speed=0.0)
    # out-of-order logs are sorted by t before gap reconstruction
    loaded_rev = list(reversed(loaded))
    assert obs_load.replay_schedule(sorted(loaded_rev,
                                           key=lambda e: e["t"])) == \
        obs_load.replay_schedule(loaded)


# ---------------------------------------------------------------------------
# open vs closed loop: the omission asymmetry on a fake target
# ---------------------------------------------------------------------------

class _StallingTarget:
    """Fake target: one armed call stalls, everything else is fast.
    No server, no sockets — pure accounting test."""

    def __init__(self, stall_at=3, stall_s=0.2, fast_s=0.001):
        self.stall_at = stall_at
        self.stall_s = stall_s
        self.fast_s = fast_s
        self.calls = 0
        self._lock = threading.Lock()

    def infer(self, payload, ctx, timeout_s=None):
        with self._lock:
            self.calls += 1
            stall = self.calls == self.stall_at
        time.sleep(self.stall_s if stall else self.fast_s)
        return 200, {"request_id": ctx.request_id}, {}


def _payload(batch):
    return {"batch": batch}


def test_open_loop_measures_from_schedule():
    """With one sender, a 200ms stall delays every later scheduled
    arrival; open-loop latency (from the schedule) must show that
    backlog, and `service_ms` (send -> reply) must stay small for the
    non-stalled requests."""
    target = _StallingTarget(stall_at=3, stall_s=0.2)
    sched = [(i * 0.001, 1) for i in range(10)]
    report = obs_load.run_open_loop(
        target, sched, _payload, max_inflight=1,
        registry=MetricsRegistry(), slo_ms=100.0)
    assert report["mode"] == "open" and report["n"] == 10
    # over half the run sat behind the stall: p50 is already inflated
    assert report["percentiles_ms"]["p90_ms"] >= 100.0
    assert report["slo"]["violations"] >= 5
    worst = report["worst"][0]
    assert worst["latency_ms"] >= 150.0
    # the stall is backlog, not per-request service: at most the one
    # stalled call has a big service_ms
    slow_service = [w for w in report["worst"]
                    if w["service_ms"] >= 150.0]
    assert len(slow_service) <= 1


def test_closed_loop_hides_the_same_stall():
    target = _StallingTarget(stall_at=3, stall_s=0.2)
    report = obs_load.run_closed_loop(
        target, _payload, workers=1, n=10, seed=3,
        registry=MetricsRegistry(), slo_ms=100.0)
    assert report["mode"] == "closed" and report["n"] == 10
    # exactly one request observed the stall; the p50 stays clean and
    # only max carries it — the coordinated-omission trap
    assert report["max_ms"] >= 150.0
    assert report["percentiles_ms"]["p50_ms"] < 100.0
    assert report["slo"]["violations"] == 1


class _RetryAfterTarget:
    def __init__(self):
        self.calls = 0

    def infer(self, payload, ctx, timeout_s=None):
        self.calls += 1
        if self.calls == 1:
            return 429, {"error": "full",
                         "request_id": ctx.request_id}, \
                {"Retry-After": "0.01"}
        return 200, {"request_id": ctx.request_id}, {}


def test_closed_loop_honors_retry_after():
    target = _RetryAfterTarget()
    t0 = time.perf_counter()
    report = obs_load.run_closed_loop(
        target, _payload, workers=1, n=3,
        registry=MetricsRegistry())
    assert time.perf_counter() - t0 >= 0.01
    assert report["by_status"] == {"200": 2, "429": 1}
    shed = [w for w in report["worst"] if w["status"] == 429]
    assert shed and shed[0]["retry_after"] == "0.01"


def test_open_loop_latency_histogram_lands_in_registry():
    reg = MetricsRegistry()
    target = _StallingTarget(stall_at=99, stall_s=0.0, fast_s=0.0)
    sched = [(0.0, 1), (0.0, 2), (0.0, 2)]
    obs_load.run_open_loop(target, sched, _payload, max_inflight=2,
                           registry=reg)
    text = reg.render_text()
    assert 'load_latency_seconds_count{bucket="b2",status="200"} 2' \
        in text
    assert "load_offered_rps" in text and "load_inflight 0" in text


# ---------------------------------------------------------------------------
# report math
# ---------------------------------------------------------------------------

def _samples(lats, batch=1, status=200):
    return [{"batch": batch, "bucket": "b%d" % batch, "status": status,
             "latency_ms": float(v), "service_ms": float(v),
             "trace_id": "%032x" % i, "request_id": "req-%d" % i}
            for i, v in enumerate(lats)]


def test_report_percentiles_and_slo():
    report = obs_load.build_report(
        _samples(range(1, 101)), mode="open", wall_s=2.0, slo_ms=90.0,
        offered_rps=50.0)
    pct = report["percentiles_ms"]
    assert pct["p50_ms"] == 50.0 and pct["p90_ms"] == 90.0
    assert pct["p99_ms"] == 99.0 and pct["p99_9_ms"] == 100.0
    assert report["max_ms"] == 100.0
    assert report["achieved_rps"] == 50.0
    assert report["slo"] == {"slo_ms": 90.0, "attainment": 0.9,
                             "violations": 10}
    assert report["by_bucket"]["b1"]["n"] == 100
    assert [w["latency_ms"] for w in report["worst"]] == \
        [100.0, 99.0, 98.0, 97.0, 96.0]
    assert obs_load.percentile([], 99.0) is None
    with pytest.raises(ValueError):
        obs_load.build_report([None], mode="open", wall_s=1.0)


def test_format_report_mentions_the_tail():
    report = obs_load.build_report(_samples([1.0, 2.0, 300.0]),
                                   mode="open", wall_s=1.0,
                                   slo_ms=100.0)
    text = obs_load.format_report(report)
    assert "open loop: 3 requests" in text
    assert "p99 300.00" in text and "worst 300.00ms" in text
    assert "slo:" in text


# ---------------------------------------------------------------------------
# joins
# ---------------------------------------------------------------------------

def test_join_tail_matches_request_then_trace():
    report = obs_load.build_report(_samples([1.0, 2.0, 50.0]),
                                   mode="open", wall_s=1.0)
    worst = report["worst"][0]
    tail_doc = {"requests": [
        {"request_id": worst["request_id"], "trace_id": "nope",
         "reason": "slow", "latency_ms": 49.0, "status": 200,
         "spans": [{"name": "serving/request"}]},
    ]}
    assert obs_load.join_tail(report, tail_doc) == 1
    assert report["worst"][0]["tail"]["reason"] == "slow"
    assert report["worst"][0]["tail"]["spans"]
    # trace_id is the fallback join key
    report2 = obs_load.build_report(_samples([1.0, 2.0, 50.0]),
                                    mode="open", wall_s=1.0)
    w2 = report2["worst"][0]
    assert obs_load.join_tail(report2, {"requests": [
        {"request_id": "other", "trace_id": w2["trace_id"],
         "reason": "slow", "latency_ms": 48.0, "status": 200,
         "spans": []}]}) == 1
    assert obs_load.join_tail(report2, {"requests": []}) == 0


def test_parse_and_join_exemplars():
    text = "\n".join([
        "# TYPE serving_total_seconds histogram",
        'serving_total_seconds_bucket{le="0.05"} 7 '
        '# {trace_id="%s"} 0.021 1700000000.000' % ("ab" * 16),
        'serving_total_seconds_bucket{le="+Inf"} 8',
        "serving_total_seconds_count 8",
    ])
    ex = obs_load.parse_exemplars(text)
    assert list(ex) == ["ab" * 16]
    hit = ex["ab" * 16][0]
    assert hit["metric"] == "serving_total_seconds"
    assert hit["le"] == "0.05" and hit["value"] == pytest.approx(0.021)
    report = obs_load.build_report(
        [{"batch": 1, "bucket": "b1", "status": 200,
          "latency_ms": 21.0, "service_ms": 21.0,
          "trace_id": "ab" * 16, "request_id": "req-x"}],
        mode="open", wall_s=1.0)
    assert obs_load.join_exemplars(report, text) == 1
    assert report["worst"][0]["exemplars"][0]["le"] == "0.05"


# ---------------------------------------------------------------------------
# latency blob -> history -> gate
# ---------------------------------------------------------------------------

def _lat_record(blob, value=100.0):
    return {"metric": "serving_slo_openloop_rps", "value": value,
            "unit": "req/s", "platform": "cpu", "latency": blob}


def _blob(scale=1.0, mode="open", **extra):
    blob = {"mode": mode, "n": 200, "p50_ms": 5.0 * scale,
            "p90_ms": 8.0 * scale, "p99_ms": 20.0 * scale,
            "p99_9_ms": 45.0 * scale, "slo_ms": 50.0,
            "slo_attainment": 0.99, "offered_rps": 100.0,
            "achieved_rps": 99.0}
    blob.update(extra)
    return blob


def test_latency_blob_survives_normalize_record():
    report = obs_load.build_report(_samples([1.0, 2.0, 3.0]),
                                   mode="open", wall_s=1.0, slo_ms=2.5,
                                   offered_rps=3.0)
    blob = obs_load.latency_blob(report)
    assert blob["mode"] == "open" and blob["n"] == 3
    assert blob["slo_attainment"] == pytest.approx(2 / 3, abs=1e-4)
    norm = obs_perf.normalize_record(_lat_record(blob), leg="pload",
                                     ts=1.0)
    assert norm["latency"]["p99_ms"] == blob["p99_ms"]
    assert norm["latency"]["mode"] == "open"
    # records without the blob stay blob-free
    assert "latency" not in obs_perf.normalize_record(
        {"metric": "m", "value": 1.0}, ts=1.0)


def _gate(records, **kw):
    return obs_perf.gate_history(
        [obs_perf.normalize_record(r, leg="pload", ts=1000.0 + i)
         for i, r in enumerate(records)], **kw)


def test_latency_gate_is_opt_in_and_names_the_percentile():
    records = [_lat_record(_blob()) for _ in range(5)]
    records.append(_lat_record(_blob(scale=3.0)))
    # opt-in: without the tolerance the regression passes
    assert _gate(records).ok
    res = _gate(records, latency_tolerance=0.25)
    assert not res.ok
    f = res.failures[0]
    assert f["kind"] == "latency"
    assert "p99_9_ms" in f["why"] and "open loop" in f["why"]
    # within tolerance passes
    ok = [_lat_record(_blob()) for _ in range(5)]
    ok.append(_lat_record(_blob(scale=1.1)))
    assert _gate(ok, latency_tolerance=0.25).ok


def test_latency_gate_same_key_fallback():
    """A candidate that only carries p50 gates on p50 against the
    baselines' p50 — never a cross-percentile comparison."""
    records = [_lat_record(_blob()) for _ in range(5)]
    records.append(_lat_record(
        {"mode": "open", "n": 10, "p50_ms": 50.0}))
    res = _gate(records, latency_tolerance=0.25)
    assert not res.ok and "p50_ms" in res.failures[0]["why"]


def test_latency_gate_mode_separation():
    """Closed-loop percentiles are omission-blind: an open-loop
    candidate must never gate against a closed-loop baseline even
    when its numbers are higher."""
    records = [_lat_record(_blob(mode="closed")) for _ in range(5)]
    records.append(_lat_record(_blob(scale=3.0, mode="open")))
    assert _gate(records, latency_tolerance=0.25).ok
    # and records with no latency blob are never failed on latency
    bare = [{"metric": "m", "value": 100.0, "platform": "cpu"}
            for _ in range(6)]
    assert _gate(bare, latency_tolerance=0.25).ok
