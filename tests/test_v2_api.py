"""v2 API parity tests (reference: python/paddle/v2 — the event-driven
SGD trainer, Parameters tar round-trip, paddle.infer, and the v2 layer
DSL over fluid)."""

import io

import numpy as np

import paddle_tpu.v2 as paddle


def test_v2_fit_a_line():
    paddle.init(use_gpu=False, trainer_count=1)
    x = paddle.layer.data(name="x",
                          type=paddle.data_type.dense_vector(13))
    y_predict = paddle.layer.fc(input=x, size=1,
                                act=paddle.activation.Linear())
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(1))
    cost = paddle.layer.square_error_cost(input=y_predict, label=y)

    parameters = paddle.parameters.create(cost)
    optimizer = paddle.optimizer.Momentum(momentum=0.9,
                                          learning_rate=1e-3)
    trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                 update_equation=optimizer)

    costs = []

    def event_handler(event):
        if isinstance(event, paddle.event.EndIteration):
            costs.append(event.cost)

    reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.uci_housing.train(),
                              buf_size=500), batch_size=20)
    trainer.train(reader=reader, num_passes=12,
                  event_handler=event_handler,
                  feeding={"x": 0, "y": 1})
    assert costs[-1] < costs[0], (costs[0], costs[-1])

    # test() runs forward-only
    result = trainer.test(reader=paddle.batch(
        paddle.dataset.uci_housing.test(), batch_size=20),
        feeding={"x": 0, "y": 1})
    assert np.isfinite(result.cost)

    # Parameters: numpy access + tar round-trip
    keys = parameters.keys()
    assert len(keys) >= 2  # weight + bias
    w = parameters.get(keys[0])
    buf = io.BytesIO()
    parameters.to_tar(buf)
    parameters.set(keys[0], np.zeros_like(w))
    assert np.allclose(parameters.get(keys[0]), 0)
    buf.seek(0)
    parameters.init_from_tar(buf)
    assert np.allclose(parameters.get(keys[0]), w)

    # infer
    test_data = [(s[0],) for s in paddle.dataset.uci_housing.test()()][:8]
    probs = paddle.infer(output_layer=y_predict, parameters=parameters,
                         input=test_data, feeding={"x": 0, "y": 1})
    assert probs.shape[0] == 8
    assert np.all(np.isfinite(probs))


def test_v2_mnist_convnet():
    paddle.init()
    images = paddle.layer.data(name="pixel",
                               type=paddle.data_type.dense_array(
                                   784, [1, 28, 28]))
    label = paddle.layer.data(name="label",
                              type=paddle.data_type.integer_value(10))
    conv_pool = paddle.networks.simple_img_conv_pool(
        input=images, filter_size=5, num_filters=8, pool_size=2,
        pool_stride=2, act=paddle.activation.Relu())
    predict = paddle.layer.fc(input=conv_pool, size=10,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)

    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=0.01))

    costs = []

    def handler(event):
        if isinstance(event, paddle.event.EndIteration):
            costs.append(event.cost)

    import paddle_tpu

    reader = paddle.batch(paddle_tpu.dataset.mnist.train(),
                          batch_size=32)

    def limited():
        for i, b in enumerate(reader()):
            if i >= 12:
                return
            yield b

    trainer.train(reader=limited, num_passes=1, event_handler=handler)
    assert np.mean(costs[-3:]) < np.mean(costs[:3]), costs


def test_v2_sequence_lstm():
    paddle.init()
    data = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(200))
    label = paddle.layer.data(name="label",
                              type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=data, size=16)
    lstm = paddle.networks.simple_lstm(input=emb, size=8)
    pooled = paddle.layer.pool(input=lstm,
                               pooling_type=paddle.pooling.Max())
    predict = paddle.layer.fc(input=pooled, size=2,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)

    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))

    rs = np.random.RandomState(3)

    def reader():
        for _ in range(10):
            batch = []
            for _ in range(8):
                n = int(rs.randint(3, 12))
                words = rs.randint(0, 200, size=n).tolist()
                lab = int(sum(words) % 2)
                batch.append((words, lab))
            yield batch

    costs = []

    def handler(event):
        if isinstance(event, paddle.event.EndIteration):
            costs.append(event.cost)

    trainer.train(reader=reader, num_passes=2, event_handler=handler)
    assert np.isfinite(costs[-1])
