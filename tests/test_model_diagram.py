"""Program visualizer (reference: paddle/utils/make_model_diagram.py,
show_pb.py)."""

import json

import paddle_tpu.fluid as fluid
from paddle_tpu.utils.model_diagram import (main, program_to_dot,
                                            program_to_text)


def _program():
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        label = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=4, act="relu")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=h, label=label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main_p


def test_dot_structure():
    dot = program_to_dot(_program())
    assert dot.startswith("digraph program {") and dot.endswith("}")
    assert "mul" in dot                       # the fc matmul op box
    assert "style=dashed" in dot              # grad ops are dashed
    assert "peripheries=2" in dot             # the sgd update doubled
    assert "fillcolor=lightgray" in dot       # parameter node
    assert "->" in dot
    # dataflow edges carry dtype/shape labels
    assert "float32" in dot


def test_text_dump_lists_every_op():
    prog = _program()
    text = program_to_text(prog)
    for op in prog.global_block().desc.ops:
        assert op.type in text
    assert "block 0" in text


def test_cli_over_saved_model(tmp_path):
    prog = _program()
    model = tmp_path / "model.json"
    model.write_text(json.dumps({"program": prog.desc.to_dict()}))
    out = tmp_path / "g.dot"
    main([str(model), str(out)])
    assert out.read_text().startswith("digraph")
