"""Alias & donation-safety analysis (analysis/alias.py, A0xx codes)
and its executor/trainer/audit wiring behind FLAGS_donation.

Donation is value-preserving: XLA reuses the donated input's buffer
for an output, so numerics across off/conservative/auto must be
BIT-identical on f32 — several tests below pin exactly that.  On the
CPU backend donation is a silent no-op (and
`pcache.donation_aliasing_safe()` is False), so tests that need the
widened path monkeypatch the backend-safety probe rather than assert
buffer deletion.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import analysis
from paddle_tpu.compile import pcache
from paddle_tpu.core.desc import OpDesc
from paddle_tpu.core.scope import Scope
from paddle_tpu.obs import mem as obs_mem
from paddle_tpu.tools.lint_cli import _build_two_segment
from paddle_tpu.tools.mem_cli import _build_adam_toy, _fork_adam_slot
from paddle_tpu.utils import flags


@pytest.fixture(autouse=True)
def _restore_donation_flags():
    old = {k: flags.get_flag(k)
           for k in ("donation", "compile_cache_dir")}
    yield
    for k, v in old.items():
        flags.set_flag(k, v)
    pcache.reset()


def _feeds(rs=None, n=4, d=64):
    rs = rs or np.random.RandomState(0)
    return {"x": rs.randn(n, d).astype(np.float32)}


def _train_losses(main, startup, cost, steps=4, d=64):
    """Fresh Executor+Scope run; returns (per-step losses, final
    param values) for exact cross-mode comparison."""
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        rs = np.random.RandomState(0)
        losses = []
        for _ in range(steps):
            out, = exe.run(main, feed=_feeds(rs, d=d),
                           fetch_list=[cost], scope=scope)
            losses.append(np.asarray(out).copy())
        params = {n: np.asarray(scope.get(n)).copy()
                  for n in main.global_block().vars
                  if scope.get(n) is not None}
    return losses, params, exe


# -- the plan ---------------------------------------------------------------

def test_mode_ladder_and_fingerprints():
    main, _startup, cost = _build_adam_toy()
    plans = {m: analysis.analyze_donation(main, fetches=[cost.name],
                                          mode=m)
             for m in ("off", "conservative", "auto")}
    auto = plans["auto"]
    assert not auto.report.errors
    nseg = len(auto.segments)
    assert any(auto.donate(i) for i in range(nseg))
    for i in range(nseg):
        assert plans["off"].donate(i) == ()
        assert set(plans["conservative"].donate(i)) \
            <= set(auto.donate(i))
    # the three modes can never share an executable
    fps = {m: p.fingerprint() for m, p in plans.items()}
    assert len(set(fps.values())) == 3, fps


def test_feed_is_never_widened():
    main, _startup, cost = _build_adam_toy()
    plan = analysis.analyze_donation(main, fetches=[cost.name],
                                     mode="auto")
    for i in range(len(plan.segments)):
        assert "x" not in plan.donate(i)
    # same result whether or not the caller names its feeds: a name
    # read before any def site is caller-owned regardless
    plan2 = analysis.analyze_donation(main, fetches=[cost.name],
                                      feeds=["x"], mode="auto")
    assert plan2.fingerprint() == plan.fingerprint()


def test_donation_mode_parsing():
    assert analysis.donation_mode("off") == "off"
    assert analysis.donation_mode("bogus") == "auto"
    from paddle_tpu.analysis.alias import state_donation

    flags.set_flag("donation", "off")
    assert state_donation() is False
    flags.set_flag("donation", "auto")
    assert state_donation() is True


# -- the A-codes ------------------------------------------------------------

def test_a001_forked_slot_and_audit_delta():
    main, _startup, cost = _build_adam_toy()
    forked = _fork_adam_slot(main)
    plan = analysis.analyze_donation(main, fetches=[cost.name],
                                     mode="auto")
    assert "A001" in plan.report.codes()
    broken = obs_mem.audit_donation(main, fetches=[cost.name],
                                    mode="auto")
    hits = [r for r in broken["reclaimable"] if r["name"] == forked]
    assert hits and hits[0].get("code") == "A001"
    assert broken["reclaimable_bytes"] > 0
    # FLAGS_donation=off surrenders exactly the donated bytes on top
    off = obs_mem.audit_donation(main, fetches=[cost.name],
                                 mode="off")
    assert not off["donated"]
    assert off["reclaimable_bytes"] == (broken["reclaimable_bytes"]
                                        + broken["donated_bytes"])


def test_a002_read_after_donation_via_stale_plan():
    main, _startup, hname, lname = _build_two_segment()
    plan = analysis.analyze_donation(main, fetches=[lname],
                                     mode="auto")
    assert any(hname in plan.widened(i)
               for i in range(len(plan.segments)))
    # mutate the program AFTER planning: a later op now reads the
    # donated buffer — verify() must refuse the stale plan
    main.desc.block(0).ops.append(
        OpDesc("scale", {"X": [hname]}, {"Out": ["__late__"]},
               {"scale": 2.0}))
    rep = plan.verify(main, fetches=[lname, "__late__"])
    assert "A002" in rep.codes()
    assert rep.errors


def test_a003_fetch_declines_widening():
    main, _startup, hname, lname = _build_two_segment()
    plan = analysis.analyze_donation(main, fetches=[lname, hname],
                                     mode="auto")
    assert "A003" in plan.report.codes()
    assert not any(hname in plan.widened(i)
                   for i in range(len(plan.segments)))


def test_a005_unsafe_backend_degrades():
    main, _startup, cost = _build_adam_toy()
    plan = analysis.analyze_donation(main, fetches=[cost.name],
                                     mode="auto", backend_safe=False)
    assert plan.effective_mode == "conservative"
    assert "A005" in plan.report.codes()
    assert not plan.report.errors


# -- executor wiring --------------------------------------------------------

def test_executor_applies_widened_plan(monkeypatch):
    monkeypatch.setattr(pcache, "donation_aliasing_safe",
                        lambda backend=None: True)
    flags.set_flag("donation", "auto")
    main, startup, hname, lname = _build_two_segment()
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        exe.run(main, feed={"x": np.zeros((4, 16), np.float32)},
                fetch_list=[lname], scope=scope)
    cp = list(exe._cache.values())[-1]
    assert cp._donation["mode"] == "auto"
    muts = [j["mutated"] for j in cp._jit_cache.values()]
    assert any(hname in m for m in muts), muts


def test_auto_degrades_on_unsafe_backend_bit_identical(monkeypatch):
    """Satellite: on a backend where executable reload drops donation
    aliasing, auto quietly becomes conservative and numerics match
    off exactly."""
    monkeypatch.setattr(pcache, "donation_aliasing_safe",
                        lambda backend=None: False)
    runs = {}
    for mode in ("off", "auto"):
        flags.set_flag("donation", mode)
        main, startup, cost = _build_adam_toy()
        runs[mode] = _train_losses(main, startup, cost)
    _losses, _params, exe = runs["auto"]
    cp = list(exe._cache.values())[-1]
    assert cp._donation["mode"] == "conservative"
    for a, b in zip(runs["off"][0], runs["auto"][0]):
        np.testing.assert_array_equal(a, b)
    for n, v in runs["off"][1].items():
        np.testing.assert_array_equal(v, runs["auto"][1][n])


def test_modes_bit_identical_f32(monkeypatch):
    """The core safety property: donation never changes a value.
    Backend forced 'safe' so auto actually widens."""
    monkeypatch.setattr(pcache, "donation_aliasing_safe",
                        lambda backend=None: True)
    runs = {}
    for mode in ("off", "conservative", "auto"):
        flags.set_flag("donation", mode)
        main, startup, cost = _build_adam_toy()
        runs[mode] = _train_losses(main, startup, cost)
    ref_losses, ref_params, _ = runs["off"]
    for mode in ("conservative", "auto"):
        losses, params, _ = runs[mode]
        for a, b in zip(ref_losses, losses):
            np.testing.assert_array_equal(a, b)
        for n, v in ref_params.items():
            np.testing.assert_array_equal(v, params[n])


def test_donation_under_amp_bf16(monkeypatch):
    """Satellite: under amp_bf16 the state dtypes take two steps to
    reach their fixed point (f32 -> bf16 -> f32 masters).  The
    donation plan must ride the re-traces: after the fixed point no
    segment traces again, and auto matches off bit-for-bit (same
    casts, donation is aliasing only)."""
    monkeypatch.setattr(pcache, "donation_aliasing_safe",
                        lambda backend=None: True)
    runs = {}
    for mode in ("off", "auto"):
        flags.set_flag("donation", mode)
        with fluid.amp.bf16_guard():
            main, startup, cost = _build_adam_toy()
            runs[mode] = _train_losses(main, startup, cost, steps=5)
    for a, b in zip(runs["off"][0], runs["auto"][0]):
        np.testing.assert_array_equal(a, b)
    # signature fixed point: at most 3 traces over 5 steps (f32 ->
    # bf16 transient -> steady); a donated-dtype mismatch against the
    # runtime signature would retrace on EVERY step (>= 5)
    _losses, _params, exe = runs["auto"]
    cp = list(exe._cache.values())[-1]
    assert cp._donation["mode"] == "auto"
    sizes = {i: j["fn"]._cache_size()
             for i, j in cp._jit_cache.items()}
    assert sizes and all(s <= 3 for s in sizes.values()), sizes


# -- compile-cache key separation -------------------------------------------

def _build_two_segment_infer():
    """fc -> print -> mean with NO optimizer: zero in-place ops, so
    the program is donation-free on every backend and all three
    modes' pcache entries are non-donated (reloadable even where
    donation_aliasing_safe is False)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        h = fluid.layers.fc(input=x, size=8)
        loss = fluid.layers.mean(x=h)
    bd = main.desc.block(0)
    i = next(i for i, od in enumerate(bd.ops)
             if od.type == "mean")
    bd.ops.insert(i, OpDesc("print", {"X": [h.name]},
                            {"Out": [h.name]},
                            {"message": "seg", "summarize": 1}))
    return main, startup, loss.name


def test_pcache_keys_separate_modes(tmp_path):
    """FLAGS_donation folds into the persistent-cache keys: each mode
    populates its own entries cold and reloads its own warm (0 new
    entries), never another mode's."""
    from paddle_tpu.obs import telemetry as obs_tele

    flags.set_flag("compile_cache_dir", str(tmp_path))
    x = np.zeros((4, 16), np.float32)

    def run_once(mode):
        flags.set_flag("donation", mode)
        main, startup, lname = _build_two_segment_infer()
        scope = Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup, scope=scope)
            exe.run(main, feed={"x": x}, fetch_list=[lname],
                    scope=scope)

    entries = {}
    for mode in ("off", "conservative", "auto"):
        before = pcache.get_cache().stats()["entries"]
        run_once(mode)
        entries[mode] = pcache.get_cache().stats()["entries"]
        assert entries[mode] > before, \
            "mode %r reused another mode's entries" % mode
    # warm rerun per mode: 0 fresh entries, served from disk
    for mode in ("off", "conservative", "auto"):
        pcache.reset()
        before = pcache.get_cache().stats()["entries"]
        hits0 = obs_tele.snapshot().get("compile_cache_hits_total", 0)
        run_once(mode)
        assert pcache.get_cache().stats()["entries"] == before
        assert obs_tele.snapshot().get("compile_cache_hits_total",
                                       0) > hits0


# -- audit ------------------------------------------------------------------

def test_audit_clean_toy_zero_reclaimable_under_auto():
    main, _startup, cost = _build_adam_toy()
    audit = obs_mem.audit_donation(main, fetches=[cost.name],
                                   mode="auto")
    assert audit["effective_mode"] == "auto"
    assert audit["reclaimable_bytes"] == 0, audit["reclaimable"]
    assert audit["donated_bytes"] > 0
    # every reclaimable entry in ANY mode carries its explanation
    off = obs_mem.audit_donation(main, fetches=[cost.name],
                                 mode="off")
    assert off["reclaimable_bytes"] > 0
    for r in off["reclaimable"]:
        assert r["reason"]
