"""paddle_tpu.resilience: retry policies, circuit breaker, the seeded
fault-injection registry, and the retry wiring into dataset downloads,
checkpoint writes and serving warmup."""

import json
import os
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.obs import telemetry as obs_tele
from paddle_tpu.resilience import faults, retry
from paddle_tpu.resilience.retry import (AttemptTimeout, CircuitBreaker,
                                         CircuitOpenError, RetryPolicy)


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

def test_retry_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise IOError("transient")
        return "ok"

    policy = RetryPolicy(max_attempts=3, base_delay=0.0, name="t")
    assert policy.call(flaky) == "ok"
    assert len(calls) == 3
    snap = obs_tele.snapshot()
    assert snap.get("retries_total{op=t}") == 2


def test_retry_exhausts_and_reraises():
    policy = RetryPolicy(max_attempts=2, base_delay=0.0, name="boom")

    def always():
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        policy.call(always)
    snap = obs_tele.snapshot()
    assert snap.get("retry_exhausted_total{op=boom}") == 1


def test_retry_nonretryable_propagates_immediately():
    calls = []

    def bug():
        calls.append(1)
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=5, base_delay=0.0).call(bug)
    assert len(calls) == 1  # no retries on non-retryable


def test_retry_backoff_full_jitter_bounds():
    import random

    policy = RetryPolicy(base_delay=0.1, max_delay=1.0,
                         rng=random.Random(0))
    for attempt in range(1, 8):
        cap = min(1.0, 0.1 * (2 ** (attempt - 1)))
        for _ in range(20):
            d = policy.backoff(attempt)
            assert 0 <= d <= cap
    nojit = RetryPolicy(base_delay=0.1, max_delay=1.0, jitter=False)
    assert nojit.backoff(1) == 0.1
    assert nojit.backoff(5) == 1.0  # capped


def test_retry_overall_deadline_stops_sleeping_past_budget():
    slept = []

    def never():
        raise IOError("x")

    policy = RetryPolicy(max_attempts=100, base_delay=10.0,
                         jitter=False, deadline=0.5,
                         sleep=slept.append)
    with pytest.raises(IOError):
        policy.call(never)
    # first backoff (10s) would blow the 0.5s budget: no sleep at all
    assert slept == []


def test_retry_attempt_timeout_retries_hung_call():
    calls = []

    def hangs_once():
        calls.append(1)
        if len(calls) == 1:
            time.sleep(5)
        return "done"

    policy = RetryPolicy(max_attempts=2, base_delay=0.0,
                         attempt_timeout=0.2)
    assert policy.call(hangs_once) == "done"
    assert len(calls) == 2


def test_retry_attempt_timeout_exhausted_raises_attempt_timeout():
    policy = RetryPolicy(max_attempts=1, attempt_timeout=0.05)
    with pytest.raises(AttemptTimeout):
        policy.call(time.sleep, 5)


def test_retry_wrap_decorator():
    calls = []

    @RetryPolicy(max_attempts=2, base_delay=0.0).wrap
    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise OSError("t")
        return 7

    assert flaky() == 7


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------

def test_circuit_opens_fast_fails_and_recovers():
    now = [0.0]
    cb = CircuitBreaker(failure_threshold=2, reset_timeout=10.0,
                        name="dep", clock=lambda: now[0])

    def boom():
        raise IOError("down")

    for _ in range(2):
        with pytest.raises(IOError):
            cb.call(boom)
    assert cb.state == cb.OPEN
    with pytest.raises(CircuitOpenError):
        cb.call(lambda: 1)  # fast fail, fn not called
    # cooldown lapses -> half-open probe; success closes
    now[0] = 11.0
    assert cb.call(lambda: 42) == 42
    assert cb.state == cb.CLOSED
    snap = obs_tele.snapshot()
    assert snap.get("circuit_opened_total{breaker=dep}") == 1
    assert snap.get("circuit_state{breaker=dep}") == 0


def test_circuit_failed_probe_reopens():
    now = [0.0]
    cb = CircuitBreaker(failure_threshold=1, reset_timeout=5.0,
                        name="dep2", clock=lambda: now[0])
    with pytest.raises(IOError):
        cb.call(lambda: (_ for _ in ()).throw(IOError()))
    now[0] = 6.0
    with pytest.raises(IOError):
        cb.call(lambda: (_ for _ in ()).throw(IOError()))  # probe fails
    assert not cb.allow()  # re-armed, still open


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

def test_faults_deterministic_after_times_and_counters():
    plan = faults.enable(seed=3)
    spec = plan.inject("p/x", "io_error", after=2, times=2)
    assert faults.check("p/x") is None
    assert faults.check("p/x") is None
    for _ in range(2):
        with pytest.raises(faults.InjectedIOError):
            faults.check("p/x")
    assert faults.check("p/x") is None  # times exhausted
    assert spec.fired == 2
    assert faults.fired_counts() == {("p/x", "io_error"): 2}
    snap = obs_tele.snapshot()
    assert snap.get("faults_injected_total{kind=io_error,point=p/x}") \
        == 2


def test_faults_probability_is_seeded_and_reproducible():
    def trial():
        plan = faults.FaultPlan(seed=42)
        plan.inject("p/y", "nonfinite", probability=0.5, times=None)
        return [plan.check("p/y") is not None for _ in range(32)]

    a, b = trial(), trial()
    assert a == b
    assert any(a) and not all(a)


def test_faults_latency_sleeps():
    faults.enable(seed=0)
    faults.inject("p/slow", "latency", latency_s=0.1)
    t0 = time.perf_counter()
    fired = faults.check("p/slow")
    assert fired is not None and fired.kind == "latency"
    assert time.perf_counter() - t0 >= 0.09


def test_faults_off_is_free_and_check_noop():
    assert not faults.active()
    assert faults.check("anything") is None
    assert faults.fired_counts() == {}
    with pytest.raises(RuntimeError):
        faults.inject("p", "io_error")  # no plan enabled


def test_faults_unknown_kind_rejected():
    plan = faults.FaultPlan()
    with pytest.raises(ValueError):
        plan.inject("p", "meteor_strike")


def test_executor_run_fault_point():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    loss = fluid.layers.mean(x=fluid.layers.fc(input=x, size=3))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    faults.enable(seed=0)
    faults.inject("executor/run", "io_error", times=1)
    with pytest.raises(faults.InjectedIOError):
        exe.run(fluid.default_main_program(),
                feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss])
    # one-shot: the next run goes through
    out, = exe.run(fluid.default_main_program(),
                   feed={"x": np.ones((2, 4), np.float32)},
                   fetch_list=[loss])
    assert np.isfinite(np.asarray(out)).all()


# ---------------------------------------------------------------------------
# dataset download: retry + partial-tmp cleanup
# ---------------------------------------------------------------------------

def test_download_retries_transient_faults(tmp_path, monkeypatch):
    from paddle_tpu.dataset import common

    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    src = tmp_path / "payload.bin"
    src.write_bytes(b"hello resilience")
    url = "file://" + str(src)

    faults.enable(seed=0)
    faults.inject("dataset/download", "io_error", times=2)
    got = common.download(url, "unit",
                          retry=RetryPolicy(max_attempts=3,
                                            base_delay=0.0,
                                            name="dl"))
    assert open(got, "rb").read() == b"hello resilience"
    assert not os.path.exists(got + ".part")
    snap = obs_tele.snapshot()
    assert snap.get("retries_total{op=dl}") == 2


def test_download_exhausted_leaves_no_partial(tmp_path, monkeypatch):
    from paddle_tpu.dataset import common

    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    src = tmp_path / "payload.bin"
    src.write_bytes(b"x")
    url = "file://" + str(src)
    faults.enable(seed=0)
    faults.inject("dataset/download", "io_error", times=None)
    with pytest.raises(IOError):
        common.download(url, "unit",
                        retry=RetryPolicy(max_attempts=3,
                                          base_delay=0.0))
    cache_dir = tmp_path / "unit"
    leftovers = [p for p in os.listdir(cache_dir)] \
        if cache_dir.exists() else []
    assert not any(p.endswith(".part") for p in leftovers), leftovers


def test_download_md5_mismatch_removes_tmp_and_retries(tmp_path,
                                                       monkeypatch):
    from paddle_tpu.dataset import common

    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    src = tmp_path / "payload.bin"
    src.write_bytes(b"data")
    url = "file://" + str(src)
    with pytest.raises(IOError, match="md5 mismatch"):
        common.download(url, "unit", md5sum="0" * 32,
                        retry=RetryPolicy(max_attempts=2,
                                          base_delay=0.0))
    cache_dir = tmp_path / "unit"
    assert not any(p.endswith(".part")
                   for p in os.listdir(cache_dir)), \
        os.listdir(cache_dir)


# ---------------------------------------------------------------------------
# checkpoint write: retry, fsync-path orphan cleanup
# ---------------------------------------------------------------------------

def _toy_program():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    loss = fluid.layers.mean(x=fluid.layers.fc(input=x, size=3))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return loss


def test_checkpoint_write_retries_injected_fault(tmp_path):
    from paddle_tpu.fluid.checkpoint import (CheckpointSaver,
                                             latest_checkpoint)

    _toy_program()
    faults.enable(seed=0)
    faults.inject("checkpoint/write", "io_error", times=1)
    saver = CheckpointSaver(str(tmp_path / "ck"), interval_secs=0)
    saver.save(1)
    saver.wait()  # the injected IOError was retried, not surfaced
    assert latest_checkpoint(str(tmp_path / "ck")) is not None
    assert faults.fired_counts() == {("checkpoint/write",
                                      "io_error"): 1}


def test_checkpoint_manifest_failure_leaves_no_orphan_tmp(tmp_path,
                                                          monkeypatch):
    from paddle_tpu.fluid import checkpoint as ckpt_mod

    _toy_program()
    root = str(tmp_path / "ck")
    saver = ckpt_mod.CheckpointSaver(
        root, interval_secs=0,
        write_retry=RetryPolicy(max_attempts=1, base_delay=0.0))

    def bad_dump(obj, fh, **kw):
        raise IOError("manifest serialization died")

    monkeypatch.setattr(ckpt_mod.json, "dump", bad_dump)
    snap = saver.save(1)
    with pytest.raises(IOError, match="manifest"):
        saver.wait()
    # the mkstemp tmp was cleaned up: only var .npz files remain
    leftovers = [f for f in os.listdir(snap)
                 if not f.endswith(".npz")]
    assert leftovers == [], leftovers
    assert ckpt_mod.latest_checkpoint(root) is None  # torn, invisible


def test_checkpoint_explicit_var_names(tmp_path):
    from paddle_tpu.core.scope import global_scope
    from paddle_tpu.fluid.checkpoint import (CheckpointSaver,
                                             load_checkpoint)

    _toy_program()
    global_scope().set("extra_state", np.arange(4, dtype=np.float32))
    saver = CheckpointSaver(str(tmp_path / "ck"), interval_secs=0,
                            var_names=["extra_state"])
    saver.save(5)
    saver.wait()
    global_scope().set("extra_state", None)
    assert load_checkpoint(str(tmp_path / "ck")) == 5
    np.testing.assert_array_equal(
        np.asarray(global_scope().get("extra_state")),
        np.arange(4, dtype=np.float32))


# ---------------------------------------------------------------------------
# coordinator: heartbeat retry over a fake client
# ---------------------------------------------------------------------------

class _FakeLeaseClient:
    def __init__(self, fail_beats=0):
        self.fail_beats = fail_beats
        self.beats = 0
        self.closed = False
        self.unregistered = False

    def keep_alive(self, lease):
        self.beats += 1
        if self.fail_beats > 0:
            self.fail_beats -= 1
            raise ConnectionError("blip")
        return True

    def unregister(self, lease):
        self.unregistered = True

    def close(self):
        self.closed = True


def test_lease_heartbeat_survives_transient_blip():
    from paddle_tpu.distributed.coordinator import ServiceLease

    client = _FakeLeaseClient(fail_beats=1)
    lease = ServiceLease(client, lease_id=1, ttl_ms=120)
    deadline = time.time() + 3
    while client.beats < 3 and time.time() < deadline:
        time.sleep(0.01)
    assert not lease.lapsed  # one blip was retried, not fatal
    lease.release()
    assert client.unregistered and client.closed


def test_lease_heartbeat_lapses_on_persistent_failure():
    from paddle_tpu.distributed.coordinator import ServiceLease

    client = _FakeLeaseClient(fail_beats=10 ** 6)
    lease = ServiceLease(client, lease_id=1, ttl_ms=120)
    deadline = time.time() + 3
    while not lease.lapsed and time.time() < deadline:
        time.sleep(0.01)
    assert lease.lapsed


# ---------------------------------------------------------------------------
# serving: request-path fault point + warmup retry
# ---------------------------------------------------------------------------

def _tiny_engine(batch_buckets=(2,)):
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid import io as fluid_io
    from paddle_tpu.serving import EngineConfig, InferenceEngine

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[4], dtype="float32")
        probs = fluid.layers.fc(input=img, size=3, act="softmax")
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    program = fluid_io.prune_program(main, [probs])
    return InferenceEngine(
        program, ["img"], [probs], scope=scope,
        config=EngineConfig(batch_buckets=list(batch_buckets)))


def test_serving_run_fault_point_raises():
    engine = _tiny_engine()
    faults.enable(seed=0)
    faults.inject("serving/run", "io_error", times=1)
    with pytest.raises(faults.InjectedIOError):
        engine.run({"img": np.zeros((2, 4), np.float32)})
    out = engine.run({"img": np.zeros((2, 4), np.float32)})
    assert np.asarray(out[0]).shape[0] == 2


def test_serving_warmup_retries_through_injected_fault():
    engine = _tiny_engine(batch_buckets=(1, 2))
    faults.enable(seed=0)
    faults.inject("serving/run", "io_error", times=1)
    assert engine.warmup() == 2  # both buckets warmed despite the fault
    snap = obs_tele.snapshot()
    assert snap.get("retries_total{op=serving_warmup}") == 1
