"""ParallelTrainer grad/loss parity on the virtual 8-device CPU mesh.

The reference's analogous coverage is ParallelExecutor/parallel_do
tests asserting multi-device loss equals single-device loss
(reference: python/paddle/v2/fluid/tests/test_parallel_op.py pattern).
Here dp=8, dp=4 x mp=2, and a 1-device mesh must produce the same
losses and final parameters on identical data — XLA GSPMD collectives
replace NCCL allreduce, so parity proves the sharded step is the same
program.
"""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu.fluid as fluid
from paddle_tpu.parallel import (make_mesh, ParallelTrainer, param_spec,
                                 batch_spec)

BATCH, DIM, HIDDEN, CLASSES = 16, 8, 1024, 4


def _build_mlp():
    # same var names for every build so state dicts are comparable
    fluid.framework.reset_unique_name()
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[BATCH, DIM],
                              dtype="float32", append_batch_size=False)
        label = fluid.layers.data(name="label", shape=[BATCH, 1],
                                  dtype="int64", append_batch_size=False)
        h = fluid.layers.fc(input=x, size=HIDDEN, act="relu")
        logits = fluid.layers.fc(input=h, size=CLASSES, act=None)
        loss = fluid.layers.softmax_with_cross_entropy(logits, label)
        avg = fluid.layers.mean(loss)
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.1, momentum=0.9).minimize(avg)
    return main, startup, avg


def _feeds(step):
    rs = np.random.RandomState(100 + step)
    return {
        "x": rs.rand(BATCH, DIM).astype(np.float32),
        "label": rs.randint(0, CLASSES, size=(BATCH, 1)).astype(np.int64),
    }


def _run(mesh, steps=4, zero_stage=0, return_trainer=False):
    main, startup, avg = _build_mlp()
    tr = ParallelTrainer(main, startup, feed_names=["x", "label"],
                         fetch_names=[avg.name], mesh=mesh,
                         zero_stage=zero_stage).init()
    losses = []
    for i in range(steps):
        (loss,) = tr.step(_feeds(i))
        losses.append(float(np.asarray(loss).reshape(-1)[0]))
    params = {n: np.asarray(v) for n, v in tr.state.items()}
    if return_trainer:
        return losses, params, tr
    return losses, params


def _assert_parity(a, b):
    losses_a, params_a = a
    losses_b, params_b = b
    np.testing.assert_allclose(losses_a, losses_b, rtol=2e-5, atol=1e-6)
    assert params_a.keys() == params_b.keys()
    for n in params_a:
        np.testing.assert_allclose(params_a[n], params_b[n],
                                   rtol=2e-4, atol=1e-5, err_msg=n)


def test_dp8_matches_single_device():
    single = _run(make_mesh(n_devices=1))
    dp8 = _run(make_mesh(n_devices=8))
    assert all(np.isfinite(single[0]))
    _assert_parity(dp8, single)


def test_dp8_trains_on_fixed_batch():
    main, startup, avg = _build_mlp()
    tr = ParallelTrainer(main, startup, feed_names=["x", "label"],
                         fetch_names=[avg.name],
                         mesh=make_mesh(n_devices=8)).init()
    feeds = _feeds(0)
    losses = [float(np.asarray(tr.step(feeds)[0]).reshape(-1)[0])
              for _ in range(6)]
    assert losses[-1] < losses[0], losses


def test_dp4_mp2_matches_single_device():
    single = _run(make_mesh(n_devices=1))
    dpmp = _run(make_mesh(n_devices=8, mp=2))
    _assert_parity(dpmp, single)

    # the hidden fc weight (DIM x HIDDEN) really is mp-sharded
    mesh = make_mesh(n_devices=8, mp=2)
    spec = param_spec("w", (DIM, HIDDEN), mesh)
    assert spec == P(None, "mp")


def test_param_spec_layouts():
    mesh = make_mesh(n_devices=8, mp=2)
    # big embedding table: rows (vocab) sharded
    assert param_spec("emb", (4096, 128), mesh) == P("mp", None)
    # wide fc: cols (output dim) sharded
    assert param_spec("fc_w", (256, 1024), mesh) == P(None, "mp")
    # small weights / biases / BN stats: replicated
    assert param_spec("fc_b", (64,), mesh) == P()
    assert param_spec("small_w", (32, 48), mesh) == P()
    assert param_spec("conv_w", (64, 3, 3, 3), mesh) == P()
    # mp absent or 1: everything replicated
    dp_only = make_mesh(n_devices=8, mp=1)
    assert param_spec("emb", (4096, 128), dp_only) == P()
    # odd cols not divisible by mp: falls back to row or replicated
    assert param_spec("w", (1024, 1023), mesh) == P("mp", None)


def test_batch_spec_layouts():
    mesh = make_mesh(n_devices=8, mp=2)
    assert batch_spec((16, 3, 32, 32), mesh) == P("dp")
    assert batch_spec((), mesh) == P()
    no_dp = make_mesh(n_devices=8, mp=2, axes=("x", "mp"))
    assert batch_spec((16, 4), no_dp) == P()


def test_parallel_do_shim_matches_plain_execution():
    """ParallelDo is a documented no-op under SPMD: the block must
    behave exactly as inline execution on a single device."""
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    pd = fluid.layers.ParallelDo(places=None)
    with pd.do():
        xi = pd.read_input(x)
        pd.write_output(fluid.layers.scale(x=xi, scale=3.0))
    out = pd()

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xs = np.arange(8, dtype=np.float32).reshape(2, 4)
    res, = exe.run(fluid.default_main_program(), feed={"x": xs},
                   fetch_list=[out])
    np.testing.assert_allclose(np.asarray(res), xs * 3.0, rtol=1e-6)


def test_zero1_matches_single_device():
    """ZeRO-1 (dp-sharded optimizer state) is the same program: losses
    and final params match the unsharded single-device run, and the
    velocity accumulators really live sharded over dp."""
    from paddle_tpu.parallel.sharding import is_optimizer_state

    single = _run(make_mesh(n_devices=1))
    z_losses, z_params, tr = _run(make_mesh(n_devices=8), zero_stage=1,
                                  return_trainer=True)
    _assert_parity((z_losses, z_params), single)

    acc_names = [n for n in tr.state if is_optimizer_state(n)]
    assert acc_names, list(tr.state)
    sharded = [n for n in acc_names
               if "dp" in tuple(tr.state[n].sharding.spec)]
    # the big fc velocities shard; shape-[1] accumulators stay replicated
    assert sharded, {n: tr.state[n].sharding.spec for n in acc_names}


def test_zero1_with_mp_composes():
    single = _run(make_mesh(n_devices=1))
    zmp = _run(make_mesh(n_devices=8, mp=2), zero_stage=1)
    _assert_parity(zmp, single)
