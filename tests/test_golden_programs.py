"""Golden ProgramDesc tests: the serialized IR of canonical topologies
is pinned to checked-in JSON (reference: trainer_config_helpers/tests/
configs/*.protostr compared by ProtobufEqualMain.cpp — same idea, JSON
instead of protostr).

Regenerate after an intentional IR change with:
    GOLDEN_REGEN=1 python -m pytest tests/test_golden_programs.py
then review the diff like any other code change.
"""

import json
import os

import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "fixtures",
                          "golden")


def _build_fit_a_line():
    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(input=x, size=1)
    loss = fluid.layers.mean(
        x=fluid.layers.square_error_cost(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return fluid.default_main_program()


def _build_conv_classifier():
    img = fluid.layers.data(name="img", shape=[1, 28, 28],
                            dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    conv = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                               act="relu")
    pool = fluid.layers.pool2d(input=conv, pool_size=2, pool_stride=2)
    logits = fluid.layers.fc(input=pool, size=10, act="softmax")
    loss = fluid.layers.mean(
        x=fluid.layers.cross_entropy(input=logits, label=label))
    fluid.optimizer.MomentumOptimizer(learning_rate=0.01,
                                      momentum=0.9).minimize(loss)
    return fluid.default_main_program()


def _build_dynamic_rnn():
    x = fluid.layers.data(name="x", shape=[8], dtype="float32",
                          lod_level=1)
    drnn = fluid.layers.DynamicRNN()
    with drnn.block():
        step = drnn.step_input(x)
        mem = drnn.memory(shape=[8], batch_ref=step, value=0.0)
        h = fluid.layers.fc(input=[step, mem], size=8, act="tanh")
        drnn.update_memory(mem, h)
        drnn.output(h)
    last = fluid.layers.sequence_last_step(input=drnn())
    loss = fluid.layers.mean(x=last)
    fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return fluid.default_main_program()


def _build_transpiled_pair():
    from paddle_tpu.distributed.transpiler import DistributeTranspiler

    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(input=x, size=1)
    loss = fluid.layers.mean(
        x=fluid.layers.square_error_cost(input=pred, label=y))
    optimize_ops, params_grads = fluid.optimizer.SGD(
        learning_rate=0.01).minimize(loss)
    t = DistributeTranspiler()
    t.transpile(optimize_ops=optimize_ops, params_grads=params_grads,
                trainer_id=0, trainers=2, pservers="127.0.0.1:6174")
    # the trainer program IS the transpiled default main program; the
    # pserver side is the transpiler's per-endpoint param-block table
    return {"trainer": fluid.default_main_program().desc.to_dict(),
            "pserver_blocks": {
                pname: [[str(ep), int(begin), int(size)]
                        for ep, begin, size in blocks]
                for pname, blocks in t.param_blocks.items()}}


def _build_deepfm():
    from paddle_tpu.models.ctr import deepfm_ctr

    ids = fluid.layers.data(name="ids", shape=[4], dtype="int64")
    label = fluid.layers.data(name="label", shape=[1], dtype="float32")
    avg_loss, _ = deepfm_ctr(ids, label, num_features=64, num_fields=4,
                             embed_dim=4, hidden_sizes=(8,))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_loss)
    # the IR must pin the SelectedRows typing of the sparse-table grads
    return fluid.default_main_program().desc.to_dict()


def _build_transformer():
    """Pins the flash_attention op's IR (attrs incl. the sp wiring) and
    the fused momentum update of the Program-stack transformer."""
    from paddle_tpu.models.transformer_program import \
        build_transformer_program

    main, startup, avg_loss, _ = build_transformer_program(
        2, 8, 32, n_layer=1, n_head=2, d_model=16, sp_axis="sp")
    with fluid.program_guard(main, startup):
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.01, momentum=0.9).minimize(avg_loss)
    return main.desc.to_dict()


CASES = {
    "fit_a_line": lambda: _build_fit_a_line().desc.to_dict(),
    "conv_classifier": lambda: _build_conv_classifier().desc.to_dict(),
    "dynamic_rnn": lambda: _build_dynamic_rnn().desc.to_dict(),
    "transpiled_pair": _build_transpiled_pair,
    "deepfm": _build_deepfm,
    "transformer": _build_transformer,
}


@pytest.mark.parametrize("case", sorted(CASES))
def test_golden_program(case):
    framework.reset_unique_name()
    got = CASES[case]()
    path = os.path.join(GOLDEN_DIR, case + ".json")
    if os.environ.get("GOLDEN_REGEN"):
        os.makedirs(GOLDEN_DIR, exist_ok=True)
        with open(path, "w") as f:
            json.dump(got, f, indent=1, sort_keys=True)
        pytest.skip("regenerated %s" % path)
    with open(path) as f:
        want = json.load(f)
    # normalize via one json round-trip (tuples -> lists)
    got = json.loads(json.dumps(got, sort_keys=True))
    assert got == want, (
        "ProgramDesc for %r changed; if intentional, regenerate with "
        "GOLDEN_REGEN=1 and review the diff" % case)


def test_golden_roundtrip():
    """The pinned descs still load and re-serialize identically."""
    from paddle_tpu.core.desc import ProgramDesc

    for case in ("fit_a_line", "conv_classifier", "dynamic_rnn",
                 "deepfm", "transformer"):
        with open(os.path.join(GOLDEN_DIR, case + ".json")) as f:
            want = json.load(f)
        desc = ProgramDesc.from_dict(want)
        again = json.loads(json.dumps(desc.to_dict(), sort_keys=True))
        assert again == want, case
