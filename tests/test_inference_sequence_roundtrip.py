"""Inference export round-trip for a ragged (LoD) sequence model
(reference: save_inference_model io.py:237 + InferenceEngine on the
understand_sentiment LSTM — deploy-time inputs are variable-length
sequences)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import io as fluid_io


def test_sequence_model_save_load_infer(tmp_path):
    V, E, H = 40, 8, 8
    words = fluid.layers.data(name="words", shape=[1], dtype="int64",
                              lod_level=1)
    emb = fluid.layers.embedding(input=words, size=[V, E])
    lstm = fluid.layers.dynamic_lstm(
        input=fluid.layers.fc(input=emb, size=4 * H), size=4 * H)[0]
    pooled = fluid.layers.sequence_pool(input=lstm, pool_type="max")
    probs = fluid.layers.fc(input=pooled, size=2, act="softmax")
    # training-only tail that export must prune away
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    loss = fluid.layers.mean(
        x=fluid.layers.cross_entropy(input=probs, label=label))
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())

    rs = np.random.RandomState(0)
    seqs = [rs.randint(0, V, size=(rs.randint(2, 7), 1)).astype(np.int64)
            for _ in range(5)]
    feeder = fluid.DataFeeder(place=place, feed_list=[words])
    feed = feeder.feed([(s,) for s in seqs])

    # a couple of train steps so exported params are non-initial
    tfeeder = fluid.DataFeeder(place=place, feed_list=[words, label])
    tfeed = tfeeder.feed([(s, np.asarray([i % 2], np.int64))
                          for i, s in enumerate(seqs)])
    for _ in range(3):
        exe.run(fluid.default_main_program(), feed=tfeed,
                fetch_list=[loss])

    model_dir = str(tmp_path / "seq_model")
    # save returns the exact pruned program it serialized — use it for
    # the reference forward so the comparison covers what was exported
    infer_prog = fluid_io.save_inference_model(model_dir, ["words"],
                                               [probs], exe)
    expect, = exe.run(infer_prog, feed=feed, fetch_list=[probs])

    # fresh scope + program: deploy-side reload
    from paddle_tpu.core import scope as scope_mod

    scope_mod.reset_global_scope()
    exe2 = fluid.Executor(place)
    prog, feed_names, fetch_vars = fluid_io.load_inference_model(
        model_dir, exe2)
    assert feed_names == ["words"]
    # the pruned program must not carry the training tail
    optypes = [op.type for op in prog.global_block().ops]
    assert "adam" not in optypes and "cross_entropy" not in optypes

    feeder2 = fluid.DataFeeder(place=place, feed_list=[feed_names[0]],
                               program=prog)
    got, = exe2.run(prog, feed=feeder2.feed([(s,) for s in seqs]),
                    fetch_list=fetch_vars)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=1e-5, atol=1e-6)
