"""paddle.v2.image preprocessing (reference: python/paddle/v2/image.py)."""

import io
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle


def _png_bytes(arr):
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()


@pytest.fixture()
def rgb():
    rs = np.random.RandomState(7)
    return rs.randint(0, 255, size=(40, 60, 3), dtype=np.uint8)


def test_load_roundtrip(rgb, tmp_path):
    p = tmp_path / "im.png"
    p.write_bytes(_png_bytes(rgb))
    im = paddle.image.load_image(str(p))
    np.testing.assert_array_equal(im, rgb)           # PNG is lossless
    gray = paddle.image.load_image(str(p), is_color=False)
    assert gray.shape == (40, 60)
    np.testing.assert_array_equal(
        paddle.image.load_image_bytes(p.read_bytes()), rgb)


def test_resize_short_preserves_aspect(rgb):
    out = paddle.image.resize_short(rgb, 20)        # h<w: h becomes 20
    assert out.shape == (20, 30, 3)
    tall = paddle.image.resize_short(rgb.transpose(1, 0, 2), 20)
    assert tall.shape == (30, 20, 3)


def test_crops_and_flip(rgb):
    c = paddle.image.center_crop(rgb, 24)
    assert c.shape == (24, 24, 3)
    np.testing.assert_array_equal(c, rgb[8:32, 18:42])
    r = paddle.image.random_crop(rgb, 24)
    assert r.shape == (24, 24, 3)
    np.testing.assert_array_equal(
        paddle.image.left_right_flip(rgb)[:, ::-1], rgb)
    chw = paddle.image.to_chw(rgb)
    assert chw.shape == (3, 40, 60)


def test_simple_transform_eval_and_train(rgb):
    mean = [127.5, 127.5, 127.5]
    out = paddle.image.simple_transform(rgb, 32, 24, is_train=False,
                                        mean=mean)
    assert out.shape == (3, 24, 24) and out.dtype == np.float32
    assert out.min() >= -128 and out.max() <= 128
    tr = paddle.image.simple_transform(rgb, 32, 24, is_train=True)
    assert tr.shape == (3, 24, 24)


def test_batch_images_from_tar(tmp_path):
    rs = np.random.RandomState(0)
    tar_path = str(tmp_path / "imgs.tar")
    img2label = {}
    with tarfile.open(tar_path, "w") as tf:
        for i in range(5):
            raw = _png_bytes(rs.randint(0, 255, size=(8, 8, 3),
                                        dtype=np.uint8))
            name = "img_%d.png" % i
            info = tarfile.TarInfo(name)
            info.size = len(raw)
            tf.addfile(info, io.BytesIO(raw))
            if i != 3:                # one unlabeled image is skipped
                img2label[name] = i
    meta = paddle.image.batch_images_from_tar(tar_path, "train",
                                              img2label, num_per_batch=2)
    shards = open(meta).read().splitlines()
    assert len(shards) == 2           # 4 labeled images, 2 per shard
    total, labels = 0, []
    for s in shards:
        z = np.load(s, allow_pickle=True)
        total += len(z["data"])
        labels += list(z["labels"])
        decoded = paddle.image.load_image_bytes(z["data"][0].tobytes())
        assert decoded.shape == (8, 8, 3)
    assert total == 4 and 3 not in labels
