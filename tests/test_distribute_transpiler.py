"""DistributeTranspiler tests (reference: tests/book_distribute/
notest_dist_fit_a_line.py pattern + test_split_var.py), run loopback in
one process plus a true multi-process run with TRAINING_ROLE env vars."""

import os
import subprocess
import sys

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import native
from paddle_tpu.distributed import (DistributeTranspiler,
                                    split_dense_variable, run_pserver)
from paddle_tpu.ops.dist import ClientPool


class _Var:
    def __init__(self, name, shape):
        self.name = name
        self.shape = shape


def test_split_dense_variable():
    """reference: tests/test_split_var.py behavior."""
    vars = [_Var("a", (4000,)), _Var("b", (10,))]
    blocks = split_dense_variable(vars, pserver_count=3,
                                  min_block_size=1024)
    by_name = {}
    for name, bid, begin, size in blocks:
        by_name.setdefault(name, []).append((begin, size))
    # `a` split into >=2 blocks covering all 4000 elements
    total = sum(s for _, s in by_name["a"])
    assert total == 4000
    assert len(by_name["a"]) >= 2
    # small `b` stays whole
    assert by_name["b"] == [(0, 10)]


def _build_fit_a_line():
    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y_predict = fluid.layers.fc(input=x, size=1, act=None)
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    cost = fluid.layers.square_error_cost(input=y_predict, label=y)
    avg_cost = fluid.layers.mean(x=cost)
    opt = fluid.optimizer.SGD(learning_rate=0.01)
    optimize_ops, params_grads = opt.minimize(avg_cost)
    return x, y, avg_cost, optimize_ops, params_grads


def test_transpiled_training_loopback():
    """Trainer program with dist_send ops against an in-process C++
    pserver pair; loss must decrease as with local SGD."""
    servers = [native.ParameterServer(num_trainers=1, sync=True)
               for _ in range(2)]
    try:
        endpoints = ",".join("127.0.0.1:%d" % s.port for s in servers)
        x, y, avg_cost, optimize_ops, params_grads = _build_fit_a_line()
        t = DistributeTranspiler()
        t.transpile(optimize_ops=optimize_ops, params_grads=params_grads,
                    trainer_id=0, pservers=endpoints, trainers=1,
                    split_method=lambda vs, n: split_dense_variable(
                        vs, n, min_block_size=4))

        place = fluid.CPUPlace()
        exe = fluid.Executor(place)
        exe.run(fluid.default_startup_program())
        t.init_pservers()

        feeder = fluid.DataFeeder(place=place, feed_list=[x, y])
        reader = paddle.batch(paddle.dataset.uci_housing.train(),
                              batch_size=20)
        losses = []
        for pass_id in range(8):
            for data in reader():
                out, = exe.run(fluid.default_main_program(),
                               feed=feeder.feed(data),
                               fetch_list=[avg_cost])
                losses.append(float(np.asarray(out).reshape(-1)[0]))
        assert losses[-1] < losses[0], (losses[0], losses[-1])
        assert losses[-1] < 1.0, losses[-1]
        # both pservers participated
        assert all(s.num_updates() > 0 for s in servers)
    finally:
        ClientPool.reset()
        for s in servers:
            s.stop()


def test_transpiled_sparse_embedding():
    """lookup_table with is_sparse=True ships SelectedRows rows only."""
    server = native.ParameterServer(num_trainers=1, sync=True)
    try:
        words = fluid.layers.data(name="w", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(input=words, size=[50, 8],
                                     is_sparse=True)
        label = fluid.layers.data(name="lbl", shape=[8], dtype="float32")
        cost = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=emb, label=label))
        opt = fluid.optimizer.SGD(learning_rate=0.5)
        optimize_ops, params_grads = opt.minimize(cost)

        t = DistributeTranspiler()
        t.transpile(optimize_ops=optimize_ops, params_grads=params_grads,
                    pservers="127.0.0.1:%d" % server.port, trainers=1)

        place = fluid.CPUPlace()
        exe = fluid.Executor(place)
        exe.run(fluid.default_startup_program())
        t.init_pservers()

        feeder = fluid.DataFeeder(place=place, feed_list=[words, label])
        rs = np.random.RandomState(0)
        ids = rs.randint(0, 50, size=(16, 1)).astype(np.int64)
        tgt = (ids.astype(np.float32) / 50.0).repeat(8, axis=1)
        feed = feeder.feed([(ids[i], tgt[i]) for i in range(16)])
        losses = []
        for _ in range(30):
            out, = exe.run(fluid.default_main_program(), feed=feed,
                           fetch_list=[cost])
            losses.append(float(np.asarray(out).reshape(-1)[0]))
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    finally:
        ClientPool.reset()
        server.stop()


_DIST_SCRIPT = r'''
import os, sys
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.distributed import DistributeTranspiler, run_pserver
from paddle_tpu.ops.dist import ClientPool

role = os.environ["TRAINING_ROLE"]
endpoint = os.environ["PSERVER_ENDPOINT"]
trainers = int(os.environ["TRAINERS"])

if role == "PSERVER":
    s = run_pserver(endpoint, trainers=trainers, sync=True)
    sys.stdout.write("READY\n"); sys.stdout.flush()
    sys.stdin.readline()   # parent closes stdin to stop us
    s.stop()
    sys.exit(0)

x = fluid.layers.data(name="x", shape=[13], dtype="float32")
y_predict = fluid.layers.fc(input=x, size=1, act=None)
y = fluid.layers.data(name="y", shape=[1], dtype="float32")
cost = fluid.layers.square_error_cost(input=y_predict, label=y)
avg_cost = fluid.layers.mean(x=cost)
optimize_ops, params_grads = fluid.optimizer.SGD(
    learning_rate=0.01).minimize(avg_cost)

t = DistributeTranspiler()
t.transpile(optimize_ops=optimize_ops, params_grads=params_grads,
            trainer_id=int(os.environ["TRAINER_ID"]),
            pservers=endpoint, trainers=trainers)
place = fluid.CPUPlace()
exe = fluid.Executor(place)
exe.run(fluid.default_startup_program())
t.init_pservers()
feeder = fluid.DataFeeder(place=place, feed_list=[x, y])
reader = paddle.batch(paddle.dataset.uci_housing.train(), batch_size=20)
losses = []
for p in range(6):
    for data in reader():
        out, = exe.run(fluid.default_main_program(),
                       feed=feeder.feed(data), fetch_list=[avg_cost])
        losses.append(float(np.asarray(out).reshape(-1)[0]))
ClientPool.reset()
ok = losses[-1] < losses[0]
print("LOSS", losses[0], losses[-1], flush=True)
sys.exit(0 if ok else 1)
'''


def test_multiprocess_roles():
    """Full parity with the reference's env-var role selection
    (reference: notest_dist_fit_a_line.py TRAINING_ROLE=PSERVER/TRAINER):
    one pserver process, two synchronized trainer processes."""
    import socket

    with socket.socket() as sk:
        sk.bind(("127.0.0.1", 0))
        port = sk.getsockname()[1]
    endpoint = "127.0.0.1:%d" % port
    env_base = {**os.environ, "PYTHONPATH": "/root/repo",
                "JAX_PLATFORMS": "cpu",
                "PSERVER_ENDPOINT": endpoint, "TRAINERS": "2"}

    ps = subprocess.Popen(
        [sys.executable, "-c", _DIST_SCRIPT],
        env={**env_base, "TRAINING_ROLE": "PSERVER"},
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
    assert ps.stdout.readline().strip() == "READY"

    trainers = [subprocess.Popen(
        [sys.executable, "-c", _DIST_SCRIPT],
        env={**env_base, "TRAINING_ROLE": "TRAINER",
             "TRAINER_ID": str(i)},
        stdout=subprocess.PIPE, text=True) for i in range(2)]
    rcs = [p.wait(timeout=240) for p in trainers]
    for p in trainers:
        print(p.stdout.read())
    ps.stdin.close()
    ps.wait(timeout=30)
    assert rcs == [0, 0], rcs


def test_async_sgd_convergence_and_staleness():
    """Async-SGD through the transpiler (reference:
    ParameterServer2.h asyncSGD:468): gradients apply immediately with
    no cross-trainer barrier, a staleness bound discards gradients
    computed against parameters >= N versions old
    (ParameterServer2.h:243), and training still converges."""
    server = native.ParameterServer(num_trainers=2, sync=False,
                                    async_lagged_threshold=4)
    try:
        endpoint = "127.0.0.1:%d" % server.port
        x, y, avg_cost, optimize_ops, params_grads = _build_fit_a_line()
        t = DistributeTranspiler()
        t.transpile(optimize_ops=optimize_ops, params_grads=params_grads,
                    pservers=endpoint, trainers=2, sync_mode=False)
        assert t.sync is False

        place = fluid.CPUPlace()
        exe = fluid.Executor(place)
        exe.run(fluid.default_startup_program())
        t.init_pservers()

        feeder = fluid.DataFeeder(place=place, feed_list=[x, y])
        reader = paddle.batch(paddle.dataset.uci_housing.train(),
                              batch_size=20)
        losses = []
        for _ in range(8):
            for data in reader():
                out, = exe.run(fluid.default_main_program(),
                               feed=feeder.feed(data),
                               fetch_list=[avg_cost])
                losses.append(float(np.asarray(out).reshape(-1)[0]))
        # async single-trainer traffic converges like sync
        assert losses[-1] < losses[0], (losses[0], losses[-1])
        assert server.num_updates() > 0
        assert server.num_lagged() == 0

        # deterministic staleness: a second client whose view of one
        # block is now 5+ versions behind gets its gradient discarded
        pname = next(iter(t.param_blocks))
        _ep, begin, size = t.param_blocks[pname][0]
        bname = "%s@%d" % (pname, begin)
        lagger = native.PServerClient("127.0.0.1", server.port)
        lagger.get_param(bname, size)          # records current version
        fresh = native.PServerClient("127.0.0.1", server.port)
        fresh.get_param(bname, size)
        for _ in range(5):                     # bump 5 versions
            fresh.send_grad(bname, np.zeros(size, np.float32))
        lagger.send_grad(bname, np.zeros(size, np.float32))
        assert not lagger.last_grad_applied    # discarded as stale
        assert server.num_lagged() >= 1
        # the stale trainer resynchronized: its next grad applies
        lagger.send_grad(bname, np.zeros(size, np.float32))
        assert lagger.last_grad_applied
        lagger.close()
        fresh.close()
    finally:
        ClientPool.reset()
        server.stop()


def test_lr_decay_warning():
    """An op writing the optimizer's LR var after transpile means the
    pserver's snapshotted LR goes stale — transpile must warn."""
    import warnings

    x, y, avg_cost, optimize_ops, params_grads = _build_fit_a_line()
    prog = fluid.default_main_program()
    block = prog.global_block()
    lr_name = optimize_ops[0].desc.input("LearningRate")[0]
    # simulate an LR-decay schedule: an op whose output is the LR var
    block.append_op(type="scale", inputs={"X": [block.var(lr_name)]},
                    outputs={"Out": [block.var(lr_name)]},
                    attrs={"scale": 0.9}, infer_shape=False)

    t = DistributeTranspiler()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        t.transpile(optimize_ops=optimize_ops, params_grads=params_grads,
                    pservers="127.0.0.1:6174", trainers=1)
    assert any("learning-rate" in str(w.message) for w in rec), \
        [str(w.message) for w in rec]
