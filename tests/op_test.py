"""Per-op test harness: output check + numeric-vs-analytic gradient check.

TPU-native equivalent of the reference OpTest base class
(reference: python/paddle/v2/fluid/tests/op_test.py:212 `OpTest`,
:97 `get_numeric_gradient`).  Differences by design:

  * the reference runs the raw op twice (CPUPlace/CUDAPlace) through the
    C++ Scope; here the op runs through the Program -> XLA pipeline on the
    test platform (virtual CPU devices), which is exactly the production
    path on TPU.
  * the numeric/analytic comparison is a Jacobian-vector-product check:
    loss = sum(w * out) for a fixed random w per checked output; analytic
    grads come from `calc_gradient` with w as the seed (reference seeds
    with ones via fill_constant), numeric grads from central differences
    of the same loss.  This checks the same quantity with a stronger
    (non-uniform) probe.

Input/output slot values accept the reference conventions:
  arr                      -> dense tensor
  (arr, lod)               -> ragged tensor (LoD offsets, reference format)
  [(name, arr), ...]       -> multi-variable slot (e.g. `sum`)
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.fluid.backward import calc_gradient
from paddle_tpu.core.ragged import RaggedTensor


def _as_ragged(arr, lod):
    return RaggedTensor(np.asarray(arr), [np.asarray(l, np.int64)
                                          for l in lod])


def _norm_slot(slot, val):
    """-> list of (var_name, feed_value, lod_level)."""
    if isinstance(val, list) and val and isinstance(val[0], tuple) \
            and isinstance(val[0][0], str):
        out = []
        for name, v in val:
            if isinstance(v, tuple):
                out.append((name, _as_ragged(v[0], v[1]), len(v[1])))
            else:
                out.append((name, np.asarray(v), 0))
        return out
    if isinstance(val, tuple):
        return [(slot, _as_ragged(val[0], val[1]), len(val[1]))]
    return [(slot, np.asarray(val), 0)]


def _np_dtype_str(arr):
    d = np.asarray(arr).dtype
    return str(d)


class OpTest:
    """Subclasses set: op_type, inputs, outputs, attrs (optional)."""

    op_type = None
    inputs = {}
    outputs = {}
    attrs = {}

    # -- program construction ------------------------------------------------

    def _build(self):
        """Fresh program with the single op; returns (prog, feeds,
        out_slot_to_names, in_entries)."""
        prog = framework.Program()
        block = prog.global_block()
        feeds = {}
        in_vars = {}
        in_entries = {}  # var name -> feed value
        for slot, val in self.inputs.items():
            entries = _norm_slot(slot, val)
            names = []
            for name, feed_val, lod_level in entries:
                vals = feed_val.values if isinstance(feed_val, RaggedTensor) \
                    else feed_val
                v = block.create_var(
                    name=name, shape=list(np.asarray(vals).shape),
                    dtype=_np_dtype_str(vals), lod_level=lod_level)
                v.stop_gradient = False
                feeds[name] = feed_val
                in_entries[name] = feed_val
                names.append(name)
            in_vars[slot] = [block.var(n) for n in names]
        out_vars = {}
        out_names = {}
        for slot, val in self.outputs.items():
            entries = _norm_slot(slot, val)
            vs = []
            for name, ref_val, lod_level in entries:
                vals = ref_val.values if isinstance(ref_val, RaggedTensor) \
                    else ref_val
                v = block.create_var(
                    name=name, shape=list(np.asarray(vals).shape),
                    dtype=_np_dtype_str(vals), lod_level=lod_level)
                vs.append(v)
            out_vars[slot] = vs
            out_names[slot] = [v.name for v in vs]
        block.append_op(type=self.op_type, inputs=in_vars,
                        outputs=out_vars, attrs=dict(self.attrs or {}))
        return prog, feeds, out_names, in_entries

    def _exe(self):
        return fluid.Executor(fluid.CPUPlace())

    # -- output check --------------------------------------------------------

    def check_output(self, atol=1e-5, rtol=1e-4, no_check_set=()):
        prog, feeds, out_names, _ = self._build()
        exe = self._exe()
        scope = fluid.Scope()
        flat_names, refs = [], []
        for slot, val in self.outputs.items():
            if slot in no_check_set:
                continue
            for (name, ref_val, _), n in zip(_norm_slot(slot, val),
                                             out_names[slot]):
                flat_names.append(n)
                refs.append(ref_val)
        results = exe.run(prog, feed=feeds, fetch_list=flat_names,
                          scope=scope, return_numpy=False)
        for name, ref, got in zip(flat_names, refs, results):
            if isinstance(ref, RaggedTensor):
                assert isinstance(got, RaggedTensor), \
                    "%s: expected ragged, got %r" % (name, type(got))
                n = int(np.asarray(ref.nvalid))
                np.testing.assert_allclose(
                    np.asarray(got.values)[:n], np.asarray(ref.values)[:n],
                    atol=atol, rtol=rtol,
                    err_msg="op %s output %s (values)" % (self.op_type, name))
                for i, (rs_ref, rs_got) in enumerate(
                        zip(ref.row_splits, got.row_splits)):
                    np.testing.assert_array_equal(
                        np.asarray(rs_got), np.asarray(rs_ref),
                        err_msg="op %s output %s lod level %d"
                        % (self.op_type, name, i))
            else:
                got = np.asarray(got)
                ref = np.asarray(ref)
                if ref.dtype.kind in "fc":
                    np.testing.assert_allclose(
                        got.astype(np.float64), ref.astype(np.float64),
                        atol=atol, rtol=rtol,
                        err_msg="op %s output %s" % (self.op_type, name))
                else:
                    np.testing.assert_array_equal(
                        got, ref,
                        err_msg="op %s output %s" % (self.op_type, name))

    # -- gradient check ------------------------------------------------------

    def check_grad(self, inputs_to_check, output_names,
                   max_relative_error=0.005, no_grad_set=None,
                   numeric_delta=None, atol=None):
        if isinstance(output_names, str):
            output_names = [output_names]
        if isinstance(inputs_to_check, str):
            inputs_to_check = [inputs_to_check]

        # map output *slot or var* names to var names
        prog, feeds, out_names, in_entries = self._build()
        block = prog.global_block()
        flat_out = []
        for want in output_names:
            if want in out_names:
                flat_out.extend(out_names[want])
            else:
                flat_out.append(want)

        # fixed probe weights per output
        rs = np.random.RandomState(2018)
        weights = {}
        for n in flat_out:
            ref = self._lookup_output_ref(n)
            vals = ref.values if isinstance(ref, RaggedTensor) else ref
            w = rs.uniform(0.5, 1.5, np.asarray(vals).shape)
            weights[n] = w.astype(np.asarray(vals).dtype)

        # resolve checked input var names (slot name or var name)
        check_names = []
        for want in inputs_to_check:
            if want in in_entries:
                check_names.append(want)
            else:
                for name, _, _ in _norm_slot(want, self.inputs[want]):
                    check_names.append(name)

        # analytic: seed each output grad with w (ragged outputs get a
        # ragged probe sharing the reference splits so the cotangent
        # pytree matches the primal's)
        wvars = []
        for n in flat_out:
            ref = self._lookup_output_ref(n)
            if isinstance(ref, RaggedTensor):
                probe = RaggedTensor(weights[n],
                                     [np.asarray(r) for r in
                                      ref.row_splits], ref.nvalid)
                lod_level = len(ref.row_splits)
            else:
                probe = weights[n]
                lod_level = 0
            wv = block.create_var(name=n + "@PROBE",
                                  shape=list(weights[n].shape),
                                  dtype=_np_dtype_str(weights[n]),
                                  lod_level=lod_level)
            wv.stop_gradient = True
            feeds[n + "@PROBE"] = probe
            wvars.append(wv)
        targets = [block.var(n) for n in flat_out]
        ngs = set(no_grad_set or ())
        grad_vars = calc_gradient(targets, [block.var(n)
                                            for n in check_names],
                                  target_gradients=wvars, no_grad_set=ngs)
        grad_names = [g.name if isinstance(g, framework.Variable) else g
                      for g in grad_vars]
        exe = self._exe()
        analytic = exe.run(prog, feed=feeds,
                           fetch_list=[g for g in grad_names if g],
                           scope=fluid.Scope(), return_numpy=False)
        analytic_by_name = {}
        it = iter(analytic)
        for cn, g in zip(check_names, grad_names):
            analytic_by_name[cn] = next(it) if g else None

        # numeric: central differences of loss = sum(w * out)
        fwd_prog, fwd_feeds, fwd_out_names, _ = self._build()
        fwd_exe = fluid.Executor(fluid.CPUPlace())
        fwd_scope = fluid.Scope()

        def loss_of(feed_map):
            outs = fwd_exe.run(fwd_prog, feed=feed_map, fetch_list=flat_out,
                               scope=fwd_scope, return_numpy=False,
                               use_program_cache=True)
            total = 0.0
            for n, o in zip(flat_out, outs):
                vals = o.values if isinstance(o, RaggedTensor) else o
                total += float(np.sum(np.asarray(vals, np.float64)
                                      * weights[n].astype(np.float64)))
            return total

        for cn in check_names:
            base = in_entries[cn]
            ragged = isinstance(base, RaggedTensor)
            base_vals = np.asarray(base.values if ragged else base,
                                   np.float64)
            delta = 1e-3 if numeric_delta is None else numeric_delta
            numeric = np.zeros_like(base_vals)
            flat = base_vals.reshape(-1)
            num_flat = numeric.reshape(-1)
            for i in range(flat.size):
                orig = flat[i]
                for sign in (+1.0, -1.0):
                    flat[i] = orig + sign * delta
                    pert = flat.reshape(base_vals.shape).astype(
                        np.asarray(base.values if ragged else base).dtype)
                    fm = dict(fwd_feeds)
                    fm[cn] = RaggedTensor(pert, [np.asarray(r) for r in
                                                 base.row_splits]) \
                        if ragged else pert
                    if sign > 0:
                        lp = loss_of(fm)
                    else:
                        lm = loss_of(fm)
                flat[i] = orig
                num_flat[i] = (lp - lm) / (2.0 * delta)

            a = analytic_by_name[cn]
            assert a is not None, "no analytic grad for %s" % cn
            a_vals = np.asarray(a.values if isinstance(a, RaggedTensor)
                                else a, np.float64)
            self._compare_grad(cn, a_vals, numeric, max_relative_error,
                               atol)

    def _compare_grad(self, name, analytic, numeric, max_rel, atol):
        analytic = analytic.reshape(numeric.shape)
        abs_a = np.abs(analytic)
        abs_n = np.abs(numeric)
        scale = np.maximum(np.maximum(abs_a, abs_n), 1e-3 if atol is None
                           else atol)
        rel = np.abs(analytic - numeric) / scale
        max_diff = rel.max() if rel.size else 0.0
        assert max_diff <= max_rel, (
            "op %s grad of %s: max relative error %g > %g\nanalytic=%s\n"
            "numeric=%s" % (self.op_type, name, max_diff, max_rel,
                            analytic.reshape(-1)[:16],
                            numeric.reshape(-1)[:16]))

    def _lookup_output_ref(self, var_name):
        for slot, val in self.outputs.items():
            for name, ref_val, _ in _norm_slot(slot, val):
                if name == var_name:
                    return ref_val
        raise KeyError(var_name)
