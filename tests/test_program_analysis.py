"""paddle_tpu.analysis: IR verifier, dataflow/hazard detection, TPU
lints, and their wiring (executor FLAGS_verify_program gate, io load
verification, serving warmup, memory-optimize delegation).

Negative tests corrupt real programs deliberately and assert the
STABLE diagnostic code (docs/ANALYSIS.md) — the contract the proglint
CLI selftest and CI enforce too."""

import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import analysis
from paddle_tpu.core.desc import BlockRef, OpDesc, VarDesc
from paddle_tpu.fluid import framework
from paddle_tpu.utils import flags


def _build_train(main=None, startup=None):
    """fc -> mse -> SGD in a fresh Program pair."""
    main = main or fluid.Program()
    startup = startup or fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return main, startup, loss


# ---------------------------------------------------------------------------
# verifier
# ---------------------------------------------------------------------------

def test_clean_program_verifies():
    main, startup, loss = _build_train()
    rep = analysis.check_program(main, fetches=[loss.name],
                                 publish=False)
    assert rep.ok(), rep.format()
    assert not rep.warnings, rep.format()
    srep = analysis.check_program(startup, publish=False)
    assert srep.ok(), srep.format()


def test_unknown_op_v001():
    main, _, _ = _build_train()
    main.desc.block(0).ops[1].type = "definitely_not_an_op"
    rep = analysis.verify_program(main, level="structural")
    assert rep.has("V001")
    d = [x for x in rep.errors if x.code == "V001"][0]
    assert d.op_index == 1 and d.op_type == "definitely_not_an_op"


def test_undeclared_var_v002():
    main, _, _ = _build_train()
    main.desc.block(0).ops[0].inputs["X"] = ["never_declared"]
    rep = analysis.verify_program(main, level="structural")
    assert rep.has("V002")
    assert any(d.var_name == "never_declared" for d in rep.errors)


def test_use_before_def_v003():
    main, _, loss = _build_train()
    ops = main.desc.block(0).ops
    idx = next(i for i, od in enumerate(ops)
               if loss.name in od.output_names())
    ops.insert(0, ops.pop(idx))  # hoist the mean above its producers
    rep = analysis.verify_program(main, level="structural")
    assert rep.has("V003"), rep.format()


def test_dangling_block_ref_v004():
    main, _, _ = _build_train()
    main.desc.block(0).ops[0].attrs["sub_block"] = BlockRef(42)
    rep = analysis.verify_program(main, level="structural")
    assert rep.has("V004")


def test_dtype_mismatch_v005():
    main, _, _ = _build_train()
    bd = main.desc.block(0)
    out = next(od.output_names()[0] for od in bd.ops
               if od.type == "mul")
    bd.vars[out].dtype = "int32"  # re-derivation says float32
    rep = analysis.verify_program(main, level="full")
    assert rep.has("V005"), rep.format()
    # structural level must NOT pay for (or catch) the re-derivation
    assert not analysis.verify_program(main,
                                       level="structural").has("V005")


def test_shape_mismatch_v006():
    main, _, _ = _build_train()
    bd = main.desc.block(0)
    out = next(od.output_names()[0] for od in bd.ops
               if od.type == "mul")
    bd.vars[out].shape = (-1, 7)  # fc emits (-1, 1)
    rep = analysis.verify_program(main, level="full")
    assert rep.has("V006"), rep.format()


def test_infer_shape_failure_v007():
    main, _, _ = _build_train()
    bd = main.desc.block(0)
    # break the matmul algebra itself: x becomes (-1, 5) against a
    # (13, 1) weight
    bd.vars["x"].shape = (-1, 5)
    rep = analysis.verify_program(main, level="full")
    assert rep.has("V007") or rep.has("V006"), rep.format()


def test_bad_attr_v008():
    main, _, _ = _build_train()
    main.desc.block(0).ops[0].attrs["hook"] = object()
    rep = analysis.verify_program(main, level="structural")
    assert rep.has("V008")


def test_inplace_first_writer_is_not_use_before_def():
    """increment(x, in_place=True) on a fed var makes the op both the
    first writer AND a reader of x — the by-name in-place idiom, legal
    when fed/scope-resident, must not be a V003."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[1], dtype="float32",
                              append_batch_size=False)
        from paddle_tpu.fluid.layers import tensor as tensor_layers

        tensor_layers.increment(x, value=1.0, in_place=True)
    rep = analysis.verify_program(main, level="structural")
    assert not rep.has("V003"), rep.format()


def test_lint_rng_seed_unknowable_on_bare_desc():
    """random_seed is Program state, not desc state: a round-tripped
    ProgramDesc must not produce L003 (the seed is unknowable, and a
    seeded program would be falsely flagged under --strict)."""
    from paddle_tpu.core.desc import ProgramDesc

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        fluid.layers.dropout(x=x, dropout_prob=0.5)
    main.random_seed = 1234
    assert not analysis.lint_program(main).has("L003")
    bare = ProgramDesc.from_dict(main.desc.to_dict())
    assert not analysis.lint_program(bare).has("L003")


# ---------------------------------------------------------------------------
# dataflow: dead code + hazards
# ---------------------------------------------------------------------------

def test_dead_op_d001_requires_fetches():
    main, _, loss = _build_train()
    bd = main.desc.block(0)
    bd.vars["__unused__"] = VarDesc("__unused__", dtype="float32",
                                    shape=(1,))
    bd.ops.append(OpDesc("scale", {"X": [loss.name]},
                         {"Out": ["__unused__"]}, {"scale": 1.0}))
    with_fetch = analysis.analyze_dataflow(main, fetches=[loss.name])
    assert with_fetch.has("D001")
    # fetch is a runtime by-name lookup: without the fetch set every
    # sink is presumed fetched, so no dead-op findings at all
    without = analysis.analyze_dataflow(main)
    assert not without.has("D001")


def test_dead_var_d002():
    main, _, _ = _build_train()
    main.desc.block(0).vars["__orphan__"] = VarDesc(
        "__orphan__", dtype="float32", shape=(4,))
    rep = analysis.analyze_dataflow(main)
    assert any(d.code == "D002" and d.var_name == "__orphan__"
               for d in rep.diagnostics)


def test_write_write_race_h001():
    main, _, _ = _build_train()
    bd = main.desc.block(0)
    i = next(i for i, od in enumerate(bd.ops) if od.type == "mul")
    od = bd.ops[i]
    bd.ops.insert(i + 1, OpDesc(od.type, dict(od.inputs),
                                dict(od.outputs), dict(od.attrs)))
    rep = analysis.analyze_dataflow(main)
    assert rep.has("H001")
    assert [d for d in rep.errors if d.code == "H001"], \
        "H001 must be error severity"


def test_inplace_alias_read_hazard_h002():
    main, _, _ = _build_train()
    bd = main.desc.block(0)
    param = next(n for n, vd in bd.vars.items() if vd.is_parameter)
    bd.vars["__shadow__"] = VarDesc("__shadow__", dtype="float32",
                                    shape=(13, 1))
    # an unordered reader of the in-place-updated parameter: nothing
    # orders it against the sgd write except list position
    bd.ops.insert(0, OpDesc("scale", {"X": [param]},
                            {"Out": ["__shadow__"]}, {"scale": 2.0}))
    rep = analysis.analyze_dataflow(main)
    assert rep.has("H002"), rep.format()
    # the clean program has NO such hazard (every Param reader feeds
    # the grad chain the sgd op consumes)
    clean, _, _ = _build_train()
    assert not analysis.analyze_dataflow(clean).has("H002")


def test_overwrite_read_race_h002_non_inplace():
    """write v -> read v -> rewrite v: the reader has no dataflow path
    to the rewrite, so a data-edge-only schedule can hand it the
    second value — the read-write half of the hazard detector, for
    writers that are NOT in-place."""
    main = fluid.Program()
    bd = main.desc.block(0)
    for n in ("c1", "c2", "v", "out", "out2"):
        bd.vars[n] = VarDesc(n, dtype="float32", shape=(4,))
    bd.ops.append(OpDesc("scale", {"X": ["c1"]}, {"Out": ["v"]},
                         {"scale": 1.0}))
    bd.ops.append(OpDesc("scale", {"X": ["v"]}, {"Out": ["out"]},
                         {"scale": 1.0}))
    bd.ops.append(OpDesc("scale", {"X": ["c2"]}, {"Out": ["v"]},
                         {"scale": 1.0}))
    bd.ops.append(OpDesc("scale", {"X": ["v"]}, {"Out": ["out2"]},
                         {"scale": 1.0}))
    rep = analysis.analyze_dataflow(main, fetches=["out", "out2"])
    assert any(d.code == "H002" and d.var_name == "v" and
               d.op_index == 2 for d in rep.diagnostics), rep.format()
    # no false H001: the read between the writes rules out lost-update
    assert not rep.has("H001"), rep.format()


def test_inplace_not_aliased_h003():
    main, _, _ = _build_train()
    bd = main.desc.block(0)
    sgd = next(od for od in bd.ops if od.type == "sgd")
    bd.vars["__forked__"] = VarDesc(
        "__forked__", dtype="float32",
        shape=bd.vars[sgd.input("Param")[0]].shape)
    sgd.outputs["ParamOut"] = ["__forked__"]  # update forks the state
    rep = analysis.analyze_dataflow(main)
    assert rep.has("H003"), rep.format()


def test_inplace_abbreviated_slot_h003():
    """ftrl's SquaredAccumOut aliases the SquaredAccumulator input —
    the abbreviated-slot convention must still map, so forking the
    accumulator state is caught."""
    main = fluid.Program()
    bd = main.desc.block(0)
    for n, shape in (("p", (4,)), ("g", (4,)), ("lr", (1,)),
                     ("sq", (4,)), ("lin", (4,)), ("sq_fork", (4,))):
        bd.vars[n] = VarDesc(n, dtype="float32", shape=shape,
                             persistable=(n != "g"))
    bd.ops.append(OpDesc(
        "ftrl",
        {"Param": ["p"], "Grad": ["g"], "LearningRate": ["lr"],
         "SquaredAccumulator": ["sq"], "LinearAccumulator": ["lin"]},
        {"ParamOut": ["p"], "SquaredAccumOut": ["sq_fork"],
         "LinearAccumOut": ["lin"]}, {}))
    rep = analysis.analyze_dataflow(main)
    assert any(d.code == "H003" and d.var_name == "sq_fork"
               for d in rep.diagnostics), rep.format()


def test_adam_beta_pow_known_hazard_and_suppression():
    """The Adam shared-scalar advance (scale beta_pow -> beta_pow after
    the update ops) is a KNOWN H002: only list order separates the
    adam reads from the in-place advance.  Safe on the current
    executor (ops lower in list order), documented in
    docs/ANALYSIS.md, and the suppression syntax handles it."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        loss = fluid.layers.mean(x=fluid.layers.fc(input=x, size=3))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    rep = analysis.check_program(main, publish=False)
    assert rep.has("H002") and rep.ok(), rep.format()
    sup = analysis.check_program(main, publish=False,
                                 suppress=("H002@scale",))
    assert not sup.has("H002") and sup.suppressed


# ---------------------------------------------------------------------------
# lints
# ---------------------------------------------------------------------------

def test_lint_dynamic_dim_l001_bucket_hints():
    main, _, _ = _build_train()
    plain = analysis.lint_program(main)
    hinted = analysis.lint_program(main,
                                   bucket_hints={"batch_buckets": [8]})
    finds = [d for d in plain.diagnostics if d.code == "L001"]
    assert finds and all(d.severity == "info" for d in finds)
    assert any("without shape buckets" in d.message for d in finds)
    assert all("bucketing covers it" in d.message
               for d in hinted.diagnostics if d.code == "L001")


def test_lint_rng_seed_l003():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        fluid.layers.dropout(x=x, dropout_prob=0.5)
    rep = analysis.lint_program(main)
    assert any(d.code == "L003" and d.op_type == "dropout"
               for d in rep.diagnostics)
    # seed plumbing silences it: program-level ...
    main.random_seed = 7
    assert not analysis.lint_program(main).has("L003")
    # ... or op-level
    main.random_seed = 0
    next(od for od in main.desc.block(0).ops
         if od.type == "dropout").attrs["fix_seed"] = True
    assert not analysis.lint_program(main).has("L003")
    # initializer idiom exempt: startup RNG writes persistable params
    _, startup2, _ = _build_train()
    assert not analysis.lint_program(startup2).has("L003")


def test_lint_amp_mix_l004():
    main = fluid.Program()
    bd = main.desc.block(0)
    bd.vars["a"] = VarDesc("a", dtype="bfloat16", shape=(4, 4))
    bd.vars["b"] = VarDesc("b", dtype="float32", shape=(4, 4))
    bd.vars["c"] = VarDesc("c", dtype="float32", shape=(4, 4))
    bd.ops.append(OpDesc("elementwise_add", {"X": ["a"], "Y": ["b"]},
                         {"Out": ["c"]}, {}))
    rep = analysis.lint_program(main)
    assert rep.has("L004")
    # persistable bf16 master
    main2 = fluid.Program()
    main2.desc.block(0).vars["w"] = VarDesc(
        "w", dtype="bfloat16", shape=(4,), persistable=True)
    assert analysis.lint_program(main2).has("L004")


def test_lint_grad_orphan_l005():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        loss = fluid.layers.mean(x=fluid.layers.fc(input=x, size=3))
        fluid.append_backward(loss)  # grads computed, never consumed
    rep = analysis.lint_program(main)
    orphans = [d for d in rep.diagnostics if d.code == "L005"]
    assert any("never applied" in d.message for d in orphans)
    # minimize() consumes them: clean
    clean, _, _ = _build_train()
    assert not analysis.lint_program(clean).has("L005")
    # declared-but-unreferenced grad debris
    main2 = fluid.Program()
    main2.desc.block(0).vars["v@GRAD"] = VarDesc(
        "v@GRAD", dtype="float32", shape=(4,))
    assert analysis.lint_program(main2).has("L005")


def test_lint_segment_split_l002():
    main = fluid.Program()
    bd = main.desc.block(0)
    for n in ("a", "b", "c"):
        bd.vars[n] = VarDesc(n, dtype="float32", shape=(4,))
    bd.ops.append(OpDesc("scale", {"X": ["a"]}, {"Out": ["b"]},
                         {"scale": 1.0}))
    bd.ops.append(OpDesc("print", {"X": ["b"]}, {"Out": ["b"]},
                         {"message": "mid"}))
    bd.ops.append(OpDesc("scale", {"X": ["b"]}, {"Out": ["c"]},
                         {"scale": 1.0}))
    rep = analysis.lint_program(main)
    assert any(d.code == "L002" and d.op_type == "print"
               for d in rep.diagnostics), rep.format()


# ---------------------------------------------------------------------------
# wiring: executor gate, io load, serving warmup
# ---------------------------------------------------------------------------

def test_executor_verify_gate_catches_before_compile():
    main, startup, loss = _build_train()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"x": np.zeros((2, 13), np.float32),
            "y": np.zeros((2, 1), np.float32)}
    prev = flags.get_flag("verify_program")
    flags.set_flag("verify_program", True)
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            out, = exe.run(main, feed=feed, fetch_list=[loss])
            assert np.isfinite(out).all()
            bad = main.clone()
            bad.desc.block(0).ops[2].type = "definitely_not_an_op"
            with pytest.raises(analysis.ProgramVerificationError) as ei:
                exe.run(bad, feed=feed, fetch_list=[loss])
            # the Diagnostic-derived error names op index + identity
            assert "op 2" in str(ei.value)
            first = ei.value.report.errors[0]
            assert first.op_index == 2 and first.block_idx == 0
    finally:
        flags.set_flag("verify_program", prev)


def test_io_load_verifies_program(tmp_path):
    main, startup, loss = _build_train()
    from paddle_tpu.fluid import io as fluid_io

    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid_io.save_inference_model(str(tmp_path), ["x", "y"],
                                      [loss], exe, main_program=main)
        # clean export loads (and re-verifies) fine
        prog, feeds, fetches = fluid_io.load_inference_model(
            str(tmp_path), exe)
        assert feeds == ["x", "y"]
        # tamper with the serialized IR: unknown op type
        path = os.path.join(str(tmp_path), "__model__")
        with open(path) as f:
            meta = json.load(f)
        meta["program"]["blocks"][0]["ops"][0]["type"] = "nope_op"
        with open(path, "w") as f:
            json.dump(meta, f)
        with pytest.raises(analysis.ProgramVerificationError):
            fluid_io.load_inference_model(str(tmp_path), exe)


def test_serving_warmup_verifies():
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid import io as fluid_io
    from paddle_tpu.obs import registry as obs_registry
    from paddle_tpu.serving import EngineConfig, InferenceEngine

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[8], dtype="float32")
        probs = fluid.layers.fc(input=img, size=3, act="softmax")
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    pruned = fluid_io.prune_program(main, [probs])

    engine = InferenceEngine(pruned, ["img"], [probs], scope=scope,
                             config=EngineConfig(batch_buckets=[2]))
    assert engine.warmup() == 1
    snap = {s["name"] for s in
            obs_registry.get_registry().to_dict()["metrics"]}
    assert "analysis_runs_total" in snap

    # corrupted program: warmup refuses before burning compiles
    bad = pruned.clone()
    bad.desc.block(0).ops[0].type = "definitely_not_an_op"
    engine2 = InferenceEngine(bad, ["img"], [probs], scope=scope,
                              config=EngineConfig(batch_buckets=[2]))
    with pytest.raises(analysis.ProgramVerificationError):
        engine2.warmup()
    # the analysis must run even when bucketing (and thus warmup
    # compiling) is disabled — exact-shape engines deploy the same
    # untrusted exports
    engine3 = InferenceEngine(bad, ["img"], [probs], scope=scope,
                              config=EngineConfig(batch_buckets=None))
    with pytest.raises(analysis.ProgramVerificationError):
        engine3.warmup()


# ---------------------------------------------------------------------------
# backward / transpiler outputs verify clean (mandatory under test)
# ---------------------------------------------------------------------------

def test_backward_output_verifies_clean():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[6], dtype="float32")
        h = fluid.layers.fc(input=x, size=4, act="relu")
        loss = fluid.layers.mean(x=fluid.layers.fc(input=h, size=1))
        fluid.append_backward(loss)
    rep = analysis.verify_program(main, level="full")
    assert rep.ok(), rep.format()
    assert not analysis.analyze_dataflow(main).errors


def test_transpiler_output_verifies_clean():
    from paddle_tpu.distributed.transpiler import DistributeTranspiler

    main = fluid.default_main_program()
    startup = fluid.default_startup_program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=pred, label=y))
        optimize_ops, params_grads = fluid.optimizer.SGD(
            learning_rate=0.01).minimize(loss)
        t = DistributeTranspiler()
        t.transpile(optimize_ops=optimize_ops,
                    params_grads=params_grads, trainer_id=0,
                    trainers=2, pservers="127.0.0.1:6174,127.0.0.1:6175")
    rep = analysis.check_program(main, publish=False)
    assert rep.ok(), rep.format()
    assert not rep.warnings, rep.format()


# ---------------------------------------------------------------------------
# dogfood: every golden builder + model topologies verify error-free
# ---------------------------------------------------------------------------

GOLDEN_BUILDERS = ["fit_a_line", "conv_classifier", "dynamic_rnn",
                   "deepfm"]


@pytest.mark.parametrize("case", GOLDEN_BUILDERS)
def test_dogfood_golden_builders(case):
    import test_golden_programs as golden

    builder = {
        "fit_a_line": golden._build_fit_a_line,
        "conv_classifier": golden._build_conv_classifier,
        "dynamic_rnn": golden._build_dynamic_rnn,
        "deepfm": golden._build_deepfm,
    }[case]
    builder()
    main = fluid.default_main_program()
    rep = analysis.check_program(main, publish=False)
    assert rep.ok(), "%s main:\n%s" % (case, rep.format())
    assert not rep.warnings, "%s main:\n%s" % (case, rep.format())
    srep = analysis.check_program(fluid.default_startup_program(),
                                  publish=False)
    assert srep.ok(), "%s startup:\n%s" % (case, srep.format())
    assert not srep.warnings, "%s startup:\n%s" % (case, srep.format())


def test_dogfood_model_builders():
    from paddle_tpu.models.image import lenet5

    img = fluid.layers.data(name="img", shape=[1, 28, 28],
                            dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    probs = lenet5(img, class_dim=10)
    loss = fluid.layers.mean(
        x=fluid.layers.cross_entropy(input=probs, label=label))
    fluid.optimizer.MomentumOptimizer(learning_rate=0.01,
                                      momentum=0.9).minimize(loss)
    rep = analysis.check_program(fluid.default_main_program(),
                                 fetches=[loss.name], publish=False)
    assert rep.ok(), rep.format()
    assert not rep.warnings, rep.format()


# ---------------------------------------------------------------------------
# memory-optimize delegation: identical reuse decisions
# ---------------------------------------------------------------------------

class _OriginalCFG:
    """The pre-refactor ControlFlowGraph, verbatim (liveness seeded
    empty, same fixpoint) — the regression oracle proving the
    analysis.dataflow delegation changed NOTHING about reuse."""

    def __init__(self, program):
        self._program = program
        block = program.global_block()
        self._ops = list(block.desc.ops)
        self._uses = [set(od.input_names()) - {"@EMPTY@"}
                      for od in self._ops]
        self._defs = [set(od.output_names()) - {"@EMPTY@"}
                      for od in self._ops]
        self._live_in = [set() for _ in self._ops]
        self._live_out = [set() for _ in self._ops]

    def analyze(self):
        changed = True
        n = len(self._ops)
        while changed:
            changed = False
            for i in reversed(range(n)):
                live_out = set()
                if i + 1 < n:
                    live_out = self._live_in[i + 1]
                live_in = self._uses[i] | (live_out - self._defs[i])
                if live_in != self._live_in[i] or \
                        live_out != self._live_out[i]:
                    self._live_in[i] = live_in
                    self._live_out[i] = live_out
                    changed = True
        return self

    def reuse_candidates(self):
        from collections import defaultdict

        persist = {n for n, v in
                   self._program.global_block().vars.items()
                   if getattr(v, "persistable", False)}
        released = defaultdict(list)
        for i in range(len(self._ops)):
            dead = (self._live_in[i] | self._defs[i]) - \
                self._live_out[i]
            for name in sorted(dead - persist):
                released[i].append(name)
        return dict(released)


def _build_mlp_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        h = x
        for _ in range(3):
            h = fluid.layers.fc(input=h, size=8, act="relu")
        out = fluid.layers.mean(x=h)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(out)
    return main, out


def test_memory_optimize_identical_reuse_decisions():
    from paddle_tpu.fluid import memory_optimization_transpiler as mot

    # two identical builds (per-program name counters make them agree)
    prog_a, out_a = _build_mlp_program()
    prog_b, out_b = _build_mlp_program()
    assert prog_a.desc.serialize_to_string() == \
        prog_b.desc.serialize_to_string()

    new_cfg = mot.ControlFlowGraph(prog_a).analyze()
    old_cfg = _OriginalCFG(prog_b).analyze()
    assert new_cfg._live_in == old_cfg._live_in
    assert new_cfg._live_out == old_cfg._live_out
    assert new_cfg.reuse_candidates() == old_cfg.reuse_candidates()

    # the full rewrite makes the SAME renames whichever liveness
    # implementation drives it
    renames_new = mot._rewrite_for_reuse(prog_a, new_cfg,
                                         {out_a.name})
    renames_old = mot._rewrite_for_reuse(prog_b, old_cfg,
                                         {out_b.name})
    assert renames_new == renames_old
    assert renames_new, "expected reuse in a 3-layer MLP"


def test_memory_optimized_program_verifies():
    """The rewrite's output is itself a verifier client: slot adoption
    must not manufacture use-before-def or hazards."""
    prog, out = _build_mlp_program()
    fluid.memory_optimize(prog, skip_opt_set=[out.name])
    rep = analysis.check_program(prog, publish=False)
    assert rep.ok(), rep.format()


# ---------------------------------------------------------------------------
# framework.InferShapeError identity
# ---------------------------------------------------------------------------

def test_infer_shape_error_names_op_and_var():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fluid.layers.data(name="x", shape=[4], dtype="float32")
        block = main.global_block()
        with pytest.raises(framework.InferShapeError) as ei:
            block.append_op(type="mul",
                            inputs={"X": ["x"], "Y": ["missing_w"]},
                            outputs={"Out": ["z"]})
    err = ei.value
    assert err.op_type == "mul"
    assert err.op_index is not None and err.block_idx == 0
    assert err.var_name == "missing_w"
    assert "mul" in str(err) and "missing_w" in str(err)


def test_infer_shape_error_on_bad_algebra():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = fluid.layers.data(name="a", shape=[3, 4], dtype="float32",
                              append_batch_size=False)
        b = fluid.layers.data(name="b", shape=[5, 6], dtype="float32",
                              append_batch_size=False)
        block = main.global_block()
        block.create_var(name="z", dtype="float32", shape=(3, 6))
        with pytest.raises(framework.InferShapeError) as ei:
            block.append_op(type="mul",
                            inputs={"X": [a], "Y": [b]},
                            outputs={"Out": ["z"]})
    assert ei.value.op_type == "mul"
    assert "mul" in str(ei.value)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_lint_cli_selftest_inprocess(capsys):
    from paddle_tpu.tools import lint_cli

    assert lint_cli.main(["--selftest"]) == 0
    assert "selftest green" in capsys.readouterr().out


def test_lint_cli_golden(capsys):
    from paddle_tpu.tools import lint_cli

    assert lint_cli.main(["--golden", "--quiet"]) == 0
    out = capsys.readouterr().out
    assert "0 error(s)" in out and "transformer.json" in out
    # --json over the fixture set is ONE parseable document
    assert lint_cli.main(["--golden", "--json"]) == 0
    docs = json.loads(capsys.readouterr().out)
    assert isinstance(docs, list) and len(docs) >= 5
    assert all(d["errors"] == 0 for d in docs)


def test_lint_cli_model_dir(tmp_path, capsys):
    from paddle_tpu.fluid import io as fluid_io
    from paddle_tpu.tools import lint_cli

    main, startup, loss = _build_train()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid_io.save_inference_model(
            str(tmp_path), ["x", "y"], [loss], exe, main_program=main,
            bucket_hints={"batch_buckets": [1, 8]})
    assert lint_cli.main([str(tmp_path), "--quiet"]) == 0
    # the export carries no training-tail debris: prune drops
    # unreferenced VarDescs, so no grad-orphan/dead-var findings
    assert "0 warning(s)" in capsys.readouterr().out
    # corrupt it: exit code goes red and the code is printed
    path = os.path.join(str(tmp_path), "__model__")
    with open(path) as f:
        meta = json.load(f)
    meta["program"]["blocks"][0]["ops"][0]["type"] = "nope_op"
    with open(path, "w") as f:
        json.dump(meta, f)
    capsys.readouterr()
    assert lint_cli.main([str(tmp_path), "--quiet"]) == 1
    assert "V001" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# report plumbing
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# liveness over while/sub-block programs (cross-block reads must pin
# variables live in the parent; sub-block liveness seeds from closures)
# ---------------------------------------------------------------------------

def _build_while_program():
    """A counter while-loop whose body reads a block-0 temp (closure)
    and accumulates into a carried var; returns (main, loss, names)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[1, 4], dtype="float32",
                              append_batch_size=False)
        # a block-0 temp read ONLY inside the while body: without the
        # cross-block live seed this op would be a false D001
        bridge = fluid.layers.scale(x=x, scale=2.0)
        acc = fluid.layers.fill_constant(shape=[1, 4],
                                         dtype="float32", value=0.0)
        i = fluid.layers.fill_constant(shape=[1], dtype="int64",
                                       value=0)
        limit = fluid.layers.fill_constant(shape=[1], dtype="int64",
                                           value=3)
        cond = fluid.layers.less_than(x=i, y=limit)
        w = fluid.layers.While(cond=cond, max_steps=8)
        with w.block():
            fluid.layers.sums(input=[acc, bridge], out=acc)
            fluid.layers.increment(x=i, value=1, in_place=True)
            fluid.layers.less_than(x=i, y=limit, cond=cond)
        loss = fluid.layers.mean(x=acc)
    return main, loss, {"bridge": bridge.name, "acc": acc.name}


def test_while_program_analyzes_clean():
    main, loss, _names = _build_while_program()
    rep = analysis.check_program(main, fetches=[loss.name],
                                 publish=False)
    assert rep.ok(), rep.format()
    # L003 must not fire either: nothing draws RNG
    assert not rep.has("L003"), rep.format()


def test_while_crossblock_read_is_not_dead():
    """The op computing a temp consumed only by the while body must
    not be a D001, and the temp not a D002 — sub-block reads pin it."""
    main, loss, names = _build_while_program()
    rep = analysis.analyze_dataflow(main, fetches=[loss.name])
    flagged = {d.var_name for d in rep.diagnostics
               if d.code in ("D001", "D002")}
    assert names["bridge"] not in flagged, rep.format()


def test_while_subblock_liveness_seeds_from_closures():
    from paddle_tpu.analysis.dataflow import (Liveness,
                                              _block_sub_reads)

    main, loss, names = _build_while_program()
    desc = main.desc
    sub_idx = next(i for i in range(len(desc.blocks)) if i > 0
                   and desc.block(i).ops)
    sub = desc.block(sub_idx)
    # carried/closure names (read by block 0 after the loop) seed the
    # final live set of the body
    cross = _block_sub_reads(desc, sub_idx)
    lv = Liveness(sub.ops, final_live=cross).analyze()
    # the accumulator is written by the body AND read next iteration /
    # after the loop: it must be live out of the body's last op
    assert names["acc"] in lv.live_out[len(sub.ops) - 1]
    # nothing the body carries may show up as releasable
    released = {n for ns in lv.reuse_candidates().values() for n in ns}
    assert names["acc"] not in released
    assert names["bridge"] not in released


def test_while_subblock_internal_temp_releases():
    """A temp local to the while body (not carried, not a closure)
    dies inside the body — the liveness the memory optimizer consumes
    must release it for reuse."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[1, 4], dtype="float32",
                              append_batch_size=False)
        acc = fluid.layers.fill_constant(shape=[1, 4],
                                         dtype="float32", value=0.0)
        i = fluid.layers.fill_constant(shape=[1], dtype="int64",
                                       value=0)
        limit = fluid.layers.fill_constant(shape=[1], dtype="int64",
                                           value=3)
        cond = fluid.layers.less_than(x=i, y=limit)
        w = fluid.layers.While(cond=cond, max_steps=8)
        with w.block():
            t = fluid.layers.scale(x=x, scale=3.0)   # body-local temp
            t2 = fluid.layers.scale(x=t, scale=0.5)  # t dies here
            fluid.layers.sums(input=[acc, t2], out=acc)
            fluid.layers.increment(x=i, value=1, in_place=True)
            fluid.layers.less_than(x=i, y=limit, cond=cond)
        fluid.layers.mean(x=acc)

    from paddle_tpu.analysis.dataflow import (Liveness,
                                              _block_sub_reads)

    desc = main.desc
    sub_idx = next(i for i in range(len(desc.blocks)) if i > 0
                   and desc.block(i).ops)
    sub = desc.block(sub_idx)
    lv = Liveness(sub.ops,
                  final_live=_block_sub_reads(desc, sub_idx)).analyze()
    released = {n for ns in lv.reuse_candidates().values() for n in ns}
    assert t.name in released, (t.name, released)
    assert acc.name not in released


def test_memory_optimize_while_program_still_verifies():
    """fluid.memory_optimize shares THE liveness engine; after buffer
    reuse rewrites a while program, the result must still verify
    clean and execute to the same value."""
    main, loss, _names = _build_while_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        feed = {"x": np.ones((1, 4), np.float32)}
        (before,) = exe.run(main, feed=feed, fetch_list=[loss.name])
    fluid.memory_optimize(main)
    rep = analysis.check_program(main, fetches=[loss.name],
                                 publish=False)
    assert rep.ok(), rep.format()
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe.run(fluid.default_startup_program())
        (after,) = exe.run(main, feed={"x": np.ones((1, 4),
                                                    np.float32)},
                           fetch_list=[loss.name])
    np.testing.assert_allclose(np.asarray(before), np.asarray(after))


def test_report_counters_published():
    from paddle_tpu.obs import registry as obs_registry

    main, _, _ = _build_train()
    main.desc.block(0).ops[1].type = "definitely_not_an_op"
    analysis.check_program(main, origin="test")
    reg = obs_registry.get_registry()
    fam = reg.counter("analysis_diagnostics_total",
                      labelnames=("code", "severity"))
    assert fam.labels(code="V001", severity="error").value >= 1
    runs = reg.counter("analysis_runs_total", labelnames=("origin",))
    assert runs.labels(origin="test").value == 1


def test_suppression_variants():
    main, _, _ = _build_train()
    main.desc.block(0).ops[1].type = "definitely_not_an_op"
    by_code = analysis.verify_program(main, suppress=("V001",),
                                      level="structural")
    assert not by_code.has("V001") and by_code.suppressed
    by_op = analysis.verify_program(
        main, suppress=("V001@definitely_not_an_op",),
        level="structural")
    assert not by_op.has("V001")
    unrelated = analysis.verify_program(main, suppress=("V001@other",),
                                        level="structural")
    assert unrelated.has("V001")
