"""Inference export pruning, incl. sub-block models (reference:
framework/prune.cc recursion + io.py save/load_inference_model)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import io as fluid_io


def _build_rnn_classifier():
    """A model whose forward pass crosses a DynamicRNN sub-block and
    whose training tail (loss/optimizer) must prune away."""
    x = fluid.layers.data(name="x", shape=[4], dtype="float32",
                          lod_level=1)
    drnn = fluid.layers.DynamicRNN()
    with drnn.block():
        step = drnn.step_input(x)
        mem = drnn.memory(shape=[6], batch_ref=step, value=0.0)
        h = fluid.layers.fc(input=[step, mem], size=6, act="tanh")
        drnn.update_memory(mem, h)
        drnn.output(h)
    seq = drnn()
    last = fluid.layers.sequence_last_step(input=seq)
    logits = fluid.layers.fc(input=last, size=3, act="softmax")
    label = fluid.layers.data(name="y", shape=[1], dtype="int64")
    loss = fluid.layers.mean(
        x=fluid.layers.cross_entropy(input=logits, label=label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return x, logits, loss


def _feed(place, x):
    rs = np.random.RandomState(0)
    seqs = [rs.rand(3, 4).tolist(), rs.rand(2, 4).tolist()]
    feeder = fluid.DataFeeder(feed_list=[x], place=place)
    return feeder.feed([(s,) for s in seqs])


def test_prune_keeps_subblock_graph(tmp_path):
    x, logits, loss = _build_rnn_classifier()
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    feeds = _feed(place, x)
    # save BEFORE the reference run: running the full program would
    # also apply the SGD update and change the weights being saved
    fluid_io.save_inference_model(str(tmp_path), ["x"], [logits], exe)
    full_feeds = dict(feeds)
    full_feeds["y"] = np.zeros((2, 1), np.int64)
    want, = exe.run(fluid.default_main_program(), feed=full_feeds,
                    fetch_list=[logits])

    # pruned program must drop the training tail but keep the rnn
    pruned = fluid_io.prune_program(fluid.default_main_program(),
                                    [logits])
    types = [op.type for op in pruned.desc.block(0).ops]
    assert "recurrent" in types or "while" in types, types
    assert not any("grad" in t or t == "sgd" for t in types), types

    # a fresh scope + reload runs the sub-block end to end
    from paddle_tpu.core import scope as scope_mod

    scope_mod._global_scope = scope_mod.Scope()
    prog, feed_names, fetch_vars = fluid_io.load_inference_model(
        str(tmp_path), exe)
    got, = exe.run(prog, feed=feeds, fetch_list=fetch_vars)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_prune_rejects_subblock_target():
    x, logits, loss = _build_rnn_classifier()
    prog = fluid.default_main_program()
    # find a var that lives only inside the rnn sub-block
    sub_names = set(prog.desc.block(1).vars) - set(prog.desc.block(0).vars)
    inner = sorted(sub_names)[0]
    with pytest.raises(ValueError, match="block-0"):
        fluid_io.prune_program(prog, [inner])


def test_prune_rejects_feed_target():
    x, logits, loss = _build_rnn_classifier()
    with pytest.raises(ValueError, match="produced by no op"):
        fluid_io.prune_program(fluid.default_main_program(), [x])
