"""Op tests: mul/matmul, elementwise family, reductions, norms.

Parity: reference tests test_mul_op.py, test_elementwise_*_op.py,
test_reduce_op.py, test_mean_op.py, test_sum_op.py, test_cos_sim_op.py,
test_squared_l2_norm_op.py, test_l1_norm_op.py, test_minus_op.py,
test_scale_op.py, test_sign_op.py, test_clip_op.py.
"""

import numpy as np
import pytest

from op_test import OpTest

RS = np.random.RandomState(123)


class TestMulOp(OpTest):
    op_type = "mul"

    def test(self):
        x = RS.rand(3, 4).astype("float32")
        y = RS.rand(4, 5).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": np.dot(x, y)}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestMulOpFlatten(OpTest):
    """num_col_dims flattening (reference: mul_op.cc x_num_col_dims)."""
    op_type = "mul"

    def test(self):
        x = RS.rand(2, 3, 4).astype("float32")
        y = RS.rand(4, 5).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"x_num_col_dims": 2}
        self.outputs = {"Out": np.dot(x.reshape(6, 4), y).reshape(2, 3, 5)}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestMatmulTranspose(OpTest):
    op_type = "matmul"

    def test(self):
        x = RS.rand(4, 3).astype("float32")
        y = RS.rand(5, 4).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_X": True, "transpose_Y": True}
        self.outputs = {"Out": np.dot(x.T, y.T)}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestMatmulBatched(OpTest):
    op_type = "matmul"

    def test(self):
        x = RS.rand(2, 3, 4).astype("float32")
        y = RS.rand(2, 4, 5).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": np.matmul(x, y)}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


def _ew_case(op_type, np_fn, grad_ok=True, max_rel=0.005):
    class _T(OpTest):
        def test(self):
            self.op_type = op_type
            x = RS.rand(3, 4).astype("float32") + 0.5
            y = RS.rand(3, 4).astype("float32") + 0.5
            self.inputs = {"X": x, "Y": y}
            self.outputs = {"Out": np_fn(x, y)}
            self.check_output()
            if grad_ok:
                self.check_grad(["X", "Y"], "Out",
                                max_relative_error=max_rel)
    return _T


TestEwAdd = _ew_case("elementwise_add", np.add)
TestEwSub = _ew_case("elementwise_sub", np.subtract)
TestEwMul = _ew_case("elementwise_mul", np.multiply)
TestEwDiv = _ew_case("elementwise_div", np.divide)
TestEwMax = _ew_case("elementwise_max", np.maximum)
TestEwMin = _ew_case("elementwise_min", np.minimum)
# pow's log-term grads amplify float32 central-difference noise
TestEwPow = _ew_case("elementwise_pow", np.power, max_rel=0.05)


class TestEwAddBroadcastAxis(OpTest):
    """Y broadcast into X at axis (reference: elementwise_op_function.h)."""
    op_type = "elementwise_add"

    def test(self):
        x = RS.rand(2, 3, 4).astype("float32")
        y = RS.rand(3,).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x + y.reshape(1, 3, 1)}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestReduceSum(OpTest):
    op_type = "reduce_sum"

    def test(self):
        x = RS.rand(3, 4, 5).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"dim": 1, "keep_dim": False}
        self.outputs = {"Out": x.sum(axis=1)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestReduceMeanKeepdim(OpTest):
    op_type = "reduce_mean"

    def test(self):
        x = RS.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"dim": 0, "keep_dim": True}
        self.outputs = {"Out": x.mean(axis=0, keepdims=True)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestReduceMax(OpTest):
    op_type = "reduce_max"

    def test(self):
        x = RS.rand(5, 6).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"dim": 1}
        self.outputs = {"Out": x.max(axis=1)}
        self.check_output()


class TestReduceAll(OpTest):
    op_type = "reduce_sum"

    def test(self):
        x = RS.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"reduce_all": True}
        self.outputs = {"Out": np.asarray(x.sum())}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestMean(OpTest):
    op_type = "mean"

    def test(self):
        x = RS.rand(4, 5).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": np.asarray(x.mean())}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSum(OpTest):
    op_type = "sum"

    def test(self):
        xs = [("x%d" % i, RS.rand(3, 4).astype("float32"))
              for i in range(3)]
        self.inputs = {"X": xs}
        self.outputs = {"Out": sum(a for _, a in xs)}
        self.check_output()
        self.check_grad(["x0", "x1"], "Out")


class TestMinus(OpTest):
    op_type = "minus"

    def test(self):
        x = RS.rand(3, 4).astype("float32")
        y = RS.rand(3, 4).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x - y}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestScale(OpTest):
    op_type = "scale"

    def test(self):
        x = RS.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"scale": 2.5}
        self.outputs = {"Out": 2.5 * x}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSign(OpTest):
    op_type = "sign"

    def test(self):
        x = (RS.rand(3, 4).astype("float32") - 0.5)
        self.inputs = {"X": x}
        self.outputs = {"Out": np.sign(x)}
        self.check_output()


class TestClip(OpTest):
    op_type = "clip"

    def test(self):
        x = RS.uniform(-1, 1, (4, 4)).astype("float32")
        # keep elements away from the clip boundary for the numeric check
        x[np.abs(np.abs(x) - 0.5) < 0.05] = 0.0
        self.inputs = {"X": x}
        self.attrs = {"min": -0.5, "max": 0.5}
        self.outputs = {"Out": np.clip(x, -0.5, 0.5)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestClipByNorm(OpTest):
    op_type = "clip_by_norm"

    def test(self):
        x = RS.rand(4, 4).astype("float32")
        norm = np.sqrt((x ** 2).sum())
        self.inputs = {"X": x}
        self.attrs = {"max_norm": 0.5}
        self.outputs = {"Out": x * (0.5 / max(norm, 0.5))}
        self.check_output()


class TestSquaredL2Norm(OpTest):
    op_type = "squared_l2_norm"

    def test(self):
        x = RS.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": np.asarray((x ** 2).sum())}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestL1Norm(OpTest):
    op_type = "l1_norm"

    def test(self):
        x = RS.uniform(0.2, 1, (3, 4)).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": np.asarray(np.abs(x).sum())}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSquaredL2Distance(OpTest):
    op_type = "squared_l2_distance"

    def test(self):
        x = RS.rand(4, 3).astype("float32")
        y = RS.rand(4, 3).astype("float32")
        d = x - y
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": (d ** 2).sum(axis=1, keepdims=True),
                        "sub_result": d}
        self.check_output(no_check_set=("sub_result",))
        self.check_grad(["X", "Y"], "Out")


class TestCosSim(OpTest):
    op_type = "cos_sim"

    def test(self):
        x = RS.rand(4, 5).astype("float32") + 0.1
        y = RS.rand(4, 5).astype("float32") + 0.1
        num = (x * y).sum(axis=1)
        xn = np.sqrt((x * x).sum(axis=1))
        yn = np.sqrt((y * y).sum(axis=1))
        out = (num / xn / yn).reshape(-1, 1)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": out, "XNorm": xn.reshape(-1, 1),
                        "YNorm": yn.reshape(-1, 1)}
        self.check_output(no_check_set=("XNorm", "YNorm"))
        self.check_grad(["X", "Y"], "Out", max_relative_error=0.05)


class TestCompareOps(OpTest):
    def test(self):
        x = RS.randint(0, 3, (4, 4)).astype("float32")
        y = RS.randint(0, 3, (4, 4)).astype("float32")
        for op, fn in [("less_than", np.less), ("less_equal", np.less_equal),
                       ("greater_than", np.greater),
                       ("greater_equal", np.greater_equal),
                       ("equal", np.equal), ("not_equal", np.not_equal)]:
            self.op_type = op
            self.inputs = {"X": x, "Y": y}
            self.outputs = {"Out": fn(x, y)}
            self.check_output()


class TestLogicalOps(OpTest):
    def test(self):
        x = RS.rand(4, 4) > 0.5
        y = RS.rand(4, 4) > 0.5
        for op, fn in [("logical_and", np.logical_and),
                       ("logical_or", np.logical_or),
                       ("logical_xor", np.logical_xor)]:
            self.op_type = op
            self.inputs = {"X": x, "Y": y}
            self.outputs = {"Out": fn(x, y)}
            self.check_output()
        self.op_type = "logical_not"
        self.inputs = {"X": x}
        self.outputs = {"Out": np.logical_not(x)}
        self.check_output()


class TestCast(OpTest):
    op_type = "cast"

    def test(self):
        x = RS.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"out_dtype": "int32"}
        self.outputs = {"Out": x.astype("int32")}
        self.check_output()


def test_ragged_reductions_mask_bucket_padding():
    """Reductions crossing the ragged row axis count VALID rows only:
    the feeder's bucket padding must not leak into sums/means/maxes
    (same contract as the loss `mean`)."""
    import paddle_tpu.fluid as fluid

    x = fluid.layers.data(name="xr", shape=[2], dtype="float32",
                          lod_level=1)
    fetches = [fluid.layers.reduce_sum(x),
               fluid.layers.reduce_mean(x),
               fluid.layers.reduce_max(x),
               fluid.layers.reduce_min(x),
               fluid.layers.reduce_sum(x, dim=0)]
    exe = fluid.Executor(fluid.CPUPlace())
    feeder = fluid.DataFeeder(place=fluid.CPUPlace(), feed_list=[x])
    # all-negative max / all-positive min: unmasked ZERO padding rows
    # would win either reduction, so these assertions probe the fill
    feed = feeder.feed([([[-1, -2], [-3, -4]],), ([[-5, -6]],)])
    s, m, mx, mn, s0 = exe.run(fluid.default_main_program(), feed=feed,
                               fetch_list=fetches)
    assert np.isclose(np.asarray(s).reshape(()), -21.0)
    assert np.isclose(np.asarray(m).reshape(()), -3.5)
    assert np.isclose(np.asarray(mx).reshape(()), -1.0)
    assert np.isclose(np.asarray(mn).reshape(()), -6.0)
    np.testing.assert_allclose(np.asarray(s0), [-9.0, -12.0])

    xp = fluid.layers.data(name="xp", shape=[1], dtype="float32",
                           lod_level=1)
    mn_pos = fluid.layers.reduce_min(xp)
    feedp = dict(feed)
    feedp.update(fluid.DataFeeder(
        place=fluid.CPUPlace(),
        feed_list=[xp]).feed([([[2.0], [7.0]],)]))
    got, = exe.run(fluid.default_main_program(), feed=feedp,
                   fetch_list=[mn_pos])
    assert np.isclose(np.asarray(got).reshape(()), 2.0)
