"""KV-cached transformer decoding (cached_attention op).

The cached step program re-uses the scope trained by the full training
program (per-program name scopes align the parameters), and its O(1)
per-token attention must agree with the full causal forward: after
greedy generation through `fluid.ProgramDecoder`, every generated
token equals the argmax of the training program's logits at the
corresponding position of the final sequence (teacher-forced check —
if the cache scattered or masked wrongly, the trajectories diverge).
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.executor import scope_guard, global_scope
from paddle_tpu.core.scope import Scope
from paddle_tpu.models.transformer_program import (
    build_transformer_program, build_transformer_cached_step_program,
    transformer_program_feeds)

B, T, V, L, H, D = 4, 16, 32, 2, 2, 16


def _train(steps=6):
    main, startup, avg_loss, _ = build_transformer_program(
        B, T, V, n_layer=L, n_head=H, d_model=D)
    with fluid.program_guard(main, startup):
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(avg_loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    for i in range(steps):
        exe.run(main, feed=transformer_program_feeds(B, T, V, seed=i),
                fetch_list=[avg_loss])
    return exe


def test_cached_decode_matches_full_forward():
    with scope_guard(Scope()):
        exe = _train()

        step_prog, _, logits, state_pairs = \
            build_transformer_cached_step_program(
                B, T, V, n_layer=L, n_head=H, d_model=D)
        dec = fluid.ProgramDecoder(
            step_prog.clone(for_test=True), token_name="tok",
            logits_name=logits.name, state_pairs=state_pairs)

        bos, gen_len = 3, 8
        d_head = D // H
        init = {"pos": np.zeros((B,), np.int64)}
        for i in range(L):
            init["k_cache_%d" % i] = np.zeros((B, H, T, d_head),
                                              np.float32)
            init["v_cache_%d" % i] = np.zeros((B, H, T, d_head),
                                              np.float32)
        toks, _ = dec.greedy(bos=bos, eos=V + 1, max_len=gen_len,
                             batch_size=B, init_state=init)
        assert toks.shape == (B, gen_len)

        # teacher-forced check against the FULL training program: at
        # position t the causal forward of [bos, toks[:-1]] must argmax
        # to toks[t]
        full = np.concatenate(
            [np.full((B, 1), bos, np.int64), toks[:, :-1]], axis=1)
        pad = np.zeros((B, T - full.shape[1]), np.int64)
        tokens = np.concatenate([full, pad], axis=1)
        infer_main, _, _, full_logits = build_transformer_program(
            B, T, V, n_layer=L, n_head=H, d_model=D)
        got_logits, = exe.run(
            infer_main.clone(for_test=True),
            feed={"tokens": tokens,
                  "positions": transformer_program_feeds(
                      B, T, V)["positions"],
                  "targets": np.zeros((B, T, 1), np.int64)},
            fetch_list=[full_logits])
        got_logits = np.asarray(got_logits)
        for t in range(gen_len):
            want = np.argmax(got_logits[:, t, :], axis=-1)
            np.testing.assert_array_equal(toks[:, t], want,
                                          err_msg="position %d" % t)

        # beam over the cached program: state expansion repeats the
        # per-row pos/caches; beam(1) equals greedy
        seqs, scores = dec.beam(beam_size=1, bos=bos, eos=V + 1,
                                max_len=gen_len, batch_size=B,
                                init_state=init)
        np.testing.assert_array_equal(seqs[:, 0, :], toks)
        assert np.all(np.isfinite(scores))


def test_cached_prefill_continuation_matches_full_forward():
    """Prompt prefill: warm the caches with a prompt in one scan, then
    generate — every token (incl. the first, predicted from the prompt)
    must match the full causal forward teacher-forced on the combined
    sequence."""
    with scope_guard(Scope()):
        exe = _train()

        step_prog, _, logits, state_pairs = \
            build_transformer_cached_step_program(
                B, T, V, n_layer=L, n_head=H, d_model=D)
        dec = fluid.ProgramDecoder(
            step_prog.clone(for_test=True), token_name="tok",
            logits_name=logits.name, state_pairs=state_pairs,
            max_positions=T)

        P, gen_len = 5, 6
        d_head = D // H
        rs = np.random.RandomState(7)
        prompt = rs.randint(0, V, size=(B, P)).astype(np.int64)
        init = {"pos": np.zeros((B,), np.int64)}
        for i in range(L):
            init["k_cache_%d" % i] = np.zeros((B, H, T, d_head),
                                              np.float32)
            init["v_cache_%d" % i] = np.zeros((B, H, T, d_head),
                                              np.float32)
        toks, _ = dec.greedy(bos=0, eos=V + 1, max_len=gen_len,
                             batch_size=B, init_state=init,
                             prompt=prompt)
        assert toks.shape == (B, gen_len)

        # overrunning the cache extent is an error, not silent clamping
        import pytest
        with pytest.raises(ValueError, match="extent"):
            dec.greedy(bos=0, eos=V + 1, max_len=T + 2, batch_size=B,
                       init_state=init, prompt=prompt)

        # prompted sampling at near-zero temperature reproduces the
        # prompted greedy trajectory through the same caches
        cold, _ = dec.sample(bos=0, eos=V + 1, max_len=gen_len,
                             batch_size=B, init_state=init,
                             prompt=prompt, temperature=1e-5)
        np.testing.assert_array_equal(cold, toks)

        # max_len=1: just the prompt's single continuation token
        one, one_len = dec.greedy(bos=0, eos=V + 1, max_len=1,
                                  batch_size=B, init_state=init,
                                  prompt=prompt)
        np.testing.assert_array_equal(one[:, 0], toks[:, 0])
        assert one.shape == (B, 1)

        # empty prompts are rejected up front
        with pytest.raises(ValueError, match="P>=1"):
            dec.greedy(bos=0, eos=V + 1, max_len=2, batch_size=B,
                       init_state=init,
                       prompt=np.zeros((B, 0), np.int64))

        # teacher-forced: full forward over [prompt, toks[:-1]]; the
        # argmax at positions P-1 .. P+gen_len-2 must reproduce toks
        seq = np.concatenate([prompt, toks[:, :-1]], axis=1)
        tokens = np.concatenate(
            [seq, np.zeros((B, T - seq.shape[1]), np.int64)], axis=1)
        infer_main, _, _, full_logits = build_transformer_program(
            B, T, V, n_layer=L, n_head=H, d_model=D)
        got_logits, = exe.run(
            infer_main.clone(for_test=True),
            feed={"tokens": tokens,
                  "positions": transformer_program_feeds(
                      B, T, V)["positions"],
                  "targets": np.zeros((B, T, 1), np.int64)},
            fetch_list=[full_logits])
        got_logits = np.asarray(got_logits)
        for t in range(gen_len):
            want = np.argmax(got_logits[:, P - 1 + t, :], axis=-1)
            np.testing.assert_array_equal(toks[:, t], want,
                                          err_msg="position %d" % t)


def test_cached_attention_op_matches_dense_reference():
    """Direct op check: running the cache step T times equals dense
    causal attention over the same sequence."""
    import jax.numpy as jnp
    from paddle_tpu.ops.registry import get_op_info
    from paddle_tpu.kernels.flash_attention import reference_attention

    rs = np.random.RandomState(0)
    b, h, t, dh = 2, 2, 6, 4
    d = h * dh
    q = rs.randn(b, t, d).astype(np.float32)
    k = rs.randn(b, t, d).astype(np.float32)
    v = rs.randn(b, t, d).astype(np.float32)

    kernel = get_op_info("cached_attention").kernel
    kc = jnp.zeros((b, h, t, dh))
    vc = jnp.zeros((b, h, t, dh))
    outs = []
    for pos in range(t):
        r = kernel(None, {
            "Q": [jnp.asarray(q[:, pos:pos + 1])],
            "KNew": [jnp.asarray(k[:, pos:pos + 1])],
            "VNew": [jnp.asarray(v[:, pos:pos + 1])],
            "KCache": [kc], "VCache": [vc],
            "Position": [jnp.asarray([pos])]}, {"num_heads": h})
        kc, vc = r["KCacheOut"][0], r["VCacheOut"][0]
        outs.append(np.asarray(r["Out"][0]))
    got = np.concatenate(outs, axis=1)          # [b, t, d]

    def heads(x):
        return x.reshape(b, t, h, dh).transpose(0, 2, 1, 3)

    ref = reference_attention(jnp.asarray(heads(q)),
                              jnp.asarray(heads(k)),
                              jnp.asarray(heads(v)), None, True)
    ref = np.asarray(ref).transpose(0, 2, 1, 3).reshape(b, t, d)
    np.testing.assert_allclose(got, ref, atol=2e-5)
