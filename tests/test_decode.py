"""Generation: dense jit beam search + greedy decode, and the LoD beam
ops through fluid layers (reference: beam_search_op test +
test_machine_translation decode path)."""

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu.fluid as fluid
from paddle_tpu.models.decode import (greedy_decode,
                                      beam_search_decode_dense)


def _toy_step_fn(V=7, C=5, seed=0):
    """A stateless scorer: logits depend on (prev token, step counter)
    via a fixed random table — deterministic and order-sensitive."""
    rs = np.random.RandomState(seed)
    table = jnp.asarray(rs.randn(V, C, V).astype(np.float32))

    def step_fn(state, tok):
        t = state["t"]
        logits = table[tok, jnp.minimum(t, C - 1)]
        return logits, {"t": t + 1}

    return step_fn, {"t": jnp.zeros((), jnp.int32)}


def _np_beam_reference(step_table, bos, eos, K, L):
    """Exhaustive numpy beam search over the same scorer (per batch=1)."""
    V = step_table.shape[0]
    beams = [([bos], 0.0, False)]
    for t in range(L):
        cand = []
        for toks, sc, done in beams:
            logits = step_table[toks[-1], min(t, step_table.shape[1] - 1)]
            logp = logits - (np.log(np.sum(np.exp(logits - np.max(logits))))
                             + np.max(logits))
            if done:
                cand.append((toks + [eos], sc, True))
                continue
            for v in range(V):
                cand.append((toks + [v], sc + float(logp[v]), v == eos))
        cand.sort(key=lambda x: -x[1])
        beams = cand[:K]
    return beams


def test_greedy_equals_beam1():
    step_fn, state = _toy_step_fn()

    def expand_state(s, n):
        return jax.tree_util.tree_map(
            lambda t: jnp.broadcast_to(t, (n,) + t.shape), s)

    B, V, L = 3, 7, 6
    bstate = expand_state(state, B)
    g_toks, _ = jax.jit(lambda s: greedy_decode(
        step_fn, s, bos=1, eos=0, max_len=L, batch_size=B))(bstate)
    seqs, scores = jax.jit(lambda s: beam_search_decode_dense(
        step_fn, s, bos=1, eos=0, beam_size=1, max_len=L,
        batch_size=B))(bstate)
    np.testing.assert_array_equal(np.asarray(g_toks),
                                  np.asarray(seqs[:, 0, :]))


def test_beam_matches_numpy_reference():
    V, C, L, K = 7, 5, 5, 3
    step_fn, state = _toy_step_fn(V, C, seed=0)
    # same table the scorer was built from (same seed)
    table = np.random.RandomState(0).randn(V, C, V).astype(np.float32)

    bstate = jax.tree_util.tree_map(
        lambda t: jnp.broadcast_to(t, (1,) + t.shape), state)
    seqs, scores = jax.jit(lambda s: beam_search_decode_dense(
        step_fn, s, bos=1, eos=0, beam_size=K, max_len=L,
        batch_size=1))(bstate)

    ref = _np_beam_reference(table, bos=1, eos=0, K=K, L=L)
    got_best = np.asarray(seqs[0, 0]).tolist()
    ref_best = ref[0][0][1:]  # drop bos
    assert got_best == ref_best, (got_best, ref_best)
    np.testing.assert_allclose(float(scores[0, 0]), ref[0][1], rtol=1e-5)


def test_fluid_beam_search_ops():
    """One beam step + decode through the program path (LoD
    semantics of beam_search_op.cc)."""
    from paddle_tpu.core.ragged import RaggedTensor
    from paddle_tpu.ops.registry import get_op_info

    # 1 source, 2 beam rows, 3 candidates per row
    ids = RaggedTensor(np.asarray([[3, 4, 5], [6, 7, 8]], np.int64),
                       [np.array([0, 2]), np.array([0, 1, 2])])
    scores = RaggedTensor(
        jnp.asarray([[0.5, 0.3, 0.2], [0.6, 0.3, 0.1]], jnp.float32),
        [np.array([0, 2]), np.array([0, 1, 2])])
    pre_ids = np.asarray([[1], [1]], np.int64)

    beam = get_op_info("beam_search").kernel
    outs = beam(None, {"pre_ids": [pre_ids], "ids": [ids],
                       "scores": [scores]},
                {"beam_size": 2, "end_id": 0, "level": 0})
    sel = outs["selected_ids"][0]
    sel_ids = np.asarray(sel.values).reshape(-1).tolist()
    # top-2 overall: 0.6 (tok 6) and 0.5 (tok 3)
    assert sorted(sel_ids) == [3, 6]

    decode = get_op_info("beam_search_decode").kernel
    outs2 = decode(None, {"Ids": [[sel]],
                          "Scores": [[outs["selected_scores"][0]]]}, {})
    sent = outs2["SentenceIds"][0]
    assert sorted(np.asarray(sent.values).reshape(-1).tolist()) == [3, 6]
