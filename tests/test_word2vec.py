"""Book test: N-gram word embedding model.

Parity target: reference python/paddle/v2/fluid/tests/book/
test_word2vec.py — 4 context words, shared embedding table, fc tower,
cross-entropy on next-word; loss must decrease.
"""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.models import word2vec_ngram


def test_word2vec():
    word_dict = paddle.dataset.imikolov.build_dict()
    dict_size = len(word_dict)

    names = ["firstw", "secondw", "thirdw", "forthw", "nextw"]
    words = [fluid.layers.data(name=n, shape=[1], dtype="int64")
             for n in names]
    predict = word2vec_ngram(words[:4], dict_size, emb_dim=32,
                             hidden_size=256)
    cost = fluid.layers.cross_entropy(input=predict, label=words[4])
    avg_cost = fluid.layers.mean(x=cost)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)

    train_reader = paddle.batch(paddle.dataset.imikolov.train(word_dict),
                                batch_size=64)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(feed_list=words, place=place)
    exe.run(fluid.default_startup_program())

    losses = []
    for pass_id in range(4):
        for data in train_reader():
            if len(data) != 64:
                continue
            loss, = exe.run(fluid.default_main_program(),
                            feed=feeder.feed(data),
                            fetch_list=[avg_cost])
            losses.append(float(loss[0]))
    assert np.isfinite(losses[-1])
    head = np.mean(losses[:8])
    tail = np.mean(losses[-8:])
    assert tail < head, (head, tail)
