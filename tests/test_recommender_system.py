"""Book test: MovieLens-style recommender.

Parity target: reference tests/book/test_recommender_system.py — user
tower (id/gender/age/job embeddings -> fc), movie tower (id embedding +
category/title sequence pools -> fc), cosine similarity scaled to
ratings, square error loss.
"""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

layers = fluid.layers


def _usr_combined():
    uid = layers.data(name="user_id", shape=[1], dtype="int64")
    usr_emb = layers.embedding(
        input=uid, size=[paddle.dataset.movielens.max_user_id() + 1, 32],
        param_attr="user_table")
    usr_fc = layers.fc(input=usr_emb, size=32)

    gender = layers.data(name="gender_id", shape=[1], dtype="int64")
    gender_fc = layers.fc(
        input=layers.embedding(input=gender, size=[2, 16],
                               param_attr="gender_table"), size=16)

    age = layers.data(name="age_id", shape=[1], dtype="int64")
    age_fc = layers.fc(
        input=layers.embedding(
            input=age, size=[len(paddle.dataset.movielens.age_table), 16],
            param_attr="age_table"), size=16)

    job = layers.data(name="job_id", shape=[1], dtype="int64")
    job_fc = layers.fc(
        input=layers.embedding(
            input=job, size=[paddle.dataset.movielens.max_job_id() + 1, 16],
            param_attr="job_table"), size=16)

    return layers.fc(input=[usr_fc, gender_fc, age_fc, job_fc],
                     size=200, act="tanh"), [uid, gender, age, job]


def _mov_combined():
    mid = layers.data(name="movie_id", shape=[1], dtype="int64")
    mov_emb = layers.embedding(
        input=mid, size=[paddle.dataset.movielens.max_movie_id() + 1, 32],
        param_attr="movie_table")
    mov_fc = layers.fc(input=mov_emb, size=32)

    cat = layers.data(name="category_id", shape=[1], dtype="int64",
                      lod_level=1)
    cat_emb = layers.embedding(
        input=cat, size=[len(paddle.dataset.movielens.movie_categories()),
                         32])
    cat_pool = layers.sequence_pool(input=cat_emb, pool_type="sum")

    title = layers.data(name="movie_title", shape=[1], dtype="int64",
                        lod_level=1)
    title_emb = layers.embedding(input=title, size=[5000, 32])
    title_pool = layers.sequence_pool(input=title_emb, pool_type="sum")

    return layers.fc(input=[mov_fc, cat_pool, title_pool],
                     size=200, act="tanh"), [mid, cat, title]


def test_recommender_system():
    usr, usr_vars = _usr_combined()
    mov, mov_vars = _mov_combined()
    inference = layers.cos_sim(X=usr, Y=mov)
    scale_infer = layers.scale(x=inference, scale=5.0)

    label = layers.data(name="score", shape=[1], dtype="float32")
    cost = layers.square_error_cost(input=scale_infer, label=label)
    avg_cost = layers.mean(x=cost)
    fluid.optimizer.SGD(learning_rate=0.2).minimize(avg_cost)

    reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.movielens.train(),
                              buf_size=1024), batch_size=64)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(feed_list=usr_vars + mov_vars + [label],
                              place=place)
    exe.run(fluid.default_startup_program())

    losses = []
    for pass_id in range(3):
        for batch in reader():
            if len(batch) != 64:
                continue
            out, = exe.run(fluid.default_main_program(),
                           feed=feeder.feed(batch),
                           fetch_list=[avg_cost])
            losses.append(float(np.asarray(out).reshape(-1)[0]))
    assert np.isfinite(losses[-1])
    assert np.mean(losses[-4:]) < np.mean(losses[:4]), (
        losses[:4], losses[-4:])
