"""Dataset stand-ins expose the reference reader API with the right
shapes/dtypes (reference: python/paddle/v2/dataset/tests)."""

import numpy as np

import paddle_tpu as paddle


def test_sentiment():
    s = next(paddle.dataset.sentiment.train()())
    words, label = s
    assert all(isinstance(w, int) for w in words)
    assert label in (0, 1)
    assert len(paddle.dataset.sentiment.get_word_dict()) > 5000


def test_wmt16():
    src, trg_in, trg_next = next(paddle.dataset.wmt16.train(100, 100)())
    assert trg_in[0] == paddle.dataset.wmt14.ID_MARK_START
    assert trg_next[-1] == paddle.dataset.wmt14.ID_MARK_END
    assert len(trg_in) == len(trg_next)


def test_mq2007_pairwise_and_listwise():
    lab, f1, f2 = next(paddle.dataset.mq2007.train("pairwise")())
    assert f1.shape == (46,) and f2.shape == (46,)
    feats, rel = next(paddle.dataset.mq2007.train("listwise")())
    assert feats.shape[1] == 46 and rel.shape[0] == feats.shape[0]


def test_flowers_and_voc():
    im, lab = next(paddle.dataset.flowers.train()())
    assert im.shape == (3, 224, 224) and 0 <= lab < 102
    im, seg = next(paddle.dataset.voc2012.train()())
    assert im.shape[0] == 3 and seg.shape == im.shape[1:]
    assert seg.max() < paddle.dataset.voc2012.CLASS_NUM
