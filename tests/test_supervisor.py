"""TrainingSupervisor: preemption-safe checkpoints, auto-resume with
batch skip, nonfinite rollback, restart budget — resume semantics must
reproduce an uninterrupted run step for step on the same seed."""

import json
import os
import signal

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as paddle
from paddle_tpu.reader import host_prefetch
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.supervisor import (Preempted,
                                              RestartBudgetExceeded,
                                              SUPERVISOR_META,
                                              TrainingSupervisor)


def _build_sgd(lr=0.1):
    paddle.init()
    x = paddle.layer.data(name="x",
                          type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y",
                          type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1)
    cost = paddle.layer.square_error_cost(input=pred, label=y)
    params = paddle.parameters.create(cost)
    return paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.9,
                                                  learning_rate=lr))


def _batches(n=6, batch=4, seed=0):
    rs = np.random.RandomState(seed)
    return [[(rs.rand(4).astype("f"), rs.rand(1).astype("f"))
             for _ in range(batch)] for _ in range(n)]


def _reader_fn(batches):
    def reader():
        for b in batches:
            yield b

    return reader


def _params_of(sgd):
    from paddle_tpu.core.scope import global_scope
    from paddle_tpu.fluid.io import is_persistable

    out = {}
    for v in sgd._main_program.list_vars():
        if is_persistable(v):
            val = global_scope().get(v.name)
            if val is not None:
                out[v.name] = np.array(val)
    return out


def _clean_run(tmp_path, fresh_programs, epochs=2):
    """Reference trajectory on a fresh workspace; returns
    (losses-by-step, sorted final param arrays)."""
    sgd = _build_sgd()
    losses = {}
    sup = TrainingSupervisor(str(tmp_path / "clean"),
                             program=sgd._main_program,
                             steps_per_checkpoint=1)
    sup.run(sgd.step_runner(feeding={"x": 0, "y": 1}),
            _reader_fn(_batches()), num_epochs=epochs,
            on_step=lambda s, l: losses.__setitem__(s, l))
    params = _params_of(sgd)
    return losses, [params[k] for k in sorted(params)]


def _reset_workspace():
    # same reset the conftest fixtures apply, but mid-test: the second
    # training run must not see the first one's programs/scope
    from paddle_tpu.core import scope as scope_mod
    from paddle_tpu.fluid import framework
    from paddle_tpu.v2 import layer as v2_layer

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()
    v2_layer._reset_data_layers()


def test_preempted_resume_matches_uninterrupted(tmp_path,
                                                fresh_programs):
    """Kill mid-epoch (injected SIGTERM), auto-resume, and the loss
    trajectory + final params match an uninterrupted run on the same
    seed, step for step."""
    clean_losses, clean_params = _clean_run(tmp_path, fresh_programs)

    _reset_workspace()
    sgd = _build_sgd()
    faults.enable(seed=0)
    faults.inject("supervisor/step", "preempt", after=3, times=1)
    losses = {}
    sup = TrainingSupervisor(str(tmp_path / "chaos"),
                             program=sgd._main_program,
                             steps_per_checkpoint=1)
    out = sup.run(sgd.step_runner(feeding={"x": 0, "y": 1}),
                  _reader_fn(_batches()), num_epochs=2,
                  on_step=lambda s, l: losses.__setitem__(s, l))
    assert out["restarts"] == 1
    assert faults.fired_counts() == {("supervisor/step",
                                      "preempt"): 1}
    assert sorted(losses) == sorted(clean_losses)
    for step in clean_losses:
        assert losses[step] == pytest.approx(clean_losses[step],
                                             abs=1e-12), step
    params = _params_of(sgd)
    for got, want in zip([params[k] for k in sorted(params)],
                         clean_params):
        np.testing.assert_array_equal(got, want)


def test_preempt_writes_urgent_checkpoint_with_meta(tmp_path,
                                                    fresh_programs):
    from paddle_tpu.fluid.checkpoint import latest_checkpoint

    sgd = _build_sgd()
    faults.enable(seed=0)
    faults.inject("supervisor/step", "preempt", after=2, times=1)
    sup = TrainingSupervisor(str(tmp_path / "ck"),
                             program=sgd._main_program,
                             steps_per_checkpoint=10 ** 6,
                             on_preempt="raise")
    with pytest.raises(Preempted):
        sup.run(sgd.step_runner(feeding={"x": 0, "y": 1}),
                _reader_fn(_batches()), num_epochs=2)
    snap = latest_checkpoint(str(tmp_path / "ck"))
    meta = json.load(open(os.path.join(snap, SUPERVISOR_META)))
    assert meta["kind"] == "urgent"
    assert meta["step"] == 3  # preempt observed after the 3rd step
    # the urgent checkpoint is resumable: a NEW supervisor (fresh
    # process in production) picks up where the preempted one left off
    sup2 = TrainingSupervisor(str(tmp_path / "ck"),
                              program=sgd._main_program,
                              steps_per_checkpoint=10 ** 6)
    out = sup2.run(sgd.step_runner(feeding={"x": 0, "y": 1}),
                   _reader_fn(_batches()), num_epochs=2)
    assert out["steps"] == 12


@pytest.mark.slow
def test_nonfinite_rolls_back_to_last_good(tmp_path, fresh_programs):
    """(slow: clean + chaos double run — the preempted-resume test
    above already covers the trajectory machinery in tier-1; this one
    runs in the ci.sh full suite.)"""
    clean_losses, clean_params = _clean_run(tmp_path, fresh_programs)

    _reset_workspace()
    sgd = _build_sgd()
    faults.enable(seed=0)
    faults.inject("supervisor/step", "nonfinite", after=4, times=1)
    losses = {}
    sup = TrainingSupervisor(str(tmp_path / "nf"),
                             program=sgd._main_program,
                             steps_per_checkpoint=1)
    out = sup.run(sgd.step_runner(feeding={"x": 0, "y": 1}),
                  _reader_fn(_batches()), num_epochs=2,
                  on_step=lambda s, l: losses.__setitem__(s, l))
    assert out["restarts"] == 1
    from paddle_tpu.obs import telemetry as obs_tele

    snap = obs_tele.snapshot()
    assert snap.get("supervisor_nonfinite_total") == 1
    assert snap.get("supervisor_restarts_total{reason=nonfinite}") == 1
    for step in clean_losses:
        assert losses[step] == pytest.approx(clean_losses[step],
                                             abs=1e-12)
    params = _params_of(sgd)
    for got, want in zip([params[k] for k in sorted(params)],
                         clean_params):
        np.testing.assert_array_equal(got, want)


def test_nonfinite_backs_off_loss_scale(tmp_path, fresh_programs):
    from paddle_tpu.fluid.amp import LossScaler

    sgd = _build_sgd()
    scaler = LossScaler(init_scale=1024.0)
    faults.enable(seed=0)
    faults.inject("supervisor/step", "nonfinite", after=2, times=1)
    sup = TrainingSupervisor(str(tmp_path / "ls"),
                             program=sgd._main_program,
                             steps_per_checkpoint=1,
                             loss_scaler=scaler)
    sup.run(sgd.step_runner(feeding={"x": 0, "y": 1}),
            _reader_fn(_batches()), num_epochs=1)
    assert scaler.scale == 512.0  # backed off once, after the restore


def test_transient_reader_fault_restarts_and_completes(
        tmp_path, fresh_programs):
    sgd = _build_sgd()
    faults.enable(seed=0)
    faults.inject("reader/pump", "io_error", after=4, times=1)
    sup = TrainingSupervisor(str(tmp_path / "rf"),
                             program=sgd._main_program,
                             steps_per_checkpoint=1)
    out = sup.run(sgd.step_runner(feeding={"x": 0, "y": 1}),
                  host_prefetch(_reader_fn(_batches()), depth=2),
                  num_epochs=2)
    assert out == {"steps": 12, "epochs": 2, "restarts": 1}


def test_restart_budget_exceeded_raises(tmp_path, fresh_programs):
    sgd = _build_sgd()
    faults.enable(seed=0)
    faults.inject("supervisor/step", "nonfinite", times=None)  # forever
    sup = TrainingSupervisor(str(tmp_path / "rb"),
                             program=sgd._main_program,
                             steps_per_checkpoint=1, max_restarts=2)
    with pytest.raises(RestartBudgetExceeded):
        sup.run(sgd.step_runner(feeding={"x": 0, "y": 1}),
                _reader_fn(_batches()), num_epochs=1)
    from paddle_tpu.obs import telemetry as obs_tele

    snap = obs_tele.snapshot()
    assert snap.get("supervisor_restarts_total{reason=nonfinite}") == 3


def test_nonretryable_step_error_propagates(tmp_path, fresh_programs):
    sgd = _build_sgd()
    sup = TrainingSupervisor(str(tmp_path / "nr"),
                             program=sgd._main_program)

    def bad_step(data):
        raise ValueError("a bug must not be retried away")

    with pytest.raises(ValueError):
        sup.run(bad_step, _reader_fn(_batches()), num_epochs=1)


def test_signal_handlers_restored_after_run(tmp_path, fresh_programs):
    before = (signal.getsignal(signal.SIGTERM),
              signal.getsignal(signal.SIGINT))
    sgd = _build_sgd()
    sup = TrainingSupervisor(str(tmp_path / "sh"),
                             program=sgd._main_program,
                             steps_per_checkpoint=10 ** 6)
    sup.run(sgd.step_runner(feeding={"x": 0, "y": 1}),
            _reader_fn(_batches(n=2)), num_epochs=1)
    assert (signal.getsignal(signal.SIGTERM),
            signal.getsignal(signal.SIGINT)) == before


def test_step_runner_surfaces_numerics_monitor_signal(tmp_path,
                                                      fresh_programs):
    """With obs.health enabled, step_runner reports the monitor's
    found-nonfinite verdict as a NaN loss — the supervisor's rollback
    trigger — and the numerics counters move."""
    import math

    from paddle_tpu.obs import health as obs_health
    from paddle_tpu.obs import telemetry as obs_tele

    obs_health.enable()
    sgd = _build_sgd()
    step = sgd.step_runner(feeding={"x": 0, "y": 1})
    bad = [(np.full(4, np.nan, np.float32),
            np.zeros(1, np.float32)) for _ in range(4)]
    assert math.isnan(step(bad))
    snap = obs_tele.snapshot()
    assert any(k.startswith("numerics_nonfinite_total{") and v > 0
               for k, v in snap.items()), snap


@pytest.mark.slow
def test_parallel_trainer_supervised_resume(tmp_path, fresh_programs):
    """The mesh-parallel trainer round-trips its sharded state through
    supervisor checkpoints: preempt, resume, same final state as an
    uninterrupted run.  (slow: two mesh-step compiles; runs in the
    ci.sh full suite.)"""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.trainer import ParallelTrainer

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(input=x, size=1)
            loss = fluid.layers.mean(
                x=fluid.layers.square_error_cost(input=pred, label=y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        mesh = make_mesh(n_devices=8, dp=8)
        return ParallelTrainer(main, startup, ["x", "y"], [loss.name],
                               mesh, seed=0).init()

    rs = np.random.RandomState(0)
    data = [{"x": rs.rand(8, 4).astype("f"),
             "y": rs.rand(8, 1).astype("f")} for _ in range(4)]

    def reader():
        for b in data:
            yield b

    # clean reference
    t_clean = build()
    sup = TrainingSupervisor.for_parallel(t_clean,
                                          str(tmp_path / "pc"),
                                          steps_per_checkpoint=1)
    sup.run_parallel(t_clean, reader, num_epochs=2)
    want = {n: t_clean.fetch_state(n) for n in t_clean.state}

    # preempted + resumed
    t_chaos = build()
    faults.enable(seed=0)
    faults.inject("supervisor/step", "preempt", after=3, times=1)
    sup2 = TrainingSupervisor.for_parallel(t_chaos,
                                           str(tmp_path / "pp"),
                                           steps_per_checkpoint=1)
    out = sup2.run_parallel(t_chaos, reader, num_epochs=2)
    assert out["restarts"] == 1
    for name in want:
        np.testing.assert_allclose(t_chaos.fetch_state(name),
                                   want[name], rtol=1e-6, atol=1e-7)
