"""Book test: semantic role labeling with a linear-chain CRF.

Parity target: reference tests/book/test_label_semantic_roles.py —
8 feature sequences embedded, stacked bidirectional LSTM, per-step
emission fc, linear_chain_crf loss + crf_decoding.
"""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

layers = fluid.layers

WORD_DICT, VERB_DICT, LABEL_DICT = paddle.dataset.conll05.get_dict()
MARK_DICT_LEN = 2
EMB = 16
HID = 32


def _db_lstm(word, predicate, mark):
    word_emb = layers.embedding(input=word, size=[len(WORD_DICT), EMB])
    pred_emb = layers.embedding(input=predicate,
                                size=[len(VERB_DICT), EMB])
    mark_emb = layers.embedding(input=mark, size=[MARK_DICT_LEN, EMB])

    hidden0 = layers.fc(input=[word_emb, pred_emb, mark_emb],
                        size=HID * 4, act="tanh")
    lstm0, _ = layers.dynamic_lstm(input=hidden0, size=HID * 4)
    fc1 = layers.fc(input=[hidden0, lstm0], size=HID * 4, act="tanh")
    lstm1, _ = layers.dynamic_lstm(input=fc1, size=HID * 4,
                                   is_reverse=True)
    return layers.fc(input=[fc1, lstm1], size=len(LABEL_DICT), act=None)


def test_label_semantic_roles():
    word = layers.data(name="word_data", shape=[1], dtype="int64",
                       lod_level=1)
    predicate = layers.data(name="verb_data", shape=[1], dtype="int64",
                            lod_level=1)
    mark = layers.data(name="mark_data", shape=[1], dtype="int64",
                       lod_level=1)
    target = layers.data(name="target", shape=[1], dtype="int64",
                         lod_level=1)

    feature_out = _db_lstm(word, predicate, mark)
    crf_cost = layers.linear_chain_crf(
        input=feature_out, label=target,
        param_attr=fluid.ParamAttr(name="crfw"))
    avg_cost = layers.mean(x=crf_cost)
    fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)

    # decoding path shares the transition parameter
    crf_decode = layers.crf_decoding(input=feature_out,
                                     param_attr=fluid.ParamAttr(name="crfw"))

    reader = paddle.batch(paddle.dataset.conll05.test(), batch_size=8)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)

    def pick(sample):
        # dataset yields (word, c_n2, c_n1, c_0, c_p1, c_p2, pred, mark,
        # label); the slim model uses word/pred/mark/label
        return sample[0], sample[6], sample[7], sample[8]

    feeder = fluid.DataFeeder(
        feed_list=[word, predicate, mark, target], place=place)
    exe.run(fluid.default_startup_program())

    losses = []
    for pass_id in range(2):
        for batch in reader():
            batch = [pick(s) for s in batch]
            if len(batch) != 8:
                continue
            out, path = exe.run(fluid.default_main_program(),
                                feed=feeder.feed(batch),
                                fetch_list=[avg_cost, crf_decode])
            losses.append(float(np.asarray(out).reshape(-1)[0]))
    assert np.isfinite(losses[-1])
    assert np.mean(losses[-4:]) < np.mean(losses[:4]), (
        losses[:4], losses[-4:])
    # viterbi path produces valid label ids (fetch is ragged: one label
    # per timestep)
    path = np.asarray(getattr(path, "values", path))
    assert path.min() >= 0 and path.max() < len(LABEL_DICT)
