"""Cluster launcher: spawn pservers + trainers as real processes and
train distributed fit_a_line through the full role protocol
(reference: paddle/scripts/cluster_train launcher behavior)."""

import os
import socket
import subprocess
import sys
import textwrap

from paddle_tpu.tools.cluster_launch import launch

TRAIN_SCRIPT = textwrap.dedent("""
    import os, sys
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
    from paddle_tpu.distributed import DistributeTranspiler
    from paddle_tpu.ops.dist import ClientPool

    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    yp = fluid.layers.fc(input=x, size=1)
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    avg = fluid.layers.mean(
        x=fluid.layers.square_error_cost(input=yp, label=y))
    oops, pg = fluid.optimizer.SGD(learning_rate=0.01).minimize(avg)
    t = DistributeTranspiler()
    t.transpile(optimize_ops=oops, params_grads=pg,
                trainer_id=int(os.environ["TRAINER_ID"]),
                pservers=os.environ["PSERVERS"],
                trainers=int(os.environ["TRAINERS"]))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    t.init_pservers()
    feeder = fluid.DataFeeder(place=fluid.CPUPlace(), feed_list=[x, y])
    rd = paddle.batch(paddle.dataset.uci_housing.train(), batch_size=20)
    losses = []
    for p in range(3):
        for d in rd():
            out, = exe.run(fluid.default_main_program(),
                           feed=feeder.feed(d), fetch_list=[avg])
            losses.append(float(np.asarray(out).reshape(-1)[0]))
    ClientPool.reset()
    sys.exit(0 if losses[-1] < losses[0] else 1)
""")


def test_cluster_launch_end_to_end(tmp_path):
    script = tmp_path / "train_dist.py"
    script.write_text(TRAIN_SCRIPT)
    ports = []
    for _ in range(2):
        with socket.socket() as sk:
            sk.bind(("127.0.0.1", 0))
            ports.append(sk.getsockname()[1])
    pservers = ["127.0.0.1:%d" % p for p in ports]

    ps_procs, tr_procs, _ = launch(
        [str(script)], pservers, trainers=2, sync=True,
        env={"PYTHONPATH": "/root/repo", "JAX_PLATFORMS": "cpu"})
    try:
        rcs = [p.wait(timeout=240) for p in tr_procs]
        assert rcs == [0, 0], rcs
    finally:
        import signal

        for p in ps_procs:
            p.send_signal(signal.SIGTERM)
        for p in ps_procs:
            p.wait(timeout=30)


def test_cluster_launch_remote_over_ssh(tmp_path):
    """--hosts mode really EXECUTES over the ssh transport (reference:
    cluster_train/paddle.py:33-104 runs remote commands, not prints).
    The transport here is a local ssh shim — same argv contract
    (`ssh host "shell command"`) with the hostname recorded so the test
    can assert per-host dispatch."""
    from paddle_tpu.tools.cluster_launch import launch_remote

    import shlex

    script = tmp_path / "train_dist.py"
    script.write_text(TRAIN_SCRIPT)
    hostlog = tmp_path / "hosts.log"
    shim = tmp_path / "fakessh"
    shim.write_text("#!/bin/bash\n"
                    "host=\"$1\"; shift\n"
                    "echo \"$host\" >> %s\n"
                    "exec bash -c \"$1\"\n" % shlex.quote(str(hostlog)))
    shim.chmod(0o755)

    # both staggered ports (base, base+1) must be free: reserve a pair
    sk1, sk2 = socket.socket(), socket.socket()
    try:
        while True:
            sk1.bind(("127.0.0.1", 0))
            port = sk1.getsockname()[1]
            try:
                sk2.bind(("127.0.0.1", port + 1))
                break
            except OSError:
                sk1.close()
                sk1 = socket.socket()
    finally:
        sk1.close()
        sk2.close()

    # two distinct loopback-resolvable "hosts"; port_step staggers the
    # pserver ports since both land on this machine
    from paddle_tpu.tools.cluster_launch import stop_remote

    ps_procs, tr_procs = launch_remote(
        [str(script)], hosts=["127.0.0.1", "localhost"],
        trainers_per_host=1, base_port=port, port_step=1, sync=True,
        python=sys.executable, ssh_cmd=(str(shim),), workdir="/root/repo",
        env={"PYTHONPATH": "/root/repo", "JAX_PLATFORMS": "cpu"})
    try:
        rcs = [p.wait(timeout=240) for p in tr_procs]
        assert rcs == [0, 0], rcs
        dispatched = hostlog.read_text().split()
        assert sorted(set(dispatched)) == ["127.0.0.1", "localhost"], \
            dispatched
    finally:
        for p in ps_procs:
            stop_remote(p)


ELASTIC_TRAIN_SCRIPT = TRAIN_SCRIPT.replace(
    'pservers=os.environ["PSERVERS"],',
    'pservers=",".join(__import__("paddle_tpu.distributed",'
    ' fromlist=["discover_pservers"]).discover_pservers()),')


def test_cluster_launch_elastic(tmp_path):
    """--elastic flow: launcher starts a master registry, pservers bind
    free ports and register slots, trainers DISCOVER the endpoints
    instead of reading a static list (reference: the etcd-driven
    go/pserver cluster bring-up)."""
    script = tmp_path / "train_dist_elastic.py"
    script.write_text(ELASTIC_TRAIN_SCRIPT)

    # endpoints are placeholders in elastic mode: only the count is used
    ps_procs, tr_procs, master = launch(
        [str(script)], ["x:0", "x:0"], trainers=2, sync=True,
        elastic=True,
        env={"PYTHONPATH": "/root/repo", "JAX_PLATFORMS": "cpu"})
    try:
        rcs = [p.wait(timeout=240) for p in tr_procs]
        assert rcs == [0, 0], rcs
    finally:
        import signal

        for p in ps_procs:
            p.send_signal(signal.SIGTERM)
        for p in ps_procs:
            p.wait(timeout=30)
        master.stop()
