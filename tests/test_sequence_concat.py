"""sequence_concat op: time-axis (per-example append) and feature-axis
modes (reference: sequence_concat_op.cc + its py test)."""

import numpy as np

import paddle_tpu.fluid as fluid


def _run(axis, A, B):
    a = fluid.layers.data(name="a", shape=[2], dtype="float32",
                          lod_level=1)
    b = fluid.layers.data(name="b", shape=[2], dtype="float32",
                          lod_level=1)
    out = fluid.layers.sequence_concat(input=[a, b], axis=axis)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(feed_list=[a, b], place=place)
    res, = exe.run(fluid.default_main_program(),
                   feed=feeder.feed(list(zip(A, B))),
                   fetch_list=[out])
    return res


def test_sequence_concat_time_axis():
    A = [[[1, 1], [2, 2]], [[3, 3]]]
    B = [[[9, 9]], [[8, 8], [7, 7]]]
    res = _run(0, A, B)
    vals = np.asarray(res.values)[:int(res.nvalid)]
    assert vals.tolist() == [[1, 1], [2, 2], [9, 9],
                             [3, 3], [8, 8], [7, 7]]
    assert res.lod() == [[0, 3, 6]]


def test_sequence_concat_feature_axis():
    A = [[[1, 1], [2, 2]], [[3, 3]]]
    B = [[[9, 9], [6, 6]], [[8, 8]]]
    res = _run(1, A, B)
    vals = np.asarray(res.values)[:int(res.nvalid)]
    assert vals.shape[1] == 4
    assert vals[0].tolist() == [1, 1, 9, 9]
