"""Book test: sentiment classification over ragged word-id sequences.

Parity target: reference tests/book/test_understand_sentiment_conv.py
(sequence_conv_pool net) and
test_understand_sentiment_dynamic_lstm.py (stacked dynamic LSTM).
"""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.models import (conv_text_classifier,
                               stacked_lstm_text_classifier)


def _train(model_fn, dict_dim, passes=3, batch_size=16, lr=0.05):
    data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                             lod_level=1)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    prob = model_fn(data, dict_dim)
    cost = fluid.layers.cross_entropy(input=prob, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    acc = fluid.layers.accuracy(input=prob, label=label)
    fluid.optimizer.Adam(learning_rate=lr).minimize(avg_cost)

    word_dict = paddle.dataset.imdb.word_dict()
    reader = paddle.batch(paddle.dataset.imdb.train(word_dict),
                          batch_size=batch_size)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(feed_list=[data, label], place=place)
    exe.run(fluid.default_startup_program())

    losses, accs = [], []
    for pass_id in range(passes):
        for batch in reader():
            if len(batch) != batch_size:
                continue
            loss, a = exe.run(fluid.default_main_program(),
                              feed=feeder.feed(batch),
                              fetch_list=[avg_cost, acc])
            losses.append(float(np.asarray(loss).reshape(-1)[0]))
            accs.append(float(np.asarray(a).reshape(-1)[0]))
    assert np.isfinite(losses[-1])
    head = np.mean(losses[:4])
    tail = np.mean(losses[-4:])
    assert tail < head, (head, tail)
    return accs[-1]


def test_understand_sentiment_conv():
    dict_dim = len(paddle.dataset.imdb.word_dict())
    _train(lambda d, n: conv_text_classifier(d, n, emb_dim=32, hid_dim=32),
           dict_dim)


def test_understand_sentiment_dynamic_lstm():
    dict_dim = len(paddle.dataset.imdb.word_dict())
    _train(lambda d, n: stacked_lstm_text_classifier(
        d, n, emb_dim=32, hid_dim=16, stacked_num=2), dict_dim, passes=2)
