"""Native C++ runtime tests, in-process loopback (reference test
strategy: pserver/test_ParameterServer2.cpp and send_recv_op_test.cc
spin server+client in one process; go/master service_test.go)."""

import threading

import numpy as np
import pytest

from paddle_tpu import native


def test_pserver_dense_sgd_roundtrip():
    s = native.ParameterServer(num_trainers=1, sync=True)
    try:
        c = native.PServerClient("127.0.0.1", s.port)
        w0 = np.arange(8, dtype=np.float32)
        c.init_param("w", w0, opt_kind=native.OPT_SGD, lr=0.1)
        grad = np.ones(8, np.float32)
        updated = c.send_grad("w", grad)
        np.testing.assert_allclose(updated, w0 - 0.1, rtol=1e-6)
        got = c.get_param("w", 8)
        np.testing.assert_allclose(got, updated)
        assert s.num_updates() == 1
        c.close()
    finally:
        s.stop()


def test_pserver_sync_barrier_two_trainers():
    """Two trainers' gradients are averaged then applied once
    (reference: ParameterServer2 addGradient barrier + doOperation)."""
    s = native.ParameterServer(num_trainers=2, sync=True)
    try:
        results = {}

        def trainer(tid, gval):
            c = native.PServerClient("127.0.0.1", s.port)
            c.init_param("w", np.zeros(4, np.float32),
                         opt_kind=native.OPT_SGD, lr=1.0)
            results[tid] = c.send_grad(
                "w", np.full(4, gval, np.float32))
            c.close()

        t1 = threading.Thread(target=trainer, args=(1, 1.0))
        t2 = threading.Thread(target=trainer, args=(2, 3.0))
        t1.start(); t2.start()
        t1.join(timeout=30); t2.join(timeout=30)
        assert not t1.is_alive() and not t2.is_alive()
        # avg grad = 2.0, lr 1.0 -> w = -2
        np.testing.assert_allclose(results[1], -2.0)
        np.testing.assert_allclose(results[2], -2.0)
        assert s.num_updates() == 1
    finally:
        s.stop()


def test_pserver_async_mode():
    """Async: each gradient applies immediately (reference: asyncSGD)."""
    s = native.ParameterServer(num_trainers=2, sync=False)
    try:
        c = native.PServerClient("127.0.0.1", s.port)
        c.init_param("w", np.zeros(2, np.float32),
                     opt_kind=native.OPT_SGD, lr=1.0)
        c.send_grad("w", np.ones(2, np.float32))
        out = c.send_grad("w", np.ones(2, np.float32))
        np.testing.assert_allclose(out, -2.0)
        assert s.num_updates() == 2
        c.close()
    finally:
        s.stop()


def test_pserver_async_staleness_bound():
    """Async gradients older than the staleness bound are discarded
    (reference: ParameterServer2.h:243 lagged-async commit control /
    ParameterServer2.cpp asyncGrdientCommitCheckAndStat)."""
    s = native.ParameterServer(num_trainers=2, sync=False,
                               async_lagged_threshold=2)
    try:
        fast = native.PServerClient("127.0.0.1", s.port)
        slow = native.PServerClient("127.0.0.1", s.port)
        fast.init_param("w", np.zeros(2, np.float32),
                        opt_kind=native.OPT_SGD, lr=1.0)
        # slow trainer reads version 0
        slow.get_param("w", 2)
        # fast trainer advances the version past the bound
        for _ in range(3):
            fast.send_grad("w", np.ones(2, np.float32))
            assert fast.last_grad_applied
        # slow trainer's gradient is 3 versions stale -> discarded,
        # but it still receives the fresh parameter
        out = slow.send_grad("w", np.full(2, 100.0, np.float32))
        assert not slow.last_grad_applied
        np.testing.assert_allclose(out, -3.0)
        assert s.num_lagged() == 1
        assert s.num_updates() == 3
        # resynchronized now: the next gradient applies
        out = slow.send_grad("w", np.ones(2, np.float32))
        assert slow.last_grad_applied
        np.testing.assert_allclose(out, -4.0)
        assert s.num_updates() == 4
        fast.close()
        slow.close()
    finally:
        s.stop()


def test_pserver_momentum_and_adam_match_numpy():
    s = native.ParameterServer(num_trainers=1, sync=True)
    try:
        c = native.PServerClient("127.0.0.1", s.port)
        # momentum
        c.init_param("wm", np.zeros(3, np.float32),
                     opt_kind=native.OPT_MOMENTUM, lr=0.1, hp1=0.9)
        g = np.array([1., 2., 3.], np.float32)
        v = np.zeros(3); w = np.zeros(3)
        for _ in range(3):
            got = c.send_grad("wm", g)
            v = 0.9 * v + g
            w = w - 0.1 * v
        np.testing.assert_allclose(got, w, rtol=1e-5)
        # adam
        c.init_param("wa", np.zeros(3, np.float32),
                     opt_kind=native.OPT_ADAM, lr=0.01,
                     hp1=0.9, hp2=0.999, hp3=1e-8)
        m = np.zeros(3); vv = np.zeros(3); wa = np.zeros(3)
        for t in range(1, 4):
            got = c.send_grad("wa", g)
            m = 0.9 * m + 0.1 * g
            vv = 0.999 * vv + 0.001 * g * g
            alpha = 0.01 * np.sqrt(1 - 0.999 ** t) / (1 - 0.9 ** t)
            wa = wa - alpha * m / (np.sqrt(vv) + 1e-8)
        np.testing.assert_allclose(got, wa, rtol=1e-4)
        c.close()
    finally:
        s.stop()


def test_pserver_sparse_rows():
    """Sparse row update + row fetch (reference: getParameterSparse,
    SelectedRows transfer)."""
    s = native.ParameterServer(num_trainers=1, sync=True)
    try:
        c = native.PServerClient("127.0.0.1", s.port)
        table = np.zeros((10, 4), np.float32)
        c.init_param("emb", table, opt_kind=native.OPT_SGD, lr=1.0)
        rows = np.array([2, 7], np.int32)
        grads = np.ones((2, 4), np.float32)
        c.send_sparse_grad("emb", rows, grads)
        got = c.get_rows("emb", np.array([2, 7, 0], np.int32), 4)
        np.testing.assert_allclose(got[0], -1.0)
        np.testing.assert_allclose(got[1], -1.0)
        np.testing.assert_allclose(got[2], 0.0)
        c.close()
    finally:
        s.stop()


def test_pserver_checkpoint_roundtrip(tmp_path):
    """Checkpoint save/load with CRC (reference: go/pserver
    checkpoint:346 w/ crc32)."""
    path = str(tmp_path / "ckpt.bin")
    s = native.ParameterServer(num_trainers=1, sync=True)
    c = native.PServerClient("127.0.0.1", s.port)
    c.init_param("w", np.arange(6, dtype=np.float32),
                 opt_kind=native.OPT_ADAM, lr=0.01, hp1=0.9, hp2=0.999)
    c.send_grad("w", np.ones(6, np.float32))
    want = c.get_param("w", 6)
    assert s.save(path) == 0
    c.close(); s.stop()

    s2 = native.ParameterServer(num_trainers=1, sync=True)
    try:
        assert s2.load(path) == 0
        c2 = native.PServerClient("127.0.0.1", s2.port)
        got = c2.get_param("w", 6)
        np.testing.assert_allclose(got, want)
        c2.close()
    finally:
        s2.stop()
    # corruption detected
    with open(path, "r+b") as f:
        f.seek(20)
        f.write(b"\xde\xad")
    s3 = native.ParameterServer(num_trainers=1, sync=True)
    try:
        assert s3.load(path) == -3
    finally:
        s3.stop()


def test_master_task_queue(tmp_path):
    """Lease/finish/fail flow + timeout requeue + failure cap
    (reference: go/master/service_test.go behaviors)."""
    m = native.Master(timeout_ms=200, failure_max=2)
    try:
        c = native.MasterClient("127.0.0.1", m.port)
        c.set_dataset(["c0", "c1", "c2", "c3"], chunks_per_task=2)
        t0, chunks0 = c.get_task()
        assert t0 >= 0 and chunks0 == ["c0", "c1"]
        t1, chunks1 = c.get_task()
        assert t1 >= 0 and chunks1 == ["c2", "c3"]
        # all leased
        t2, _ = c.get_task()
        assert t2 == native.MasterClient.NO_TASK
        c.task_finished(t0)
        # fail t1 -> requeued
        c.task_failed(t1)
        t1b, chunks1b = c.get_task()
        assert t1b == t1 and chunks1b == ["c2", "c3"]
        # fail again -> discarded (failure_max=2); the pass is now
        # drained: PASS_FINISHED reported once, then the finished task
        # recycles for the next pass
        c.task_failed(t1b)
        t3, _ = c.get_task()
        assert t3 == native.MasterClient.PASS_FINISHED
        t4, chunks4 = c.get_task()
        assert t4 >= 0 and chunks4 == ["c0", "c1"]
        c.close()
    finally:
        m.stop()


def test_master_timeout_requeues():
    import time

    m = native.Master(timeout_ms=150, failure_max=5)
    try:
        c = native.MasterClient("127.0.0.1", m.port)
        c.set_dataset(["a"], chunks_per_task=1)
        t0, _ = c.get_task()
        assert t0 >= 0
        time.sleep(0.6)  # lease expires
        t1, chunks = c.get_task()
        assert t1 == t0 and chunks == ["a"]
        c.close()
    finally:
        m.stop()


def test_master_snapshot_recover(tmp_path):
    path = str(tmp_path / "master.snap")
    m = native.Master(timeout_ms=5000, failure_max=3)
    c = native.MasterClient("127.0.0.1", m.port)
    c.set_dataset(["x", "y"], chunks_per_task=1)
    tid, _ = c.get_task()  # leased; snapshot returns it to todo
    assert m.snapshot(path) == 0
    c.close(); m.stop()

    m2 = native.Master(timeout_ms=5000, failure_max=3)
    try:
        assert m2.recover(path) == 0
        c2 = native.MasterClient("127.0.0.1", m2.port)
        seen = set()
        for _ in range(2):
            t, chunks = c2.get_task()
            assert t >= 0
            seen.update(chunks)
        assert seen == {"x", "y"}
        c2.close()
    finally:
        m2.stop()


def test_pserver_stop_unblocks_sync_waiter():
    """stop() must wake a trainer blocked on the sync barrier (e.g. its
    peer died) instead of deadlocking the join."""
    import time

    s = native.ParameterServer(num_trainers=2, sync=True)
    c = native.PServerClient("127.0.0.1", s.port)
    c.init_param("w", np.zeros(2, np.float32), opt_kind=native.OPT_SGD,
                 lr=1.0)
    err = {}

    def lone_trainer():
        try:
            c.send_grad("w", np.ones(2, np.float32))  # blocks: no peer
        except RuntimeError as e:
            err["e"] = e

    t = threading.Thread(target=lone_trainer)
    t.start()
    time.sleep(0.3)
    s.stop()  # must not deadlock
    t.join(timeout=10)
    assert not t.is_alive()
    assert "e" in err  # waiter surfaced the shutdown, not a fake update
    c.close()


def test_pserver_checkpoint_preserves_optimizer_config(tmp_path):
    """A restored server must keep the same optimizer kind/lr, not fall
    back to default SGD."""
    path = str(tmp_path / "ckpt.bin")
    s = native.ParameterServer(num_trainers=1, sync=True)
    c = native.PServerClient("127.0.0.1", s.port)
    c.init_param("w", np.zeros(2, np.float32),
                 opt_kind=native.OPT_MOMENTUM, lr=0.5, hp1=0.9)
    g = np.ones(2, np.float32)
    c.send_grad("w", g)          # v=1, w=-0.5
    assert s.save(path) == 0
    c.close(); s.stop()

    s2 = native.ParameterServer(num_trainers=1, sync=True)
    try:
        assert s2.load(path) == 0
        c2 = native.PServerClient("127.0.0.1", s2.port)
        got = c2.send_grad("w", g)   # v=0.9+1=1.9, w=-0.5-0.95=-1.45
        np.testing.assert_allclose(got, -1.45, rtol=1e-6)
        c2.close()
    finally:
        s2.stop()


def test_master_recover_keeps_dataset_guard(tmp_path):
    """recover() restores dataset_set_, so a post-recovery set_dataset
    does not duplicate the dataset."""
    path = str(tmp_path / "m.snap")
    m = native.Master(timeout_ms=5000, failure_max=3)
    c = native.MasterClient("127.0.0.1", m.port)
    c.set_dataset(["x"], chunks_per_task=1)
    assert m.snapshot(path) == 0
    c.close(); m.stop()

    m2 = native.Master(timeout_ms=5000, failure_max=3)
    try:
        assert m2.recover(path) == 0
        c2 = native.MasterClient("127.0.0.1", m2.port)
        c2.set_dataset(["x"], chunks_per_task=1)  # must be a no-op
        t0, _ = c2.get_task()
        assert t0 >= 0
        t1, _ = c2.get_task()
        assert t1 == native.MasterClient.NO_TASK  # no duplicate task
        c2.close()
    finally:
        m2.stop()


def test_master_client_dead_master_raises():
    m = native.Master(timeout_ms=5000, failure_max=3)
    c = native.MasterClient("127.0.0.1", m.port)
    m.stop()
    with pytest.raises(ConnectionError):
        for _ in range(3):  # first call may drain a buffered response
            c.get_task()
    c.close()


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "data.rio")
    w = native.RecordIOWriter(path)
    records = [b"hello", b"x" * 1000, b"", b"world"]
    for r in records:
        w.write(r)
    w.close()
    rd = native.RecordIOReader(path)
    got = list(rd)
    rd.close()
    assert got == records
    # corruption detected
    with open(path, "r+b") as f:
        f.seek(10)
        f.write(b"\xff")
    rd = native.RecordIOReader(path)
    with pytest.raises(IOError):
        list(rd)
    rd.close()


def test_buddy_allocator():
    a = native.BuddyAllocator(1 << 16, min_block=64)
    try:
        p1 = a.alloc(100)   # -> 128 block
        p2 = a.alloc(64)
        assert a.used == 128 + 64
        a.free(p1)
        assert a.used == 64
        a.free(p2)
        assert a.used == 0
        # coalescing: after freeing everything a max-size alloc works
        p3 = a.alloc(1 << 15)
        assert p3
        a.free(p3)
        with pytest.raises(MemoryError):
            a.alloc(1 << 20)
    finally:
        a.destroy()
