"""Reader creators incl. recordio + master-distributed cloud_reader
(reference: python/paddle/v2/reader/creator.py, v2/master/client.py)."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import native
from paddle_tpu.reader import creator


def test_np_array_and_text_file(tmp_path):
    arr = np.arange(6).reshape(3, 2)
    assert [r.tolist() for r in creator.np_array(arr)()] == \
        [[0, 1], [2, 3], [4, 5]]
    p = tmp_path / "t.txt"
    p.write_text("a\nb\nc\n")
    assert list(creator.text_file(str(p))()) == ["a", "b", "c"]


def test_recordio_roundtrip_pickled_samples(tmp_path):
    path = str(tmp_path / "chunk0.rio")
    samples = [([1, 2, 3], 0), ([4, 5], 1)]
    creator.recordio_writer(path, samples)
    assert list(creator.recordio(path)()) == samples


def test_cloud_reader_via_master(tmp_path):
    """Samples flow chunk-files -> master task lease -> reader, exactly
    once per pass."""
    chunks = []
    all_samples = []
    for i in range(4):
        path = str(tmp_path / ("c%d.rio" % i))
        samples = [(i, j) for j in range(3)]
        creator.recordio_writer(path, samples)
        chunks.append(path)
        all_samples.extend(samples)

    m = native.Master(timeout_ms=10000, failure_max=3)
    try:
        boot = native.MasterClient("127.0.0.1", m.port)
        boot.set_dataset(chunks, chunks_per_task=2)
        boot.close()

        rd = creator.cloud_reader("127.0.0.1:%d" % m.port, pass_num=1)
        got = list(rd())
        assert sorted(got) == sorted(all_samples)

        # second pass serves the same data again (queue rotated)
        rd2 = creator.cloud_reader("127.0.0.1:%d" % m.port, pass_num=1)
        got2 = list(rd2())
        assert sorted(got2) == sorted(all_samples)
    finally:
        m.stop()


def test_trainer_config_helpers_dsl():
    """The original DSL trains a model end-to-end through the one
    TPU stack (reference: trainer_config_helpers/tests/layers_test)."""
    import paddle_tpu.v2 as paddle_v2
    from paddle_tpu import trainer_config_helpers as tch

    paddle_v2.init()
    x = tch.data_layer(name="x", type=tch.dense_vector(8))
    y = tch.data_layer(name="y", type=tch.dense_vector(1))
    h = tch.fc_layer(input=x, size=16, act=tch.ReluActivation())
    pred = tch.fc_layer(input=h, size=1, act=tch.LinearActivation())
    cost = tch.regression_cost(input=pred, label=y)

    params = paddle_v2.parameters.create(cost)
    trainer = paddle_v2.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle_v2.optimizer.Adam(learning_rate=0.05))

    rs = np.random.RandomState(0)
    w = rs.randn(8, 1).astype(np.float32)

    def reader():
        for _ in range(10):
            batch = []
            for _ in range(16):
                xv = rs.randn(8).astype(np.float32)
                batch.append((xv, xv @ w))
            yield batch

    costs = []
    trainer.train(reader=reader, num_passes=2, event_handler=lambda e:
                  costs.append(e.cost)
                  if isinstance(e, paddle_v2.event.EndIteration) else None)
    assert costs[-1] < costs[0]
