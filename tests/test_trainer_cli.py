"""paddle_trainer-style CLI (reference: paddle/trainer/TrainerMain.cpp
`paddle train --config=...` over config_parser + trainer_config_helpers
configs): config executes, passes train, cost falls, params tar saved,
warm-start resumes."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG = os.path.join(REPO, "examples", "trainer_config_fit_a_line.py")


def _run_cli(args, timeout=240):
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"}
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.tools.trainer_cli"] + args,
        env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout)


def test_trainer_cli_end_to_end(tmp_path):
    out_dir = str(tmp_path / "output")
    r = _run_cli(["--config=%s" % CONFIG, "--num_passes=2",
                  "--save_dir=%s" % out_dir, "--log_period=50"])
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stdout.splitlines() if "AvgCost" in l]
    assert len(lines) == 2, r.stdout
    costs = [float(l.split("AvgCost ")[1].split(",")[0]) for l in lines]
    assert costs[1] < costs[0], costs
    tar0 = os.path.join(out_dir, "pass-00000", "params.tar")
    tar1 = os.path.join(out_dir, "pass-00001", "params.tar")
    assert os.path.exists(tar0) and os.path.exists(tar1)

    # warm start from pass-1 params (ParamUtil --init_model_path):
    # continues from the better model, so the first pass's cost stays
    # below the cold run's first pass
    r2 = _run_cli(["--config=%s" % CONFIG, "--num_passes=1",
                   "--init_model_path=%s" % tar1, "--start_pass=2",
                   "--log_period=50"])
    assert r2.returncode == 0, r2.stderr[-2000:]
    warm = [float(l.split("AvgCost ")[1].split(",")[0])
            for l in r2.stdout.splitlines() if "AvgCost" in l]
    assert warm and warm[0] < costs[0], (warm, costs)
    assert "Pass 2" in r2.stdout
