"""Elastic pserver coordination over the native master's TTL-lease
registry (reference: go/pserver/etcd_client.go:31-97 — slot
registration with TTL keep-alive, desired-count rendezvous, trainer
re-discovery; go/pserver/service.go checkpoint/restore)."""

import time

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu import native
from paddle_tpu.distributed import (DistributeTranspiler,
                                    ElasticRegistry)
from paddle_tpu.ops.dist import ClientPool

TTL_MS = 300


def test_lease_register_expire_rediscover():
    master = native.Master()
    try:
        reg = ElasticRegistry("127.0.0.1", master.port)
        # two pservers claim the two slots; a third finds none free
        slot_a, lease_a = reg.register_pserver("h1:1", 2, ttl_ms=TTL_MS)
        slot_b, lease_b = reg.register_pserver("h2:2", 2, ttl_ms=TTL_MS)
        assert {slot_a, slot_b} == {0, 1}
        try:
            reg.register_pserver("h3:3", 2, ttl_ms=TTL_MS, timeout=0.3)
            raise AssertionError("third pserver should find no slot")
        except TimeoutError:
            pass

        # rendezvous sees both, ordered by slot
        assert reg.wait_for_pservers(2, timeout=5) == ["h1:1", "h2:2"]

        # keep-alive holds the lease well past one TTL
        time.sleep(TTL_MS / 1000.0 * 3)
        assert len(reg.pservers()) == 2
        assert not lease_a.lapsed

        # kill pserver A (stop heartbeating): its lease lapses and
        # discovery stops returning it
        lease_a._stop.set()
        lease_a._thread.join(timeout=5)
        deadline = time.time() + 5
        while len(reg.pservers()) != 1 and time.time() < deadline:
            time.sleep(0.05)
        assert reg.pservers() == {slot_b: "h2:2"}

        # a replacement claims the freed slot; rendezvous recovers
        slot_c, lease_c = reg.register_pserver("h4:4", 2, ttl_ms=TTL_MS)
        assert slot_c == slot_a
        assert sorted(reg.pservers().values()) == ["h2:2", "h4:4"]
        lease_b.release()
        lease_c.release()
        reg.close()
    finally:
        master.stop()


def test_kill_pserver_and_recover_training():
    """End-to-end elasticity: trainer discovers pservers through the
    registry, one pserver dies mid-training, a replacement restores
    its shard from checkpoint and re-registers, the trainer
    re-discovers and training continues converging."""
    import tempfile
    import os

    master = native.Master()
    servers = [native.ParameterServer(num_trainers=1, sync=True)
               for _ in range(2)]
    reg = ElasticRegistry("127.0.0.1", master.port)
    leases = {}
    try:
        for s in servers:
            slot, lease = reg.register_pserver(
                "127.0.0.1:%d" % s.port, 2, ttl_ms=TTL_MS)
            leases[slot] = lease

        # trainer side: rendezvous for the endpoints, then transpile
        endpoints = reg.wait_for_pservers(2, timeout=10)
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1, act=None)
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        cost = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=pred, label=y))
        optimize_ops, params_grads = fluid.optimizer.SGD(
            learning_rate=0.1).minimize(cost)
        t = DistributeTranspiler()
        t.transpile(optimize_ops=optimize_ops, params_grads=params_grads,
                    pservers=",".join(endpoints), trainers=1,
                    split_method=lambda vs, n:
                        __import__("paddle_tpu.distributed",
                                   fromlist=["split_dense_variable"])
                        .split_dense_variable(vs, n, min_block_size=2))

        place = fluid.CPUPlace()
        exe = fluid.Executor(place)
        exe.run(fluid.default_startup_program())
        t.init_pservers()

        rs = np.random.RandomState(0)
        xs = rs.rand(32, 4).astype(np.float32)
        ys = (xs @ np.array([1.0, -2.0, 0.5, 3.0], np.float32)
              ).reshape(-1, 1)
        feeder = fluid.DataFeeder(place=place, feed_list=[x, y])
        feed = feeder.feed([(xs[i], ys[i]) for i in range(32)])

        losses = []
        for _ in range(10):
            out, = exe.run(fluid.default_main_program(), feed=feed,
                           fetch_list=[cost])
            losses.append(float(np.asarray(out).reshape(-1)[0]))

        # ---- pserver 0 dies; its shard recovers from checkpoint ----
        ckpt = os.path.join(tempfile.mkdtemp(), "ps0.ckpt")
        assert servers[0].save(ckpt) == 0
        dead_port = servers[0].port
        leases[0]._stop.set()            # heartbeat stops with it
        servers[0].stop()
        ClientPool.reset()               # trainer drops dead sockets
        deadline = time.time() + 5
        while len(reg.pservers()) != 1 and time.time() < deadline:
            time.sleep(0.05)

        replacement = native.ParameterServer(num_trainers=1, sync=True)
        assert replacement.load(ckpt) == 0
        slot, lease = reg.register_pserver(
            "127.0.0.1:%d" % replacement.port, 2, ttl_ms=TTL_MS)
        assert slot == 0
        leases[0] = lease
        servers[0] = replacement

        # trainer re-discovers and repoints the dead endpoint's blocks
        new_endpoints = reg.wait_for_pservers(2, timeout=10)
        assert "127.0.0.1:%d" % dead_port not in new_endpoints
        remap = {"127.0.0.1:%d" % dead_port:
                 "127.0.0.1:%d" % replacement.port}
        for pname, blocks in t.param_blocks.items():
            t.param_blocks[pname] = [
                (remap.get(ep, ep), b, s) for ep, b, s in blocks]
        for op in fluid.default_main_program().global_block().ops:
            if op.type == "dist_send":
                op.desc.attrs["blocks"] = [
                    (remap.get(ep, ep), b, s)
                    for ep, b, s in op.desc.attrs["blocks"]]

        for _ in range(10):
            out, = exe.run(fluid.default_main_program(), feed=feed,
                           fetch_list=[cost])
            losses.append(float(np.asarray(out).reshape(-1)[0]))
        # training continued from the checkpointed state: no blow-up,
        # further convergence
        assert losses[-1] < losses[9], (losses[9], losses[-1])
        assert losses[-1] < losses[0]
        assert replacement.num_updates() > 0
    finally:
        ClientPool.reset()
        for lease in leases.values():
            lease._stop.set()
        for s in servers:
            s.stop()
        reg.close()
        master.stop()
