"""Op tests: activation family (reference: test_activation_op.py)."""

import numpy as np

from op_test import OpTest

RS = np.random.RandomState(7)


def _case(op_type, np_fn, attrs=None, lo=-1.0, hi=1.0, grad=True,
          max_rel=0.005, avoid=None):
    class _T(OpTest):
        def test(self):
            self.op_type = op_type
            x = RS.uniform(lo, hi, (4, 5)).astype("float32")
            if avoid is not None:
                # push points away from non-differentiable kinks
                for kink in avoid:
                    x[np.abs(x - kink) < 0.08] += 0.2
            self.inputs = {"X": x}
            self.attrs = attrs or {}
            self.outputs = {"Out": np_fn(x.astype("float64")).astype(
                "float32")}
            self.check_output()
            if grad:
                self.check_grad(["X"], "Out", max_relative_error=max_rel)
    _T.__name__ = "Test" + op_type.title().replace("_", "")
    return _T


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


TestSigmoid = _case("sigmoid", _sigmoid)
TestLogsigmoid = _case("logsigmoid", lambda x: np.log(_sigmoid(x)))
TestExp = _case("exp", np.exp)
TestRelu = _case("relu", lambda x: np.maximum(x, 0), avoid=[0.0])
TestTanh = _case("tanh", np.tanh)
TestTanhShrink = _case("tanh_shrink", lambda x: x - np.tanh(x),
                       max_rel=0.05)
TestSqrt = _case("sqrt", np.sqrt, lo=0.2, hi=1.2)
TestAbs = _case("abs", np.abs, avoid=[0.0])
TestCeil = _case("ceil", np.ceil, grad=False)
TestFloor = _case("floor", np.floor, grad=False)
TestRound = _case("round", np.round, grad=False)
TestReciprocal = _case("reciprocal", lambda x: 1.0 / x, lo=0.5, hi=1.5)
TestLog = _case("log", np.log, lo=0.3, hi=1.5)
TestSquare = _case("square", np.square)
TestSoftplus = _case("softplus", lambda x: np.log(1 + np.exp(x)))
TestSoftsign = _case("softsign", lambda x: x / (1 + np.abs(x)))
TestBRelu = _case("brelu", lambda x: np.clip(x, -0.3, 0.6),
                  attrs={"t_min": -0.3, "t_max": 0.6},
                  avoid=[-0.3, 0.6])
TestLeakyRelu = _case("leaky_relu", lambda x: np.where(x >= 0, x, 0.1 * x),
                      attrs={"alpha": 0.1}, avoid=[0.0])
TestElu = _case("elu", lambda x: np.where(x >= 0, x, 1.5 * (np.exp(x) - 1)),
                attrs={"alpha": 1.5}, avoid=[0.0])
TestRelu6 = _case("relu6", lambda x: np.clip(x, 0, 6), avoid=[0.0])
TestPowAct = _case("pow", lambda x: np.power(x, 3.0),
                   attrs={"factor": 3.0}, lo=0.2, hi=1.2)
TestSTanh = _case("stanh", lambda x: 1.7159 * np.tanh(2.0 / 3.0 * x),
                  attrs={"scale_a": 2.0 / 3.0, "scale_b": 1.7159})
TestSoftshrink = _case(
    "softshrink",
    lambda x: np.where(x > 0.4, x - 0.4, np.where(x < -0.4, x + 0.4, 0.0)),
    attrs={"lambda": 0.4}, avoid=[-0.4, 0.4])
TestHardShrink = _case(
    "hard_shrink", lambda x: np.where(np.abs(x) > 0.4, x, 0.0),
    attrs={"threshold": 0.4}, avoid=[-0.4, 0.4])
TestThresholdedRelu = _case(
    "thresholded_relu", lambda x: np.where(x > 0.3, x, 0.0),
    attrs={"threshold": 0.3}, avoid=[0.3])
TestHardSigmoid = _case(
    "hard_sigmoid", lambda x: np.clip(0.3 * x + 0.5, 0, 1),
    attrs={"slope": 0.3, "offset": 0.5}, grad=False)
TestSwish = _case("swish", lambda x: x * _sigmoid(2.0 * x),
                  attrs={"beta": 2.0})


class TestSoftmaxOp(OpTest):
    op_type = "softmax"

    def test(self):
        x = RS.uniform(-1, 1, (4, 6)).astype("float32")
        e = np.exp(x - x.max(axis=-1, keepdims=True))
        self.inputs = {"X": x}
        self.outputs = {"Out": e / e.sum(axis=-1, keepdims=True)}
        self.check_output()
        self.check_grad(["X"], "Out", max_relative_error=0.05)


class TestPReluOp(OpTest):
    op_type = "prelu"

    def test(self):
        x = RS.uniform(-1, 1, (4, 5)).astype("float32")
        x[np.abs(x) < 0.05] += 0.2
        alpha = np.asarray([0.25], dtype="float32")
        self.inputs = {"X": x, "Alpha": alpha}
        self.outputs = {"Out": np.where(x >= 0, x, 0.25 * x)}
        self.check_output()
        self.check_grad(["X", "Alpha"], "Out")
