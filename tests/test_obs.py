"""paddle_tpu.obs: span tracer (nesting, threads, Chrome JSON schema),
labeled metrics registry, telemetry, executor/profiler back-compat,
and the unified serving /metrics surface.

Tier-1 (CPU): the observability layer must never change results — it
only watches — so these tests assert on the emitted events/metrics and
on the old profiler API staying intact underneath."""

import json
import threading

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.obs import registry as obs_registry
from paddle_tpu.obs import telemetry as obs_tele
from paddle_tpu.obs import trace as obs_trace
from paddle_tpu.tools.obs_dump import (validate_chrome_trace,
                                       validate_prometheus_text)


@pytest.fixture(autouse=True)
def _tracer_off_after():
    yield
    obs_trace.disable()
    obs_trace.reset()


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------

def test_span_nesting_containment():
    with obs_trace.tracing():
        with obs_trace.span("outer", kind="test"):
            with obs_trace.span("inner"):
                pass
            with obs_trace.span("inner2"):
                pass
    events = [e for e in obs_trace.events() if e["ph"] == "X"]
    by_name = {e["name"]: e for e in events}
    assert set(by_name) == {"outer", "inner", "inner2"}
    outer, inner = by_name["outer"], by_name["inner"]
    assert outer["tid"] == inner["tid"]
    # children close before the parent, so containment holds
    for child in (inner, by_name["inner2"]):
        assert outer["ts"] <= child["ts"] + 1e-3
        assert child["ts"] + child["dur"] <= \
            outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"] == {"kind": "test"}


def test_span_disabled_is_noop():
    assert not obs_trace.is_enabled()
    with obs_trace.span("ghost"):
        pass
    obs_trace.instant("ghost_i")
    assert obs_trace.events() == []


def test_span_set_args_and_instant():
    with obs_trace.tracing():
        with obs_trace.span("s") as sp:
            sp.set(batch=4, compiled=True)
        obs_trace.instant("moment", label="x")
    evs = obs_trace.events()
    sp = next(e for e in evs if e["name"] == "s")
    assert sp["args"] == {"batch": 4, "compiled": True}
    inst = next(e for e in evs if e["name"] == "moment")
    assert inst["ph"] == "i" and inst["args"] == {"label": "x"}


def test_tracer_thread_safety_and_tracks():
    n_threads, n_spans = 8, 50

    def worker(i):
        for j in range(n_spans):
            with obs_trace.span("w%d" % i, j=j):
                pass

    with obs_trace.tracing():
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    evs = obs_trace.events()
    spans = [e for e in evs if e["ph"] == "X"]
    assert len(spans) == n_threads * n_spans
    assert obs_trace.dropped_events() == 0
    # per-thread tracks: each worker's spans share one tid (the OS may
    # reuse idents of exited threads, so distinct-count can be < N);
    # every track announced itself with a thread_name meta row
    tids = {e["name"]: set() for e in spans}
    for e in spans:
        tids[e["name"]].add(e["tid"])
    assert all(len(s) == 1 for s in tids.values())
    all_tids = set().union(*tids.values())
    metas = [e for e in evs if e["ph"] == "M"
             and e["name"] == "thread_name"]
    assert {m["tid"] for m in metas} == all_tids


def test_tracer_buffer_bound_counts_drops():
    with obs_trace.tracing(max_events=10):
        for i in range(50):
            with obs_trace.span("s%d" % i):
                pass
        assert obs_trace.dropped_events() > 0
        doc = obs_trace.to_chrome_trace()
    assert doc["otherData"]["dropped_events"] > 0
    assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) <= 10
    # the guard-scoped bound must NOT leak: a later enable() (no
    # explicit bound) gets the previous cap back, not the tiny one —
    # otherwise every trace in the process silently drops events
    # after the tenth
    obs_trace.enable()
    try:
        for i in range(50):
            with obs_trace.span("t%d" % i):
                pass
        assert obs_trace.dropped_events() == 0
        assert len([e for e in obs_trace.events()
                    if e["ph"] == "X"]) == 50
    finally:
        obs_trace.disable()
        obs_trace.reset()


def test_chrome_trace_schema_and_file_round_trip(tmp_path):
    with obs_trace.tracing():
        with obs_trace.span("a"):
            with obs_trace.span("b"):
                pass
    path = str(tmp_path / "trace.json")
    doc = obs_trace.export_chrome_trace(path)
    validate_chrome_trace(doc)
    with open(path) as f:
        reloaded = json.load(f)
    events = validate_chrome_trace(reloaded)
    assert {"a", "b"} <= {e["name"] for e in events}


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_labeled_counter_render_and_identity():
    reg = obs_registry.MetricsRegistry()
    fam = reg.counter("widgets_total", "widgets", labelnames=("kind",))
    fam.labels(kind="a").inc(2)
    fam.labels(kind="b").inc()
    assert fam.labels(kind="a") is fam.labels(kind="a")
    assert reg.counter("widgets_total", labelnames=("kind",)) is fam
    text = reg.render_text()
    assert '# TYPE widgets_total counter' in text
    assert 'widgets_total{kind="a"} 2' in text
    assert 'widgets_total{kind="b"} 1' in text
    # a family is not directly incrementable; labels must match
    with pytest.raises(ValueError):
        fam.inc()
    with pytest.raises(ValueError):
        fam.labels(wrong="x")
    # name re-registration with different type/labels is an error
    with pytest.raises(ValueError):
        reg.gauge("widgets_total")


def test_registry_labeled_histogram_render():
    reg = obs_registry.MetricsRegistry()
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0),
                      labelnames=("stage",))
    h.labels(stage="pad").observe(0.05)
    h.labels(stage="pad").observe(0.5)
    text = reg.render_text()
    assert 'lat_seconds_bucket{stage="pad",le="0.1"} 1' in text
    assert 'lat_seconds_bucket{stage="pad",le="+Inf"} 2' in text
    assert 'lat_seconds_count{stage="pad"} 2' in text
    names = validate_prometheus_text(text)
    assert "lat_seconds_bucket" in names


def test_registry_groups_and_jsonl():
    root = obs_registry.MetricsRegistry()
    sub = obs_registry.MetricsRegistry()
    sub.counter("sub_total").inc(3)
    root.gauge("root_gauge").set(1.5)
    root.attach("grp", sub)
    text = root.render_text()
    assert "root_gauge 1.5" in text and "sub_total 3" in text
    samples = {s["name"]: s for s in root.to_dict()["metrics"]}
    assert samples["sub_total"]["group"] == "grp"
    for line in root.render_jsonl().strip().splitlines():
        json.loads(line)
    # replacing a mount drops the old sub-registry from the render
    root.attach("grp", obs_registry.MetricsRegistry())
    assert "sub_total" not in root.render_text()


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def test_telemetry_step_and_gauges():
    reg = obs_registry.get_registry()
    steps_before = reg.counter(
        "trainer_steps_total", labelnames=("trainer",)) \
        .labels(trainer="t_obs").value
    with obs_tele.step("t_obs", examples=32):
        pass
    fam = reg.counter("trainer_steps_total", labelnames=("trainer",))
    assert fam.labels(trainer="t_obs").value == steps_before + 1
    assert reg.counter("trainer_examples_total",
                       labelnames=("trainer",)) \
        .labels(trainer="t_obs").value >= 32
    assert reg.gauge("trainer_examples_per_sec",
                     labelnames=("trainer",)) \
        .labels(trainer="t_obs").value > 0
    obs_tele.set_gauge("trainer_grad_norm", 1.25, trainer="t_obs")
    assert reg.gauge("trainer_grad_norm", labelnames=("trainer",)) \
        .labels(trainer="t_obs").value == 1.25
    obs_tele.set_gauge("loss_scale", 2.0)
    assert reg.gauge("loss_scale").value == 2.0
    flat = obs_tele.snapshot()
    assert flat["trainer_steps_total{trainer=t_obs}"] >= 1


def test_telemetry_snapshot_delta_counters_vs_gauges():
    reg = obs_registry.get_registry()
    reg.counter("delta_total").inc(2)
    reg.gauge("delta_gauge").set(5)
    reg.histogram("delta_seconds").observe(0.2)
    before = obs_tele.snapshot()
    assert obs_tele.snapshot_delta(before) == {}   # nothing moved
    reg.counter("delta_total").inc(3)
    reg.gauge("delta_gauge").set(7)
    reg.histogram("delta_seconds").observe(0.3)
    reg.counter("delta_untouched_total").inc(0)    # new but at 0
    d = obs_tele.snapshot_delta(before)
    assert d["delta_total"] == 3                   # increment, not 5
    assert d["delta_gauge"] == 7                   # current value
    assert d["delta_seconds_count"] == 1
    assert abs(d["delta_seconds_sum"] - 0.3) < 1e-6
    assert "delta_untouched_total" not in d


def test_telemetry_snapshot_delta_gauge_disappears():
    """A gauge present in `before` but gone from the registry (reset,
    or a family child that no longer renders) must simply drop out of
    the delta — never KeyError, never report a phantom value."""
    reg = obs_registry.get_registry()
    reg.gauge("vanishing_gauge").set(3)
    reg.counter("surviving_total").inc(1)
    before = obs_tele.snapshot()
    assert before["vanishing_gauge"] == 3
    # a fresh registry: the gauge (and everything else) is gone
    obs_registry.reset_registry()
    reg2 = obs_registry.get_registry()
    reg2.counter("surviving_total").inc(5)
    d = obs_tele.snapshot_delta(before)
    assert "vanishing_gauge" not in d
    # the surviving counter diffs against the OLD snapshot's 1
    assert d["surviving_total"] == 4
    # and the degenerate case: delta against a gauge-only snapshot
    # over an empty registry is just empty
    obs_registry.reset_registry()
    assert obs_tele.snapshot_delta({"vanishing_gauge": 3}) == {}


def test_registry_concurrent_writers_exact_totals():
    """Counter/histogram increments from many threads (racing the
    labeled-family get-or-create path too) must land exactly; a
    concurrent render/snapshot must neither crash nor corrupt."""
    reg = obs_registry.get_registry()
    n_threads, n_iter = 8, 400
    errors = []

    def writer(tid):
        try:
            for i in range(n_iter):
                reg.counter("conc_total").inc()
                reg.counter("conc_labeled_total",
                            labelnames=("worker",)) \
                   .labels(worker="w%d" % (tid % 4)).inc()
                reg.histogram(
                    "conc_seconds",
                    buckets=(0.001, 0.01, 0.1)).observe(0.01 * (i % 3))
                reg.gauge("conc_gauge").set(i)
        except Exception as exc:  # noqa: BLE001 — surface in main
            errors.append(exc)

    def reader():
        try:
            for _ in range(50):
                text = reg.render_text()
                validate_prometheus_text(text)
                obs_tele.snapshot()
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)] \
        + [threading.Thread(target=reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert reg.counter("conc_total").value == n_threads * n_iter
    fam = reg.counter("conc_labeled_total", labelnames=("worker",))
    assert sum(s["value"] for s in fam.samples()) == n_threads * n_iter
    hist = reg.histogram("conc_seconds", buckets=(0.001, 0.01, 0.1))
    assert hist.count == n_threads * n_iter
    # the final render is stable and parseable after the storm
    names = validate_prometheus_text(reg.render_text())
    assert "conc_total" in names and "conc_labeled_total" in names


def test_registry_histogram_count_below_interpolates():
    h = obs_registry.Histogram("lat", buckets=(0.01, 0.1, 1.0))
    assert h.fraction_below(0.05) == 1.0  # empty: nothing violates
    for v in (0.005, 0.05, 0.5, 5.0):     # one per bucket incl +Inf
        h.observe(v)
    assert h.count_below(0.01) == 1
    # halfway through the (0.01, 0.1] bucket: 1 full + 0.5 interp
    assert abs(h.count_below(0.055) - 1.5) < 1e-9
    assert h.count_below(1.0) == 3
    # beyond the largest finite bound: the +Inf bucket counts
    assert h.count_below(10.0) == 4
    assert abs(h.fraction_below(0.1) - 0.5) < 1e-9


def _tiny_program():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    h = fluid.layers.fc(input=x, size=3, act="relu")
    out = fluid.layers.mean(x=h)
    return x, out


def test_executor_telemetry_counts_runs_transfers_and_retraces():
    _, out = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    runs0 = obs_registry.get_registry().counter(
        "executor_runs_total").value
    h2d0 = obs_tele.transfer_bytes("h2d")
    traces0 = obs_tele.jit_trace_count()
    exe.run(fluid.default_main_program(),
            feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[out])
    assert obs_registry.get_registry().counter(
        "executor_runs_total").value > runs0
    assert obs_tele.transfer_bytes("h2d") - h2d0 >= 2 * 4 * 4
    traces_after_first = obs_tele.jit_trace_count()
    assert traces_after_first > traces0  # first call compiled
    # same shape again: no retrace counted
    exe.run(fluid.default_main_program(),
            feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[out])
    assert obs_tele.jit_trace_count() == traces_after_first
    # new batch size: the jit specializes -> retrace detected even
    # though neither profiler nor tracing is enabled
    exe.run(fluid.default_main_program(),
            feed={"x": np.ones((5, 4), np.float32)}, fetch_list=[out])
    assert obs_tele.jit_trace_count() > traces_after_first


# ---------------------------------------------------------------------------
# executor/profiler integration + back-compat
# ---------------------------------------------------------------------------

def test_executor_spans_and_profiler_records_together():
    _, out = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": np.ones((2, 4), np.float32)}
    with obs_trace.tracing():
        with fluid.profiler.profiler():
            exe.run(fluid.default_main_program(), feed=feed,
                    fetch_list=[out])
            exe.run(fluid.default_main_program(), feed=feed,
                    fetch_list=[out], eager=True)
    # old API: the per-op/per-segment table still populates
    records = fluid.profiler.get_profile_records()
    assert any("jit_segment" in k for k in records)
    assert any("mul" in k or "matmul" in k for k in records)
    # new layer: the same activity produced trace spans
    events = validate_chrome_trace(obs_trace.to_chrome_trace())
    names = {e["name"] for e in events if e["ph"] == "X"}
    assert any(n.startswith("executor/run") for n in names)
    assert any(n.startswith("executor/jit_segment") for n in names)
    assert any("mean" in n for n in names)  # eager op span
    # run spans contain their segment spans on the same thread
    runs = [e for e in events if e["ph"] == "X"
            and e["name"] == "executor/run"]
    segs = [e for e in events if e["ph"] == "X"
            and e["name"].startswith("executor/jit_segment")]
    assert any(r["ts"] <= s["ts"] + 1e-3
               and s["ts"] + s["dur"] <= r["ts"] + r["dur"] + 1e-3
               and r["tid"] == s["tid"]
               for r in runs for s in segs)


def test_tracing_without_profiler_leaves_table_empty():
    _, out = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.profiler.reset_profiler()
    with obs_trace.tracing():
        exe.run(fluid.default_main_program(),
                feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[out], eager=True)
    # spans recorded, but the profiler table stays untouched
    assert any(e["ph"] == "X" for e in obs_trace.events())
    assert fluid.profiler.get_profile_records() == {}


def test_profile_records_min_clamped_for_zero_call_entries():
    fluid.profiler.reset_profiler()
    # a defaultdict read (e.g. an aborted record_event path) creates a
    # zero-call entry; the exported table must not leak inf
    fluid.profiler._records["phantom"]  # noqa: B018 — touch creates it
    fluid.profiler.record("real", 0.5)
    records = fluid.profiler.get_profile_records()
    assert records["phantom"]["calls"] == 0
    assert records["phantom"]["min"] == 0.0
    assert records["real"]["min"] == 0.5
    fluid.profiler.reset_profiler()


def test_profiler_record_delegates_to_registry():
    before = obs_tele.snapshot().get(
        "profiler_event_calls_total{event=obs_delegate}", 0)
    fluid.profiler.record("obs_delegate", 0.01)
    flat = obs_tele.snapshot()
    assert flat["profiler_event_calls_total{event=obs_delegate}"] \
        == before + 1
    assert flat["profiler_event_seconds_total{event=obs_delegate}"] > 0


# ---------------------------------------------------------------------------
# serving shim: unified /metrics
# ---------------------------------------------------------------------------

def test_serving_metrics_render_is_unified():
    from paddle_tpu.serving.metrics import ServingMetrics

    metrics = ServingMetrics()
    metrics.requests_total.inc(2)
    obs_registry.get_registry().counter("executor_runs_total").inc(0)
    text = metrics.render_text()
    # old serving names preserved...
    assert "serving_requests_total 2" in text
    assert "serving_queue_seconds_count 0" in text
    # ...next to executor-side metrics from the shared registry
    assert "executor_runs_total" in text
    validate_prometheus_text(text)
    # the shim still mirrors stage latencies into the profiler table
    metrics.observe_stage("queue", 0.004)
    assert "serving/queue" in fluid.profiler.get_profile_records()


def test_obs_dump_cli_dump_modes(tmp_path):
    from paddle_tpu.tools import obs_dump

    # the registry is reset between tests (conftest fresh_obs); the
    # dump needs at least one sample of its own
    obs_registry.get_registry().counter("cli_dump_total").inc()
    with obs_trace.tracing():
        with obs_trace.span("cli_span"):
            pass
        trace_path = str(tmp_path / "t.json")
        metrics_path = str(tmp_path / "m.prom")
        rc = obs_dump.main(["--trace-out", trace_path,
                            "--metrics-out", metrics_path])
    assert rc == 0
    events = validate_chrome_trace(trace_path)
    assert any(e["name"] == "cli_span" for e in events)
    with open(metrics_path) as f:
        validate_prometheus_text(f.read())
    assert obs_dump.main(["--check", trace_path]) == 0
    jsonl_path = str(tmp_path / "m.jsonl")
    assert obs_dump.main(["--metrics-out", jsonl_path,
                          "--format", "jsonl"]) == 0
    with open(jsonl_path) as f:
        for line in f.read().strip().splitlines():
            json.loads(line)
