"""Op tests: loss family (reference: test_cross_entropy_op.py,
test_softmax_with_cross_entropy_op.py, test_sigmoid_cross_entropy_with_
logits_op.py, test_huber_loss_op.py, test_hinge_loss_op.py,
test_log_loss_op.py, test_rank_loss_op.py, test_margin_rank_loss_op.py,
test_modified_huber_loss_op.py, test_smooth_l1_loss_op.py)."""

import numpy as np

from op_test import OpTest

RS = np.random.RandomState(42)


def _softmax(x):
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


class TestCrossEntropy(OpTest):
    op_type = "cross_entropy"

    def test(self):
        n, c = 5, 4
        x = _softmax(RS.uniform(-1, 1, (n, c))).astype("float32")
        label = RS.randint(0, c, (n, 1)).astype("int64")
        out = -np.log(x[np.arange(n), label.ravel()] + 1e-8)
        self.inputs = {"X": x, "Label": label}
        self.outputs = {"Y": out.reshape(n, 1).astype("float32")}
        self.check_output()
        self.check_grad(["X"], "Y", max_relative_error=0.05)


class TestCrossEntropySoft(OpTest):
    op_type = "cross_entropy"

    def test(self):
        n, c = 5, 4
        x = _softmax(RS.uniform(-1, 1, (n, c))).astype("float32")
        label = _softmax(RS.uniform(-1, 1, (n, c))).astype("float32")
        out = (-label * np.log(x + 1e-8)).sum(axis=1, keepdims=True)
        self.inputs = {"X": x, "Label": label}
        self.attrs = {"soft_label": True}
        self.outputs = {"Y": out.astype("float32")}
        self.check_output()
        self.check_grad(["X"], "Y", max_relative_error=0.05,
                        no_grad_set={"Label"})


class TestSoftmaxWithCrossEntropy(OpTest):
    op_type = "softmax_with_cross_entropy"

    def test(self):
        n, c = 5, 4
        logits = RS.uniform(-1, 1, (n, c)).astype("float32")
        label = RS.randint(0, c, (n, 1)).astype("int64")
        sm = _softmax(logits)
        loss = -np.log(sm[np.arange(n), label.ravel()])
        self.inputs = {"Logits": logits, "Label": label}
        self.outputs = {"Softmax": sm.astype("float32"),
                        "Loss": loss.reshape(n, 1).astype("float32")}
        self.check_output()
        self.check_grad(["Logits"], "Loss", max_relative_error=0.05)


class TestSigmoidCrossEntropyWithLogits(OpTest):
    op_type = "sigmoid_cross_entropy_with_logits"

    def test(self):
        x = RS.uniform(-2, 2, (5, 4)).astype("float32")
        label = RS.randint(0, 2, (5, 4)).astype("float32")
        sig = 1 / (1 + np.exp(-x))
        out = -label * np.log(sig) - (1 - label) * np.log(1 - sig)
        self.inputs = {"X": x, "Label": label}
        self.outputs = {"Out": out.astype("float32")}
        self.check_output(atol=1e-4)
        self.check_grad(["X"], "Out", max_relative_error=0.05,
                        no_grad_set={"Label"})


class TestHingeLoss(OpTest):
    op_type = "hinge_loss"

    def test(self):
        logits = RS.uniform(-2, 2, (6, 1)).astype("float32")
        labels = RS.randint(0, 2, (6, 1)).astype("float32")
        out = np.maximum(0, 1 - (2 * labels - 1) * logits)
        self.inputs = {"Logits": logits, "Labels": labels}
        self.outputs = {"Loss": out.astype("float32")}
        self.check_output()
        self.check_grad(["Logits"], "Loss", no_grad_set={"Labels"},
                        max_relative_error=0.01)


class TestHuberLoss(OpTest):
    op_type = "huber_loss"

    def test(self):
        x = RS.uniform(0, 1, (6, 1)).astype("float32")
        y = RS.uniform(0, 1, (6, 1)).astype("float32")
        delta = 0.5
        r = y - x
        loss = np.where(np.abs(r) <= delta, 0.5 * r * r,
                        delta * (np.abs(r) - 0.5 * delta))
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"delta": delta}
        self.outputs = {"Residual": r.astype("float32"),
                        "Out": loss.astype("float32")}
        self.check_output(no_check_set=("Residual",))
        self.check_grad(["X", "Y"], "Out", max_relative_error=0.02)


class TestLogLoss(OpTest):
    op_type = "log_loss"

    def test(self):
        eps = 1e-4
        p = RS.uniform(0.1, 0.9, (6, 1)).astype("float32")
        l = RS.randint(0, 2, (6, 1)).astype("float32")
        loss = -l * np.log(p + eps) - (1 - l) * np.log(1 - p + eps)
        self.inputs = {"Predicted": p, "Labels": l}
        self.attrs = {"epsilon": eps}
        self.outputs = {"Loss": loss.astype("float32")}
        self.check_output()
        self.check_grad(["Predicted"], "Loss", no_grad_set={"Labels"},
                        max_relative_error=0.02)


class TestRankLoss(OpTest):
    op_type = "rank_loss"

    def test(self):
        label = RS.randint(0, 2, (6, 1)).astype("float32")
        left = RS.uniform(-1, 1, (6, 1)).astype("float32")
        right = RS.uniform(-1, 1, (6, 1)).astype("float32")
        d = left - right
        out = np.log1p(np.exp(d)) - label * d
        self.inputs = {"Label": label, "Left": left, "Right": right}
        self.outputs = {"Out": out.astype("float32")}
        self.check_output()
        self.check_grad(["Left", "Right"], "Out", no_grad_set={"Label"},
                        max_relative_error=0.02)


class TestMarginRankLoss(OpTest):
    op_type = "margin_rank_loss"

    def test(self):
        label = (RS.randint(0, 2, (6, 1)) * 2 - 1).astype("float32")
        x1 = RS.uniform(-1, 1, (6, 1)).astype("float32")
        x2 = RS.uniform(-1, 1, (6, 1)).astype("float32")
        margin = 0.1
        out = np.maximum(0, -label * (x1 - x2) + margin)
        self.inputs = {"Label": label, "X1": x1, "X2": x2}
        self.attrs = {"margin": margin}
        self.outputs = {"Out": out.astype("float32"),
                        "Activated": (out > 0).astype("float32")}
        self.check_output(no_check_set=("Activated",))


class TestModifiedHuberLoss(OpTest):
    op_type = "modified_huber_loss"

    def test(self):
        x = RS.uniform(-2, 2, (6, 1)).astype("float32")
        y = RS.randint(0, 2, (6, 1)).astype("float32")
        z = (2 * y - 1) * x
        loss = np.where(z < -1, -4 * z,
                        np.where(z < 1, np.square(1 - z), 0.0))
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"IntermediateVal": z.astype("float32"),
                        "Out": loss.astype("float32")}
        self.check_output(no_check_set=("IntermediateVal",))


class TestSmoothL1Loss(OpTest):
    op_type = "smooth_l1_loss"

    def test(self):
        x = RS.uniform(0, 1, (5, 4)).astype("float32")
        y = RS.uniform(0, 1, (5, 4)).astype("float32")
        sigma = 2.0
        s2 = sigma * sigma
        d = x - y
        ad = np.abs(d)
        val = np.where(ad < 1 / s2, 0.5 * s2 * d * d, ad - 0.5 / s2)
        out = val.sum(axis=1, keepdims=True)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"sigma": sigma}
        self.outputs = {"Diff": d.astype("float32"),
                        "Out": out.astype("float32")}
        self.check_output(no_check_set=("Diff",))
        self.check_grad(["X", "Y"], "Out", max_relative_error=0.02)
