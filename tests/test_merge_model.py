"""Single-file model packaging (reference: paddle/utils/merge_model.py)."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.utils.merge_model import (load_merged_model,
                                          merge_inference_model,
                                          merge_v2_model)


def _feeds(n=6):
    rs = np.random.RandomState(3)
    return {"x": rs.rand(n, 13).astype(np.float32)}


def test_merge_inference_dir_roundtrip(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.fc(input=x, size=4, act="tanh")
        out = fluid.layers.fc(input=y, size=1, act=None)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    want, = exe.run(main, feed=_feeds(), fetch_list=[out], scope=scope)

    model_dir = str(tmp_path / "model")
    with fluid.scope_guard(scope):
        fluid.io.save_inference_model(model_dir, ["x"], [out], exe,
                                      main_program=main)
    merged = merge_inference_model(model_dir, str(tmp_path / "one.tar"))

    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        prog, feeds, fetches = load_merged_model(merged, exe,
                                                 scope=scope2)
        assert feeds == ["x"]
        got, = exe.run(prog, feed=_feeds(), fetch_list=fetches,
                       scope=scope2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6)


def test_merge_v2_model(tmp_path):
    import paddle_tpu.v2 as v2

    v2.init(use_gpu=False)
    x = v2.layer.data(name="x", type=v2.data_type.dense_vector(13))
    hidden = v2.layer.fc(input=x, size=4, act=v2.activation.Tanh())
    out = v2.layer.fc(input=hidden, size=1,
                      act=v2.activation.Linear())
    params = v2.parameters.create(out)

    param_file = str(tmp_path / "params.tar")
    with open(param_file, "wb") as f:
        params.to_tar(f)

    merged = merge_v2_model(out, param_file,
                            str(tmp_path / "deploy.tar"))

    # the merged file alone reproduces the v2 inference result
    feed = _feeds()
    want = paddle.infer(output_layer=out, parameters=params,
                        input=[(row,) for row in feed["x"]])

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        prog, feeds, fetches = load_merged_model(merged, exe,
                                                 scope=scope)
        got, = exe.run(prog, feed={feeds[0]: feed["x"]},
                       fetch_list=fetches, scope=scope)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
