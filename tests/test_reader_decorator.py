"""Reader decorator semantics (reference: v2/reader/tests/decorator_test.py,
creator_test.py — same behavioral contract, own implementation)."""

import numpy as np
import pytest

import paddle_tpu.reader as reader


def _counting(n):
    def r():
        return iter(range(n))

    return r


def test_map_readers():
    out = list(reader.map_readers(lambda a, b: a + b,
                                  _counting(4), _counting(4))())
    assert out == [0, 2, 4, 6]


def test_shuffle_is_permutation():
    out = list(reader.shuffle(_counting(10), buf_size=4)())
    assert sorted(out) == list(range(10))


def test_chain():
    out = list(reader.chain(_counting(2), _counting(3))())
    assert out == [0, 1, 0, 1, 2]


def test_compose_flattens_tuples():
    def pairs():
        for i in range(3):
            yield (i, i * 10)

    out = list(reader.compose(_counting(3), pairs)())
    assert out == [(0, 0, 0), (1, 1, 10), (2, 2, 20)]


def test_compose_misaligned_raises():
    from paddle_tpu.reader.decorator import ComposeNotAligned

    misaligned = reader.compose(_counting(3), _counting(5))
    with pytest.raises(ComposeNotAligned):
        list(misaligned())


def test_compose_unchecked_stops_at_shortest():
    out = list(reader.compose(_counting(3), _counting(5),
                              check_alignment=False)())
    assert len(out) == 3


def test_compose_numpy_samples():
    """Samples may be arrays; the alignment check must not broadcast."""

    def arrays():
        for i in range(3):
            yield np.full((4,), i)

    out = list(reader.compose(arrays, arrays)())
    assert len(out) == 3 and len(out[0]) == 2


def test_buffered_preserves_order():
    out = list(reader.buffered(_counting(100), size=7)())
    assert out == list(range(100))


def test_firstn():
    assert list(reader.firstn(_counting(100), 5)()) == [0, 1, 2, 3, 4]
    assert list(reader.firstn(_counting(3), 5)()) == [0, 1, 2]


def test_cache_replays_single_pass_source():
    calls = []

    def once():
        calls.append(1)
        return iter(range(4))

    cached = reader.cache(once)
    assert list(cached()) == list(cached()) == [0, 1, 2, 3]
    assert len(calls) == 1


@pytest.mark.parametrize("order", [False, True])
def test_xmap_readers(order):
    out = list(reader.xmap_readers(lambda x: x * 2, _counting(50),
                                   process_num=4, buffer_size=8,
                                   order=order)())
    if order:
        assert out == [2 * i for i in range(50)]
    else:
        assert sorted(out) == [2 * i for i in range(50)]


def test_buffered_propagates_reader_exception():
    def failing():
        yield 1
        raise RuntimeError("corrupt source")

    it = reader.buffered(failing, size=4)()
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="corrupt source"):
        list(it)


@pytest.mark.parametrize("order", [False, True])
def test_xmap_propagates_mapper_exception(order):
    def mapper(x):
        if x == 7:
            raise ValueError("bad sample")
        return x

    it = reader.xmap_readers(mapper, _counting(50), process_num=2,
                             buffer_size=4, order=order)()
    with pytest.raises(ValueError, match="bad sample"):
        list(it)


def test_batch_shapes():
    batches = list(reader.batch(_counting(10), 4)())
    assert [len(b) for b in batches] == [4, 4]  # drop_last default
    batches = list(reader.batch(_counting(10), 4, drop_last=False)())
    assert [len(b) for b in batches] == [4, 4, 2]
