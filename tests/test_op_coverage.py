"""Coverage sweep for previously-untested ops + enforcement.

reference: python/paddle/v2/fluid/tests/test_*_op.py (one numeric
check per op over op_test.py:212 OpTest) — here the long tail is
gathered in one module, and `test_every_op_is_covered` fails whenever
a newly registered op lacks a test or an explicit skip reason.
"""

import os
import re

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.ragged import RaggedTensor, SelectedRows
from paddle_tpu.ops.registry import get_op_info, registered_ops

from op_test import OpTest


def _rag(seqs, dtype=np.float32):
    return RaggedTensor.from_sequences(
        [np.asarray(s, dtype) for s in seqs])


def _kernel(op):
    return get_op_info(op).kernel


# ---------------------------------------------------------------------------
# dense math / vision tail
# ---------------------------------------------------------------------------

class TestConvShift(OpTest):
    op_type = "conv_shift"
    rs = np.random.RandomState(0)
    x = rs.rand(3, 8).astype(np.float32)
    y = rs.rand(3, 3).astype(np.float32)
    ref = np.zeros_like(x)
    for i in range(3):
        for j in range(8):
            for k in range(3):
                ref[i, j] += x[i, (j + k - 1) % 8] * y[i, k]
    inputs = {"X": x, "Y": y}
    outputs = {"Out": ref}

    def test(self):
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestLinearComb(OpTest):
    op_type = "linear_comb"
    rs = np.random.RandomState(1)
    x = rs.rand(4, 6).astype(np.float32)   # k=3 chunks of size 2
    w = rs.rand(4, 3).astype(np.float32)
    ref = np.einsum("bk,bks->bs", w, x.reshape(4, 3, 2))
    inputs = {"X": x, "W": w}
    outputs = {"Out": ref}
    attrs = {"size": 2}

    def test(self):
        self.check_output()
        self.check_grad(["X", "W"], "Out")


class TestRotate(OpTest):
    op_type = "rotate"
    rs = np.random.RandomState(2)
    maps = rs.rand(2, 3, 4, 5).astype(np.float32)
    ref = np.flip(np.swapaxes(maps, 2, 3), axis=2).reshape(2, -1)
    inputs = {"X": maps.reshape(2, -1)}
    outputs = {"Out": ref}
    attrs = {"channels": 3, "height": 4, "width": 5}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestScaleSubRegion(OpTest):
    op_type = "scale_sub_region"
    rs = np.random.RandomState(3)
    x = rs.rand(2, 2, 4, 4).astype(np.float32)
    idx = np.array([[1, 1, 2, 3, 1, 2], [2, 2, 1, 2, 3, 4]], np.int32)
    ref = x.copy()
    for b in range(2):
        c0, c1, h0, h1, w0, w1 = idx[b] - 1
        ref[b, c0:c1 + 1, h0:h1 + 1, w0:w1 + 1] *= 2.0
    inputs = {"X": x, "Indices": idx}
    outputs = {"Out": ref}
    attrs = {"value": 2.0}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out", no_grad_set={"Indices"})


class TestSoftRelu(OpTest):
    op_type = "soft_relu"
    rs = np.random.RandomState(4)
    x = (rs.rand(3, 5).astype(np.float32) - 0.5) * 4
    inputs = {"X": x}
    outputs = {"Out": np.log1p(np.exp(x)).astype(np.float32)}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestReduceMin(OpTest):
    op_type = "reduce_min"
    x = np.arange(12, dtype=np.float32).reshape(3, 4)[:, ::-1].copy()
    inputs = {"X": x}
    outputs = {"Out": x.min(axis=1)}
    attrs = {"dim": 1, "keep_dim": False}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestIncrement(OpTest):
    op_type = "increment"
    x = np.array([3.0], np.float32)
    inputs = {"X": x}
    outputs = {"Out": x + 2.5}
    attrs = {"step": 2.5}

    def test(self):
        self.check_output()


class TestGRUUnit(OpTest):
    op_type = "gru_unit"
    rs = np.random.RandomState(5)
    D = 3
    x = rs.rand(4, 3 * D).astype(np.float32)
    h_prev = rs.rand(4, D).astype(np.float32)
    w = rs.rand(D, 3 * D).astype(np.float32)

    def _sig(a):
        return 1 / (1 + np.exp(-a))

    ur = _sig(x[:, :2 * D] + h_prev @ w[:, :2 * D])
    u, r = ur[:, :D], ur[:, D:]
    c = np.tanh(x[:, 2 * D:] + (r * h_prev) @ w[:, 2 * D:])
    h = u * h_prev + (1 - u) * c
    inputs = {"Input": x, "HiddenPrev": h_prev, "Weight": w}
    outputs = {"Gate": np.concatenate([u, r, c], 1).astype(np.float32),
               "ResetHiddenPrev": (r * h_prev).astype(np.float32),
               "Hidden": h.astype(np.float32)}

    def test(self):
        self.check_output()
        # fused sigmoid/tanh chains in f32: central differences carry
        # more noise than the elementwise ops, hence the wider bound
        self.check_grad(["Input", "HiddenPrev", "Weight"], "Hidden",
                        max_relative_error=0.03)


class TestLSTMUnit(OpTest):
    op_type = "lstm_unit"
    rs = np.random.RandomState(6)
    D = 3
    x = rs.rand(4, 4 * D).astype(np.float32)
    c_prev = rs.rand(4, D).astype(np.float32)

    def _sig(a):
        return 1 / (1 + np.exp(-a))

    i, f, o, g = (_sig(x[:, :D]), _sig(x[:, D:2 * D] + 0.5),
                  _sig(x[:, 2 * D:3 * D]), np.tanh(x[:, 3 * D:]))
    c = f * c_prev + i * g
    h = o * np.tanh(c)
    inputs = {"X": x, "C_prev": c_prev}
    outputs = {"C": c.astype(np.float32), "H": h.astype(np.float32)}
    attrs = {"forget_bias": 0.5}

    def test(self):
        self.check_output()
        self.check_grad(["X", "C_prev"], ["C", "H"])


class TestCrossEntropySelfnorm(OpTest):
    op_type = "cross_entropy_selfnorm"
    rs = np.random.RandomState(7)
    p = rs.rand(4, 5).astype(np.float32) + 0.1
    lab = np.array([[0], [2], [4], [1]], np.int64)
    z = p.sum(1)
    picked = p[np.arange(4), lab.reshape(-1)]
    ref = (-np.log(picked / z) + 0.1 * np.log(z) ** 2)[:, None]
    inputs = {"X": p, "Label": lab}
    outputs = {"Out": ref.astype(np.float32)}
    attrs = {"softmax_selfnorm_alpha": 0.1}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out", no_grad_set={"Label"})


# ---------------------------------------------------------------------------
# sequence tail (ragged in/out)
# ---------------------------------------------------------------------------

class TestSequenceReverse(OpTest):
    op_type = "sequence_reverse"
    seqs = [[[1, 2], [3, 4], [5, 6]], [[7, 8]]]
    inputs = {"X": (np.array([[1, 2], [3, 4], [5, 6], [7, 8]],
                             np.float32), [[0, 3, 4]])}
    outputs = {"Y": (np.array([[5, 6], [3, 4], [1, 2], [7, 8]],
                              np.float32), [[0, 3, 4]])}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Y")


class TestSequenceSoftmax(OpTest):
    op_type = "sequence_softmax"
    v = np.array([[1.0], [2.0], [3.0], [1.0], [1.0]], np.float32)
    e1 = np.exp([1.0, 2.0, 3.0])
    e1 = e1 / e1.sum()
    ref = np.array([[e1[0]], [e1[1]], [e1[2]], [0.5], [0.5]], np.float32)
    inputs = {"X": (v, [[0, 3, 5]])}
    outputs = {"Out": (ref, [[0, 3, 5]])}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestSequenceExpandDense(OpTest):
    op_type = "sequence_expand"
    x = np.array([[1.0, 10.0], [2.0, 20.0]], np.float32)
    yv = np.zeros((5, 1), np.float32)
    ref = np.array([[1, 10], [1, 10], [1, 10], [2, 20], [2, 20]],
                   np.float32)
    inputs = {"X": x, "Y": (yv, [[0, 3, 5]])}
    outputs = {"Out": (ref, [[0, 3, 5]])}

    def test(self):
        self.check_output()


class TestSequenceReshape(OpTest):
    op_type = "sequence_reshape"
    v = np.arange(12, dtype=np.float32).reshape(3, 4)
    inputs = {"X": (v, [[0, 2, 3]])}
    outputs = {"Out": (v.reshape(6, 2), [[0, 4, 6]])}
    attrs = {"new_dim": 2}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSequenceSlice(OpTest):
    op_type = "sequence_slice"
    v = np.arange(10, dtype=np.float32).reshape(5, 2)
    off = np.array([[1], [0]], np.int64)
    ln = np.array([[2], [1]], np.int64)
    # seq0 rows 0:3 -> rows 1:3; seq1 rows 3:5 -> row 3
    ref_rows = np.stack([v[1], v[2], v[3]])
    inputs = {"X": (v, [[0, 3, 5]]), "Offset": off, "Length": ln}
    outputs = {}  # checked manually (flat buffer keeps size)

    def test(self):
        out = _kernel(self.op_type)(
            None, {"X": [_rag([self.v[:3], self.v[3:]])],
                   "Offset": [jnp.asarray(self.off)],
                   "Length": [jnp.asarray(self.ln)]}, {})["Out"][0]
        n = int(out.nvalid)
        np.testing.assert_allclose(np.asarray(out.values)[:n],
                                   self.ref_rows)
        np.testing.assert_array_equal(np.asarray(out.last_splits()),
                                      [0, 2, 3])


def test_lod_reset_op():
    out = _kernel("lod_reset")(
        None, {"X": [_rag([[1, 2], [3, 4]])]},
        {"target_lod": [0, 1, 4]})["Out"][0]
    np.testing.assert_array_equal(np.asarray(out.last_splits()),
                                  [0, 1, 4])


def test_row_conv_op():
    v = np.arange(8, dtype=np.float32).reshape(4, 2)
    filt = np.array([[1.0, 1.0], [0.5, 0.5]], np.float32)
    x = _rag([v[:3], v[3:]])
    out = _kernel("row_conv")(
        None, {"X": [x], "Filter": [jnp.asarray(filt)]}, {})["Out"][0]
    got = np.asarray(out.values)[:4]
    # seq0: out[t] = x[t]*f0 + x[t+1]*f1 (within bounds)
    want = np.array([v[0] + 0.5 * v[1], v[1] + 0.5 * v[2], v[2],
                     v[3]], np.float32)
    np.testing.assert_allclose(got, want)


def test_kmax_seq_score_op():
    x = _rag([[[0.1], [0.9], [0.5]], [[0.7]]])
    out = _kernel("kmax_seq_score")(None, {"X": [x]},
                                    {"beam_size": 2})["Out"][0]
    np.testing.assert_array_equal(
        np.asarray(out.values).reshape(-1), [1, 2, 0])
    np.testing.assert_array_equal(np.asarray(out.last_splits()),
                                  [0, 2, 3])


def test_sub_nested_seq_op():
    vals = np.arange(12, dtype=np.float32).reshape(6, 2)
    nested = RaggedTensor(jnp.asarray(vals),
                          [np.array([0, 2, 3], np.int32),     # outer
                           np.array([0, 2, 4, 6], np.int32)])  # inner
    sel = _rag([[[1]], [[0]]], dtype=np.int64)
    out = _kernel("sub_nested_seq")(None, {"X": [nested], "S": [sel]},
                                    {})["Out"][0]
    got = np.asarray(out.values)[:int(out.nvalid)]
    np.testing.assert_allclose(got, vals[2:6])  # inner seq 1 then 2


def test_dense_sequence_roundtrip():
    x = _rag([[[1, 2], [3, 4], [5, 6]], [[7, 8]]])
    dense = _kernel("sequence_to_dense")(None, {"X": [x]}, {})
    padded, mask = dense["Out"][0], dense["Mask"][0]
    # pads to the flat buffer length (static shape), not max seq len
    assert padded.shape == (2, 4, 2)
    np.testing.assert_array_equal(np.asarray(mask),
                                  [[1, 1, 1, 0], [1, 0, 0, 0]])
    back = _kernel("dense_to_sequence")(
        None, {"X": [padded], "Like": [x]}, {})["Out"][0]
    np.testing.assert_allclose(np.asarray(back.values)[:4],
                               np.asarray(x.values)[:4])


def test_seq_unnest_expand_renest_roundtrip():
    vals = np.arange(12, dtype=np.float32).reshape(6, 2)
    nested = RaggedTensor(jnp.asarray(vals),
                          [np.array([0, 2, 3], np.int32),
                           np.array([0, 2, 4, 6], np.int32)])
    un = _kernel("seq_unnest")(None, {"X": [nested]}, {})
    inner, ref = un["Inner"][0], un["OuterRef"][0]
    assert inner.lod_level == 1 and inner.nseq() == 3
    static = np.array([[1.0], [2.0]], np.float32)
    exp = _kernel("seq_outer_expand")(
        None, {"X": [jnp.asarray(static)], "OuterRef": [ref]}, {})["Out"][0]
    np.testing.assert_allclose(np.asarray(exp).reshape(-1), [1, 1, 2])
    out = _kernel("seq_renest")(
        None, {"X": [inner], "OuterRef": [ref]}, {})["Out"][0]
    assert out.lod_level == 2
    np.testing.assert_array_equal(np.asarray(out.row_splits[0]),
                                  [0, 2, 3])
    # mismatched renest fails fast in eager mode
    with pytest.raises(ValueError, match="outer splits"):
        _kernel("seq_renest")(
            None, {"X": [jnp.zeros((5, 2))], "OuterRef": [ref]}, {})


def test_sequence_conv_functional():
    """context_projection path: sequence_conv trains through a group."""
    x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                          lod_level=1)
    h = fluid.layers.sequence_conv(input=x, num_filters=4, filter_size=3)
    loss = fluid.layers.mean(x=h)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    feeder = fluid.DataFeeder(feed_list=[x], place=place)
    rs = np.random.RandomState(0)
    feeds = feeder.feed([(rs.rand(4, 3).tolist(),),
                         (rs.rand(2, 3).tolist(),)])
    vals = [float(np.asarray(exe.run(
        fluid.default_main_program(), feed=feeds,
        fetch_list=[loss])[0]).reshape(-1)[0]) for _ in range(3)]
    assert all(np.isfinite(v) for v in vals)
    assert vals[-1] != vals[0]  # the filter is actually updating


# ---------------------------------------------------------------------------
# vision tail
# ---------------------------------------------------------------------------

def test_unpool_op():
    x = jnp.asarray(np.array([[[[5.0, 7.0], [9.0, 11.0]]]], np.float32))
    idx = jnp.asarray(np.array([[[[0, 3], [10, 15]]]], np.int32))
    out = _kernel("unpool")(None, {"X": [x], "Indices": [idx]},
                            {"unpooling_size": [2, 2]})["Out"][0]
    out = np.asarray(out).reshape(16)
    want = np.zeros(16, np.float32)
    want[[0, 3, 10, 15]] = [5, 7, 9, 11]
    np.testing.assert_allclose(out, want)


def test_roi_pool_op():
    x = jnp.asarray(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    rois = jnp.asarray(np.array([[0, 0, 0, 3, 3]], np.float32))
    out = _kernel("roi_pool")(
        None, {"X": [x], "ROIs": [rois]},
        {"pooled_height": 2, "pooled_width": 2,
         "spatial_scale": 1.0})["Out"][0]
    np.testing.assert_allclose(
        np.asarray(out).reshape(2, 2), [[5, 7], [13, 15]])


def test_conv2d_dynamic_filter_matches_shared_conv():
    """When every sample carries the same filter row, the dynamic-filter
    conv must equal the ordinary conv2d."""
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(2, 3, 6, 6).astype(np.float32))
    w = rs.rand(4, 3, 3, 3).astype(np.float32)
    wrow = jnp.asarray(np.tile(w.reshape(1, -1), (2, 1)))
    dyn = _kernel("conv2d_dynamic_filter")(
        None, {"Input": [x], "Filter": [wrow]},
        {"strides": [1, 1], "paddings": [1, 1], "num_filters": 4,
         "ksize": [3, 3]})["Output"][0]
    shared = _kernel("conv2d")(
        None, {"Input": [x], "Filter": [jnp.asarray(w)]},
        {"strides": [1, 1], "paddings": [1, 1]})["Output"][0]
    np.testing.assert_allclose(np.asarray(dyn), np.asarray(shared),
                               rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# metrics / random / misc tail
# ---------------------------------------------------------------------------

def test_precision_recall_perfect():
    idx = jnp.asarray(np.array([0, 1, 2, 1], np.int32))
    out = _kernel("precision_recall")(
        None, {"Indices": [idx], "Labels": [idx],
               "MaxProbs": [jnp.ones((4, 1))]},
        {"class_number": 3})
    metrics = np.asarray(out["BatchMetrics"][0])
    np.testing.assert_allclose(metrics, np.ones(6), atol=1e-5)


def test_auc_perfect_separation():
    preds = jnp.asarray(
        np.array([[0.1, 0.9], [0.9, 0.1], [0.2, 0.8], [0.8, 0.2]],
                 np.float32))
    label = jnp.asarray(np.array([[1], [0], [1], [0]], np.int32))
    out = _kernel("auc")(None, {"Out": [preds], "Indices": [preds],
                                "Label": [label]}, {})
    assert float(np.asarray(out["AUC"][0])[0]) > 0.95


def test_random_ops_moments():
    def run(op, attrs):
        class Ctx:
            def next_rng(self):
                import jax

                return jax.random.PRNGKey(0)

        return np.asarray(_kernel(op)(Ctx(), {}, attrs)["Out"][0])

    g = run("gaussian_random", {"shape": [2000], "mean": 1.0, "std": 2.0,
                                "dtype": "float32"})
    assert abs(g.mean() - 1.0) < 0.2 and abs(g.std() - 2.0) < 0.2
    u = run("uniform_random", {"shape": [2000], "min": -1.0, "max": 3.0,
                               "dtype": "float32"})
    assert u.min() >= -1.0 and u.max() <= 3.0
    assert abs(u.mean() - 1.0) < 0.2


def test_nce_cost_positive_and_trains():
    x = fluid.layers.data(name="x", shape=[6], dtype="float32")
    lab = fluid.layers.data(name="lab", shape=[1], dtype="int64")
    cost = fluid.layers.nce(input=x, label=lab, num_total_classes=20,
                            num_neg_samples=5)
    loss = fluid.layers.mean(x=cost)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rs = np.random.RandomState(0)
    feeds = {"x": rs.rand(8, 6).astype(np.float32),
             "lab": rs.randint(0, 20, (8, 1)).astype(np.int64)}
    vals = [float(np.asarray(exe.run(
        fluid.default_main_program(), feed=feeds,
        fetch_list=[loss])[0]).reshape(-1)[0]) for _ in range(5)]
    assert all(v > 0 for v in vals), vals
    assert vals[-1] < vals[0], vals


def test_sampling_id_respects_distribution():
    class Ctx:
        def next_rng(self):
            import jax

            return jax.random.PRNGKey(7)

    # delta distributions: the sample must be the certain id
    p = jnp.asarray(np.eye(4, dtype=np.float32)[[2, 0, 3]])
    out = _kernel("sampling_id")(Ctx(), {"X": [p]}, {})["Out"][0]
    np.testing.assert_array_equal(np.asarray(out), [2, 0, 3])


def test_lambda_cost_properties():
    # correctly ordered scores -> lower cost than inverted scores
    labels = _rag([[[2.0], [1.0], [0.0]]])
    good = _rag([[[0.9], [0.5], [0.1]]])
    bad = _rag([[[0.1], [0.5], [0.9]]])

    def cost(scores):
        out = _kernel("lambda_cost")(
            None, {"Score": [scores], "Label": [labels]},
            {"NDCG_num": 3})["Out"][0]
        return float(np.asarray(out.values).sum())

    assert cost(bad) > cost(good) >= 0.0


def test_misc_small_ops():
    out = _kernel("assign_value")(
        None, {}, {"shape": [2, 2], "dtype": "float32",
                   "values": [1.0, 2.0, 3.0, 4.0]})["Out"][0]
    np.testing.assert_allclose(np.asarray(out), [[1, 2], [3, 4]])

    out = _kernel("cast_embedding_ids")(
        None, {"X": [jnp.asarray(np.array([1, 2], np.int64))]}, {})
    assert np.asarray(out["Out"][0]).dtype == np.int32

    assert bool(np.asarray(_kernel("is_empty")(
        None, {"X": [jnp.zeros((0, 3))]}, {})["Out"][0]))
    assert not bool(np.asarray(_kernel("is_empty")(
        None, {"X": [jnp.zeros((1, 3))]}, {})["Out"][0]))

    srows = SelectedRows(jnp.asarray(np.array([1, 5], np.int32)),
                         jnp.asarray(np.ones((2, 3), np.float32)), 8)
    outs = _kernel("split_selected_rows")(
        None, {"X": [srows]}, {"height_sections": [4, 4]})["Out"]
    assert len(outs) == 2
    np.testing.assert_array_equal(np.asarray(outs[0].rows), [1, 0])
    np.testing.assert_array_equal(np.asarray(outs[1].rows), [0, 1])
    # row 5 lands in shard 1 rebased to 1 with its values intact
    np.testing.assert_allclose(np.asarray(outs[1].values)[1], 1.0)


def test_tensor_array_and_control_ops():
    """write_to_array / read_from_array / lod_array_length /
    max_sequence_len / conditional_block / get_places via their layer
    surfaces (reference: tensor array + control-flow op tests)."""
    x = fluid.layers.data(name="x", shape=[2], dtype="float32")
    i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
    arr = fluid.layers.array_write(x, i=i)
    i2 = fluid.layers.increment(x=i, value=1, in_place=False)
    fluid.layers.array_write(x, i=i2, array=arr)
    length = fluid.layers.array_length(arr)
    back = fluid.layers.array_read(array=arr, i=i)

    cond = fluid.layers.less_than(x=i, y=i2)
    ie = fluid.layers.IfElse(cond)
    with ie.true_block():
        ie.output(fluid.layers.scale(x=ie.input(x), scale=2.0))
    with ie.false_block():
        ie.output(ie.input(x))
    out = ie()
    out = out[0] if isinstance(out, (list, tuple)) else out

    places = fluid.layers.get_places()

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feeds = {"x": np.array([[1.0, 2.0]], np.float32)}
    l, b, o = exe.run(fluid.default_main_program(), feed=feeds,
                      fetch_list=[length, back, out])
    assert int(np.asarray(l).reshape(-1)[0]) == 2
    np.testing.assert_allclose(np.asarray(b), [[1, 2]])
    np.testing.assert_allclose(np.asarray(o), [[2, 4]])


# ---------------------------------------------------------------------------
# enforcement
# ---------------------------------------------------------------------------

# ops deliberately without a direct test, with the reason
SKIPPED_OPS = {
    "feed": "executor plumbing; every test feeds through it",
    "fetch": "executor plumbing; every test fetches through it",
    "load": "exercised via io save/load round-trip tests",
    "save": "exercised via io save/load round-trip tests",
}


def test_every_op_is_covered():
    """Every registered op must be named in some test file (directly or
    via its layer test) or carry an explicit skip reason — the
    reference enforces per-op tests by convention (~150 test_*_op.py
    files); this makes the convention executable."""
    test_dir = os.path.dirname(__file__)
    src = ""
    for fn in sorted(os.listdir(test_dir)):
        if fn.endswith(".py") and fn != os.path.basename(__file__):
            with open(os.path.join(test_dir, fn)) as f:
                src += f.read()
    src += open(os.path.join(test_dir,
                             os.path.basename(__file__))).read()
    missing = []
    for op in sorted(registered_ops()):
        if op in SKIPPED_OPS:
            continue
        if not re.search(r"\b%s\b" % re.escape(op), src):
            missing.append(op)
    assert not missing, (
        "ops with no test coverage (add a case here or a reasoned "
        "entry in SKIPPED_OPS): %s" % ", ".join(missing))
