"""End-to-end CTC training, the OCR-demo flow (reference:
warpctc_op.cc + ctc_align_op.cc driving demo-style sequence labeling):
feature sequences -> fc logits -> CTC loss -> SGD; after training the
greedy decode recovers the target label sequences."""

import numpy as np

import paddle_tpu.fluid as fluid

V = 5        # classes 1..5; 0 is the CTC blank
FEAT = 6


def _make_data(rs, n_seqs=4):
    """Each class k gets a distinct feature direction; input step t
    emits the feature of target symbol t (one frame per symbol, so the
    only valid CTC path is the label itself — the loss is then free of
    the classic half-mass blank saddle and any optimizer converges).
    Targets avoid adjacent repeats so greedy merge-decode is exact."""
    protos = rs.randn(V + 1, FEAT).astype(np.float32) * 2.0
    xs, ys = [], []
    for _ in range(n_seqs):
        target = [int(rs.randint(1, V + 1))]
        for _ in range(int(rs.randint(1, 3))):
            nxt = int(rs.randint(1, V + 1))
            while nxt == target[-1]:
                nxt = int(rs.randint(1, V + 1))
            target.append(nxt)
        frames = [protos[t] + rs.randn(FEAT).astype(np.float32) * 0.05
                  for t in target]
        xs.append(np.stack(frames, 0))
        ys.append(np.asarray(target, np.int64).reshape(-1, 1))
    return xs, ys


def test_ctc_train_and_greedy_decode():
    x = fluid.layers.data(name="x", shape=[FEAT], dtype="float32",
                          lod_level=1)
    y = fluid.layers.data(name="y", shape=[1], dtype="int64",
                          lod_level=1)
    logits = fluid.layers.fc(input=x, size=V + 1, act=None)
    loss = fluid.layers.mean(
        x=fluid.layers.warpctc(input=logits, label=y, blank=0))
    decoded = fluid.layers.ctc_greedy_decoder(logits, blank=0)
    fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())

    rs = np.random.RandomState(0)
    xs, ys = _make_data(rs)
    feeder = fluid.DataFeeder(place=place, feed_list=[x, y])
    feed = feeder.feed(list(zip(xs, ys)))

    losses = []
    for _ in range(200):
        out, = exe.run(fluid.default_main_program(), feed=feed,
                       fetch_list=[loss])
        losses.append(float(np.asarray(out).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])

    dec, = exe.run(fluid.default_main_program(), feed=feed,
                   fetch_list=[decoded], return_numpy=False)
    splits = np.asarray(dec.row_splits[-1])
    vals = np.asarray(dec.values).reshape(-1)
    got = [vals[splits[i]:splits[i + 1]].tolist()
           for i in range(len(splits) - 1)]
    want = [yy.reshape(-1).tolist() for yy in ys]
    assert got == want, (got, want)
