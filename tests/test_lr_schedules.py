"""In-program learning-rate schedules (reference:
parameter/LearningRateScheduler.cpp poly/exp/linear schedules): each
schedule's per-step LR matches the closed form, and an optimizer
driven by a schedule Variable actually applies the decayed rate."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import lr_schedules


def _run_schedule(build, steps):
    lr = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    out = []
    for _ in range(steps):
        v, = exe.run(fluid.default_main_program(), fetch_list=[lr])
        out.append(float(np.asarray(v).reshape(-1)[0]))
    return np.asarray(out)


def test_exponential_and_natural_and_inverse():
    lrs = _run_schedule(
        lambda: lr_schedules.exponential_decay(0.1, 4, 0.5), 8)
    want = 0.1 * 0.5 ** (np.arange(1, 9) / 4.0)
    np.testing.assert_allclose(lrs, want, rtol=1e-5)

    from paddle_tpu.fluid import framework
    from paddle_tpu.core import scope as scope_mod

    for build, ref in [
        (lambda: lr_schedules.exponential_decay(0.1, 4, 0.5,
                                                staircase=True),
         lambda t: 0.1 * 0.5 ** np.floor(t / 4.0)),
        (lambda: lr_schedules.natural_exp_decay(0.2, 5, 0.7),
         lambda t: 0.2 * np.exp(-0.7 * t / 5.0)),
        (lambda: lr_schedules.inverse_time_decay(0.3, 2, 0.5),
         lambda t: 0.3 / (1 + 0.5 * t / 2.0)),
    ]:
        framework.switch_main_program(framework.Program())
        framework.switch_startup_program(framework.Program())
        scope_mod.reset_global_scope()
        lrs = _run_schedule(build, 6)
        np.testing.assert_allclose(lrs, ref(np.arange(1.0, 7.0)),
                                   rtol=1e-5)


def test_polynomial_decay():
    lrs = _run_schedule(
        lambda: lr_schedules.polynomial_decay(
            1.0, 4, end_learning_rate=0.1, power=2.0), 8)
    t = np.minimum(np.arange(1.0, 9.0), 4.0)
    want = (1.0 - 0.1) * (1 - t / 4.0) ** 2 + 0.1
    np.testing.assert_allclose(lrs, want, rtol=1e-5)


def test_polynomial_decay_cycle():
    lrs = _run_schedule(
        lambda: lr_schedules.polynomial_decay(
            1.0, 3, end_learning_rate=0.0, power=1.0, cycle=True), 7)
    t = np.arange(1.0, 8.0)
    n = np.maximum(np.ceil(t / 3.0), 1.0) * 3.0
    want = (1 - t / n)
    np.testing.assert_allclose(lrs, want, rtol=1e-5)


def test_piecewise_decay():
    lrs = _run_schedule(
        lambda: lr_schedules.piecewise_decay([3, 6], [1.0, 0.5, 0.1]),
        8)
    want = [1.0, 1.0, 0.5, 0.5, 0.5, 0.1, 0.1, 0.1]
    np.testing.assert_allclose(lrs, want, rtol=1e-6)


def test_schedule_drives_optimizer():
    """The schedule Variable feeds SGD: the applied step size halves
    when the schedule does (w -= lr * grad with grad = 1)."""
    w = fluid.layers.create_parameter  # noqa: F841 (API presence)
    x = fluid.layers.data(name="x", shape=[1], dtype="float32")
    pred = fluid.layers.fc(
        input=x, size=1, bias_attr=False,
        param_attr=fluid.ParamAttr(name="w",
                                   initializer=fluid.initializer
                                   .Constant(0.0)))
    loss = fluid.layers.mean(x=pred)     # d loss / d w = mean(x) = 1
    # steps are 1-based; step < 3 takes the first value
    lr = lr_schedules.piecewise_decay([3], [0.5, 0.25])
    fluid.optimizer.SGD(learning_rate=lr).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    from paddle_tpu.core.scope import global_scope

    feed = {"x": np.ones((4, 1), np.float32)}
    deltas = []
    prev = 0.0
    for _ in range(4):
        exe.run(fluid.default_main_program(), feed=feed,
                fetch_list=[loss])
        cur = float(np.asarray(global_scope().get("w")).reshape(-1)[0])
        deltas.append(round(prev - cur, 6))
        prev = cur
    assert deltas == [0.5, 0.5, 0.25, 0.25], deltas


def test_v2_schedule_spellings():
    """Reference LearningRateScheduler formulas, samples-based
    (samples = step * batch_size)."""
    from paddle_tpu.fluid import framework
    from paddle_tpu.core import scope as scope_mod

    B = 4
    n = np.arange(1.0, 7.0) * B
    for name, a, b, ref in [
        ("poly", 0.01, 0.75,
         lambda n: 0.5 * (1 + 0.01 * n) ** -0.75),
        ("exp", 0.5, 8.0, lambda n: 0.5 * 0.5 ** (n / 8.0)),
        ("discexp", 0.5, 8.0,
         lambda n: 0.5 * 0.5 ** np.floor(n / 8.0)),
        ("linear", 0.02, 0.3,
         lambda n: np.maximum(0.5 - 0.02 * n, 0.3)),
    ]:
        framework.switch_main_program(framework.Program())
        framework.switch_startup_program(framework.Program())
        scope_mod.reset_global_scope()
        lrs = _run_schedule(
            lambda: lr_schedules.v2_schedule(name, 0.5, decay_a=a,
                                             decay_b=b, batch_size=B),
            6)
        np.testing.assert_allclose(lrs, ref(n), rtol=1e-5,
                                   err_msg=name)
    assert lr_schedules.v2_schedule("constant", 0.25) == 0.25
