"""PytreeOptimizer: one declarative update rule, two surfaces.

The same fluid.optimizer instance must produce identical training
whether its rule is emitted as program ops (executor surface) or driven
over a params pytree by PytreeOptimizer (schedule surface for
pipeline/MoE stacked params).  Bitwise, because both surfaces call the
same registered op kernel on the same values.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu.fluid as fluid
from paddle_tpu.parallel import PytreeOptimizer
from paddle_tpu.core.scope import Scope
from paddle_tpu.fluid.executor import scope_guard, fetch_var


def _program_reference(make_opt, w0, grads_seq):
    """Train a single [4,3] parameter with fixed injected grads through
    the executor; returns the parameter trajectory."""
    main = fluid.Program()
    startup = fluid.Program()
    fluid.framework.reset_unique_name()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4, 3], dtype="float32",
                              append_batch_size=False)
        w = fluid.layers.create_parameter(
            [4, 3], "float32",
            default_initializer=fluid.initializer.Constant(0.0))
        # loss = sum(w * x) so dL/dw == the injected x exactly
        loss = fluid.layers.reduce_sum(fluid.layers.elementwise_mul(x=w,
                                                                    y=x))
        make_opt().minimize(loss)

    traj = []
    with scope_guard(Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        from paddle_tpu.fluid.executor import global_scope
        global_scope().set(w.name, jnp.asarray(w0))
        for g in grads_seq:
            exe.run(main, feed={"x": g}, fetch_list=[loss])
            traj.append(np.asarray(fetch_var(w.name)))
    return traj


def _pytree_run(make_opt, w0, grads_seq):
    opt = PytreeOptimizer(make_opt())
    params = {"w": jnp.asarray(w0)}
    state = opt.init(params)
    traj = []
    for g in grads_seq:
        params, state = opt.apply(params, {"w": jnp.asarray(g)}, state)
        traj.append(np.asarray(params["w"]))
    return traj, state


OPTS = {
    "sgd": lambda: fluid.optimizer.SGD(learning_rate=0.1),
    "momentum": lambda: fluid.optimizer.Momentum(learning_rate=0.1,
                                                 momentum=0.9),
    "adam": lambda: fluid.optimizer.Adam(learning_rate=0.05),
    "adagrad": lambda: fluid.optimizer.Adagrad(learning_rate=0.1),
    "rmsprop": lambda: fluid.optimizer.RMSProp(learning_rate=0.05),
    "adadelta": lambda: fluid.optimizer.Adadelta(),
}


@pytest.mark.parametrize("name", sorted(OPTS))
def test_pytree_matches_program_surface(name):
    rs = np.random.RandomState(1)
    w0 = rs.randn(4, 3).astype("float32")
    grads = [rs.randn(4, 3).astype("float32") for _ in range(4)]

    want = _program_reference(OPTS[name], w0, grads)
    got, state = _pytree_run(OPTS[name], w0, grads)

    for step, (a, b) in enumerate(zip(got, want)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7,
                                   err_msg="%s step %d" % (name, step))


def test_shared_scalars_advance():
    """Adam's beta powers decay once per apply, like the program's
    trailing scale ops."""
    opt = PytreeOptimizer(fluid.optimizer.Adam(learning_rate=0.01,
                                               beta1=0.9, beta2=0.99))
    params = {"w": jnp.ones((2, 2))}
    state = opt.init(params)
    assert np.isclose(float(state["shared"]["beta1_pow_acc"]), 0.9)
    for i in range(3):
        params, state = opt.apply(params, {"w": jnp.ones((2, 2))}, state)
    assert np.isclose(float(state["shared"]["beta1_pow_acc"]), 0.9 ** 4)
    assert np.isclose(float(state["shared"]["beta2_pow_acc"]), 0.99 ** 4)
