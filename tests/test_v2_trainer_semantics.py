"""v2 trainer semantics: test() purity, fine-tune startup behavior,
feed-slot resolution (regressions for review findings)."""

import numpy as np

import paddle_tpu.v2 as paddle


def _linear_topology():
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1)
    cost = paddle.layer.square_error_cost(input=pred, label=y)
    return x, y, pred, cost


def test_test_does_not_update_parameters():
    paddle.init()
    _, _, _, cost = _linear_topology()
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.9,
                                                  learning_rate=0.1))
    key = params.keys()[0]
    before = params.get(key).copy()

    rs = np.random.RandomState(0)

    def reader():
        for _ in range(3):
            yield [(rs.rand(4).astype("f"), rs.rand(1).astype("f"))
                   for _ in range(5)]

    res = trainer.test(reader=reader, feeding={"x": 0, "y": 1})
    assert np.isfinite(res.cost)
    np.testing.assert_allclose(params.get(key), before)


def test_loaded_weights_survive_trainer_construction():
    """Fine-tune flow: Parameters.set before SGD() must not be clobbered
    by re-running parameter init ops (only new accumulators init)."""
    paddle.init()
    _, _, _, cost = _linear_topology()
    params = paddle.parameters.create(cost)
    k = params.keys()[0]
    loaded = np.full(params.get(k).shape, 7.0, np.float32)
    params.set(k, loaded)

    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.01))
    np.testing.assert_allclose(params.get(k), 7.0)

    def reader():
        yield [(np.ones(4, "f"), np.ones(1, "f")) for _ in range(4)]

    trainer.train(reader=reader, num_passes=1)
    assert not np.allclose(params.get(k), 7.0)


def test_infer_rejects_wrong_feed_width():
    paddle.init()
    _, _, pred, cost = _linear_topology()
    paddle.parameters.create(cost)
    import pytest

    with pytest.raises(ValueError):
        paddle.infer(output_layer=pred,
                     input=[(np.ones(4, "f"), np.ones(1, "f"))],
                     feeding={"x": 0, "y": 1})


def test_train_save_dir_writes_pass_tars(tmp_path):
    """paddle_trainer --save_dir behavior: one parameters tar per pass,
    loadable with Parameters.from_tar."""
    import os

    x, y, pred, cost = _linear_topology()
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(learning_rate=0.01))
    rs = np.random.RandomState(0)

    def reader():
        for _ in range(4):
            yield (rs.rand(4).astype(np.float32),
                   rs.rand(1).astype(np.float32))

    save_dir = str(tmp_path / "passes")
    trainer.train(paddle.batch(reader, batch_size=2), num_passes=3,
                  feeding={"x": 0, "y": 1}, save_dir=save_dir)
    tars = sorted(os.listdir(save_dir))
    assert tars == ["pass_00000.tar", "pass_00001.tar", "pass_00002.tar"]
    # snapshot trained values BEFORE loading: from_tar writes into the
    # same global scope, so comparing live views would be vacuous
    trained = {n: np.array(np.asarray(params.get(n)))
               for n in params.names()}
    from paddle_tpu.core.scope import global_scope

    for n in trained:
        global_scope().set(n, np.zeros_like(trained[n]))
    with open(os.path.join(save_dir, tars[-1]), "rb") as f:
        restored = paddle.parameters.Parameters.from_tar(f)
    for name, want in trained.items():
        np.testing.assert_array_equal(np.asarray(restored.get(name)),
                                      want)
