"""v2 trainer semantics: test() purity, fine-tune startup behavior,
feed-slot resolution (regressions for review findings)."""

import numpy as np

import paddle_tpu.v2 as paddle


def _linear_topology():
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1)
    cost = paddle.layer.square_error_cost(input=pred, label=y)
    return x, y, pred, cost


def test_test_does_not_update_parameters():
    paddle.init()
    _, _, _, cost = _linear_topology()
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.9,
                                                  learning_rate=0.1))
    key = params.keys()[0]
    before = params.get(key).copy()

    rs = np.random.RandomState(0)

    def reader():
        for _ in range(3):
            yield [(rs.rand(4).astype("f"), rs.rand(1).astype("f"))
                   for _ in range(5)]

    res = trainer.test(reader=reader, feeding={"x": 0, "y": 1})
    assert np.isfinite(res.cost)
    np.testing.assert_allclose(params.get(key), before)


def test_loaded_weights_survive_trainer_construction():
    """Fine-tune flow: Parameters.set before SGD() must not be clobbered
    by re-running parameter init ops (only new accumulators init)."""
    paddle.init()
    _, _, _, cost = _linear_topology()
    params = paddle.parameters.create(cost)
    k = params.keys()[0]
    loaded = np.full(params.get(k).shape, 7.0, np.float32)
    params.set(k, loaded)

    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.01))
    np.testing.assert_allclose(params.get(k), 7.0)

    def reader():
        yield [(np.ones(4, "f"), np.ones(1, "f")) for _ in range(4)]

    trainer.train(reader=reader, num_passes=1)
    assert not np.allclose(params.get(k), 7.0)


def test_infer_rejects_wrong_feed_width():
    paddle.init()
    _, _, pred, cost = _linear_topology()
    paddle.parameters.create(cost)
    import pytest

    with pytest.raises(ValueError):
        paddle.infer(output_layer=pred,
                     input=[(np.ones(4, "f"), np.ones(1, "f"))],
                     feeding={"x": 0, "y": 1})
