"""The SPMD mainline (paddle_tpu.spmd) on the virtual 8-device mesh.

Four contracts pinned here:

  * the plan artifact: regex rules layered over the `param_spec`
    heuristics, boundary behavior of `param_spec_reason` /
    `zero1_spec_reason` (exact min_shard_dim edges, precedence ties,
    non-divisible dims MUST carry a reason), save/load round-trip
    with a stable fingerprint, and the trainer refusing a plan built
    for a different mesh;
  * training parity: the plan-driven pjit step (fused GSPMD, the
    overlapped bucketed-ring schedule, and rules+zero1) produces the
    single-device losses and params on identical data;
  * resilience: sharded checkpoint save -> restore reassembles the
    exact state with NOTHING densified, and a supervisor attached via
    `attach_supervisor` auto-resumes a fresh trainer from the sharded
    snapshots;
  * measurement: MULTICHIP records carry platform_class / comm blobs,
    the perf gate refuses cross-class baselines, and `ptune fit`
    prices the comm coefficient only from same-class multichip pairs.
"""

import json
import os

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu.fluid as fluid
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.mesh import parse_mesh_spec
from paddle_tpu.parallel.sharding import (param_spec_reason,
                                          zero1_spec_reason)
from paddle_tpu.spmd import (PartitionPlan, SpmdTrainer,
                             attach_supervisor, build_partition_plan,
                             load_rules, match_partition_rules)

BATCH, DIM, HIDDEN, CLASSES = 16, 8, 1024, 4


def _build_mlp():
    # same var names for every build so state dicts are comparable
    fluid.framework.reset_unique_name()
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[BATCH, DIM],
                              dtype="float32", append_batch_size=False)
        label = fluid.layers.data(name="label", shape=[BATCH, 1],
                                  dtype="int64", append_batch_size=False)
        h = fluid.layers.fc(input=x, size=HIDDEN, act="relu")
        logits = fluid.layers.fc(input=h, size=CLASSES, act=None)
        loss = fluid.layers.softmax_with_cross_entropy(logits, label)
        avg = fluid.layers.mean(loss)
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.1, momentum=0.9).minimize(avg)
    return main, startup, avg


def _feeds(step):
    rs = np.random.RandomState(100 + step)
    return {
        "x": rs.rand(BATCH, DIM).astype(np.float32),
        "label": rs.randint(0, CLASSES,
                            size=(BATCH, 1)).astype(np.int64),
    }


def _run(mesh, steps=4, **kw):
    main, startup, avg = _build_mlp()
    tr = SpmdTrainer(main, startup, feed_names=["x", "label"],
                     fetch_names=[avg.name], mesh=mesh,
                     use_pcache=False, **kw).init()
    losses = []
    for i in range(steps):
        (loss,) = tr.step(_feeds(i))
        losses.append(float(np.asarray(loss).reshape(-1)[0]))
    params = {n: np.asarray(v) for n, v in tr.state.items()}
    return losses, params, tr


def _assert_parity(a, b):
    np.testing.assert_allclose(a[0], b[0], rtol=2e-5, atol=1e-6)
    assert a[1].keys() == b[1].keys()
    for n in a[1]:
        np.testing.assert_allclose(a[1][n], b[1][n],
                                   rtol=2e-4, atol=1e-5, err_msg=n)


# -- param_spec_reason / zero1_spec_reason boundaries ----------------------

class TestSpecReasons:
    mesh = parse_mesh_spec("dp=4,mp=2")  # static: zero devices needed

    def test_rows_vs_cols_precedence_tie(self):
        # rows == min_shard_dim*mp and rows == cols: the tie goes to
        # row sharding (the embedding-table rule fires first)
        spec, reason = param_spec_reason("w", (1024, 1024), self.mesh)
        assert spec == P("mp", None) and reason is None
        # one more col flips rows >= cols off; cols odd, rows still
        # divisible and >= min_shard_dim -> row sharding wins again
        spec, _ = param_spec_reason("w", (1024, 1025), self.mesh)
        assert spec == P("mp", None)
        # rows below the table threshold, cols divisible: cols win
        spec, reason = param_spec_reason("w", (512, 512), self.mesh)
        assert spec == P(None, "mp") and reason is None

    def test_min_shard_dim_exact_boundary(self):
        # 512 is IN (>= min_shard_dim), 511 is OUT — with odd cols the
        # row rule is the only path, so the boundary is visible alone
        spec, reason = param_spec_reason("w", (512, 511), self.mesh)
        assert spec == P("mp", None) and reason is None
        spec, reason = param_spec_reason("w", (511, 511), self.mesh)
        assert spec == P()
        assert "below min_shard_dim 512" in reason

    def test_non_divisible_dims_carry_a_reason(self):
        # both dims big enough but neither divides mp=2: forced
        # replication must explain itself (the S001 citation)
        spec, reason = param_spec_reason("w", (515, 515), self.mesh)
        assert spec == P()
        assert reason is not None and "not divisible" in reason
        # policy replication (non-2-D, or mp absent) has NO reason
        assert param_spec_reason("conv", (64, 3, 3, 3),
                                 self.mesh) == (P(), None)
        assert param_spec_reason("w", (515, 515),
                                 parse_mesh_spec("dp=8")) == (P(), None)

    def test_zero1_boundaries(self):
        mesh = parse_mesh_spec("dp=8")
        # exact boundary: dim == dp shards; scalar never does
        spec, reason = zero1_spec_reason(P(), (8,), mesh)
        assert spec == P("dp") and reason is None
        spec, reason = zero1_spec_reason(P(), (), mesh)
        assert spec == P() and "scalar" in reason
        # no free dim divides dp -> full copies, with the count cited
        spec, reason = zero1_spec_reason(P(), (7, 9), mesh)
        assert spec == P() and "8 full copies" in reason
        # a dim already taken by mp is skipped, not double-booked
        mesh2 = parse_mesh_spec("dp=4,mp=2")
        spec, reason = zero1_spec_reason(P("mp", None), (1024, 1024),
                                         mesh2)
        assert spec == P("mp", "dp") and reason is None
        # dp absent/1: base spec passes through untouched
        assert zero1_spec_reason(P(), (8,), parse_mesh_spec("mp=2")) \
            == (P(), None)


# -- the plan artifact -----------------------------------------------------

def test_rule_matching_precedence():
    rules = load_rules([[r"fc_.*\.w_0", ["mp", None]],
                        [r".*\.w_0", [None, "mp"]]])
    spec, pat = match_partition_rules(rules, "fc_1.w_0")
    assert spec == ("mp", None) and pat == r"fc_.*\.w_0"
    spec, _ = match_partition_rules(rules, "conv0.w_0")
    assert spec == (None, "mp")
    assert match_partition_rules(rules, "fc_1.b_0") == (None, None)


def test_plan_roundtrip_and_fingerprint(tmp_path):
    main, _startup, avg = _build_mlp()
    mesh = parse_mesh_spec("dp=4,mp=2")
    plan = build_partition_plan(main, mesh, ["x", "label"],
                                [avg.name])
    again = build_partition_plan(main, mesh, ["x", "label"],
                                 [avg.name])
    assert plan.fingerprint() == again.fingerprint()

    path = str(tmp_path / "plan.json")
    plan.save(path)
    loaded = PartitionPlan.load(path)
    assert loaded.fingerprint() == plan.fingerprint()
    assert loaded.var_specs == plan.var_specs
    assert loaded.mesh_axes == {"dp": 4, "mp": 2}
    # the summary is the human artifact: layout + comm floor render
    assert "comm" in plan.summary()


def test_rules_reshape_the_plan():
    main, _startup, avg = _build_mlp()
    mesh = parse_mesh_spec("dp=4,mp=2")
    base = build_partition_plan(main, mesh, ["x", "label"],
                                [avg.name])
    # the heuristic row-shards fc_1.w_0 (HIDDEN x CLASSES); the rule
    # flips it to column sharding — layout and fingerprint must move
    assert tuple(base.var_specs["fc_1.w_0"]) == ("mp", None)
    ruled = build_partition_plan(
        main, mesh, ["x", "label"], [avg.name],
        rules=load_rules([[r"fc_1\.w_0", [None, "mp"]]]))
    assert tuple(ruled.var_specs["fc_1.w_0"]) == (None, "mp")
    assert base.var_specs["fc_1.w_0"] != ruled.var_specs["fc_1.w_0"]
    assert base.fingerprint() != ruled.fingerprint()


def test_trainer_rejects_mismatched_plan():
    main, startup, avg = _build_mlp()
    plan = build_partition_plan(main, parse_mesh_spec("dp=2,mp=2"),
                                ["x", "label"], [avg.name])
    tr = SpmdTrainer(main, startup, feed_names=["x", "label"],
                     fetch_names=[avg.name],
                     mesh=make_mesh(n_devices=8), plan=plan,
                     use_pcache=False)
    with pytest.raises(ValueError, match="pshard plan"):
        tr.init()


# -- training parity -------------------------------------------------------

def test_gspmd_step_matches_single_device():
    single = _run(make_mesh(n_devices=1))
    dp8 = _run(make_mesh(n_devices=8))
    assert all(np.isfinite(single[0]))
    assert dp8[2].step_mode == "gspmd"
    _assert_parity(dp8, single)


def test_overlapped_dp_matches_single_device():
    single = _run(make_mesh(n_devices=1))
    over = _run(make_mesh(n_devices=8), bucket_bytes=64 << 10)
    assert over[2].step_mode == "overlap-dp"
    _assert_parity(over, single)


def test_overlap_falls_back_with_reason():
    # zero1 breaks the replicated-params precondition: the trainer
    # must fall back to the fused path and say why
    _, _, tr = _run(make_mesh(n_devices=8), steps=1,
                    bucket_bytes=64 << 10, zero_stage=1)
    assert tr.step_mode == "gspmd"
    assert tr.overlap_fallback_reason


def test_rules_zero1_matches_single_device():
    single = _run(make_mesh(n_devices=1))
    sharded = _run(make_mesh(n_devices=8, mp=2), zero_stage=1,
                   rules=[[r"fc_1\.w_0", [None, "mp"]]])
    _assert_parity(sharded, single)
    # the rule really drove the compiled layout, not just the plan
    tr = sharded[2]
    assert tuple(tr.plan.var_specs["fc_1.w_0"]) == (None, "mp")
    assert "mp" in str(tr._shardings["fc_1.w_0"].spec)


# -- sharded checkpoints + supervisor resume -------------------------------

def test_sharded_checkpoint_roundtrip_no_densify(tmp_path):
    _, _, tr = _run(make_mesh(n_devices=8, mp=2), steps=2,
                    zero_stage=1)
    snap = tr.save_checkpoint(str(tmp_path), step=2)
    # the manifest-last discipline: the global manifest names the mesh
    manifest = json.load(
        open(os.path.join(snap, "_spmd_manifest.json")))
    assert manifest["mesh"] == {"dp": 4, "mp": 2}

    main, startup, avg = _build_mlp()
    fresh = SpmdTrainer(main, startup, feed_names=["x", "label"],
                        fetch_names=[avg.name],
                        mesh=make_mesh(n_devices=8, mp=2),
                        zero_stage=1, use_pcache=False).init()
    info = fresh.restore_checkpoint(str(tmp_path))
    assert info["step"] == 2 and info["densified"] == []
    for n in tr.state:
        np.testing.assert_array_equal(np.asarray(fresh.state[n]),
                                      np.asarray(tr.state[n]),
                                      err_msg=n)


def test_supervisor_auto_resume_sharded(tmp_path):
    root = str(tmp_path / "sup")
    _, _, tr = _run(make_mesh(n_devices=8, mp=2), steps=3,
                    zero_stage=1,
                    rules=[[r"fc_1\.w_0", ["mp", None]]])
    sup = attach_supervisor(tr, root, interval_secs=0.0)
    sup._saver.save(3)
    sup._saver.wait()

    # a relaunched job: fresh trainer, same programs, same mesh — the
    # supervisor must find the sharded snapshot and restore through
    # the saver protocol (never a dense scope checkpoint)
    main, startup, avg = _build_mlp()
    tr2 = SpmdTrainer(main, startup, feed_names=["x", "label"],
                      fetch_names=[avg.name],
                      mesh=make_mesh(n_devices=8, mp=2),
                      zero_stage=1,
                      rules=[[r"fc_1\.w_0", ["mp", None]]],
                      use_pcache=False).init()
    sup2 = attach_supervisor(tr2, root, interval_secs=0.0)
    assert sup2._latest_snapshot() is not None
    assert sup2._restore_latest() == 3
    for n in tr.state:
        np.testing.assert_array_equal(np.asarray(tr2.state[n]),
                                      np.asarray(tr.state[n]),
                                      err_msg=n)


# -- platform_class gating + comm calibration ------------------------------

def _record(step_ms, platform="cpu", n_devices=None, mesh=None,
            comm=None, ts=0):
    rec = {"ts": ts, "metric": "multichip_mlp", "leg": "L",
           "value": 1000.0 / step_ms, "unit": "img/s",
           "step_ms": step_ms, "mfu": None, "amp_bf16": False,
           "platform": platform}
    if n_devices:
        rec["n_devices"] = n_devices
        rec["platform_class"] = "%s:d%d" % (platform, n_devices)
    if mesh:
        rec["mesh"] = mesh
        rec["platform_class"] += ":" + ",".join(
            "%s=%d" % kv for kv in sorted(mesh.items()))
    if comm:
        rec["comm"] = comm
    return rec


def test_gate_refuses_cross_class_baseline():
    from paddle_tpu.obs import perf as obs_perf

    history = [_record(10.0, ts=i) for i in range(3)]
    cand = _record(10.0, n_devices=8, mesh={"dp": 8}, ts=9)
    res = obs_perf.gate_history(history + [cand])
    assert not res.ok
    assert any("platform class mismatch" in f["why"]
               for f in res.failures)
    # same class present: the 8-device baseline gates the 8-device run
    history8 = [_record(10.0, n_devices=8, mesh={"dp": 8}, ts=i)
                for i in range(3)]
    res = obs_perf.gate_history(history8 + [cand])
    assert res.ok
    assert any(c.get("platform_class") == "cpu:d8:dp=8"
               for c in res.checked)


def test_fit_prices_comm_from_multichip_pairs():
    from paddle_tpu.obs import perf as obs_perf
    from paddle_tpu.tune import fit as tune_fit

    comm = {"wire_bytes": 1 << 20, "pred_s": 1e-3, "measured_s": 3e-3}
    recs = [_record(10.0, n_devices=8, mesh={"dp": 8}, comm=comm,
                    ts=i) for i in range(3)]
    pairs = tune_fit.join_comm_history(recs)
    assert len(pairs) == 3
    assert pairs[0]["platform_class"] == "cpu:d8:dp=8"
    cal = tune_fit.fit_calibration([], comm_pairs=pairs)
    assert cal.coef["comm"] == pytest.approx(3.0)
    assert "multichip measurement" in cal.note
    # no multichip pairs: the comm term stays analytic, and says so
    cal = tune_fit.fit_calibration([], comm_pairs=[])
    assert cal.coef.get("comm", 1.0) == pytest.approx(1.0)


def test_multichip_bench_record_schema(tmp_path):
    from paddle_tpu.spmd import bench as spmd_bench

    hist = str(tmp_path / "hist.jsonl")
    rec = spmd_bench.run_leg(model="lenet5", mesh_spec="dp=8",
                             batch=16, iters=2, warmup=1,
                             history=hist)
    assert rec["unit"] == "img/s" and rec["value"] > 0
    assert rec["n_devices"] == 8 and rec["mesh"] == {"dp": 8, "mp": 1}
    assert rec["platform_class"].startswith("cpu:d8:")
    comm = rec["comm"]
    assert comm["wire_bytes"] > 0 and comm["measured_s"] > 0
    # the history line round-trips through the fit's comm join
    from paddle_tpu.obs import perf as obs_perf
    from paddle_tpu.tune import fit as tune_fit

    (line,) = obs_perf.load_history(hist)
    assert line["platform_class"] == rec["platform_class"]
    assert tune_fit.join_comm_history([line])
