"""Milestone B: MNIST MLP + conv nets.

Parity target: reference python/paddle/v2/fluid/tests/book/
test_recognize_digits.py (mlp and conv variants; loss falls, accuracy
rises on the synthetic class-templated MNIST stand-in).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid


def loss_net(hidden, label):
    prediction = fluid.layers.fc(input=hidden, size=10, act="softmax")
    loss = fluid.layers.cross_entropy(input=prediction, label=label)
    return fluid.layers.mean(x=loss), fluid.layers.accuracy(
        input=prediction, label=label)


def mlp(img, label):
    hidden = fluid.layers.fc(input=img, size=128, act="tanh")
    hidden = fluid.layers.fc(input=hidden, size=128, act="tanh")
    return loss_net(hidden, label)


def conv_net(img, label):
    conv_pool_1 = fluid.nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=8, pool_size=2,
        pool_stride=2, act="relu")
    conv_pool_2 = fluid.nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=16, pool_size=2,
        pool_stride=2, act="relu")
    return loss_net(conv_pool_2, label)


@pytest.mark.parametrize("nn_type", ["mlp", "conv"])
def test_recognize_digits(nn_type):
    if nn_type == "mlp":
        img = fluid.layers.data(name="img", shape=[784], dtype="float32")
    else:
        img = fluid.layers.data(name="img", shape=[1, 28, 28],
                                dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    net = mlp if nn_type == "mlp" else conv_net
    avg_loss, acc = net(img, label)

    test_program = fluid.default_main_program().clone()

    optimizer = fluid.optimizer.Adam(learning_rate=0.002)
    optimizer.minimize(avg_loss)

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())

    BATCH = 64

    def train_rd():
        for sample in paddle.batch(
                paddle.reader.shuffle(paddle.dataset.mnist.train(),
                                      buf_size=500),
                batch_size=BATCH)():
            if nn_type == "conv":
                sample = [(np.reshape(s[0], (1, 28, 28)), s[1])
                          for s in sample]
            yield sample

    feeder = fluid.DataFeeder(feed_list=[img, label], place=place)

    losses, accs = [], []
    for pass_id in range(6):
        for data in train_rd():
            loss_v, acc_v = exe.run(fluid.default_main_program(),
                                    feed=feeder.feed(data),
                                    fetch_list=[avg_loss, acc])
            losses.append(float(loss_v[0]))
            accs.append(float(acc_v[0]))

    last_acc = np.mean(accs[-8:])
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    assert last_acc > 0.9, last_acc

    # test program (cloned before optimizer) must run without updating
    data = next(iter(train_rd()))
    tl, ta = exe.run(test_program, feed=feeder.feed(data),
                     fetch_list=[avg_loss, acc])
    assert np.isfinite(tl[0])
