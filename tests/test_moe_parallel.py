"""Expert parallelism: switch_moe dispatch/combine over an "ep" mesh
axis matches the dense per-token expert computation, drops respect
capacity, and gradients flow to expert weights."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from paddle_tpu.parallel.moe import (switch_moe, moe_shard_map,
                                     init_moe_params)

D, H, E = 8, 16, 8


def _mesh(shape, names):
    n = int(np.prod(shape))
    devs = np.array(jax.devices()[:n]).reshape(shape)
    return Mesh(devs, axis_names=names)


def _dense_reference(params, x):
    """Every token through its argmax expert, weighted by the router
    prob — what switch_moe computes when nothing is dropped."""
    logits = x @ params["gate_w"]
    probs = jax.nn.softmax(logits, axis=-1)
    gate = jnp.max(probs, axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    w1 = params["w1"][idx]          # [b, d, h]
    b1 = params["b1"][idx]
    w2 = params["w2"][idx]
    b2 = params["b2"][idx]
    h = jax.nn.relu(jnp.einsum("bd,bdh->bh", x, w1) + b1)
    out = jnp.einsum("bh,bhd->bd", h, w2) + b2
    return out * gate[:, None]


def test_moe_matches_dense_no_drops():
    params = init_moe_params(0, D, H, E)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(32, D).astype(np.float32))

    mesh = _mesh((4,), ("ep",))
    # capacity_factor high enough that no token is ever dropped
    fn = moe_shard_map(mesh, capacity_factor=float(E))
    y, aux = fn(params, x)
    ref = _dense_reference(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=2e-5, atol=1e-5)
    assert float(aux) >= 1.0 - 1e-5  # E*sum(f*m) >= 1, == 1 if balanced


def test_moe_dp_x_ep():
    params = init_moe_params(1, D, H, E)
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(64, D).astype(np.float32))

    mesh = _mesh((2, 4), ("dp", "ep"))
    fn = moe_shard_map(mesh, capacity_factor=float(E))
    y, aux = fn(params, x)
    ref = _dense_reference(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=2e-5, atol=1e-5)


def test_moe_capacity_drops_tokens():
    """With capacity 1 slot per expert per shard, overflow tokens get a
    zero output (Switch semantics), never a crash."""
    params = init_moe_params(2, D, H, E)
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(32, D).astype(np.float32))

    mesh = _mesh((4,), ("ep",))
    tight = moe_shard_map(mesh, capacity_factor=0.25)
    loose = moe_shard_map(mesh, capacity_factor=float(E))
    y_tight, _ = tight(params, x)
    y_loose, _ = loose(params, x)
    tight_rows = np.abs(np.asarray(y_tight)).sum(axis=1)
    loose_rows = np.abs(np.asarray(y_loose)).sum(axis=1)
    dropped = (tight_rows == 0) & (loose_rows > 0)
    assert dropped.any()  # congestion actually dropped something
    kept = tight_rows > 0
    np.testing.assert_allclose(np.asarray(y_tight)[kept],
                               np.asarray(y_loose)[kept],
                               rtol=2e-5, atol=1e-5)


def test_moe_gradients_flow():
    params = init_moe_params(3, D, H, E)
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(32, D).astype(np.float32))
    mesh = _mesh((4,), ("ep",))
    fn = moe_shard_map(mesh, capacity_factor=float(E))

    def loss(params):
        y, aux = fn(params, x)
        return jnp.mean(y ** 2) + 0.01 * aux

    grads = jax.grad(loss)(params)
    for name in ("gate_w", "w1", "w2"):
        g = np.asarray(grads[name])
        assert np.isfinite(g).all(), name
        assert np.abs(g).sum() > 0, name


def test_moe_aux_identical_across_meshes():
    """The load-balancing aux averages over every token-sharding axis:
    the same global batch must yield the same aux on an ep-only mesh
    and a dp x ep mesh (router grads must match the reported loss)."""
    params = init_moe_params(4, D, H, E)
    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(64, D).astype(np.float32))

    _, aux_ep = moe_shard_map(_mesh((4,), ("ep",)),
                              capacity_factor=float(E))(params, x)
    _, aux_dp = moe_shard_map(_mesh((2, 4), ("dp", "ep")),
                              capacity_factor=float(E))(params, x)
    np.testing.assert_allclose(float(aux_ep), float(aux_dp), rtol=1e-6)


def test_moe_program_expert():
    """The expert network as a fluid-built Program (vmapped over the
    local expert axis): dispatch/combine trains and the output depends
    on the Program experts' weights."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.parallel import MoEProgramLayer

    def build_expert():
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            h = fluid.layers.data(name="h", shape=[D], dtype="float32")
            out = fluid.layers.fc(input=h, size=D, act="tanh")
        return main, startup, "h", out.name

    mesh = _mesh((2, 4), ("dp", "ep"))
    layer = MoEProgramLayer(build_expert, n_experts=E, d_model=D,
                            mesh=mesh, capacity_factor=float(E))
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(64, D).astype(np.float32))

    def loss_fn(params):
        y, aux = layer(params, x)
        return jnp.mean((y - x) ** 2) + 0.01 * aux

    params = layer.params
    step = jax.jit(lambda p: (loss_fn(p), jax.grad(loss_fn)(p)))
    losses = []
    for _ in range(10):
        loss, grads = step(params)
        params = jax.tree_util.tree_map(lambda p, g: p - 0.2 * g,
                                        params, grads)
        losses.append(float(loss))
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    g = np.asarray(grads["experts"]["fc_0.w_0"])
    assert np.isfinite(g).all() and np.abs(g).sum() > 0
