"""ProgramDecoder: compiled generation from a single-step fluid Program.

A tiny RNN LM is trained through the executor; the SAME step program
then generates via (a) ProgramDecoder (one jitted scan, the deploy hot
path) and (b) a per-step executor loop (how the host-op path steps) —
greedy outputs must match token for token, and beam(1) must equal
greedy.
"""

import numpy as np

import paddle_tpu.fluid as fluid

V, E, H = 23, 12, 16
BOS, EOS = 1, 0


def _build_step_program():
    """One decode step: token [B] + hidden [B,H] -> logits [B,V] +
    new hidden."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        tok = fluid.layers.data(name="tok", shape=[-1], dtype="int64",
                                append_batch_size=False)
        h_in = fluid.layers.data(name="h_in", shape=[-1, H],
                                 dtype="float32", append_batch_size=False)
        emb = fluid.layers.embedding(tok, size=[V, E])
        h_out = fluid.layers.fc(input=[emb, h_in], size=H, act="tanh")
        logits = fluid.layers.fc(input=h_out, size=V, act=None)
    return main, startup, tok, h_in, h_out, logits


def _train(main, startup, logits_name, steps=30):
    """A few SGD steps on random next-token data so weights are
    non-initial (generation must reflect training)."""
    train_prog = main.clone()
    with fluid.program_guard(train_prog, startup):
        label = fluid.layers.data(name="label", shape=[-1, 1],
                                  dtype="int64", append_batch_size=False)
        logits_var = train_prog.global_block().var(logits_name)
        loss = fluid.layers.mean(
            x=fluid.layers.softmax_with_cross_entropy(logits_var, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rs = np.random.RandomState(0)
    for _ in range(steps):
        feed = {"tok": rs.randint(0, V, size=(8,)).astype(np.int64),
                "h_in": rs.randn(8, H).astype(np.float32),
                "label": rs.randint(0, V, size=(8, 1)).astype(np.int64)}
        exe.run(train_prog, feed=feed, fetch_list=[loss])
    return exe


def _greedy_by_executor_loop(exe, main, logits, h_out, batch, max_len):
    """Per-step fetch loop — the shape of the host-op generation path."""
    tok = np.full((batch,), BOS, np.int64)
    h = np.zeros((batch, H), np.float32)
    done = np.zeros((batch,), bool)
    out = []
    for _ in range(max_len):
        lg, h = exe.run(main, feed={"tok": tok, "h_in": h},
                        fetch_list=[logits, h_out])
        nxt = np.argmax(np.asarray(lg), axis=-1).astype(np.int64)
        nxt = np.where(done, EOS, nxt)
        done |= nxt == EOS
        out.append(nxt)
        tok = nxt
    return np.stack(out, axis=1)


def test_program_decoder_matches_executor_loop():
    main, startup, tok, h_in, h_out, logits = _build_step_program()
    exe = _train(main, startup, logits.name)

    batch, max_len = 5, 12
    dec = fluid.ProgramDecoder(main, token_name="tok",
                               logits_name=logits.name,
                               state_pairs=[("h_in", h_out.name)])
    toks, lengths = dec.greedy(
        bos=BOS, eos=EOS, max_len=max_len,
        init_state={"h_in": np.zeros((batch, H), np.float32)})

    want = _greedy_by_executor_loop(exe, main, logits, h_out, batch,
                                    max_len)
    np.testing.assert_array_equal(toks, want)
    assert lengths.shape == (batch,)

    # beam(1) == greedy on the same program
    seqs, scores = dec.beam(
        beam_size=1, bos=BOS, eos=EOS, max_len=max_len,
        init_state={"h_in": np.zeros((batch, H), np.float32)})
    np.testing.assert_array_equal(seqs[:, 0, :], toks)
    assert np.all(np.isfinite(scores))


def test_program_decoder_sampling():
    """Temperature→0 sampling converges to greedy; temperature 1 with
    different seeds diversifies; top_k=1 equals greedy by definition."""
    main, startup, tok, h_in, h_out, logits = _build_step_program()
    _train(main, startup, logits.name)
    dec = fluid.ProgramDecoder(main, token_name="tok",
                               logits_name=logits.name,
                               state_pairs=[("h_in", h_out.name)])
    batch, max_len = 5, 10
    init = {"h_in": np.zeros((batch, H), np.float32)}

    greedy, _ = dec.greedy(bos=BOS, eos=EOS, max_len=max_len,
                           init_state=init)
    cold, _ = dec.sample(bos=BOS, eos=EOS, max_len=max_len,
                         init_state=init, temperature=1e-5)
    np.testing.assert_array_equal(cold, greedy)
    top1, _ = dec.sample(bos=BOS, eos=EOS, max_len=max_len,
                         init_state=init, top_k=1)
    np.testing.assert_array_equal(top1, greedy)

    a, _ = dec.sample(bos=BOS, eos=EOS, max_len=max_len,
                      init_state=init, seed=1, temperature=1.5)
    b, _ = dec.sample(bos=BOS, eos=EOS, max_len=max_len,
                      init_state=init, seed=2, temperature=1.5)
    assert not np.array_equal(a, b), "different seeds should diverge"
    assert ((a >= 0) & (a < V)).all()


def test_program_decoder_beam_orders_scores():
    main, startup, tok, h_in, h_out, logits = _build_step_program()
    _train(main, startup, logits.name)
    dec = fluid.ProgramDecoder(main, token_name="tok",
                               logits_name=logits.name,
                               state_pairs=[("h_in", h_out.name)])
    seqs, scores = dec.beam(
        beam_size=3, bos=BOS, eos=EOS, max_len=8,
        init_state={"h_in": np.zeros((4, H), np.float32)})
    assert seqs.shape == (4, 3, 8)
    # best-first ordering per source
    assert np.all(np.diff(scores, axis=1) <= 1e-6)
