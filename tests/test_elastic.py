"""Elastic data-parallel training (paddle_tpu.resilience.elastic):
the generation-numbered view-change protocol over the native master's
TTL-lease store, the generation-stamped sharded manifests with stale
refusal, the real mesh shrink/grow with densified restore, the
no-split-brain guarantee under heartbeat turbulence, and the
supervisor's `elastic_resize` restart reason."""

import json
import os
import time

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu.fluid as fluid
from paddle_tpu import native
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.elastic import (ClusterView,
                                           ElasticMembership,
                                           ElasticTrainer, feed_slice,
                                           latest_elastic_checkpoint)
from paddle_tpu.spmd.checkpoint import (SPMD_MANIFEST,
                                        StaleGenerationError,
                                        measure_densify_restore,
                                        restore_sharded, save_sharded)

TTL_MS = 300


def _poll_until(members, predicate, timeout=15.0, dead=()):
    deadline = time.time() + timeout
    while True:
        views = {}
        for m in members:
            if m in dead:
                continue
            try:
                views[m.host] = m.poll()
            except (IOError, OSError):
                views[m.host] = m.view
        if predicate(views):
            return views
        assert time.time() < deadline, \
            "protocol did not converge: %r" % views
        time.sleep(0.02)


# -- generation-stamped manifests + stale refusal ---------------------------

class TestManifestGeneration:
    def test_manifest_records_elastic_identity(self, tmp_path):
        snap = save_sharded(tmp_path, 7, {"w": np.arange(8.0)},
                            mesh_axes={"dp": 2}, generation=5,
                            plan_fingerprint="fp123")
        with open(os.path.join(snap, SPMD_MANIFEST)) as f:
            man = json.load(f)
        assert man["generation"] == 5
        assert man["plan_fingerprint"] == "fp123"
        assert man["mesh"] == {"dp": 2}

    def test_stale_host_refused_with_both_generations(self, tmp_path):
        snap = save_sharded(tmp_path, 7, {"w": np.arange(8.0)},
                            generation=5)
        mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
        shardings = {"w": NamedSharding(mesh, P())}
        with pytest.raises(StaleGenerationError) as err:
            restore_sharded(snap, shardings, max_generation=4)
        assert err.value.manifest_generation == 5
        assert err.value.caller_generation == 4
        assert "generation 5" in str(err.value)
        assert "generation 4" in str(err.value)
        # equal or newer caller generation restores fine; legacy
        # manifests (no stamp) read back as generation 0
        state, info = restore_sharded(snap, shardings,
                                      max_generation=5)
        assert info["generation"] == 5
        np.testing.assert_array_equal(np.asarray(state["w"]),
                                      np.arange(8.0))

    def test_latest_elastic_checkpoint_prefers_newest_generation(
            self, tmp_path):
        # host a saved step 9 at gen 1; host b saved step 3 at gen 2 —
        # the POST-SHRINK snapshot (higher generation) must win even
        # at a lower step
        save_sharded(tmp_path / "a", 9, {"w": np.ones(4)},
                     generation=1)
        save_sharded(tmp_path / "b", 3, {"w": np.zeros(4)},
                     generation=2)
        snap = latest_elastic_checkpoint(tmp_path)
        assert snap is not None and os.sep + "b" + os.sep in snap


# -- the membership protocol ------------------------------------------------

class TestMembershipProtocol:
    def test_bootstrap_shrink_grow_generations(self):
        master = native.Master()
        members = []
        try:
            for host in ("ma", "mb", "mc"):
                members.append(ElasticMembership(
                    "127.0.0.1:%d" % master.port, host=host,
                    ttl_ms=TTL_MS).join())
            a, b, c = members
            _poll_until(members, lambda vs: all(
                v.gen >= 1 and len(v.hosts) == 3 for v in vs.values()))
            gen0 = a.view.gen
            assert a.view.hosts == ["ma", "mb", "mc"]
            assert a.view == b.view == c.view

            # mb stops heartbeating: only true lease expiry removes it
            b._member_lease._stop.set()
            b._member_lease._thread.join(timeout=5)
            _poll_until(members, lambda vs: all(
                v.gen > gen0 and v.hosts == ["ma", "mc"]
                for h, v in vs.items() if h != "mb"), dead=(b,))
            gen1 = a.view.gen
            assert a.view.reason == "host_lost"

            # rejoin commits a grow at a still-higher generation
            b._member_lease = None
            b.join()
            _poll_until(members, lambda vs: all(
                v.gen > gen1 and v.hosts == ["ma", "mb", "mc"]
                for v in vs.values()))
            assert a.view.reason == "rejoin"
            assert a.view.gen > gen1 > gen0 >= 1
        finally:
            for m in members:
                m.close()
            master.stop()

    def test_view_json_roundtrip_single_line(self):
        view = ClusterView(3, ["b", "a"], reason="host_lost",
                           proposer="a")
        blob = view.to_json()
        assert "\n" not in blob
        back = ClusterView.from_json(blob)
        assert back == view and back.hosts == ["a", "b"]
        assert back.reason == "host_lost" and back.proposer == "a"

    def test_no_split_brain_under_heartbeat_turbulence(self):
        """Satellite: injected `coordinator/heartbeat` latency +
        io_error make both members' heartbeats slow and flaky — but
        their leases keep renewing, so the leader must NOT shrink a
        slow-but-alive host.  Only genuinely stopping the heartbeat
        (true lease expiry) may commit the shrink."""
        ttl = 600
        master = native.Master()
        a = b = None
        try:
            a = ElasticMembership("127.0.0.1:%d" % master.port,
                                  host="sa", ttl_ms=ttl).join()
            b = ElasticMembership("127.0.0.1:%d" % master.port,
                                  host="sb", ttl_ms=ttl).join()
            _poll_until([a, b], lambda vs: all(
                v.gen >= 1 and len(v.hosts) == 2 for v in vs.values()))
            gen0 = a.view.gen

            faults.enable(seed=11)
            # each beat stalls hard (but under the TTL) and two RPCs
            # die outright (retried within the beat budget)
            lat = faults.inject("coordinator/heartbeat", "latency",
                                latency_s=ttl / 1000.0 / 3, times=6)
            # reached once the latency spec exhausts; both fires land
            # in one beat's retry budget (max_attempts=3)
            ioe = faults.inject("coordinator/heartbeat", "io_error",
                                times=2)
            deadline = time.time() + ttl / 1000.0 * 3
            while time.time() < deadline:
                view = a.poll()
                assert view.gen == gen0 and len(view.hosts) == 2, \
                    "split-brain shrink: a slow-but-alive host was " \
                    "declared dead (%r)" % view
                time.sleep(0.05)
            assert lat.fired >= 4 and ioe.fired >= 1, (lat, ioe)
            assert not b._member_lease.lapsed
            faults.disable()

            # control: ACTUAL expiry (heartbeat stopped) does shrink
            b._member_lease._stop.set()
            b._member_lease._thread.join(timeout=5)
            _poll_until([a], lambda vs: vs["sa"].gen > gen0
                        and vs["sa"].hosts == ["sa"])
            assert a.view.reason == "host_lost"
        finally:
            faults.disable()
            for m in (a, b):
                if m is not None:
                    m.close()
            master.stop()


# -- the elastic trainer ----------------------------------------------------

BATCH, DIM, HIDDEN, CLASSES = 16, 8, 1024, 4


def _build_mlp():
    fluid.framework.reset_unique_name()
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[BATCH, DIM],
                              dtype="float32", append_batch_size=False)
        label = fluid.layers.data(name="label", shape=[BATCH, 1],
                                  dtype="int64",
                                  append_batch_size=False)
        h = fluid.layers.fc(input=x, size=HIDDEN, act="relu")
        logits = fluid.layers.fc(input=h, size=CLASSES, act=None)
        loss = fluid.layers.softmax_with_cross_entropy(logits, label)
        avg = fluid.layers.mean(loss)
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.1, momentum=0.9).minimize(avg)
    return main, startup, ["x", "label"], [avg.name]


def _feeds(step):
    rs = np.random.RandomState(100 + step)
    return {"x": rs.rand(BATCH, DIM).astype(np.float32),
            "label": rs.randint(0, CLASSES,
                                size=(BATCH, 1)).astype(np.int64)}


class TestElasticTrainer:
    def test_shrink_densifies_and_grow_restores(self, tmp_path):
        """The simulated fleet: 2 hosts x 4 devices.  Losing a host
        REALLY rebuilds the mesh dp 8 -> 4 (plan re-derived over the
        new axis sizes) and the zero1 state restores through the
        densify path; the rejoin grows back to dp=8."""
        master = native.Master()
        h0 = h1 = None
        try:
            h0 = ElasticMembership("127.0.0.1:%d" % master.port,
                                   host="t0", ttl_ms=TTL_MS).join()
            h1 = ElasticMembership("127.0.0.1:%d" % master.port,
                                   host="t1", ttl_ms=TTL_MS).join()
            et = ElasticTrainer(h0, _build_mlp,
                                tmp_path / "ckpts",
                                devices_per_host=4, zero_stage=1)
            _poll_until([h0, h1], lambda vs: all(
                v.gen >= 1 and len(v.hosts) == 2 for v in vs.values()))
            assert et.maybe_resize()["direction"] == "bootstrap"
            assert et.dp == 8
            assert et.trainer.elastic_generation == et.generation

            # one fixed batch throughout: the loss must decrease
            # monotonically ACROSS resizes iff state actually carried
            losses = [float(np.asarray(et.step(_feeds(0))[0])
                            .reshape(-1)[0]) for _ in range(2)]
            et.save(2)

            h1._member_lease._stop.set()
            h1._member_lease._thread.join(timeout=5)
            deadline = time.time() + 15
            shrink = None
            while shrink is None:
                assert time.time() < deadline, "shrink never committed"
                shrink = et.maybe_resize(save_step=2)
                time.sleep(0.02)
            assert shrink["direction"] == "shrink"
            assert shrink["reason"] == "host_lost"
            assert et.dp == 4
            # zero1 moments were 8-way sharded; the 4-way mesh can't
            # place them shard-exact — the densify path must have run
            assert shrink["densified"], shrink
            losses.append(float(np.asarray(et.step(_feeds(0))[0])
                                .reshape(-1)[0]))
            et.save(3)

            h1._member_lease = None
            h1.join()
            deadline = time.time() + 15
            grow = None
            while grow is None:
                assert time.time() < deadline, "grow never committed"
                h1.poll()
                grow = et.maybe_resize(save_step=3)
                time.sleep(0.02)
            assert grow["direction"] == "grow"
            assert grow["reason"] == "rejoin"
            assert et.dp == 8
            losses.append(float(np.asarray(et.step(_feeds(0))[0])
                                .reshape(-1)[0]))
            assert all(np.isfinite(l) for l in losses), losses
            assert losses[-1] < losses[0], losses

            from paddle_tpu.obs import telemetry as obs_tele

            snap = obs_tele.snapshot()
            assert snap.get("elastic_resizes_total{direction=shrink,"
                            "reason=host_lost}", 0) >= 1, snap
            assert snap.get("elastic_resizes_total{direction=grow,"
                            "reason=rejoin}", 0) >= 1, snap
            assert snap.get("elastic_generation") == et.generation
            assert snap.get("elastic_lost_hosts_total", 0) >= 1
        finally:
            for m in (h0, h1):
                if m is not None:
                    m.close()
            master.stop()

    def test_feed_slice_deterministic_and_exhaustive(self):
        hosts = ["w2", "w0", "w1"]
        slices = [feed_slice(h, hosts, 16) for h in sorted(hosts)]
        assert slices == [(0, 6), (6, 11), (11, 16)]
        # every member computes the same split from the view alone
        assert feed_slice("w1", ["w0", "w1", "w2"], 16) == (6, 11)


# -- densify measurement (sized) --------------------------------------------

def test_measure_densify_restore_blob(tmp_path):
    blob = measure_densify_restore(tmp_path, from_dp=8, to_dp=4,
                                   n_vars=2, rows=512, cols=64)
    assert blob["kind"] == "paddle_tpu.densify_restore_measurement"
    assert blob["from_mesh"] == {"dp": 8}
    assert blob["to_mesh"] == {"dp": 4}
    assert blob["densified"] == 2 and blob["verified"]
    assert blob["bytes_total"] == 2 * 512 * 64 * 4
    assert blob["seconds"] > 0 and blob["mib_per_s"] > 0


# -- supervisor integration -------------------------------------------------

class _FakeSaver:
    """Minimal supervisor-saver protocol (dense side unused)."""

    interval_secs = 1e9

    def __init__(self, root):
        self.root = str(root)
        self._snaps = []
        self.restores = 0

    def save(self, step, scope=None):
        snap = os.path.join(self.root,
                            "snap_%05d_%02d" % (step, len(self._snaps)))
        os.makedirs(snap, exist_ok=True)
        self._snaps.append((step, snap))
        return snap

    def wait(self):
        pass

    def latest(self):
        return self._snaps[-1][1] if self._snaps else None

    def restore_latest(self, scope=None):
        self.restores += 1
        return self._snaps[-1][0] if self._snaps else None


def test_supervisor_elastic_resize_reason_and_generation(tmp_path):
    """Satellite: `supervisor_restarts_total{reason=elastic_resize}`
    is distinct from preempt, the resize cycle does NOT roll state
    back to a pre-resize snapshot, and `supervisor.json` records the
    generation so a full-job restart resumes the post-shrink view."""
    from paddle_tpu.obs import telemetry as obs_tele
    from paddle_tpu.resilience.supervisor import (ElasticResized,
                                                  SUPERVISOR_META,
                                                  TrainingSupervisor)

    saver = _FakeSaver(tmp_path)
    sup = TrainingSupervisor(str(tmp_path), saver=saver,
                             steps_per_checkpoint=100, generation=1)
    fired = {"done": False}

    def step_fn(batch):
        if sup._step == 2 and not fired["done"]:
            fired["done"] = True
            raise ElasticResized(2, direction="shrink")
        return 1.0 / (sup._step + 1)

    summary = sup.run(step_fn, lambda: iter(range(5)), num_epochs=1)
    assert summary["steps"] == 5 and summary["restarts"] == 1
    # the elastic layer owns the post-resize state: no rollback ran
    assert saver.restores == 0
    assert sup.generation == 2
    snap = obs_tele.snapshot()
    assert snap.get("supervisor_restarts_total{reason=elastic_resize}"
                    ) == 1, snap
    assert "supervisor_restarts_total{reason=preempt}" not in snap
    with open(os.path.join(saver.latest(), SUPERVISOR_META)) as f:
        meta = json.load(f)
    assert meta["generation"] == 2
    # a fresh supervisor resuming from this meta adopts the generation
    sup2 = TrainingSupervisor(str(tmp_path), saver=saver,
                              steps_per_checkpoint=100)
    sup2._restore_latest()
    assert sup2.generation == 2
