"""Eager (per-op debug) executor vs whole-block jit: the same program,
feeds, and initial state must train the same way in both modes
(SURVEY's op-by-op vs compiled parity hard part; reference behavior:
executor.cc runs the same kernels the fused path does)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.core import scope as scope_mod


def _build():
    img = fluid.layers.data(name="img", shape=[1, 8, 8],
                            dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    conv = fluid.layers.conv2d(input=img, num_filters=4, filter_size=3,
                               act=None)
    bn = fluid.layers.batch_norm(input=conv, act="relu")
    logits = fluid.layers.fc(input=bn, size=3, act=None)
    loss = fluid.layers.mean(
        x=fluid.layers.softmax_with_cross_entropy(
            logits, label))
    fluid.optimizer.MomentumOptimizer(learning_rate=0.05,
                                      momentum=0.9).minimize(loss)
    return loss


def _run(eager, feeds, steps=5):
    scope_mod.reset_global_scope()
    from paddle_tpu.fluid import framework

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    framework.reset_unique_name()
    loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    out = []
    for _ in range(steps):
        v, = exe.run(fluid.default_main_program(), feed=feeds,
                     fetch_list=[loss], eager=eager)
        out.append(float(np.asarray(v).reshape(-1)[0]))
    return out


def test_eager_matches_jit_training():
    rs = np.random.RandomState(0)
    feeds = {"img": rs.rand(6, 1, 8, 8).astype(np.float32),
             "label": rs.randint(0, 3, size=(6, 1)).astype(np.int64)}
    jit_losses = _run(eager=False, feeds=feeds)
    eager_losses = _run(eager=True, feeds=feeds)
    # same kernels, different fusion: float drift only
    np.testing.assert_allclose(eager_losses, jit_losses, rtol=2e-5,
                               atol=2e-6)
    # and training actually progressed
    assert jit_losses[-1] < jit_losses[0]
