"""paddle_tpu.compile — fingerprint stability, the persistent
executable cache, and its executor wiring.

The fingerprint tests are table-driven per ISSUE 9: the same Program
rebuilt (even in a fresh process) must fingerprint identically, and
ANY semantic change — an op attr, a dtype, a mesh axis, the pass
pipeline — must change it.
"""

import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.compile import fingerprint, pcache
from paddle_tpu.core.scope import Scope
from paddle_tpu.fluid import executor as executor_mod
from paddle_tpu.obs import telemetry as obs_tele
from paddle_tpu.utils import flags


@pytest.fixture(autouse=True)
def _reset_compile_state():
    yield
    flags.set_flag("compile_cache_dir", "")
    flags.set_flag("compile_passes", "")
    pcache.reset()


def _build_mlp():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        h = fluid.layers.fc(input=x, size=4, act="tanh")
        y = fluid.layers.fc(input=h, size=2, act="softmax")
    return main, startup, y.name


def _fp(main, fetch, **kw):
    kw.setdefault("feeds", ["x"])
    kw.setdefault("fetches", [fetch])
    return fingerprint.program_fingerprint(main, **kw)


# ---------------------------------------------------------------------------
# fingerprint stability
# ---------------------------------------------------------------------------

class TestFingerprint:
    def test_same_program_rebuilt_same_fingerprint(self):
        m1, _, f1 = _build_mlp()
        m2, _, f2 = _build_mlp()
        assert m1 is not m2
        assert _fp(m1, f1) == _fp(m2, f2)

    def test_clone_same_fingerprint(self):
        m, _, f = _build_mlp()
        assert _fp(m, f) == _fp(m.clone(), f)

    def test_fresh_process_same_fingerprint(self):
        """The restart contract: an independent interpreter building
        the same Program computes the same fingerprint."""
        m, _, f = _build_mlp()
        here = _fp(m, f)
        code = (
            "import paddle_tpu.fluid as fluid\n"
            "from paddle_tpu.compile import fingerprint\n"
            "main, startup = fluid.Program(), fluid.Program()\n"
            "with fluid.program_guard(main, startup):\n"
            "    x = fluid.layers.data(name='x', shape=[8],"
            " dtype='float32')\n"
            "    h = fluid.layers.fc(input=x, size=4, act='tanh')\n"
            "    y = fluid.layers.fc(input=h, size=2, act='softmax')\n"
            "print(fingerprint.program_fingerprint(main, feeds=['x'],"
            " fetches=[y.name]))\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="")
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        out = subprocess.run([sys.executable, "-c", code], cwd=repo,
                             env=env, capture_output=True, text=True,
                             timeout=240)
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip().splitlines()[-1] == here

    @pytest.mark.parametrize("label,mutate", [
        ("op attr", lambda m: m.global_block().desc.ops[0]
            .attrs.update(extra_knob=3.0)),
        ("var dtype", lambda m: setattr(
            m.global_block().desc.vars["x"], "dtype", "int32")),
        ("var shape", lambda m: setattr(
            m.global_block().desc.vars["x"], "shape", (-1, 16))),
        ("extra op", lambda m: m.global_block().desc.ops.append(
            m.global_block().desc.ops[0])),
        ("op order", lambda m: m.global_block().desc.ops.reverse()),
    ])
    def test_ir_changes_change_fingerprint(self, label, mutate):
        m, _, f = _build_mlp()
        base = _fp(m, f)
        mutated = m.clone()
        mutate(mutated)
        assert _fp(mutated, f) != base, label

    def test_context_changes_change_fingerprint(self):
        m, _, f = _build_mlp()
        base = _fp(m, f)
        table = {
            "feeds": _fp(m, f, feeds=["x", "x2"]),
            "fetches": _fp(m, "other_fetch"),
            "flags": _fp(m, f, flag_items=[("amp_bf16", True)]),
            "pipeline": _fp(m, f, pipeline_id="v1:dce,cse"),
            "mesh": _fp(m, f, mesh={"dp": 4, "mp": 2}),
            "mesh axis": _fp(m, f, mesh={"dp": 8}),
        }
        for label, fp in table.items():
            assert fp != base, label
        assert len(set(table.values())) == len(table)

    def test_values_signature(self):
        a = np.zeros((2, 3), np.float32)
        assert fingerprint.values_signature({"a": a}) == \
            fingerprint.values_signature([("a", np.ones((2, 3),
                                                        np.float32))])
        assert fingerprint.values_signature({"a": a}) != \
            fingerprint.values_signature(
                {"a": np.zeros((2, 4), np.float32)})
        assert fingerprint.values_signature({"a": a}) != \
            fingerprint.values_signature(
                {"a": np.zeros((2, 3), np.int32)})


# ---------------------------------------------------------------------------
# the persistent cache itself
# ---------------------------------------------------------------------------

def _compiled_unit(scale=2.0):
    import jax
    import jax.numpy as jnp

    def f(x):
        return x * scale

    return jax.jit(f).lower(jnp.ones((4,), jnp.float32)).compile()


class TestPersistentCache:
    def test_put_get_roundtrip(self, tmp_path):
        import jax.numpy as jnp

        cache = pcache.PersistentCache(str(tmp_path))
        kind = cache.put("a" * 64, _compiled_unit(),
                         compile_seconds=0.5)
        assert kind == "serialized"
        loaded = cache.get("a" * 64)
        assert loaded is not None
        np.testing.assert_array_equal(
            np.asarray(loaded(jnp.ones((4,), jnp.float32))),
            np.full((4,), 2.0, np.float32))
        snap = obs_tele.snapshot()
        assert snap["compile_cache_hits_total"] == 1
        assert snap["compile_cache_saved_compile_seconds_total"] \
            == pytest.approx(0.5)

    def test_missing_key_is_miss(self, tmp_path):
        cache = pcache.PersistentCache(str(tmp_path))
        assert cache.get("b" * 64) is None
        assert obs_tele.snapshot()["compile_cache_misses_total"] == 1

    def test_corrupt_entry_quarantined_not_raised(self, tmp_path):
        cache = pcache.PersistentCache(str(tmp_path))
        cache.put("c" * 64, _compiled_unit())
        path = cache._entry_path("c" * 64)
        blob = bytearray(open(path, "rb").read())
        blob[-10] ^= 0xFF
        open(path, "wb").write(bytes(blob))
        assert cache.get("c" * 64) is None  # miss, no exception
        assert not os.path.exists(path)
        assert os.path.exists(os.path.join(
            str(tmp_path), "quarantine", os.path.basename(path)))
        snap = obs_tele.snapshot()
        assert snap["compile_cache_errors_total{kind=corrupt}"] == 1

    def test_truncated_entry_quarantined(self, tmp_path):
        cache = pcache.PersistentCache(str(tmp_path))
        cache.put("d" * 64, _compiled_unit())
        path = cache._entry_path("d" * 64)
        blob = open(path, "rb").read()
        open(path, "wb").write(blob[:len(blob) // 2])
        assert cache.get("d" * 64) is None

    def test_serialize_unsupported_stores_stub(self, tmp_path,
                                               monkeypatch):
        from jax.experimental import serialize_executable as se

        def boom(compiled):
            raise ValueError("Compilation does not support "
                             "serialization")

        monkeypatch.setattr(se, "serialize", boom)
        cache = pcache.PersistentCache(str(tmp_path))
        kind = cache.put("e" * 64, _compiled_unit(),
                         compile_seconds=1.0)
        assert kind == "stub"
        assert cache.get("e" * 64) is None  # stub loads are misses
        assert cache.stats()["entries"] == 1  # but stats see them

    def test_lru_eviction_by_size(self, tmp_path):
        cache = pcache.PersistentCache(str(tmp_path), max_bytes=1)
        cache._max_bytes = 10 ** 9  # let both land first
        cache.put("f" * 64, _compiled_unit())
        os.utime(cache._entry_path("f" * 64), (1, 1))  # oldest-used
        cache.put("g" * 64, _compiled_unit(3.0))
        size_one = os.path.getsize(cache._entry_path("g" * 64))
        cache._max_bytes = size_one  # room for exactly one entry
        assert cache.evict() == 1
        assert not os.path.exists(cache._entry_path("f" * 64))
        assert os.path.exists(cache._entry_path("g" * 64))
        assert obs_tele.snapshot()[
            "compile_cache_evictions_total"] == 1

    def test_gc_clears_quarantine(self, tmp_path):
        cache = pcache.PersistentCache(str(tmp_path))
        cache.put("h" * 64, _compiled_unit())
        path = cache._entry_path("h" * 64)
        open(path, "wb").write(b"garbage")
        cache.get("h" * 64)  # quarantines
        assert cache.stats()["quarantined"] == 1
        summary = cache.gc()
        assert summary["quarantine_cleared"] == 1
        assert cache.stats()["quarantined"] == 0


# ---------------------------------------------------------------------------
# executor wiring
# ---------------------------------------------------------------------------

def _build_scale_program(scale=2.0):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.scale(x=x, scale=scale)
        z = fluid.layers.scale(x=y, scale=3.0)
    return main, startup, z.name


class TestExecutorPCache:
    def _run(self, main, startup, fetch, x):
        exe = executor_mod.Executor(executor_mod.CPUPlace())
        with executor_mod.scope_guard(Scope()):
            exe.run(startup)
            return np.asarray(exe.run(main, feed={"x": x},
                                      fetch_list=[fetch])[0])

    def test_restart_reload_zero_compiles(self, tmp_path):
        flags.set_flag("compile_cache_dir", str(tmp_path))
        x = np.arange(8, dtype=np.float32).reshape(2, 4)
        cold = self._run(*_build_scale_program(), x)
        assert pcache.get_cache().stats()["entries"] > 0
        pcache.reset()
        before = obs_tele.jit_trace_count()
        warm = self._run(*_build_scale_program(), x)
        assert obs_tele.jit_trace_count() == before
        np.testing.assert_array_equal(cold, warm)
        assert obs_tele.snapshot()["compile_cache_hits_total"] >= 1

    def test_different_shapes_get_distinct_entries(self, tmp_path):
        flags.set_flag("compile_cache_dir", str(tmp_path))
        main, startup, fetch = _build_scale_program()
        exe = executor_mod.Executor(executor_mod.CPUPlace())
        with executor_mod.scope_guard(Scope()):
            exe.run(startup)
            exe.run(main, feed={"x": np.zeros((2, 4), np.float32)},
                    fetch_list=[fetch])
            exe.run(main, feed={"x": np.zeros((5, 4), np.float32)},
                    fetch_list=[fetch])
        assert pcache.get_cache().stats()["entries"] == 2

    def test_attr_change_misses(self, tmp_path):
        flags.set_flag("compile_cache_dir", str(tmp_path))
        x = np.arange(8, dtype=np.float32).reshape(2, 4)
        self._run(*_build_scale_program(2.0), x)
        hits0 = obs_tele.snapshot().get("compile_cache_hits_total", 0)
        out = self._run(*_build_scale_program(5.0), x)
        np.testing.assert_array_equal(out, x * 15.0)
        assert obs_tele.snapshot().get("compile_cache_hits_total",
                                       0) == hits0
        assert pcache.get_cache().stats()["entries"] == 2

    def test_disabled_flag_means_no_disk_io(self, tmp_path):
        x = np.arange(8, dtype=np.float32).reshape(2, 4)
        self._run(*_build_scale_program(), x)
        assert "compile_cache_hits_total" not in obs_tele.snapshot()
        assert os.listdir(str(tmp_path)) == []

    def test_corrupt_entry_recompiles_and_requarantines(self,
                                                        tmp_path):
        flags.set_flag("compile_cache_dir", str(tmp_path))
        x = np.arange(8, dtype=np.float32).reshape(2, 4)
        cold = self._run(*_build_scale_program(), x)
        cache = pcache.get_cache()
        entry = next(cache._iter_entries())
        open(entry, "wb").write(b"PTPC1\nnot json\n")
        pcache.reset()
        out = self._run(*_build_scale_program(), x)
        np.testing.assert_array_equal(cold, out)
        assert pcache.get_cache().stats()["quarantined"] == 1
        # the recompile re-stored a clean entry
        assert pcache.get_cache().stats()["entries"] >= 1


class TestProgramCacheEvictionMetric:
    def test_eviction_counted_and_logged(self, monkeypatch, caplog):
        import logging

        monkeypatch.setattr(executor_mod.Executor, "_CACHE_MAX", 1)
        exe = executor_mod.Executor(executor_mod.CPUPlace())
        x = np.arange(8, dtype=np.float32).reshape(2, 4)
        with executor_mod.scope_guard(Scope()), \
                caplog.at_level(logging.DEBUG,
                                logger="paddle_tpu.executor"):
            for scale in (2.0, 3.0):
                main, startup, fetch = _build_scale_program(scale)
                exe.run(startup)
                exe.run(main, feed={"x": x}, fetch_list=[fetch])
        snap = obs_tele.snapshot()
        assert snap["executor_program_cache_evictions_total"] >= 1
        assert any("evicted program cache entry" in r.message
                   for r in caplog.records)
