"""Op tests: beam_search, beam_search_decode, prior_box, iou_similarity,
bipartite_match, detection_output, positive_negative_pair (reference:
beam_search_op_test.cc, beam_search_decode_op_test.cc,
test_prior_box_op.py, test_iou_similarity_op.py (later era),
test_bipartite_match_op.py, test_detection_output_op.py (v2 era),
test_positive_negative_pair_op.py)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.core.ragged import RaggedTensor
from op_test import OpTest

RS = np.random.RandomState(77)


def _run_op(op_type, inputs, outputs_spec, attrs):
    """inputs: name -> (value, lod_level); outputs_spec: slot ->
    [(name, dtype)]"""
    prog = framework.Program()
    block = prog.global_block()
    ins = {}
    feeds = {}
    for slot, entries in inputs.items():
        vs = []
        for name, val, lod in entries:
            arr = val.values if isinstance(val, RaggedTensor) else val
            v = block.create_var(name=name,
                                 shape=list(np.asarray(arr).shape),
                                 dtype=str(np.asarray(arr).dtype),
                                 lod_level=lod)
            feeds[name] = val
            vs.append(v)
        ins[slot] = vs
    outs = {}
    fetch = []
    for slot, entries in outputs_spec.items():
        vs = []
        for name, dtype in entries:
            v = block.create_var(name=name, shape=[1], dtype=dtype)
            vs.append(v)
            fetch.append(name)
        outs[slot] = vs
    block.append_op(type=op_type, inputs=ins, outputs=outs, attrs=attrs)
    exe = fluid.Executor(fluid.CPUPlace())
    return exe.run(prog, feed=feeds, fetch_list=fetch,
                   scope=fluid.Scope(), return_numpy=False)


def test_beam_search():
    """Mirrors reference beam_search_op_test.cc: 2 sources x 2 beams,
    4 candidates each, beam_size 2, end_id 0."""
    # pre_ids: [4, 1]; beam row 2's prefix hit end_id
    pre_ids = RaggedTensor(
        np.asarray([[1], [2], [0], [4]], np.int64),
        [np.asarray([0, 2, 4]), np.asarray([0, 1, 2, 3, 4])])
    ids = RaggedTensor(
        np.asarray([[4, 2, 5], [2, 1, 3], [3, 5, 2], [8, 2, 1]],
                   np.int64),
        [np.asarray([0, 2, 4]), np.asarray([0, 1, 2, 3, 4])])
    scores = RaggedTensor(
        np.asarray([[0.5, 0.3, 0.2], [0.6, 0.3, 0.1],
                    [0.9, 0.5, 0.1], [0.7, 0.5, 0.1]], np.float32),
        ids.row_splits)

    sel_ids, sel_scores = _run_op(
        "beam_search",
        {"pre_ids": [("pre", pre_ids, 2)],
         "ids": [("ids", ids, 2)],
         "scores": [("sc", scores, 2)]},
        {"selected_ids": [("sid", "int64")],
         "selected_scores": [("ssc", "float32")]},
        {"level": 0, "beam_size": 2, "end_id": 0})

    # source 0: top2 of {.5,.3,.2,.6,.3,.1} -> (row1 id2 .6), (row0 id4 .5)
    # source 1: top2 -> (row2 id3 .9), (row3 id8 .7); row2 prefix==end ->
    # pruned -> only row3 survives
    np.testing.assert_array_equal(
        np.asarray(sel_ids.values).ravel(), [4, 2, 8])
    np.testing.assert_allclose(
        np.asarray(sel_scores.values).ravel(), [0.5, 0.6, 0.7], rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(sel_ids.row_splits[0]),
                                  [0, 2, 4])
    np.testing.assert_array_equal(np.asarray(sel_ids.row_splits[1]),
                                  [0, 1, 2, 2, 3])


def test_beam_search_decode():
    """Two-step decode, one source, beam 2: backtrack chains."""
    # step0: 2 items (roots), rows [0,1] of one source
    step0 = RaggedTensor(
        np.asarray([[1], [2]], np.int64),
        [np.asarray([0, 2]), np.asarray([0, 1, 2])])
    s_step0 = RaggedTensor(
        np.asarray([[0.1], [0.2]], np.float32), step0.row_splits)
    # step1: item0 of step0 -> tokens 3,4 ; item1 -> token 5
    step1 = RaggedTensor(
        np.asarray([[3], [4], [5]], np.int64),
        [np.asarray([0, 2]), np.asarray([0, 2, 3])])
    s_step1 = RaggedTensor(
        np.asarray([[0.3], [0.4], [0.5]], np.float32), step1.row_splits)

    prog = framework.Program()
    block = prog.global_block()
    ids_v = block.create_var(name="ids_arr", shape=[1], dtype="int64")
    sc_v = block.create_var(name="sc_arr", shape=[1], dtype="float32")
    out_i = block.create_var(name="sent_ids", shape=[1], dtype="int64")
    out_s = block.create_var(name="sent_scores", shape=[1],
                             dtype="float32")
    block.append_op(type="beam_search_decode",
                    inputs={"Ids": [ids_v], "Scores": [sc_v]},
                    outputs={"SentenceIds": [out_i],
                             "SentenceScores": [out_s]})
    exe = fluid.Executor(fluid.CPUPlace())
    sent_ids, sent_scores = exe.run(
        prog,
        feed={"ids_arr": [step0, step1], "sc_arr": [s_step0, s_step1]},
        fetch_list=["sent_ids", "sent_scores"], scope=fluid.Scope(),
        return_numpy=False)

    # three hypotheses: [1,3], [1,4], [2,5]
    np.testing.assert_array_equal(
        np.asarray(sent_ids.values).ravel(), [1, 3, 1, 4, 2, 5])
    np.testing.assert_array_equal(np.asarray(sent_ids.row_splits[0]),
                                  [0, 3])
    np.testing.assert_array_equal(np.asarray(sent_ids.row_splits[1]),
                                  [0, 2, 4, 6])


class TestPriorBox(OpTest):
    op_type = "prior_box"

    def test(self):
        feat = RS.rand(1, 8, 2, 2).astype("float32")
        image = RS.rand(1, 3, 16, 16).astype("float32")
        min_sizes, ar = [4.0], [2.0]
        self.inputs = {"Input": feat, "Image": image}
        self.attrs = {"min_sizes": min_sizes, "max_sizes": [],
                      "aspect_ratios": ar, "flip": True, "clip": True,
                      "variances": [0.1, 0.1, 0.2, 0.2]}
        # num_priors = 1 (min) + 2 (ar 2.0 + flip)
        H = W = 2
        num_priors = 3
        step = 16 / 2
        boxes = np.zeros((H, W, num_priors, 4), "float32")
        whs = [(2.0, 2.0),
               (4.0 * np.sqrt(2.0) / 2, 4.0 / np.sqrt(2.0) / 2),
               (4.0 * np.sqrt(0.5) / 2, 4.0 / np.sqrt(0.5) / 2)]
        for i in range(H):
            for j in range(W):
                cx, cy = (j + 0.5) * step, (i + 0.5) * step
                for k, (pw, ph) in enumerate(whs):
                    boxes[i, j, k] = [
                        max((cx - pw) / 16, 0), max((cy - ph) / 16, 0),
                        min((cx + pw) / 16, 1), min((cy + ph) / 16, 1)]
        var = np.tile(np.asarray([0.1, 0.1, 0.2, 0.2], "float32"),
                      (H, W, num_priors, 1))
        self.outputs = {"Boxes": boxes, "Variances": var}
        self.check_output(atol=1e-5)


class TestIouSimilarity(OpTest):
    op_type = "iou_similarity"

    def test(self):
        x = np.asarray([[0, 0, 2, 2], [1, 1, 3, 3]], "float32")
        y = np.asarray([[0, 0, 2, 2], [2, 2, 4, 4]], "float32")
        out = np.asarray([[1.0, 0.0], [1.0 / 7, 1.0 / 7]], "float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": out}
        self.check_output(atol=1e-5)


class TestBipartiteMatch(OpTest):
    op_type = "bipartite_match"

    def test(self):
        dist = np.asarray([[0.1, 0.9, 0.3],
                           [0.8, 0.2, 0.7]], "float32")
        self.inputs = {"DistMat": dist}
        # greedy: best overall is (0,1,.9) -> col1=row0; next best among
        # remaining rows/cols: (1,0,.8) -> col0=row1; rows exhausted
        self.outputs = {
            "ColToRowMatchIndices": np.asarray([[1, 0, -1]], "int32"),
            "ColToRowMatchDis": np.asarray([[0.8, 0.9, 0.0]], "float32")}
        self.check_output()


def test_detection_output():
    n_prior, num_classes = 2, 3
    loc = np.zeros((1, n_prior * 4), "float32")  # no offset: keep priors
    conf = np.zeros((1, n_prior * num_classes), "float32")
    conf[0, 0 * num_classes + 1] = 4.0   # prior 0 -> class 1 confident
    conf[0, 1 * num_classes + 2] = 4.0   # prior 1 -> class 2 confident
    priors = np.asarray([[0.1, 0.1, 0.4, 0.4],
                         [0.5, 0.5, 0.9, 0.9],
                         [0.1, 0.1, 0.2, 0.2],
                         [0.1, 0.1, 0.2, 0.2]], "float32")
    out, = _run_op(
        "detection_output",
        {"Loc": [("loc", loc, 0)], "Conf": [("conf", conf, 0)],
         "PriorBox": [("prior", priors, 0)]},
        {"Out": [("out", "float32")]},
        {"num_classes": num_classes, "background_label_id": 0,
         "nms_threshold": 0.45, "confidence_threshold": 0.3,
         "top_k": 10, "nms_top_k": 10})
    out = np.asarray(out)
    assert out.shape == (2, 7)
    # both detections kept, sorted by score; boxes equal the priors
    labels = sorted(out[:, 1].tolist())
    assert labels == [1.0, 2.0]
    for row in out:
        prior_idx = 0 if row[1] == 1.0 else 1
        np.testing.assert_allclose(row[3:], priors[prior_idx], atol=1e-5)


def test_positive_negative_pair():
    score = np.asarray([[0.8], [0.2], [0.5], [0.6]], "float32")
    label = np.asarray([[1.0], [0.0], [1.0], [0.0]], "float32")
    query = np.asarray([[1], [1], [2], [2]], "int64")
    pos, neg, neu = _run_op(
        "positive_negative_pair",
        {"Score": [("s", score, 0)], "Label": [("l", label, 0)],
         "QueryID": [("q", query, 0)]},
        {"PositivePair": [("pp", "float32")],
         "NegativePair": [("np_", "float32")],
         "NeutralPair": [("nu", "float32")]},
        {"column": 0})
    # q1: (0.8,1) vs (0.2,0) correct -> pos; q2: (0.5,1) vs (0.6,0)
    # wrong -> neg
    assert float(np.asarray(pos)[0]) == 1.0
    assert float(np.asarray(neg)[0]) == 1.0
    assert float(np.asarray(neu)[0]) == 0.0
