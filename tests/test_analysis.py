"""Roofline analysis (fluid/analysis.py): exact FLOP accounting from
the Program IR and report structure."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import analysis


def _conv_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[8, 3, 32, 32],
                                dtype="float32", append_batch_size=False)
        t = fluid.layers.conv2d(input=img, num_filters=16, filter_size=3,
                                padding=1)
        loss = fluid.layers.mean(x=t)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main


def test_conv_flops_exact():
    main = _conv_program()
    costs = {t: f for t, f, _, _ in
             analysis.program_costs(main)}
    # out [8,16,32,32], per-out MACs 3*3*3 -> flops = 2*numel_out*27
    expect = 2 * 8 * 16 * 32 * 32 * 27
    assert costs["conv2d"] == expect
    assert costs["conv2d_grad"] == 2 * expect  # dgrad + wgrad


def test_mul_flops_and_grad():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[64, 128],
                              dtype="float32", append_batch_size=False)
        t = fluid.layers.fc(input=x, size=256)
        loss = fluid.layers.mean(x=t)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    costs = {}
    for t, f, _, _ in analysis.program_costs(main):
        costs[t] = costs.get(t, 0) + f
    assert costs["mul"] == 2 * 64 * 256 * 128
    assert costs["mul_grad"] == 2 * costs["mul"]


def test_bf16_act_halves_activation_bytes_only():
    main = _conv_program()
    by_f32 = {t: b for t, _, b, _ in analysis.program_costs(main)}
    by_bf16 = {t: b for t, _, b, _ in
               analysis.program_costs(main, bf16_act=True)}
    # conv reads/writes big activations: bytes must drop, but not halve
    # exactly (the persistable filter stays 4B)
    assert by_bf16["conv2d"] < by_f32["conv2d"]
    n_act = 8 * 3 * 32 * 32 + 8 * 16 * 32 * 32
    n_w = 16 * 3 * 3 * 3
    assert by_f32["conv2d"] == 4 * (n_act + n_w)
    assert by_bf16["conv2d"] == 2 * n_act + 4 * n_w


def test_report_shape_and_floors():
    main = _conv_program()
    rep = analysis.roofline_report(main, peak_tflops=100, hbm_gbps=500)
    assert rep["floor_ms_ideal"] <= rep["floor_ms_serial"]
    assert rep["total_gflops"] > 0 and rep["total_gbytes"] > 0
    txt = analysis.format_report(rep)
    assert "step floor" in txt and "conv2d" in txt
