"""Aux subsystems: flags from env, eager per-op profiler attribution,
check_nan_inf (reference: utils/Flags.cpp, platform/profiler.h,
executor.cc:29 FLAGS_check_nan_inf)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.utils import flags


def _tiny_program():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    h = fluid.layers.fc(input=x, size=3, act="relu")
    out = fluid.layers.mean(x=h)
    return x, out


def test_flags_env_bootstrap(monkeypatch):
    monkeypatch.setenv("FLAGS_check_nan_inf", "true")
    flags.parse_flags_from_env()
    assert flags.get_flag("check_nan_inf") is True
    flags.set_flag("check_nan_inf", False)
    assert flags.get_flag("check_nan_inf") is False


def test_eager_profiler_per_op_table(capsys):
    x, out = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": np.ones((2, 4), np.float32)}
    with fluid.profiler.profiler(sorted_key="calls"):
        exe.run(fluid.default_main_program(), feed=feed,
                fetch_list=[out], eager=True)
    printed = capsys.readouterr().out
    # per-op rows appear (mul/elementwise_add from fc, relu, mean)
    assert "Event" in printed
    records = fluid.profiler.get_profile_records()
    assert any("mul" in k or "matmul" in k for k in records), records
    assert any("mean" in k for k in records), records


def test_jit_profiler_per_segment_table(capsys):
    """Compiled path: one timed row per XLA segment, with the
    trace/compile call split out from steady-state rows
    (reference ParseEvents analog: platform/profiler.h:133-146)."""
    x, out = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": np.ones((2, 4), np.float32)}
    with fluid.profiler.profiler():
        for _ in range(3):
            exe.run(fluid.default_main_program(), feed=feed,
                    fetch_list=[out])
    printed = capsys.readouterr().out
    assert "jit_segment[" in printed
    records = fluid.profiler.get_profile_records()
    seg_rows = {k: v for k, v in records.items() if "jit_segment" in k}
    assert any(k.endswith("/first(trace)") for k in seg_rows), seg_rows
    steady = [v for k, v in seg_rows.items()
              if not k.endswith("/first(trace)")]
    assert steady and steady[0]["calls"] == 2, seg_rows


def test_check_nan_inf_flag():
    x = fluid.layers.data(name="x", shape=[2], dtype="float32")
    y = fluid.layers.log(x)  # log(-1) -> nan
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    bad = {"x": np.array([[-1.0, 1.0]], np.float32)}
    # without the flag: nan flows through silently
    out, = exe.run(fluid.default_main_program(), feed=bad,
                   fetch_list=[y], eager=True)
    assert np.isnan(np.asarray(out)).any()
    flags.set_flag("check_nan_inf", True)
    try:
        with pytest.raises(FloatingPointError):
            exe.run(fluid.default_main_program(), feed=bad,
                    fetch_list=[y], eager=True)
    finally:
        flags.set_flag("check_nan_inf", False)
