"""v2 layer zoo: export surface + forward/backward checks for the
extended layers (reference: trainer_config_helpers/layers.py ~100
`*_layer` functions + tests/layers_test_config.py build-everything
style)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as paddle
from paddle_tpu.v2 import layer as v2_layer


def _forward(fetches, feeds):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    outs = exe.run(fluid.default_main_program(), feed=feeds,
                   fetch_list=list(fetches))
    return [np.asarray(o) for o in outs]


def test_export_surface():
    """The DSL exports at least 80 layer names and every one resolves
    to a callable (VERDICT round-2 item 3: >= 80)."""
    assert len(v2_layer.__all__) >= 80, len(v2_layer.__all__)
    for n in v2_layer.__all__:
        assert callable(getattr(v2_layer, n)), n
    # the trainer_config_helpers DSL mirrors the reference *_layer names
    from paddle_tpu import trainer_config_helpers as tch

    for ref_name in ["maxout_layer", "spp_layer", "bilinear_interp_layer",
                     "tensor_layer", "conv_projection", "dotmul_operator",
                     "conv_operator", "scaling_projection",
                     "slice_projection", "trans_full_matrix_projection",
                     "nce_layer", "hsigmoid", "multibox_loss_layer",
                     "factorization_machine", "gated_unit_layer"]:
        assert callable(getattr(tch, ref_name)), ref_name


def test_mixed_layer_projection_family():
    """mixed() summing every projection type trains end to end."""
    x = paddle.layer.data(name="x",
                          type=paddle.data_type.dense_vector(8))
    y = paddle.layer.data(name="y",
                          type=paddle.data_type.dense_vector(8))
    out = paddle.layer.mixed(
        size=8,
        input=[
            paddle.layer.full_matrix_projection(input=x, size=8),
            paddle.layer.trans_full_matrix_projection(input=x, size=8),
            paddle.layer.scaling_projection(input=x),
            paddle.layer.slice_projection(input=x,
                                          slices=[(0, 4), (4, 8)]),
            paddle.layer.identity_projection(input=x),
            paddle.layer.dotmul_projection(input=x),
            paddle.layer.dotmul_operator(a=x, b=y),
        ])
    cost = paddle.layer.mse_cost(input=out, label=y)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)

    rs = np.random.RandomState(0)
    feeds = {"x": rs.rand(4, 8).astype(np.float32),
             "y": rs.rand(4, 8).astype(np.float32)}
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = [float(np.asarray(exe.run(
        fluid.default_main_program(), feed=feeds,
        fetch_list=[cost])[0]).reshape(-1)[0]) for _ in range(6)]
    assert losses[-1] < losses[0], losses


def test_slice_projection_values():
    x = paddle.layer.data(name="x",
                          type=paddle.data_type.dense_vector(6))
    out = paddle.layer.mixed(input=[
        paddle.layer.slice_projection(input=x, slices=[(1, 3), (5, 6)])])
    feeds = {"x": np.arange(12, dtype=np.float32).reshape(2, 6)}
    got, = _forward([out], feeds)
    np.testing.assert_allclose(got, [[1, 2, 5], [7, 8, 11]])


def test_slice_projection_rejects_bad_ranges():
    x = paddle.layer.data(name="x",
                          type=paddle.data_type.dense_vector(6))
    with pytest.raises(ValueError):
        paddle.layer.slice_projection(input=x, slices=[(4, 9)])


def test_conv_projection_and_operator():
    img = paddle.layer.data(
        name="img", type=paddle.data_type.dense_vector(3 * 8 * 8))
    img4 = fluid.layers.reshape(x=img, shape=[-1, 3, 8, 8])
    filt = paddle.layer.data(
        name="filt", type=paddle.data_type.dense_vector(2 * 3 * 3 * 3))
    proj_out = paddle.layer.mixed(input=[
        paddle.layer.conv_projection(input=img4, filter_size=3,
                                     num_filters=2, padding=1)])
    op_out = paddle.layer.mixed(input=[
        paddle.layer.conv_operator(img=img4, filter=filt, filter_size=3,
                                   num_filters=2, padding=1)])
    rs = np.random.RandomState(0)
    feeds = {"img": rs.rand(2, 3 * 8 * 8).astype(np.float32),
             "filt": rs.rand(2, 2 * 3 * 3 * 3).astype(np.float32)[:1]
             .repeat(2, 0)}
    a, b = _forward([proj_out, op_out], feeds)
    assert a.shape == (2, 2, 8, 8) and b.shape == (2, 2, 8, 8)
    assert np.isfinite(a).all() and np.isfinite(b).all()


def test_elementwise_zoo_forward():
    x = paddle.layer.data(name="x",
                          type=paddle.data_type.dense_vector(8))
    y = paddle.layer.data(name="y",
                          type=paddle.data_type.dense_vector(8))
    w = paddle.layer.data(name="w",
                          type=paddle.data_type.dense_vector(1))
    fetches = [
        paddle.layer.interpolation(input=[x, y], weight=w),
        paddle.layer.power(input=x, weight=w),
        paddle.layer.sum_to_one_norm(input=x),
        paddle.layer.row_l2_norm(input=x),
        paddle.layer.dot_prod(a=x, b=y),
        paddle.layer.l2_distance(a=x, b=y),
        paddle.layer.clip(input=x, min=0.2, max=0.8),
        paddle.layer.scale_shift(input=x),
        paddle.layer.repeat(input=x, num_repeats=2),
        paddle.layer.resize(input=x, size=4),
        paddle.layer.out_prod(a=x, b=y),
        paddle.layer.factorization_machine(input=x, factor_size=3),
        paddle.layer.gated_unit(input=x, size=5),
        paddle.layer.tensor(a=x, b=y, size=3),
        paddle.layer.selective_fc(input=x, size=6),
    ]
    rs = np.random.RandomState(1)
    feeds = {"x": rs.rand(4, 8).astype(np.float32) + 0.1,
             "y": rs.rand(4, 8).astype(np.float32) + 0.1,
             "w": rs.rand(4, 1).astype(np.float32)}
    outs = _forward(fetches, feeds)
    shapes = [o.shape for o in outs]
    assert shapes[0] == (4, 8)            # interpolation
    assert shapes[4] == (4, 1)            # dot_prod
    assert shapes[5] == (4, 1)            # l2_distance
    assert shapes[8] == (4, 16)           # repeat
    assert shapes[9] == (8, 4)            # resize
    assert shapes[10] == (4, 64)          # out_prod (flattened, as ref)
    assert shapes[13] == (4, 3)           # tensor
    for o in outs:
        assert np.isfinite(o).all()
    # clip actually clips
    assert outs[6].min() >= 0.2 and outs[6].max() <= 0.8


def test_image_zoo_forward():
    img = paddle.layer.data(
        name="img", type=paddle.data_type.dense_vector(4 * 8 * 8))
    x = fluid.layers.reshape(x=img, shape=[-1, 4, 8, 8])
    fetches = [
        paddle.layer.maxout(input=x, groups=2),
        paddle.layer.spp(input=x, pyramid_height=2),
        paddle.layer.img_cmrnorm(input=x, size=3),
        paddle.layer.pad(input=x, pad_c=(0, 0), pad_h=(1, 1),
                         pad_w=(1, 1)),
        paddle.layer.bilinear_interp(input=x, out_size_x=16,
                                     out_size_y=16),
        paddle.layer.switch_order(input=x),
        paddle.layer.block_expand(input=x, block_x=4, block_y=4,
                                  stride_x=4, stride_y=4),
    ]
    rs = np.random.RandomState(2)
    feeds = {"img": rs.rand(2, 4 * 8 * 8).astype(np.float32)}
    outs = _forward(fetches, feeds)
    assert outs[0].shape == (2, 2, 8, 8)     # maxout: c/groups
    assert outs[1].shape[0] == 2             # spp flattens
    assert outs[2].shape == (2, 4, 8, 8)     # lrn
    assert outs[3].shape == (2, 4, 10, 10)   # pad
    assert outs[4].shape == (2, 4, 16, 16)   # bilinear
    assert outs[5].shape == (2, 8, 8, 4)     # NCHW->NHWC
    for o in outs:
        assert np.isfinite(np.asarray(o, dtype=object).astype(
            np.float32)).all() if o.dtype != object else True


def test_cost_zoo():
    left = paddle.layer.data(name="l",
                             type=paddle.data_type.dense_vector(1))
    right = paddle.layer.data(name="r",
                              type=paddle.data_type.dense_vector(1))
    lbl = paddle.layer.data(name="lab",
                            type=paddle.data_type.dense_vector(1))
    x = paddle.layer.data(name="x",
                          type=paddle.data_type.dense_vector(4))
    multi_lbl = paddle.layer.data(
        name="mlab", type=paddle.data_type.dense_vector(4))
    fetches = [
        paddle.layer.rank_cost(left=left, right=right, label=lbl),
        paddle.layer.huber_regression_cost(input=left, label=lbl),
        paddle.layer.huber_classification_cost(input=left, label=lbl),
        paddle.layer.smooth_l1_cost(input=x, label=multi_lbl),
        paddle.layer.multi_binary_label_cross_entropy(
            input=x, label=multi_lbl),
    ]
    rs = np.random.RandomState(3)
    sig = 1 / (1 + np.exp(-rs.randn(4, 4).astype(np.float32)))
    feeds = {"l": rs.rand(4, 1).astype(np.float32),
             "r": rs.rand(4, 1).astype(np.float32),
             "lab": (rs.rand(4, 1) > 0.5).astype(np.float32),
             "x": sig,
             "mlab": (rs.rand(4, 4) > 0.5).astype(np.float32)}
    outs = _forward(fetches, feeds)
    for o in outs:
        assert o.size == 1 and np.isfinite(o).all(), o


def test_multibox_loss_bipartite_guarantee():
    """A gt box whose best prior IoU is below the threshold must still
    produce a positive match (reference MultiBoxLossLayer.cpp matches
    every gt to its best prior unconditionally first)."""
    import jax.numpy as jnp
    from paddle_tpu.ops.registry import get_op_info
    from paddle_tpu.core.ragged import RaggedTensor

    P, C = 2, 2
    pboxes = np.array([[0.0, 0.0, 0.2, 0.2], [0.8, 0.8, 1.0, 1.0]],
                      np.float32)
    prior = np.concatenate([pboxes, np.full((P, 4), 0.1, np.float32)])
    # one tiny gt barely overlapping prior 0: IoU << 0.5
    gt = RaggedTensor(jnp.asarray([[0.15, 0.15, 0.5, 0.5]], jnp.float32),
                      [jnp.asarray([0, 1], jnp.int32)])
    lab = RaggedTensor(jnp.asarray([[1]], jnp.int32),
                       [jnp.asarray([0, 1], jnp.int32)])
    kernel = get_op_info("multibox_loss").kernel
    out = kernel(None, {
        "Loc": [jnp.zeros((1, P * 4))], "Conf": [jnp.zeros((1, P * C))],
        "PriorBox": [jnp.asarray(prior)], "GtBox": [gt],
        "GtLabel": [lab]}, {"num_classes": C})
    loss = float(np.asarray(out["Loss"][0]).reshape(-1)[0])
    assert loss > 0.0, loss  # the object is learned, not dropped


def test_multibox_loss_trains():
    """SSD loss: loc/conf heads + priors + ragged gt, loss decreases
    under SGD (reference: MultiBoxLossLayer.cpp semantics)."""
    P, C = 6, 3
    feat = fluid.layers.data(name="feat", shape=[16], dtype="float32")
    loc = fluid.layers.fc(input=feat, size=P * 4)
    conf = fluid.layers.fc(input=feat, size=P * C)
    prior = fluid.layers.data(name="prior", shape=[2 * P, 4],
                              dtype="float32",
                              append_batch_size=False)
    gt_box = fluid.layers.data(name="gt_box", shape=[4],
                               dtype="float32", lod_level=1)
    gt_lab = fluid.layers.data(name="gt_lab", shape=[1],
                               dtype="int64", lod_level=1)
    cost = paddle.layer.multibox_loss(
        input_loc=loc, input_conf=conf, priorbox=prior, label=gt_lab,
        gt_box=gt_box, num_classes=C)
    fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)

    pboxes = np.array(
        [[0.0, 0.0, 0.4, 0.4], [0.3, 0.3, 0.7, 0.7],
         [0.6, 0.6, 1.0, 1.0], [0.0, 0.5, 0.5, 1.0],
         [0.5, 0.0, 1.0, 0.5], [0.2, 0.2, 0.8, 0.8]], np.float32)
    prior_np = np.concatenate([pboxes, np.full((P, 4), 0.1,
                                               np.float32)], 0)
    rs = np.random.RandomState(0)
    place = fluid.CPUPlace()
    feeder = fluid.DataFeeder(feed_list=[feat, gt_box, gt_lab],
                              place=place)
    samples = [
        (rs.rand(16).astype(np.float32),
         [[0.05, 0.05, 0.35, 0.35], [0.55, 0.55, 0.95, 0.95]],
         [[1], [2]]),
        (rs.rand(16).astype(np.float32),
         [[0.25, 0.25, 0.75, 0.75]],
         [[1]]),
    ]
    feeds = feeder.feed(samples)
    feeds["prior"] = prior_np
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    losses = [float(np.asarray(exe.run(
        fluid.default_main_program(), feed=feeds,
        fetch_list=[cost])[0]).reshape(-1)[0]) for _ in range(8)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses
