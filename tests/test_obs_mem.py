"""paddle_tpu.obs.mem: static memory timeline vs XLA actuals, the
donation audit, OOM pre-flight/post-mortems, gauge retirement, and
the memory regression gate (PR 15)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.scope import Scope
from paddle_tpu.obs import flight as obs_flight
from paddle_tpu.obs import health as obs_health
from paddle_tpu.obs import mem as obs_mem
from paddle_tpu.obs import perf as obs_perf
from paddle_tpu.obs import registry as obs_registry
from paddle_tpu.utils import flags as pt_flags

# the pinned static-vs-XLA factor for the golden fixtures: the static
# liveness walk and XLA's buffer assignment must stay within 4x of
# each other on CPU (measured: lenet5 1.65, mlp 2.41 — XLA's temp
# arena holds fusion scratch the IR walk can't see, and the walk
# counts logical bytes, not padded layouts)
PINNED_FACTOR = 4.0


def _build_lenet5(batch=8):
    from paddle_tpu import models as zoo

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        image = fluid.layers.data(
            name="image", shape=[batch, 1, 28, 28], dtype="float32",
            append_batch_size=False)
        logits = zoo.lenet5(image, class_dim=10)
        label = fluid.layers.data(
            name="label", shape=[batch, 1], dtype="int64",
            append_batch_size=False)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.01, momentum=0.9).minimize(loss)
    feeds = {"image": np.random.RandomState(0)
             .rand(batch, 1, 28, 28).astype("float32"),
             "label": np.random.RandomState(1)
             .randint(0, 10, (batch, 1)).astype("int64")}
    return main, startup, loss, feeds


def _build_mlp(batch=16):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[batch, 256],
                              dtype="float32",
                              append_batch_size=False)
        h = fluid.layers.fc(input=x, size=512, act="relu")
        h = fluid.layers.fc(input=h, size=256, act="relu")
        y = fluid.layers.fc(input=h, size=10)
        label = fluid.layers.data(name="label", shape=[batch, 1],
                                  dtype="int64",
                                  append_batch_size=False)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(y, label))
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.01, momentum=0.9).minimize(loss)
    feeds = {"x": np.random.RandomState(0)
             .rand(batch, 256).astype("float32"),
             "label": np.random.RandomState(1)
             .randint(0, 10, (batch, 1)).astype("int64")}
    return main, startup, loss, feeds


def _build_adam_toy():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[64], dtype="float32")
        h = fluid.layers.fc(input=x, size=32)
        cost = fluid.layers.mean(x=h)
        fluid.optimizer.AdamOptimizer(
            learning_rate=0.01).minimize(cost)
    return main, startup, cost


def _run_captured(main, startup, loss, feeds):
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        with obs_health.force_attribution():
            exe.run(main, feed=feeds, fetch_list=[loss], scope=scope)
    return exe, scope


# ---------------------------------------------------------------------------
# static timeline
# ---------------------------------------------------------------------------

def test_timeline_matches_peak_walk():
    """liveness_peak_bytes is the timeline's peak — one shared walk."""
    from paddle_tpu.analysis.dataflow import (liveness_peak_bytes,
                                              liveness_timeline)

    main, _startup, loss, _ = _build_lenet5()
    bd = main.desc.block(0)
    final = {n for n, vd in bd.vars.items() if vd.persistable}
    final.add(loss.name)

    def nbytes(name):
        vd = bd.vars.get(name)
        if vd is None or vd.persistable or vd.shape is None:
            return 0
        return int(np.prod([max(s, 1) for s in vd.shape])) * 4

    tl = liveness_timeline(bd.ops, nbytes, final, top_n=4)
    peak, peak_op = liveness_peak_bytes(bd.ops, nbytes, final)
    assert tl["peak_bytes"] == peak and tl["peak_op"] == peak_op
    assert len(tl["series"]) == len(bd.ops)
    assert max(tl["series"]) == peak
    # blamed buffers: sorted largest-first, all live at the peak, each
    # with a defining op at or before the peak
    sizes = [b["bytes"] for b in tl["top_buffers"]]
    assert sizes == sorted(sizes, reverse=True) and sizes[0] > 0
    for b in tl["top_buffers"]:
        assert b["def_op"] is None or b["def_op"] <= peak_op


def test_program_timeline_and_render():
    main, _startup, loss, _ = _build_lenet5()
    tl = obs_mem.program_timeline(main, fetches=[loss.name], top_n=5)
    assert tl["ops"] == len(main.desc.block(0).ops)
    assert tl["peak_bytes"] > 0 and tl["params_bytes"] > 0
    assert tl["total_peak_bytes"] == \
        tl["peak_bytes"] + tl["params_bytes"]
    assert tl["peak_op_type"] == tl["op_types"][tl["peak_op"]]
    text = obs_mem.render_timeline(tl)
    assert "<- peak" in text
    assert tl["top_buffers"][0]["name"] in text


def test_timeline_chrome_trace_counter_track(tmp_path):
    from paddle_tpu.tools.obs_dump import validate_chrome_trace

    main, _startup, loss, _ = _build_lenet5()
    tl = obs_mem.program_timeline(main, fetches=[loss.name])
    path = str(tmp_path / "mem_trace.json")
    obs_mem.timeline_chrome_trace(tl, path=path)
    events = validate_chrome_trace(path)
    counters = [ev for ev in events if ev["ph"] == "C"]
    assert len(counters) == tl["ops"]
    assert max(ev["args"]["live_bytes"] for ev in counters) \
        == tl["peak_bytes"]


# ---------------------------------------------------------------------------
# golden fixtures: static estimate vs XLA actuals (CPU backend)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("build", [_build_lenet5, _build_mlp],
                         ids=["lenet5", "mlp"])
def test_static_peak_within_pinned_factor_of_xla(build):
    main, startup, loss, feeds = build()
    _run_captured(main, startup, loss, feeds)
    rep = obs_mem.drift_report()
    joined = [r for r in rep["segments"] if r["ratio"]]
    assert joined, "executor registered no drift-joinable segments"
    for row in joined:
        assert 1.0 / PINNED_FACTOR <= row["ratio"] <= PINNED_FACTOR, \
            "segment %s: static %d vs xla %d (ratio %.3f) outside " \
            "the pinned %gx factor" % (
                row["segment"], row["static_peak_bytes"],
                row["xla_program_bytes"], row["ratio"], PINNED_FACTOR)
    # the join also published the ratio gauge per segment
    snap = {k: v for k, v in
            __import__("paddle_tpu.obs.telemetry",
                       fromlist=["snapshot"]).snapshot().items()
            if k.startswith("mem_estimate_ratio{")}
    assert snap, "mem_estimate_ratio gauge never published"


def test_store_dump_load_roundtrip(tmp_path):
    main, startup, loss, feeds = _build_lenet5()
    _run_captured(main, startup, loss, feeds)
    path = str(tmp_path / "store.json")
    obs_mem.dump_store(path)
    offline = obs_mem.drift_report(obs_mem.load_store(path))
    live = obs_mem.drift_report()
    assert offline["n"] == live["n"] > 0
    assert offline["median_ratio"] == live["median_ratio"]
    with pytest.raises(ValueError):
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as f:
            json.dump({"kind": "other"}, f)
        obs_mem.load_store(bad)


def test_calibration_blob_feeds_ptune(tmp_path):
    from paddle_tpu.tune.fit import load_hbm_calibration

    main, startup, loss, feeds = _build_lenet5()
    _run_captured(main, startup, loss, feeds)
    rep = obs_mem.drift_report()
    blob = obs_mem.calibration_blob(rep, model="lenet5")
    assert blob["kind"] == obs_mem.MEM_CALIBRATION_KIND
    path = str(tmp_path / "cal.json")
    obs_mem.save_calibration(blob, path)
    ratio = load_hbm_calibration(path)
    assert ratio == rep["median_ratio"] > 0
    # wrong kind / unusable ratio must raise, never silently widen
    with pytest.raises(ValueError):
        bad = str(tmp_path / "notcal.json")
        with open(bad, "w") as f:
            json.dump({"kind": "something"}, f)
        load_hbm_calibration(bad)


def test_rank_applies_hbm_ratio():
    """A measured ratio scales the static peak before the S005 budget
    check: a budget the analytic peak fits busts under ratio 10."""
    from paddle_tpu.tune import models as tune_models
    from paddle_tpu.tune import rank as tune_rank
    from paddle_tpu.tune.space import SearchSpace

    builder = tune_models.builder("lenet5")
    cands = SearchSpace(1, meshes=["dp=1"], pipelines=["none"],
                        batches=[8], micro_batches=[1]).points()
    analytic = tune_rank.rank(builder, cands, 1, model="lenet5",
                              hbm_gb=1.0, bf16_act=False)
    assert analytic.ranked and not analytic.rejected
    budget_gb = (analytic.ranked[0].peak_hbm_bytes * 3) / 2 ** 30
    calibrated = tune_rank.rank(builder, cands, 1, model="lenet5",
                                hbm_gb=budget_gb, bf16_act=False,
                                hbm_ratio=10.0)
    assert not calibrated.ranked and calibrated.rejected
    rej = calibrated.rejected[0]
    assert rej.code == "S005" and "calibration" in rej.message


# ---------------------------------------------------------------------------
# donation audit
# ---------------------------------------------------------------------------

def test_donation_audit_clean_program():
    main, _startup, cost = _build_adam_toy()
    audit = obs_mem.audit_donation(main, fetches=[cost.name])
    assert audit["donated"] and audit["donated_bytes"] > 0
    assert not audit["reclaimable"]
    donated_names = {d["name"] for d in audit["donated"]}
    # the param and both Adam moments advance in place -> donated
    assert any(n.endswith("moment1_0") for n in donated_names)
    assert any(d["kind"] == "param" for d in audit["donated"])


def test_donation_audit_finds_forked_adam_slot():
    from paddle_tpu.core.desc import VarDesc

    main, _startup, cost = _build_adam_toy()
    bd = main.desc.block(0)
    forked = None
    for od in bd.ops:
        if od.type == "adam":
            forked = od.input("Moment1")[0]
            src = bd.vars[forked]
            fork = forked + "__fork"
            bd.vars[fork] = VarDesc(fork, src.type, src.dtype,
                                    src.shape, persistable=True)
            od.outputs["Moment1Out"] = [fork]
            break
    assert forked
    audit = obs_mem.audit_donation(main, fetches=[cost.name])
    hits = [r for r in audit["reclaimable"] if r["name"] == forked]
    assert hits and hits[0]["bytes"] > 0
    assert hits[0]["kind"] == "optimizer_state"
    assert "forks" in hits[0]["reason"]
    assert audit["reclaimable_bytes"] >= hits[0]["bytes"]
    text = obs_mem.render_audit(audit)
    assert "RECLAIM" in text and forked in text


def test_donation_audit_dropped_alias():
    """A declared in-place out slot missing from the op strands the
    input buffer — the 'dropped alias' class."""
    main, _startup, cost = _build_adam_toy()
    bd = main.desc.block(0)
    name = None
    for od in bd.ops:
        if od.type == "adam":
            name = od.input("Moment2")[0]
            del od.outputs["Moment2Out"]
            break
    audit = obs_mem.audit_donation(main, fetches=[cost.name])
    hits = [r for r in audit["reclaimable"] if r["name"] == name]
    assert hits and "absent" in hits[0]["reason"]


# ---------------------------------------------------------------------------
# OOM pre-flight + post-mortem
# ---------------------------------------------------------------------------

def test_oom_context_is_empty_for_non_oom():
    assert obs_mem.oom_context(ValueError("boom")) == {}
    assert obs_mem.is_oom(MemoryError("x"))
    assert obs_mem.is_oom(RuntimeError("RESOURCE_EXHAUSTED: hbm"))
    assert not obs_mem.is_oom(RuntimeError("shape mismatch"))


def test_preflight_budget_and_flight_bundle(tmp_path):
    main, startup, loss, feeds = _build_lenet5()
    tl = obs_mem.program_timeline(main, fetches=[loss.name], top_n=8)
    recorder = obs_flight.install(out_dir=str(tmp_path), capacity=8)
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    prev = pt_flags.get_flag("mem_budget_gb")
    try:
        with fluid.scope_guard(scope):
            exe.run(startup, scope=scope)
            pt_flags.set_flag("mem_budget_gb", 1e-6)
            with pytest.raises(obs_mem.MemoryBudgetError) as ei:
                exe.run(main, feed=feeds, fetch_list=[loss],
                        scope=scope, use_program_cache=False)
        assert "RESOURCE_EXHAUSTED" in str(ei.value)
        assert ei.value.timeline is not None
        # a budget the program fits compiles fine
        pt_flags.set_flag("mem_budget_gb", 16.0)
        with fluid.scope_guard(scope):
            exe.run(main, feed=feeds, fetch_list=[loss], scope=scope)
    finally:
        pt_flags.set_flag("mem_budget_gb", prev)
        obs_flight.uninstall()
    bundle = recorder.last_bundle_path
    assert bundle and os.path.exists(bundle)
    with open(bundle) as f:
        doc = json.load(f)
    ooms = [n["oom"] for n in doc["notes"] if n.get("oom")]
    assert ooms, "flight bundle carries no oom note"
    # the bundle's top blamed buffer IS the static timeline's peak
    # resident (the acceptance contract)
    assert ooms[0]["top_buffers"][0]["name"] == \
        tl["top_buffers"][0]["name"]
    from paddle_tpu.tools.obs_dump import render_flight

    rendered = render_flight(bundle)
    assert "OOM post-mortem" in rendered
    assert tl["top_buffers"][0]["name"] in rendered


# ---------------------------------------------------------------------------
# gauge retirement on program-cache eviction (satellite fix)
# ---------------------------------------------------------------------------

def _segment_gauge_labels(name):
    fam = obs_registry.get_registry().gauge(name,
                                            labelnames=("segment",))
    return {dict(s.get("labels", {})).get("segment")
            for s in fam.samples()}


def test_segment_gauges_retired_on_eviction():
    main, startup, loss, feeds = _build_lenet5()
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe._CACHE_MAX = 1  # instance override: evict on the 2nd program
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        with obs_health.force_attribution():
            exe.run(main, feed=feeds, fetch_list=[loss], scope=scope)
    assert _segment_gauge_labels("mem_static_peak_bytes"), \
        "attribution run published no mem gauges"
    labels_before = _segment_gauge_labels("xla_temp_bytes")
    assert labels_before
    # a second distinct program evicts the first from the LRU
    main2, startup2, loss2, feeds2 = _build_mlp()
    scope2 = Scope()
    with fluid.scope_guard(scope2):
        exe.run(startup2, scope=scope2)
        exe.run(main2, feed=feeds2, fetch_list=[loss2], scope=scope2)
    # the lenet5 program's segment labels are gone from every
    # per-segment family (xla_* and mem_*), not frozen forever
    lenet_labels = {l for l in labels_before if "conv2d" in (l or "")}
    assert lenet_labels
    for fam in ("xla_temp_bytes", "xla_argument_bytes",
                "mem_static_peak_bytes", "mem_xla_program_bytes",
                "mem_estimate_ratio"):
        assert not (_segment_gauge_labels(fam) & lenet_labels), \
            "evicted segment labels still render in %s" % fam
    assert not (set(obs_mem.segments()) & lenet_labels)


def test_eviction_keeps_labels_shared_with_live_program():
    """Labels are shape-independent: evicting one of two structurally
    identical programs must NOT retire the survivor's gauges (it is
    warm and would never re-publish them)."""
    main_a, startup_a, loss_a, feeds = _build_lenet5()
    main_b, startup_b, loss_b, _ = _build_lenet5()
    init_exe = fluid.Executor(fluid.CPUPlace())  # keeps startups out
    exe = fluid.Executor(fluid.CPUPlace())       # of the tiny cache
    exe._CACHE_MAX = 1
    scope_a, scope_b = Scope(), Scope()
    with fluid.scope_guard(scope_a):
        init_exe.run(startup_a, scope=scope_a)
        with obs_health.force_attribution():
            exe.run(main_a, feed=feeds, fetch_list=[loss_a],
                    scope=scope_a)
    labels = _segment_gauge_labels("mem_static_peak_bytes")
    assert labels
    with fluid.scope_guard(scope_b):
        init_exe.run(startup_b, scope=scope_b)
        # identical structure -> identical labels; inserting B evicts
        # A, but B still owns every label
        exe.run(main_b, feed=feeds, fetch_list=[loss_b],
                scope=scope_b)
    assert _segment_gauge_labels("mem_static_peak_bytes") == labels
    assert _segment_gauge_labels("xla_temp_bytes") >= labels


# ---------------------------------------------------------------------------
# history + regression gate (satellite: bench memory blob)
# ---------------------------------------------------------------------------

def _mem_record(value, peak_bytes, platform="tpu"):
    return {"metric": "resnet50_train_imgs_per_sec_batch128",
            "value": value, "unit": "img/s", "step_ms": 50.0,
            "amp_bf16": True, "platform": platform,
            "memory": {"static_peak_bytes": peak_bytes,
                       "xla_total_bytes": peak_bytes,
                       "estimate_ratio": 1.0}}


def test_normalize_record_forwards_memory():
    norm = obs_perf.normalize_record(_mem_record(2400.0, 1 << 30),
                                     leg="default-b128")
    assert norm["memory"]["xla_total_bytes"] == 1 << 30
    assert norm["memory"]["estimate_ratio"] == 1.0
    # records without the blob normalize without the key
    rec = _mem_record(2400.0, 1 << 30)
    del rec["memory"]
    assert "memory" not in obs_perf.normalize_record(rec)


def test_gate_memory_regression_opt_in():
    base = 1 << 30
    records = [obs_perf.normalize_record(_mem_record(2400.0, base),
                                         ts=i) for i in range(4)]
    # newest run: same throughput, 40% more HBM
    records.append(obs_perf.normalize_record(
        _mem_record(2400.0, int(base * 1.4)), ts=9))
    # memory is OPT-IN: the default gate passes
    assert obs_perf.gate_history(records).ok
    result = obs_perf.gate_history(records, mem_tolerance=0.10)
    assert not result.ok
    assert result.failures[0]["kind"] == "memory"
    assert "peak memory" in result.failures[0]["why"]
    # within tolerance passes
    ok = obs_perf.gate_history(records[:-1], mem_tolerance=0.10)
    assert ok.ok


def test_gate_memory_never_mixes_keys():
    """A candidate that lost its AOT capture (static bytes only) must
    not gate its static peak against an XLA-bytes baseline — the two
    quantities legitimately differ by the pinned factor.  With no
    shared key the memory check is a no-op, not a false verdict."""
    base = 1 << 30
    records = []
    for i in range(4):
        r = obs_perf.normalize_record(_mem_record(2400.0, base), ts=i)
        del r["memory"]["static_peak_bytes"]  # baseline: xla only
        records.append(r)
    cand = obs_perf.normalize_record(
        _mem_record(2400.0, int(base * 0.5)), ts=9)
    del cand["memory"]["xla_total_bytes"]     # candidate: static only
    records.append(cand)
    # static 0.5 GiB vs xla 1.0 GiB would "pass" a real regression if
    # mixed — and a static candidate ABOVE an xla baseline would
    # false-fail; either way the keys must not join
    assert obs_perf.gate_history(records, mem_tolerance=0.10).ok
    cand["memory"]["static_peak_bytes"] = int(base * 2)
    assert obs_perf.gate_history(records, mem_tolerance=0.10).ok
    # once the baseline shares the static key, the same candidate
    # fails on it
    for r in records[:-1]:
        r["memory"]["static_peak_bytes"] = base
    result = obs_perf.gate_history(records, mem_tolerance=0.10)
    assert not result.ok
    assert "static_peak_bytes" in result.failures[0]["why"]


def test_bench_memory_blob_shapes():
    main, _startup, loss, _feeds = _build_lenet5()
    blob = obs_mem.bench_memory_blob(main, fetches=[loss.name])
    assert blob["static_peak_bytes"] == \
        blob["params_bytes"] + blob["activation_peak_bytes"]
    assert "estimate_ratio" not in blob  # no xla capture given
    blob2 = obs_mem.bench_memory_blob(
        main, fetches=[loss.name],
        xla_stats={"xla_temp_bytes": 1000, "xla_argument_bytes": 500,
                   "xla_output_bytes": 100})
    assert blob2["xla_total_bytes"] == 1600
    # actual/static — the SAME direction as mem_estimate_ratio and
    # the calibration blob (1.0 = static model exact)
    assert blob2["estimate_ratio"] == round(
        1600 / blob2["static_peak_bytes"], 4)


# ---------------------------------------------------------------------------
# satellites: S005 blame + serving /healthz memory section
# ---------------------------------------------------------------------------

def test_s005_cites_top_peak_buffers():
    from paddle_tpu import analysis

    main, _startup, loss, _feeds = _build_mlp()
    plan = analysis.analyze_sharding(main, {"dp": 4, "mp": 2},
                                     fetches=[loss.name],
                                     hbm_gb=1e-6, publish=False)
    errs = [d for d in plan.report.errors if d.code == "S005"]
    assert errs
    top = plan.hbm_breakdown["top_buffers"]
    assert top and top[0]["bytes"] > 0
    # the message names WHICH activations to remat, not just totals
    assert "top resident" in errs[0].message
    assert top[0]["name"] in errs[0].message


def test_serving_healthz_memory_section():
    from paddle_tpu.fluid import io as fluid_io
    from paddle_tpu.serving import (EngineConfig, InferenceEngine,
                                    InferenceServer, ServerConfig)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[8],
                                dtype="float32")
        probs = fluid.layers.fc(input=img, size=3, act="softmax")
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    program = fluid_io.prune_program(main, [probs])
    engine = InferenceEngine(program, ["img"], [probs], scope=scope,
                             config=EngineConfig(batch_buckets=[2, 4]))
    server = InferenceServer(engine, ServerConfig(port=0,
                                                  warmup=False))
    engine.warmup()
    body = server.health_signals()
    # CPU exposes no allocator stats, but warmup captured per-bucket
    # XLA bytes through the attribution artifacts
    assert "memory" in body, body
    buckets = body["memory"]["bucket_xla_bytes"]
    assert set(buckets) == {"2", "4"}
    assert all(v >= 0 for v in buckets.values())
    snap = {k: v for k, v in
            __import__("paddle_tpu.obs.telemetry",
                       fromlist=["snapshot"]).snapshot().items()
            if k.startswith("mem_bucket_xla_bytes{")}
    assert len(snap) == 2
