"""paddle_tpu.compile.opt_passes — the cost-model-guided optimization
passes (layout / fuse / auto_remat) on the PassManager.

The load-bearing contracts:
  * every golden-fixture topology optimized through
    "default+layout+fuse+auto_remat" (and the forced-knob variant)
    keeps the verifier green and its fetches numerically equal —
    bit-identical in f32, tolerance-equal under amp_bf16;
  * pipeline ids are distinct per pass AND per knob setting, so
    pcache entries can never alias across configs;
  * the layout pass accepts/declines off the TPU-tiled roofline, and
    the layout/fuse-optimized ResNet-50 b256 program carries a
    strictly lower max(MXU, HBM) floor than the unoptimized one;
  * a deliberately-broken rewrite is rejected by the verifier before
    the desc can reach XLA.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.compile import opt_passes, passes
from paddle_tpu.core.ragged import RaggedTensor
from paddle_tpu.core.scope import Scope
from paddle_tpu.fluid import executor as executor_mod
from paddle_tpu.fluid.fusion import FUSED_ELEMWISE_OP
from paddle_tpu.utils import flags


@pytest.fixture(autouse=True)
def _reset_flags():
    yield
    flags.set_flag("compile_passes", "")
    fluid.amp.disable_bf16()


# ---------------------------------------------------------------------------
# golden-fixture builders (the canonical topologies the golden-IR tests
# pin) + concrete feeds so both the plain and the optimized program run
# ---------------------------------------------------------------------------

def _build_fit_a_line():
    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(input=x, size=1)
    loss = fluid.layers.mean(
        x=fluid.layers.square_error_cost(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    rs = np.random.RandomState(0)
    feed = {"x": rs.rand(4, 13).astype(np.float32),
            "y": rs.rand(4, 1).astype(np.float32)}
    return loss.name, feed


def _build_conv_classifier():
    img = fluid.layers.data(name="img", shape=[1, 28, 28],
                            dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    conv = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                               act="relu")
    pool = fluid.layers.pool2d(input=conv, pool_size=2, pool_stride=2)
    logits = fluid.layers.fc(input=pool, size=10, act="softmax")
    loss = fluid.layers.mean(
        x=fluid.layers.cross_entropy(input=logits, label=label))
    fluid.optimizer.MomentumOptimizer(learning_rate=0.01,
                                      momentum=0.9).minimize(loss)
    rs = np.random.RandomState(0)
    feed = {"img": rs.rand(4, 1, 28, 28).astype(np.float32),
            "label": rs.randint(0, 10, size=(4, 1)).astype(np.int64)}
    return loss.name, feed


def _build_dynamic_rnn():
    x = fluid.layers.data(name="x", shape=[8], dtype="float32",
                          lod_level=1)
    drnn = fluid.layers.DynamicRNN()
    with drnn.block():
        step = drnn.step_input(x)
        mem = drnn.memory(shape=[8], batch_ref=step, value=0.0)
        h = fluid.layers.fc(input=[step, mem], size=8, act="tanh")
        drnn.update_memory(mem, h)
        drnn.output(h)
    last = fluid.layers.sequence_last_step(input=drnn())
    loss = fluid.layers.mean(x=last)
    fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    rs = np.random.RandomState(0)
    seqs = [rs.rand(n, 8).astype(np.float32) for n in (3, 5)]
    return loss.name, {"x": RaggedTensor.from_sequences(seqs)}


def _build_deepfm():
    from paddle_tpu.models.ctr import deepfm_ctr

    ids = fluid.layers.data(name="ids", shape=[4], dtype="int64")
    label = fluid.layers.data(name="label", shape=[1], dtype="float32")
    avg_loss, _ = deepfm_ctr(ids, label, num_features=64, num_fields=4,
                             embed_dim=4, hidden_sizes=(8,))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_loss)
    rs = np.random.RandomState(0)
    feed = {"ids": rs.randint(0, 64, size=(4, 4)).astype(np.int64),
            "label": rs.randint(0, 2, size=(4, 1)).astype(np.float32)}
    return avg_loss.name, feed


def _build_transformer():
    from paddle_tpu.models.transformer_program import (
        build_transformer_program, transformer_program_feeds)

    main, startup, avg_loss, _ = build_transformer_program(
        2, 8, 32, n_layer=1, n_head=2, d_model=16)
    with fluid.program_guard(main, startup):
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.01, momentum=0.9).minimize(avg_loss)
    from paddle_tpu.fluid import framework

    framework.switch_main_program(main)
    framework.switch_startup_program(startup)
    return avg_loss.name, transformer_program_feeds(2, 8, 32)


GOLDEN_BUILDERS = {
    "fit_a_line": _build_fit_a_line,
    "conv_classifier": _build_conv_classifier,
    "dynamic_rnn": _build_dynamic_rnn,
    "deepfm": _build_deepfm,
    "transformer": _build_transformer,
}

# the acceptance pipeline, plus a variant that FORCES every opt pass to
# fire (layout ignores the cost gate, auto_remat's budget is 0) with
# non-default knobs so the knob plumbing is numerically covered too
PIPELINES = [
    "default+layout+fuse+auto_remat",
    "default+layout:force=1+fuse:cap=2+auto_remat:stride=2:budget_gb=0",
]


def _snap_scope(scope):
    """Deep-copy snapshot: the executor donates param buffers on the
    in-place update path, so shared arrays would be deleted by the
    first run."""
    import jax

    s = Scope()
    for n in scope.local_var_names():
        v = scope.get(n)
        if isinstance(v, jax.Array):
            v = jax.device_put(np.asarray(v))
        s.set_local(n, v)
    return s


def _run_both(main, opt, fetch, feed):
    """Run plain and optimized from IDENTICAL initial params (one
    startup run, snapshotted per program)."""
    startup = fluid.default_startup_program()
    exe = executor_mod.Executor(executor_mod.CPUPlace())
    base = Scope()
    with executor_mod.scope_guard(base):
        exe.run(startup)
    outs = []
    for prog in (main, opt):
        with executor_mod.scope_guard(_snap_scope(base)):
            outs.append(np.asarray(
                exe.run(prog, feed=feed, fetch_list=[fetch])[0]))
    return outs


class TestGoldenFixtureNumerics:
    @pytest.mark.parametrize("pipeline", PIPELINES)
    @pytest.mark.parametrize("case", sorted(GOLDEN_BUILDERS))
    def test_fetches_bit_identical_f32(self, case, pipeline):
        fetch, feed = GOLDEN_BUILDERS[case]()
        main = fluid.default_main_program()
        pm = passes.PassManager(pipeline, verify_level="full")
        opt = pm.run(main, fetches=[fetch])
        plain, optimized = _run_both(main, opt, fetch, feed)
        np.testing.assert_array_equal(plain, optimized)

    def test_amp_bf16_tolerance_equal(self):
        fluid.amp.enable_bf16()
        fetch, feed = _build_conv_classifier()
        main = fluid.default_main_program()
        pm = passes.PassManager(PIPELINES[1], verify_level="structural")
        opt = pm.run(main, fetches=[fetch])
        plain, optimized = _run_both(main, opt, fetch, feed)
        np.testing.assert_allclose(plain, optimized, rtol=5e-2,
                                   atol=5e-2)

    def test_forced_pipeline_actually_rewrites(self):
        # budget_gb=0 forces remat on the training fixture — the
        # acceptance spec must not green-light a no-op pipeline
        fetch, _ = _build_conv_classifier()
        pm = passes.PassManager(PIPELINES[1])
        pm.run(fluid.default_main_program(), fetches=[fetch])
        changed = {r["pass"]: r["changed"] for r in pm.records}
        assert changed["auto_remat:budget_gb=0.0:stride=2"], pm.records


class TestSpecGrammar:
    def test_plus_separator_equals_comma(self):
        a = passes.PassManager("default+layout+fuse")
        b = passes.PassManager("dce,fold,cse,dve,layout,fuse")
        assert a.pipeline_id == b.pipeline_id

    def test_pipeline_ids_distinct_per_knob(self):
        specs = ["default",
                 "default+layout+fuse",
                 "default+layout+fuse:cap=2",
                 "default+layout+fuse:cap=4",
                 "default+layout+fuse+auto_remat",
                 "default+layout+fuse+auto_remat:stride=2",
                 "default+layout+fuse+auto_remat:stride=4",
                 "default+layout+fuse+auto_remat:stride=4:budget_gb=0"]
        ids = [passes.pipeline_id(s) for s in specs]
        assert len(set(ids)) == len(ids), ids

    def test_knob_changes_pcache_fingerprint(self):
        from paddle_tpu.compile import fingerprint

        _fetch, _feed = _build_fit_a_line()
        main = fluid.default_main_program()
        fps = {fingerprint.program_fingerprint(
            main, pipeline_id=passes.pipeline_id(s))
            for s in ("default", "default+fuse", "default+fuse:cap=2")}
        assert len(fps) == 3

    def test_explicit_default_knob_is_same_pipeline(self):
        # "fuse:cap=0" IS the bare fuse pass: one semantics -> one
        # pipeline id (no duplicate pcache entries / ptune points)
        assert passes.pipeline_id("fuse:cap=0") == \
            passes.pipeline_id("fuse")
        assert passes.pipeline_id("layout:force=0") == \
            passes.pipeline_id("layout")
        assert passes.pipeline_id("fuse:cap=4") != \
            passes.pipeline_id("fuse")

    def test_float_knob_token_reparses(self):
        # '%g' rendered 2e6 as '2e+06', whose '+' is a token
        # separator — the canonical spec must round-trip through the
        # parser (tune/space normalizes specs exactly this way)
        pid = passes.pipeline_id("auto_remat:budget_gb=2000000")
        spec = passes.PassManager(
            "auto_remat:budget_gb=2000000", verify=False).spec
        assert passes.pipeline_id(spec) == pid
        assert "+" not in spec

    def test_unknown_knob_rejected(self):
        with pytest.raises(ValueError, match="no option"):
            passes.PassManager("fuse:nope=1")

    def test_invalid_knob_value_rejected(self):
        with pytest.raises(ValueError, match="cap"):
            passes.PassManager("fuse:cap=1")
        with pytest.raises(ValueError, match="stride"):
            passes.PassManager("auto_remat:stride=0")

    def test_malformed_token_rejected(self):
        with pytest.raises(ValueError, match="malformed"):
            passes.PassManager("fuse:cap")


class TestLayoutPass:
    def _forward_conv(self, channels):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(
                name="img", shape=[4, channels, 8, 8], dtype="float32",
                append_batch_size=False)
            conv = fluid.layers.conv2d(input=img, num_filters=channels,
                                       filter_size=3, padding=1,
                                       act="relu")
            pool = fluid.layers.pool2d(input=conv, pool_size=8,
                                       pool_type="avg",
                                       global_pooling=True)
            out = fluid.layers.fc(input=pool, size=4)
        return main, out.name

    def test_declines_without_fetches(self):
        # the fetch-layout guard cannot protect an undeclared runtime
        # fetch: without a fetch set the pass declines, like dce/fuse
        main, fetch = self._forward_conv(8)
        pm = passes.PassManager("layout:force=1")
        opt = pm.run(main, fetches=[])
        assert not pm.records[0]["changed"]
        assert "dce contract" in pm.records[0]["note"]
        assert opt.desc.serialize_to_string() == \
            main.desc.serialize_to_string()

    def test_declines_on_training_program(self):
        fetch, _feed = _build_conv_classifier()
        pm = passes.PassManager("layout:force=1")
        opt = pm.run(fluid.default_main_program(), fetches=[fetch])
        rec = pm.records[0]
        assert not rec["changed"] and "before append_backward" \
            in rec["note"]
        assert opt.desc.serialize_to_string() == \
            fluid.default_main_program().desc.serialize_to_string()

    def test_cost_gate_declines_tiny_channels(self):
        # C=8 pads to 128 lanes in NHWC: the tiled roofline says NCHW
        # is cheaper and the pass must decline on its own
        main, fetch = self._forward_conv(8)
        pm = passes.PassManager("layout")
        opt = pm.run(main, fetches=[fetch])
        rec = pm.records[0]
        assert not rec["changed"] and "no win" in rec["note"]
        assert all(od.attr("data_layout", "NCHW") == "NCHW"
                   for od in opt.global_block().desc.ops)

    def test_fetched_intermediate_declines_even_shape_invariant(self):
        """Regression: a fetched in-chain 4-D var with C==H==W
        permutes NCHW->NHWC to an IDENTICAL shape — the fetch guard
        must test layout-map membership, not shape equality, or the
        fetch silently returns permuted data."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[2, 8, 8, 8],
                                    dtype="float32",
                                    append_batch_size=False)
            conv = fluid.layers.conv2d(input=img, num_filters=8,
                                       filter_size=3, padding=1,
                                       act="relu")
            out = fluid.layers.reduce_sum(conv)
        # conv output shape [2, 8, 8, 8]: permutation-invariant
        mid = next(od.output("Out")[0]
                   for od in main.global_block().desc.ops
                   if od.type == "relu")
        pm = passes.PassManager("layout:force=1", explain=True)
        opt = pm.run(main, fetches=[mid, out.name])
        rec = pm.records[0]
        assert not rec["changed"], rec
        assert "changes layout" in rec["note"]
        assert opt.desc.serialize_to_string() == \
            main.desc.serialize_to_string()

    def test_force_converts_and_preserves_numerics(self):
        main, fetch = self._forward_conv(8)
        startup = fluid.Program()  # params live in main's startup
        pm = passes.PassManager("layout:force=1", verify_level="full",
                                explain=True)
        opt = pm.run(main, fetches=[fetch])
        rec = pm.records[0]
        assert rec["changed"] and rec["diff"]["inserted_transposes"] >= 1
        assert any(od.attr("data_layout") == "NHWC"
                   for od in opt.global_block().desc.ops)


class TestFusePass:
    def _residual_forward(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4, 8],
                                  dtype="float32",
                                  append_batch_size=False)
            y = fluid.layers.scale(x=x, scale=2.0)
            z = fluid.layers.elementwise_add(x=x, y=y)
            r = fluid.layers.relu(z)
            out = fluid.layers.reduce_sum(r)
        return main, out.name

    def test_fuses_chain_and_numerics(self):
        main, fetch = self._residual_forward()
        pm = passes.PassManager("fuse", verify_level="full")
        opt = pm.run(main, fetches=[fetch])
        types = [od.type for od in opt.global_block().desc.ops]
        assert FUSED_ELEMWISE_OP == "fused_elemwise_chain"
        assert "fused_elemwise_chain" in types
        # scale -> add -> relu collapse into one op
        assert "relu" not in types and "elementwise_add" not in types
        xv = np.random.RandomState(0).rand(4, 8).astype(np.float32)
        exe = executor_mod.Executor(executor_mod.CPUPlace())
        with executor_mod.scope_guard(Scope()):
            a = np.asarray(exe.run(main, feed={"x": xv},
                                   fetch_list=[fetch])[0])
            b = np.asarray(exe.run(opt, feed={"x": xv},
                                   fetch_list=[fetch])[0])
        np.testing.assert_array_equal(a, b)

    def test_cap_bounds_group_size(self):
        main, fetch = self._residual_forward()
        pm = passes.PassManager("fuse:cap=2")
        opt = pm.run(main, fetches=[fetch])
        for od in opt.global_block().desc.ops:
            if od.type == FUSED_ELEMWISE_OP:
                assert len(od.attr("inner_types")) <= 2

    def test_fetched_intermediate_never_fused(self):
        main, _ = self._residual_forward()
        # fetch the chain intermediate: the chain must stop before it
        mid = next(od.output("Out")[0]
                   for od in main.global_block().desc.ops
                   if od.type == "elementwise_add")
        pm = passes.PassManager("fuse")
        opt = pm.run(main, fetches=[mid])
        assert mid in opt.global_block().desc.vars
        types = [od.type for od in opt.global_block().desc.ops]
        assert "relu" in types  # consumer of the fetched value survives

    def test_declines_without_fetches(self):
        main, _ = self._residual_forward()
        pm = passes.PassManager("fuse")
        opt = pm.run(main, fetches=[])
        assert not pm.records[0]["changed"]
        assert "dce contract" in pm.records[0]["note"]

    def test_multi_use_intermediate_not_fused(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4, 8],
                                  dtype="float32",
                                  append_batch_size=False)
            y = fluid.layers.relu(x)
            a = fluid.layers.scale(x=y, scale=2.0)
            b = fluid.layers.scale(x=y, scale=3.0)  # second use of y
            out = fluid.layers.elementwise_add(x=a, y=b)
        pm = passes.PassManager("fuse")
        opt = pm.run(main, fetches=[out.name])
        types = [od.type for od in opt.global_block().desc.ops]
        assert "relu" in types  # y has two consumers: never fused away


class TestAutoRematPass:
    def test_declines_within_budget(self):
        fetch, _feed = _build_conv_classifier()
        pm = passes.PassManager("auto_remat")  # 16 GiB default budget
        pm.run(fluid.default_main_program(), fetches=[fetch])
        rec = pm.records[0]
        assert not rec["changed"] and "within" in rec["note"]

    def test_forced_remat_reduces_activation_peak(self):
        fetch, _feed = _build_conv_classifier()
        main = fluid.default_main_program()
        before = opt_passes.activation_peak_bytes(main.desc, [fetch])
        pm = passes.PassManager("auto_remat:stride=2:budget_gb=0",
                                explain=True)
        opt = pm.run(main, fetches=[fetch])
        rec = pm.records[0]
        assert rec["changed"]
        peaks = rec["diff"]["activation_peak_bytes"]
        assert peaks["before"] == before
        assert peaks["after"] < peaks["before"]
        assert any("recompute_barrier" == od.type
                   for od in opt.global_block().desc.ops)

    def test_declines_on_forward_program(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            out = fluid.layers.scale(x=x, scale=2.0)
        pm = passes.PassManager("auto_remat:budget_gb=0")
        pm.run(main, fetches=[out.name])
        assert not pm.records[0]["changed"]
        assert "backward" in pm.records[0]["note"]


class TestVerifierRejection:
    def test_broken_opt_rewrite_rejected(self, monkeypatch):
        from paddle_tpu.analysis.diagnostics import \
            ProgramVerificationError

        class BreakIR(passes.RewritePass):
            name = "fuse"  # masquerade in the registry slot

            def run(self, desc, ctx):
                # drop a var another op still reads: V002
                bd = desc.block(0)
                victim = next(n for n, vd in bd.vars.items()
                              if not vd.persistable)
                del bd.vars[victim]
                return {"broke": [victim]}

        monkeypatch.setitem(passes._PASSES, "fuse", BreakIR())
        fetch, _feed = _build_conv_classifier()
        with pytest.raises(ProgramVerificationError):
            passes.PassManager("fuse").run(
                fluid.default_main_program(), fetches=[fetch])


class TestResnet50B256Floor:
    def test_layout_fuse_strictly_lower_max_floor(self):
        """ISSUE 14 acceptance: the roofline cost model must predict a
        strictly lower max(MXU, HBM) floor for the layout/fuse-
        optimized ResNet-50 b256 program than for the unoptimized
        one — under the tiled accounting the layout gate uses AND
        under the default accounting (the fuse win alone)."""
        from paddle_tpu import models
        from paddle_tpu.fluid import analysis

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            image = fluid.layers.data(
                name="image", shape=[256, 3, 224, 224],
                dtype="float32", append_batch_size=False)
            logits = models.resnet50(image, class_dim=1000)
        pm = passes.PassManager("default+layout+fuse")
        opt = pm.run(main, fetches=[logits.name])
        changed = {r["pass"]: r["changed"] for r in pm.records}
        # layout must be accepted by its OWN cost gate (not forced),
        # and fuse must find the residual add+relu chains
        assert changed["layout"] and changed["fuse"], pm.records

        def max_floor(prog, tiled):
            rep = analysis.roofline_report(prog, tpu_tiling=tiled)
            return max(rep["total_gflops"] * 1e9
                       / (rep["peak_tflops"] * 1e12),
                       rep["unique_gbytes"] / rep["hbm_gbps"])

        assert max_floor(opt, True) < max_floor(main, True)
        assert max_floor(opt, False) < max_floor(main, False)


class TestTiledRoofline:
    def test_tile_padding_math(self):
        from paddle_tpu.fluid.analysis import _numel_tiled

        assert _numel_tiled((4, 7, 7), 4) == 4 * 8 * 128
        assert _numel_tiled((4, 7, 7), 2) == 4 * 16 * 128
        assert _numel_tiled((256,), 4) == 256 * 8
        assert _numel_tiled((300,), 4) == 384 * 8
        assert _numel_tiled((), 4) == 8 * 128
        assert _numel_tiled((2, 8, 128), 4) == 2 * 8 * 128

    def test_report_flags_tiling(self):
        _fetch, _feed = _build_fit_a_line()
        from paddle_tpu.fluid import analysis

        main = fluid.default_main_program()
        plain = analysis.roofline_report(main)
        tiled = analysis.roofline_report(main, tpu_tiling=True)
        assert not plain["tpu_tiling"] and tiled["tpu_tiling"]
        assert tiled["unique_gbytes"] >= plain["unique_gbytes"]
