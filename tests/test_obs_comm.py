"""paddle_tpu.obs.comm: per-bucket comm spans, overlap-efficiency
truth, drift calibration, cross-host merge, and the comm regression
gate (tools/comm_cli.py `pcomm` is the operator surface; scripts/ci.sh
runs its --selftest).
"""

import json
import os
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import native
from paddle_tpu.obs import comm as obs_comm
from paddle_tpu.obs import fleet as obs_fleet
from paddle_tpu.obs import flight as obs_flight
from paddle_tpu.obs import perf as obs_perf
from paddle_tpu.obs import registry as obs_registry
from paddle_tpu.obs import trace as obs_trace
from paddle_tpu.parallel import make_mesh
from paddle_tpu.spmd import SpmdTrainer
from paddle_tpu.spmd import overlap as spmd_overlap
from paddle_tpu.tools.obs_dump import validate_chrome_trace
from paddle_tpu.tune import fit as tune_fit

BATCH, DIM, HIDDEN, CLASSES = 16, 8, 1024, 4


def _build_mlp():
    # the test_spmd probe: big first layer, small head, so a KB-scale
    # bucket cap yields several buckets in reduce order
    fluid.framework.reset_unique_name()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[BATCH, DIM],
                              dtype="float32", append_batch_size=False)
        label = fluid.layers.data(name="label", shape=[BATCH, 1],
                                  dtype="int64", append_batch_size=False)
        h = fluid.layers.fc(input=x, size=HIDDEN, act="relu")
        logits = fluid.layers.fc(input=h, size=CLASSES, act=None)
        loss = fluid.layers.softmax_with_cross_entropy(logits, label)
        avg = fluid.layers.mean(loss)
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.1, momentum=0.9).minimize(avg)
    return main, startup, avg


def _feeds(step=0):
    rs = np.random.RandomState(100 + step)
    return {
        "x": rs.rand(BATCH, DIM).astype(np.float32),
        "label": rs.randint(0, CLASSES,
                            size=(BATCH, 1)).astype(np.int64),
    }


def _make_trainer(mesh, bucket_bytes):
    main, startup, avg = _build_mlp()
    return SpmdTrainer(main, startup, feed_names=["x", "label"],
                       fetch_names=[avg.name], mesh=mesh,
                       bucket_bytes=bucket_bytes,
                       use_pcache=False).init()


@pytest.fixture(scope="module")
def overlap_setup():
    """ONE traced overlapped dp=8 trainer shared across this module:
    the schedule spans fire at jit-trace time only, so the trace runs
    once with tracing on and COPIES of the captured events/schedule/
    host-context survive the per-test `fresh_obs` reset (the trainer
    object itself is reused — recompiling it per test would blow the
    tier-1 budget)."""
    obs_trace.enable()
    obs_comm.reset()
    mesh = make_mesh(n_devices=8, dp=8)
    trainer = _make_trainer(mesh, 24 << 10)
    trainer.step(_feeds(0))
    assert trainer.step_mode == "overlap-dp", trainer.step_mode
    setup = {
        "trainer": trainer,
        "sched": obs_comm.last_schedule(),
        "events": [dict(e) for e in obs_trace.events()],
        "host_ctx": obs_flight.host_context(),
    }
    obs_trace.disable()
    obs_trace.reset()
    yield setup


# -- trace-time schedule spans ---------------------------------------------

def test_schedule_names_last_produced_first(overlap_setup):
    sched = overlap_setup["sched"]
    trainer = overlap_setup["trainer"]
    assert sched and sched["collective"] == "allreduce"
    assert sched["axis"] == "dp" and sched["mean"]
    assert sched["n_buckets"] >= 2
    assert sched["total_bytes"] == sum(b["bytes"]
                                       for b in sched["buckets"])
    # flattened bucket members in EXACTLY the last-produced-first
    # (DDP) order the program's reduce seam defines
    _split, grad_order = spmd_overlap._split_point(
        list(trainer.main_program.desc.block(0).ops))
    flat = [n for b in sched["buckets"] for n in b["names"]]
    want = [g for g in reversed(grad_order) if g in set(flat)]
    assert flat == want, (flat, want)


def test_span_nesting_bytes_labels_and_instants(overlap_setup):
    sched = overlap_setup["sched"]
    evs = overlap_setup["events"]
    parents = [e for e in evs
               if e.get("name") == "comm/bucketed_allreduce"]
    assert parents, [e.get("name") for e in evs]
    assert parents[0]["args"]["n_buckets"] == sched["n_buckets"]
    assert parents[0]["args"]["total_bytes"] == sched["total_bytes"]
    bspans = [e for e in evs if e.get("name") == "comm/bucket"]
    assert len(bspans) == sched["n_buckets"]
    for i, e in enumerate(bspans):
        assert e["args"]["bucket"] == i
        assert e["args"]["bytes"] == sched["buckets"][i]["bytes"] > 0
        assert e["args"]["names"] == len(sched["buckets"][i]["names"])
        assert e["args"]["first"] == sched["buckets"][i]["names"][0]
    launches = [e for e in evs
                if e.get("name") == "comm/bucket_launch"]
    completes = [e for e in evs
                 if e.get("name") == "comm/bucket_complete"]
    assert len(launches) == len(completes) == sched["n_buckets"]
    # the overlap schedule's seam marker fired inside the same trace
    assert any(e.get("name") == "comm/reduce_seam" for e in evs)


def test_record_schedule_counter_and_reset():
    obs_trace.enable()
    sched = obs_comm.record_schedule(
        "allreduce", "dp",
        [{"bucket": 0, "names": ["b@GRAD", "a@GRAD"], "bytes": 128},
         {"bucket": 1, "names": ["w@GRAD"], "bytes": 64}])
    assert obs_comm.last_schedule() is sched
    assert sched["n_buckets"] == 2 and sched["total_bytes"] == 192
    ctr = obs_registry.get_registry().counter(
        "comm_bucket_schedules_total", labelnames=("collective",))
    vals = {s["labels"]["collective"]: s["value"]
            for s in ctr.samples()}
    assert vals["allreduce"] == 1
    assert any(e.get("name") == "comm/schedule"
               for e in obs_trace.events())
    # span helpers nest one comm/bucket per bucket inside the parent
    with obs_comm.schedule_span(sched):
        for i in range(sched["n_buckets"]):
            with obs_comm.bucket_span(sched, i):
                pass
    evs = obs_trace.events()
    assert len([e for e in evs
                if e.get("name") == "comm/bucket"]) == 2
    assert len([e for e in evs
                if e.get("name") == "comm/bucket_launch"]) == 2
    obs_comm.reset()
    assert obs_comm.last_schedule() is None


# -- runtime truth + overlap split -----------------------------------------

def test_measure_trainer_comm_rows_and_metrics(overlap_setup):
    trainer = overlap_setup["trainer"]
    rep = obs_comm.measure_trainer_comm(trainer, reps=1)
    assert rep and rep["collective"] == "allreduce" and rep["n"] == 8
    assert len(rep["buckets"]) >= 2
    for r in rep["buckets"]:
        assert r["measured_s"] > 0 and r["pred_s"] > 0
        assert r["wire_bytes"] > r["bytes"]  # ring wire > payload
        assert r["ratio"] == r["measured_s"] / r["pred_s"]
    assert rep["measured_s"] == pytest.approx(
        sum(r["measured_s"] for r in rep["buckets"]))
    reg = obs_registry.get_registry()
    hist = reg.histogram("comm_collective_seconds",
                         labelnames=("collective", "bucket"))
    buckets_seen = {s["labels"]["bucket"] for s in hist.samples()
                    if s["labels"].get("collective") == "allreduce"}
    assert {str(r["bucket"]) for r in rep["buckets"]} <= buckets_seen
    ctr = reg.counter("comm_bytes_total", labelnames=("collective",))
    total = sum(s["value"] for s in ctr.samples()
                if s["labels"]["collective"] == "allreduce")
    assert total == rep["wire_bytes"]  # reps=1: one replay per bucket


def test_overlap_report_split_and_gauges(overlap_setup):
    trainer = overlap_setup["trainer"]
    bucket_report = obs_comm.measure_trainer_comm(trainer, reps=1)
    rep = obs_comm.overlap_report(trainer, _feeds(0), reps=1,
                                  bucket_report=bucket_report)
    assert rep["supported"] and rep["step_mode"] == "overlap-dp"
    assert rep["plan_fingerprint"] == trainer.plan.fingerprint()
    assert rep["bucket_bytes"] == 24 << 10
    assert rep["step_s"] > 0 and rep["compute_s"] > 0
    assert rep["comm_s"] == pytest.approx(bucket_report["measured_s"])
    assert rep["exposed_s"] >= 0
    assert 0.0 <= rep["overlap_efficiency"] <= 1.0
    # the split is internally consistent: exposed + hidden == comm
    # (unless everything was exposed and hidden clamped to 0)
    assert rep["exposed_s"] + rep["hidden_s"] \
        == pytest.approx(rep["comm_s"]) \
        or rep["exposed_s"] >= rep["comm_s"]
    reg = obs_registry.get_registry()
    (exposed,) = reg.gauge("comm_exposed_seconds").samples()
    assert exposed["value"] == pytest.approx(rep["exposed_s"],
                                             abs=1e-6)
    (eff,) = reg.gauge("overlap_efficiency").samples()
    assert eff["value"] == pytest.approx(rep["overlap_efficiency"],
                                         abs=1e-4)


def test_overlap_report_fallback_carries_no_exposed_s():
    # a dp=4,mp=2 mesh falls back to gspmd at init: the report is
    # refused WITHOUT an exposed_s, so a fallback run structurally
    # cannot enter the overlap-efficiency baseline
    trainer = _make_trainer(make_mesh(n_devices=8, dp=4, mp=2),
                            24 << 10)
    assert trainer.step_mode == "gspmd"
    rep = obs_comm.overlap_report(trainer, _feeds(0), reps=1)
    assert not rep["supported"]
    assert rep["overlap_fallback_reason"]
    assert rep["plan_fingerprint"] == trainer.plan.fingerprint()
    assert "exposed_s" not in rep and "overlap_efficiency" not in rep


def test_trainer_stamps_flight_host_context(overlap_setup):
    ctx = overlap_setup["host_ctx"]
    trainer = overlap_setup["trainer"]
    assert ctx.get("process_index") == 0
    assert ctx.get("mesh_axes", {}).get("dp") == 8
    assert ctx.get("plan_fingerprint") == trainer.plan.fingerprint()
    assert ctx.get("host")


# -- drift -> calibration blob -> ptune fit --------------------------------

_BUCKET_REPORT = {
    "collective": "allreduce", "axis": "dp", "n": 8,
    "bucket_bytes": 1 << 10, "measured_s": 0.0035, "pred_s": 0.0015,
    "wire_bytes": 2625,
    "buckets": [
        {"bucket": 0, "names": ["b", "a"], "bytes": 1000,
         "wire_bytes": 1750, "pred_s": 0.001, "measured_s": 0.002,
         "ratio": 2.0},
        {"bucket": 1, "names": ["w"], "bytes": 500, "wire_bytes": 875,
         "pred_s": 0.0005, "measured_s": 0.0015, "ratio": 3.0},
    ],
}


def test_drift_report_rows_and_gauge():
    drift = obs_comm.drift_report(_BUCKET_REPORT)
    assert drift["n"] == 2 and drift["median_ratio"] == 2.5
    assert [r["bucket"] for r in drift["rows"]] == [0, 1]
    gauge = obs_registry.get_registry().gauge(
        "comm_estimate_ratio", labelnames=("bucket",))
    vals = {s["labels"]["bucket"]: s["value"]
            for s in gauge.samples()}
    assert vals == {"0": 2.0, "1": 3.0}
    assert obs_comm.drift_report(None)["n"] == 0


def test_calibration_blob_roundtrip_and_class_discipline(tmp_path):
    blob = obs_comm.calibration_blob(
        _BUCKET_REPORT, platform_class="cpu:d8:dp=8", model="mlp")
    assert blob["kind"] == obs_comm.COMM_CALIBRATION_KIND
    assert blob["n"] == 2 and blob["comm_ratio"] == 2.5
    assert all(p["platform_class"] == "cpu:d8:dp=8"
               for p in blob["pairs"])
    path = str(tmp_path / "comm_cal.json")
    obs_comm.save_calibration(blob, path)
    pairs = tune_fit.load_comm_calibration(path)
    assert len(pairs) == 2 and pairs[0]["leg"].endswith("bucket0")
    cal = tune_fit.fit_calibration([], comm_pairs=pairs)
    assert cal.coef["comm"] == pytest.approx(2.5)
    # same-platform-class discipline: training legs from a DIFFERENT
    # class keep the analytic prior instead of ingesting these pairs
    foreign = [{"leg": "ptune:x", "measured_s": 0.1,
                "meas_compute_s": 0.08, "overhead_s": 0.01,
                "platform_class": "tpu:d8:dp=8"}]
    cal2 = tune_fit.fit_calibration(foreign, comm_pairs=pairs)
    assert cal2.coef["comm"] == 1.0
    assert "kept analytic" in cal2.note
    # nothing measured -> no blob (the CLI turns this into rc 2)
    assert obs_comm.calibration_blob({"buckets": []}) is None
    assert obs_comm.calibration_blob(None,
                                     platform_class="x") is None


def test_load_comm_calibration_refuses_bad_blobs(tmp_path):
    wrong = tmp_path / "mem.json"
    wrong.write_text(json.dumps(
        {"kind": "paddle_tpu.mem_calibration", "pairs": []}))
    with pytest.raises(ValueError, match="not a pcomm"):
        tune_fit.load_comm_calibration(str(wrong))
    # right kind, nothing usable: must raise, never silently keep the
    # analytic prior while claiming to have fitted
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps(
        {"kind": obs_comm.COMM_CALIBRATION_KIND,
         "pairs": [{"leg": "x", "measured_s": 0.0, "pred_s": 0.001},
                   {"leg": "y", "measured_s": 0.01, "pred_s": -1}]}))
    with pytest.raises(ValueError, match="no usable"):
        tune_fit.load_comm_calibration(str(empty))


# -- history schema + the comm gate ----------------------------------------

def test_normalize_record_forwards_comm_blob():
    norm = obs_perf.normalize_record(
        {"metric": "m", "value": 1.0,
         "comm": {"measured_s": 0.005, "pred_s": 0.002,
                  "exposed_s": 0.001, "hidden_s": 0.004,
                  "overlap_efficiency": 0.8,
                  "step_mode": "overlap-dp", "plan_fingerprint": "fp",
                  "buckets": [{"bucket": 0}]}})
    comm = norm["comm"]
    assert comm["exposed_s"] == 0.001
    assert comm["step_mode"] == "overlap-dp"
    assert comm["plan_fingerprint"] == "fp"
    # per-bucket detail stays OUT of history lines
    assert "buckets" not in comm
    # fallback stamp rides along; absent comm -> absent key
    norm2 = obs_perf.normalize_record(
        {"metric": "m", "value": 1.0,
         "comm": {"measured_s": 10.0, "step_mode": "gspmd",
                  "overlap_fallback_reason": "mesh is not pure dp"}})
    assert norm2["comm"]["overlap_fallback_reason"]
    assert "exposed_s" not in norm2["comm"]
    assert "comm" not in obs_perf.normalize_record(
        {"metric": "m", "value": 1.0})


def _comm_history(path, regress=False, candidate_fallback=False):
    """±2% exposed-comm noise plus one mid-history gspmd fallback
    record (no exposed_s, huge measured_s) that must not drag the
    overlap baseline."""
    noise = [1.0, 0.99, 1.012, 0.994, 1.009, 0.98]
    ts = 1_700_000_000.0
    for i, n in enumerate(noise):
        e = 0.004 * (1.2 if (regress and i == len(noise) - 1) else n)
        obs_perf.append_history(
            {"metric": "mlp_multichip_imgs_per_sec",
             "value": round(512.0 * n, 2), "unit": "img/s",
             "step_ms": 31.0, "platform": "cpu",
             "comm": {"measured_s": 0.005, "exposed_s": round(e, 6),
                      "overlap_efficiency": 0.8,
                      "step_mode": "overlap-dp",
                      "plan_fingerprint": "fp0"}},
            path, leg="dp=8", ts=ts + i)
        if i == 2:
            obs_perf.append_history(
                {"metric": "mlp_multichip_imgs_per_sec",
                 "value": 512.0, "unit": "img/s", "step_ms": 31.0,
                 "platform": "cpu",
                 "comm": {"measured_s": 10.0, "step_mode": "gspmd",
                          "overlap_fallback_reason": "not pure dp"}},
                path, leg="dp=8", ts=ts + i + 0.5)
    if candidate_fallback:
        obs_perf.append_history(
            {"metric": "mlp_multichip_imgs_per_sec", "value": 512.0,
             "unit": "img/s", "step_ms": 31.0, "platform": "cpu",
             "comm": {"measured_s": 0.02, "step_mode": "gspmd",
                      "overlap_fallback_reason": "not pure dp"}},
            path, leg="dp=8", ts=ts + 10)
    return path


def test_comm_gate_passes_noise_fails_regression(tmp_path):
    ok = _comm_history(str(tmp_path / "ok.jsonl"))
    res = obs_perf.gate_history(obs_perf.load_history(ok),
                                comm_tolerance=0.1)
    assert res.ok, obs_perf.format_gate(res)

    bad = _comm_history(str(tmp_path / "bad.jsonl"), regress=True)
    res = obs_perf.gate_history(obs_perf.load_history(bad),
                                comm_tolerance=0.1)
    assert not res.ok and res.failures[0]["kind"] == "comm"
    assert "exposed_s" in res.failures[0]["why"]
    # the gate is OPT-IN: without the flag, throughput noise hides
    # the regression — exactly why the flag exists
    assert obs_perf.gate_history(obs_perf.load_history(bad)).ok


def test_comm_gate_same_key_discipline(tmp_path):
    # a fallback CANDIDATE carries no exposed_s, so it gates on
    # measured_s — against the overlapped baseline's standalone ring
    # (0.005s), the 0.02s ring fails on THAT key, and the mid-history
    # fallback record (measured_s=10) never polluted the exposed_s
    # baseline of the overlapped runs before it
    path = _comm_history(str(tmp_path / "fb.jsonl"),
                         candidate_fallback=True)
    res = obs_perf.gate_history(obs_perf.load_history(path),
                                comm_tolerance=0.1)
    assert not res.ok and res.failures[0]["kind"] == "comm"
    assert "measured_s" in res.failures[0]["why"]
    assert "exposed_s" not in res.failures[0]["why"]


# -- span windows, clock exchange, cross-host merge ------------------------

def _fake_window(host, epoch_wall, n=3):
    return {"host": host, "ts": epoch_wall + 1.0,
            "epoch_wall": epoch_wall, "dropped": 0,
            "events": [{"name": "step", "cat": "paddle_tpu",
                        "ph": "X", "ts": 1000.0 * i, "dur": 500.0,
                        "tid": 0} for i in range(n)]}


def test_merge_windows_rebases_with_offsets():
    # hostB's wall clock runs 0.5s ahead; the estimated offset cancels
    # it, putting both hosts' epochs on the same corrected instant
    wa = _fake_window("hostA", 100.0)
    wb = _fake_window("hostB", 100.5)
    merged = obs_comm.merge_windows({"hostA": wa, "hostB": wb},
                                    {"hostB": 0.5})
    events = validate_chrome_trace(merged)
    names = {e["args"]["name"]: e["pid"] for e in events
             if e.get("name") == "process_name"}
    assert names == {"hostA": 1, "hostB": 2}
    assert merged["otherData"]["hosts"] == ["hostA", "hostB"]
    assert merged["otherData"]["clock_offsets"]["hostB"] == 0.5
    a_ts = sorted(e["ts"] for e in events
                  if e.get("ph") == "X" and e["pid"] == 1)
    b_ts = sorted(e["ts"] for e in events
                  if e.get("ph") == "X" and e["pid"] == 2)
    assert a_ts == b_ts  # fully cancelled
    # without the offset, hostB's track sits 0.5s (5e5 us) later
    plain = obs_comm.merge_windows({"hostA": wa, "hostB": wb})
    b_plain = sorted(e["ts"] for e in plain["traceEvents"]
                     if e.get("ph") == "X" and e["pid"] == 2)
    assert b_plain[0] - b_ts[0] == pytest.approx(5e5, abs=1.0)
    assert obs_comm.merge_windows({})["otherData"]["hosts"] == []


def test_span_window_payload_filters_and_anchors():
    obs_trace.enable()
    sched = obs_comm.record_schedule(
        "allreduce", "dp",
        [{"bucket": 0, "names": ["a@GRAD"], "bytes": 64}])
    with obs_comm.schedule_span(sched):
        with obs_comm.bucket_span(sched, 0):
            pass
    payload = obs_comm.span_window_payload(host="me", limit=16)
    assert payload["host"] == "me" and payload["events"]
    assert payload["ts"] > 0
    # epoch_wall anchors the trace epoch near (wall now - perf now
    # since epoch): sanity-bound it to the recent past.  ts is rounded
    # to ms, so it can land up to 0.5ms BEFORE epoch_wall when the
    # whole body ran faster than that
    assert -0.001 <= payload["ts"] - payload["epoch_wall"] < 3600
    assert all(e["ph"] in ("X", "i") for e in payload["events"])
    assert any(e["name"] == "comm/bucket" for e in payload["events"])


def test_clock_offset_recovery_over_lease_store():
    master = native.Master()
    addr = "127.0.0.1:%d" % master.port
    responder = None
    try:
        responder = obs_comm.ClockResponder(
            addr, host="skewed", poll_s=0.02, skew_s=0.25).start()
        offsets = obs_comm.estimate_clock_offsets(
            addr, ["skewed"], reps=2, timeout_s=5.0)
        off = offsets["skewed"]
        assert off is not None and abs(off - 0.25) < 0.2, offsets
        # a host with no responder yields None, not a hang
        silent = obs_comm.estimate_clock_offsets(
            addr, ["ghost"], reps=1, timeout_s=0.3)
        assert silent["ghost"] is None
    finally:
        if responder is not None:
            responder.stop()
        master.stop()


def test_span_push_collect_reporter_lease_and_age_gauge():
    obs_trace.enable()
    sched = obs_comm.record_schedule(
        "allreduce", "dp",
        [{"bucket": 0, "names": ["a@GRAD"], "bytes": 64}])
    with obs_comm.schedule_span(sched):
        with obs_comm.bucket_span(sched, 0):
            pass
    master = native.Master()
    addr = "127.0.0.1:%d" % master.port
    reporter = None
    try:
        # bare push: update is unregister + register (immutable lease)
        lease = obs_comm.push_span_window(addr, host="bare", limit=64)
        assert lease is not None
        lease2 = obs_comm.push_span_window(addr, host="bare",
                                           limit=64, lease_prev=lease)
        assert lease2 is not None
        # FleetReporter rides the span window beside its snapshot
        reporter = obs_fleet.FleetReporter(addr, host="ridden",
                                           interval_s=60.0,
                                           span_window=64)
        assert reporter.push_once()
        assert reporter._span_lease is not None
        windows = obs_comm.collect_span_windows(addr)
        assert {"bare", "ridden"} <= set(windows)
        assert windows["bare"]["events"]
        assert windows["ridden"]["epoch_wall"] > 0
        # the aggregator publishes per-host snapshot age ...
        agg = obs_fleet.FleetAggregator()
        assert agg.collect(addr) >= 1
        agg.stragglers()
        age = obs_registry.get_registry().gauge(
            "fleet_snapshot_age_seconds", labelnames=("host",))
        ages = {s["labels"]["host"]: s["value"]
                for s in age.samples()}
        assert "ridden" in ages and ages["ridden"] >= 0
        # ... and retires it (plus the span window) when the host
        # leaves the fleet
        reporter.stop(unregister=True)
        reporter = None
        agg.collect(addr)
        agg.stragglers()
        assert not any(s["labels"]["host"] == "ridden"
                       for s in age.samples())
        assert "ridden" not in obs_comm.collect_span_windows(addr)
    finally:
        if reporter is not None:
            reporter.stop(unregister=True)
        master.stop()


def test_fleet_snapshot_age_from_ingest():
    agg = obs_fleet.FleetAggregator()
    agg.ingest({"host": "old", "ts": time.time() - 7.0,
                "metrics": {}})
    agg.stragglers()
    age = obs_registry.get_registry().gauge(
        "fleet_snapshot_age_seconds", labelnames=("host",))
    ages = {s["labels"]["host"]: s["value"] for s in age.samples()}
    assert ages["old"] >= 6.5


# -- flight host context ---------------------------------------------------

def test_flight_host_context_merge_delete_and_dump(tmp_path):
    obs_flight.set_host_context(host="h3", process_index=3,
                                mesh_axes={"dp": 8})
    obs_flight.set_host_context(plan_fingerprint="fp9")
    ctx = obs_flight.host_context()
    assert ctx["process_index"] == 3 and ctx["plan_fingerprint"] \
        == "fp9"
    # None deletes a key
    obs_flight.set_host_context(plan_fingerprint=None)
    assert "plan_fingerprint" not in obs_flight.host_context()
    recorder = obs_flight.install(out_dir=str(tmp_path), capacity=4)
    try:
        bundle = recorder.dump(reason="test")
    finally:
        obs_flight.uninstall()
    with open(bundle) as f:
        doc = json.load(f)
    assert doc["host_context"]["host"] == "h3"
    assert doc["host_context"]["mesh_axes"] == {"dp": 8}
    # cleared context -> no host_context key at all
    obs_flight.clear_host_context()
    recorder = obs_flight.install(out_dir=str(tmp_path), capacity=4)
    try:
        bundle2 = recorder.dump(reason="test2")
    finally:
        obs_flight.uninstall()
    with open(bundle2) as f:
        assert "host_context" not in json.load(f)
