"""v2 recurrent_group / memory / beam_search generation (reference:
trainer_config_helpers/layers.py recurrent_group:4082, memory:3590,
beam_search:4406; runtime RecurrentGradientMachine.h:32,307-309)."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.v2 as v2
import paddle_tpu.fluid as fluid

layer = v2.layer


def _run_seq(out, feeds, lod_feeds):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    blk = fluid.default_main_program().global_block()
    feeder = fluid.DataFeeder(
        place=fluid.CPUPlace(), feed_list=[blk.var(n) for n in feeds])
    rows = [tuple(lod_feeds[n][i] for n in feeds)
            for i in range(len(lod_feeds[feeds[0]]))]
    res, = exe.run(fluid.default_main_program(), feed=feeder.feed(rows),
                   fetch_list=[out], return_numpy=False)
    if hasattr(res, "values"):
        return np.asarray(res.values)[:int(res.nvalid)], res.lod()
    return np.asarray(res), None


def test_recurrent_group_accumulator():
    x = layer.data(name="x",
                   type=v2.data_type.dense_vector_sequence(3))

    def step(y):
        mem = layer.memory(name="acc", size=3)
        out = layer.addto(input=[mem, y], act=None)
        mem.set_input(out)
        return out

    out = layer.recurrent_group(step=step, input=x)
    seqs = [[[1, 1, 1], [2, 2, 2], [3, 3, 3]], [[10, 0, 0], [1, 1, 1]]]
    vals, lod = _run_seq(out, ["x"], {"x": seqs})
    assert vals.tolist() == [[1, 1, 1], [3, 3, 3], [6, 6, 6],
                             [10, 0, 0], [11, 1, 1]]
    assert lod == [[0, 3, 5]]


def test_recurrent_group_reverse():
    x = layer.data(name="x",
                   type=v2.data_type.dense_vector_sequence(2))

    def step(y):
        mem = layer.memory(name="racc", size=2)
        out = layer.addto(input=[mem, y], act=None)
        mem.set_input(out)
        return out

    out = layer.recurrent_group(step=step, input=x, reverse=True)
    seqs = [[[1, 0], [2, 0], [4, 0]]]
    vals, _ = _run_seq(out, ["x"], {"x": seqs})
    # reverse accumulation = suffix sums, in original order
    assert vals.tolist() == [[7, 0], [6, 0], [4, 0]]


def test_recurrent_group_static_input():
    x = layer.data(name="x",
                   type=v2.data_type.dense_vector_sequence(2))
    s = layer.data(name="s", type=v2.data_type.dense_vector(2))

    def step(y, st):
        return layer.addto(input=[y, st], act=None)

    out = layer.recurrent_group(
        step=step, input=[x, layer.StaticInput(input=s)])
    seqs = [[[1, 1], [2, 2]]]
    vals, _ = _run_seq(out, ["x", "s"],
                       {"x": seqs, "s": [[10.0, 20.0]]})
    assert vals.tolist() == [[11, 21], [12, 22]]


def test_recurrent_group_named_memory_link():
    """memory(name=N) links to the layer registered under N — the
    reference's name-based wiring, no explicit set_input."""
    x = layer.data(name="x",
                   type=v2.data_type.dense_vector_sequence(2))

    def step(y):
        mem = layer.memory(name="state", size=2)
        out = layer.addto(input=[mem, y], name="state")
        return out

    out = layer.recurrent_group(step=step, input=x)
    seqs = [[[1, 2], [3, 4]]]
    vals, _ = _run_seq(out, ["x"], {"x": seqs})
    assert vals.tolist() == [[1, 2], [4, 6]]


def test_lstm_step_group():
    """lstmemory_group pattern: lstm_step_layer + get_output_layer for
    the cell memory."""
    H = 4
    x = layer.data(name="x",
                   type=v2.data_type.dense_vector_sequence(4 * H))

    def step(y):
        out_mem = layer.memory(name="h", size=H)
        cell_mem = layer.memory(name="c", size=H)
        h = layer.lstm_step_layer(input=y, state=cell_mem, size=H,
                                  name="h")
        layer.get_output_layer(input=h, arg_name="state", name="c")
        return h

    out = layer.recurrent_group(step=step, input=x)
    rs = np.random.RandomState(0)
    seqs = [rs.rand(3, 4 * H).tolist(), rs.rand(2, 4 * H).tolist()]
    vals, lod = _run_seq(out, ["x"], {"x": seqs})
    assert vals.shape == (5, H)
    assert np.all(np.isfinite(vals))
    assert lod == [[0, 3, 5]]


def test_recurrent_layer_matches_numpy():
    x = layer.data(name="x",
                   type=v2.data_type.dense_vector_sequence(2))
    out = layer.recurrent(
        input=x, act=v2.activation.Linear(),
        param_attr=v2.attr.Param(initial_std=0.0, initial_mean=0.5),
        bias_attr=False)
    seqs = [[[1.0, 1.0], [1.0, 1.0]]]
    vals, _ = _run_seq(out, ["x"], {"x": seqs})
    # out_t = x_t + h_{t-1} @ W with W all 0.5 (reference
    # RecurrentLayer semantics: input unprojected)
    W = np.full((2, 2), 0.5, np.float32)
    h = np.zeros(2, np.float32)
    expect = []
    for t in range(2):
        h = np.ones(2, np.float32) + h @ W
        expect.append(h.copy())
    np.testing.assert_allclose(vals, np.asarray(expect), rtol=1e-5)


def _build_gen_topology(V=7, E=4, H=4):
    src = layer.data(name="src",
                     type=v2.data_type.integer_value_sequence(V))
    src_emb = layer.embedding(input=src, size=E)
    enc = layer.pool(input=src_emb, pooling_type=v2.pooling.Sum)
    boot = layer.fc(input=enc, size=H, act=v2.activation.Tanh())

    def gen_step(cur_emb):
        mem = layer.memory(name="dec", size=H, boot_layer=boot)
        inp = layer.fc(input=[cur_emb, mem], size=H * 3, act=None)
        g = layer.gru_step_layer(input=inp, output_mem=mem, size=H,
                                 name="dec")
        return layer.fc(input=g, size=V,
                        act=v2.activation.Softmax())

    return layer.beam_search(
        step=gen_step,
        input=[layer.GeneratedInput(size=V, embedding_name="trg_emb",
                                    embedding_size=E)],
        bos_id=0, eos_id=1, beam_size=3, max_length=6)


def test_beam_search_generation():
    beam = _build_gen_topology()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    data = [([2, 3, 4],), ([5, 6],)]
    probs, ids = paddle.infer(output_layer=beam, input=data,
                              field=["prob", "id"])
    probs = np.asarray(probs)
    assert probs.shape == (2, 3)
    # scores sorted best-first per sample
    assert np.all(np.diff(probs, axis=1) <= 1e-6)
    seqs, cur = [], []
    for w in ids:
        if w == -1:
            seqs.append(cur)
            cur = []
        else:
            cur.append(w)
    assert len(seqs) == 6          # 2 samples x beam 3
    for s in seqs:
        assert s[0] == 0 and s[-1] == 1        # bos ... eos
        assert len(s) <= 2 + 6                 # max_length bound


def test_seqgen_train_then_decode():
    """End-to-end seqgen through the v2 API: train a tiny seq2seq with a
    recurrent_group decoder (teacher forcing), then beam-decode with the
    same parameters (reference: demo/seqToseq train.conf/gen.conf flow).
    The model must learn to echo a constant target."""
    V, E, H = 6, 4, 4
    names = {"emb": "trg_emb", "in": "dec_in", "gru": "dec_gru",
             "out": "dec_out"}

    src = layer.data(name="src",
                     type=v2.data_type.integer_value_sequence(V))
    src_emb = layer.embedding(input=src, size=E)
    enc = layer.pool(input=src_emb, pooling_type=v2.pooling.Sum)
    boot = layer.fc(input=enc, size=H, act=v2.activation.Tanh(),
                    param_attr=v2.attr.Param(name="boot_w"))

    trg = layer.data(name="trg",
                     type=v2.data_type.integer_value_sequence(V))
    trg_emb = layer.embedding(input=trg, size=E,
                              param_attr=v2.attr.Param(name=names["emb"]))
    lbl = layer.data(name="lbl",
                     type=v2.data_type.integer_value_sequence(V))

    def dec_step(cur_emb):
        mem = layer.memory(name="dec", size=H, boot_layer=boot)
        inp = layer.fc(
            input=[cur_emb, mem], size=H * 3, act=None,
            param_attr=[v2.attr.Param(name=names["in"] + "_x"),
                        v2.attr.Param(name=names["in"] + "_h")])
        g = layer.gru_step_layer(
            input=inp, output_mem=mem, size=H, name="dec",
            param_attr=v2.attr.Param(name=names["gru"]))
        return layer.fc(input=g, size=V, act=v2.activation.Softmax(),
                        param_attr=v2.attr.Param(name=names["out"]))

    dec = layer.recurrent_group(step=dec_step, input=trg_emb)
    cost = layer.classification_cost(input=dec, label=lbl)

    params = v2.parameters.create(cost)
    trainer = v2.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=v2.optimizer.Adam(learning_rate=0.05))

    # task: regardless of src, produce 2 3 then eos(1)
    def reader():
        rs = np.random.RandomState(7)
        for _ in range(8):
            batch = []
            for _b in range(8):
                s = rs.randint(2, V, size=3).tolist()
                batch.append((s, [0, 2, 3], [2, 3, 1]))
            yield batch

    costs = []
    trainer.train(
        reader=reader, num_passes=6,
        feeding={"src": 0, "trg": 1, "lbl": 2},
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, v2.event.EndIteration) else None)
    assert costs[-1] < costs[0]

    # generation topology sharing the learned params by name
    def gen_step(cur_emb):
        mem = layer.memory(name="dec", size=H, boot_layer=boot)
        inp = layer.fc(
            input=[cur_emb, mem], size=H * 3, act=None,
            param_attr=[v2.attr.Param(name=names["in"] + "_x"),
                        v2.attr.Param(name=names["in"] + "_h")])
        g = layer.gru_step_layer(
            input=inp, output_mem=mem, size=H, name="dec",
            param_attr=v2.attr.Param(name=names["gru"]))
        return layer.fc(input=g, size=V, act=v2.activation.Softmax(),
                        param_attr=v2.attr.Param(name=names["out"]))

    beam = layer.beam_search(
        step=gen_step,
        input=[layer.GeneratedInput(size=V, embedding_name=names["emb"],
                                    embedding_size=E)],
        bos_id=0, eos_id=1, beam_size=2, max_length=5)

    probs, ids = paddle.infer(output_layer=beam,
                              input=[([2, 3, 4],)],
                              field=["prob", "id"])
    seqs, cur = [], []
    for w in ids:
        if w == -1:
            seqs.append(cur)
            cur = []
        else:
            cur.append(w)
    # best beam must have learned the target: bos 2 3 eos
    assert seqs[0] == [0, 2, 3, 1], seqs


# -- nested sequences (SubsequenceInput) -------------------------------------

def test_nested_group_inner_accumulation():
    """Outer=sentences, inner=words: the step runs an inner recurrence
    per subsequence; outputs re-nest to lod 2 (reference:
    RecurrentGradientMachine.h:32 nested mode)."""
    x = layer.data(name="x",
                   type=v2.data_type.dense_vector_sub_sequence(2))

    def outer_step(sent):
        def inner_step(w):
            mem = layer.memory(name="nacc", size=2)
            out = layer.addto(input=[mem, w], act=None)
            mem.set_input(out)
            return out

        return layer.recurrent_group(step=inner_step, input=sent)

    out = layer.recurrent_group(step=outer_step,
                                input=layer.SubsequenceInput(x))
    # sample 0: 2 sentences; sample 1: 1 sentence
    seqs = [[[[1, 0], [2, 0]], [[5, 0], [1, 0], [1, 0]]],
            [[[7, 0]]]]
    vals, lod = _run_seq(out, ["x"], {"x": seqs})
    # prefix sums restart at every sentence
    assert vals.tolist() == [[1, 0], [3, 0], [5, 0], [6, 0], [7, 0],
                             [7, 0]]
    assert lod[0] == [0, 2, 3]          # outer: sentences per sample
    assert lod[-1] == [0, 2, 5, 6]      # inner: words per sentence


def test_nested_group_sentence_encoder_trains():
    """Hierarchical model: words->sentence encodings (nested group),
    then an ordinary recurrent_group over sentences; trains end to
    end."""
    words = layer.data(name="words",
                       type=v2.data_type.dense_vector_sub_sequence(4))
    glob = layer.data(name="glob", type=v2.data_type.dense_vector(4))
    label = layer.data(name="label", type=v2.data_type.dense_vector(1))

    def encode_sentence(sent, g):
        h = layer.fc(input=sent, size=6, act=v2.activation.Tanh())
        h2 = layer.fc(input=g, size=6)  # expanded static, per sentence
        enc = layer.last_seq(input=h)
        return layer.addto(input=[enc, h2], act=None)

    sent_seq = layer.recurrent_group(
        step=encode_sentence,
        input=[layer.SubsequenceInput(words),
               layer.StaticInput(glob)])
    doc = layer.last_seq(input=sent_seq)
    pred = layer.fc(input=doc, size=1)
    cost = layer.mse_cost(input=pred, label=label)
    fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)

    rs = np.random.RandomState(0)
    docs = [[rs.rand(rs.randint(2, 5), 4).tolist()
             for _ in range(rs.randint(1, 4))] for _ in range(6)]
    globs = [rs.rand(4).tolist() for _ in range(6)]
    labels = [[float(len(d))] for d in docs]

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    blk = fluid.default_main_program().global_block()
    feeder = fluid.DataFeeder(
        place=fluid.CPUPlace(),
        feed_list=[blk.var("words"), blk.var("glob"), blk.var("label")])
    feeds = feeder.feed(list(zip(docs, globs, labels)))
    losses = [float(np.asarray(exe.run(
        fluid.default_main_program(), feed=feeds,
        fetch_list=[cost])[0]).reshape(-1)[0]) for _ in range(10)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


def test_nested_group_outer_memory_raises():
    x = layer.data(name="x",
                   type=v2.data_type.dense_vector_sub_sequence(2))

    def outer_step(sent):
        layer.memory(name="om", size=2)  # cross-subsequence state
        return layer.last_seq(input=sent)

    import pytest

    with pytest.raises(NotImplementedError, match="subsequence"):
        layer.recurrent_group(step=outer_step,
                              input=layer.SubsequenceInput(x))
