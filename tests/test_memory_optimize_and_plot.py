"""memory_optimize pass interface + v2 Ploter (reference:
memory_optimization_transpiler.py, v2/plot/plot.py)."""

import numpy as np

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as paddle_v2


def _build_mlp():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    h1 = fluid.layers.fc(input=x, size=8, act="relu")
    h2 = fluid.layers.fc(input=h1, size=8, act="relu")
    h3 = fluid.layers.fc(input=h2, size=8, act="relu")
    out = fluid.layers.mean(x=h3)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(out)
    return out


def test_memory_optimize_liveness():
    out = _build_mlp()

    released, renames = fluid.memory_optimize(
        fluid.default_main_program(), skip_opt_set=[out.name],
        rewrite=False)
    all_released = {n for names in released.values() for n in names}
    # intermediate activations die; parameters never released
    assert any("tmp" in n or "@" in n for n in all_released), all_released
    params = [v.name for v in
              fluid.default_main_program().global_block().vars.values()
              if isinstance(v, fluid.Parameter)]
    assert not (set(params) & all_released)
    assert renames == {}
    # the analysis result is consistent with actually running the program
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    loss, = exe.run(feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[out])
    assert np.isfinite(loss).all()


def test_memory_optimize_rewrite_reuses_and_preserves_results():
    """The rewriting pass (reference: memory_optimization_transpiler
    rewrite loop): later temps adopt dead temps' slots, the live-var
    count drops, and training results are bit-identical."""
    out = _build_mlp()
    prog = fluid.default_main_program()

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": np.arange(8, dtype=np.float32).reshape(2, 4) / 8.0}
    baseline = [np.asarray(exe.run(prog, feed=feed,
                                   fetch_list=[out])[0]).copy()
                for _ in range(3)]

    n_vars_before = len(prog.global_block().desc.vars)
    _, renames = fluid.memory_optimize(prog, skip_opt_set=[out.name])
    assert renames, "expected at least one slot reuse in a 3-layer MLP"
    assert len(prog.global_block().desc.vars) == n_vars_before - \
        len(renames)
    assert out.name not in renames

    # reset state and retrain: identical losses step for step
    from paddle_tpu.core import scope as scope_mod

    scope_mod.reset_global_scope()
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(fluid.default_startup_program())
    for expect in baseline:
        got = np.asarray(exe2.run(prog, feed=feed,
                                  fetch_list=[out])[0])
        np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_v2_ploter(capsys):
    ploter = paddle_v2.plot.Ploter("train", "test")
    ploter.append("train", 0, 1.0)
    ploter.append("train", 1, 0.5)
    ploter.append("test", 0, 0.9)
    ploter.__disable_plot__ = True  # text mode for CI determinism
    ploter.plot()
    out = capsys.readouterr().out
    assert "train" in out and "test" in out
    ploter.reset()
    assert ploter.__plot_data__["train"].step == []
