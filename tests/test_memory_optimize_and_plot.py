"""memory_optimize pass interface + v2 Ploter (reference:
memory_optimization_transpiler.py, v2/plot/plot.py)."""

import numpy as np

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as paddle_v2


def test_memory_optimize_liveness():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    h1 = fluid.layers.fc(input=x, size=8, act="relu")
    h2 = fluid.layers.fc(input=h1, size=8, act="relu")
    out = fluid.layers.mean(x=h2)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(out)

    released = fluid.memory_optimize(fluid.default_main_program())
    all_released = {n for names in released.values() for n in names}
    # intermediate activations die; parameters never released
    assert any("tmp" in n or "@" in n for n in all_released), all_released
    params = [v.name for v in
              fluid.default_main_program().global_block().vars.values()
              if isinstance(v, fluid.Parameter)]
    assert not (set(params) & all_released)
    # the analysis result is consistent with actually running the program
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    loss, = exe.run(feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[out])
    assert np.isfinite(loss).all()


def test_v2_ploter(capsys):
    ploter = paddle_v2.plot.Ploter("train", "test")
    ploter.append("train", 0, 1.0)
    ploter.append("train", 1, 0.5)
    ploter.append("test", 0, 0.9)
    ploter.__disable_plot__ = True  # text mode for CI determinism
    ploter.plot()
    out = capsys.readouterr().out
    assert "train" in out and "test" in out
    ploter.reset()
    assert ploter.__plot_data__["train"].step == []
