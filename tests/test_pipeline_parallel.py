"""GPipe pipeline parallelism: parity of the ppermute ring schedule
against sequentially applied stages, forward and backward, incl. pp x dp.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from paddle_tpu.parallel.pipeline import (
    gpipe_spmd, pipeline_apply, split_microbatches, stack_stage_params)

D = 16


def _stage_params(rng, n_stages):
    return [{"w": jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.3),
             "b": jnp.asarray(rng.randn(D).astype(np.float32) * 0.1)}
            for _ in range(n_stages)]


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _sequential(per_stage, x):
    for p in per_stage:
        x = _stage_fn(p, x)
    return x


def _mesh(shape, names):
    n = int(np.prod(shape))
    devs = np.array(jax.devices()[:n]).reshape(shape)
    return Mesh(devs, axis_names=names)


def test_pipeline_forward_matches_sequential():
    rng = np.random.RandomState(0)
    per_stage = _stage_params(rng, 4)
    stacked = stack_stage_params(per_stage)
    x = jnp.asarray(rng.randn(24, D).astype(np.float32))

    mesh = _mesh((4,), ("pp",))
    out = pipeline_apply(mesh, _stage_fn, stacked, x, n_microbatches=6)
    ref = _sequential(per_stage, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_pipeline_pp_x_dp_training_grads():
    """pp=4 x dp=2: loss AND parameter gradients through the pipelined
    schedule match the unpipelined computation — jax.grad transposes
    the ppermute ring into the backward pipeline."""
    rng = np.random.RandomState(1)
    per_stage = _stage_params(rng, 4)
    stacked = stack_stage_params(per_stage)
    x = jnp.asarray(rng.randn(16, D).astype(np.float32))
    tgt = jnp.asarray(rng.randn(16, D).astype(np.float32))

    mesh = _mesh((4, 2), ("pp", "dp"))

    def piped_loss(stacked):
        out = pipeline_apply(mesh, _stage_fn, stacked, x,
                             n_microbatches=4)
        return jnp.mean((out - tgt) ** 2)

    def seq_loss(stacked):
        per = [jax.tree_util.tree_map(lambda l: l[i], stacked)
               for i in range(4)]
        return jnp.mean((_sequential(per, x) - tgt) ** 2)

    l_p, g_p = jax.value_and_grad(piped_loss)(stacked)
    l_s, g_s = jax.value_and_grad(seq_loss)(stacked)
    np.testing.assert_allclose(float(l_p), float(l_s), rtol=1e-5)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_p[k]), np.asarray(g_s[k]),
                                   rtol=5e-4, atol=1e-6)


def test_pipeline_more_microbatches_than_stages():
    rng = np.random.RandomState(2)
    per_stage = _stage_params(rng, 2)
    stacked = stack_stage_params(per_stage)
    x = jnp.asarray(rng.randn(32, D).astype(np.float32))

    mesh = _mesh((2,), ("pp",))
    out = pipeline_apply(mesh, _stage_fn, stacked, x, n_microbatches=8,
                         remat=True)
    ref = _sequential(per_stage, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_split_microbatches_validates():
    with pytest.raises(ValueError):
        split_microbatches(jnp.zeros((10, 3)), 4)
    mb = split_microbatches(jnp.zeros((12, 3)), 4)
    assert mb.shape == (4, 3, 3)


def test_pipeline_stage_count_mismatch():
    rng = np.random.RandomState(3)
    stacked = stack_stage_params(_stage_params(rng, 2))
    mesh = _mesh((4,), ("pp",))
    with pytest.raises(ValueError):
        pipeline_apply(mesh, _stage_fn, stacked,
                       jnp.zeros((8, D)), n_microbatches=2)


def test_pipeline_stages_with_ring_attention():
    """All-axis composition: pp pipeline stages whose interior runs
    ring attention over sp, with dp-sharded microbatches — one
    shard_map over a (pp, dp, sp) mesh.  Parity against sequential
    stages with dense attention on the full sequence."""
    import functools
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel.sharding import shard_map_norep
    from paddle_tpu.parallel.ring import ring_attention
    from paddle_tpu.kernels.flash_attention import reference_attention

    d, T = 8, 8
    rng = np.random.RandomState(5)

    def block_params():
        z = lambda *s: jnp.asarray(rng.randn(*s).astype(np.float32) * 0.3)
        return {"wq": z(d, d), "wk": z(d, d), "wv": z(d, d),
                "wo": z(d, d), "w1": z(d, d), "w2": z(d, d)}

    per_stage = [block_params() for _ in range(2)]
    stacked = stack_stage_params(per_stage)

    def block(p, x, attend):
        q, k, v = x @ p["wq"], x @ p["wk"], x @ p["wv"]
        o = attend(q[:, None], k[:, None], v[:, None])[:, 0]
        x = x + o @ p["wo"]
        return x + jnp.tanh(x @ p["w1"]) @ p["w2"]

    # pipelined: ring attention inside the pp stage (same shard_map)
    def ring_attend(q, k, v):
        return ring_attention(q, k, v, "sp", causal=True)

    def device_fn(stacked, x_mb):
        return gpipe_spmd(functools.partial(block, attend=ring_attend),
                          stacked, x_mb, axis_name="pp")

    mesh = _mesh((2, 2, 2), ("pp", "dp", "sp"))
    x = jnp.asarray(rng.randn(2, 4, T, d).astype(np.float32))  # [M,mb,T,d]
    spec = P(None, "dp", "sp", None)
    piped = shard_map_norep(device_fn, mesh=mesh,
                            in_specs=(jax.tree_util.tree_map(
                                lambda _: P("pp"), stacked), spec),
                            out_specs=spec)(stacked, x)

    # reference: sequential stages, dense causal attention, full T
    def dense_attend(q, k, v):
        return reference_attention(q, k, v, None, True)

    ref = x.reshape(8, T, d)
    for p in per_stage:
        ref = block(p, ref, dense_attend)
    np.testing.assert_allclose(np.asarray(piped).reshape(8, T, d),
                               np.asarray(ref), rtol=3e-5, atol=3e-6)


def test_pipeline_program_trainer():
    """Pipeline stages built through the Program stack (fluid layers ->
    FunctionalProgram) train through the microbatch schedule: parameter
    names are stable across stages, the stacked states shard over pp,
    and the loss decreases."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.parallel import PipelineProgramTrainer

    def build_stage(i):
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            h = fluid.layers.data(name="h", shape=[D], dtype="float32")
            out = fluid.layers.fc(input=h, size=D, act="tanh")
        return main, startup, "h", out.name

    mesh = _mesh((4, 2), ("pp", "dp"))
    trainer = PipelineProgramTrainer(
        build_stage, mesh, n_microbatches=4,
        optimizer=fluid.optimizer.Momentum(learning_rate=0.2,
                                           momentum=0.9))
    rs = np.random.RandomState(0)
    x = rs.randn(16, D).astype(np.float32)
    tgt = np.tanh(x @ (np.eye(D, dtype=np.float32) * 0.5))
    losses = [trainer.step(x, tgt) for _ in range(12)]
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    # real framework optimizer state drives the schedule: velocity
    # accumulators exist per stacked stage param and are non-zero
    vel = trainer.opt_state["slots"]["velocity"]
    assert sorted(vel) == sorted(trainer.stacked)
    for name, v in vel.items():
        assert v.shape == trainer.stacked[name].shape
        assert np.abs(np.asarray(v)).max() > 0, name
