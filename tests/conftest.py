"""Test config: force an 8-device virtual CPU mesh before JAX import so
multi-chip sharding tests run without TPU hardware (the driver separately
dry-runs the multichip path)."""

import os

# force CPU: the session env may point JAX_PLATFORMS at the single real
# TPU (axon tunnel); tests must never contend for it
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'` (ROADMAP.md); scripts/ci.sh runs the
    # full suite including slow-marked tests
    config.addinivalue_line(
        "markers", "slow: heavier tests excluded from the tier-1 "
                   "budget (-m 'not slow')")

# the axon sitecustomize (PYTHONPATH=/root/.axon_site) force-selects the
# TPU platform via jax.config at interpreter start, overriding the env
# var; override it back before any backend initializes
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.fixture(autouse=True)
def fresh_obs():
    """Observability state is process-global (default registry, span
    tracer, flight recorder, health switch): reset it around every
    test so counters don't bleed across tests and order-dependent
    assertions can't flake."""
    from paddle_tpu.obs import comm as obs_comm
    from paddle_tpu.obs import flight as obs_flight
    from paddle_tpu.obs import health as obs_health
    from paddle_tpu.obs import mem as obs_mem
    from paddle_tpu.obs import perf as obs_perf
    from paddle_tpu.obs import registry as obs_registry
    from paddle_tpu.obs import tail as obs_tail
    from paddle_tpu.obs import telemetry as obs_tele
    from paddle_tpu.obs import trace as obs_trace
    from paddle_tpu.resilience import faults as r_faults

    obs_registry.reset_registry()
    obs_mem.reset()
    obs_comm.reset()
    obs_trace.disable()
    obs_trace.reset()
    r_faults.disable()
    yield
    obs_mem.reset()
    obs_comm.reset()
    obs_health.disable()
    obs_flight.uninstall()
    obs_flight.clear_host_context()
    obs_perf.uninstall()
    obs_tail.uninstall()
    obs_tele.install_step_observer(None)
    obs_trace.disable()
    obs_trace.reset()
    r_faults.disable()


@pytest.fixture(autouse=True)
def fresh_programs():
    """Each test gets fresh default programs/scope (the reference's tests
    run one per process; ours share a process)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework
    from paddle_tpu.core import scope as scope_mod

    from paddle_tpu.v2 import layer as v2_layer

    old_main = framework.switch_main_program(framework.Program())
    old_startup = framework.switch_startup_program(framework.Program())
    old_scope = scope_mod._global_scope
    scope_mod._global_scope = scope_mod.Scope()
    v2_layer._reset_data_layers()
    yield
    framework.switch_main_program(old_main)
    framework.switch_startup_program(old_startup)
    scope_mod._global_scope = old_scope
