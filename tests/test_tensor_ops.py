"""Op tests: tensor manipulation (reference: test_concat_op.py,
test_split_op.py, test_reshape_op.py, test_transpose_op.py,
test_expand_op.py, test_pad_op.py, test_crop_op.py, test_gather_op.py,
test_scatter_op.py, test_top_k_op.py, test_multiplex_op.py,
test_fill_*.py, test_assign_*.py, test_one_hot, test_lookup_table_op.py,
test_shape_op, test_im2sequence, test_bilinear_tensor_product_op.py)."""

import numpy as np

from op_test import OpTest

RS = np.random.RandomState(11)


class TestConcat(OpTest):
    op_type = "concat"

    def test(self):
        xs = [("c%d" % i, RS.rand(2, 3).astype("float32"))
              for i in range(3)]
        self.inputs = {"X": xs}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": np.concatenate([a for _, a in xs], axis=1)}
        self.check_output()
        self.check_grad(["c0", "c2"], "Out")


class TestSplit(OpTest):
    op_type = "split"

    def test(self):
        x = RS.rand(4, 6).astype("float32")
        parts = np.split(x, 3, axis=1)
        self.inputs = {"X": x}
        self.attrs = {"num": 3, "axis": 1}
        self.outputs = {"Out": [("s%d" % i, p)
                                for i, p in enumerate(parts)]}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSplitSections(OpTest):
    op_type = "split"

    def test(self):
        x = RS.rand(4, 6).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"sections": [1, 2, 3], "axis": 1}
        self.outputs = {"Out": [("t0", x[:, :1]), ("t1", x[:, 1:3]),
                                ("t2", x[:, 3:])]}
        self.check_output()


class TestReshape(OpTest):
    op_type = "reshape"

    def test(self):
        x = RS.rand(2, 6).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"shape": [3, -1]}
        self.outputs = {"Out": x.reshape(3, 4)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestTranspose(OpTest):
    op_type = "transpose"

    def test(self):
        x = RS.rand(2, 3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"axis": [1, 2, 0]}
        self.outputs = {"Out": x.transpose(1, 2, 0)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestExpand(OpTest):
    op_type = "expand"

    def test(self):
        x = RS.rand(2, 3).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"expand_times": [2, 2]}
        self.outputs = {"Out": np.tile(x, (2, 2))}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestPad(OpTest):
    op_type = "pad"

    def test(self):
        x = RS.rand(2, 3).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"paddings": [1, 0, 0, 2], "pad_value": 0.5}
        self.outputs = {"Out": np.pad(x, [(1, 0), (0, 2)],
                                      constant_values=0.5)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestCrop(OpTest):
    op_type = "crop"

    def test(self):
        x = RS.rand(4, 5).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"offsets": [1, 2], "shape": [2, 3]}
        self.outputs = {"Out": x[1:3, 2:5]}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestGather(OpTest):
    op_type = "gather"

    def test(self):
        x = RS.rand(6, 3).astype("float32")
        idx = np.asarray([1, 3, 5], dtype="int32")
        self.inputs = {"X": x, "Index": idx}
        self.outputs = {"Out": x[idx]}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestScatter(OpTest):
    op_type = "scatter"

    def test(self):
        ref = RS.rand(5, 3).astype("float32")
        idx = np.asarray([1, 3], dtype="int32")
        upd = RS.rand(2, 3).astype("float32")
        out = ref.copy()
        out[idx] = upd
        self.inputs = {"Ref": ref, "Index": idx, "Updates": upd}
        self.outputs = {"Out": out}
        self.check_output()


class TestTopK(OpTest):
    op_type = "top_k"

    def test(self):
        x = RS.rand(4, 6).astype("float32")
        k = 2
        idx = np.argsort(-x, axis=1)[:, :k]
        vals = np.take_along_axis(x, idx, axis=1)
        self.inputs = {"X": x}
        self.attrs = {"k": k}
        self.outputs = {"Out": vals, "Indices": idx.astype("int64")}
        self.check_output()


class TestMultiplex(OpTest):
    op_type = "multiplex"

    def test(self):
        xs = [("m%d" % i, RS.rand(4, 3).astype("float32"))
              for i in range(3)]
        ids = RS.randint(0, 3, (4, 1)).astype("int32")
        out = np.stack([xs[ids[i, 0]][1][i] for i in range(4)])
        self.inputs = {"Ids": ids, "X": xs}
        self.outputs = {"Out": out}
        self.check_output()


class TestFillConstant(OpTest):
    op_type = "fill_constant"

    def test(self):
        self.inputs = {}
        self.attrs = {"shape": [3, 4], "value": 2.5, "dtype": "float32"}
        self.outputs = {"Out": np.full((3, 4), 2.5, "float32")}
        self.check_output()


class TestFillZerosLike(OpTest):
    op_type = "fill_zeros_like"

    def test(self):
        x = RS.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": np.zeros_like(x)}
        self.check_output()


class TestFillConstantBatchSizeLike(OpTest):
    op_type = "fill_constant_batch_size_like"

    def test(self):
        x = RS.rand(5, 4).astype("float32")
        self.inputs = {"Input": x}
        self.attrs = {"shape": [-1, 7], "value": 1.5, "dtype": "float32"}
        self.outputs = {"Out": np.full((5, 7), 1.5, "float32")}
        self.check_output()


class TestAssign(OpTest):
    op_type = "assign"

    def test(self):
        x = RS.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": x}
        self.check_output()


class TestOneHot(OpTest):
    op_type = "one_hot"

    def test(self):
        ids = RS.randint(0, 5, (4, 1)).astype("int64")
        out = np.zeros((4, 5), "float32")
        out[np.arange(4), ids.ravel()] = 1.0
        self.inputs = {"X": ids}
        self.attrs = {"depth": 5}
        self.outputs = {"Out": out}
        self.check_output()


class TestLookupTable(OpTest):
    op_type = "lookup_table"

    def test(self):
        table = RS.rand(10, 4).astype("float32")
        ids = RS.randint(0, 10, (5, 1)).astype("int64")
        self.inputs = {"W": table, "Ids": ids}
        self.outputs = {"Out": table[ids.ravel()]}
        self.check_output()
        self.check_grad(["W"], "Out")


class TestShapeOp(OpTest):
    op_type = "shape"

    def test(self):
        x = RS.rand(3, 4).astype("float32")
        self.inputs = {"Input": x}
        self.outputs = {"Out": np.asarray([3, 4], dtype="int64")}
        self.check_output()


class TestBilinearTensorProduct(OpTest):
    op_type = "bilinear_tensor_product"

    def test(self):
        b, m, n, o = 3, 4, 5, 2
        x = RS.rand(b, m).astype("float32")
        y = RS.rand(b, n).astype("float32")
        w = RS.rand(o, m, n).astype("float32")
        bias = RS.rand(1, o).astype("float32")
        out = np.einsum("bm,omn,bn->bo", x, w, y) + bias
        self.inputs = {"X": x, "Y": y, "Weight": w, "Bias": bias}
        self.outputs = {"Out": out.astype("float32")}
        self.check_output(atol=1e-4)
        self.check_grad(["X", "Y", "Weight", "Bias"], "Out",
                        max_relative_error=0.02)


class TestIm2Sequence(OpTest):
    op_type = "im2sequence"

    def test(self):
        # 1x1 kernel stride 1: output rows are just pixels scanned row-major
        x = RS.rand(1, 2, 3, 3).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"kernels": [1, 1], "strides": [1, 1],
                      "paddings": [0, 0, 0, 0]}
        out = x[0].transpose(1, 2, 0).reshape(9, 2)
        self.outputs = {"Out": (out, [[0, 9]])}
        self.check_output()


class TestFill(OpTest):
    op_type = "fill"

    def test(self):
        self.inputs = {}
        self.attrs = {"shape": [2, 3], "dtype": "float32",
                      "data": [1, 2, 3, 4, 5, 6]}
        self.outputs = {"Out": np.arange(1.0, 7.0, dtype="float32")
                        .reshape(2, 3)}
        self.check_output()
