"""Op-name parity audit against the reference's REGISTER_OP calls.

Extracts every forward op name registered in the reference's
paddle/operators/*.cc (recursively) and asserts each is either
registered here or on the explicit subsumed-by-design list.  Skips when
the reference checkout is not present (e.g. a user's CI).
"""

import glob
import os
import re

import pytest

import paddle_tpu  # noqa: F401 — registers every op
from paddle_tpu.ops import registered_ops

REFERENCE_OPS_DIR = "/root/reference/paddle/operators"

# capabilities delivered by the architecture rather than an op kernel:
# NCCL/send/recv are XLA GSPMD collectives + the native pserver
# transport; parallel_do is the dp mesh axis; rnn_memory_helper is the
# recurrent op's scan carries.
SUBSUMED = {
    "ncclAllReduce", "ncclBcast", "ncclReduce", "ncclInit", "nccl",
    "send", "recv", "parallel_do",
    "rnn_memory_helper", "rnn_memory_helper_grad",
    # macro parameter inside reduce_op.cc's kernel-registration helper,
    # not an op name
    "reduce_type",
}

# several reference ops register CPU kernels through a different macro
# than their op registration (e.g. CPU-only ops) — scan all of them
_PATTERNS = [re.compile(p) for p in (
    r"REGISTER_OP\s*\(\s*([a-z0-9_]+)",
    r"REGISTER_OP_WITHOUT_GRADIENT\s*\(\s*([a-z0-9_]+)",
    r"REGISTER_OP_EX\s*\(\s*([a-z0-9_]+)",
    r"REGISTER_OPERATOR\s*\(\s*([a-z0-9_]+)",
    r"REGISTER_OP_CPU_KERNEL\s*\(\s*([a-z0-9_]+)",
)]


def _reference_op_names():
    names = set()
    for path in glob.glob(os.path.join(REFERENCE_OPS_DIR, "**", "*.c*"),
                          recursive=True):
        with open(path, errors="ignore") as f:
            src = f.read()
        for pattern in _PATTERNS:
            for m in pattern.finditer(src):
                names.add(m.group(1))
    return names


@pytest.mark.skipif(not os.path.isdir(REFERENCE_OPS_DIR),
                    reason="reference checkout not present")
def test_every_reference_op_is_covered():
    ref = _reference_op_names()
    assert len(ref) > 200, "extraction regressed: %d names" % len(ref)
    ours = set(registered_ops())
    missing = sorted(n for n in ref
                     if n not in ours and n not in SUBSUMED
                     and not n.endswith("_grad"))
    assert not missing, (
        "reference ops with no registered equivalent and no "
        "subsumed-by-design entry: %s" % missing)
