"""Request-scoped tracing + fleet aggregation (obs.context, obs.tail,
obs.fleet, registry exemplars, coordinator heartbeat telemetry) — the
distributed-observability layer (docs/OBSERVABILITY.md "Request
tracing & exemplars" / "Fleet aggregation & stragglers",
docs/SERVING.md request-id/traceparent contract)."""

import http.client
import json
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.scope import Scope
from paddle_tpu.fluid import io as fluid_io
from paddle_tpu.obs import context as obs_context
from paddle_tpu.obs import fleet as obs_fleet
from paddle_tpu.obs import flight as obs_flight
from paddle_tpu.obs import registry as obs_registry
from paddle_tpu.obs import tail as obs_tail
from paddle_tpu.obs import telemetry as obs_tele
from paddle_tpu.resilience import faults as r_faults
from paddle_tpu.serving import (InferenceEngine, EngineConfig,
                                InferenceServer, ServerConfig)
from paddle_tpu.tools.obs_dump import (render_tail,
                                       validate_prometheus_text,
                                       validate_tail_dump)

TRACE_ID = "0af7651916cd43dd8448eb211c80319c"
PARENT_SPAN = "b7ad6b7169203331"
TRACEPARENT = "00-%s-%s-01" % (TRACE_ID, PARENT_SPAN)
# the injected-slow request gets its OWN trace id so exemplar/tail
# assertions can't be satisfied by the fast request
SLOW_TRACE_ID = "deadbeefcafe43dd8448eb211c80319c"
SLOW_TRACEPARENT = "00-%s-%s-01" % (SLOW_TRACE_ID, PARENT_SPAN)


# ---------------------------------------------------------------------------
# obs.context
# ---------------------------------------------------------------------------

def test_traceparent_parse_and_echo():
    ctx = obs_context.new_context(TRACEPARENT)
    assert ctx.trace_id == TRACE_ID
    assert ctx.parent_span_id == PARENT_SPAN
    assert ctx.span_id != PARENT_SPAN and len(ctx.span_id) == 16
    echo = ctx.traceparent()
    version, trace_id, span_id, flags = echo.split("-")
    assert (version, trace_id, span_id, flags) \
        == ("00", TRACE_ID, ctx.span_id, "01")


@pytest.mark.parametrize("header", [
    None, "", "garbage", "00-zz-yy-01",
    "00-" + "0" * 32 + "-" + PARENT_SPAN + "-01",   # all-zero trace
    "00-" + TRACE_ID + "-" + "0" * 16 + "-01",      # all-zero span
    "ff-" + TRACE_ID + "-" + PARENT_SPAN + "-01",   # reserved version
    "00-" + TRACE_ID[:30] + "-" + PARENT_SPAN + "-01",  # short trace
    # right length but not hex: int(x, 16) would accept '_' and '+'
    "00-" + TRACE_ID[:15] + "_" + TRACE_ID[16:] + "-" + PARENT_SPAN
    + "-01",
    "00-" + TRACE_ID + "-+" + PARENT_SPAN[1:] + "-01",
])
def test_malformed_traceparent_mints_fresh(header):
    assert obs_context.from_traceparent(header) is None
    ctx = obs_context.new_context(header)   # never fails the request
    assert len(ctx.trace_id) == 32 and ctx.parent_span_id is None


def test_span_nesting_and_cross_thread_record():
    ctx = obs_context.TraceContext()
    with obs_context.use(ctx):
        assert obs_context.current() is ctx
        with obs_context.span("outer"):
            with obs_context.span("inner"):
                pass
    assert obs_context.current() is None

    # worker thread: no binding, records against the carried ctx
    def worker():
        ctx.record("stage", time.time(), 0.001)

    t = threading.Thread(target=worker)
    t.start()
    t.join()

    roots = ctx.span_tree()
    by_name = {n["name"]: n for n in roots}
    # outer/inner nested; the cross-thread record roots at ctx.span_id
    # (no explicit root span recorded -> both are roots)
    assert "outer" in by_name and "stage" in by_name
    outer = by_name["outer"]
    assert [c["name"] for c in outer["children"]] == ["inner"]
    assert by_name["stage"]["parent_span_id"] == ctx.span_id


def test_context_span_list_is_bounded():
    ctx = obs_context.TraceContext(max_spans=4)
    for i in range(10):
        ctx.record("s%d" % i, time.time(), 0.0)
    assert len(ctx.span_records()) == 4
    assert ctx.dropped_spans == 6


# ---------------------------------------------------------------------------
# registry exemplars
# ---------------------------------------------------------------------------

def test_histogram_exemplar_lands_in_bucket_and_renders():
    reg = obs_registry.MetricsRegistry()
    h = reg.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0))
    h.observe(0.005)                       # no exemplar
    h.observe(0.05, exemplar=TRACE_ID)     # le=0.1 bucket
    h.observe(5.0, exemplar={"trace_id": "beef"})  # +Inf bucket
    ex = h.exemplars()
    assert set(ex) == {"0.1", "+Inf"}
    assert ex["0.1"][0] == {"trace_id": TRACE_ID}
    assert ex["0.1"][1] == 0.05
    # exemplars are opt-in (OpenMetrics negotiation): the default
    # text-format render must stay stock-scraper-parseable
    plain = reg.render_text()
    assert " # " not in plain
    text = reg.render_text(exemplars=True)
    bucket_line = [l for l in text.splitlines()
                   if 'le="0.1"' in l][0]
    assert bucket_line.startswith('lat_seconds_bucket{le="0.1"} 2 # ')
    assert 'trace_id="%s"' % TRACE_ID in bucket_line
    # un-exemplared buckets render exactly as before
    assert 'lat_seconds_bucket{le="0.01"} 1\n' in text + "\n"
    # the validator understands the exemplar suffix
    names = validate_prometheus_text(text)
    assert "lat_seconds_bucket" in names


def test_exemplar_last_write_wins_per_bucket():
    h = obs_registry.Histogram("h", buckets=(1.0,))
    h.observe(0.5, exemplar="first")
    h.observe(0.7, exemplar="second")
    assert h.exemplars()["1"][0] == {"trace_id": "second"}


# ---------------------------------------------------------------------------
# obs.tail
# ---------------------------------------------------------------------------

def test_tail_recorder_classify_capture_and_bound(tmp_path):
    rec = obs_tail.TailRecorder(capacity=2, slow_ms=10.0)
    ctx = obs_context.TraceContext()
    ctx.record("serving/request", time.time(), 0.02,
               span_id=ctx.span_id, parent_span_id=None)
    assert rec.offer(ctx, 5.0, status=200) is None      # fast + ok
    assert rec.offer(ctx, 50.0, status=200) == "slow"
    assert rec.offer(ctx, 5.0, status=504) == "error"   # 5xx
    assert rec.offer(ctx, 50.0, status=500) == "error"  # error outranks
    records = rec.records()
    assert len(records) == 2                            # ring bound
    assert [r["reason"] for r in records] == ["error", "error"]
    fam = obs_registry.get_registry().counter(
        "tail_captured_total", labelnames=("reason",))
    assert fam.labels(reason="slow").value == 1
    assert fam.labels(reason="error").value == 2

    path = str(tmp_path / "tail.json")
    rec.dump(path)
    doc = validate_tail_dump(path)
    assert doc["evicted"] == 1 and doc["total_captured"] == 3
    rendered = render_tail(path)
    assert "serving/request" in rendered
    assert ctx.trace_id in rendered


def test_tail_module_level_offer_noop_without_recorder():
    assert obs_tail.get_recorder() is None
    assert obs_tail.offer(obs_context.TraceContext(), 1e9, 500) is None
    rec = obs_tail.install(capacity=4, slow_ms=None)
    assert obs_tail.offer(obs_context.TraceContext(), 1e9, 200) is None
    assert obs_tail.offer(obs_context.TraceContext(), 1.0, 500) \
        == "error"
    assert len(rec.records()) == 1


# ---------------------------------------------------------------------------
# serving loopback: the full request-tracing contract
# ---------------------------------------------------------------------------

def _tiny_server(tmp_path, **cfg_kw):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[8], dtype="float32")
        probs = fluid.layers.fc(input=img, size=3, act="softmax")
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    program = fluid_io.prune_program(main, [probs])
    engine = InferenceEngine(program, ["img"], [probs], scope=scope,
                             config=EngineConfig(batch_buckets=[2]))
    return InferenceServer(engine, ServerConfig(port=0, **cfg_kw))


def _post(host, port, payload, headers=None):
    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("POST", "/v1/infer", json.dumps(payload),
                     dict({"Content-Type": "application/json"},
                          **(headers or {})))
        resp = conn.getresponse()
        return (resp.status, json.loads(resp.read()),
                dict(resp.getheaders()))
    finally:
        conn.close()


def _get(host, port, path, headers=None):
    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("GET", path, headers=headers or {})
        resp = conn.getresponse()
        return (resp.status, resp.read().decode(),
                dict(resp.getheaders()))
    finally:
        conn.close()


def test_server_request_tracing_contract(tmp_path):
    """Acceptance: request_id + traceparent echo on every reply
    (success, 400, 503), the slow request's exemplar in /metrics, its
    span tree in /debug/tail, and the JSONL access log."""
    log_path = str(tmp_path / "access.jsonl")
    server = _tiny_server(tmp_path, tail_slow_ms=50.0,
                          access_log=log_path).start()
    host, port = server.address
    payload = {"inputs": {"img": [[0.5] * 8]}}
    try:
        # 200: request_id minted, caller's trace continued
        st, body, headers = _post(host, port, payload,
                                  {"traceparent": TRACEPARENT})
        assert st == 200 and body["request_id"]
        assert headers["traceparent"].split("-")[1] == TRACE_ID
        assert headers["x-request-id"] == body["request_id"]

        # injected slow path -> exemplar + tail capture
        plan = r_faults.enable(seed=0)
        plan.inject("serving/run", "latency", latency_s=0.12, times=1)
        try:
            st, slow_body, _ = _post(
                host, port, payload,
                {"traceparent": SLOW_TRACEPARENT})
            assert st == 200
        finally:
            r_faults.disable()

        # plain 0.0.4 scrape: parseable by stock Prometheus, NO
        # exemplar syntax; OpenMetrics-negotiated scrape carries the
        # exemplar with the slow request's trace id + # EOF
        _, plain, plain_headers = _get(host, port, "/metrics")
        validate_prometheus_text(plain)
        assert " # " not in plain
        assert plain_headers["Content-Type"].startswith("text/plain")
        _, text, om_headers = _get(
            host, port, "/metrics",
            {"Accept": "application/openmetrics-text"})
        validate_prometheus_text(text)
        assert om_headers["Content-Type"].startswith(
            "application/openmetrics-text")
        assert text.endswith("# EOF\n")
        assert any("serving_total_seconds_bucket" in line
                   and " # " in line and SLOW_TRACE_ID in line
                   for line in text.splitlines()), text
        # OM counter families drop the _total suffix in TYPE lines
        assert "# TYPE serving_requests counter" in text
        assert "serving_requests_total " in text
        assert "# TYPE serving_requests_total counter" in plain

        st, tail_text, _ = _get(host, port, "/debug/tail")
        doc = validate_tail_dump(json.loads(tail_text))
        assert st == 200 and len(doc["requests"]) == 1
        captured = doc["requests"][0]
        assert captured["reason"] == "slow"
        assert captured["trace_id"] == SLOW_TRACE_ID
        assert captured["request_id"] == slow_body["request_id"]
        names = set()

        def walk(nodes):
            for n in nodes:
                names.add(n["name"])
                walk(n["children"])

        walk(captured["spans"])
        assert {"serving/request", "serving/admission",
                "serving/queue_wait", "serving/batch_assemble",
                "serving/pad_bucket", "serving/device_execute",
                "serving/split_serialize"} <= names, names
        # the tree is rooted at the single request span
        roots = captured["spans"]
        assert len(roots) == 1 and roots[0]["name"] \
            == "serving/request"

        # 400: bad input still answers with a request_id
        st, body400, _ = _post(host, port, {"inputs": {}})
        assert st == 400 and body400["request_id"]

        # 503 draining: rejection body carries a request_id too, but
        # the drain shed must NOT churn the tail ring (it would evict
        # the pre-drain captures an operator wants)
        server.draining = True
        st, body503, _ = _post(host, port, payload)
        server.draining = False
        assert st == 503 and body503["request_id"]
        assert len(server.tail.records()) == 1
    finally:
        server.shutdown()

    lines = [json.loads(l) for l in open(log_path)]
    assert len(lines) == 4
    assert [l["status"] for l in lines] == [200, 200, 400, 503]
    ok = lines[0]
    assert ok["request_id"] and ok["trace_id"] == TRACE_ID
    assert ok["batch"] == 1 and ok["bucket"] == 2
    assert all(isinstance(l["latency_ms"], float) for l in lines)


def test_server_shed_429_not_tail_captured(tmp_path):
    """Sustained overload sheds 429s continuously; capturing their
    empty span trees would churn the bounded ring and evict the
    captures that matter (same contract as drain 503s)."""
    from paddle_tpu.serving.batcher import QueueFullError

    server = _tiny_server(tmp_path, tail_slow_ms=50.0).start()
    try:
        def full(*a, **kw):
            raise QueueFullError("admission queue full (64 waiting)")

        server.batcher.submit_and_wait = full
        status, body = server.handle_infer(
            {"inputs": {"img": [[0.5] * 8]}})
        assert status == 429 and body["request_id"]
        assert server.tail.records() == []
    finally:
        server.shutdown()


def test_start_fleet_reporter_rejects_conflicting_args():
    from paddle_tpu.distributed import coordinator as coord

    rep = obs_fleet.FleetReporter("127.0.0.1:1", host="a",
                                  interval_s=60.0)
    assert coord._fleet_reporter[0] is None
    coord._fleet_reporter[0] = rep
    try:
        # argless call (init_multihost's path) returns the running one
        assert coord.start_fleet_reporter() is rep
        assert coord.start_fleet_reporter(master="127.0.0.1:1",
                                          host="a") is rep
        with pytest.raises(RuntimeError):
            coord.start_fleet_reporter(master="other:2")
        with pytest.raises(RuntimeError):
            coord.start_fleet_reporter(host="b")
    finally:
        coord._fleet_reporter[0] = None


def test_server_no_access_log_by_default(tmp_path):
    server = _tiny_server(tmp_path).start()
    try:
        assert server._access_log is None
        # in-process callers (no HTTP) get the same contract
        status, body = server.handle_infer(
            {"inputs": {"img": [[0.5] * 8]}})
        assert status == 200 and body["request_id"]
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# flight bundles name the active request
# ---------------------------------------------------------------------------

def test_flight_bundle_embeds_trace_context(tmp_path):
    rec = obs_flight.FlightRecorder(out_dir=str(tmp_path))
    ctx = obs_context.TraceContext()
    with obs_context.use(ctx):
        path = rec.dump(reason="test", exc=ValueError("boom"))
    doc = json.load(open(path))
    assert doc["trace_context"] == {"trace_id": ctx.trace_id,
                                    "span_id": ctx.span_id,
                                    "request_id": ctx.request_id}
    from paddle_tpu.tools.obs_dump import render_flight

    rendered = render_flight(path)
    assert ctx.request_id in rendered and ctx.trace_id in rendered

    # no context bound -> no trace_context key (pre-existing contract)
    path2 = rec.dump(reason="test2")
    assert "trace_context" not in json.load(open(path2))


# ---------------------------------------------------------------------------
# fleet aggregation
# ---------------------------------------------------------------------------

def _snap(host, step_s, n_steps, ts=1.0):
    return {"host": host, "ts": ts, "metrics": {
        "trainer_step_seconds{trainer=v2}_sum": step_s * n_steps,
        "trainer_step_seconds{trainer=v2}_count": n_steps,
        "executor_runs_total": n_steps}}


def test_fleet_aggregator_merge_and_straggler_gauges():
    agg = obs_fleet.FleetAggregator()
    agg.ingest(_snap("host0", 0.010, 10))
    agg.ingest(_snap("host1", 0.011, 10))
    agg.ingest(_snap("host2", 0.100, 10))   # the straggler
    report = agg.stragglers()
    assert report["flagged"] == ["host2"]
    assert report["step_ms"]["host2"] == pytest.approx(100.0)
    assert report["median_ms"] == pytest.approx(11.0)

    merged = agg.merged_samples()
    assert merged["executor_runs_total{host=host0}"] == 10
    assert "trainer_step_seconds{host=host2,trainer=v2}_sum" in merged

    reg = obs_registry.get_registry()
    straggler = reg.gauge("fleet_straggler", labelnames=("host",))
    assert straggler.labels(host="host2").value == 1
    assert straggler.labels(host="host0").value == 0
    assert reg.gauge("fleet_hosts").value == 3
    host_ms = reg.gauge("fleet_host_step_ms", labelnames=("host",))
    assert host_ms.labels(host="host2").value == pytest.approx(100.0)

    text = agg.render_text()
    assert "executor_runs_total{host=host1} 10" in text


def test_fleet_aggregator_newest_snapshot_wins_and_bad_ingest():
    agg = obs_fleet.FleetAggregator()
    agg.ingest(_snap("h", 0.2, 10, ts=2.0))
    agg.ingest(_snap("h", 0.1, 10, ts=1.0))   # older: ignored
    assert agg.step_times()["h"] == pytest.approx(200.0)
    with pytest.raises(ValueError):
        agg.ingest({"metrics": {}})            # no host
    # a host with no step data merges but never flags
    agg.ingest({"host": "idle", "ts": 3.0,
                "metrics": {"executor_runs_total": 1}})
    assert "idle" not in agg.step_times()
    assert agg.stragglers()["flagged"] == []   # single-step-host fleet


def test_fleet_reporter_push_collect_roundtrip():
    """Two workers push through a REAL master lease store; the
    aggregator pulls both, flags the inflated host, and a stopped
    reporter's snapshot disappears with its lease."""
    native = pytest.importorskip("paddle_tpu.native")
    master = native.Master()
    addr = "127.0.0.1:%d" % master.port
    try:
        # this process IS host "fast": run real (tiny) steps
        for _ in range(3):
            with obs_tele.step("fleet_test", examples=1):
                pass
        rep = obs_fleet.FleetReporter(addr, host="fast",
                                      interval_s=60.0)
        assert rep.push_once()
        # second push re-registers (update path)
        assert rep.push_once()

        # a corrupt push (valid JSON, not a dict) must be skipped,
        # not abort the collection
        bad_client = native.MasterClient("127.0.0.1", master.port)
        assert bad_client.register("/obs/bad", "42", 60000) is not None
        bad_client.close()

        agg = obs_fleet.FleetAggregator()
        agg.ingest(_snap("slow", 0.5, 4, ts=time.time()))
        assert agg.collect(addr) == 1
        assert set(agg.hosts()) == {"fast", "slow"}
        report = agg.stragglers()
        assert report["flagged"] == ["slow"], report

        rep.stop(unregister=True)
        agg2 = obs_fleet.FleetAggregator()
        assert agg2.collect(addr) == 0

        # dead-host expiry: the lease is gone, so a re-collect DROPS
        # the store-sourced host from the merged view (the directly
        # ingested one stays) and the re-publish retires its gauges
        assert agg.collect(addr) == 0
        assert agg.hosts() == ["slow"]
        agg.stragglers()
        host_ms = obs_registry.get_registry().gauge(
            "fleet_host_step_ms", labelnames=("host",))
        assert not any(s.get("labels", {}).get("host") == "fast"
                       for s in host_ms.samples())
        assert any(s.get("labels", {}).get("host") == "slow"
                   for s in host_ms.samples())
    finally:
        master.stop()


# ---------------------------------------------------------------------------
# coordinator heartbeat telemetry under injected faults
# ---------------------------------------------------------------------------

def test_service_lease_heartbeat_histogram_and_fault_survival():
    """Satellite acceptance: injected latency + io_error faults on the
    heartbeat path land in the new coordinator_heartbeat_seconds
    histogram / failure counter, and the lease SURVIVES the budgeted
    retry (the io_error is retried on a fresh connection within one
    beat, well inside the TTL)."""
    native = pytest.importorskip("paddle_tpu.native")
    from paddle_tpu.distributed import ElasticRegistry
    from paddle_tpu.distributed import coordinator as coordinator_mod

    ttl_ms = 600
    master = native.Master()
    lease = reg = None
    try:
        plan = r_faults.enable(seed=0)
        # beat 1 pays an injected 30ms stall; beat 2 an io_error
        lat = plan.inject("coordinator/heartbeat", "latency",
                          latency_s=0.03, times=1)
        ioe = plan.inject("coordinator/heartbeat", "io_error",
                          after=1, times=1)
        reg = ElasticRegistry("127.0.0.1", master.port)
        slot, lease = reg.register_pserver("h:1", 1, ttl_ms=ttl_ms)
        assert slot == 0
        # outlive several TTLs: both faults must have fired and been
        # absorbed without the lease lapsing
        deadline = time.time() + 10
        while (lat.fired < 1 or ioe.fired < 1) \
                and time.time() < deadline:
            time.sleep(0.02)
        time.sleep(ttl_ms / 1000.0 * 1.5)
        assert lat.fired == 1 and ioe.fired == 1
        assert not lease.lapsed
        assert reg.pservers() == {0: "h:1"}

        hist = obs_registry.get_registry().histogram(
            "coordinator_heartbeat_seconds",
            coordinator_mod.HEARTBEAT_SECONDS_BUCKETS)
        assert hist.count >= 3          # several beats landed
        assert hist.max >= 0.03         # the injected stall is visible
        failures = obs_registry.get_registry().counter(
            "coordinator_heartbeat_failures_total")
        assert failures.value == 1      # exactly the injected io_error
    finally:
        # the heartbeat thread MUST be joined before the master stops:
        # a keep-alive racing a dead master is undefined in the native
        # transport (same discipline as test_elastic_coordination)
        if lease is not None:
            lease.release()
        if reg is not None:
            reg.close()
        r_faults.disable()
        master.stop()


# ---------------------------------------------------------------------------
# mega_bench emits the platform-stale warning at emit time
# ---------------------------------------------------------------------------

def test_mega_bench_warns_on_stale_platform(tmp_path, monkeypatch,
                                            capsys):
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.syspath_prepend(os.path.join(repo, "scripts"))
    monkeypatch.syspath_prepend(repo)
    import bench
    import mega_bench

    store = {
        "resnet50-train-img/s|b128": {"metric": "resnet50",
                                      "platform": "tpu-stale",
                                      "value": 100.0},
        "vgg16-train-img/s|b64": {"metric": "vgg16",
                                  "platform": "tpu-v6e-1",
                                  "value": 50.0},
        "alex|skipped": {"metric": "alex", "skipped": "compile-timeout",
                         "platform": ""},
    }
    path = str(tmp_path / "BENCH.json")
    with open(path, "w") as f:
        json.dump(store, f)
    monkeypatch.setattr(bench, "_LAST_TPU_PATH", path)
    mega_bench._warn_stale_platform("headline-leg", set(store))
    out = capsys.readouterr().out
    assert "WARNING: leg headline-leg emitted platform-stale record" \
        in out
    assert "resnet50-train-img/s|b128" in out
    assert "vgg16" not in out          # fresh platform: no warning
    assert "alex|skipped" not in out   # skip markers exempt
