"""Book test: CIFAR-10 image classification (VGG + ResNet).

Parity target: reference tests/book/test_image_classification_train.py
— vgg16_bn_drop and resnet_cifar10 on CIFAR, a few real training
iterations, loss must improve.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.models import resnet_cifar10, vgg


def _train(model_fn, batch_size=16, iters=10, lr=0.01):
    image = fluid.layers.data(name="pixel", shape=[3, 32, 32],
                              dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    logits = model_fn(image)  # model heads emit logits
    cost = fluid.layers.softmax_with_cross_entropy(logits=logits,
                                                   label=label)
    avg_cost = fluid.layers.mean(x=cost)
    fluid.optimizer.Adam(learning_rate=lr).minimize(avg_cost)

    reader = paddle.batch(paddle.dataset.cifar.train10(),
                          batch_size=batch_size)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(feed_list=[image, label], place=place)
    exe.run(fluid.default_startup_program())

    losses = []
    for batch in reader():
        if len(batch) != batch_size:
            continue
        out, = exe.run(fluid.default_main_program(),
                       feed=feeder.feed(batch),
                       fetch_list=[avg_cost])
        losses.append(float(np.asarray(out).reshape(-1)[0]))
        if len(losses) >= iters:
            break
    assert np.isfinite(losses[-1]), losses
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses
    return losses


def test_image_classification_resnet():
    _train(lambda im: resnet_cifar10(im, class_dim=10, depth=20))


def test_image_classification_vgg():
    _train(lambda im: vgg(im, class_dim=10, depth=16, with_bn=True,
                          drop_rate=0.0))
