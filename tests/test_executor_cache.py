"""Executor program-cache keying: tokens must never alias across
program lifetimes (id() can be reused after GC; reference executors
key on the C++ ProgramDesc identity which has the same hazard)."""

import gc

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework


def _build_and_run(exe, scale):
    """Fresh program computing x * scale; same topology/version for
    every scale so only the cache token distinguishes them."""
    prog = framework.Program()
    startup = framework.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.scale(x=x, scale=float(scale))
    out, = exe.run(prog, feed={"x": np.ones((1, 4), np.float32)},
                   fetch_list=[y])
    return float(np.asarray(out).reshape(-1)[0])


def test_program_tokens_unique_across_gc():
    tokens = set()
    for _ in range(50):
        p = framework.Program()
        assert p._cache_token not in tokens
        tokens.add(p._cache_token)
        del p
        gc.collect()


def test_no_stale_cache_hit_after_program_rebuild():
    exe = fluid.Executor(fluid.CPUPlace())
    # interleave builds and drops so CPython is free to reuse object
    # ids; results must always track the live program's computation
    for scale in (2.0, 3.0, 5.0, 7.0):
        got = _build_and_run(exe, scale)
        assert got == scale, (got, scale)
        gc.collect()


def test_int64_feed_overflow_is_loud():
    """int64 feeds narrow to int32 (x64 off); out-of-range ids must
    raise instead of silently wrapping (embedding/beam id corruption)."""
    import pytest

    x = fluid.layers.data(name="ids", shape=[1], dtype="int64")
    y = fluid.layers.cast(x=x, dtype="float32")
    exe = fluid.Executor(fluid.CPUPlace())
    ok = exe.run(fluid.default_main_program(),
                 feed={"ids": np.array([[5]], np.int64)},
                 fetch_list=[y])
    assert float(np.asarray(ok[0]).reshape(-1)[0]) == 5.0
    with pytest.raises(OverflowError, match="int32 range"):
        exe.run(fluid.default_main_program(),
                feed={"ids": np.array([[2 ** 40]], np.int64)},
                fetch_list=[y])


def test_clone_gets_its_own_cache_slot():
    prog = framework.Program()
    startup = framework.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        fluid.layers.scale(x=x, scale=2.0)
    clone = prog.clone()
    assert clone._cache_token != prog._cache_token
