"""LoD rank-table machinery: rank table, tensor<->array conversion,
shrink_memory, reorder, split/merge + IfElse (reference:
lod_rank_table_op, lod_tensor_to_array_op, shrink_rnn_memory_op,
split_lod_tensor_op / merge_lod_tensor_op tests)."""

import numpy as np

import paddle_tpu.fluid as fluid

layers = fluid.layers


def _feed_seq(place, name_to_seqs, feed_vars):
    feeder = fluid.DataFeeder(place=place, feed_list=feed_vars)
    n = len(next(iter(name_to_seqs.values())))
    rows = [tuple(name_to_seqs[v.name][i] for v in feed_vars)
            for i in range(n)]
    return feeder.feed(rows)


def test_rank_table_array_roundtrip():
    x = layers.data(name="x", shape=[2], dtype="float32", lod_level=1)
    table = layers.lod_rank_table(x)
    arr = layers.lod_tensor_to_array(x, table)
    back = layers.array_to_lod_tensor(arr, table)
    reordered = layers.reorder_lod_tensor_by_rank(x, table)

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    seqs = [[[1, 1]],                       # len 1
            [[2, 2], [3, 3], [4, 4]],      # len 3
            [[5, 5], [6, 6]]]              # len 2
    feed = _feed_seq(place, {"x": seqs}, [x])
    out_back, out_reord = exe.run(
        fluid.default_main_program(), feed=feed,
        fetch_list=[back, reordered], return_numpy=False)

    vals = np.asarray(out_back.values)[:int(out_back.nvalid)]
    # rank order: seq1 (len3), seq2 (len2), seq0 (len1)
    expect = [[2, 2], [3, 3], [4, 4], [5, 5], [6, 6], [1, 1]]
    assert vals.tolist() == expect
    assert out_back.lod() == [[0, 3, 5, 6]]

    rvals = np.asarray(out_reord.values)[:int(out_reord.nvalid)]
    assert rvals.tolist() == expect


def test_shrink_memory():
    from paddle_tpu.core.rank_table import LoDRankTable
    from paddle_tpu.ops.registry import get_op_info

    table = LoDRankTable.from_lengths([1, 3, 2])
    kernel = get_op_info("shrink_rnn_memory").kernel
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = kernel(None, {"X": [x], "RankTable": [table],
                        "I": [np.array([1])]}, {})
    # active at step 1: lengths 3 and 2 -> prefix of 2 rows
    assert np.asarray(out["Out"][0]).shape == (2, 4)
    out0 = kernel(None, {"X": [x], "RankTable": [table],
                         "I": [np.array([2])]}, {})
    assert np.asarray(out0["Out"][0]).shape == (1, 4)


def test_ifelse_row_routing():
    """Rows with x < 0 negate, others pass through (reference IfElse
    pattern)."""
    x = layers.data(name="x", shape=[1], dtype="float32")
    zero = fluid.layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    cond = layers.less_than(x=x, y=zero)

    ie = layers.IfElse(cond)
    with ie.true_block():
        xt = ie.input(x)
        ie.output(fluid.layers.scale(x=xt, scale=-1.0))
    with ie.false_block():
        xf = ie.input(x)
        ie.output(xf)
    out = ie()

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    xs = np.array([[-1.0], [2.0], [-3.0], [4.0]], np.float32)
    res, = exe.run(fluid.default_main_program(), feed={"x": xs},
                   fetch_list=[out])
    np.testing.assert_allclose(np.asarray(res).reshape(-1),
                               [1.0, 2.0, 3.0, 4.0])


def test_split_merge_lod_roundtrip_ragged():
    """split_lod_tensor -> merge_lod_tensor over a ragged (LoD) input
    must reconstruct the original sequences in mask order (reference:
    merge_lod_tensor_op.cc supports LoD outputs)."""
    from paddle_tpu.core.ragged import RaggedTensor
    from paddle_tpu.ops.registry import get_op_info

    vals = np.arange(12, dtype=np.float32).reshape(6, 2)
    x = RaggedTensor(vals, [np.array([0, 1, 4, 6], np.int32)])  # lens 1,3,2
    mask = np.array([[1], [0], [1]], np.int32)

    split = get_op_info("split_lod_tensor").kernel
    merge = get_op_info("merge_lod_tensor").kernel
    parts = split(None, {"X": [x], "Mask": [mask]}, {})
    out_t, out_f = parts["OutTrue"][0], parts["OutFalse"][0]
    assert np.asarray(out_t.row_splits[-1]).tolist() == [0, 1, 3]
    assert np.asarray(out_f.row_splits[-1]).tolist() == [0, 3]

    merged = merge(None, {"X": [x], "Mask": [mask], "InTrue": [out_t],
                          "InFalse": [out_f]}, {})["Out"][0]
    assert isinstance(merged, RaggedTensor)
    np.testing.assert_allclose(np.asarray(merged.values), vals)
    assert np.asarray(merged.row_splits[-1]).tolist() == [0, 1, 4, 6]


def test_print_layer_passthrough(capsys):
    x = layers.data(name="x", shape=[2], dtype="float32")
    y = layers.Print(x, message="dbg")
    out = fluid.layers.mean(x=y)
    exe = fluid.Executor(fluid.CPUPlace())
    res, = exe.run(fluid.default_main_program(),
                   feed={"x": np.ones((2, 2), np.float32)},
                   fetch_list=[out])
    assert np.isclose(float(np.asarray(res).reshape(-1)[0]), 1.0)
