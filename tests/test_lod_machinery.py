"""LoD rank-table machinery: rank table, tensor<->array conversion,
shrink_memory, reorder, split/merge + IfElse (reference:
lod_rank_table_op, lod_tensor_to_array_op, shrink_rnn_memory_op,
split_lod_tensor_op / merge_lod_tensor_op tests)."""

import numpy as np

import paddle_tpu.fluid as fluid

layers = fluid.layers


def _feed_seq(place, name_to_seqs, feed_vars):
    feeder = fluid.DataFeeder(place=place, feed_list=feed_vars)
    n = len(next(iter(name_to_seqs.values())))
    rows = [tuple(name_to_seqs[v.name][i] for v in feed_vars)
            for i in range(n)]
    return feeder.feed(rows)


def test_rank_table_array_roundtrip():
    x = layers.data(name="x", shape=[2], dtype="float32", lod_level=1)
    table = layers.lod_rank_table(x)
    arr = layers.lod_tensor_to_array(x, table)
    back = layers.array_to_lod_tensor(arr, table)
    reordered = layers.reorder_lod_tensor_by_rank(x, table)

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    seqs = [[[1, 1]],                       # len 1
            [[2, 2], [3, 3], [4, 4]],      # len 3
            [[5, 5], [6, 6]]]              # len 2
    feed = _feed_seq(place, {"x": seqs}, [x])
    out_back, out_reord = exe.run(
        fluid.default_main_program(), feed=feed,
        fetch_list=[back, reordered], return_numpy=False)

    vals = np.asarray(out_back.values)[:int(out_back.nvalid)]
    # rank order: seq1 (len3), seq2 (len2), seq0 (len1)
    expect = [[2, 2], [3, 3], [4, 4], [5, 5], [6, 6], [1, 1]]
    assert vals.tolist() == expect
    assert out_back.lod() == [[0, 3, 5, 6]]

    rvals = np.asarray(out_reord.values)[:int(out_reord.nvalid)]
    assert rvals.tolist() == expect


def test_nested_rank_table_array_roundtrip():
    """lod_level-2 input through the rank-table machinery (reference:
    the nested-sequence mode of RecurrentGradientMachine.h:32 on
    lod_rank_table/lod_tensor_to_array): ranking at level 0 orders
    outer sequences by subsequence count, each array step is a lod-1
    batch of the t-th subsequences, and the roundtrip reassembles the
    nested tensor in rank order."""
    from paddle_tpu.core.ragged import RaggedTensor
    from paddle_tpu.ops.registry import get_op_info

    # doc A: 1 sentence [1,2]; doc B: 2 sentences [3],[4,5,6]
    vals = np.arange(1, 7, dtype=np.float32).reshape(6, 1)
    x = RaggedTensor(vals,
                     [np.array([0, 1, 3], np.int32),        # outer
                      np.array([0, 2, 3, 6], np.int32)])    # inner
    rank = get_op_info("lod_rank_table").kernel
    to_arr = get_op_info("lod_tensor_to_array").kernel
    to_lod = get_op_info("array_to_lod_tensor").kernel
    reorder = get_op_info("reorder_lod_tensor_by_rank").kernel

    table = rank(None, {"X": [x]}, {"level": 0})["Out"][0]
    # doc B (2 sentences) ranks first
    assert table.indices() == [1, 0]
    assert table.lengths() == [2, 1]

    steps = to_arr(None, {"X": [x], "RankTable": [table]}, {})["Out"][0]
    assert len(steps) == 2
    # step 0: first sentences of B then A -> [3] and [1,2]
    s0 = steps[0]
    assert np.asarray(s0.values).reshape(-1).tolist() == [3, 1, 2]
    assert np.asarray(s0.row_splits[-1]).tolist() == [0, 1, 3]
    # step 1: only B is still active -> [4,5,6]
    s1 = steps[1]
    assert np.asarray(s1.values).reshape(-1).tolist() == [4, 5, 6]

    back = to_lod(None, {"X": [steps], "RankTable": [table]},
                  {})["Out"][0]
    assert back.lod_level == 2
    assert np.asarray(back.values).reshape(-1).tolist() == \
        [3, 4, 5, 6, 1, 2]
    assert np.asarray(back.row_splits[0]).tolist() == [0, 2, 3]
    assert np.asarray(back.row_splits[1]).tolist() == [0, 1, 4, 6]

    reord = reorder(None, {"X": [x], "RankTable": [table]}, {})["Out"][0]
    assert np.asarray(reord.values).reshape(-1).tolist() == \
        [3, 4, 5, 6, 1, 2]
    assert np.asarray(reord.row_splits[0]).tolist() == [0, 2, 3]
    assert np.asarray(reord.row_splits[1]).tolist() == [0, 1, 4, 6]


def test_nested_dynamic_rnn():
    """Nested-sequence DynamicRNN, compiled form: sequence_unnest
    flattens subsequences into the batch, an inner DynamicRNN recurs
    over tokens within each subsequence, sequence_renest lifts the
    per-subsequence encodings to a sentence-level sequence, and an
    outer DynamicRNN recurs across subsequences — the full nested
    recurrence of the reference's RecurrentGradientMachine, with both
    loops as masked scans."""
    x = layers.data(name="x", shape=[2], dtype="float32", lod_level=2)

    inner, outer_ref = layers.sequence_unnest(x)
    drnn_in = layers.DynamicRNN()
    with drnn_in.block():
        tok = drnn_in.step_input(inner)
        mem = drnn_in.memory(shape=[2], batch_ref=tok, value=0.0)
        acc = layers.elementwise_add(x=mem, y=tok)
        drnn_in.update_memory(mem, acc)
        drnn_in.output(acc)
    token_sums = layers.sequence_last_step(drnn_in())  # per subsequence
    sent_seq = layers.sequence_renest(token_sums, outer_ref)

    drnn_out = layers.DynamicRNN()
    with drnn_out.block():
        sent = drnn_out.step_input(sent_seq)
        mem = drnn_out.memory(shape=[2], batch_ref=sent, value=0.0)
        acc = layers.elementwise_add(x=mem, y=sent)
        drnn_out.update_memory(mem, acc)
        drnn_out.output(acc)
    doc_enc = layers.sequence_last_step(drnn_out())  # [docs, 2]

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    # doc A: sentences [[1,1],[2,2]] and [[3,3]]; doc B: [[10,10]]
    docs = [[[[1, 1], [2, 2]], [[3, 3]]],
            [[[10, 10]]]]
    feeder = fluid.DataFeeder(place=place, feed_list=[x])
    feed = feeder.feed([(d,) for d in docs])
    out, = exe.run(fluid.default_main_program(), feed=feed,
                   fetch_list=[doc_enc])
    # doc A: sent sums (3,3) and (3,3) -> outer sum (6,6); doc B: (10,10)
    np.testing.assert_allclose(np.asarray(out),
                               [[6, 6], [10, 10]])


def test_shrink_memory():
    from paddle_tpu.core.rank_table import LoDRankTable
    from paddle_tpu.ops.registry import get_op_info

    table = LoDRankTable.from_lengths([1, 3, 2])
    kernel = get_op_info("shrink_rnn_memory").kernel
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = kernel(None, {"X": [x], "RankTable": [table],
                        "I": [np.array([1])]}, {})
    # active at step 1: lengths 3 and 2 -> prefix of 2 rows
    assert np.asarray(out["Out"][0]).shape == (2, 4)
    out0 = kernel(None, {"X": [x], "RankTable": [table],
                         "I": [np.array([2])]}, {})
    assert np.asarray(out0["Out"][0]).shape == (1, 4)


def test_ifelse_row_routing():
    """Rows with x < 0 negate, others pass through (reference IfElse
    pattern)."""
    x = layers.data(name="x", shape=[1], dtype="float32")
    zero = fluid.layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    cond = layers.less_than(x=x, y=zero)

    ie = layers.IfElse(cond)
    with ie.true_block():
        xt = ie.input(x)
        ie.output(fluid.layers.scale(x=xt, scale=-1.0))
    with ie.false_block():
        xf = ie.input(x)
        ie.output(xf)
    out = ie()

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    xs = np.array([[-1.0], [2.0], [-3.0], [4.0]], np.float32)
    res, = exe.run(fluid.default_main_program(), feed={"x": xs},
                   fetch_list=[out])
    np.testing.assert_allclose(np.asarray(res).reshape(-1),
                               [1.0, 2.0, 3.0, 4.0])


def test_split_merge_lod_roundtrip_ragged():
    """split_lod_tensor -> merge_lod_tensor over a ragged (LoD) input
    must reconstruct the original sequences in mask order (reference:
    merge_lod_tensor_op.cc supports LoD outputs)."""
    from paddle_tpu.core.ragged import RaggedTensor
    from paddle_tpu.ops.registry import get_op_info

    vals = np.arange(12, dtype=np.float32).reshape(6, 2)
    x = RaggedTensor(vals, [np.array([0, 1, 4, 6], np.int32)])  # lens 1,3,2
    mask = np.array([[1], [0], [1]], np.int32)

    split = get_op_info("split_lod_tensor").kernel
    merge = get_op_info("merge_lod_tensor").kernel
    parts = split(None, {"X": [x], "Mask": [mask]}, {})
    out_t, out_f = parts["OutTrue"][0], parts["OutFalse"][0]
    assert np.asarray(out_t.row_splits[-1]).tolist() == [0, 1, 3]
    assert np.asarray(out_f.row_splits[-1]).tolist() == [0, 3]

    merged = merge(None, {"X": [x], "Mask": [mask], "InTrue": [out_t],
                          "InFalse": [out_f]}, {})["Out"][0]
    assert isinstance(merged, RaggedTensor)
    np.testing.assert_allclose(np.asarray(merged.values), vals)
    assert np.asarray(merged.row_splits[-1]).tolist() == [0, 1, 4, 6]


def test_print_layer_passthrough(capsys):
    x = layers.data(name="x", shape=[2], dtype="float32")
    y = layers.Print(x, message="dbg")
    out = fluid.layers.mean(x=y)
    exe = fluid.Executor(fluid.CPUPlace())
    res, = exe.run(fluid.default_main_program(),
                   feed={"x": np.ones((2, 2), np.float32)},
                   fetch_list=[out])
    assert np.isclose(float(np.asarray(res).reshape(-1)[0]), 1.0)
