"""The examples/ scripts stay runnable.  Opt-in (RUN_EXAMPLES=1):
each spawns training subprocesses and takes minutes on CPU, so the
default suite only asserts they parse/import."""

import ast
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = sorted(
    os.path.join(_REPO, "examples", f)
    for f in os.listdir(os.path.join(_REPO, "examples"))
    if f.endswith(".py"))


@pytest.mark.parametrize("path", _EXAMPLES,
                         ids=[os.path.basename(p) for p in _EXAMPLES])
def test_example_parses(path):
    with open(path) as f:
        tree = ast.parse(f.read(), path)
    # every example must be directly runnable and document itself
    assert ast.get_docstring(tree), path
    if os.path.basename(path).startswith("trainer_config_"):
        # CLI config files are consumed by tools/trainer_cli.py, not
        # run directly — no __main__ guard expected
        return
    assert any(isinstance(n, ast.If) and "__main__" in ast.dump(n.test)
               for n in tree.body), "%s has no __main__ guard" % path


@pytest.mark.skipif(not os.environ.get("RUN_EXAMPLES"),
                    reason="spawns real training; set RUN_EXAMPLES=1")
@pytest.mark.parametrize("path,env", [
    ("train_image_classification.py", {"PASSES": "1", "BATCH": "16"}),
    ("scale_five_axes.py", {}),
    ("dist_pserver_fit_a_line.py", {}),
    ("ctr_deepfm_sparse.py", {"FEATURES": "512", "FIELDS": "4",
                              "BATCH": "64", "STEPS": "15"}),
    ("transformer_lm.py", {"STEPS": "60", "SEQ_LEN": "32"}),
], ids=lambda v: v if isinstance(v, str) else "")
def test_example_runs(path, env):
    full_env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                **env}
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", path)],
        env=full_env, timeout=900, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
