"""Layout-transform pass (fluid/data_transform.py).

Parity target: the reference's kernel-boundary layout transforms
(framework/data_transform.cc:29, data_layout_transform.cc) — here a
one-shot IR rewrite to NHWC with explicit transpose ops at layout
boundaries, applied before the backward so gradients follow.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.data_transform import convert_layout


def _build_convnet(with_bias=True):
    """conv(+bias) -> bn -> relu(fused in bn act) -> pool -> conv ->
    global-pool -> fc -> softmax CE loss over an 8x8 image."""
    image = fluid.layers.data(name="image", shape=[4, 3, 8, 8],
                              dtype="float32", append_batch_size=False)
    label = fluid.layers.data(name="label", shape=[4, 1], dtype="int64",
                              append_batch_size=False)
    t = fluid.layers.conv2d(input=image, num_filters=8, filter_size=3,
                            padding=1, act=None,
                            bias_attr=with_bias or False)
    t = fluid.layers.batch_norm(input=t, act="relu")
    t = fluid.layers.pool2d(input=t, pool_size=2, pool_stride=2)
    t = fluid.layers.conv2d(input=t, num_filters=16, filter_size=3,
                            padding=1, act="relu", bias_attr=False)
    t = fluid.layers.pool2d(input=t, pool_size=4, pool_type="avg",
                            global_pooling=True)
    logits = fluid.layers.fc(input=t, size=10, act=None)
    loss = fluid.layers.softmax_with_cross_entropy(logits, label)
    return fluid.layers.mean(loss)


def _feeds(seed=0):
    rs = np.random.RandomState(seed)
    return {"image": rs.rand(4, 3, 8, 8).astype(np.float32),
            "label": rs.randint(0, 10, size=(4, 1)).astype(np.int64)}


def _train_losses(to_nhwc, steps=3):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        avg_loss = _build_convnet()
        n_transforms = convert_layout(main) if to_nhwc else 0
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.1, momentum=0.9).minimize(avg_loss)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    losses = []
    for step in range(steps):
        out, = exe.run(main, feed=_feeds(step), fetch_list=[avg_loss],
                       scope=scope)
        losses.append(float(np.asarray(out).ravel()[0]))
    return losses, n_transforms, main


def test_nhwc_training_matches_nchw():
    """The rewritten program trains identically (transposes are exact;
    conv/pool numerics are the same math in a different dim order)."""
    ref, _, _ = _train_losses(to_nhwc=False)
    got, n, _ = _train_losses(to_nhwc=True)
    assert n > 0
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-5)


def test_transform_count_and_placement():
    """A straight conv chain crosses the layout boundary exactly twice:
    once into NHWC at the first conv, once back to NCHW at the fc —
    every capable/agnostic op in between rides the NHWC layout with no
    transform (the de-dup the reference gets from its transform
    cache)."""
    _, n, main = _train_losses(to_nhwc=True, steps=1)
    assert n == 2, n
    ops = [op.type for op in main.global_block().desc.ops]
    assert ops.count("transpose") >= 2
    # every conv/pool/bn now declares NHWC
    for op in main.global_block().desc.ops:
        if op.type in ("conv2d", "pool2d", "batch_norm"):
            assert op.attr("data_layout") == "NHWC", op


def test_bias_axis_rewritten():
    """The conv bias broadcast (elementwise_add axis=1 over [C]) must
    follow the channel to dim 3 under NHWC."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        _build_convnet(with_bias=True)
        convert_layout(main)
    block = main.global_block()

    def rank(op):
        return len(block.desc.var(op.input("X")[0]).shape)

    adds = [op for op in block.desc.ops
            if op.type == "elementwise_add" and op.attr("axis") is not None]
    assert adds, "expected a bias add"
    # the conv bias (4-D data input) follows the channel to dim 3; the
    # fc bias (2-D) is layout-free and must stay untouched
    assert [op.attr("axis") for op in adds if rank(op) == 4] == [3]
    assert [op.attr("axis") for op in adds if rank(op) == 2] == [1]


def test_desc_shapes_follow_layout():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        _build_convnet()
        convert_layout(main)
    block = main.global_block()
    conv_out = next(op.output("Output")[0]
                    for op in block.desc.ops if op.type == "conv2d")
    assert block.desc.var(conv_out).shape == (4, 8, 8, 8)  # NHWC: C last
    # the rewritten program still serializes (golden-program contract)
    main.desc.serialize_to_string()


def test_refuses_built_backward():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        avg_loss = _build_convnet()
        fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_loss)
    with pytest.raises(ValueError, match="append_backward"):
        convert_layout(main)
