"""Op tests: conv/pool/norm family (reference: test_conv2d_op.py,
test_conv2d_transpose_op.py, test_conv3d_op.py, test_pool2d_op.py,
test_pool3d_op.py, test_pool_max_op.py, test_batch_norm_op.py,
test_layer_norm_op, test_lrn_op.py, test_maxout_op.py, test_dropout_op.py,
test_norm_op.py)."""

import numpy as np

from op_test import OpTest

RS = np.random.RandomState(3)


def _conv2d_ref(x, w, stride, pad, dilation=(1, 1), groups=1):
    n, cin, h, ww = x.shape
    cout, cin_g, kh, kw = w.shape
    eh = (kh - 1) * dilation[0] + 1
    ew = (kw - 1) * dilation[1] + 1
    oh = (h + 2 * pad[0] - eh) // stride[0] + 1
    ow = (ww + 2 * pad[1] - ew) // stride[1] + 1
    xp = np.pad(x, [(0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])])
    out = np.zeros((n, cout, oh, ow), x.dtype)
    cpg = cout // groups
    for b in range(n):
        for oc in range(cout):
            g = oc // cpg
            for i in range(oh):
                for j in range(ow):
                    patch = xp[b,
                               g * cin_g:(g + 1) * cin_g,
                               i * stride[0]:i * stride[0] + eh:dilation[0],
                               j * stride[1]:j * stride[1] + ew:dilation[1]]
                    out[b, oc, i, j] = (patch * w[oc]).sum()
    return out


class TestConv2d(OpTest):
    op_type = "conv2d"

    def test(self):
        x = RS.rand(2, 3, 5, 5).astype("float32")
        w = RS.rand(4, 3, 3, 3).astype("float32")
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [1, 1], "paddings": [1, 1]}
        self.outputs = {"Output": _conv2d_ref(x, w, (1, 1), (1, 1))}
        self.check_output(atol=1e-4)
        self.check_grad(["Input", "Filter"], "Output",
                        max_relative_error=0.03)


class TestConv2dStrideGroups(OpTest):
    op_type = "conv2d"

    def test(self):
        x = RS.rand(1, 4, 6, 6).astype("float32")
        w = RS.rand(4, 2, 3, 3).astype("float32")
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [2, 2], "paddings": [0, 0], "groups": 2}
        self.outputs = {"Output": _conv2d_ref(x, w, (2, 2), (0, 0),
                                              groups=2)}
        self.check_output(atol=1e-4)


class TestConv2dDilation(OpTest):
    op_type = "conv2d"

    def test(self):
        x = RS.rand(1, 2, 7, 7).astype("float32")
        w = RS.rand(3, 2, 3, 3).astype("float32")
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [1, 1], "paddings": [2, 2],
                      "dilations": [2, 2]}
        self.outputs = {"Output": _conv2d_ref(x, w, (1, 1), (2, 2),
                                              dilation=(2, 2))}
        self.check_output(atol=1e-4)


class TestConv2dTranspose(OpTest):
    op_type = "conv2d_transpose"

    def test(self):
        x = RS.rand(1, 3, 4, 4).astype("float32")
        w = RS.rand(3, 2, 3, 3).astype("float32")  # [in_c, out_c, kh, kw]
        stride, pad = (2, 2), (1, 1)
        n, cin, h, ww = x.shape
        _, cout, kh, kw = w.shape
        oh = (h - 1) * stride[0] - 2 * pad[0] + kh
        ow = (ww - 1) * stride[1] - 2 * pad[1] + kw
        out = np.zeros((n, cout, oh + 2 * pad[0], ow + 2 * pad[1]),
                       x.dtype)
        for b in range(n):
            for ic in range(cin):
                for i in range(h):
                    for j in range(ww):
                        out[b, :, i * stride[0]:i * stride[0] + kh,
                            j * stride[1]:j * stride[1] + kw] += \
                            x[b, ic, i, j] * w[ic]
        out = out[:, :, pad[0]:pad[0] + oh, pad[1]:pad[1] + ow]
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": list(stride), "paddings": list(pad)}
        self.outputs = {"Output": out}
        self.check_output(atol=1e-4)
        self.check_grad(["Input", "Filter"], "Output",
                        max_relative_error=0.03)


class TestConv3d(OpTest):
    op_type = "conv3d"

    def test(self):
        x = RS.rand(1, 2, 4, 4, 4).astype("float32")
        w = RS.rand(3, 2, 2, 2, 2).astype("float32")
        n, cin, d, h, ww = x.shape
        cout = 3
        out = np.zeros((1, 3, 3, 3, 3), "float32")
        for oc in range(cout):
            for i in range(3):
                for j in range(3):
                    for k in range(3):
                        patch = x[0, :, i:i + 2, j:j + 2, k:k + 2]
                        out[0, oc, i, j, k] = (patch * w[oc]).sum()
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [1, 1, 1], "paddings": [0, 0, 0]}
        self.outputs = {"Output": out}
        self.check_output(atol=1e-4)


def _pool2d_ref(x, ksize, stride, pad, ptype, exclusive=True):
    n, c, h, w = x.shape
    oh = (h + 2 * pad[0] - ksize[0]) // stride[0] + 1
    ow = (w + 2 * pad[1] - ksize[1]) // stride[1] + 1
    out = np.zeros((n, c, oh, ow), x.dtype)
    for i in range(oh):
        for j in range(ow):
            h0 = i * stride[0] - pad[0]
            w0 = j * stride[1] - pad[1]
            h1, w1 = h0 + ksize[0], w0 + ksize[1]
            h0c, w0c = max(h0, 0), max(w0, 0)
            h1c, w1c = min(h1, h), min(w1, w)
            patch = x[:, :, h0c:h1c, w0c:w1c]
            if ptype == "max":
                out[:, :, i, j] = patch.max(axis=(2, 3))
            else:
                div = (h1c - h0c) * (w1c - w0c) if exclusive \
                    else ksize[0] * ksize[1]
                out[:, :, i, j] = patch.sum(axis=(2, 3)) / div
    return out


class TestPool2dMax(OpTest):
    op_type = "pool2d"

    def test(self):
        x = RS.rand(2, 3, 5, 5).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "max", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0]}
        self.outputs = {"Out": _pool2d_ref(x, [2, 2], [2, 2], [0, 0],
                                           "max")}
        self.check_output()
        self.check_grad(["X"], "Out", max_relative_error=0.05)


class TestPool2dAvgPadded(OpTest):
    op_type = "pool2d"

    def test(self):
        x = RS.rand(1, 2, 5, 5).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "ksize": [3, 3],
                      "strides": [2, 2], "paddings": [1, 1]}
        self.outputs = {"Out": _pool2d_ref(x, [3, 3], [2, 2], [1, 1],
                                           "avg")}
        self.check_output()
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestPool2dGlobal(OpTest):
    op_type = "pool2d"

    def test(self):
        x = RS.rand(2, 3, 4, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "global_pooling": True,
                      "ksize": [1, 1]}
        self.outputs = {"Out": x.mean(axis=(2, 3), keepdims=True)}
        self.check_output()


class TestPool3d(OpTest):
    op_type = "pool3d"

    def test(self):
        x = RS.rand(1, 2, 4, 4, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "max", "ksize": [2, 2, 2],
                      "strides": [2, 2, 2], "paddings": [0, 0, 0]}
        out = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).max(axis=(3, 5, 7))
        self.outputs = {"Out": out}
        self.check_output()


class TestMaxPool2dWithIndex(OpTest):
    op_type = "max_pool2d_with_index"

    def test(self):
        x = RS.rand(1, 2, 4, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"ksize": [2, 2], "strides": [2, 2],
                      "paddings": [0, 0]}
        patches = x.reshape(1, 2, 2, 2, 2, 2).transpose(0, 1, 2, 4, 3, 5)
        flat = patches.reshape(1, 2, 2, 2, 4)
        out = flat.max(axis=-1)
        self.outputs = {"Out": out}
        self.check_output(no_check_set=("Mask",))


class TestBatchNormInfer(OpTest):
    op_type = "batch_norm"

    def test(self):
        c = 3
        x = RS.rand(2, c, 4, 4).astype("float32")
        scale = RS.rand(c).astype("float32") + 0.5
        bias = RS.rand(c).astype("float32")
        mean = RS.rand(c).astype("float32")
        var = RS.rand(c).astype("float32") + 0.5
        eps = 1e-5
        ref = (x - mean.reshape(1, c, 1, 1)) / np.sqrt(
            var.reshape(1, c, 1, 1) + eps) * scale.reshape(1, c, 1, 1) \
            + bias.reshape(1, c, 1, 1)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": mean, "Variance": var}
        self.attrs = {"is_test": True, "epsilon": eps}
        self.outputs = {"Y": ref}
        self.check_output(atol=1e-4)


class TestBatchNormTrain(OpTest):
    op_type = "batch_norm"

    def test(self):
        c = 3
        x = RS.rand(4, c, 3, 3).astype("float32")
        scale = np.ones(c, "float32")
        bias = np.zeros(c, "float32")
        mean = np.zeros(c, "float32")
        var = np.ones(c, "float32")
        eps = 1e-5
        mu = x.mean(axis=(0, 2, 3))
        sig2 = x.var(axis=(0, 2, 3))
        ref = (x - mu.reshape(1, c, 1, 1)) / np.sqrt(
            sig2.reshape(1, c, 1, 1) + eps)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": mean, "Variance": var}
        self.attrs = {"is_test": False, "epsilon": eps, "momentum": 0.9}
        self.outputs = {"Y": ref}
        self.check_output(
            atol=1e-4,
            no_check_set=("MeanOut", "VarianceOut", "SavedMean",
                          "SavedVariance"))


class TestBatchNormTrainUnshiftedStats(OpTest):
    """FLAGS_bn_shifted_stats=0 (the perf A/B knob) must compute the
    same statistics via the plain one-pass form."""
    op_type = "batch_norm"

    def test(self):
        from paddle_tpu.utils import flags

        c = 3
        x = RS.rand(4, c, 3, 3).astype("float32")
        mu = x.mean(axis=(0, 2, 3))
        sig2 = x.var(axis=(0, 2, 3))
        eps = 1e-5
        ref = (x - mu.reshape(1, c, 1, 1)) / np.sqrt(
            sig2.reshape(1, c, 1, 1) + eps)
        self.inputs = {"X": x, "Scale": np.ones(c, "float32"),
                       "Bias": np.zeros(c, "float32"),
                       "Mean": np.zeros(c, "float32"),
                       "Variance": np.ones(c, "float32")}
        self.attrs = {"is_test": False, "epsilon": eps, "momentum": 0.9}
        self.outputs = {"Y": ref}
        prev = flags.get_flag("bn_shifted_stats")
        flags.set_flag("bn_shifted_stats", False)
        try:
            self.check_output(
                atol=1e-4,
                no_check_set=("MeanOut", "VarianceOut", "SavedMean",
                              "SavedVariance"))
        finally:
            flags.set_flag("bn_shifted_stats", prev)


class TestBatchNormGradTrain(OpTest):
    """The closed-form backward (A*dy + B*x + D) against central
    differences — training mode, batch statistics."""
    op_type = "batch_norm"

    def test(self):
        rs = np.random.RandomState(19)
        c = 3
        x = rs.rand(4, c, 3, 3).astype("float32")
        scale = rs.rand(c).astype("float32") + 0.5
        bias = rs.rand(c).astype("float32")
        eps = 1e-5
        mu = x.mean(axis=(0, 2, 3)).reshape(1, c, 1, 1)
        sig2 = x.var(axis=(0, 2, 3)).reshape(1, c, 1, 1)
        ref = (x - mu) / np.sqrt(sig2 + eps) * \
            scale.reshape(1, c, 1, 1) + bias.reshape(1, c, 1, 1)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": np.zeros(c, "float32"),
                       "Variance": np.ones(c, "float32")}
        self.attrs = {"is_test": False, "epsilon": eps,
                      "momentum": 0.9}
        self.outputs = {"Y": ref}
        # BN's f32 forward sums ~100 near-cancelling terms, so
        # central differences carry ~5e-4 of rounding noise at the
        # default delta; widen the probe step and the tiny-element
        # floor (the formula itself is autodiff-checked to 4e-7)
        self.check_grad(["X", "Scale", "Bias"], "Y",
                        max_relative_error=0.03, numeric_delta=1e-2,
                        atol=5e-3)


class TestBatchNormGradInfer(OpTest):
    """Test mode: dx = dy * scale * rsqrt(var+eps) — running stats
    carry no gradient."""
    op_type = "batch_norm"

    def test(self):
        rs = np.random.RandomState(23)
        c = 3
        x = rs.rand(2, c, 4, 4).astype("float32")
        scale = rs.rand(c).astype("float32") + 0.5
        bias = rs.rand(c).astype("float32")
        mean = rs.rand(c).astype("float32")
        var = rs.rand(c).astype("float32") + 0.5
        eps = 1e-5
        ref = (x - mean.reshape(1, c, 1, 1)) / np.sqrt(
            var.reshape(1, c, 1, 1) + eps) * scale.reshape(1, c, 1, 1) \
            + bias.reshape(1, c, 1, 1)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": mean, "Variance": var}
        self.attrs = {"is_test": True, "epsilon": eps}
        self.outputs = {"Y": ref}
        # BN's f32 forward sums ~100 near-cancelling terms, so
        # central differences carry ~5e-4 of rounding noise at the
        # default delta; widen the probe step and the tiny-element
        # floor (the formula itself is autodiff-checked to 4e-7)
        self.check_grad(["X", "Scale", "Bias"], "Y",
                        max_relative_error=0.03, numeric_delta=1e-2,
                        atol=5e-3)


class TestBatchNormGradNHWC(OpTest):
    """The layout-capable grad: channel statistics over NHWC."""
    op_type = "batch_norm"

    def test(self):
        rs = np.random.RandomState(29)
        c = 3
        x = rs.rand(4, 3, 3, c).astype("float32")
        scale = rs.rand(c).astype("float32") + 0.5
        bias = rs.rand(c).astype("float32")
        eps = 1e-5
        mu = x.mean(axis=(0, 1, 2))
        sig2 = x.var(axis=(0, 1, 2))
        ref = (x - mu) / np.sqrt(sig2 + eps) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": np.zeros(c, "float32"),
                       "Variance": np.ones(c, "float32")}
        self.attrs = {"is_test": False, "epsilon": eps,
                      "momentum": 0.9, "data_layout": "NHWC"}
        self.outputs = {"Y": ref}
        # BN's f32 forward sums ~100 near-cancelling terms, so
        # central differences carry ~5e-4 of rounding noise at the
        # default delta; widen the probe step and the tiny-element
        # floor (the formula itself is autodiff-checked to 4e-7)
        self.check_grad(["X", "Scale", "Bias"], "Y",
                        max_relative_error=0.03, numeric_delta=1e-2,
                        atol=5e-3)


class TestLayerNorm(OpTest):
    op_type = "layer_norm"

    def test(self):
        x = RS.rand(4, 6).astype("float32")
        scale = RS.rand(6).astype("float32") + 0.5
        bias = RS.rand(6).astype("float32")
        eps = 1e-5
        mu = x.mean(axis=1, keepdims=True)
        sig2 = x.var(axis=1, keepdims=True)
        ref = (x - mu) / np.sqrt(sig2 + eps) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"epsilon": eps}
        self.outputs = {"Y": ref}
        self.check_output(atol=1e-4, no_check_set=("Mean", "Variance"))


class TestLayerNormGrad(OpTest):
    """Closed-form LN backward vs central differences (same noise
    considerations as the BN grad checks above)."""
    op_type = "layer_norm"

    def test(self):
        rs = np.random.RandomState(41)
        x = rs.rand(4, 6).astype("float32")
        scale = rs.rand(6).astype("float32") + 0.5
        bias = rs.rand(6).astype("float32")
        eps = 1e-5
        mu = x.mean(axis=1, keepdims=True)
        sig2 = x.var(axis=1, keepdims=True)
        ref = (x - mu) / np.sqrt(sig2 + eps) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"epsilon": eps}
        self.outputs = {"Y": ref}
        self.check_grad(["X", "Scale", "Bias"], "Y",
                        max_relative_error=0.03, numeric_delta=1e-2,
                        atol=5e-3)


class TestLayerNormGradNoAffine(OpTest):
    """Optional Scale/Bias absent: only X@GRAD is produced."""
    op_type = "layer_norm"

    def test(self):
        # own seed + wider rows: central differences at delta=1e-2 on
        # 4-element normalization rows carry ~0.08 truncation error,
        # and the shared module RandomState made the draw depend on
        # which tests ran first (flaked under pytest -k subsets)
        rs = np.random.RandomState(11)
        x = rs.rand(3, 3, 8).astype("float32")
        eps = 1e-5
        x2 = x.reshape(9, 8)
        mu = x2.mean(axis=1, keepdims=True)
        sig2 = x2.var(axis=1, keepdims=True)
        ref = ((x2 - mu) / np.sqrt(sig2 + eps)).reshape(x.shape)
        self.inputs = {"X": x}
        self.attrs = {"epsilon": eps, "begin_norm_axis": 2}
        self.outputs = {"Y": ref}
        self.check_grad(["X"], "Y", max_relative_error=0.03,
                        numeric_delta=1e-3, atol=5e-3)


class TestLRN(OpTest):
    op_type = "lrn"

    def test(self):
        x = RS.rand(2, 4, 3, 3).astype("float32")
        n, k, alpha, beta = 5, 2.0, 1e-4, 0.75
        sq = x ** 2
        c = x.shape[1]
        den = np.zeros_like(x)
        for i in range(c):
            lo, hi = max(0, i - n // 2), min(c, i + n // 2 + 1)
            den[:, i] = k + alpha * sq[:, lo:hi].sum(axis=1)
        ref = x / den ** beta
        self.inputs = {"X": x}
        self.attrs = {"n": n, "k": k, "alpha": alpha, "beta": beta}
        self.outputs = {"Out": ref}
        self.check_output(atol=1e-4, no_check_set=("MidOut",))


class TestMaxout(OpTest):
    op_type = "maxout"

    def test(self):
        x = RS.rand(2, 6, 3, 3).astype("float32")
        groups = 3
        ref = x.reshape(2, 2, 3, 3, 3).max(axis=2)
        self.inputs = {"X": x}
        self.attrs = {"groups": groups}
        self.outputs = {"Out": ref}
        self.check_output()


class TestNormOp(OpTest):
    op_type = "norm"

    def test(self):
        x = RS.rand(3, 4).astype("float32") + 0.1
        self.inputs = {"X": x}
        self.attrs = {"axis": 1, "epsilon": 1e-10}
        norm = np.sqrt((x ** 2).sum(axis=1, keepdims=True) + 1e-10)
        self.outputs = {"Out": x / norm}
        self.check_output(atol=1e-5, no_check_set=("Norm",))


class TestDropoutInfer(OpTest):
    op_type = "dropout"

    def test(self):
        x = RS.rand(4, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"dropout_prob": 0.35, "is_test": True}
        self.outputs = {"Out": x * (1 - 0.35)}
        self.check_output(no_check_set=("Mask",))


class TestBatchNormGradSavedStats(OpTest):
    """With the full output set declared (as production programs built
    by fluid.layers.batch_norm do), the grad op receives the forward's
    SavedMean/SavedVariance as O@-slots and must reuse them instead of
    re-sweeping X — and still match central differences."""
    op_type = "batch_norm"

    def test(self):
        rs = np.random.RandomState(31)
        c = 3
        x = rs.rand(4, c, 3, 3).astype("float32")
        scale = rs.rand(c).astype("float32") + 0.5
        bias = rs.rand(c).astype("float32")
        mean = np.zeros(c, "float32")
        var = np.ones(c, "float32")
        eps = 1e-5
        mu = x.mean(axis=(0, 2, 3))
        sig2 = x.var(axis=(0, 2, 3))
        ref = (x - mu.reshape(1, c, 1, 1)) / np.sqrt(
            sig2.reshape(1, c, 1, 1) + eps) * scale.reshape(1, c, 1, 1) \
            + bias.reshape(1, c, 1, 1)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": mean, "Variance": var}
        self.attrs = {"is_test": False, "epsilon": eps, "momentum": 0.9}
        self.outputs = {"Y": ref,
                        "MeanOut": 0.9 * mean + 0.1 * mu,
                        "VarianceOut": 0.9 * var + 0.1 * sig2,
                        "SavedMean": mu, "SavedVariance": sig2}
        self.check_grad(["X", "Scale", "Bias"], "Y",
                        max_relative_error=0.03, numeric_delta=1e-2,
                        atol=5e-3)


class TestBatchNormGradThroughStats(OpTest):
    """Gradient flowing ONLY through the statistic outputs (OG@Y is
    empty): the closed-form backward must fold the SavedMean/
    SavedVariance/MeanOut cotangents into dx instead of crashing or
    dropping them (the generic vjp it replaced handled this case)."""
    op_type = "batch_norm"

    def test(self):
        rs = np.random.RandomState(37)
        c = 2
        x = rs.rand(3, c, 2, 2).astype("float32")
        scale = rs.rand(c).astype("float32") + 0.5
        bias = rs.rand(c).astype("float32")
        mean = np.zeros(c, "float32")
        var = np.ones(c, "float32")
        eps = 1e-5
        mu = x.mean(axis=(0, 2, 3))
        sig2 = x.var(axis=(0, 2, 3))
        ref = (x - mu.reshape(1, c, 1, 1)) / np.sqrt(
            sig2.reshape(1, c, 1, 1) + eps) * scale.reshape(1, c, 1, 1) \
            + bias.reshape(1, c, 1, 1)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": mean, "Variance": var}
        self.attrs = {"is_test": False, "epsilon": eps, "momentum": 0.9}
        self.outputs = {"Y": ref,
                        "MeanOut": 0.9 * mean + 0.1 * mu,
                        "VarianceOut": 0.9 * var + 0.1 * sig2,
                        "SavedMean": mu, "SavedVariance": sig2}
        self.check_grad(["X"], ["SavedMean", "SavedVariance", "MeanOut"],
                        max_relative_error=0.03, numeric_delta=1e-2,
                        atol=5e-3)


class TestLayerNormGradSavedStats(OpTest):
    """Full output set declared: the LN backward must reuse the
    forward's O@Mean/O@Variance (not re-reduce X) and stay correct."""
    op_type = "layer_norm"

    def test(self):
        rs = np.random.RandomState(43)
        x = rs.rand(4, 6).astype("float32")
        scale = rs.rand(6).astype("float32") + 0.5
        bias = rs.rand(6).astype("float32")
        eps = 1e-5
        mu = x.mean(axis=1)
        sig2 = x.var(axis=1)
        ref = (x - mu[:, None]) / np.sqrt(sig2[:, None] + eps) * scale \
            + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"epsilon": eps}
        self.outputs = {"Y": ref, "Mean": mu, "Variance": sig2}
        self.check_grad(["X", "Scale", "Bias"], "Y",
                        max_relative_error=0.03, numeric_delta=1e-2,
                        atol=5e-3)


class TestLayerNormGradThroughStats(OpTest):
    """Gradient only through Mean/Variance (OG@Y empty): the per-row
    cotangents fold into dx; Scale/Bias get zero grads but no crash."""
    op_type = "layer_norm"

    def test(self):
        rs = np.random.RandomState(47)
        x = rs.rand(4, 6).astype("float32")
        scale = rs.rand(6).astype("float32") + 0.5
        bias = rs.rand(6).astype("float32")
        eps = 1e-5
        mu = x.mean(axis=1)
        sig2 = x.var(axis=1)
        ref = (x - mu[:, None]) / np.sqrt(sig2[:, None] + eps) * scale \
            + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"epsilon": eps}
        self.outputs = {"Y": ref, "Mean": mu, "Variance": sig2}
        self.check_grad(["X"], ["Mean", "Variance"],
                        max_relative_error=0.03, numeric_delta=1e-2,
                        atol=5e-3)


def test_bn_grad_reads_saved_stats_slot():
    """The saved-stats fast path must actually READ O@SavedMean/
    O@SavedVariance: feeding deliberately wrong saved stats must change
    dx vs the recompute fallback (guards the slot name against the
    O@-prefix regression this test was written for)."""
    import jax.numpy as jnp
    from paddle_tpu.ops import registry

    kern = registry.get_op_info("batch_norm").grad_kernel
    rs = np.random.RandomState(53)
    x = jnp.asarray(rs.rand(2, 3, 2, 2).astype("float32"))
    dy = jnp.asarray(rs.rand(2, 3, 2, 2).astype("float32"))
    scale = jnp.ones(3, jnp.float32)
    base = {"X": [x], "Scale": [scale], "OG@Y": [dy]}
    attrs = {"is_test": False, "epsilon": 1e-5, "momentum": 0.9}
    dx_recompute = kern(None, dict(base), attrs)["X@GRAD"][0]
    wrong = {**base, "O@SavedMean": [jnp.full(3, 7.0)],
             "O@SavedVariance": [jnp.full(3, 9.0)]}
    dx_saved = kern(None, wrong, attrs)["X@GRAD"][0]
    assert not np.allclose(np.asarray(dx_recompute),
                           np.asarray(dx_saved)), \
        "grad kernel ignored the saved statistics slots"
