"""fluid.nets.scaled_dot_product_attention numeric check against a
numpy reference (reference: the nets-module attention composite;
multi-head folding must reproduce per-head softmax(QK^T/sqrt(d))V)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import nets


def _np_attention(q, k, v, heads):
    b, tq, d = q.shape
    tk = k.shape[1]
    hd, hv = d // heads, v.shape[-1] // heads
    out = np.empty((b, tq, v.shape[-1]), np.float32)
    for i in range(b):
        for h in range(heads):
            qs = q[i, :, h * hd:(h + 1) * hd]
            ks = k[i, :, h * hd:(h + 1) * hd]
            vs = v[i, :, h * hv:(h + 1) * hv]
            s = qs @ ks.T / np.sqrt(hd)
            e = np.exp(s - s.max(-1, keepdims=True))
            w = e / e.sum(-1, keepdims=True)
            out[i, :, h * hv:(h + 1) * hv] = w @ vs
    return out


def test_scaled_dot_product_attention_matches_numpy():
    b, tq, tk, d, heads = 2, 3, 5, 8, 2
    rs = np.random.RandomState(0)
    qn = rs.randn(b, tq, d).astype(np.float32)
    kn = rs.randn(b, tk, d).astype(np.float32)
    vn = rs.randn(b, tk, d).astype(np.float32)

    q = fluid.layers.data(name="q", shape=[b, tq, d], dtype="float32",
                          append_batch_size=False)
    k = fluid.layers.data(name="k", shape=[b, tk, d], dtype="float32",
                          append_batch_size=False)
    v = fluid.layers.data(name="v", shape=[b, tk, d], dtype="float32",
                          append_batch_size=False)
    for heads_n in (1, heads):
        ctx = nets.scaled_dot_product_attention(q, k, v,
                                                num_heads=heads_n)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        out, = exe.run(fluid.default_main_program(),
                       feed={"q": qn, "k": kn, "v": vn},
                       fetch_list=[ctx])
        np.testing.assert_allclose(np.asarray(out),
                                   _np_attention(qn, kn, vn, heads_n),
                                   rtol=2e-5, atol=2e-6)


def test_scaled_dot_product_attention_dynamic_batch():
    """The default data-layer spelling (append_batch_size=True, batch
    dim -1) must work: every internal reshape carries a single -1."""
    tq, d, heads = 3, 8, 2
    rs = np.random.RandomState(1)
    xn = rs.randn(4, tq, d).astype(np.float32)

    x = fluid.layers.data(name="x", shape=[tq, d], dtype="float32")
    ctx = nets.scaled_dot_product_attention(x, x, x, num_heads=heads)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    out, = exe.run(fluid.default_main_program(), feed={"x": xn},
                   fetch_list=[ctx])
    np.testing.assert_allclose(np.asarray(out),
                               _np_attention(xn, xn, xn, heads),
                               rtol=2e-5, atol=2e-6)


def test_scaled_dot_product_attention_flash_path():
    """use_flash=True lowers to the fused op and agrees with the
    composed path (cross-attention shapes, both head counts)."""
    b, tq, tk, d = 2, 4, 6, 8
    rs = np.random.RandomState(3)
    qn = rs.randn(b, tq, d).astype(np.float32)
    kn = rs.randn(b, tk, d).astype(np.float32)
    vn = rs.randn(b, tk, d).astype(np.float32)

    q = fluid.layers.data(name="q", shape=[b, tq, d], dtype="float32",
                          append_batch_size=False)
    k = fluid.layers.data(name="k", shape=[b, tk, d], dtype="float32",
                          append_batch_size=False)
    v = fluid.layers.data(name="v", shape=[b, tk, d], dtype="float32",
                          append_batch_size=False)
    for heads in (1, 2):
        composed = nets.scaled_dot_product_attention(q, k, v,
                                                     num_heads=heads)
        fused = nets.scaled_dot_product_attention(q, k, v,
                                                  num_heads=heads,
                                                  use_flash=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        a, b_out = exe.run(fluid.default_main_program(),
                           feed={"q": qn, "k": kn, "v": vn},
                           fetch_list=[composed, fused])
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_out),
                                   rtol=2e-5, atol=2e-6)

    import pytest
    with pytest.raises(ValueError, match="dropout"):
        nets.scaled_dot_product_attention(q, k, v, num_heads=2,
                                          dropout_rate=0.1,
                                          use_flash=True)
