"""CTR DeepFM end-to-end: local convergence and the distributed sparse
path — SelectedRows gradients shipping rows (not dense tensors) to the
native pserver (reference: BASELINE.json configs[5] CTR workload,
paddle/operators/lookup_table_op.cc sparse grads,
paddle/pserver/ParameterServer2.h:510 sparse row access)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu import native
from paddle_tpu.models.ctr import deepfm_ctr
from paddle_tpu.distributed import DistributeTranspiler
from paddle_tpu.ops.dist import ClientPool

NUM_FEATURES = 120
NUM_FIELDS = 4


def _make_ctr_data(n=256, seed=0):
    """Synthetic CTR batch: the click probability mixes a per-feature
    linear signal and one pairwise interaction, the two things DeepFM's
    FM head is built to capture."""
    rs = np.random.RandomState(seed)
    # field f draws ids from its own slice of the shared feature space
    per_field = NUM_FEATURES // NUM_FIELDS
    ids = np.stack([rs.randint(f * per_field, (f + 1) * per_field, size=n)
                    for f in range(NUM_FIELDS)], axis=1).astype(np.int64)
    w = rs.randn(NUM_FEATURES) * 0.7
    latent = rs.randn(NUM_FEATURES, 3)
    logit = w[ids].sum(axis=1)
    logit += np.einsum("nd,nd->n", latent[ids[:, 0]], latent[ids[:, 1]])
    label = (rs.rand(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float32)
    return ids, label.reshape(-1, 1)


def _build_deepfm():
    ids = fluid.layers.data(name="ids", shape=[NUM_FIELDS], dtype="int64")
    label = fluid.layers.data(name="label", shape=[1], dtype="float32")
    avg_loss, predict = deepfm_ctr(ids, label, NUM_FEATURES, NUM_FIELDS,
                                   embed_dim=8, hidden_sizes=(32, 16))
    return ids, label, avg_loss, predict


def test_deepfm_local_convergence():
    ids_var, label_var, avg_loss, _ = _build_deepfm()
    optimize_ops, params_grads = fluid.optimizer.Adam(
        learning_rate=1e-2).minimize(avg_loss)
    # the embedding grads must be SelectedRows (the sparse path)
    from paddle_tpu.core.types import VarType

    sparse_grads = [g for _p, g in params_grads
                    if g.type == VarType.SELECTED_ROWS]
    assert len(sparse_grads) == 2  # second-order + first-order tables

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    feeder = fluid.DataFeeder(place=place, feed_list=[ids_var, label_var])
    ids, label = _make_ctr_data()
    feed = feeder.feed([(ids[i], label[i]) for i in range(len(ids))])
    losses = []
    for _ in range(60):
        out, = exe.run(fluid.default_main_program(), feed=feed,
                       fetch_list=[avg_loss])
        losses.append(float(np.asarray(out).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_deepfm_sparse_pserver_end_to_end():
    """Train DeepFM through the DistributeTranspiler over two native
    pservers; the embedding updates must provably ship as sparse rows
    (pserver row counter), and the loss must decrease."""
    servers = [native.ParameterServer(num_trainers=1, sync=True)
               for _ in range(2)]
    try:
        endpoints = ",".join("127.0.0.1:%d" % s.port for s in servers)
        ids_var, label_var, avg_loss, _ = _build_deepfm()
        optimize_ops, params_grads = fluid.optimizer.Adam(
            learning_rate=0.02).minimize(avg_loss)

        t = DistributeTranspiler()
        t.transpile(optimize_ops=optimize_ops, params_grads=params_grads,
                    pservers=endpoints, trainers=1)

        place = fluid.CPUPlace()
        exe = fluid.Executor(place)
        exe.run(fluid.default_startup_program())
        t.init_pservers()

        feeder = fluid.DataFeeder(place=place,
                                  feed_list=[ids_var, label_var])
        ids, label = _make_ctr_data(n=128)
        feed = feeder.feed([(ids[i], label[i]) for i in range(len(ids))])
        losses = []
        for _ in range(40):
            out, = exe.run(fluid.default_main_program(), feed=feed,
                           fetch_list=[avg_loss])
            losses.append(float(np.asarray(out).reshape(-1)[0]))
        assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])

        # the sparse tables ship rows: each step sends 128*4 id rows per
        # table; the counter counts rows actually applied server-side
        total_sparse_rows = sum(s.num_sparse_rows() for s in servers)
        assert total_sparse_rows >= 40 * 128 * NUM_FIELDS, \
            total_sparse_rows
        # dense (fc) blocks also updated
        assert all(s.num_updates() > 0 for s in servers)
    finally:
        ClientPool.reset()
        for s in servers:
            s.stop()
