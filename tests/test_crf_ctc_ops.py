"""Op tests: linear_chain_crf, crf_decoding, chunk_eval, warpctc,
ctc_align, edit_distance, sequence_erase (reference:
test_linear_chain_crf_op.py, test_crf_decoding_op.py,
test_chunk_eval_op.py, test_warpctc_op.py, test_ctc_align_op.py,
test_edit_distance_op.py, test_sequence_erase_op.py)."""

import numpy as np

from op_test import OpTest

RS = np.random.RandomState(99)


def _crf_ref(emissions, transition, labels):
    """Brute-force NLL over all tag paths for one sequence."""
    import itertools
    a, b, w = transition[0], transition[1], transition[2:]
    T, D = emissions.shape

    def score(path):
        s = a[path[0]] + b[path[-1]] + emissions[np.arange(T), path].sum()
        for t in range(1, T):
            s += w[path[t - 1], path[t]]
        return s

    z = np.logaddexp.reduce([score(p) for p in
                             itertools.product(range(D), repeat=T)])
    return z - score(labels)


class TestLinearChainCRF(OpTest):
    op_type = "linear_chain_crf"

    def test(self):
        D = 3
        lod = [[0, 2, 5, 6]]
        T = lod[0][-1]
        emission = RS.uniform(-1, 1, (T, D)).astype("float32")
        transition = RS.uniform(-0.5, 0.5, (D + 2, D)).astype("float32")
        label = RS.randint(0, D, (T, 1)).astype("int64")

        nll = []
        for s in range(len(lod[0]) - 1):
            lo, hi = lod[0][s], lod[0][s + 1]
            nll.append(_crf_ref(
                emission[lo:hi].astype("float64"),
                transition.astype("float64"),
                label[lo:hi, 0]))
        self.inputs = {"Emission": (emission, lod),
                       "Transition": transition,
                       "Label": (label, lod)}
        self.outputs = {
            "LogLikelihood": np.asarray(nll, "float32").reshape(-1, 1)}
        self.check_output(
            atol=1e-4,
            no_check_set=("Alpha", "EmissionExps", "TransitionExps"))
        self.check_grad(["Emission", "Transition"], "LogLikelihood",
                        max_relative_error=0.05, no_grad_set={"Label"})


class TestCRFDecoding(OpTest):
    op_type = "crf_decoding"

    def test(self):
        import itertools
        D = 3
        lod = [[0, 3, 5]]
        T = lod[0][-1]
        emission = RS.uniform(-1, 1, (T, D)).astype("float32")
        transition = RS.uniform(-0.5, 0.5, (D + 2, D)).astype("float32")

        a, b, w = transition[0], transition[1], transition[2:]
        best = np.zeros((T, 1), "int32")
        for s in range(len(lod[0]) - 1):
            lo, hi = lod[0][s], lod[0][s + 1]
            L = hi - lo
            paths = list(itertools.product(range(D), repeat=L))

            def score(p):
                sc = a[p[0]] + b[p[-1]] + \
                    emission[lo:hi][np.arange(L), p].sum()
                for t in range(1, L):
                    sc += w[p[t - 1], p[t]]
                return sc

            best[lo:hi, 0] = paths[int(np.argmax([score(p)
                                                  for p in paths]))]
        self.inputs = {"Emission": (emission, lod),
                       "Transition": transition}
        self.outputs = {"ViterbiPath": (best, lod)}
        self.check_output()


class TestChunkEvalIOB(OpTest):
    op_type = "chunk_eval"

    def test(self):
        # tags: IOB, 2 types: 0=B-0, 1=I-0, 2=B-1, 3=I-1, 4=O
        lod = [[0, 6]]
        # label:  B-0 I-0 O  B-1 I-1 O  -> chunks (0,1,t0), (3,4,t1)
        label = np.asarray([0, 1, 4, 2, 3, 4]).reshape(-1, 1) \
            .astype("int64")
        # infer:  B-0 I-0 O  B-1 O   O  -> chunks (0,1,t0), (3,3,t1)
        infer = np.asarray([0, 1, 4, 2, 4, 4]).reshape(-1, 1) \
            .astype("int64")
        self.inputs = {"Inference": (infer, lod), "Label": (label, lod)}
        self.attrs = {"num_chunk_types": 2, "chunk_scheme": "IOB"}
        self.outputs = {
            "Precision": np.asarray([0.5], "float32"),
            "Recall": np.asarray([0.5], "float32"),
            "F1-Score": np.asarray([0.5], "float32"),
            "NumInferChunks": np.asarray([2], "int32"),
            "NumLabelChunks": np.asarray([2], "int32"),
            "NumCorrectChunks": np.asarray([1], "int32")}
        self.check_output()


def _ctc_ref(logp, labels, blank):
    """Brute-force CTC -log p(labels | logits) for one sequence."""
    import itertools
    T, C = logp.shape
    total = -np.inf
    for path in itertools.product(range(C), repeat=T):
        # collapse
        out = []
        prev = None
        for t in path:
            if t != prev:
                if t != blank:
                    out.append(t)
            prev = t
        if out == list(labels):
            total = np.logaddexp(total,
                                 sum(logp[t, path[t]] for t in range(T)))
    return -total


class TestWarpCTC(OpTest):
    op_type = "warpctc"

    def test(self):
        C = 4  # classes incl. blank 0
        logits_lod = [[0, 4, 7]]
        label_lod = [[0, 2, 3]]
        T = logits_lod[0][-1]
        logits = RS.uniform(-1, 1, (T, C)).astype("float32")
        labels = np.asarray([[1], [2], [3]], dtype="int64")

        losses = []
        for s in range(2):
            lo, hi = logits_lod[0][s], logits_lod[0][s + 1]
            llo, lhi = label_lod[0][s], label_lod[0][s + 1]
            lg = logits[lo:hi].astype("float64")
            lp = lg - np.log(np.exp(lg).sum(axis=1, keepdims=True))
            losses.append(_ctc_ref(lp, labels[llo:lhi, 0].tolist(), 0))
        self.inputs = {"Logits": (logits, logits_lod),
                       "Label": (labels, label_lod)}
        self.attrs = {"blank": 0, "norm_by_times": False}
        self.outputs = {
            "Loss": np.asarray(losses, "float32").reshape(-1, 1)}
        self.check_output(atol=1e-4, no_check_set=("WarpCTCGrad",))
        self.check_grad(["Logits"], "Loss", max_relative_error=0.05,
                        no_grad_set={"Label"})


class TestCTCAlign(OpTest):
    op_type = "ctc_align"

    def test(self):
        lod = [[0, 6, 10]]
        x = np.asarray([0, 1, 1, 0, 2, 2, 0, 3, 0, 3]).reshape(-1, 1) \
            .astype("int32")
        self.inputs = {"Input": (x, lod)}
        self.attrs = {"blank": 0, "merge_repeated": True}
        out = np.asarray([1, 2, 3, 3]).reshape(-1, 1).astype("int32")
        self.outputs = {"Output": (out, [[0, 2, 4]])}
        self.check_output()


class TestEditDistance(OpTest):
    op_type = "edit_distance"

    def test(self):
        hyp_lod = [[0, 3, 7]]
        ref_lod = [[0, 4, 8]]
        # "kitten" style: hyp [1,2,3] vs ref [1,3,3,4] -> distance 2
        hyps = np.asarray([1, 2, 3, 5, 6, 7, 8]).reshape(-1, 1) \
            .astype("int64")
        refs = np.asarray([1, 3, 3, 4, 5, 6, 9, 8]).reshape(-1, 1) \
            .astype("int64")
        self.inputs = {"Hyps": (hyps, hyp_lod), "Refs": (refs, ref_lod)}
        self.outputs = {"Out": np.asarray([[2.0], [1.0]], "float32"),
                        "SequenceNum": np.asarray([2], "int32")}
        self.check_output()


class TestEditDistanceNormalized(OpTest):
    op_type = "edit_distance"

    def test(self):
        hyps = np.asarray([1, 2, 3]).reshape(-1, 1).astype("int64")
        refs = np.asarray([1, 3, 3, 4]).reshape(-1, 1).astype("int64")
        self.inputs = {"Hyps": (hyps, [[0, 3]]),
                       "Refs": (refs, [[0, 4]])}
        self.attrs = {"normalized": True}
        self.outputs = {"Out": np.asarray([[0.5]], "float32"),
                        "SequenceNum": np.asarray([1], "int32")}
        self.check_output()


class TestSequenceErase(OpTest):
    op_type = "sequence_erase"

    def test(self):
        lod = [[0, 4, 7]]
        x = np.asarray([1, 0, 2, 0, 0, 3, 4]).reshape(-1, 1) \
            .astype("int32")
        self.inputs = {"X": (x, lod)}
        self.attrs = {"tokens": [0]}
        out = np.asarray([1, 2, 3, 4]).reshape(-1, 1).astype("int32")
        self.outputs = {"Out": (out, [[0, 2, 4]])}
        self.check_output()
