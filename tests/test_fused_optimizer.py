"""Fused (stacked) optimizer updates: bit-parity with unfused ops.

The fusion pass (paddle_tpu/fluid/fusion.py) concatenates flattened
same-recipe per-parameter updates into one `fused_update` op; because
every recipe is elementwise per parameter, training must be
*bit-identical* with fusion on or off.  The reference reaches the same
end with hand-fused GPU training kernels
(paddle/math/TrainingAlgorithmOp.cu); here it is an IR rewrite, so we
can assert parity directly.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import fusion
from paddle_tpu.utils import flags as _flags


@pytest.fixture(autouse=True)
def _fusion_on():
    """This file tests the fusion pass itself, so force the flag on
    (default is off: the measured TPU A/B showed the stack is a small
    net loss under XLA — see utils/flags.py)."""
    prev = _flags.get_flag("fuse_optimizer")
    _flags.set_flag("fuse_optimizer", True)
    yield
    _flags.set_flag("fuse_optimizer", prev)


def _build_convnet(optimizer_fn, seed=7):
    """A small conv classifier with several same-shape and
    different-shape params, built in its own program pair."""
    main = fluid.Program()
    startup = fluid.Program()
    fluid.framework.reset_unique_name()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[1, 12, 12],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.conv2d(input=img, num_filters=4, filter_size=3,
                                act="relu")
        h = fluid.layers.conv2d(input=h, num_filters=4, filter_size=3,
                                act="relu")
        h = fluid.layers.fc(input=h, size=10, act="softmax")
        loss = fluid.layers.mean(
            x=fluid.layers.cross_entropy(input=h, label=label))
        opt = optimizer_fn()
        ops, _ = opt.minimize(loss)
    return main, startup, loss, ops


def _train(main, startup, loss, steps=4, seed=3):
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid.executor import scope_guard, fetch_var

    with scope_guard(Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(seed)
        feeds = [{"img": rng.randn(8, 1, 12, 12).astype("float32"),
                  "label": rng.randint(0, 10, (8, 1)).astype("int64")}
                 for _ in range(steps)]
        losses = [exe.run(main, feed=f, fetch_list=[loss])[0] for f in feeds]
        params = {p.name: np.asarray(fetch_var(p.name))
                  for p in main.global_block().all_parameters()}
    return losses, params


OPTIMIZERS = {
    "sgd": lambda: fluid.optimizer.SGD(learning_rate=0.05),
    "momentum": lambda: fluid.optimizer.Momentum(learning_rate=0.05,
                                                 momentum=0.9),
    "adam": lambda: fluid.optimizer.Adam(learning_rate=0.01),
    "adagrad": lambda: fluid.optimizer.Adagrad(learning_rate=0.05),
    "rmsprop": lambda: fluid.optimizer.RMSProp(learning_rate=0.01),
    "adadelta": lambda: fluid.optimizer.Adadelta(),
}


# adam's update divides by sqrt(m2)+eps; XLA's CPU backend lowers that
# through a vectorized rsqrt whose low bit depends on lane position, so
# concatenation shifts results by <= a few ulp.  Every other recipe is
# lowered with exactly-rounded elementwise ops and must match bitwise.
_EXACT = {"sgd", "momentum", "adagrad", "rmsprop", "adadelta"}


@pytest.mark.parametrize("name", sorted(OPTIMIZERS))
def test_bit_parity_fused_vs_unfused(name):
    make = OPTIMIZERS[name]

    main_f, startup_f, loss_f, ops_f = _build_convnet(make)
    main_u, startup_u, loss_u, ops_u = _build_convnet(make)
    fusion.unfuse_update_ops(main_u.global_block())

    fused_types = {op.type for op in ops_f}
    assert "fused_update" in fused_types, fused_types
    unfused_types = {op.type for op in main_u.global_block().ops}
    assert "fused_update" not in unfused_types

    losses_f, params_f = _train(main_f, startup_f, loss_f)
    losses_u, params_u = _train(main_u, startup_u, loss_u)

    assert params_f.keys() == params_u.keys()
    if name in _EXACT:
        for lf, lu in zip(losses_f, losses_u):
            assert np.array_equal(lf, lu), (name, lf, lu)
        for pname in params_f:
            assert np.array_equal(params_f[pname], params_u[pname]), \
                (name, pname)
    else:
        for pname in params_f:
            np.testing.assert_allclose(params_f[pname], params_u[pname],
                                       rtol=2e-6, atol=1e-7,
                                       err_msg="%s/%s" % (name, pname))


def test_fusion_groups_by_recipe():
    """All same-dtype params of one optimizer collapse into one op
    (6 params here: 2 conv w, 2 conv b, fc w, fc b)."""
    main, _, _, ops = _build_convnet(
        lambda: fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9))
    assert len(ops) == 1 and ops[0].type == "fused_update"
    assert len(ops[0].desc.input("Param")) == 6
    # velocity slots stacked, learning rate shared
    assert "Velocity" in ops[0].attr("stacked_slots")
    assert "LearningRate" not in ops[0].attr("stacked_slots")


def test_unfuse_round_trip():
    """fuse -> unfuse reproduces the per-parameter ops exactly."""
    main_a, _, _, _ = _build_convnet(
        lambda: fluid.optimizer.Adam(learning_rate=0.01))
    main_b, _, _, _ = _build_convnet(
        lambda: fluid.optimizer.Adam(learning_rate=0.01))

    block_a = main_a.global_block()
    fusion.unfuse_update_ops(block_a)
    block_b = main_b.global_block()
    fusion.unfuse_update_ops(block_b)
    a = [od.to_dict() for od in block_a.desc.ops]
    b = [od.to_dict() for od in block_b.desc.ops]
    assert a == b


def test_two_adam_instances_never_share_a_group():
    """Two Adam instances have distinct beta-pow vars; blockwide fusion
    must not stack their [1]-shaped scalars into one group."""
    main = fluid.Program()
    startup = fluid.Program()
    fluid.framework.reset_unique_name()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        h1 = fluid.layers.fc(input=x, size=4)
        h2 = fluid.layers.fc(input=x, size=4)
        loss1 = fluid.layers.mean(x=h1)
        loss2 = fluid.layers.mean(x=h2)
        ops1, _ = fluid.optimizer.Adam(learning_rate=0.01).minimize(
            loss1, fuse_updates=False)
        ops2, _ = fluid.optimizer.Adam(learning_rate=0.01).minimize(
            loss2, fuse_updates=False)
    block = main.global_block()
    fused = fusion.fuse_update_ops(block)
    for op in fused:
        if op.type != "fused_update":
            continue
        # every member of a group reads the same beta-pow vars
        assert len(set(op.desc.input("Beta1Pow"))) == 1
        assert "Beta1Pow" not in op.attr("stacked_slots")


def test_one_optimizer_two_programs():
    """An optimizer instance reused across programs creates fresh state
    vars in each (regression: shared scalars were cached by name only)."""
    opt = fluid.optimizer.Adam(learning_rate=0.01)
    mains = []
    for _ in range(2):
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            loss = fluid.layers.mean(x=fluid.layers.fc(input=x, size=4))
            opt.minimize(loss)
        mains.append(main)
    for main in mains:
        block = main.global_block()
        for op in block.ops:
            if op.type in ("adam", "fused_update", "scale"):
                for names in op.desc.inputs.values():
                    for n in names:
                        assert block.has_var_recursive(n), \
                            "%s reads %r not in its program" % (op.type, n)


def test_fuse_flag_env_override(monkeypatch):
    from paddle_tpu.utils import flags as flags_mod

    monkeypatch.setenv("FLAGS_fuse_optimizer", "0")
    flags_mod.parse_flags_from_env(["fuse_optimizer"])
    try:
        assert flags_mod.get_flag("fuse_optimizer") is False
        _, _, _, ops = _build_convnet(
            lambda: fluid.optimizer.SGD(learning_rate=0.1))
        assert all(op.type == "sgd" for op in ops) and len(ops) == 6
    finally:
        flags_mod.set_flag("fuse_optimizer", True)


def test_fused_op_survives_desc_round_trip():
    """stacked_slots / inner_type attrs serialize through the JSON IR."""
    from paddle_tpu.core.desc import ProgramDesc

    main, _, _, _ = _build_convnet(
        lambda: fluid.optimizer.SGD(learning_rate=0.1))
    d = main.desc.to_dict()
    back = ProgramDesc.from_dict(d)
    fused = [od for od in back.block(0).ops if od.type == "fused_update"]
    assert fused and fused[0].attrs["inner_type"] == "sgd"


def test_size_cap_keeps_big_params_unfused():
    """FLAGS_fuse_optimizer_max_numel: tiny tensors stack, the big
    matmul kernel keeps its own per-parameter op (launch overhead is
    about count; concat/split HBM traffic is about bytes)."""
    from paddle_tpu.utils import flags as flags_mod

    prev = flags_mod.get_flag("fuse_optimizer_max_numel")
    flags_mod.set_flag("fuse_optimizer_max_numel", 1000)
    try:
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[64], dtype="float32")
            t = fluid.layers.fc(input=x, size=64)     # 64x64 > cap
            t = fluid.layers.fc(input=t, size=8)      # 64x8 + biases < cap
            loss = fluid.layers.mean(x=t)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        ops = [op for op in main.global_block().ops
               if op.type in ("sgd", "fused_update")]
        by_type = {}
        for op in ops:
            by_type.setdefault(op.type, []).append(op)
        # the 64x64 weight stays per-param; the small ones stack
        assert len(by_type["sgd"]) == 1
        big = by_type["sgd"][0].desc.input("Param")[0]
        blk = main.global_block()
        shape = blk.var_recursive(big).shape
        assert int(shape[0]) * int(shape[1]) > 1000
        assert len(by_type["fused_update"]) == 1
        assert len(by_type["fused_update"][0].desc.input("Param")) == 3
    finally:
        flags_mod.set_flag("fuse_optimizer_max_numel", prev)
