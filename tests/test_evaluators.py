"""Evaluator breadth: detection mAP, CTC/edit-distance error, and the
v2 evaluator DSL (reference: gserver/evaluators/Evaluator.cpp,
CTCErrorEvaluator.cpp, DetectionMAPEvaluator.cpp +
trainer_config_helpers/evaluators.py)."""

import numpy as np
import jax.numpy as jnp

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as v2
from paddle_tpu.core.ragged import RaggedTensor
from paddle_tpu.ops.registry import get_op_info


def _rag(rows, splits, dtype=np.float32):
    return RaggedTensor(jnp.asarray(np.asarray(rows, dtype)),
                        [np.asarray(splits, np.int64)])


def test_detection_map_op_scores():
    kernel = get_op_info("detection_map").kernel
    # two images; class 1: one perfect detection + one false positive
    # ranked below it; class 2: detection misses its gt (IoU 0)
    det = _rag([[1, 0.9, 0.1, 0.1, 0.5, 0.5],
                [1, 0.3, 0.7, 0.7, 0.9, 0.9],
                [2, 0.8, 0.0, 0.0, 0.1, 0.1]], [0, 2, 3])
    gt = _rag([[1, 0.1, 0.1, 0.5, 0.5],
               [2, 0.5, 0.5, 0.9, 0.9]], [0, 1, 2])
    m = float(np.asarray(kernel(None, {"DetectRes": [det],
                                       "Label": [gt]}, {})["MAP"][0])[0])
    # class 1 AP = 1.0 (top det matches), class 2 AP = 0 -> mAP 0.5
    np.testing.assert_allclose(m, 0.5, atol=1e-6)

    # integral ap_type also computes
    m2 = float(np.asarray(kernel(
        None, {"DetectRes": [det], "Label": [gt]},
        {"ap_type": "integral"})["MAP"][0])[0])
    assert 0.0 <= m2 <= 1.0


def test_detection_map_difficult_handling():
    kernel = get_op_info("detection_map").kernel
    det = _rag([[1, 0.9, 0.1, 0.1, 0.5, 0.5]], [0, 1])
    gt_hard = _rag([[1, 0.1, 0.1, 0.5, 0.5, 1.0]], [0, 1])  # difficult
    out = kernel(None, {"DetectRes": [det], "Label": [gt_hard]}, {})
    assert float(np.asarray(out["MAP"][0])[0]) == 0.0  # no countable gt
    out = kernel(None, {"DetectRes": [det], "Label": [gt_hard]},
                 {"evaluate_difficult": True})
    np.testing.assert_allclose(np.asarray(out["MAP"][0])[0], 1.0)


def test_detection_map_duplicate_is_false_positive():
    """VOC protocol: a second detection of an already-matched gt is a
    false positive, never re-matched to a lesser-overlap gt."""
    kernel = get_op_info("detection_map").kernel
    det = _rag([[1, 0.9, 0.0, 0.0, 1.0, 1.0],
                [1, 0.8, 0.0, 0.0, 1.0, 1.0]], [0, 2])
    gt = _rag([[1, 0.0, 0.0, 1.0, 1.0],
               [1, 0.0, 0.0, 0.8, 0.8]], [0, 2])
    m = float(np.asarray(kernel(None, {"DetectRes": [det],
                                       "Label": [gt]}, {})["MAP"][0])[0])
    # det2 duplicates gt A -> FP; recall caps at 0.5:
    # 11-point AP = 6/11 (precision 1.0 up to recall .5, 0 beyond)
    np.testing.assert_allclose(m, 6.0 / 11.0, atol=1e-6)


def test_precision_recall_positive_label():
    x = v2.layer.data(name="x", type=v2.data_type.dense_vector(3))
    lab = v2.layer.data(name="lab", type=v2.data_type.integer_value(3))
    prf = v2.evaluator.precision_recall_evaluator(input=x, label=lab,
                                                  positive_label=1)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    # predictions: argmax -> [1, 1, 0, 2]; labels [1, 0, 1, 2]
    probs = np.array([[0.1, 0.8, 0.1], [0.2, 0.7, 0.1],
                      [0.9, 0.05, 0.05], [0.1, 0.1, 0.8]], np.float32)
    labels = np.array([[1], [0], [1], [2]], np.int64)
    out, = exe.run(fluid.default_main_program(),
                   feed={"x": probs, "lab": labels}, fetch_list=[prf])
    p, r, f1 = np.asarray(out).reshape(-1)
    np.testing.assert_allclose(p, 0.5, atol=1e-5)   # 1 tp / 2 pred
    np.testing.assert_allclose(r, 0.5, atol=1e-5)   # 1 tp / 2 actual
    np.testing.assert_allclose(f1, 0.5, atol=1e-5)


def test_fluid_edit_distance_evaluator():
    hyp = fluid.layers.data(name="hyp", shape=[1], dtype="int64",
                            lod_level=1)
    ref = fluid.layers.data(name="ref", shape=[1], dtype="int64",
                            lod_level=1)
    ev = fluid.evaluator.EditDistance(input=hyp, label=ref)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    feeder = fluid.DataFeeder(feed_list=[hyp, ref], place=place)
    # seq0 identical (distance 0), seq1 one substitution (distance 1)
    feeds = feeder.feed([([[1], [2], [3]], [[1], [2], [3]]),
                         ([[4], [5]], [[4], [6]])])
    exe.run(fluid.default_main_program(), feed=feeds,
            fetch_list=ev.metrics)
    avg, err = ev.eval(exe)
    np.testing.assert_allclose(avg, [0.5])   # (0 + 1) / 2 sequences
    np.testing.assert_allclose(err, [0.5])   # 1 of 2 wrong


def test_fluid_detection_map_evaluator():
    det = fluid.layers.data(name="det", shape=[6], dtype="float32",
                            lod_level=1)
    gt = fluid.layers.data(name="gt", shape=[5], dtype="float32",
                           lod_level=1)
    ev = fluid.evaluator.DetectionMAP(detect_res=det, label=gt)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    feeder = fluid.DataFeeder(feed_list=[det, gt], place=place)
    feeds = feeder.feed([
        ([[1, 0.9, 0.1, 0.1, 0.5, 0.5]], [[1, 0.1, 0.1, 0.5, 0.5]])])
    exe.run(fluid.default_main_program(), feed=feeds,
            fetch_list=ev.metrics)
    np.testing.assert_allclose(ev.eval(exe), [1.0])


def test_v2_evaluator_dsl():
    x = v2.layer.data(name="x", type=v2.data_type.dense_vector(4))
    lab = v2.layer.data(name="lab", type=v2.data_type.integer_value(4))
    probs = v2.layer.fc(input=x, size=4,
                        act=v2.activation.Softmax())
    err = v2.evaluator.classification_error_evaluator(input=probs,
                                                      label=lab)
    pr = v2.evaluator.precision_recall_evaluator(input=probs, label=lab)
    colsum = v2.evaluator.column_sum_evaluator(input=probs)
    total = v2.evaluator.sum_evaluator(input=probs)

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    rs = np.random.RandomState(0)
    feeds = {"x": rs.rand(6, 4).astype(np.float32),
             "lab": rs.randint(0, 4, (6, 1)).astype(np.int64)}
    e, p, c, t = exe.run(fluid.default_main_program(), feed=feeds,
                         fetch_list=[err, pr, colsum, total])
    assert 0.0 <= float(np.asarray(e).reshape(-1)[0]) <= 1.0
    assert np.asarray(p).shape[-1] == 6  # macro/micro P R F1
    assert np.asarray(c).shape == (4,)
    np.testing.assert_allclose(np.asarray(t).reshape(-1)[0], 6.0,
                               rtol=1e-4)


def test_v2_ctc_and_auc_evaluators():
    hyp = v2.layer.data(
        name="hyp", type=v2.data_type.integer_value_sequence(10))
    ref = v2.layer.data(
        name="ref", type=v2.data_type.integer_value_sequence(10))
    cer = v2.evaluator.ctc_error_evaluator(input=hyp, label=ref)

    score = v2.layer.data(name="score", type=v2.data_type.dense_vector(2))
    blab = v2.layer.data(name="blab", type=v2.data_type.integer_value(2))
    auc = v2.evaluator.auc_evaluator(input=score, label=blab)

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    blk = fluid.default_main_program().global_block()
    feeder = fluid.DataFeeder(
        feed_list=[blk.var("hyp"), blk.var("ref")], place=place)
    feeds = feeder.feed([([[1], [2]], [[1], [3]])])
    feeds["score"] = np.array([[0.1, 0.9], [0.8, 0.2]], np.float32)
    feeds["blab"] = np.array([[1], [0]], np.int64)
    c, a = exe.run(fluid.default_main_program(), feed=feeds,
                   fetch_list=[cer, auc])
    np.testing.assert_allclose(np.asarray(c).reshape(-1), [1.0])
    assert float(np.asarray(a).reshape(-1)[0]) > 0.9
