"""Torch weight import (reference: paddle/utils/torch2paddle.py) —
fidelity-tested: the imported program must reproduce torch's forward
outputs."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu.fluid as fluid
from paddle_tpu.utils.torch2paddle import (load_torch_state,
                                           torch_state_to_numpy)


def test_mlp_outputs_match():
    tnet = torch.nn.Sequential(
        torch.nn.Linear(13, 8), torch.nn.Tanh(),
        torch.nn.Linear(8, 3))
    x = np.random.RandomState(0).rand(5, 13).astype(np.float32)
    with torch.no_grad():
        want = tnet(torch.from_numpy(x)).numpy()

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data(name="x", shape=[5, 13],
                               dtype="float32", append_batch_size=False)
        h = fluid.layers.fc(input=xv, size=8, act="tanh")
        out = fluid.layers.fc(input=h, size=3, act=None)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    written = load_torch_state(main, tnet.state_dict(), scope=scope)
    assert len(written) == 4
    got, = exe.run(main, feed={"x": x}, fetch_list=[out], scope=scope)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                               atol=1e-6)


def test_conv_outputs_match():
    tconv = torch.nn.Conv2d(3, 6, kernel_size=3, padding=1)
    x = np.random.RandomState(1).rand(2, 3, 8, 8).astype(np.float32)
    with torch.no_grad():
        want = tconv(torch.from_numpy(x)).numpy()

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data(name="x", shape=[2, 3, 8, 8],
                               dtype="float32", append_batch_size=False)
        out = fluid.layers.conv2d(input=xv, num_filters=6,
                                  filter_size=3, padding=1, act=None)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    load_torch_state(main, tconv.state_dict(), scope=scope)
    got, = exe.run(main, feed={"x": x}, fetch_list=[out], scope=scope)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4,
                               atol=1e-5)


def test_name_map_and_shape_guard():
    tnet = torch.nn.Linear(4, 2)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data(name="x", shape=[1, 4],
                               dtype="float32", append_batch_size=False)
        fluid.layers.fc(input=xv, size=2, act=None,
                        param_attr=fluid.ParamAttr(name="w0"),
                        bias_attr=fluid.ParamAttr(name="b0"))
    scope = fluid.Scope()
    written = load_torch_state(
        main, tnet.state_dict(), scope=scope,
        name_map={"w0": "weight", "b0": "bias"})
    assert set(written) == {"w0", "b0"}
    assert scope.get("w0").shape == (4, 2)     # transposed into [in,out]

    bad = torch.nn.Linear(5, 2)                # wrong in-features
    with pytest.raises(ValueError, match="does not fit"):
        load_torch_state(main, bad.state_dict(), scope=scope,
                         name_map={"w0": "weight"})


def test_state_roundtrip_via_file(tmp_path):
    tnet = torch.nn.Linear(3, 3)
    p = str(tmp_path / "m.pt")
    torch.save(tnet.state_dict(), p)
    arrs = torch_state_to_numpy(p)
    assert list(arrs) == ["weight", "bias"]
    assert arrs["weight"].shape == (3, 3)
