"""Book test: neural machine translation (seq2seq), teacher-forced.

Parity target: reference tests/book/test_machine_translation.py — WMT14
reader feeding (src, trg_in, trg_next) ragged id sequences; encoder LSTM
+ DynamicRNN decoder; cross-entropy on next-token; loss decreases.
"""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.models import seq2seq

DICT_SIZE = 1000


def test_machine_translation():
    src = fluid.layers.data(name="src_word_id", shape=[1], dtype="int64",
                            lod_level=1)
    trg_in = fluid.layers.data(name="target_language_word", shape=[1],
                               dtype="int64", lod_level=1)
    trg_next = fluid.layers.data(name="target_language_next_word",
                                 shape=[1], dtype="int64", lod_level=1)

    prob = seq2seq(src, trg_in, DICT_SIZE, DICT_SIZE,
                   emb_dim=32, hidden_dim=32)
    cost = fluid.layers.cross_entropy(input=prob, label=trg_next)
    avg_cost = fluid.layers.mean(x=cost)
    fluid.optimizer.Adam(learning_rate=0.02).minimize(avg_cost)

    reader = paddle.batch(paddle.dataset.wmt14.train(DICT_SIZE),
                          batch_size=8)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(feed_list=[src, trg_in, trg_next],
                              place=place)
    exe.run(fluid.default_startup_program())

    losses = []
    for batch in reader():
        if len(batch) != 8:
            continue
        out, = exe.run(fluid.default_main_program(),
                       feed=feeder.feed(batch),
                       fetch_list=[avg_cost])
        losses.append(float(np.asarray(out).reshape(-1)[0]))
        if len(losses) >= 60:
            break
    assert np.isfinite(losses[-1])
    assert np.mean(losses[-6:]) < np.mean(losses[:6]), (
        losses[:6], losses[-6:])
