"""v2 networks composites (reference:
trainer_config_helpers/networks.py — lstmemory_unit/group :717-940,
gru_unit/group :940-1226, bidirectional_gru :1226, simple_attention
:1400, dot_product_attention :1498, multi_head_attention :1580,
small_vgg :517, vgg_16_network :547): each composite trains end-to-end
through the v2 DSL and the loss decreases."""

import numpy as np

import paddle_tpu.v2 as v2
import paddle_tpu.fluid as fluid
from paddle_tpu.v2 import layer, networks

V = 30   # toy vocab
H = 8


def _feed(names, data):
    blk = fluid.default_main_program().global_block()
    feeder = fluid.DataFeeder(place=fluid.CPUPlace(),
                              feed_list=[blk.var(n) for n in names])
    return feeder.feed(data)


def _train(cost, feed, iters, lr=3e-2):
    fluid.optimizer.Adam(learning_rate=lr).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = []
    for _ in range(iters):
        out, = exe.run(feed=feed, fetch_list=[cost])
        losses.append(float(np.asarray(out).reshape(-1)[0]))
    return losses


def test_recurrent_group_composites_train():
    """gru_group + lstmemory_group + bidirectional_gru composed in one
    classifier; per-step states visible, loss decreases."""
    x = layer.data(name="x", type=v2.data_type.dense_vector_sequence(6))
    g = networks.gru_group(input=layer.fc(input=x, size=12), size=4)
    l = networks.lstmemory_group(input=layer.fc(input=x, size=16),
                                 size=4)
    bg = networks.bidirectional_gru(input=x, size=4)
    pooled = layer.pool(input=layer.concat(input=[g, l]))
    pred = layer.fc(input=layer.concat(input=[pooled, bg]), size=1)
    lab = layer.data(name="y", type=v2.data_type.dense_vector(1))
    cost = layer.mse_cost(input=pred, label=lab)

    rs = np.random.RandomState(0)
    data = [(rs.rand(rs.randint(2, 5), 6).tolist(), [1.0])
            for _ in range(4)]
    losses = _train(cost, _feed(["x", "y"], data), 12, lr=5e-2)
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def _nmt_data(rs, n=6):
    data = []
    for _ in range(n):
        s = rs.randint(0, V, size=rs.randint(2, 6)).tolist()
        t = rs.randint(0, V, size=rs.randint(2, 6)).tolist()
        data.append((s, t, t[1:] + [1]))
    return data


def test_attention_nmt_through_v2_dsl():
    """The book's attention-NMT chapter shape through the v2 DSL:
    GRU encoder, simple_attention context inside the decoder's
    recurrent_group (encoder visible as a StaticInput sequence),
    gru_unit decoder; memorizes a toy batch."""
    src = layer.data(name="src",
                     type=v2.data_type.integer_value_sequence(V))
    trg = layer.data(name="trg",
                     type=v2.data_type.integer_value_sequence(V))
    nxt = layer.data(name="nxt",
                     type=v2.data_type.integer_value_sequence(V))
    enc = networks.simple_gru(input=layer.embedding(input=src, size=H),
                              size=H)
    enc_proj = layer.fc(input=enc, size=H, bias_attr=False)
    enc_last = layer.last_seq(input=enc)
    trg_emb = layer.embedding(input=trg, size=H)

    def decoder_step(cur_emb, enc_seq, enc_p):
        dec_mem = layer.memory(name="dec_state", size=H,
                               boot_layer=enc_last)
        context = networks.simple_attention(
            encoded_sequence=enc_seq, encoded_proj=enc_p,
            decoder_state=dec_mem)
        gates = layer.fc(input=layer.concat(input=[cur_emb, context]),
                         size=H * 3, bias_attr=False)
        h = networks.gru_unit(input=gates, size=H, name="dec_state")
        return layer.fc(input=h, size=V, act=v2.activation.Softmax())

    probs = layer.recurrent_group(
        step=decoder_step,
        input=[trg_emb,
               layer.StaticInput(input=enc, is_seq=True),
               layer.StaticInput(input=enc_proj, is_seq=True)])
    cost = layer.classification_cost(input=probs, label=nxt)

    data = _nmt_data(np.random.RandomState(0))
    losses = _train(cost, _feed(["src", "trg", "nxt"], data), 80)
    # starts at ~ln(V) and memorizes the toy batch
    assert losses[0] < np.log(V) * 1.3
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])


def test_dot_product_and_multi_head_attention():
    """dot_product_attention and multi_head_attention pool a static
    encoder sequence against a dense query state."""
    src = layer.data(name="src",
                     type=v2.data_type.dense_vector_sequence(H))
    lab = layer.data(name="y", type=v2.data_type.dense_vector(1))
    state = layer.pool(input=src, pooling_type="average")

    ctx_dot = networks.dot_product_attention(
        encoded_sequence=src, attended_sequence=src,
        transformed_state=state)
    ctx_mh = networks.multi_head_attention(
        query=state, key=src, value=src, key_proj_size=4,
        value_proj_size=4, head_num=2,
        attention_type="dot-product attention")
    ctx_add = networks.multi_head_attention(
        query=state, key=src, value=src, key_proj_size=4,
        value_proj_size=4, head_num=2,
        attention_type="additive attention")
    pred = layer.fc(input=layer.concat(input=[ctx_dot, ctx_mh, ctx_add]),
                    size=1)
    cost = layer.mse_cost(input=pred, label=lab)

    rs = np.random.RandomState(1)
    data = [(rs.rand(rs.randint(2, 5), H).tolist(),
             [float(i % 2)]) for i in range(4)]
    losses = _train(cost, _feed(["src", "y"], data), 15, lr=5e-2)
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_attention_nmt_train_then_beam_generate():
    """The NMT chapter's full loop through the v2 DSL: train the
    attention decoder with teacher forcing, then beam-search GENERATE
    with the same parameters — simple_attention runs inside the
    generation step over the beam-expanded encoder sequence (reference:
    demo/seqToseq gen flow over RecurrentGradientMachine::beamSearch)."""
    import paddle_tpu as paddle
    from paddle_tpu.v2.attr import Param

    E = 6
    names = {"semb": "att_src_emb", "temb": "att_trg_emb",
             "proj": "att_enc_proj", "boot": "att_boot",
             "gates": "att_gates", "gru": "att_gru", "out": "att_out",
             "transform": "att_transform", "score": "att_score"}

    src = layer.data(name="src",
                     type=v2.data_type.integer_value_sequence(V))

    def encode(seq_in):
        emb = layer.embedding(input=seq_in, size=E,
                              param_attr=Param(name=names["semb"]))
        enc = networks.simple_gru(input=emb, size=H)
        proj = layer.fc(input=enc, size=H, bias_attr=False,
                        param_attr=Param(name=names["proj"]))
        boot = layer.fc(input=layer.last_seq(input=enc), size=H,
                        act=v2.activation.Tanh(),
                        param_attr=Param(name=names["boot"]))
        return enc, proj, boot

    def decoder_step(cur_emb, enc_seq, enc_p, boot):
        mem = layer.memory(name="att_dec", size=H, boot_layer=boot)
        ctx = networks.simple_attention(
            encoded_sequence=enc_seq, encoded_proj=enc_p,
            decoder_state=mem, name="att_head",
            transform_param_attr=Param(name=names["transform"]),
            softmax_param_attr=Param(name=names["score"]))
        gates = layer.fc(input=layer.concat(input=[cur_emb, ctx]),
                         size=H * 3, bias_attr=False,
                         param_attr=Param(name=names["gates"]))
        h = networks.gru_unit(input=gates, size=H, name="att_dec",
                              gru_param_attr=Param(name=names["gru"]),
                              gru_bias_attr=Param(name=names["gru"]
                                                  + ".b"))
        return layer.fc(input=h, size=V, act=v2.activation.Softmax(),
                        param_attr=Param(name=names["out"]),
                        bias_attr=Param(name=names["out"] + ".b"))

    # --- training graph (teacher forcing) ---
    enc, enc_proj, boot = encode(src)
    trg = layer.data(name="trg",
                     type=v2.data_type.integer_value_sequence(V))
    nxt = layer.data(name="nxt",
                     type=v2.data_type.integer_value_sequence(V))
    trg_emb = layer.embedding(input=trg, size=E,
                              param_attr=Param(name=names["temb"]))
    probs = layer.recurrent_group(
        step=lambda cur, es, ep: decoder_step(cur, es, ep, boot),
        input=[trg_emb,
               layer.StaticInput(input=enc, is_seq=True),
               layer.StaticInput(input=enc_proj, is_seq=True)])
    cost = layer.classification_cost(input=probs, label=nxt)

    # task: whatever the source, emit "2 3 eos(1)" after bos(0)
    data = [([2, 3, 4], [0, 2, 3], [2, 3, 1]),
            ([5, 4], [0, 2, 3], [2, 3, 1])] * 3
    losses = _train(cost, _feed(["src", "trg", "nxt"], data), 60)
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])

    # --- generation graph: same parameter names, beam decode ---
    beam = layer.beam_search(
        step=lambda cur, es, ep, b: decoder_step(cur, es, ep, b),
        input=[layer.GeneratedInput(size=V,
                                    embedding_name=names["temb"],
                                    embedding_size=E),
               layer.StaticInput(input=enc, is_seq=True),
               layer.StaticInput(input=enc_proj, is_seq=True),
               layer.StaticInput(input=boot)],
        bos_id=0, eos_id=1, beam_size=3, max_length=6)

    gen_probs, ids = paddle.infer(
        output_layer=beam, input=[([2, 3, 4],), ([5, 4],)],
        field=["prob", "id"])
    seqs, cur = [], []
    for w in ids:
        if w == -1:
            seqs.append(cur)
            cur = []
        else:
            cur.append(int(w))
    assert len(seqs) == 6  # 2 samples x beam 3
    for s in seqs:
        assert s[0] == 0
    # the trained model's best beam per sample is the taught sequence
    best = [seqs[0], seqs[3]]
    for s in best:
        assert s == [0, 2, 3, 1], (s, seqs)


def test_small_vgg_builds_and_steps():
    """small_vgg (CIFAR shape): one training step, finite loss."""
    img = layer.data(name="img",
                     type=v2.data_type.dense_array(3 * 32 * 32,
                                                   [3, 32, 32]))
    lab = layer.data(name="lbl", type=v2.data_type.integer_value(10))
    probs = networks.small_vgg(input_image=img, num_channels=3,
                               num_classes=10)
    cost = layer.classification_cost(input=probs, label=lab)

    rs = np.random.RandomState(0)
    data = [(rs.rand(3 * 32 * 32).tolist(), [rs.randint(0, 10)])
            for _ in range(2)]
    losses = _train(cost, _feed(["img", "lbl"], data), 1, lr=1e-2)
    assert np.isfinite(losses[0])


def test_vgg_16_network_builds_and_steps():
    """vgg_16_network: one training step at reduced resolution."""
    img = layer.data(name="img",
                     type=v2.data_type.dense_array(3 * 32 * 32,
                                                   [3, 32, 32]))
    lab = layer.data(name="lbl", type=v2.data_type.integer_value(10))
    probs = networks.vgg_16_network(input_image=img, num_channels=3,
                                    num_classes=10)
    cost = layer.classification_cost(input=probs, label=lab)

    rs = np.random.RandomState(0)
    data = [(rs.rand(3 * 32 * 32).tolist(), [rs.randint(0, 10)])
            for _ in range(2)]
    losses = _train(cost, _feed(["img", "lbl"], data), 1, lr=1e-2)
    assert np.isfinite(losses[0])
