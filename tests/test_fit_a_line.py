"""Milestone A: linear regression end-to-end.

Parity target: reference python/paddle/v2/fluid/tests/book/
test_fit_a_line.py — same program structure (fc -> square_error_cost ->
mean -> SGD), loss must fall below threshold.
"""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid


def test_fit_a_line(tmp_path):
    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y_predict = fluid.layers.fc(input=x, size=1, act=None)
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")

    cost = fluid.layers.square_error_cost(input=y_predict, label=y)
    avg_cost = fluid.layers.mean(x=cost)

    sgd_optimizer = fluid.optimizer.SGD(learning_rate=0.01)
    sgd_optimizer.minimize(avg_cost)

    BATCH_SIZE = 20
    train_reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.uci_housing.train(),
                              buf_size=500),
        batch_size=BATCH_SIZE)

    place = fluid.CPUPlace()
    feeder = fluid.DataFeeder(place=place, feed_list=[x, y])
    exe = fluid.Executor(place)

    exe.run(fluid.default_startup_program())

    first_loss = None
    last_loss = None
    for pass_id in range(30):
        for data in train_reader():
            avg_loss_value, = exe.run(fluid.default_main_program(),
                                      feed=feeder.feed(data),
                                      fetch_list=[avg_cost])
            if first_loss is None:
                first_loss = float(avg_loss_value[0])
            last_loss = float(avg_loss_value[0])
        if last_loss < 0.05:
            break
    assert last_loss < first_loss, (first_loss, last_loss)
    assert last_loss < 0.15, last_loss

    # save/load persistables roundtrip (reference test does this each pass)
    model_dir = str(tmp_path / "fit_a_line.model")
    fluid.io.save_persistables(exe, model_dir)
    fluid.io.load_persistables(exe, model_dir)
    again, = exe.run(fluid.default_main_program(),
                     feed=feeder.feed(next(train_reader())),
                     fetch_list=[avg_cost])
    assert np.isfinite(again[0])
