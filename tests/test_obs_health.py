"""paddle_tpu.obs.health + obs.flight: jit-safe numerics monitoring,
the eager NaN bisection, XLA memory/cost attribution gauges, the crash
flight recorder, and the enriched serving /healthz.

Tier-1 (CPU).  The acceptance loop lives in
test_nan_training_full_loop: a deliberately-NaN training run makes
`numerics_nonfinite_total` count, `locate_nonfinite` names the first
offending op, and the induced crash leaves a flight bundle that
`obs_dump --flight` renders."""

import http.client
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.scope import Scope
from paddle_tpu.fluid.amp import LossScaler
from paddle_tpu.fluid.executor import NonfiniteError
from paddle_tpu.obs import flight as obs_flight
from paddle_tpu.obs import health as obs_health
from paddle_tpu.obs import registry as obs_registry
from paddle_tpu.obs import telemetry as obs_tele
from paddle_tpu.tools import obs_dump
from paddle_tpu.utils import flags


def _train_program():
    """x -> fc -> mean cost with SGD update ops; returns
    (cost, params_grads)."""
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    h = fluid.layers.fc(input=x, size=3)
    cost = fluid.layers.mean(x=h)
    _, pg = fluid.optimizer.SGDOptimizer(learning_rate=0.1) \
        .minimize(cost)
    return cost, pg


def _run_startup():
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe


NAN_BATCH = np.full((2, 4), np.nan, np.float32)
ONES_BATCH = np.ones((2, 4), np.float32)


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------

def test_isfinite_and_count_nonfinite_ops():
    import jax.numpy as jnp

    from paddle_tpu.ops.registry import get_op_info

    x = jnp.asarray([1.0, np.nan, np.inf, -2.0], jnp.float32)
    fin = get_op_info("isfinite").kernel(None, {"X": [x]}, {})["Out"][0]
    assert not bool(np.asarray(fin)[0])
    cnt = get_op_info("count_nonfinite").kernel(
        None, {"X": [x]}, {})["Out"][0]
    assert np.asarray(cnt)[0] == 2
    ok = get_op_info("isfinite").kernel(
        None, {"X": [jnp.zeros((3,))]}, {})["Out"][0]
    assert bool(np.asarray(ok)[0])


# ---------------------------------------------------------------------------
# check_nan_inf: direct coverage of the eager flag path (satellite)
# ---------------------------------------------------------------------------

def test_check_nan_inf_eager_raises_with_op_identity():
    cost, _ = _train_program()
    exe = _run_startup()
    prev = flags.get_flag("check_nan_inf")
    flags.set_flag("check_nan_inf", True)
    try:
        with pytest.raises(NonfiniteError) as ei:
            exe.run(fluid.default_main_program(),
                    feed={"x": NAN_BATCH}, fetch_list=[cost],
                    eager=True)
    finally:
        flags.set_flag("check_nan_inf", prev)
    err = ei.value
    assert err.op_type == "mul"        # fc's matmul is the first op
    assert err.op_index == 0
    assert err.var_name and err.nonfinite_count > 0


def test_check_nan_inf_does_not_guard_jitted_path():
    """The documented gap: the flag only scans the eager interpreter —
    a jitted run of the same NaN feed completes silently (which is why
    health.locate_nonfinite exists)."""
    cost, _ = _train_program()
    exe = _run_startup()
    prev = flags.get_flag("check_nan_inf")
    flags.set_flag("check_nan_inf", True)
    try:
        outs = exe.run(fluid.default_main_program(),
                       feed={"x": NAN_BATCH}, fetch_list=[cost])
    finally:
        flags.set_flag("check_nan_inf", prev)
    assert np.isnan(np.asarray(outs[0])).all()


# ---------------------------------------------------------------------------
# NumericsMonitor
# ---------------------------------------------------------------------------

def test_numerics_monitor_counts_maxabs_and_grad_norm():
    cost, pg = _train_program()
    exe = _run_startup()
    main = fluid.default_main_program()
    mon = obs_health.NumericsMonitor.for_train_program(
        main, cost=cost, params_grads=pg).install()
    assert mon.fetch_names
    assert mon.install() is mon  # idempotent

    outs = exe.run(main, feed={"x": ONES_BATCH},
                   fetch_list=[cost] + mon.fetch_names)
    s = mon.record(dict(zip(mon.fetch_names, outs[1:])))
    assert not s["found_nonfinite"]
    assert all(c == 0 for c in s["nonfinite"].values())
    assert s["grad_global_norm"] > 0
    assert np.isfinite(s["grad_global_norm"])

    outs = exe.run(main, feed={"x": NAN_BATCH},
                   fetch_list=[cost] + mon.fetch_names)
    s = mon.record(outs[1:])   # sequence form
    assert s["found_nonfinite"]
    assert sum(s["nonfinite"].values()) > 0

    # registry side: the counter family carries per-tensor children,
    # the gauges landed
    flat = obs_tele.snapshot()
    assert any(k.startswith("numerics_nonfinite_total{") and v > 0
               for k, v in flat.items())
    assert any(k.startswith("numerics_max_abs{") for k in flat)
    assert "grad_global_norm" in flat


def test_numerics_monitor_grad_discovery_matches_params_grads():
    cost, pg = _train_program()
    main = fluid.default_main_program()
    discovered = obs_health.NumericsMonitor(main)._discover_grads()
    assert set(discovered) == {g.name for _, g in pg if g is not None}


def test_numerics_monitor_v2_trainer_wiring():
    """health.enable() makes the v2 SGD loop install a monitor and
    feed the registry without any trainer-code changes by the user."""
    import paddle_tpu.v2 as paddle

    paddle.init()
    x = paddle.layer.data(name="x",
                          type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y",
                          type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1)
    cost = paddle.layer.square_error_cost(input=pred, label=y)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(
            momentum=0.9, learning_rate=0.1))

    def reader():
        yield [(np.ones(4, np.float32), np.ones(1, np.float32))]
        yield [(np.full(4, np.nan, np.float32),
                np.ones(1, np.float32))]

    obs_health.enable()
    try:
        trainer.train(reader=reader, num_passes=1,
                      feeding={"x": 0, "y": 1})
    finally:
        obs_health.disable()
    flat = obs_tele.snapshot()
    assert any(k.startswith("numerics_nonfinite_total{") and v > 0
               for k, v in flat.items()), flat
    assert "grad_global_norm" in flat


def test_numerics_monitor_parallel_trainer_wiring():
    """The mesh-parallel trainer installs a monitor too: the reductions
    run INSIDE the sharded jitted step and come back as replicated
    scalars, stripped before the user sees the fetches."""
    from paddle_tpu.parallel import ParallelTrainer, make_mesh

    fluid.framework.reset_unique_name()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[8, 4], dtype="float32",
                              append_batch_size=False)
        h = fluid.layers.fc(input=x, size=4)
        avg = fluid.layers.mean(x=h)
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(avg)
    obs_health.enable()
    try:
        tr = ParallelTrainer(main, startup, feed_names=["x"],
                             fetch_names=[avg.name],
                             mesh=make_mesh(n_devices=8)).init()
    finally:
        obs_health.disable()
    fetches = tr.step({"x": np.full((8, 4), np.nan, np.float32)})
    assert len(fetches) == 1          # monitor fetches were stripped
    flat = obs_tele.snapshot()
    assert any(k.startswith("numerics_nonfinite_total{") and v > 0
               for k, v in flat.items()), flat
    assert "grad_global_norm" in flat


def test_loss_scaler_dynamics_and_gauge():
    scaler = LossScaler(init_scale=1024.0, growth_interval=2,
                        min_scale=1.0)
    assert obs_tele.snapshot()["amp_loss_scale"] == 1024.0
    assert scaler.update(True) == 512.0       # overflow: back off
    assert scaler.update(False) == 512.0      # 1 clean step
    assert scaler.update(False) == 1024.0     # growth_interval reached
    assert obs_tele.snapshot()["amp_loss_scale"] == 1024.0
    for _ in range(40):
        scaler.update(True)
    assert scaler.scale == 1.0                # floored at min_scale

    # monitor drives the scaler from the on-device nonfinite counts
    cost, pg = _train_program()
    exe = _run_startup()
    main = fluid.default_main_program()
    mon = obs_health.NumericsMonitor.for_train_program(
        main, cost=cost, params_grads=pg,
        loss_scaler=LossScaler(init_scale=8.0, min_scale=1.0)).install()
    outs = exe.run(main, feed={"x": NAN_BATCH},
                   fetch_list=[cost] + mon.fetch_names)
    s = mon.record(outs[1:])
    assert s["loss_scale"] == 4.0


# ---------------------------------------------------------------------------
# locate_nonfinite
# ---------------------------------------------------------------------------

def test_locate_nonfinite_names_first_op_and_preserves_state():
    cost, _ = _train_program()
    exe = _run_startup()
    main = fluid.default_main_program()
    from paddle_tpu.core.scope import global_scope

    w_before = np.array(global_scope().get("fc_0.w_0"))
    report = obs_health.locate_nonfinite(main, {"x": NAN_BATCH})
    assert report is not None
    assert report["op_type"] == "mul"
    assert report["op_index"] == 0
    assert report["nonfinite_count"] > 0
    assert "mul" in report["message"]
    # the replay ran against a scope clone: optimizer state untouched
    np.testing.assert_array_equal(
        w_before, np.array(global_scope().get("fc_0.w_0")))
    # a finite feed replays clean
    assert obs_health.locate_nonfinite(main, {"x": ONES_BATCH}) is None
    # the check_nan_inf flag was restored
    assert flags.get_flag("check_nan_inf") is False


# ---------------------------------------------------------------------------
# XLA memory/cost attribution
# ---------------------------------------------------------------------------

def test_xla_cost_gauges_after_jit_build():
    prev = flags.get_flag("xla_cost_attribution")
    flags.set_flag("xla_cost_attribution", True)
    try:
        cost, _ = _train_program()
        exe = _run_startup()
        exe.run(fluid.default_main_program(), feed={"x": ONES_BATCH},
                fetch_list=[cost])
    finally:
        flags.set_flag("xla_cost_attribution", prev)
    flat = obs_tele.snapshot()
    seg_labels = [k for k in flat
                  if k.startswith("xla_argument_bytes{segment=")]
    assert seg_labels, "no xla_* gauges after a jit build:\n%s" % flat
    assert any(k.startswith("xla_flops{") for k in flat)
    # the gauges ride the unified /metrics render
    text = obs_registry.get_registry().render_text()
    assert "xla_argument_bytes{" in text


def test_xla_cost_attribution_off_by_default():
    assert flags.get_flag("xla_cost_attribution") is False
    cost, _ = _train_program()
    exe = _run_startup()
    exe.run(fluid.default_main_program(), feed={"x": ONES_BATCH},
            fetch_list=[cost])
    assert not any(k.startswith("xla_")
                   for k in obs_tele.snapshot())


def test_xla_cost_gauges_from_serving_warmup():
    """The serving surface gets attribution without any flag fiddling:
    warmup() turns it on for its bucket builds and restores it."""
    engine = _serving_engine(check_numerics=False)
    assert engine.warmup() == 2
    assert flags.get_flag("xla_cost_attribution") is False  # restored
    flat = obs_tele.snapshot()
    assert any(k.startswith("xla_argument_bytes{") for k in flat), flat
    assert any(k.startswith("xla_flops{") for k in flat)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_ring_bound_and_bundle_schema(tmp_path):
    rec = obs_flight.FlightRecorder(out_dir=str(tmp_path), capacity=4)
    for i in range(10):
        rec.record_step("t", i, feeds={"x": ONES_BATCH}, loss=float(i))
    rec.note("unit", detail="ctx")
    path = rec.dump(reason="unit-test")
    doc = obs_dump.validate_flight_bundle(path)
    assert len(doc["steps"]) == 4                 # ring bound
    assert doc["dropped_steps"] == 6
    assert [r["step"] for r in doc["steps"]] == [6, 7, 8, 9]
    assert doc["steps"][-1]["loss"] == 9.0
    assert doc["steps"][0]["feeds"] == {"x": "float32[2, 4]"}
    assert doc["notes"][-1]["origin"] == "unit"
    assert isinstance(doc["registry"], dict)
    rendered = obs_dump.render_flight(doc)
    assert "unit-test" in rendered


def test_flight_step_records_carry_telemetry_deltas(tmp_path):
    rec = obs_flight.FlightRecorder(out_dir=str(tmp_path))
    obs_registry.get_registry().counter("flight_probe_total").inc(3)
    r1 = rec.record_step("t", 0)
    assert r1["telemetry_delta"].get("flight_probe_total") == 3
    r2 = rec.record_step("t", 1)         # nothing moved since
    assert "flight_probe_total" not in r2["telemetry_delta"]
    obs_registry.get_registry().counter("flight_probe_total").inc()
    r3 = rec.record_step("t", 2)
    # counter deltas are INCREMENTS (1 tick this step), not the new
    # cumulative value (4) — a post-mortem reads per-step movement
    assert r3["telemetry_delta"].get("flight_probe_total") == 1


def test_flight_dump_storm_rotates_and_rate_limits(tmp_path):
    # rotation: total files bounded, NEWEST crashes keep their bundles
    # (a lifetime cap would spend the budget on early handled errors
    # and leave the genuine crash at the end with no post-mortem)
    rec = obs_flight.FlightRecorder(out_dir=str(tmp_path),
                                    max_bundles=2,
                                    min_dump_interval_s=0.0)
    last = [rec.dump_once(RuntimeError("e%d" % i), reason="storm")
            for i in range(10)][-1]
    bundles = sorted(f for f in os.listdir(str(tmp_path))
                     if f.startswith("flight_"))
    assert len(bundles) == 2              # rotated, not 10 files
    assert os.path.basename(last) in bundles   # newest survived
    assert rec.suppressed_dumps == 0

    # rate limit: within the interval, dump_once reuses the last path
    rec2 = obs_flight.FlightRecorder(out_dir=str(tmp_path / "rl"),
                                     min_dump_interval_s=3600.0)
    p1 = rec2.dump_once(RuntimeError("a"), reason="x")
    p2 = rec2.dump_once(RuntimeError("b"), reason="x")
    assert p1 == p2 and rec2.suppressed_dumps == 1


def test_flight_install_excepthook_and_dedup(tmp_path):
    rec = obs_flight.install(out_dir=str(tmp_path),
                             min_dump_interval_s=0.0)
    assert obs_flight.active()
    try:
        exc = RuntimeError("boom")
        p1 = obs_flight.on_crash(exc, origin="layer-a")
        p2 = obs_flight.on_crash(exc, origin="layer-b")  # same object
        assert p1 == p2                   # one bundle per exception
        assert os.path.exists(p1)
        # the chained excepthook writes for a fresh exception
        exc2 = ValueError("uncaught")
        sys.excepthook(ValueError, exc2, None)
        assert rec.last_bundle_path != p1
        doc = obs_dump.validate_flight_bundle(rec.last_bundle_path)
        assert doc["exception"]["type"] == "ValueError"
        with obs_flight.suppressed():
            assert obs_flight.on_crash(RuntimeError("x")) is None
    finally:
        obs_flight.uninstall()
    assert not obs_flight.active()
    assert obs_flight.on_crash(RuntimeError("after")) is None


def test_flight_crash_in_trainer_leaves_bundle(tmp_path):
    """Satellite: a trainer step that raises must leave a parseable
    bundle with the last step records and a registry snapshot, and
    obs_dump --flight must render it."""
    import paddle_tpu.v2 as paddle

    paddle.init()
    x = paddle.layer.data(name="x",
                          type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y",
                          type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1)
    cost = paddle.layer.square_error_cost(input=pred, label=y)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(
            momentum=0.9, learning_rate=0.1))

    def reader():
        yield [(np.ones(4, np.float32), np.ones(1, np.float32))]
        yield [(np.ones(7, np.float32),  # wrong width: step raises
                np.ones(1, np.float32))]

    rec = obs_flight.install(out_dir=str(tmp_path))
    try:
        with pytest.raises(Exception):
            trainer.train(reader=reader, num_passes=1,
                          feeding={"x": 0, "y": 1})
    finally:
        obs_flight.uninstall()
    bundle = rec.last_bundle_path
    assert bundle and os.path.exists(bundle)
    doc = obs_dump.validate_flight_bundle(bundle)
    assert doc["exception"] is not None
    assert doc["steps"], "no step records before the crash"
    assert doc["steps"][-1]["trainer"] == "v2"
    assert doc["registry"]
    assert any(n["origin"].startswith(("v2/train", "executor/run"))
               for n in doc["notes"])
    assert obs_dump.main(["--flight", bundle]) == 0


# ---------------------------------------------------------------------------
# the acceptance loop
# ---------------------------------------------------------------------------

def test_nan_training_full_loop(tmp_path, capsys):
    """Deliberately-NaN training run end to end:
    numerics_nonfinite_total increments -> locate_nonfinite names the
    first offending op -> the induced crash leaves a flight bundle ->
    obs_dump --flight renders it; xla_* gauges landed from the jit
    builds along the way."""
    cost, pg = _train_program()
    exe = _run_startup()
    main = fluid.default_main_program()
    mon = obs_health.NumericsMonitor.for_train_program(
        main, cost=cost, params_grads=pg).install()

    # 1. the monitored (jitted) run counts the nonfinites on device
    #    (memory/cost attribution on, as a serving/bench surface would)
    flags.set_flag("xla_cost_attribution", True)
    try:
        outs = exe.run(main, feed={"x": NAN_BATCH},
                       fetch_list=[cost] + mon.fetch_names)
    finally:
        flags.set_flag("xla_cost_attribution", False)
    assert mon.record(outs[1:])["found_nonfinite"]
    before = obs_tele.snapshot()
    assert any(k.startswith("numerics_nonfinite_total{") and v > 0
               for k, v in before.items())

    # 2. bisection names the first op that went non-finite
    report = obs_health.locate_nonfinite(main, {"x": NAN_BATCH})
    assert report["op_type"] == "mul" and report["op_index"] == 0

    # 3. the induced crash (eager check_nan_inf path through the
    #    executor) writes a flight bundle via the exception hook
    rec = obs_flight.install(out_dir=str(tmp_path))
    flags.set_flag("check_nan_inf", True)
    try:
        with pytest.raises(NonfiniteError):
            exe.run(main, feed={"x": NAN_BATCH}, fetch_list=[cost],
                    eager=True, use_program_cache=False)
    finally:
        flags.set_flag("check_nan_inf", False)
        obs_flight.uninstall()
    bundle = rec.last_bundle_path
    assert bundle and os.path.exists(bundle)
    doc = obs_dump.validate_flight_bundle(bundle)
    assert doc["exception"]["type"] == "NonfiniteError"
    # the bundle's registry snapshot carries the numerics counters AND
    # the per-segment memory/cost attribution
    assert any(k.startswith("numerics_nonfinite_total{") and v > 0
               for k, v in doc["registry"].items())
    assert any(k.startswith("xla_") for k in doc["registry"])

    # 4. the CLI renders it
    assert obs_dump.main(["--flight", bundle]) == 0
    out = capsys.readouterr().out
    assert "NonfiniteError" in out


# ---------------------------------------------------------------------------
# serving: check_numerics + enriched /healthz
# ---------------------------------------------------------------------------

def _serving_engine(check_numerics):
    from paddle_tpu.fluid import io as fluid_io
    from paddle_tpu.serving import EngineConfig, InferenceEngine

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[8], dtype="float32")
        probs = fluid.layers.fc(input=img, size=3, act="softmax")
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    program = fluid_io.prune_program(main, [probs])
    return InferenceEngine(
        program, ["img"], [probs], scope=scope,
        config=EngineConfig(batch_buckets=[2, 4],
                            check_numerics=check_numerics))


def test_engine_check_numerics_counts_nonfinite_outputs():
    engine = _serving_engine(check_numerics=True)
    engine.run({"img": np.zeros((2, 8), np.float32)})
    assert sum(v for k, v in obs_tele.snapshot().items()
               if k.startswith("numerics_nonfinite_total{")) == 0
    engine.run({"img": np.full((2, 8), np.nan, np.float32)})
    flat = obs_tele.snapshot()
    fetch = engine.fetch_names[0]
    assert flat["numerics_nonfinite_total{tensor=%s}" % fetch] > 0


def test_healthz_reports_registry_signals():
    from paddle_tpu.serving import InferenceServer
    from paddle_tpu.serving.server import ServerConfig

    engine = _serving_engine(check_numerics=True)
    server = InferenceServer(engine, ServerConfig(port=0)).start()
    host, port = server.address
    try:
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("POST", "/v1/infer", json.dumps(
            {"inputs": {"img": np.full((2, 8), np.nan).tolist()}}),
            {"Content-Type": "application/json"})
        assert conn.getresponse().status == 200
        conn.close()

        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        conn.close()
        assert resp.status == 200
        assert body["status"] == "ok"
        for key in ("queue_depth", "inflight_batches", "requests_total",
                    "responses_total", "errors_total", "shed_total",
                    "compile_cache_miss_total",
                    "numerics_nonfinite_total", "jit_traces_total"):
            assert key in body, body
        assert body["responses_total"] >= 1
        assert body["numerics_nonfinite_total"] > 0
        assert body["jit_traces_total"] > 0
        # in-process view agrees with the HTTP one
        sig = server.health_signals()
        assert sig["status"] == "ok"
    finally:
        server.shutdown()
    assert server.health_signals()["status"] == "draining"
