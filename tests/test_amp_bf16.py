"""bf16 mixed precision: MXU ops compute in bf16 with f32 master
weights (reference fp16 analog: paddle/math/float16.h)."""

import numpy as np

import paddle_tpu.fluid as fluid


def _train(steps=8):
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    label = fluid.layers.data(name="y", shape=[1], dtype="int64")
    h = fluid.layers.fc(input=x, size=32, act="relu")
    logits = fluid.layers.fc(input=h, size=4)
    cost = fluid.layers.mean(
        x=fluid.layers.softmax_with_cross_entropy(logits=logits,
                                                  label=label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    rs = np.random.RandomState(0)
    xs = rs.randn(32, 16).astype(np.float32)
    ys = (xs[:, :1] > 0).astype(np.int64)
    losses = []
    for _ in range(steps):
        out, = exe.run(fluid.default_main_program(),
                       feed={"x": xs, "y": ys}, fetch_list=[cost])
        losses.append(float(np.asarray(out).reshape(-1)[0]))
    return losses


def test_bf16_training_converges_and_params_stay_f32():
    with fluid.amp.bf16_guard():
        assert fluid.amp.bf16_enabled()
        losses = _train()
    assert losses[-1] < losses[0], losses
    # master weights stayed f32
    from paddle_tpu.core import scope as scope_mod

    block = fluid.default_main_program().global_block()
    for var in block.vars.values():
        if isinstance(var, fluid.Parameter):
            val = scope_mod.global_scope().get(var.name)
            assert np.asarray(val).dtype == np.float32
    assert not fluid.amp.bf16_enabled()


def test_bf16_conv_training_step():
    """The round-2 bench crash: conv grads under bf16 AMP.  Trains the
    driver's mini ResNet (conv+bn residual blocks) for three steps under
    bf16_guard — exercises conv2d forward AND both transpose convs of
    the vjp at a uniform dtype."""
    import jax
    from paddle_tpu.jit import FunctionalProgram, state_from_scope
    from __graft_entry__ import _build_model, _mini_resnet

    with fluid.amp.bf16_guard():
        main, startup, _, avg_loss = _build_model(
            _mini_resnet, 4, 16, 16, with_loss=True)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)
        fp = FunctionalProgram(main, ["image", "label"], [avg_loss.name])
        state = state_from_scope(fp, scope)
        rs = np.random.RandomState(0)
        feeds = {"image": rs.rand(4, 3, 16, 16).astype(np.float32),
                 "label": rs.randint(0, 16, (4, 1)).astype(np.int64)}
        step = jax.jit(lambda s, f: fp(s, f))
        losses = []
        for _ in range(3):
            fetches, state = step(state, feeds)
            losses.append(float(np.asarray(fetches[0]).reshape(-1)[0]))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


def test_bf16_conv_transpose_grad():
    """conv2d_transpose under AMP: forward + grad must be dtype-safe."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.registry import get_op_info

    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.rand(2, 4, 8, 8).astype(np.float32))
    w = jnp.asarray(rs.rand(4, 3, 3, 3).astype(np.float32))
    attrs = {"strides": [2, 2], "paddings": [1, 1], "dilations": [1, 1]}
    kernel = get_op_info("conv2d_transpose").kernel

    def loss(x, w):
        out = kernel(None, {"Input": [x], "Filter": [w]}, attrs)
        return jnp.sum(out["Output"][0] ** 2)

    with fluid.amp.bf16_guard():
        val, grads = jax.value_and_grad(loss, argnums=(0, 1))(x, w)
    assert np.isfinite(float(val))
    assert grads[0].shape == x.shape and grads[1].shape == w.shape
    assert grads[0].dtype == jnp.float32


def test_bf16_toggle_invalidates_cached_executable():
    """Same program, flag flipped between runs: results must reflect
    the new policy (cache key includes the flag)."""
    x = fluid.layers.data(name="x", shape=[64], dtype="float32")
    y = fluid.layers.fc(input=x, size=64, bias_attr=False,
                        param_attr=fluid.ParamAttr(
                            initializer=fluid.initializer.Constant(
                                1.0 + 2.0 ** -10)))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": np.full((1, 64), 1.0 + 2.0 ** -10, np.float32)}
    f32_out, = exe.run(fluid.default_main_program(), feed=feed,
                       fetch_list=[y])
    with fluid.amp.bf16_guard():
        bf16_out, = exe.run(fluid.default_main_program(), feed=feed,
                            fetch_list=[y])
    # 1+2^-10 is not representable in bf16 -> results differ
    assert not np.allclose(f32_out, bf16_out)
    again, = exe.run(fluid.default_main_program(), feed=feed,
                     fetch_list=[y])
    np.testing.assert_allclose(again, f32_out)
