"""bf16 mixed precision: MXU ops compute in bf16 with f32 master
weights (reference fp16 analog: paddle/math/float16.h)."""

import numpy as np

import paddle_tpu.fluid as fluid


def _train(steps=8):
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    label = fluid.layers.data(name="y", shape=[1], dtype="int64")
    h = fluid.layers.fc(input=x, size=32, act="relu")
    logits = fluid.layers.fc(input=h, size=4)
    cost = fluid.layers.mean(
        x=fluid.layers.softmax_with_cross_entropy(logits=logits,
                                                  label=label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    rs = np.random.RandomState(0)
    xs = rs.randn(32, 16).astype(np.float32)
    ys = (xs[:, :1] > 0).astype(np.int64)
    losses = []
    for _ in range(steps):
        out, = exe.run(fluid.default_main_program(),
                       feed={"x": xs, "y": ys}, fetch_list=[cost])
        losses.append(float(np.asarray(out).reshape(-1)[0]))
    return losses


def test_bf16_training_converges_and_params_stay_f32():
    with fluid.amp.bf16_guard():
        assert fluid.amp.bf16_enabled()
        losses = _train()
    assert losses[-1] < losses[0], losses
    # master weights stayed f32
    from paddle_tpu.core import scope as scope_mod

    block = fluid.default_main_program().global_block()
    for var in block.vars.values():
        if isinstance(var, fluid.Parameter):
            val = scope_mod.global_scope().get(var.name)
            assert np.asarray(val).dtype == np.float32
    assert not fluid.amp.bf16_enabled()


def test_bf16_conv_training_step():
    """The round-2 bench crash: conv grads under bf16 AMP.  Trains the
    driver's mini ResNet (conv+bn residual blocks) for three steps under
    bf16_guard — exercises conv2d forward AND both transpose convs of
    the vjp at a uniform dtype."""
    import jax
    from paddle_tpu.jit import FunctionalProgram, state_from_scope
    from __graft_entry__ import _build_model, _mini_resnet

    with fluid.amp.bf16_guard():
        main, startup, _, avg_loss = _build_model(
            _mini_resnet, 4, 16, 16, with_loss=True)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)
        fp = FunctionalProgram(main, ["image", "label"], [avg_loss.name])
        state = state_from_scope(fp, scope)
        rs = np.random.RandomState(0)
        feeds = {"image": rs.rand(4, 3, 16, 16).astype(np.float32),
                 "label": rs.randint(0, 16, (4, 1)).astype(np.int64)}
        step = jax.jit(lambda s, f: fp(s, f))
        losses = []
        for _ in range(3):
            fetches, state = step(state, feeds)
            losses.append(float(np.asarray(fetches[0]).reshape(-1)[0]))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


def test_bf16_conv_transpose_grad():
    """conv2d_transpose under AMP: forward + grad must be dtype-safe."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.registry import get_op_info

    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.rand(2, 4, 8, 8).astype(np.float32))
    w = jnp.asarray(rs.rand(4, 3, 3, 3).astype(np.float32))
    attrs = {"strides": [2, 2], "paddings": [1, 1], "dilations": [1, 1]}
    kernel = get_op_info("conv2d_transpose").kernel

    def loss(x, w):
        out = kernel(None, {"Input": [x], "Filter": [w]}, attrs)
        return jnp.sum(out["Output"][0] ** 2)

    with fluid.amp.bf16_guard():
        val, grads = jax.value_and_grad(loss, argnums=(0, 1))(x, w)
    assert np.isfinite(float(val))
    assert grads[0].shape == x.shape and grads[1].shape == w.shape
    assert grads[0].dtype == jnp.float32


def test_bf16_toggle_invalidates_cached_executable():
    """Same program, flag flipped between runs: results must reflect
    the new policy (cache key includes the flag)."""
    x = fluid.layers.data(name="x", shape=[64], dtype="float32")
    y = fluid.layers.fc(input=x, size=64, bias_attr=False,
                        param_attr=fluid.ParamAttr(
                            initializer=fluid.initializer.Constant(
                                1.0 + 2.0 ** -10)))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": np.full((1, 64), 1.0 + 2.0 ** -10, np.float32)}
    f32_out, = exe.run(fluid.default_main_program(), feed=feed,
                       fetch_list=[y])
    with fluid.amp.bf16_guard():
        bf16_out, = exe.run(fluid.default_main_program(), feed=feed,
                            fetch_list=[y])
    # 1+2^-10 is not representable in bf16 -> results differ
    assert not np.allclose(f32_out, bf16_out)
    again, = exe.run(fluid.default_main_program(), feed=feed,
                     fetch_list=[y])
    np.testing.assert_allclose(again, f32_out)


def test_bf16_activation_policy():
    """FLAGS_amp_bf16_act: conv/matmul results stay bf16 between ops
    (halving HBM traffic on the elementwise chains), while fetches and
    losses remain f32 at the API boundary."""
    import jax.numpy as jnp
    from paddle_tpu.ops.registry import get_op_info
    from paddle_tpu.utils import flags

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(2, 4, 8, 8).astype(np.float32))
    w = jnp.asarray(rs.rand(8, 4, 3, 3).astype(np.float32))
    conv = get_op_info("conv2d").kernel
    attrs = {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1]}

    with fluid.amp.bf16_guard():
        out = conv(None, {"Input": [x], "Filter": [w]}, attrs)["Output"][0]
        assert out.dtype == jnp.bfloat16
        # policy off: legacy cast-back-to-f32 behaviour
        flags.set_flag("amp_bf16_act", False)
        try:
            out32 = conv(None, {"Input": [x], "Filter": [w]},
                         attrs)["Output"][0]
        finally:
            flags.set_flag("amp_bf16_act", True)
        assert out32.dtype == jnp.float32

    # executor fetch boundary upcasts bf16 to f32
    x_in = fluid.layers.data(name="xa", shape=[16], dtype="float32")
    y = fluid.layers.fc(input=x_in, size=8)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    with fluid.amp.bf16_guard():
        out, = exe.run(fluid.default_main_program(),
                       feed={"xa": rs.rand(4, 16).astype(np.float32)},
                       fetch_list=[y])
    assert np.asarray(out).dtype == np.float32


def test_bf16_act_resnet_loss_matches_f32():
    """Mini-ResNet first-step loss under the bf16-activation policy is
    close to the f32 loss (bf16 keeps f32's exponent; ~3 decimal digits
    of mantissa over this shallow net)."""
    import jax
    from paddle_tpu.jit import FunctionalProgram, state_from_scope
    from __graft_entry__ import _build_model, _mini_resnet

    def first_loss(amp):
        ctx = fluid.amp.bf16_guard() if amp else _noop()
        with ctx:
            main, startup, _, avg_loss = _build_model(
                _mini_resnet, 4, 16, 16, with_loss=True)
            scope = fluid.Scope()
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup, scope=scope)
            fp = FunctionalProgram(main, ["image", "label"],
                                   [avg_loss.name])
            state = state_from_scope(fp, scope)
            rs = np.random.RandomState(0)
            feeds = {"image": rs.rand(4, 3, 16, 16).astype(np.float32),
                     "label": rs.randint(0, 16, (4, 1)).astype(np.int64)}
            fetches, _ = jax.jit(lambda s, f: fp(s, f))(state, feeds)
            return float(np.asarray(fetches[0]).reshape(-1)[0])

    import contextlib

    @contextlib.contextmanager
    def _noop():
        yield

    l_amp = first_loss(True)
    l_f32 = first_loss(False)
    assert abs(l_amp - l_f32) / max(abs(l_f32), 1e-6) < 0.05, \
        (l_amp, l_f32)


def test_bf16_lstm_training_step():
    """Recurrent path under the bf16-activation policy: the lstm/gru
    scan carries stay f32 (cross-timestep accumulators) while the MXU
    projections run bf16 — the scan must be dtype-stable."""
    from paddle_tpu.core.ragged import RaggedTensor
    from paddle_tpu.models.text import stacked_lstm_text_classifier

    with fluid.amp.bf16_guard():
        data = fluid.layers.data(name="w_amp", shape=[1], dtype="int64",
                                 lod_level=1)
        probs = stacked_lstm_text_classifier(data, 100, hid_dim=16)
        label = fluid.layers.data(name="l_amp", shape=[1], dtype="int64")
        loss = fluid.layers.mean(
            x=fluid.layers.cross_entropy(input=probs, label=label))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rs = np.random.RandomState(0)
        seqs = [rs.randint(0, 100, size=(rs.randint(3, 7), 1))
                .astype(np.int64) for _ in range(6)]
        feeds = {"w_amp": RaggedTensor.from_sequences(seqs),
                 "l_amp": rs.randint(0, 2, size=(6, 1)).astype(np.int64)}
        losses = [float(np.asarray(
            exe.run(fluid.default_main_program(), feed=feeds,
                    fetch_list=[loss])[0]).reshape(-1)[0])
            for _ in range(4)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


def test_bn_backward_reuses_forward_statistics():
    """IR-level perf contract: the whole conv+BN training step must
    contain exactly 5 per-channel (0,2,3) reductions — 2 forward
    statistics (sum, sum-of-squares), 2 backward grad sums (g1, g2),
    and the conv bias grad.  A 6th/7th reduction means batch_norm_grad
    stopped reusing the forward's SavedMean/SavedVariance (the O@-slot
    regression fixed this round) and is re-sweeping the activation."""
    import re

    import jax
    import jax.numpy as jnp

    import paddle_tpu.fluid as fluid
    from paddle_tpu.jit import FunctionalProgram, state_from_scope
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid.executor import scope_guard

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[8, 16, 16],
                              dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        c = fluid.layers.conv2d(input=x, num_filters=8, filter_size=3,
                                padding=1)
        bn = fluid.layers.batch_norm(input=c, act="relu")
        p = fluid.layers.pool2d(input=bn, pool_size=16, pool_type="avg")
        logits = fluid.layers.fc(input=p, size=10, act="softmax")
        loss = fluid.layers.mean(
            x=fluid.layers.cross_entropy(input=logits, label=y))
        fluid.optimizer.Momentum(learning_rate=0.1,
                                 momentum=0.9).minimize(loss)

    with scope_guard(Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fp = FunctionalProgram(main, ["x", "y"], [loss.name])
        state = state_from_scope(fp)
        feeds = {"x": jnp.zeros((4, 8, 16, 16), jnp.float32),
                 "y": jnp.zeros((4, 1), jnp.int32)}
        jaxpr = str(jax.make_jaxpr(lambda s, f: fp(s, f))(state, feeds))
    per_channel = len(re.findall(r"axes=\(0, 2, 3\)", jaxpr))
    # Upper bound only: the exact count (5 = 2 fwd stats + 2 bwd sums
    # + conv bias grad) is brittle against unrelated ops and jaxpr
    # printing changes; the lower bound (grad actually READS the saved
    # slots rather than recomputing) is pinned by the dedicated
    # slot-read unit test (test_conv_norm_ops.py
    # test_bn_grad_reads_saved_stats_slot).
    assert per_channel <= 5, (
        "expected at most 5 per-channel reductions (2 fwd stats + "
        "2 bwd sums + conv bias grad), found %d — batch_norm_grad is "
        "re-sweeping the activation instead of reusing saved "
        "statistics" % per_channel)
