"""paddle_tpu.analysis.shard: the static SPMD/sharding analyzer and
its wiring (S0xx codes, mesh validation, reasoned spec fallbacks, the
comm cost model, the FLAGS_verify_sharding trainer gate, transpiler
split validation).

Negative tests seed real sharding mistakes and assert the STABLE
diagnostic code (docs/ANALYSIS.md) — the same contract the proglint
--mesh selftest and CI enforce."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import analysis
from paddle_tpu.analysis import costmodel
from paddle_tpu.parallel import (MeshConfig, make_mesh, parse_mesh_spec,
                                 param_spec_reason, zero1_spec_reason)
from paddle_tpu.utils import flags


def _build_mlp(batch=None, width=1024):
    """fc -> relu -> fc -> mean(+SGD) in a fresh Program pair; width
    1024 makes the fc weights mp-shardable under the default rules."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        if batch is None:
            x = fluid.layers.data(name="x", shape=[width],
                                  dtype="float32")
        else:
            x = fluid.layers.data(name="x", shape=[batch, width],
                                  dtype="float32",
                                  append_batch_size=False)
        h = fluid.layers.fc(input=x, size=width, act="relu")
        h2 = fluid.layers.fc(input=h, size=width)
        loss = fluid.layers.mean(x=h2)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss.name


# ---------------------------------------------------------------------------
# mesh descriptions (satellite: errors must NAME the axes)
# ---------------------------------------------------------------------------

def test_parse_mesh_spec():
    cfg = parse_mesh_spec("dp=4,mp=2")
    assert dict(cfg.shape) == {"dp": 4, "mp": 2}
    assert list(MeshConfig.parse("pp=4,dp=2").shape) == ["pp", "dp"]
    with pytest.raises(ValueError, match="unknown axis"):
        parse_mesh_spec("dp=4,zz=2")
    with pytest.raises(ValueError, match="axis=size"):
        parse_mesh_spec("dp4")
    with pytest.raises(ValueError, match="named twice"):
        parse_mesh_spec("dp=2,dp=4")


def test_mesh_config_validate_names_axes():
    with pytest.raises(ValueError) as err:
        MeshConfig(dp=4, mp=3).validate(8)
    assert "dp=4" in str(err.value) and "mp=3" in str(err.value)
    # dp=None: the remaining devices must divide the other axes
    with pytest.raises(ValueError) as err:
        MeshConfig(mp=3).validate(8)
    assert "mp=3" in str(err.value)
    MeshConfig(dp=4, mp=2).validate(8)
    MeshConfig(mp=2).validate(8)


def test_make_mesh_error_names_axes():
    with pytest.raises(ValueError) as err:
        make_mesh(n_devices=8, dp=4, mp=3)
    assert "dp=4" in str(err.value) and "mp=3" in str(err.value)
    with pytest.raises(ValueError) as err:
        make_mesh(n_devices=8, mp=3)
    assert "mp=3" in str(err.value) and "8" in str(err.value)


# ---------------------------------------------------------------------------
# reasoned spec fallbacks (satellite: no more silent replication)
# ---------------------------------------------------------------------------

def test_param_spec_reason():
    mesh = parse_mesh_spec("dp=4,mp=2")
    spec, reason = param_spec_reason("w", (1024, 1024), mesh)
    assert tuple(spec) == ("mp", None) and reason is None  # row table
    spec, reason = param_spec_reason("w", (512, 1024), mesh)
    assert tuple(spec) == (None, "mp") and reason is None
    # deliberate policy: non-2D / no mp axis -> no reason
    assert param_spec_reason("c", (64, 3, 3, 3), mesh)[1] is None
    assert param_spec_reason("w", (1024, 1024),
                             parse_mesh_spec("dp=8"))[1] is None
    # forced fallbacks carry the why
    _, r = param_spec_reason("w", (100, 200), mesh)
    assert r and "min_shard_dim" in r
    _, r = param_spec_reason("w", (513, 1023), mesh)
    assert r and "not divisible" in r


def test_zero1_spec_reason():
    mesh = parse_mesh_spec("dp=4,mp=2")
    spec, reason = zero1_spec_reason((), (1024,), mesh)
    assert tuple(spec) == ("dp",) and reason is None
    _, r = zero1_spec_reason((), (6,), mesh)
    assert r and "dp=4" in r
    _, r = zero1_spec_reason((), (), mesh)
    assert r and "scalar" in r


# ---------------------------------------------------------------------------
# S0xx diagnostics
# ---------------------------------------------------------------------------

def test_s001_unmatched_rule():
    main, _, loss = _build_mlp()
    plan = analysis.analyze_sharding(
        main, {"dp": 4, "mp": 2}, fetches=[loss],
        rules=[("^no_such_param$", ())], publish=False)
    diags = [d for d in plan.report.diagnostics if d.code == "S001"]
    assert diags and all(d.severity == "warning" for d in diags)
    assert "matched no partition rule" in diags[0].message


def test_s001_heuristic_cites_reason():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[513], dtype="float32")
        fluid.layers.fc(input=x, size=1023)  # 513x1023: near miss
    plan = analysis.analyze_sharding(main, {"dp": 4, "mp": 2},
                                     publish=False)
    diags = [d for d in plan.report.diagnostics if d.code == "S001"
             and d.var_name == "fc_0.w_0"]
    assert diags and "not divisible" in diags[0].message
    assert plan.param_reasons["fc_0.w_0"]


def test_s002_concrete_feed_batch_is_error():
    main, _, loss = _build_mlp(batch=6)
    plan = analysis.analyze_sharding(main, {"dp": 4, "mp": 2},
                                     fetches=[loss], publish=False,
                                     concrete_feeds=True)
    errs = [d for d in plan.report.errors if d.code == "S002"]
    assert errs and errs[0].var_name == "x"
    assert "dp=4" in errs[0].message


def test_s002_pinned_feed_batch_is_advisory():
    main, _, loss = _build_mlp(batch=6)
    plan = analysis.analyze_sharding(main, {"dp": 4, "mp": 2},
                                     fetches=[loss], publish=False)
    assert plan.report.ok()
    infos = [d for d in plan.report.by_severity("info")
             if d.code == "S002"]
    assert infos and "rebuild" in infos[0].message


def test_s002_rule_forced_non_divisible_param():
    main, _, loss = _build_mlp()
    # a rule that row-shards a 1024-row weight over a 3-wide axis
    plan = analysis.analyze_sharding(
        main, {"dp": 2, "mp": 3}, fetches=[loss],
        rules=[(r"\.w_0$", ("mp", None)), (".*", ())], publish=False)
    errs = [d for d in plan.report.errors if d.code == "S002"]
    assert errs, plan.report.format()
    assert "mp=3" in errs[0].message


def test_s004_unknown_axis_in_rule_or_feed_spec():
    """A typo'd axis name in a partition rule / feed override must
    NOT silently analyze as unsharded (factor 1)."""
    main, _, loss = _build_mlp()
    plan = analysis.analyze_sharding(
        main, {"dp": 4, "mp": 2}, fetches=[loss],
        rules=[(r"\.w_0$", ("tp", None)), (".*", ())], publish=False)
    errs = [d for d in plan.report.errors if d.code == "S004"]
    assert errs and "'tp'" in errs[0].message, plan.report.format()
    plan = analysis.analyze_sharding(
        main, {"dp": 4, "mp": 2}, fetches=[loss],
        feed_specs={"x": ("data",)}, publish=False)
    assert any(d.code == "S004" and d.var_name == "x"
               for d in plan.report.errors), plan.report.format()


def test_comm_pricing_follows_dtype():
    """bf16 tensors price their collectives at 2 bytes/element, same
    as the dtype-aware grad-sync path — rankings stay consistent."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = fluid.layers.data(name="a", shape=[8, 16],
                              dtype="bfloat16",
                              append_batch_size=False)
        b = fluid.layers.data(name="b", shape=[8, 16],
                              dtype="bfloat16",
                              append_batch_size=False)
        fluid.layers.elementwise_add(x=a, y=b)
    plan = analysis.analyze_sharding(
        main, {"dp": 4, "mp": 2}, feed_specs={"b": ("mp",)},
        publish=False)
    ev = next(e for e in plan.comm.events
              if e.collective == "allgather")
    assert ev.payload_bytes == 8 * 16 * 2, ev.to_dict()


def test_s003_conflicting_layouts():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = fluid.layers.data(name="a", shape=[8, 16], dtype="float32",
                              append_batch_size=False)
        b = fluid.layers.data(name="b", shape=[8, 16], dtype="float32",
                              append_batch_size=False)
        fluid.layers.elementwise_add(x=a, y=b)
    plan = analysis.analyze_sharding(
        main, {"dp": 4, "mp": 2}, feed_specs={"b": ("mp",)},
        publish=False)
    diags = [d for d in plan.report.diagnostics if d.code == "S003"]
    assert diags and diags[0].op_type == "elementwise_add"
    # the implicit reshard is priced
    assert any(ev.collective == "allgather"
               for ev in plan.comm.events)


def test_s004_pipeline_schedule():
    rep = analysis.check_pipeline({"pp": 4, "dp": 2}, n_stages=3,
                                  n_microbatches=8)
    assert [d.code for d in rep.errors] == ["S004"]
    assert "3 stages" in rep.errors[0].message
    rep = analysis.check_pipeline({"pp": 4}, n_stages=4,
                                  n_microbatches=2)
    assert rep.ok() and rep.has("S004")  # bubble warning
    rep = analysis.check_pipeline({"dp": 8}, n_stages=4,
                                  n_microbatches=8)
    assert not rep.ok() and "not a mesh axis" in rep.errors[0].message
    rep = analysis.check_pipeline({"pp": 4}, n_stages=4,
                                  n_microbatches=3, batch_size=8)
    assert any("not divisible into 3 microbatches" in d.message
               for d in rep.errors)
    # degenerate pp=1 / zero microbatches must not crash
    rep = analysis.check_pipeline({"pp": 1}, n_stages=1,
                                  n_microbatches=0)
    assert rep.ok()


def test_s004_moe_schedule():
    rep = analysis.check_moe({"dp": 2, "ep": 4}, n_experts=6)
    assert not rep.ok() and "6 experts" in rep.errors[0].message
    # guaranteed capacity overflow: factor 0.25 drops 3/4 of tokens
    rep = analysis.check_moe({"dp": 2, "ep": 4}, n_experts=8,
                             capacity_factor=0.25, tokens=1024)
    assert rep.has("S004")
    assert any("dropped EVERY step" in d.message
               for d in rep.diagnostics)
    # clean config
    rep = analysis.check_moe({"dp": 2, "ep": 4}, n_experts=8,
                             capacity_factor=2.0, tokens=1024)
    assert rep.ok() and not rep.diagnostics


def test_s004_ring_schedule():
    rep = analysis.check_ring({"dp": 4, "mp": 2}, seq_len=32)
    assert not rep.ok()
    rep = analysis.check_ring({"sp": 2, "dp": 4}, seq_len=33)
    assert rep.has("S002")
    rep = analysis.check_ring({"sp": 2, "dp": 4}, seq_len=32,
                              n_heads=3, mode="ulysses")
    assert rep.has("S004")
    assert analysis.check_ring({"sp": 2, "dp": 4}, seq_len=32,
                               n_heads=4, mode="ulysses").ok()


def test_s005_hbm_budget():
    main, _, loss = _build_mlp()
    plan = analysis.analyze_sharding(main, {"dp": 4, "mp": 2},
                                     fetches=[loss], hbm_gb=1e-6,
                                     publish=False)
    errs = [d for d in plan.report.errors if d.code == "S005"]
    assert errs and "budget" in errs[0].message
    assert plan.peak_hbm_bytes > 0
    bd = plan.hbm_breakdown
    assert bd["params_bytes"] > 0 and bd["activation_peak_bytes"] > 0
    # a sane budget passes
    ok = analysis.analyze_sharding(main, {"dp": 4, "mp": 2},
                                   fetches=[loss], hbm_gb=16,
                                   publish=False)
    assert not ok.report.has("S005")


def test_hbm_shrinks_with_mp():
    main, _, loss = _build_mlp()
    rep1 = analysis.analyze_sharding(main, {"dp": 8}, fetches=[loss],
                                     publish=False)
    rep2 = analysis.analyze_sharding(main, {"dp": 4, "mp": 2},
                                     fetches=[loss], publish=False)
    # mp shards the two 1024x1024 weights: params halve (roughly)
    assert rep2.hbm_breakdown["params_bytes"] < \
        rep1.hbm_breakdown["params_bytes"]


# ---------------------------------------------------------------------------
# comm cost model
# ---------------------------------------------------------------------------

def test_collective_wire_bytes():
    assert costmodel.collective_wire_bytes("allreduce", 1000, 4) == 1500
    assert costmodel.collective_wire_bytes("allgather", 1000, 4) == 750
    assert costmodel.collective_wire_bytes("allreduce", 1000, 1) == 0
    with pytest.raises(ValueError):
        costmodel.collective_wire_bytes("gossip", 1, 2)


def test_comm_cost_dp_grad_sync():
    main, _, loss = _build_mlp()
    plan = analysis.analyze_sharding(main, {"dp": 8}, fetches=[loss],
                                     publish=False)
    sync = [ev for ev in plan.comm.events
            if "grad sync" in ev.detail]
    # 2 weights + 2 biases, all replicated under dp-only
    assert len(sync) == 4
    w = next(ev for ev in sync if "fc_0.w_0" in ev.detail)
    # 1024*1024*4 bytes, ring all-reduce factor 2*(8-1)/8
    assert w.wire_bytes == int(1024 * 1024 * 4 * 2 * 7 / 8)
    assert plan.comm.totals()["allreduce"] > 0
    assert plan.comm.step_seconds_floor() > 0


def test_comm_cost_zero1_reduce_scatter():
    main, _, loss = _build_mlp()
    plan = analysis.analyze_sharding(main, {"dp": 8}, fetches=[loss],
                                     zero_stage=1, publish=False)
    colls = {ev.collective for ev in plan.comm.events}
    assert "reducescatter" in colls and "allgather" in colls


def test_comm_cost_published_to_registry():
    from paddle_tpu.obs import registry as obs_registry

    main, _, loss = _build_mlp()
    analysis.analyze_sharding(main, {"dp": 8}, fetches=[loss],
                              publish=True)
    snap = {s["name"] for s in
            obs_registry.get_registry().to_dict()["metrics"]}
    assert "shard_comm_bytes_total" in snap
    assert "shard_peak_hbm_bytes" in snap


def test_batched_matmul_contraction_dim():
    """matmul with ndim>2 operands: Y's contraction dim is -2, not the
    batch dim — a dp-sharded batch dim on Y must not fake an S003."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = fluid.layers.data(name="a", shape=[8, 16, 32],
                              dtype="float32",
                              append_batch_size=False)
        b = fluid.layers.data(name="b", shape=[8, 32, 16],
                              dtype="float32",
                              append_batch_size=False)
        fluid.layers.matmul(x=a, y=b)
    plan = analysis.analyze_sharding(main, {"dp": 4, "mp": 2},
                                     publish=False)
    # both batch dims carry dp; contractions are unsharded: no S003,
    # no partial-sum allreduce
    assert not plan.report.has("S003"), plan.report.format()
    assert not any("partial-sum" in ev.detail
                   for ev in plan.comm.events)


def test_mp_sharding_plan_and_matmul_partials():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[1024], dtype="float32")
        # 1024x1024 row-shardable table feeding a matmul: rows >= cols
        # and >= min_shard_dim*mp -> P(mp, None), a sharded contraction
        h = fluid.layers.fc(input=x, size=2048)
        loss = fluid.layers.mean(x=h)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    plan = analysis.analyze_sharding(main, {"dp": 4, "mp": 2},
                                     fetches=[loss.name],
                                     publish=False)
    assert plan.sharded_params(), plan.param_reasons
    assert plan.report.ok(), plan.report.format()


# ---------------------------------------------------------------------------
# trust-boundary wiring
# ---------------------------------------------------------------------------

class _MustNotRun:
    """An 'executor' that fails the test if anything executes."""

    def run(self, *a, **k):
        raise AssertionError("startup executed: the sharding gate did "
                             "not reject before lowering")


def test_trainer_init_rejects_s002_before_any_lowering():
    from paddle_tpu.obs import registry as obs_registry
    from paddle_tpu.parallel import ParallelTrainer

    main, startup, loss = _build_mlp(batch=6)  # 6 % dp=4 != 0
    mesh = make_mesh(n_devices=8, mp=2)
    prev = flags.get_flag("verify_sharding")
    flags.set_flag("verify_sharding", True)
    try:
        trainer = ParallelTrainer(main, startup, feed_names=["x"],
                                  fetch_names=[loss], mesh=mesh)
        with pytest.raises(analysis.ProgramVerificationError) as err:
            trainer.init(executor=_MustNotRun())
        assert "S002" in str(err.value)
        assert "x" in str(err.value) and "dp=4" in str(err.value)
    finally:
        flags.set_flag("verify_sharding", prev)
    # zero jit traces: nothing compiled, the executor never ran, no
    # telemetry counter was ever created
    snap = {s["name"] for s in
            obs_registry.get_registry().to_dict()["metrics"]}
    assert "executor_jit_traces_total" not in snap
    assert trainer.state is None and trainer._step_fn is None


def test_trainer_init_passes_clean_program_with_gate():
    from paddle_tpu.parallel import ParallelTrainer

    main, startup, loss = _build_mlp(batch=8, width=64)
    mesh = make_mesh(n_devices=8, mp=2)
    prev = flags.get_flag("verify_sharding")
    flags.set_flag("verify_sharding", True)
    try:
        trainer = ParallelTrainer(main, startup, feed_names=["x"],
                                  fetch_names=[loss], mesh=mesh).init()
        (out,) = trainer.step(
            {"x": np.random.RandomState(0)
             .rand(8, 64).astype(np.float32)})
        assert np.isfinite(np.asarray(out)).all()
    finally:
        flags.set_flag("verify_sharding", prev)


def test_make_parallel_step_gate():
    from paddle_tpu.jit import FunctionalProgram, state_from_scope
    from paddle_tpu.parallel import make_parallel_step

    main, startup, loss = _build_mlp(batch=6)
    mesh = make_mesh(n_devices=8, mp=2)
    scope = fluid.Scope()
    fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
    fp = FunctionalProgram(main, ["x"], [loss])
    state = state_from_scope(fp, scope)
    prev = flags.get_flag("verify_sharding")
    flags.set_flag("verify_sharding", True)
    try:
        with pytest.raises(analysis.ProgramVerificationError):
            make_parallel_step(main, ["x"], [loss], mesh, state)
    finally:
        flags.set_flag("verify_sharding", prev)


def test_pipeline_apply_gate():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel import pipeline_apply, stack_stage_params

    mesh = make_mesh(n_devices=8, pp=4, axes=("pp", "dp"))
    stages = [{"w": jnp.eye(8, dtype=jnp.float32)} for _ in range(4)]
    stacked = stack_stage_params(stages)
    x = jnp.ones((8, 8), jnp.float32)
    prev = flags.get_flag("verify_sharding")
    flags.set_flag("verify_sharding", True)
    try:
        with pytest.raises(analysis.ProgramVerificationError) as err:
            # 3 microbatches cannot tile the batch of 8
            pipeline_apply(mesh, lambda p, h: h @ p["w"], stacked, x, 3)
        assert "S004" in str(err.value)
    finally:
        flags.set_flag("verify_sharding", prev)


def test_schedule_introspection_hooks():
    from paddle_tpu.parallel import (expert_capacity, moe_axis_info,
                                     pipeline_schedule_info,
                                     sp_axis_info)

    info = pipeline_schedule_info({"pp": 4, "dp": 2}, 8,
                                  batch_size=32)
    assert info["stages"] == 4 and info["ticks"] == 11
    assert info["microbatch_size"] == 4
    assert 0 < info["bubble_fraction"] < 1
    assert expert_capacity(128, 8, 2.0) == 32
    m = moe_axis_info({"dp": 2, "ep": 4}, 8, tokens=1024)
    assert m["experts_per_device"] == 2 and m["capacity"] > 0
    s = sp_axis_info({"sp": 2}, seq_len=32, n_heads=4, mode="ulysses")
    assert s["local_seq"] == 16 and s["local_heads"] == 2


def test_transpiler_validates_split_blocks():
    from paddle_tpu.distributed.transpiler import DistributeTranspiler

    def bad_split(var_list, pserver_count, **kw):
        # drops the tail of every parameter
        return [(v.name, 0, 0, max(int(np.prod(v.shape)) - 1, 1))
                for v in var_list]

    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(input=x, size=1)
    loss = fluid.layers.mean(
        x=fluid.layers.square_error_cost(input=pred, label=y))
    optimize_ops, params_grads = fluid.optimizer.SGD(
        learning_rate=0.01).minimize(loss)
    t = DistributeTranspiler()
    with pytest.raises(ValueError, match="covers"):
        t.transpile(optimize_ops=optimize_ops,
                    params_grads=params_grads, trainer_id=0,
                    trainers=2, pservers="127.0.0.1:6174",
                    split_method=bad_split)

    def dropping_split(var_list, pserver_count, **kw):
        # silently forgets every parameter but the first
        from paddle_tpu.distributed.transpiler import \
            split_dense_variable

        return split_dense_variable(var_list[:1], pserver_count)

    with pytest.raises(ValueError, match="no pserver blocks"):
        DistributeTranspiler().transpile(
            optimize_ops=optimize_ops, params_grads=params_grads,
            trainer_id=0, trainers=2, pservers="127.0.0.1:6174",
            split_method=dropping_split)


# ---------------------------------------------------------------------------
# the acceptance sweep: clean programs on all four dryrun mesh shapes
# ---------------------------------------------------------------------------

DRYRUN_MESHES = ["dp=4,mp=2", "dp=2,mp=2,sp=2", "pp=4,dp=2",
                 "dp=2,ep=4"]


@pytest.mark.parametrize("mesh_spec", DRYRUN_MESHES)
def test_lenet5_clean_on_dryrun_meshes(mesh_spec):
    from paddle_tpu.models.image import lenet5

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[1, 28, 28],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1],
                                  dtype="int64")
        probs = lenet5(img, class_dim=10)
        loss = fluid.layers.mean(
            x=fluid.layers.cross_entropy(input=probs, label=label))
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.01, momentum=0.9).minimize(loss)
    plan = analysis.analyze_sharding(main, parse_mesh_spec(mesh_spec),
                                     fetches=[loss.name],
                                     publish=False)
    assert plan.report.ok(), plan.report.format()


def test_lint_cli_golden_mesh(capsys):
    from paddle_tpu.tools import lint_cli

    rc = lint_cli.main(["--golden", "--quiet", "--mesh", "dp=4,mp=2"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "mesh={'dp': 4, 'mp': 2}" in out


def test_lint_cli_mesh_publishes_each_finding_once(tmp_path, capsys):
    """--mesh must not re-publish the already-counted base report:
    every diagnostic lands in analysis_diagnostics_total exactly
    once."""
    import json
    import os

    from paddle_tpu.obs import registry as obs_registry
    from paddle_tpu.tools import lint_cli

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4, 8], dtype="float32",
                              append_batch_size=False)
        fluid.layers.scale(x=x, scale=2.0)
        # a declared-but-unreferenced var: exactly one D002 info
        main.global_block().create_var(name="orphan", shape=[1],
                                       dtype="float32")
    model_dir = str(tmp_path / "model")
    os.makedirs(model_dir)
    with open(os.path.join(model_dir, "__model__"), "w") as f:
        json.dump({"program": main.desc.to_dict()}, f)
    rc = lint_cli.main([model_dir, "--mesh", "dp=4", "--quiet"])
    capsys.readouterr()
    assert rc == 0
    samples = [s for s in
               obs_registry.get_registry().to_dict()["metrics"]
               if s["name"] == "analysis_diagnostics_total"
               and (s.get("labels") or {}).get("code") == "D002"]
    assert samples and samples[0]["value"] == 1, samples


def test_lint_cli_mesh_json(tmp_path, capsys):
    import json
    import os

    from paddle_tpu.tools import lint_cli

    main, _, loss = _build_mlp(width=64)
    export = fluid.Program()
    model_dir = str(tmp_path / "model")
    os.makedirs(model_dir)
    with open(os.path.join(model_dir, "__model__"), "w") as f:
        json.dump({"program": main.desc.to_dict(),
                   "fetch_names": [loss]}, f)
    rc = lint_cli.main([model_dir, "--mesh", "dp=8", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["sharding"]["mesh"] == {"dp": 8}
    assert doc["sharding"]["comm"]["totals"].get("allreduce", 0) > 0
    assert doc["sharding"]["peak_hbm_bytes"] > 0
