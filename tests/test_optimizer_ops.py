"""Op tests: optimizer update ops, dense + SelectedRows sparse paths
(reference: test_sgd_op.py, test_momentum_op.py, test_adam_op.py,
test_adamax_op.py, test_adagrad_op.py, test_decayed_adagrad_op.py,
test_adadelta_op.py, test_rmsprop_op.py, test_ftrl_op.py,
test_proximal_gd_op.py, test_proximal_adagrad_op.py)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.core.ragged import SelectedRows
from op_test import OpTest

RS = np.random.RandomState(5)


def _pgl(shape=(4, 3)):
    p = RS.rand(*shape).astype("float32")
    g = RS.rand(*shape).astype("float32")
    lr = np.asarray([0.1], dtype="float32")
    return p, g, lr


class TestSGD(OpTest):
    op_type = "sgd"

    def test(self):
        p, g, lr = _pgl()
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr}
        self.outputs = {"ParamOut": p - 0.1 * g}
        self.check_output()


class TestMomentum(OpTest):
    op_type = "momentum"

    def test(self):
        p, g, lr = _pgl()
        v = RS.rand(*p.shape).astype("float32")
        mu = 0.9
        v_out = mu * v + g
        self.inputs = {"Param": p, "Grad": g, "Velocity": v,
                       "LearningRate": lr}
        self.attrs = {"mu": mu}
        self.outputs = {"ParamOut": p - 0.1 * v_out, "VelocityOut": v_out}
        self.check_output()


class TestMomentumNesterov(OpTest):
    op_type = "momentum"

    def test(self):
        p, g, lr = _pgl()
        v = RS.rand(*p.shape).astype("float32")
        mu = 0.9
        v_out = mu * v + g
        p_out = p - (g + mu * v_out) * 0.1
        self.inputs = {"Param": p, "Grad": g, "Velocity": v,
                       "LearningRate": lr}
        self.attrs = {"mu": mu, "use_nesterov": True}
        self.outputs = {"ParamOut": p_out, "VelocityOut": v_out}
        self.check_output()


class TestAdam(OpTest):
    op_type = "adam"

    def test(self):
        p, g, lr = _pgl()
        m1 = RS.rand(*p.shape).astype("float32")
        m2 = RS.rand(*p.shape).astype("float32")
        b1, b2, eps = 0.9, 0.999, 1e-8
        b1p = np.asarray([b1 ** 3], dtype="float32")
        b2p = np.asarray([b2 ** 3], dtype="float32")
        m1o = b1 * m1 + (1 - b1) * g
        m2o = b2 * m2 + (1 - b2) * g * g
        lr_t = 0.1 * np.sqrt(1 - b2p) / (1 - b1p)
        p_out = p - lr_t * m1o / (np.sqrt(m2o) + eps)
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr,
                       "Moment1": m1, "Moment2": m2,
                       "Beta1Pow": b1p, "Beta2Pow": b2p}
        self.attrs = {"beta1": b1, "beta2": b2, "epsilon": eps}
        self.outputs = {"ParamOut": p_out, "Moment1Out": m1o,
                        "Moment2Out": m2o}
        self.check_output()


class TestAdamax(OpTest):
    op_type = "adamax"

    def test(self):
        p, g, lr = _pgl()
        m = RS.rand(*p.shape).astype("float32")
        inf = RS.rand(*p.shape).astype("float32") + 0.1
        b1, b2, eps = 0.9, 0.999, 1e-8
        b1p = np.asarray([b1 ** 2], dtype="float32")
        m_out = b1 * m + (1 - b1) * g
        inf_out = np.maximum(b2 * inf, np.abs(g))
        p_out = p - (0.1 / (1 - b1p)) * m_out / (inf_out + eps)
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr,
                       "Moment": m, "InfNorm": inf, "Beta1Pow": b1p}
        self.attrs = {"beta1": b1, "beta2": b2, "epsilon": eps}
        self.outputs = {"ParamOut": p_out, "MomentOut": m_out,
                        "InfNormOut": inf_out}
        self.check_output()


class TestAdagrad(OpTest):
    op_type = "adagrad"

    def test(self):
        p, g, lr = _pgl()
        mom = RS.rand(*p.shape).astype("float32")
        eps = 1e-6
        mom_out = mom + g * g
        p_out = p - 0.1 * g / (np.sqrt(mom_out) + eps)
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr,
                       "Moment": mom}
        self.attrs = {"epsilon": eps}
        self.outputs = {"ParamOut": p_out, "MomentOut": mom_out}
        self.check_output()


class TestDecayedAdagrad(OpTest):
    op_type = "decayed_adagrad"

    def test(self):
        p, g, lr = _pgl()
        mom = RS.rand(*p.shape).astype("float32")
        decay, eps = 0.95, 1e-6
        mom_out = decay * mom + (1 - decay) * g * g
        p_out = p - 0.1 * g / (np.sqrt(mom_out) + eps)
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr,
                       "Moment": mom}
        self.attrs = {"decay": decay, "epsilon": eps}
        self.outputs = {"ParamOut": p_out, "MomentOut": mom_out}
        self.check_output()


class TestAdadelta(OpTest):
    op_type = "adadelta"

    def test(self):
        p, g, _ = _pgl()
        asg = RS.rand(*p.shape).astype("float32")
        asu = RS.rand(*p.shape).astype("float32")
        rho, eps = 0.95, 1e-6
        asg_out = rho * asg + (1 - rho) * g * g
        update = -np.sqrt((asu + eps) / (asg_out + eps)) * g
        asu_out = rho * asu + (1 - rho) * update * update
        self.inputs = {"Param": p, "Grad": g, "AvgSquaredGrad": asg,
                       "AvgSquaredUpdate": asu}
        self.attrs = {"rho": rho, "epsilon": eps}
        self.outputs = {"ParamOut": p + update,
                        "AvgSquaredGradOut": asg_out,
                        "AvgSquaredUpdateOut": asu_out}
        self.check_output()


class TestRmsprop(OpTest):
    op_type = "rmsprop"

    def test(self):
        p, g, lr = _pgl()
        ms = RS.rand(*p.shape).astype("float32")
        mom = RS.rand(*p.shape).astype("float32")
        rho, eps, mu = 0.9, 1e-10, 0.9
        ms_out = rho * ms + (1 - rho) * g * g
        mom_out = mu * mom + 0.1 * g / np.sqrt(ms_out + eps)
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr,
                       "MeanSquare": ms, "Moment": mom}
        self.attrs = {"decay": rho, "epsilon": eps, "momentum": mu}
        self.outputs = {"ParamOut": p - mom_out, "MomentOut": mom_out,
                        "MeanSquareOut": ms_out}
        self.check_output()


class TestFtrl(OpTest):
    op_type = "ftrl"

    def test(self):
        p, g, lr = _pgl()
        sq = RS.rand(*p.shape).astype("float32")
        lin = RS.rand(*p.shape).astype("float32")
        l1, l2, lrp = 0.1, 0.2, -0.5
        new_sq = sq + g * g
        sigma = (np.sqrt(new_sq) - np.sqrt(sq)) / 0.1
        lin_out = lin + g - sigma * p
        denom = np.sqrt(new_sq) / 0.1 + 2 * l2
        pre = (l1 * np.sign(lin_out) - lin_out) / denom
        p_out = np.where(np.abs(lin_out) > l1, pre, 0.0)
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr,
                       "SquaredAccumulator": sq, "LinearAccumulator": lin}
        self.attrs = {"l1": l1, "l2": l2, "lr_power": lrp}
        self.outputs = {"ParamOut": p_out.astype("float32"),
                        "SquaredAccumOut": new_sq,
                        "LinearAccumOut": lin_out}
        self.check_output(atol=1e-4)


class TestProximalGD(OpTest):
    op_type = "proximal_gd"

    def test(self):
        p, g, lr = _pgl()
        l1, l2 = 0.1, 0.2
        prox = p - 0.1 * g
        p_out = np.sign(prox) / (1 + 0.1 * l2) * \
            np.maximum(np.abs(prox) - 0.1 * l1, 0)
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr}
        self.attrs = {"l1": l1, "l2": l2}
        self.outputs = {"ParamOut": p_out.astype("float32")}
        self.check_output()


class TestProximalAdagrad(OpTest):
    op_type = "proximal_adagrad"

    def test(self):
        p, g, lr = _pgl()
        mom = RS.rand(*p.shape).astype("float32")
        l1, l2 = 0.1, 0.2
        mom_out = mom + g * g
        lr_t = 0.1 / np.sqrt(mom_out)
        prox = p - lr_t * g
        p_out = np.sign(prox) / (1 + lr_t * l2) * \
            np.maximum(np.abs(prox) - lr_t * l1, 0)
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr,
                       "Moment": mom}
        self.attrs = {"l1": l1, "l2": l2}
        self.outputs = {"ParamOut": p_out.astype("float32"),
                        "MomentOut": mom_out}
        self.check_output()


def test_sgd_selected_rows():
    """Sparse SGD: only touched rows update (reference sgd_op.cc
    SelectedRows path)."""
    prog = __import__("paddle_tpu.fluid.framework",
                      fromlist=["Program"]).Program()
    block = prog.global_block()
    p = RS.rand(6, 3).astype("float32")
    rows = np.asarray([1, 4], dtype="int64")
    gvals = RS.rand(2, 3).astype("float32")
    grad = SelectedRows(rows, gvals, height=6)
    lr = np.asarray([0.5], dtype="float32")

    pv = block.create_var(name="P", shape=[6, 3], dtype="float32")
    from paddle_tpu.core.types import VarType
    gv = block.create_var(name="G", shape=[6, 3], dtype="float32",
                          type=VarType.SELECTED_ROWS)
    lv = block.create_var(name="LR", shape=[1], dtype="float32")
    ov = block.create_var(name="PO", shape=[6, 3], dtype="float32")
    block.append_op(type="sgd",
                    inputs={"Param": pv, "Grad": gv, "LearningRate": lv},
                    outputs={"ParamOut": ov})
    exe = fluid.Executor(fluid.CPUPlace())
    out, = exe.run(prog, feed={"P": p, "G": grad, "LR": lr},
                   fetch_list=["PO"], scope=fluid.Scope())
    expect = p.copy()
    expect[rows] -= 0.5 * gvals
    np.testing.assert_allclose(out, expect, rtol=1e-5)
