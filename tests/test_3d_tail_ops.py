"""The 3-D op tail + legacy cond (the last absent reference ops).

reference: conv_transpose_op.cc:197 (conv3d_transpose),
pool_with_index_op.cc:276 (max_pool3d_with_index), cond_op.cc:229
(sample-dependent cond).
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.core.desc import BlockRef

from op_test import OpTest

RS = np.random.RandomState(11)


def _conv3d_transpose_ref(x, w, stride, pad):
    n, cin, d, h, ww = x.shape
    cin2, cout, kd, kh, kw = w.shape
    od = (d - 1) * stride[0] - 2 * pad[0] + kd
    oh = (h - 1) * stride[1] - 2 * pad[1] + kh
    ow = (ww - 1) * stride[2] - 2 * pad[2] + kw
    out = np.zeros((n, cout, od + 2 * pad[0], oh + 2 * pad[1],
                    ow + 2 * pad[2]), np.float32)
    for b in range(n):
        for ic in range(cin):
            for zz in range(d):
                for yy in range(h):
                    for xx in range(ww):
                        patch = x[b, ic, zz, yy, xx] * w[ic]  # [cout,kd,kh,kw]
                        out[b, :, zz * stride[0]:zz * stride[0] + kd,
                            yy * stride[1]:yy * stride[1] + kh,
                            xx * stride[2]:xx * stride[2] + kw] += patch
    if any(pad):
        out = out[:, :, pad[0]:pad[0] + od, pad[1]:pad[1] + oh,
                  pad[2]:pad[2] + ow]
    return out


def _max_pool3d_ref(x, ksize, stride):
    n, c, d, h, w = x.shape
    od = (d - ksize[0]) // stride[0] + 1
    oh = (h - ksize[1]) // stride[1] + 1
    ow = (w - ksize[2]) // stride[2] + 1
    out = np.zeros((n, c, od, oh, ow), x.dtype)
    mask = np.zeros((n, c, od, oh, ow), np.int32)
    for b in range(n):
        for cc in range(c):
            for i in range(od):
                for j in range(oh):
                    for k in range(ow):
                        blk = x[b, cc,
                                i * stride[0]:i * stride[0] + ksize[0],
                                j * stride[1]:j * stride[1] + ksize[1],
                                k * stride[2]:k * stride[2] + ksize[2]]
                        out[b, cc, i, j, k] = blk.max()
                        zi, yi, xi = np.unravel_index(blk.argmax(),
                                                      blk.shape)
                        mask[b, cc, i, j, k] = (
                            (i * stride[0] + zi) * h * w
                            + (j * stride[1] + yi) * w
                            + k * stride[2] + xi)
    return out, mask


class TestConv3dTranspose(OpTest):
    op_type = "conv3d_transpose"

    def test(self):
        x = RS.rand(2, 3, 3, 4, 4).astype("float32")
        w = RS.rand(3, 4, 2, 3, 3).astype("float32")
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [2, 1, 1], "paddings": [0, 1, 0],
                      "dilations": [1, 1, 1]}
        self.outputs = {"Output": _conv3d_transpose_ref(
            x, w, (2, 1, 1), (0, 1, 0))}
        self.check_output(atol=2e-4)
        self.check_grad(["Input", "Filter"], "Output",
                        max_relative_error=0.03)


class TestMaxPool3dWithIndex(OpTest):
    op_type = "max_pool3d_with_index"

    def test(self):
        x = RS.rand(2, 2, 4, 4, 4).astype("float32")
        out, mask = _max_pool3d_ref(x, (2, 2, 2), (2, 2, 2))
        self.inputs = {"X": x}
        self.attrs = {"ksize": [2, 2, 2], "strides": [2, 2, 2],
                      "paddings": [0, 0, 0]}
        self.outputs = {"Out": out, "Mask": mask}
        self.check_output(atol=1e-6)
        self.check_grad(["X"], "Out", no_grad_set=("Mask",),
                        max_relative_error=0.02)

    def test_global(self):
        x = RS.rand(1, 2, 3, 3, 3).astype("float32")
        out, mask = _max_pool3d_ref(x, (3, 3, 3), (1, 1, 1))
        self.inputs = {"X": x}
        self.attrs = {"ksize": [1, 1, 1], "strides": [1, 1, 1],
                      "paddings": [0, 0, 0], "global_pooling": True}
        self.outputs = {"Out": out, "Mask": mask}
        self.check_output(atol=1e-6)


def test_legacy_cond_rowwise():
    """cond_op.cc semantics: Out[i] = true_subnet(X)[i] where Cond[i],
    else false_subnet(X)[i]."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[6, 4], dtype="float32",
                              append_batch_size=False)
        c = fluid.layers.data(name="c", shape=[6], dtype="int64",
                              append_batch_size=False)

        tb = main.create_block()
        t_out = tb.create_var(name="branch_out", dtype="float32")
        tb.append_op(type="scale", inputs={"X": [x]},
                     outputs={"Out": [t_out]}, attrs={"scale": 2.0})
        main.rollback()
        fb = main.create_block()
        f_out = fb.create_var(name="branch_out", dtype="float32")
        fb.append_op(type="scale", inputs={"X": [x]},
                     outputs={"Out": [f_out]}, attrs={"scale": -1.0})
        main.rollback()

        out = main.global_block().create_var(name="cond_out",
                                             dtype="float32")
        main.global_block().append_op(
            type="cond", inputs={"Cond": [c], "Xs": [x]},
            outputs={"Outs": [out]},
            attrs={"true_block": BlockRef(tb.idx),
                   "false_block": BlockRef(fb.idx),
                   "x_names": [x.name], "out_names": ["branch_out"]},
            infer_shape=False)

    xv = RS.randn(6, 4).astype("float32")
    cv = np.array([1, 0, 1, 1, 0, 0], np.int64)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got, = exe.run(main, feed={"x": xv, "c": cv}, fetch_list=[out])
    want = np.where(cv[:, None] != 0, 2.0 * xv, -xv)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)
