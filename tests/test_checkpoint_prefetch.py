"""CheckpointSaver interval snapshots w/ CRC + torn-write fallback,
reader prefetching, and the multi-host coordinator's mesh builder."""

import json
import os
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.checkpoint import (CheckpointSaver, load_checkpoint,
                                         latest_checkpoint)


def _toy_program():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.fc(input=x, size=3)
    loss = fluid.layers.mean(x=y)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def test_checkpoint_save_load_roundtrip(tmp_path):
    loss = _toy_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": np.ones((2, 4), np.float32)}
    exe.run(fluid.default_main_program(), feed=feed, fetch_list=[loss])

    root = str(tmp_path / "ckpts")
    saver = CheckpointSaver(root, interval_secs=0, max_to_keep=2)
    snap = saver.save(step=7)
    saver.wait()
    assert latest_checkpoint(root) == snap

    # perturb every param, then restore
    from paddle_tpu.core.scope import global_scope
    from paddle_tpu.fluid.io import is_persistable

    names = [v.name for v in
             fluid.default_main_program().list_vars() if is_persistable(v)]
    before = {n: np.array(global_scope().get(n)) for n in names
              if global_scope().get(n) is not None}
    for n in before:
        global_scope().set(n, np.zeros_like(before[n]))
    step = load_checkpoint(root)
    assert step == 7
    for n, v in before.items():
        np.testing.assert_array_equal(np.asarray(global_scope().get(n)), v)


def test_checkpoint_gc_and_interval(tmp_path):
    loss = _toy_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    root = str(tmp_path / "ckpts")
    saver = CheckpointSaver(root, interval_secs=3600, max_to_keep=2)
    assert saver.save(1) is not None
    saver.wait()
    assert saver.maybe_save(2) is None  # interval not due
    saver.interval_secs = 0
    for s in (3, 4, 5):
        assert saver.maybe_save(s) is not None
        saver.wait()
    from paddle_tpu.fluid.checkpoint import _snapshot_dirs

    kept = _snapshot_dirs(root)
    assert len(kept) == 2
    assert kept[-1].endswith("%09d" % 5)


def test_checkpoint_torn_write_falls_back(tmp_path):
    loss = _toy_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    root = str(tmp_path / "ckpts")
    saver = CheckpointSaver(root, interval_secs=0, max_to_keep=5)
    saver.save(1)
    saver.wait()
    good = latest_checkpoint(root)
    saver.save(2)
    saver.wait()
    bad = latest_checkpoint(root)
    # corrupt one tensor of snapshot 2 (simulated torn write)
    manifest = json.load(open(os.path.join(bad, "_manifest.json")))
    victim = next(iter(manifest.values()))["file"]
    with open(os.path.join(bad, victim), "r+b") as f:
        f.seek(0)
        f.write(b"\xde\xad\xbe\xef")
    assert load_checkpoint(root, strict=False) == 1  # fell back to good
    # a snapshot with no manifest at all is invisible
    os.remove(os.path.join(bad, "_manifest.json"))
    assert latest_checkpoint(root) == good


def test_host_prefetch_order_and_errors():
    from paddle_tpu.reader import host_prefetch

    def reader():
        for i in range(20):
            yield i

    got = list(host_prefetch(reader, depth=3)())
    assert got == list(range(20))

    def boom():
        yield 1
        raise RuntimeError("reader failed")

    it = host_prefetch(boom)()
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="reader failed"):
        list(it)


def test_device_prefetch_feeds_executor():
    from paddle_tpu.reader import device_prefetch

    loss = _toy_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rs = np.random.RandomState(0)

    def batches():
        for _ in range(5):
            yield {"x": rs.rand(2, 4).astype(np.float32)}

    vals = []
    for feed in device_prefetch(batches, place=fluid.CPUPlace())():
        out, = exe.run(fluid.default_main_program(), feed=feed,
                       fetch_list=[loss])
        vals.append(float(np.asarray(out).reshape(-1)[0]))
    assert len(vals) == 5 and all(np.isfinite(v) for v in vals)


def test_global_mesh_axis_selection():
    from paddle_tpu.distributed import global_mesh, init_multihost
    import jax

    assert init_multihost() is False  # single host no-op
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    m = global_mesh(mp=2, sp=2)
    assert dict(m.shape) == {"dp": 2, "mp": 2, "sp": 2}
    m2 = global_mesh(pp=4)
    assert dict(m2.shape) == {"dp": 2, "pp": 4}
    with pytest.raises(ValueError):
        global_mesh(dp=3, mp=5)


def test_checkpoint_ragged_persistable_roundtrip(tmp_path):
    from paddle_tpu.core.ragged import RaggedTensor
    from paddle_tpu.core.scope import global_scope
    import jax.numpy as jnp

    _toy_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rt = RaggedTensor.from_sequences(
        [np.arange(3, dtype=np.float32).reshape(3, 1),
         np.arange(2, dtype=np.float32).reshape(2, 1)])
    global_scope().set("ragged_state", rt)

    root = str(tmp_path / "ckpts")
    saver = CheckpointSaver(root, interval_secs=0)
    saver._var_names = lambda: ["ragged_state"]  # focus on the ragged var
    saver.save(3)
    saver.wait()
    global_scope().set("ragged_state", None)
    assert load_checkpoint(root) == 3
    back = global_scope().get("ragged_state")
    np.testing.assert_array_equal(np.asarray(back.values),
                                  np.asarray(rt.values))
    np.testing.assert_array_equal(np.asarray(back.row_splits[0]),
                                  np.asarray(rt.row_splits[0]))


def test_checkpoint_all_corrupt_raises_strict(tmp_path):
    _toy_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    root = str(tmp_path / "ckpts")
    saver = CheckpointSaver(root, interval_secs=0)
    saver.save(1)
    saver.wait()
    snap = latest_checkpoint(root)
    manifest = json.load(open(os.path.join(snap, "_manifest.json")))
    victim = next(iter(manifest.values()))["file"]
    with open(os.path.join(snap, victim), "r+b") as f:
        f.write(b"\x00\x00\x00\x00")
    with pytest.raises(IOError):
        load_checkpoint(root)               # strict default
    assert load_checkpoint(root, strict=False) is None
    assert load_checkpoint(str(tmp_path / "empty")) is None  # truly empty


def test_prefetch_early_abandon_stops_worker():
    import threading
    from paddle_tpu.reader import host_prefetch

    before = threading.active_count()
    produced = []

    def reader():
        for i in range(10_000):
            produced.append(i)
            yield i

    for i, item in enumerate(host_prefetch(reader, depth=2)()):
        if i == 3:
            break
    # worker must wind down instead of blocking on the full queue
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before
    assert len(produced) < 100  # it stopped early, not after 10k


def test_pump_exception_propagates_and_thread_exits():
    """Regression: an exception raised inside the _pump worker (from
    the reader OR the transform) must reach the consumer — not be
    swallowed — and the pump thread must exit instead of leaking."""
    import threading
    from paddle_tpu.reader import host_prefetch

    before = threading.active_count()

    def boom_mid_stream():
        yield 1
        yield 2
        raise IOError("disk fell over")

    it = host_prefetch(boom_mid_stream, depth=1)()
    assert next(it) == 1
    with pytest.raises(IOError, match="disk fell over"):
        list(it)

    def bad_transform(item):
        raise ValueError("transform died")

    it2 = host_prefetch(lambda: iter(range(5)), depth=2,
                        transform=bad_transform)()
    with pytest.raises(ValueError, match="transform died"):
        next(it2)

    # both pump threads must wind down (not block in q.put forever)
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.02)
    assert threading.active_count() <= before


def test_pump_injected_fault_reaches_consumer():
    """The chaos hook inside _pump surfaces like any reader failure:
    the consumer sees the injected IOError and can restart the epoch
    (what resilience.TrainingSupervisor does)."""
    from paddle_tpu.reader import host_prefetch
    from paddle_tpu.resilience import faults

    faults.enable(seed=0)
    faults.inject("reader/pump", "io_error", after=2, times=1)
    it = host_prefetch(lambda: iter(range(10)), depth=2)()
    got = [next(it), next(it)]
    with pytest.raises(faults.InjectedIOError):
        list(it)
    assert got == [0, 1]
    # one-shot: a fresh epoch streams clean
    assert list(host_prefetch(lambda: iter(range(4)), depth=2)()) \
        == [0, 1, 2, 3]


def test_device_prefetch_leaves_int64_on_host():
    """int64 narrowing depends on the target var dtype, which only the
    executor knows — device_prefetch must NOT device_put int64 (JAX
    would silently wrap ids past 2^31 before the executor's guard)."""
    import jax
    from paddle_tpu.reader import device_prefetch

    big = np.array([2 ** 40], dtype=np.int64)

    def batches():
        yield {"ids": big, "x": np.ones((1, 4), np.float32)}

    (feed,) = list(device_prefetch(batches, place=fluid.CPUPlace())())
    assert feed["ids"].dtype == np.int64        # untouched host array
    assert not isinstance(feed["ids"], jax.Array)
    assert isinstance(feed["x"], jax.Array)     # floats pre-placed
    np.testing.assert_array_equal(feed["ids"], big)


def test_make_mesh_extended_axes():
    import jax
    from paddle_tpu.parallel import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    m = make_mesh(n_devices=8, pp=4)
    assert dict(m.shape) == {"dp": 2, "pp": 4}
    m2 = make_mesh(n_devices=8, mp=2, sp=2)
    assert dict(m2.shape) == {"dp": 2, "mp": 2, "sp": 2}
    m3 = make_mesh(n_devices=8, mp=2)   # back-compat: keeps (dp, mp)
    assert dict(m3.shape) == {"dp": 4, "mp": 2}
    m4 = make_mesh(n_devices=8, mp=1, drop_unit_axes=True)
    assert dict(m4.shape) == {"dp": 8}


def test_checkpoint_gc_removes_torn_snapshots(tmp_path):
    _toy_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    root = str(tmp_path / "ckpts")
    saver = CheckpointSaver(root, interval_secs=0, max_to_keep=5)
    saver.save(1)
    saver.wait()
    # fake a crashed mid-write snapshot: var files, no manifest
    torn = os.path.join(root, "checkpoint_%09d" % 2)
    os.makedirs(torn)
    open(os.path.join(torn, "junk.npz"), "wb").write(b"x")
    saver.save(3)
    saver.wait()
    from paddle_tpu.fluid.checkpoint import _snapshot_dirs

    assert not os.path.exists(torn)          # dead dir collected
    assert len(_snapshot_dirs(root)) == 2    # steps 1 and 3 remain


def test_make_mesh_rejects_dropped_axis_and_keeps_dp():
    import jax
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.distributed import global_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    with pytest.raises(ValueError, match="omits"):
        make_mesh(n_devices=8, sp=2, axes=("dp", "mp"))
    m = global_mesh(mp=8)
    assert dict(m.shape) == {"dp": 1, "mp": 8}  # dp survives at size 1
