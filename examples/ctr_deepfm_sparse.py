"""CTR DeepFM with sparse updates over the native parameter server.

The reference's CTR workload (BASELINE.json configs[5]): a DeepFM model
whose embedding-table gradients ship as SelectedRows ROWS — not dense
tensors — to the pserver, which scatter-applies the optimizer per row
(reference: paddle/operators/lookup_table_op.cc sparse grads,
paddle/pserver/ParameterServer2.h:510 sparse row access).

    python examples/ctr_deepfm_sparse.py            # local (no pserver)
    python examples/ctr_deepfm_sparse.py --pserver  # in-proc pserver pair
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # run from anywhere in the checkout

import numpy as np

NUM_FEATURES = int(os.environ.get("FEATURES", "10000"))
NUM_FIELDS = int(os.environ.get("FIELDS", "16"))
BATCH = int(os.environ.get("BATCH", "256"))
STEPS = int(os.environ.get("STEPS", "60"))


def synthetic_ctr_reader(seed=0):
    """Synthetic Criteo-shaped batches: ids per field plus a click label
    driven by a linear + one pairwise-interaction signal."""
    rs = np.random.RandomState(seed)
    per_field = NUM_FEATURES // NUM_FIELDS
    w = rs.randn(NUM_FEATURES) * 0.5
    latent = rs.randn(NUM_FEATURES, 4)
    while True:
        ids = np.stack(
            [rs.randint(f * per_field, (f + 1) * per_field, size=BATCH)
             for f in range(NUM_FIELDS)], axis=1).astype(np.int64)
        logit = w[ids].sum(axis=1)
        logit += np.einsum("nd,nd->n", latent[ids[:, 0]],
                           latent[ids[:, 1]])
        label = (rs.rand(BATCH) < 1 / (1 + np.exp(-logit)))
        yield ids, label.astype(np.float32).reshape(-1, 1)


def main():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models.ctr import deepfm_ctr

    use_pserver = "--pserver" in sys.argv

    ids_var = fluid.layers.data(name="ids", shape=[NUM_FIELDS],
                                dtype="int64")
    label_var = fluid.layers.data(name="label", shape=[1],
                                  dtype="float32")
    avg_loss, predict = deepfm_ctr(ids_var, label_var, NUM_FEATURES,
                                   NUM_FIELDS, embed_dim=16,
                                   hidden_sizes=(128, 64))
    optimize_ops, params_grads = fluid.optimizer.Adam(
        learning_rate=1e-2).minimize(avg_loss)

    servers = []
    t = None
    if use_pserver:
        from paddle_tpu import native
        from paddle_tpu.distributed import DistributeTranspiler

        servers = [native.ParameterServer(num_trainers=1, sync=True)
                   for _ in range(2)]
        endpoints = ",".join("127.0.0.1:%d" % s.port for s in servers)
        t = DistributeTranspiler()
        t.transpile(optimize_ops=optimize_ops, params_grads=params_grads,
                    pservers=endpoints, trainers=1)

    place = fluid.TPUPlace(0)
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    if t is not None:
        t.init_pservers()

    feeder = fluid.DataFeeder(place=place, feed_list=[ids_var, label_var])
    reader = synthetic_ctr_reader()
    for step in range(STEPS):
        ids, label = next(reader)
        feed = feeder.feed([(ids[i], label[i]) for i in range(BATCH)])
        loss, = exe.run(fluid.default_main_program(), feed=feed,
                        fetch_list=[avg_loss])
        if step % 10 == 0 or step == STEPS - 1:
            print("step %3d  logloss %.4f" %
                  (step, float(np.asarray(loss).reshape(-1)[0])),
                  flush=True)

    if use_pserver:
        rows = sum(s.num_sparse_rows() for s in servers)
        print("sparse rows applied server-side:", rows, flush=True)
        from paddle_tpu.ops.dist import ClientPool

        ClientPool.reset()
        for s in servers:
            s.stop()


if __name__ == "__main__":
    main()
