"""paddle_trainer-style config: linear regression on uci_housing
(reference: the `paddle train --config=...` flow of
TrainerMain.cpp + trainer_config_helpers configs).

    python -m paddle_tpu.tools.trainer_cli \
        --config=examples/trainer_config_fit_a_line.py --num_passes=5
"""

from paddle_tpu.trainer_config_helpers import *  # noqa: F401,F403

settings(batch_size=20, learning_rate=0.01,
         learning_method=MomentumOptimizer(momentum=0.9))  # noqa: F405

define_py_data_sources2(                                   # noqa: F405
    train_list="train", test_list="test",
    module="paddle_tpu.dataset.uci_housing_provider",
    obj="provide")

x = data_layer(name="x", size=13)                          # noqa: F405
y_predict = fc_layer(input=x, size=1,                      # noqa: F405
                     act=LinearActivation())               # noqa: F405
y = data_layer(name="y", size=1)                           # noqa: F405
cost = mse_cost(input=y_predict, label=y)                  # noqa: F405

outputs(cost)                                              # noqa: F405
