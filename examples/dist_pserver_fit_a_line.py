"""Distributed training over the native C++ parameter server.

Spawns pservers + trainers on this host via the cluster launcher (the
reference's paddle.py/fabric flow), with the DistributeTranspiler
splitting the program into trainer/pserver halves:

    python examples/dist_pserver_fit_a_line.py

Role processes re-enter this file with TRAINING_ROLE set, exactly like
the reference's book_distribute scripts.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # run from anywhere in the checkout


import numpy as np


def run_trainer():
    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
    from paddle_tpu.distributed import DistributeTranspiler

    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y_pred = fluid.layers.fc(input=x, size=1)
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    loss = fluid.layers.mean(
        x=fluid.layers.square_error_cost(input=y_pred, label=y))
    opt = fluid.optimizer.SGD(learning_rate=0.001)
    optimize_ops, params_grads = opt.minimize(loss)

    pservers = os.environ["PSERVERS"]
    trainer_id = int(os.environ.get("TRAINER_ID", "0"))
    trainers = int(os.environ.get("TRAINERS", "1"))
    sync = os.environ.get("PADDLE_SYNC", "1") == "1"

    # rewrites the main program in place: optimizer ops become
    # dist_send ops against the pserver endpoints
    t = DistributeTranspiler()
    t.transpile(optimize_ops=optimize_ops, params_grads=params_grads,
                trainer_id=trainer_id, pservers=pservers,
                trainers=trainers, sync=sync)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    if trainer_id == 0:
        t.init_pservers()  # push initial parameter values

    feeder = fluid.DataFeeder(place=fluid.TPUPlace(0), feed_list=[x, y])
    reader = paddle.batch(paddle.dataset.uci_housing.train(),
                          batch_size=20)
    for pass_id in range(3):
        costs = []
        for data in reader():
            out, = exe.run(feed=feeder.feed(data), fetch_list=[loss])
            costs.append(float(np.asarray(out).reshape(-1)[0]))
        print("trainer %d pass %d avg cost %.4f"
              % (trainer_id, pass_id, float(np.mean(costs))), flush=True)


def main():
    if os.environ.get("TRAINING_ROLE") == "TRAINER":
        run_trainer()
        return
    # parent: spawn 2 pservers + 2 trainers on loopback
    from paddle_tpu.tools.cluster_launch import launch

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(
        __file__)))
    child_pythonpath = os.pathsep.join(
        p for p in (repo_root, os.environ.get("PYTHONPATH")) if p)
    ps_procs, tr_procs, _ = launch(
        [os.path.abspath(__file__)],
        pservers=["127.0.0.1:7164", "127.0.0.1:7165"],
        trainers=2, sync=True,
        # pservers import paddle_tpu via `python -c`, so the repo root
        # must reach them through the environment
        env={"JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
             "PYTHONPATH": child_pythonpath})
    rc = 0
    for p in tr_procs:
        rc |= p.wait(timeout=600)
    for p in ps_procs:
        p.terminate()
    sys.exit(rc)


if __name__ == "__main__":
    main()
