"""Train an image classifier with the fluid API: bf16 AMP, prefetched
device feeds, and interval checkpoints.

    python examples/train_image_classification.py            # smallnet
    MODEL=resnet50 BATCH=64 python examples/train_image_classification.py

Uses the CIFAR-10 reader (synthetic fallback offline; set
PADDLE_TPU_ALLOW_DOWNLOAD=1 for the real dataset).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # run from anywhere in the checkout


import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import models
from paddle_tpu.fluid.checkpoint import CheckpointSaver, load_checkpoint
from paddle_tpu.reader import device_prefetch


def main():
    model = os.environ.get("MODEL", "smallnet")
    batch = int(os.environ.get("BATCH", "64"))
    passes = int(os.environ.get("PASSES", "2"))
    ckpt_dir = os.environ.get("CKPT_DIR", "/tmp/paddle_tpu_cifar_ckpts")

    fluid.amp.enable_bf16()  # MXU dtype policy; f32 masters

    image = fluid.layers.data(name="image", shape=[3, 32, 32],
                              dtype="float32")
    model_fn = {"smallnet": models.smallnet_mnist_cifar,
                "resnet50": models.resnet50}[model]
    logits = model_fn(image, class_dim=10)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    loss = fluid.layers.mean(x=fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=label))
    acc = fluid.layers.accuracy(input=fluid.layers.softmax(logits),
                                label=label)
    fluid.optimizer.MomentumOptimizer(
        learning_rate=0.01, momentum=0.9).minimize(loss)

    place = fluid.TPUPlace(0)
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    start_step = load_checkpoint(ckpt_dir, strict=False) or 0
    if start_step:
        print("resumed from step", start_step)

    feeder = fluid.DataFeeder(place=place, feed_list=[image, label])
    train_reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.cifar.train10(),
                              buf_size=2048), batch_size=batch)
    saver = CheckpointSaver(ckpt_dir, interval_secs=120, max_to_keep=3)

    step = start_step
    for pass_id in range(passes):
        feeds = device_prefetch(
            lambda: (feeder.feed(d) for d in train_reader()), place=place)
        for feed in feeds():
            fetched = exe.run(feed=feed, fetch_list=[loss, acc])
            step += 1
            if step % 20 == 0:
                print("pass %d step %d loss %.4f acc %.3f"
                      % (pass_id, step,
                         float(np.asarray(fetched[0]).reshape(-1)[0]),
                         float(np.asarray(fetched[1]).reshape(-1)[0])),
                      flush=True)
            saver.maybe_save(step)
    saver.save(step)
    saver.wait()
    print("done; checkpoints in", ckpt_dir)


if __name__ == "__main__":
    main()
