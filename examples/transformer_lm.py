"""Train a small transformer LM on the Program stack, then generate
with the compiled decoders.

    python examples/transformer_lm.py
    SEQ_LEN=128 D_MODEL=256 N_LAYER=4 python examples/transformer_lm.py

The model is fluid-built (models/transformer_program.py): attention is
the `flash_attention` op — the pallas online-softmax kernel on TPU,
interpret mode on CPU — and training runs real Momentum ops (stacked
fused updates).  Generation reuses the trained weights through
`fluid.ProgramDecoder`: one decode step expressed as a Program, the
whole loop compiled (docs/DESIGN_jit_beam_search.md).

Data is a synthetic integer-sequence "language" with a repeating
structure the model can learn quickly.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # run from anywhere in the checkout

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.models.transformer_program import (
    build_transformer_program, build_transformer_step_program)


def synthetic_batch(rs, batch, seq_len, vocab):
    """Next-token data over arithmetic sequences mod vocab (learnable
    in a few steps)."""
    start = rs.randint(2, vocab, size=(batch, 1))
    step = rs.randint(1, 5, size=(batch, 1))
    seq = (start + step * np.arange(seq_len + 1)) % vocab
    tokens = seq[:, :-1].astype(np.int64)
    targets = seq[:, 1:, None].astype(np.int64)
    positions = np.broadcast_to(np.arange(seq_len),
                                (batch, seq_len)).astype(np.int64)
    return {"tokens": tokens,
            "positions": np.ascontiguousarray(positions),
            "targets": targets}


def main():
    batch = int(os.environ.get("BATCH", "16"))
    seq_len = int(os.environ.get("SEQ_LEN", "32"))
    vocab = int(os.environ.get("VOCAB", "64"))
    d_model = int(os.environ.get("D_MODEL", "64"))
    n_layer = int(os.environ.get("N_LAYER", "2"))
    steps = int(os.environ.get("STEPS", "40"))

    main_prog, startup, avg_loss, logits = build_transformer_program(
        batch, seq_len, vocab, n_layer=n_layer, n_head=4,
        d_model=d_model)
    with fluid.program_guard(main_prog, startup):
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_loss)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    rs = np.random.RandomState(0)
    first = last = None
    for step in range(steps):
        feed = synthetic_batch(rs, batch, seq_len, vocab)
        (loss,) = exe.run(main_prog, feed=feed, fetch_list=[avg_loss])
        last = float(np.asarray(loss).reshape(-1)[0])
        if first is None:
            first = last
        if step % 10 == 0:
            print("step %d loss %.4f" % (step, last), flush=True)
    print("loss %.4f -> %.4f" % (first, last), flush=True)
    assert last < first, "training did not reduce the loss"

    # generation: the sliding-window step program carries the token
    # window as decode state; per-program name scopes make its
    # parameters line up with the trained program, so the SAME scope
    # drives it (fluid.ProgramDecoder compiles the whole loop)
    gen_batch, window = 4, seq_len
    step_prog, _, step_logits, new_window = \
        build_transformer_step_program(
            gen_batch, window, vocab, n_layer=n_layer, n_head=4,
            d_model=d_model)
    decoder = fluid.ProgramDecoder(
        step_prog.clone(for_test=True), token_name="tok",
        logits_name=step_logits.name,
        state_pairs=[("window", new_window.name),
                     ("positions", "positions")])

    # one shared prompt (start 5, step 3): the decoder's scalar `bos`
    # is the prompt's true last token, so step 0 appends it and the
    # first prediction continues the sequence
    stride = 3
    seq = (5 + stride * np.arange(window + 1)) % vocab
    prompt = np.broadcast_to(seq[:window], (gen_batch, window))
    positions = np.broadcast_to(np.arange(window),
                                (gen_batch, window)).astype(np.int64)
    toks, _ = decoder.greedy(
        bos=int(seq[window]), eos=vocab + 1,  # no eos in this language
        max_len=16,
        init_state={"window": np.ascontiguousarray(prompt).astype(np.int64),
                    "positions": np.ascontiguousarray(positions)})
    gen = np.asarray(toks)[0].tolist()
    print("prompt tail:", seq[window - 3:window + 1].tolist(), flush=True)
    print("generated:  ", gen, flush=True)

    # the learned language is arithmetic mod vocab: the continuation
    # should keep stepping by `stride` far more often than chance
    full = np.concatenate([[int(seq[window])], gen])
    acc = float(np.mean((np.diff(full) % vocab) == stride))
    print("pattern-follow accuracy: %.2f" % acc, flush=True)
    # chance is 1/vocab ~ 0.016; a briefly-trained model lands well
    # above it (deterministic seed)
    assert acc > 0.15, acc

    # same decoder, stochastic: temperature sampling diversifies
    sampled, _ = decoder.sample(
        bos=int(seq[window]), eos=vocab + 1, max_len=16,
        init_state={"window": np.ascontiguousarray(prompt)
                    .astype(np.int64),
                    "positions": np.ascontiguousarray(positions)},
        seed=1, temperature=1.2)
    print("sampled:    ", np.asarray(sampled)[0].tolist(), flush=True)


if __name__ == "__main__":
    main()
