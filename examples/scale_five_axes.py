"""Scale a model over the five parallelism axes on one mesh.

Demonstrates every distributed axis on a virtual CPU mesh (run on a
real pod by dropping the env overrides and calling
distributed.init_multihost() on every host):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/scale_five_axes.py

  dp  data parallel      ParallelTrainer gradient all-reduce
  mp  tensor parallel    sharded weights via GSPMD
  sp  sequence parallel  ring attention (ppermute ICI ring)
  pp  pipeline parallel  GPipe microbatch ring (parallel.pipeline)
  ep  expert parallel    Switch-MoE all_to_all (parallel.moe)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # run from anywhere in the checkout

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.parallel import (make_mesh, pipeline_apply,
                                 stack_stage_params, init_moe_params,
                                 moe_shard_map)


def pipelined_mlp(n_devices):
    """pp x dp: 4 tanh-MLP stages, microbatches streamed on the ring."""
    mesh = make_mesh(n_devices=n_devices, pp=4,
                     axes=("pp", "dp"), drop_unit_axes=False)
    rs = np.random.RandomState(0)
    stages = [{"w": jnp.asarray(rs.randn(32, 32).astype(np.float32) * .3),
               "b": jnp.zeros((32,), jnp.float32)} for _ in range(4)]
    stacked = stack_stage_params(stages)
    dp = mesh.shape["dp"]
    x = jnp.asarray(rs.randn(8 * dp, 32).astype(np.float32))
    tgt = jnp.tanh(x @ jnp.asarray(rs.randn(32, 32).astype(np.float32)))

    def stage(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    def loss_fn(s):
        return jnp.mean((pipeline_apply(mesh, stage, s, x, 4) - tgt) ** 2)

    step = jax.jit(lambda s: jax.value_and_grad(loss_fn)(s))
    for i in range(5):
        loss, grads = step(stacked)
        stacked = jax.tree_util.tree_map(lambda p, g: p - 0.3 * g,
                                         stacked, grads)
        print("pipeline mesh=%s step %d loss %.4f"
              % (dict(mesh.shape), i, float(loss)), flush=True)


def moe_layer(n_devices):
    """dp x ep: tokens all_to_all to their expert's owner and back."""
    mesh = make_mesh(n_devices=n_devices, ep=4, axes=("dp", "ep"),
                     drop_unit_axes=False)
    params = init_moe_params(0, 32, 64, 8)
    fn = moe_shard_map(mesh, capacity_factor=2.0)
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(128, 32).astype(np.float32))

    def loss_fn(p):
        y, aux = fn(p, x)
        return jnp.mean((y - x) ** 2) + 0.01 * aux

    step = jax.jit(lambda p: jax.value_and_grad(loss_fn)(p))
    for i in range(5):
        loss, grads = step(params)
        params = jax.tree_util.tree_map(lambda a, g: a - 0.1 * g,
                                        params, grads)
        print("moe mesh=%s step %d loss %.4f"
              % (dict(mesh.shape), i, float(loss)), flush=True)


def sequence_parallel_transformer(n_devices):
    """dp x mp x sp: ring-attention transformer training step — the
    sequence axis shards over sp (K/V rotate the ICI ring), weights
    over mp, batch over dp."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.models.transformer import (init_transformer,
                                               transformer_loss,
                                               transformer_param_specs)

    dp, mp, sp = n_devices // 4, 2, 2
    mesh = Mesh(np.array(jax.devices()[:n_devices]).reshape(dp, mp, sp),
                axis_names=("dp", "mp", "sp"))
    params = init_transformer(0, vocab_size=128, n_layer=2, n_head=4,
                              d_model=64, max_len=64)
    meta = params["_meta"]
    specs = transformer_param_specs(params)
    arrs = {n: jax.device_put(v, NamedSharding(mesh, specs[n]))
            for n, v in params.items() if n != "_meta"}
    rs = np.random.RandomState(2)
    tokens = jnp.asarray(rs.randint(0, 128, (2 * dp, 32)), jnp.int32)
    targets = jnp.asarray(rs.randint(0, 128, (2 * dp, 32)), jnp.int32)

    def loss_fn(arrs):
        return transformer_loss({**arrs, "_meta": meta}, tokens, targets,
                                attn_impl="ring", mesh=mesh)

    with mesh:
        step = jax.jit(lambda a: jax.value_and_grad(loss_fn)(a))
        for i in range(3):
            loss, grads = step(arrs)
            arrs = {n: v - 0.05 * grads[n] for n, v in arrs.items()}
            print("transformer mesh=%s step %d loss %.4f"
                  % (dict(mesh.shape), i, float(loss)), flush=True)


def main():
    n = len(jax.devices())
    print(n, "devices:", jax.devices()[0].platform, flush=True)
    pipelined_mlp(n)
    moe_layer(n)
    if n % 4 == 0:
        sequence_parallel_transformer(n)


if __name__ == "__main__":
    main()
