"""UCI housing dataset stand-in (reference: python/paddle/v2/dataset/
uci_housing.py — 13 features, scalar target)."""

from .common import synthetic_linear

__all__ = ["train", "test", "feature_num"]

feature_num = 13
_TRAIN_N = 404
_TEST_N = 102


def train():
    x, y = synthetic_linear(_TRAIN_N, feature_num, w_seed=1000, x_seed=1)

    def reader():
        for i in range(x.shape[0]):
            yield x[i], y[i]

    return reader


def test():
    x, y = synthetic_linear(_TEST_N, feature_num, w_seed=1000, x_seed=7)

    def reader():
        for i in range(x.shape[0]):
            yield x[i], y[i]

    return reader
