"""WMT14 translation stand-in (reference: python/paddle/v2/dataset/
wmt14.py — (src_ids, trg_ids, trg_ids_next) with <s>/<e>/<unk>)."""

from .common import rng

__all__ = ["train", "test", "ID_MARK_START", "ID_MARK_END", "ID_MARK_UNK"]

ID_MARK_START = 0
ID_MARK_END = 1
ID_MARK_UNK = 2

_DICT = 30000


def _reader(n, dict_size, seed):
    r = rng(seed)

    def reader():
        for _ in range(n):
            src_len = int(r.randint(3, 20))
            src = r.randint(3, dict_size, size=src_len).tolist()
            # target = reversed source with offset: a learnable mapping
            trg = [(t + 17) % dict_size for t in reversed(src)]
            trg = [max(3, t) for t in trg]
            trg_in = [ID_MARK_START] + trg
            trg_next = trg + [ID_MARK_END]
            yield src, trg_in, trg_next

    return reader


def train(dict_size=_DICT):
    return _reader(1024, dict_size, 55)


def test(dict_size=_DICT):
    return _reader(128, dict_size, 56)
