"""Pascal VOC2012 segmentation stand-in (reference: python/paddle/v2/
dataset/voc2012.py — image + per-pixel class-label map pairs)."""

from .common import rng

__all__ = ["train", "test", "val", "CLASS_NUM"]

CLASS_NUM = 21


def _reader(n, seed, size=64):
    r = rng(seed)

    def reader():
        for _ in range(n):
            im = r.rand(3, size, size).astype("float32")
            # blocky label map correlated with channel 0
            lab = (im[0] * CLASS_NUM).astype("int64") % CLASS_NUM
            yield im, lab

    return reader


def train():
    return _reader(128, 95)


def test():
    return _reader(32, 96)


def val():
    return _reader(32, 97)
