"""NLTK movie-review sentiment stand-in (reference:
python/paddle/v2/dataset/sentiment.py — word-id sequences + 0/1 polarity
labels over a 2-class corpus)."""

from .common import rng

__all__ = ["train", "test", "get_word_dict", "NUM_TRAINING_INSTANCES",
           "NUM_TOTAL_INSTANCES"]

_VOCAB = 5147
NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000


def get_word_dict():
    return {("w%d" % i): i for i in range(_VOCAB)}


def _reader(n, seed):
    r = rng(seed)

    def reader():
        for _ in range(n):
            length = int(r.randint(8, 60))
            # polarity-correlated token distribution: class k draws
            # more tokens from its half of the vocab
            label = int(r.randint(0, 2))
            lo = 0 if label == 0 else _VOCAB // 2
            words = (lo + r.randint(0, _VOCAB // 2,
                                    size=length)).tolist()
            yield words, label

    return reader


def train():
    return _reader(NUM_TRAINING_INSTANCES, 71)


def test():
    return _reader(NUM_TOTAL_INSTANCES - NUM_TRAINING_INSTANCES, 72)
